"""BuildCheckpoint: sharded, fingerprint-guarded build persistence.

The checkpoint rung of the resilience ladder: when the *process* dies
(preemption, OOM-kill, a tunnel hang that outlives every retry), the
on-disk state is what resumes. Two estimator families use it:

- **Forests** (:class:`ForestCheckpoint`): each completed tree group —
  one device program's worth — persists as it lands; a re-run with the
  same params and data resumes after the last finished group. Per-tree
  RNG draws happen up front either way, so a resumed forest is
  bit-identical to an uninterrupted one.
- **Boosting** (:class:`BoostCheckpoint`): completed GBDT rounds persist
  at round-group granularity together with the resume *state* (the f64
  raw-margin matrix, score history, early-stopping counters). The
  per-(seed, round, row) RNG keying of subsample/colsample masks makes a
  resumed ensemble bit-identical to an uninterrupted one — pinned in
  ``tests/test_resilience.py``.

Layout (v2 — replaces PR-era single-``.npz`` rewrites, whose append cost
was O(groups x forest size); v1 files are not resumable and restart with
a warning):

- ``path`` holds a small JSON **manifest**: version, kind, fingerprint,
  item count, the shard list, and the current state file.
- each append writes ONE new shard ``<path>.shard-NNNN.npz`` holding just
  that group's trees — append cost is O(group), not O(total) — then the
  state file (if any), then rewrites the manifest. Every write is
  write-temp + ``os.replace``, and the manifest goes *last*: a crash at
  any point leaves the previous manifest pointing at fully-written files,
  so recovery never sees a torn group. Orphaned files from a crashed
  append are ignored (and overwritten or removed later).

A fingerprint of params, data, targets, and weights guards resume:
checkpoints from different inputs (or a corrupted file set) restart from
scratch with a warning instead of silently mixing two models. Everything
is pickle-free: arrays via ``np.load(allow_pickle=False)``, headers JSON.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import warnings

import numpy as np

_CKPT_VERSION = 2
_FORMAT = "mpitree_tpu-checkpoint"


def _fingerprint(params: dict, X: np.ndarray, y: np.ndarray,
                 sample_weight) -> str:
    """Stable digest of everything that determines the fitted model.

    Hashes the constructor params (JSON), the data's shape/dtype and
    content, targets, and weights — resuming onto different inputs would
    silently mix two models, so a mismatch restarts from scratch instead.
    """
    h = hashlib.sha256()
    h.update(json.dumps(params, sort_keys=True, default=str).encode())
    for a in (X, y):
        a = np.ascontiguousarray(a)
        h.update(str((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())
    if sample_weight is not None:
        h.update(np.ascontiguousarray(sample_weight).tobytes())
    return h.hexdigest()


def _atomic_bytes(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def _atomic_npz(path: str, payload: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)


class BuildCheckpoint:
    """Incremental sharded persistence for an estimator build (see module
    docstring). ``kind`` distinguishes forest vs boosting manifests so a
    path can never resume across estimator families."""

    kind = "build"

    def __init__(self, path: str, fingerprint: str):
        self.path = os.fspath(path)
        self.fingerprint = fingerprint
        self.trees: list = []
        # Resume state (boosting): {name: ndarray} or None. Written on
        # every append that passes one; the manifest points at the file.
        self.state: dict | None = None
        self._shards: list = []  # [{"file": basename, "n": int}]
        self._state_file: str | None = None

    # -- paths -------------------------------------------------------------
    def _sibling(self, name: str) -> str:
        return os.path.join(os.path.dirname(self.path) or ".", name)

    def _shard_name(self, idx: int) -> str:
        return f"{os.path.basename(self.path)}.shard-{idx:04d}.npz"

    def _state_name(self) -> str:
        return f"{os.path.basename(self.path)}.state-{len(self.trees):06d}.npz"

    # -- open/resume -------------------------------------------------------
    @classmethod
    def open(cls, path, params: dict, X, y, sample_weight) -> "BuildCheckpoint":
        """Load a resumable checkpoint, or a fresh one on any mismatch."""
        fp = _fingerprint(params, X, y, sample_weight)
        ck = cls(path, fp)
        parent = os.path.dirname(ck.path)
        if parent:
            # Fail here (before any training work) or not at all: the
            # first flush happens AFTER completed groups, and an
            # unwritable path discovered there would abort the very fit
            # checkpointing exists to protect.
            os.makedirs(parent, exist_ok=True)
        if not os.path.exists(ck.path):
            return ck
        try:
            ck._load()
        except Exception as e:  # noqa: BLE001 — a bad checkpoint restarts
            warnings.warn(
                f"{cls.kind} checkpoint at {ck.path} not resumable "
                f"({type(e).__name__}: {e}); starting fresh",
                stacklevel=3,
            )
            ck.trees = []
            ck.state = None
            ck._shards = []
            ck._state_file = None
        return ck

    def _load(self) -> None:
        from mpitree_tpu.utils.serialize import _read_tree

        with open(self.path, "rb") as f:
            manifest = json.loads(f.read().decode())
        if (manifest.get("format") != _FORMAT
                or manifest.get("version") != _CKPT_VERSION):
            raise ValueError("unknown checkpoint format/version")
        if manifest.get("kind") != self.kind:
            raise ValueError(
                f"checkpoint kind {manifest.get('kind')!r} != {self.kind!r}"
            )
        if manifest.get("fingerprint") != self.fingerprint:
            raise ValueError("fingerprint mismatch")
        trees: list = []
        for sh in manifest.get("shards", ()):
            with np.load(self._sibling(sh["file"]), allow_pickle=False) as z:
                head = json.loads(str(z["header"]))
                if head["n"] != sh["n"]:
                    raise ValueError(f"shard {sh['file']} count mismatch")
                trees.extend(
                    _read_tree(z, f"tree{i}_") for i in range(int(sh["n"]))
                )
        if len(trees) != int(manifest["n_items"]):
            raise ValueError("manifest/shard item-count mismatch")
        state = None
        sf = manifest.get("state_file")
        if sf:
            with np.load(self._sibling(sf), allow_pickle=False) as z:
                head = json.loads(str(z["header"]))
                if int(head["n_items"]) != len(trees):
                    raise ValueError("state/manifest item-count mismatch")
                state = {
                    k[2:]: z[k] for k in z.files if k.startswith("s_")
                }
        self.trees = trees
        self.state = state
        self._shards = list(manifest.get("shards", ()))
        self._state_file = sf

    # -- append ------------------------------------------------------------
    def append(self, new_trees: list, state: dict | None = None) -> None:
        """Persist ``new_trees`` (and optional resume ``state``) as
        completed.

        O(group) write cost: one new shard file per call; earlier shards
        are never rewritten. Write order (shard -> state -> manifest, each
        atomic-by-rename) makes a crash at ANY point recoverable to the
        previous consistent manifest.
        """
        from mpitree_tpu.utils.serialize import _tree_arrays

        shard = self._shard_name(len(self._shards))
        payload: dict = {"header": json.dumps({"n": len(new_trees)})}
        for i, t in enumerate(new_trees):
            payload.update(_tree_arrays(f"tree{i}_", t))
        _atomic_npz(self._sibling(shard), payload)

        self.trees.extend(new_trees)
        self._shards.append({"file": shard, "n": len(new_trees)})

        prev_state_file = self._state_file
        if state is not None:
            sf = self._state_name()
            spay = {"header": json.dumps({"n_items": len(self.trees)})}
            spay.update({f"s_{k}": np.asarray(v) for k, v in state.items()})
            _atomic_npz(self._sibling(sf), spay)
            self.state = state
            self._state_file = sf

        manifest = {
            "format": _FORMAT,
            "version": _CKPT_VERSION,
            "kind": self.kind,
            "fingerprint": self.fingerprint,
            "n_items": len(self.trees),
            "shards": self._shards,
            "state_file": self._state_file,
        }
        _atomic_bytes(self.path, json.dumps(manifest).encode())
        if prev_state_file and prev_state_file != self._state_file:
            # Superseded state is garbage once the manifest moved on; a
            # crash before this unlink leaves a harmless orphan that
            # done() sweeps.
            try:
                os.unlink(self._sibling(prev_state_file))
            except OSError:
                pass

    @property
    def shard_count(self) -> int:
        """Shard files the manifest currently references — what the
        ``checkpoint_compact_every`` wiring compares against."""
        return len(self._shards)

    def maybe_compact(self, every, obs=None) -> bool:
        """The ONE ``checkpoint_compact_every`` trigger both boosting
        flush paths call: compact once the manifest references ``every``
        shard files (None = never), counting through ``obs``."""
        if every is None or self.shard_count < int(every):
            return False
        self.compact()
        if obs is not None:
            obs.counter("checkpoint_compactions")
        return True

    def compact(self, min_shards: int = 2) -> bool:
        """Merge every referenced shard into ONE (long-run hygiene,
        ISSUE 14); returns whether a compaction happened.

        Very long forest/boosting builds otherwise accumulate one file
        per flush, and every resume pays one ``np.load`` per shard. The
        manifest stays the commit point: the merged shard is written
        first under a FRESH name (never overwriting a referenced file),
        the manifest flips to it atomically, and only then are the old
        shards unlinked — a crash at ANY point recovers to either the
        pre-compaction state (old manifest, merged file an ignored
        orphan) or the post-compaction state (new manifest, old shards
        harmless orphans ``done()`` sweeps). No-op below ``min_shards``.
        """
        from mpitree_tpu.utils.serialize import _tree_arrays

        if len(self._shards) < max(int(min_shards), 2):
            return False
        # Tree-count-salted name: unique across compaction generations
        # and disjoint from the plain shard-NNNN series, so it can never
        # collide with a file a (current or previous) manifest references.
        merged = (
            f"{os.path.basename(self.path)}"
            f".shard-merged-{len(self.trees):06d}.npz"
        )
        payload: dict = {"header": json.dumps({"n": len(self.trees)})}
        for i, t in enumerate(self.trees):
            payload.update(_tree_arrays(f"tree{i}_", t))
        _atomic_npz(self._sibling(merged), payload)

        old = self._shards
        self._shards = [{"file": merged, "n": len(self.trees)}]
        manifest = {
            "format": _FORMAT,
            "version": _CKPT_VERSION,
            "kind": self.kind,
            "fingerprint": self.fingerprint,
            "n_items": len(self.trees),
            "shards": self._shards,
            "state_file": self._state_file,
        }
        _atomic_bytes(self.path, json.dumps(manifest).encode())
        for sh in old:
            if sh["file"] == merged:
                continue
            try:
                os.unlink(self._sibling(sh["file"]))
            except OSError:
                pass  # a crash-window orphan; done() sweeps
        return True

    def done(self) -> None:
        """Remove manifest, shards, and state once the full fit succeeded
        (orphans from crashed appends included). ``glob.escape``: a
        checkpoint path with glob metacharacters (``run[1]/gb.ckpt``)
        must still sweep its siblings."""
        esc = glob.escape(self.path)
        for p in (
            [self.path]
            + glob.glob(esc + ".shard-*.npz")
            + glob.glob(esc + ".state-*.npz")
        ):
            try:
                os.unlink(p)
            except OSError:
                pass


class ForestCheckpoint(BuildCheckpoint):
    """Forest-build checkpoint: with ``RandomForestClassifier(
    checkpoint=path)`` the build runs in tree-axis sized groups, each
    persisted as it completes (see BuildCheckpoint for the file scheme
    and guarantees)."""

    kind = "forest"


class BoostCheckpoint(BuildCheckpoint):
    """Boosting checkpoint: completed rounds' trees plus the resume state
    (raw margins, score history, early-stopping counters) — see
    ``boosting/gradient_boosting.py`` for what the state carries."""

    kind = "gbdt"
