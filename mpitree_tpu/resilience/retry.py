"""The retry/backoff/failover ladder around device-engine dispatches.

The ladder, rung by rung (each rung emits a typed obs event, so
``fit_report_`` carries the whole recovery story):

1. **Retry in place** (:func:`retry_device`, folded into
   :func:`device_failover`): a *transient* loss (UNAVAILABLE /
   DEADLINE_EXCEEDED / connection blip — ``failure.is_transient_failure``)
   re-dispatches on the accelerator after exponential backoff with
   deterministic jitter, up to ``ResilienceConfig.max_retries`` times.
   This is the everyday case on tunneled transports, and before this rung
   existed every blip cliff-dropped the whole fit to the 10-100x slower
   host tier. Event: ``device_retry``; counter: ``device_retries``.
2. **Host failover** (the final rung of :func:`device_failover`): retry
   budget exhausted, or a non-transient device failure (INTERNAL compiler
   crash, DATA_LOSS). The host tier consumes the same binned inputs and
   produces the identical tree (the engine-identity contract), so losing
   the accelerator costs wall-clock, not the job. Event:
   ``device_failover``; counter: ``device_failovers``.

User errors re-raise untouched from every rung, and
``MPITREE_TPU_ELASTIC=0`` turns the whole ladder off (device failures
raise — the CI stance). Checkpointing (``resilience.checkpoint``) is the
rung *below* this module: when the process itself dies, the on-disk
group/round state is what resumes.
"""

from __future__ import annotations

import time
import warnings

from mpitree_tpu.resilience import chaos
from mpitree_tpu.resilience.config import (
    ResilienceConfig,
    backoff_delay,
    elastic_enabled,
)
from mpitree_tpu.resilience.failure import (
    is_device_failure,
    is_oom_failure,
    is_transient_failure,
)


def _oom_postmortem(e: BaseException, what: str, obs) -> None:
    """Attach the memory ledger's top arrays to the record when a
    dispatch died of RESOURCE_EXHAUSTED (ISSUE 12).

    OOM is classified terminal (``failure._TERMINAL_MARKERS``), so the
    retry rung never burns its budget on it — this postmortem is what
    the fit_report_ carries instead: the analytical ledger's largest
    per-device arrays, i.e. what to shrink. One event per record
    (re-raises down the ladder must not duplicate it)."""
    if obs is None or not is_oom_failure(e):
        return
    rec = getattr(obs, "record", None)
    if rec is None or any(
        ev.get("kind") == "oom_postmortem" for ev in rec.events
    ):
        return
    mem = rec.memory or {}
    top = sorted(
        mem.get("arrays", []),
        key=lambda a: -int(a.get("bytes_per_device", 0)),
    )[:5]
    obs.counter("device_ooms")
    obs.event(
        "oom_postmortem",
        f"device OOM during {what} ({type(e).__name__}: "
        f"{str(e)[:160]}); terminal — not retried. The memory ledger's "
        "largest per-device arrays are attached (top); shrink the "
        "binding one or widen the data axis.",
        hbm_peak_bytes=mem.get("hbm_peak_bytes"),
        peak_phase=mem.get("peak_phase"),
        top=[
            {"name": a.get("name"),
             "bytes": int(a.get("bytes_per_device", 0))}
            for a in top
        ],
    )


def _transient_retry(e: BaseException, attempt: int, cfg: ResilienceConfig,
                     what: str, obs) -> bool:
    """One retry-rung step: classify, account, warn, back off.

    True means "re-dispatch on the device tier" (the sleep already
    happened); False means the rung does not apply — not transient, the
    ladder is disabled, or the budget is spent — and the caller moves to
    its next rung. The ONE copy of the rung both ladder entry points
    share, so the event fields and warning text can never drift between
    them. ``is_transient_failure`` implies ``is_device_failure`` (its
    markers are the retryable subset), so callers need no second check
    before this rung.
    """
    if not (elastic_enabled() and is_transient_failure(e)
            and attempt < cfg.max_retries):
        return False
    delay = backoff_delay(cfg, attempt, salt=what)
    n = attempt + 1
    if obs is not None:
        obs.counter("device_retries")
        obs.event(
            "device_retry",
            f"transient device failure during {what} "
            f"({type(e).__name__}: {str(e)[:160]}); retry "
            f"{n}/{cfg.max_retries} on the device tier",
            attempt=n, delay_s=round(delay, 3),
        )
    warnings.warn(
        f"transient device failure during {what} "
        f"({type(e).__name__}: {str(e)[:160]}); retrying on the device "
        f"tier in {delay:.2f}s ({n}/{cfg.max_retries})",
        stacklevel=3,
    )
    time.sleep(delay)
    return True


def retry_device(device_fn, *, what: str, obs=None,
                 config: ResilienceConfig | None = None):
    """Run ``device_fn`` with the retry rung only; re-raise when exhausted.

    For callers with no host twin of the work (the boosting round loop —
    its recovery rung below retries is the round checkpoint, not a host
    rebuild). Transient failures re-dispatch with backoff; everything
    else (including non-transient device failures) raises to the caller.
    """
    cfg = config if config is not None else ResilienceConfig.from_env()
    attempt = 0
    while True:
        try:
            chaos.step("dispatch")
            return device_fn()
        except Exception as e:  # noqa: BLE001 — classified, not swallowed
            if not _transient_retry(e, attempt, cfg, what, obs):
                _oom_postmortem(e, what, obs)
                raise
            attempt += 1


def device_failover(device_fn, host_fn, *, what: str, obs=None,
                    config: ResilienceConfig | None = None):
    """Run ``device_fn`` through the full ladder; ``host_fn`` is the last
    rung.

    The TPU-native answer to the reference's abort-the-job failure mode:
    transient losses retry on the accelerator (see module docstring);
    only an exhausted retry budget or a terminal device failure rebuilds
    on the host tier, which consumes the same binned inputs and produces
    the identical tree — so losing the accelerator mid-fit costs
    wall-clock, not the job. User errors re-raise untouched; with
    elasticity disabled (``MPITREE_TPU_ELASTIC=0``) device failures
    re-raise too.

    ``obs``: any PhaseTimer/BuildObserver — retry counts and rung events
    land in ``fit_report_`` through it. Callers' ``host_fn`` closures
    emit their own ``device_failover`` event with site context.
    """
    cfg = config if config is not None else ResilienceConfig.from_env()
    attempt = 0
    while True:
        try:
            chaos.step("dispatch")
            return device_fn()
        except Exception as e:  # noqa: BLE001 — classified, not swallowed
            if not (elastic_enabled() and is_device_failure(e)):
                _oom_postmortem(e, what, obs)
                raise
            if _transient_retry(e, attempt, cfg, what, obs):
                attempt += 1
                continue
            _oom_postmortem(e, what, obs)
            if obs is not None:
                obs.counter("device_failovers")
            warnings.warn(
                f"device failure during {what} ({type(e).__name__}: "
                f"{str(e)[:200]}); rebuilding on the host tier"
                + (f" after {attempt} device retries" if attempt else ""),
                stacklevel=2,
            )
            return host_fn()
