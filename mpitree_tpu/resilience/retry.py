"""The retry/backoff/failover ladder around device-engine dispatches.

The ladder, rung by rung (each rung emits a typed obs event, so
``fit_report_`` carries the whole recovery story). Resilience v2
(ISSUE 14) inserted rungs 1 and 3:

1. **Sub-build retry** (``resume=``, a
   :class:`~mpitree_tpu.resilience.recovery.SnapshotSlot`): a transient
   loss while a level/expansion/dispatch snapshot is pending re-invokes
   the build closure, and the engine fast-forwards *from the last
   completed boundary* instead of restarting the fit — a blip at level
   17 of a depth-20 build re-dispatches levels >= 17 only. Event:
   ``level_retry`` (granularity + resume position attached); counter:
   ``level_retries``. The budget is per position and resets on progress
   (recovery.SnapshotSlot); when one position keeps failing, the slot
   clears and the ladder falls through to the full-build rungs below.
2. **Retry in place** (:func:`retry_device`, folded into
   :func:`device_failover`): a *transient* loss (UNAVAILABLE /
   DEADLINE_EXCEEDED / connection blip — ``failure.is_transient_failure``)
   re-dispatches on the accelerator after exponential backoff with
   deterministic jitter, up to ``ResilienceConfig.max_retries`` times.
   This is the everyday case on tunneled transports, and before this rung
   existed every blip cliff-dropped the whole fit to the 10-100x slower
   host tier. Event: ``device_retry``; counter: ``device_retries``.
3. **OOM rescue** (``rescue=``, a
   :class:`~mpitree_tpu.resilience.recovery.OomRescue`): a
   RESOURCE_EXHAUSTED whose memory-ledger postmortem names a
   chunk-scaled array shrinks the knob it scales with (halved
   ``max_frontier_chunk`` / subtraction->direct /
   ``rounds_per_dispatch``->1) and re-dispatches ON DEVICE with the
   shrunk, re-preflighted plan — bounded at 3 shrinks. Event:
   ``oom_rescue``; counter: ``oom_rescues``. A non-clearing OOM falls
   through to the host rung after the ladder, postmortem attached.
4. **Host failover** (the final rung of :func:`device_failover`): every
   budget exhausted, or a non-transient device failure (INTERNAL compiler
   crash, DATA_LOSS). The host tier consumes the same binned inputs and
   produces the identical tree (the engine-identity contract), so losing
   the accelerator costs wall-clock, not the job. Event:
   ``device_failover``; counter: ``device_failovers``.

User errors re-raise untouched from every rung, and
``MPITREE_TPU_ELASTIC=0`` turns the whole ladder off (device failures
raise — the CI stance). Checkpointing (``resilience.checkpoint``) is the
rung *below* this module: when the process itself dies, the on-disk
group/round state is what resumes.
"""

from __future__ import annotations

import time
import warnings

from mpitree_tpu.resilience import chaos
from mpitree_tpu.resilience.config import (
    ResilienceConfig,
    backoff_delay,
    elastic_enabled,
)
from mpitree_tpu.resilience.failure import (
    is_device_failure,
    is_oom_failure,
    is_transient_failure,
)


def _oom_postmortem(e: BaseException, what: str, obs) -> None:
    """Attach the memory ledger's top arrays to the record when a
    dispatch died of RESOURCE_EXHAUSTED (ISSUE 12).

    OOM is classified terminal (``failure._TERMINAL_MARKERS``), so the
    retry rung never burns its budget on it — this postmortem is what
    the fit_report_ carries instead: the analytical ledger's largest
    per-device arrays, i.e. what to shrink. One event per record
    (re-raises down the ladder must not duplicate it)."""
    if obs is None or not is_oom_failure(e):
        return
    rec = getattr(obs, "record", None)
    if rec is None or any(
        ev.get("kind") == "oom_postmortem" for ev in rec.events
    ):
        return
    mem = rec.memory or {}
    top = sorted(
        mem.get("arrays", []),
        key=lambda a: -int(a.get("bytes_per_device", 0)),
    )[:5]
    obs.counter("device_ooms")
    obs.event(
        "oom_postmortem",
        f"device OOM during {what} ({type(e).__name__}: "
        f"{str(e)[:160]}); terminal — not retried. The memory ledger's "
        "largest per-device arrays are attached (top); shrink the "
        "binding one or widen the data axis.",
        hbm_peak_bytes=mem.get("hbm_peak_bytes"),
        peak_phase=mem.get("peak_phase"),
        top=[
            {"name": a.get("name"),
             "bytes": int(a.get("bytes_per_device", 0))}
            for a in top
        ],
    )


def _transient_retry(e: BaseException, attempt: int, cfg: ResilienceConfig,
                     what: str, obs) -> bool:
    """One retry-rung step: classify, account, warn, back off.

    True means "re-dispatch on the device tier" (the sleep already
    happened); False means the rung does not apply — not transient, the
    ladder is disabled, or the budget is spent — and the caller moves to
    its next rung. The ONE copy of the rung both ladder entry points
    share, so the event fields and warning text can never drift between
    them. ``is_transient_failure`` implies ``is_device_failure`` (its
    markers are the retryable subset), so callers need no second check
    before this rung.
    """
    if not (elastic_enabled() and is_transient_failure(e)
            and attempt < cfg.max_retries):
        return False
    delay = backoff_delay(cfg, attempt, salt=what)
    n = attempt + 1
    if obs is not None:
        obs.counter("device_retries")
        obs.event(
            "device_retry",
            f"transient device failure during {what} "
            f"({type(e).__name__}: {str(e)[:160]}); retry "
            f"{n}/{cfg.max_retries} on the device tier",
            attempt=n, delay_s=round(delay, 3),
        )
    warnings.warn(
        f"transient device failure during {what} "
        f"({type(e).__name__}: {str(e)[:160]}); retrying on the device "
        f"tier in {delay:.2f}s ({n}/{cfg.max_retries})",
        stacklevel=3,
    )
    time.sleep(delay)
    return True


def _subbuild_retry(e: BaseException, resume, cfg: ResilienceConfig,
                    what: str, obs) -> bool:
    """The sub-build rung (ISSUE 14): a transient failure with a pending
    engine snapshot re-invokes the build closure, which fast-forwards
    from the last completed level/expansion/dispatch.

    True means "re-invoke ``device_fn``" (the engine will find the
    snapshot; the sleep already happened). False = no snapshot, not
    transient, or the per-position budget is spent — the slot is then
    cleared (recovery.SnapshotSlot.note_retry) so the full-build rungs
    below restart clean instead of resuming into the same failure.
    """
    if resume is None or resume.snapshot is None:
        return False
    if not (elastic_enabled() and is_transient_failure(e)):
        return False
    snap = resume.snapshot
    if not resume.note_retry(cfg.max_retries):
        return False
    delay = backoff_delay(cfg, resume.retries - 1, salt=f"{what}#sub")
    if obs is not None:
        obs.counter("level_retries")
        obs.event(
            "level_retry",
            f"transient device failure during {what} "
            f"({type(e).__name__}: {str(e)[:160]}); re-dispatching from "
            f"the last completed {snap.kind} ({snap.position}) instead "
            f"of restarting the build "
            f"(retry {resume.retries}/{cfg.max_retries} at this position)",
            granularity=snap.kind, resume_at=int(snap.position),
            attempt=resume.retries, delay_s=round(delay, 3),
        )
    warnings.warn(
        f"transient device failure during {what} "
        f"({type(e).__name__}: {str(e)[:160]}); resuming from "
        f"{snap.kind} {snap.position} in {delay:.2f}s "
        f"({resume.retries}/{cfg.max_retries})",
        stacklevel=3,
    )
    time.sleep(delay)
    return True


def _oom_rescue(e: BaseException, rescue, what: str, obs) -> bool:
    """The OOM-rescue rung (ISSUE 14): RESOURCE_EXHAUSTED with a priced,
    shrinkable plan re-dispatches on-device under the shrunk config
    (recovery.OomRescue owns the knob choice, the bound, and the typed
    event). False falls through toward the host rung."""
    if rescue is None or not (elastic_enabled() and is_oom_failure(e)):
        return False
    return rescue.attempt(e, what=what)


def retry_device(device_fn, *, what: str, obs=None,
                 config: ResilienceConfig | None = None,
                 resume=None, rescue=None):
    """Run ``device_fn`` with the device-side rungs only (sub-build
    resume -> transient retry -> OOM rescue); re-raise when exhausted.

    For callers with no host twin of the work (the boosting round loop —
    its recovery rung below retries is the round checkpoint, not a host
    rebuild). Transient failures re-dispatch with backoff; everything
    else (including non-transient device failures) raises to the caller.

    ``resume``: a :class:`~mpitree_tpu.resilience.recovery.SnapshotSlot`
    shared with the build closure; ``rescue`` an
    :class:`~mpitree_tpu.resilience.recovery.OomRescue` the closure
    applies to its config on every (re-)dispatch.
    """
    cfg = config if config is not None else ResilienceConfig.from_env()
    attempt = 0
    while True:
        try:
            chaos.step("dispatch")
            return device_fn()
        except Exception as e:  # noqa: BLE001 — classified, not swallowed
            if _subbuild_retry(e, resume, cfg, what, obs):
                continue
            if _transient_retry(e, attempt, cfg, what, obs):
                attempt += 1
                continue
            if _oom_rescue(e, rescue, what, obs):
                continue
            _oom_postmortem(e, what, obs)
            raise


def device_failover(device_fn, host_fn, *, what: str, obs=None,
                    config: ResilienceConfig | None = None,
                    resume=None, rescue=None):
    """Run ``device_fn`` through the full ladder; ``host_fn`` is the last
    rung.

    The TPU-native answer to the reference's abort-the-job failure mode:
    transient losses retry on the accelerator — from the last completed
    sub-build boundary when the engine snapshotted one (``resume=``) —
    and a shrinkable OOM re-dispatches under a shrunk plan (``rescue=``,
    see module docstring); only exhausted budgets or a terminal device
    failure rebuild on the host tier, which consumes the same binned
    inputs and produces the identical tree — so losing the accelerator
    mid-fit costs wall-clock, not the job. User errors re-raise
    untouched; with elasticity disabled (``MPITREE_TPU_ELASTIC=0``)
    device failures re-raise too.

    ``obs``: any PhaseTimer/BuildObserver — retry counts and rung events
    land in ``fit_report_`` through it. Callers' ``host_fn`` closures
    emit their own ``device_failover`` event with site context.
    """
    cfg = config if config is not None else ResilienceConfig.from_env()
    attempt = 0
    while True:
        try:
            chaos.step("dispatch")
            return device_fn()
        except Exception as e:  # noqa: BLE001 — classified, not swallowed
            if not (elastic_enabled() and is_device_failure(e)):
                _oom_postmortem(e, what, obs)
                raise
            if _subbuild_retry(e, resume, cfg, what, obs):
                continue
            if _transient_retry(e, attempt, cfg, what, obs):
                attempt += 1
                continue
            if _oom_rescue(e, rescue, what, obs):
                continue
            _oom_postmortem(e, what, obs)
            if obs is not None:
                obs.counter("device_failovers")
            warnings.warn(
                f"device failure during {what} ({type(e).__name__}: "
                f"{str(e)[:200]}); rebuilding on the host tier"
                + (f" after {attempt} device retries" if attempt else ""),
                stacklevel=2,
            )
            return host_fn()
