"""Chunk-at-a-time device placement: binned chunks land straight on
their mesh slot, so no host ever holds the assembled matrix.

Per-slot buffers start as device-resident zeros (``jnp.zeros`` under
``jax.default_device`` — a host-side zeros + transfer would briefly cost
a full shard of host RAM, exactly what this tier exists to avoid). Each
binned chunk is split along the layout's row/column blocks, each piece
``device_put`` to its slot, and scattered into the buffer with a DONATED
``dynamic_update_slice`` — per-device residency stays one shard plus one
in-flight piece. The finished buffers assemble into ONE global
``jax.Array`` under the partition table's ``x_binned`` sharding
(``jax.make_array_from_single_device_arrays``), which
``mesh.shard_build_inputs`` then recognizes as already placed.

Multi-host: every process calls :func:`assemble_binned` with its own
chunk stream and its global ``row_offset``; each fills only the row
blocks its addressable devices own (pieces for remote blocks are
skipped), and the global array spans all processes — the same
single-controller contract as the build engines. A process's rows must
cover exactly the row blocks of its local devices (contiguous shard
deals via ``chunks.shard_for_process`` satisfy this when hosts hold
equal row counts; the assembler validates coverage and raises
otherwise, it never silently drops rows).
"""

from __future__ import annotations

from functools import partial

import numpy as np

from mpitree_tpu.parallel import partition


# graftlint: host-fn — ingest orchestration: per-chunk device_put and
# the donated scatter are its deliberate host-loop job
def assemble_binned(mesh, binned_chunks, *, n_rows: int, n_features: int,
                    row_offset: int = 0):
    """Assemble int32 binned chunks into the global sharded matrix.

    ``binned_chunks`` yields (n_i, F) int32 arrays in row order whose
    rows total ``n_rows - row_offset`` locally (single-process:
    ``row_offset=0`` and the stream covers every row). Returns the
    global (rows_pad, feat_pad) device array, sharded per the rule
    table.
    """
    import jax
    import jax.numpy as jnp

    layout = partition.ingest_layout(mesh, n_rows, n_features)
    sr, sc = layout["shard_rows"], layout["shard_cols"]
    grid = layout["grid"]
    dr, df = grid.shape

    @partial(jax.jit, donate_argnums=0)
    def _scatter(buf, piece, r0):
        return jax.lax.dynamic_update_slice(buf, piece, (r0, 0))

    local = {d.id for d in jax.local_devices()}
    buffers: dict = {}
    for di in range(dr):
        for fi in range(df):
            dev = grid[di, fi]
            if dev.id not in local:
                continue
            with jax.default_device(dev):
                buffers[(di, fi)] = jnp.zeros((sr, sc), jnp.int32)

    covered = np.zeros(dr, np.int64)  # rows this process wrote per block
    cursor = int(row_offset)
    for xb in binned_chunks:
        xb = np.ascontiguousarray(xb, np.int32)
        n = xb.shape[0]
        if xb.shape[1] != n_features:
            raise ValueError(
                f"binned chunk has {xb.shape[1]} features, expected "
                f"{n_features}"
            )
        lo = cursor
        while lo < cursor + n:
            di = lo // sr
            hi = min(cursor + n, (di + 1) * sr)
            rows = xb[lo - cursor:hi - cursor]
            if any((di, fi) in buffers for fi in range(df)):
                for fi in range(df):
                    if (di, fi) not in buffers:
                        continue
                    c0 = fi * sc
                    w = min(sc, n_features - c0)
                    piece = rows[:, c0:c0 + w]
                    if w < sc:  # zero-pad the edge feature block
                        piece = np.concatenate(
                            [piece,
                             np.zeros((len(rows), sc - w), np.int32)],
                            axis=1,
                        )
                    dev = grid[di, fi]
                    piece_d = jax.device_put(
                        np.ascontiguousarray(piece), dev
                    )
                    buffers[(di, fi)] = _scatter(
                        buffers[(di, fi)], piece_d,
                        np.int32(lo - di * sr),
                    )
                covered[di] += len(rows)
            lo = hi
        cursor += n

    # Coverage check: every LOCAL row block must be exactly full (modulo
    # the trailing padding rows of the last global block).
    for di in range(dr):
        if not any((di, fi) in buffers for fi in range(df)):
            continue
        want = min(sr, max(n_rows - di * sr, 0))
        if int(covered[di]) != want:
            raise ValueError(
                f"ingest row block {di} got {int(covered[di])} rows, "
                f"expected {want}: each process's chunk stream must cover "
                "exactly its local devices' row blocks (align shard sizes "
                "or rebalance shard_for_process)"
            )

    arrays = [buffers[k] for k in sorted(buffers)]
    return jax.make_array_from_single_device_arrays(
        (layout["rows_pad"], layout["feat_pad"]),
        layout["sharding"], arrays,
    )
