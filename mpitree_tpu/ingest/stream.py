"""The ingest pipeline: sketch pass → packed edges → bin+place pass.

Two passes over a repeatable chunk source (``chunks.py``):

1. **sketch** — every chunk updates the mergeable per-feature quantile
   sketches (``sketch.py``) and appends its targets/weights to the
   host-resident per-row state (the ONE O(N) host cost streaming keeps;
   pure numpy). Multi-host, sketches then merge across processes so all
   hosts derive identical edges.
2. **bin + place** — the merged sketches pack into the same
   ``(thresholds, n_cand, n_bins)`` table ``bin_dataset`` builds
   (``ops.binning.pack_edges``); each chunk re-streams, bins against it
   (``bin_with_thresholds`` — bit-identical ids), and lands directly on
   its mesh slot (``place.assemble_binned``).

Chunk size resolves through the ``obs.memory`` planner
(``ingest_chunk_rows`` against the ``MPITREE_TPU_HOST_BYTES`` budget)
whenever the source lets the pipeline pick; the priced plan
(``plan_ingest``) rides the observer into ``record.memory``.
"""

from __future__ import annotations

import time

import numpy as np

from mpitree_tpu.ingest import chunks as chunks_mod
from mpitree_tpu.ingest import place as place_mod
from mpitree_tpu.ingest import spill as spill_mod
from mpitree_tpu.ingest.sketch import SketchSet, resolve_capacity
from mpitree_tpu.obs import memory as memory_lib
from mpitree_tpu.ops.binning import StreamedBinnedData, bin_with_thresholds


class StreamedDataset:
    """A host-chunked training set — what ``fit(dataset=...)`` consumes.

    ``chunk_rows=None`` defers to the planner
    (:func:`obs.memory.ingest_chunk_rows` under the
    ``MPITREE_TPU_HOST_BYTES`` budget) for sources that support
    re-chunking; iterator sources own their chunk shapes.
    """

    def __init__(self, source, *, chunk_rows: int | None = None,
                 sketch_capacity: int | None = None):
        if not hasattr(source, "chunks"):
            raise TypeError(
                "source must implement .chunks() (see mpitree_tpu.ingest."
                "chunks); use the from_* constructors for common layouts"
            )
        self.source = source
        self.chunk_rows = chunk_rows
        self.sketch_capacity = resolve_capacity(sketch_capacity)

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_arrays(cls, X, y, sample_weight=None, *,
                    chunk_rows: int | None = None, **kw) -> StreamedDataset:
        """In-memory arrays streamed in ``chunk_rows`` slices (the
        identity-grid/testing form — real out-of-core inputs come from
        shards or iterators)."""
        return cls(
            chunks_mod.ArrayChunks(X, y, sample_weight),
            chunk_rows=chunk_rows, **kw,
        )

    @classmethod
    def from_npy(cls, x_paths, y_paths, weight_paths=None, *,
                 chunk_rows: int | None = None, **kw) -> StreamedDataset:
        """Memory-mapped ``.npy`` shard pairs (globs or path lists)."""
        return cls(
            chunks_mod.NpyShards(x_paths, y_paths, weight_paths),
            chunk_rows=chunk_rows, **kw,
        )

    @classmethod
    def from_npz(cls, paths, *, x_key="X", y_key="y", weight_key=None,
                 **kw) -> StreamedDataset:
        """``.npz`` shard files, one chunk per file."""
        return cls(
            chunks_mod.NpzShards(
                paths, x_key=x_key, y_key=y_key, weight_key=weight_key
            ), **kw,
        )

    @classmethod
    def from_chunks(cls, chunks_or_factory, **kw) -> StreamedDataset:
        """A list of ``(X, y[, w])`` tuples, or a zero-arg factory
        returning a fresh iterator of them per pass (the pipeline
        streams twice — a bare generator would arrive exhausted)."""
        return cls(chunks_mod.IterChunks(chunks_or_factory), **kw)

    # -- iteration ---------------------------------------------------------
    def resolve_chunk_rows(self) -> int | None:
        """The planner-derived chunk size (None for sources that own
        their chunking or whose width is unknown before the stream)."""
        if self.chunk_rows is not None:
            return int(self.chunk_rows)
        nf = getattr(self.source, "n_features", None)
        if nf is None:
            return None
        return memory_lib.ingest_chunk_rows(int(nf))

    def chunks(self, *, validate: bool = True):
        yield from self.source.chunks(
            self.resolve_chunk_rows(), validate=validate
        )


def sketch_dataset(ds: StreamedDataset) -> tuple:
    """Pass 1: (SketchSet, y, sample_weight|None) from one stream.

    ``y``/weights accumulate as chunk pieces and concatenate once at the
    end — per-row host state, not the matrix. Raises on an empty stream
    (nothing to fit) and on chunks that change width mid-stream.
    """
    sketches: SketchSet | None = None
    y_parts: list = []
    w_parts: list = []
    saw_w = None
    for X, y, w in ds.chunks():
        if sketches is None:
            sketches = SketchSet(
                X.shape[1], capacity=ds.sketch_capacity
            )
            saw_w = w is not None
        if (w is not None) != saw_w:
            raise ValueError(
                "chunk stream mixes weighted and unweighted chunks"
            )
        sketches.update(X)
        y_parts.append(np.asarray(y))
        if w is not None:
            w_parts.append(w)
    if sketches is None or sketches.n_rows == 0:
        raise ValueError("empty chunk stream: nothing to fit")
    sketches.merge_across_processes()
    y_all = np.concatenate(y_parts)
    w_all = np.concatenate(w_parts) if w_parts else None
    return sketches, y_all, w_all


def _allgather_rows(local: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Concatenate every process's per-row vector in rank order (the
    same order the global row offsets assume). Uneven lengths gather
    through one padded buffer; non-numeric labels cannot ride the
    collective and are refused with a recipe."""
    from jax.experimental import multihost_utils

    if not np.issubdtype(np.asarray(local).dtype, np.number):
        raise TypeError(
            "multi-host streamed fits need numeric targets/weights (the "
            f"cross-process gather cannot move dtype {local.dtype!r}); "
            "encode labels to integers before streaming"
        )
    width = int(counts.max(initial=1))
    buf = np.zeros(width, np.asarray(local).dtype)
    buf[: len(local)] = local
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    return np.concatenate([
        gathered[p, : int(c)] for p, c in enumerate(counts)
    ])


class StreamRowProvider:
    """Raw-row gather over the chunk stream — the hybrid refine tail's
    data source when no materialized matrix exists.

    ``gather(rows)`` makes ONE pass over the source and returns the
    requested global rows as a dense f32 block in ``rows`` order
    (``rows`` must be sorted ascending; refine candidates' row sets are
    disjoint, so their sorted union qualifies). Host residency is one
    chunk plus the gathered block — the refine tail's candidates are a
    small fraction of the training set by construction.
    """

    def __init__(self, ds: StreamedDataset, *, n_rows: int,
                 row_offset: int = 0):
        self._ds = ds
        self.n_rows = int(n_rows)
        self.row_offset = int(row_offset)

    def gather(self, rows: np.ndarray) -> np.ndarray:
        rows = np.asarray(rows, np.int64)
        out = None
        pos = self.row_offset
        found = 0
        for X, _, _ in self._ds.chunks(validate=False):
            n = X.shape[0]
            lo, hi = np.searchsorted(rows, [pos, pos + n])
            if hi > lo:
                if out is None:
                    out = np.empty((len(rows), X.shape[1]), np.float32)
                out[lo:hi] = X[rows[lo:hi] - pos]
                found += hi - lo
            pos += n
        if found != len(rows):
            raise ValueError(
                f"streamed refine gather found {found}/{len(rows)} rows "
                "in the local chunk stream — multi-host streamed refine "
                "needs every process's rows and is not supported; set "
                "refine_depth=None for multi-host streamed fits"
            )
        return out


class IngestResult:
    """What one full ingest produces: the device-assembled
    ``StreamedBinnedData``, host per-row state, and the stats/plan the
    observer records. ``close()`` releases the spill store (no-op when
    the source was re-iterable)."""

    def __init__(self, binned, y, sample_weight, stats, *, dataset=None,
                 spill=None, row_offset: int = 0):
        self.binned = binned
        self.y = y
        self.sample_weight = sample_weight
        self.stats = stats
        self.dataset = dataset
        self.spill = spill
        self.row_offset = int(row_offset)

    def row_provider(self) -> StreamRowProvider | None:
        """A raw-row gather handle for the refine tail (None when the
        source is unknown)."""
        if self.dataset is None:
            return None
        return StreamRowProvider(
            self.dataset, n_rows=int(self.binned.n_rows),
            row_offset=self.row_offset,
        )

    def close(self) -> None:
        if self.spill is not None:
            self.spill.close()
            self.spill = None


# graftlint: host-fn — ingest driver: two host streaming passes and the
# per-chunk device placement are its deliberate job
def ingest_dataset(ds: StreamedDataset, *, mesh, max_bins: int = 256,
                   binning: str = "auto", obs=None) -> IngestResult:
    """Run both passes and assemble the mesh-resident binned matrix.

    Multi-host, each process streams its own shard (build ``ds`` from
    ``shard_for_process``-dealt paths) and this function computes the
    process's global row offset from an allgather of local row counts.
    """
    import jax

    from mpitree_tpu.parallel import mesh as mesh_lib

    if binning not in ("auto", "exact", "quantile"):
        raise ValueError(f"unknown binning mode: {binning!r}")
    # One-shot sources ride the spill rung (or are refused with the
    # knob named) BEFORE the first pass consumes them.
    ds.source, spill_store = spill_mod.resolve_spill(ds.source, obs=obs)
    t0 = time.perf_counter()
    sketches, y_local, w_local = sketch_dataset(ds)
    sketch_s = time.perf_counter() - t0

    n_local = len(y_local)
    row_offset = 0
    n_rows = sketches.n_rows  # global after merge_across_processes
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        counts = np.asarray(multihost_utils.process_allgather(
            np.array([n_local], np.int64)
        )).reshape(-1)
        row_offset = int(counts[: jax.process_index()].sum())
        # Targets/weights must be GLOBAL like the matrix: the build's
        # per-row host state (and the classifier's label encoding) spans
        # every process's rows — a process-local y would shape-mismatch
        # the global placement, and classes_ derived from a local shard
        # could diverge across hosts when a class is absent from one.
        y_local = _allgather_rows(y_local, counts)
        if w_local is not None:
            w_local = _allgather_rows(w_local, counts)

    thresholds, n_cand, n_bins, quantized = sketches.to_thresholds(
        max_bins=max_bins, binning=binning
    )
    F = sketches.n_features
    chunk_rows = ds.resolve_chunk_rows() or memory_lib.ingest_chunk_rows(F)
    plan = memory_lib.plan_ingest(
        rows=n_rows, features=F, chunk_rows=chunk_rows,
        sketch_capacity=ds.sketch_capacity,
        mesh_axes={
            "data": mesh_lib.data_shards(mesh),
            "feature": mesh_lib.feature_shards(mesh),
        },
        max_bins=max_bins,
        spill_bytes=(
            None if spill_store is None else int(spill_store.bytes)
        ),
    )
    if obs is not None:
        obs.memory_plan(plan)

    t1 = time.perf_counter()
    # validate=False: the sketch pass already proved every row finite —
    # a second full finiteness sweep over an out-of-core dataset would
    # double the host-side scan cost for nothing.
    xb = place_mod.assemble_binned(
        mesh,
        (bin_with_thresholds(X, thresholds, n_cand)
         for X, _, _ in ds.chunks(validate=False)),
        n_rows=n_rows, n_features=F, row_offset=row_offset,
    )
    place_s = time.perf_counter() - t1

    binned = StreamedBinnedData(
        x_binned=xb, thresholds=thresholds, n_cand=n_cand,
        n_bins=n_bins, quantized=quantized, n_rows=n_rows,
        chunk_rows=int(chunk_rows),
    )
    stats = {
        "rows": int(n_rows),
        "rows_local": int(n_local),
        "features": int(F),
        "chunk_rows": int(chunk_rows),
        "n_bins": int(n_bins),
        "quantized": bool(quantized),
        "sketch_exact": bool(sketches.exact),
        "sketch_bytes": int(sketches.nbytes()),
        "sketch_s": round(sketch_s, 4),
        "bin_place_s": round(place_s, 4),
        "rows_per_s_host": (
            round(n_local / (sketch_s + place_s), 1)
            if sketch_s + place_s > 0 else None
        ),
    }
    if obs is not None:
        obs.decision(
            "ingest", "streamed",
            reason=(
                "fit(dataset=...): chunked sketch+bin ingest — the raw "
                "matrix never materializes on host; chunk size derived "
                f"from the {memory_lib.HOST_BUDGET_ENV} planner budget"
            ),
            **{k: stats[k] for k in (
                "rows", "features", "chunk_rows", "quantized",
                "sketch_exact",
            )},
        )
        host_rss = memory_lib.host_rss_bytes()
        if host_rss:
            stats["host_rss_bytes"] = int(host_rss)
    if spill_store is not None:
        stats["spill_bytes"] = int(spill_store.bytes)
        stats["spill_chunks"] = len(spill_store.names)
    return IngestResult(
        binned, y_local, w_local, stats,
        dataset=ds, spill=spill_store, row_offset=row_offset,
    )
