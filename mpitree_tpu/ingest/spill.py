"""Spill-to-disk rung for one-shot chunk iterators.

The ingest pipeline streams its source at least twice (sketch pass, then
bin+place; a hybrid refine tail adds a third raw-row pass), so chunk
sources must be re-iterable. A one-shot iterator — a socket reader, a
database cursor, a generator the caller cannot cheaply restart — can
still stream, IF the first pass tees every chunk to disk so later passes
replay from the spill instead of the exhausted iterator.

Layout mirrors ``resilience.checkpoint``'s durability contract: each
chunk lands as ``chunk-NNNNNN.npz`` via write-tmp-then-``os.replace``,
and a JSON manifest is written LAST — a spill directory without a
manifest is an aborted first pass and replay refuses it, never serving a
partial stream. The store is size-capped (``MPITREE_TPU_SPILL_BYTES``);
crossing the cap raises before the offending chunk is kept, so a
misconfigured stream cannot silently fill a disk.
"""

from __future__ import annotations

import io
import json
import os
import tempfile

import numpy as np

from mpitree_tpu.config import knobs

MANIFEST = "manifest.json"
SPILL_VERSION = 1


def _atomic_bytes(path: str, payload: bytes) -> None:
    """Write-tmp-then-replace: readers never observe a partial file."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class SpillStore:
    """One ingest run's on-disk chunk tail: append → commit → replay."""

    def __init__(self, directory: str, *, cap_bytes: int | None = None):
        self.dir = str(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.cap_bytes = int(
            knobs.value("MPITREE_TPU_SPILL_BYTES")
            if cap_bytes is None else cap_bytes
        )
        self.bytes = 0
        self.names: list = []
        self.rows = 0
        self.weighted = False
        self.committed = False

    # -- first pass --------------------------------------------------------
    def append(self, X: np.ndarray, y: np.ndarray, w) -> None:
        """Spill one normalized chunk; refuses past the size cap."""
        buf = io.BytesIO()
        arrays = {"X": X, "y": y}
        if w is not None:
            arrays["w"] = w
            self.weighted = True
        np.savez(buf, **arrays)
        payload = buf.getvalue()
        if self.bytes + len(payload) > self.cap_bytes:
            raise RuntimeError(
                f"spill store at {self.dir} would exceed its "
                f"MPITREE_TPU_SPILL_BYTES cap ({self.cap_bytes} bytes) at "
                f"chunk {len(self.names)} ({self.bytes + len(payload)} "
                "bytes total): raise the cap, shrink the stream, or hand "
                "the pipeline a re-iterable source"
            )
        name = f"chunk-{len(self.names):06d}.npz"
        _atomic_bytes(os.path.join(self.dir, name), payload)
        self.bytes += len(payload)
        self.rows += int(X.shape[0])
        self.names.append(name)

    def commit(self) -> None:
        """Manifest write = the commit point (checkpoint discipline)."""
        manifest = {
            "version": SPILL_VERSION,
            "chunks": self.names,
            "rows": int(self.rows),
            "bytes": int(self.bytes),
            "weighted": bool(self.weighted),
        }
        _atomic_bytes(
            os.path.join(self.dir, MANIFEST),
            json.dumps(manifest, indent=0).encode(),
        )
        self.committed = True

    # -- replay ------------------------------------------------------------
    def chunks(self, chunk_rows=None, *, validate: bool = True):
        """Replay the committed stream at its recorded chunk shapes
        (``chunk_rows`` is ignored, like ``NpzShards``)."""
        path = os.path.join(self.dir, MANIFEST)
        if not os.path.exists(path):
            raise RuntimeError(
                f"spill store at {self.dir} has no manifest: the first "
                "pass never committed (aborted stream?) — refusing to "
                "replay a partial spill"
            )
        with open(path) as f:
            manifest = json.load(f)
        for name in manifest["chunks"]:
            with np.load(os.path.join(self.dir, name)) as z:
                yield (
                    z["X"], z["y"],
                    z["w"] if manifest["weighted"] else None,
                )

    def close(self) -> None:
        """Best-effort cleanup of the spill files and directory."""
        try:
            for name in os.listdir(self.dir):
                if name == MANIFEST or name.startswith("chunk-"):
                    os.unlink(os.path.join(self.dir, name))
            os.rmdir(self.dir)
        except OSError:
            pass  # a stray file or a racing reader: leave the directory


class SpillTee:
    """A one-shot source made repeatable: the first ``.chunks()`` pass
    drains the underlying iterator while teeing every chunk into the
    store; every later pass replays from disk."""

    one_shot = False  # the whole point

    def __init__(self, source, store: SpillStore):
        self._source = source
        self.store = store
        self.n_features = getattr(source, "n_features", None)
        self.n_rows = getattr(source, "n_rows", None)

    def chunks(self, chunk_rows=None, *, validate: bool = True):
        if self.store.committed:
            yield from self.store.chunks(chunk_rows, validate=validate)
            return
        for X, y, w in self._source.chunks(chunk_rows, validate=validate):
            self.store.append(X, y, w)
            yield X, y, w
        self.store.commit()


def resolve_spill(source, *, obs=None):
    """Gate a one-shot source through the spill rung.

    Re-iterable sources pass through untouched. One-shot sources require
    ``MPITREE_TPU_SPILL_DIR``; with it set, the source wraps in a
    :class:`SpillTee` over a fresh store subdirectory and the typed
    ``ingest_spill`` decision records the rung. Returns
    ``(source, store | None)``.
    """
    if not getattr(source, "one_shot", False):
        return source, None
    spill_dir = knobs.value("MPITREE_TPU_SPILL_DIR")
    if not spill_dir:
        raise ValueError(
            "one-shot chunk iterator with no spill rung: the ingest "
            "pipeline streams its source more than once (sketch, then "
            "bin+place), so a bare iterator must spill — set "
            "MPITREE_TPU_SPILL_DIR to a scratch directory (size-capped "
            "by MPITREE_TPU_SPILL_BYTES) or pass a re-iterable source "
            "(a zero-arg factory, shard paths, or a chunk list)"
        )
    store = SpillStore(
        tempfile.mkdtemp(prefix="spill-", dir=str(spill_dir))
    )
    if obs is not None:
        obs.decision(
            "ingest_spill", "spill",
            reason=(
                "one-shot chunk iterator: first pass tees every chunk to "
                "disk (atomic chunk files, manifest-last commit) so the "
                "bin+place and refine passes replay from the spill"
            ),
            dir=store.dir, cap_bytes=int(store.cap_bytes),
        )
    return SpillTee(source, store), store
