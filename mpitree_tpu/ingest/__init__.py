"""mpitree_tpu.ingest — out-of-core streaming ingestion (ISSUE 15).

The last single-host bottleneck after the PR-10 2-D mesh was ``fit(X, y)``
itself: the raw feature matrix had to exist whole in one host's RAM
before binning. This tier removes it. Input arrives host-chunked (plain
chunk iterators, in-memory arrays re-chunked for testing, or
memory-mapped ``.npy``/``.npz`` shards); ONE streaming pass fits a
mergeable per-feature quantile sketch (``sketch.py`` — bit-identical to
``ops.binning.bin_dataset``'s edges on shared sizes, documented
approximate past the sketch capacity); a second pass bins each chunk
against the packed thresholds and ``device_put``s it DIRECTLY onto its
mesh slot per ``parallel/partition.py``'s ``x_binned`` rule
(``place.py``) — the full raw matrix never materializes on any host.

Chunk sizing derives from the ``obs.memory`` planner's host budget
(``memory.ingest_chunk_rows`` — the priced form of "how many rows fit"),
never from ad-hoc constants. Multi-host fits ride the existing
``parallel.distributed.initialize()``: each process streams only its own
shard of the source and the sketches merge across processes.

Estimator surface: ``DecisionTreeClassifier().fit(StreamedDataset...)``
(or ``fit(dataset=...)``); construct datasets via
:meth:`StreamedDataset.from_arrays` / :meth:`~StreamedDataset.from_npy` /
:meth:`~StreamedDataset.from_npz` / :meth:`~StreamedDataset.from_chunks`.
"""

from mpitree_tpu.ingest.chunks import (
    ArrayChunks,
    IterChunks,
    NpyShards,
    NpzShards,
    shard_for_process,
)
from mpitree_tpu.ingest.sketch import FeatureSketch, SketchSet
from mpitree_tpu.ingest.stream import (
    IngestResult,
    StreamedDataset,
    ingest_dataset,
    sketch_dataset,
)

__all__ = [
    "ArrayChunks",
    "FeatureSketch",
    "IngestResult",
    "IterChunks",
    "NpyShards",
    "NpzShards",
    "SketchSet",
    "StreamedDataset",
    "ingest_dataset",
    "shard_for_process",
    "sketch_dataset",
]
