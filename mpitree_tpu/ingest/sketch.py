"""Mergeable per-feature quantile sketches — the streaming binner's core.

``ops.binning.bin_dataset`` selects edges as ORDER STATISTICS of the full
column: exact mode keeps every unique value, quantile mode gathers the
sorted column at host-f64 indices (``_quantile_indices``). A streaming
pass cannot sort the full column, but it can maintain the column's exact
``(unique value, count)`` summary — unique sets merge associatively
across chunks (and, multi-host, across processes), and any order
statistic reads off the merged summary by cumulative count. While the
summary stays exact, streamed edges are therefore **bit-identical** to
the in-memory path's on shared sizes:

- exact/auto edges: ``values[:-1]`` == ``np.unique(col)[:-1]``;
- quantile edges: ``sorted_col[i] == values[searchsorted(cumsum(counts),
  i, side="right")]`` for every gather index ``i`` — the SAME
  ``_quantile_indices`` host-f64 arithmetic, the same ``np.unique``
  dedup.

Past :data:`SKETCH_CAPACITY` unique values per feature the summary
COMPACTS (documented sketch-mode fallback): adjacent pairs collapse —
even-index values absorb their right neighbor's count — which preserves
total weight and keeps every edge a real data value, at the cost of
rank error bounded by the widest surviving gap. Compaction is
deterministic (no RNG) and merge-stable, so every mesh size and chunk
split of the same stream produces the same sketch; a compacted feature
forces ``quantized=True`` and is flagged ``exact=False`` so callers can
refuse ``binning="exact"``.

Host-side numpy only — no jax import at module level.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from mpitree_tpu.ops.binning import _quantile_indices, pack_edges
from mpitree_tpu.config import knobs

# Per-feature unique-value cap before the sketch compacts (~12 MiB of
# (f32 value, i64 count) pairs per feature at the default). Overridable
# per call and via the env knob for constrained hosts.
SKETCH_CAPACITY = 1 << 20
SKETCH_CAPACITY_ENV = "MPITREE_TPU_SKETCH_CAPACITY"


def resolve_capacity(capacity: int | None = None) -> int:
    if capacity is not None:
        return max(int(capacity), 2)
    env = knobs.raw(SKETCH_CAPACITY_ENV)
    if env:
        try:
            return max(int(env), 2)
        except ValueError:
            pass
    return SKETCH_CAPACITY


def _merge_unique(v1, c1, v2, c2) -> tuple:
    """Merge two sorted-unique (values, counts) summaries exactly."""
    if not len(v1):
        return v2, c2
    if not len(v2):
        return v1, c1
    v = np.concatenate([v1, v2])
    c = np.concatenate([c1, c2])
    uv, inv = np.unique(v, return_inverse=True)
    uc = np.zeros(len(uv), np.int64)
    np.add.at(uc, inv, c)
    return uv, uc


@dataclasses.dataclass
class FeatureSketch:
    """One feature's mergeable ``(unique values, counts)`` summary."""

    values: np.ndarray = dataclasses.field(
        default_factory=lambda: np.empty(0, np.float32)
    )
    counts: np.ndarray = dataclasses.field(
        default_factory=lambda: np.empty(0, np.int64)
    )
    exact: bool = True
    capacity: int = SKETCH_CAPACITY

    @property
    def n(self) -> int:
        """Total weight (rows) the sketch has absorbed."""
        return int(self.counts.sum())

    @property
    def n_unique(self) -> int:
        return len(self.values)

    def update(self, col: np.ndarray) -> None:
        """Absorb one chunk's column (must already be finite f32)."""
        uv, uc = np.unique(
            np.ascontiguousarray(col, np.float32), return_counts=True
        )
        self.values, self.counts = _merge_unique(
            self.values, self.counts, uv, uc.astype(np.int64)
        )
        self._compact_if_needed()

    def merge(self, other: FeatureSketch) -> None:
        """Absorb another sketch (cross-chunk / cross-process merge)."""
        self.values, self.counts = _merge_unique(
            self.values, self.counts, other.values, other.counts
        )
        self.exact = self.exact and other.exact
        self._compact_if_needed()

    def _compact_if_needed(self) -> None:
        while len(self.values) > self.capacity:
            # Pair-collapse: even indices keep their value and absorb the
            # right neighbor's count. Values remain real data, total
            # weight is preserved, and the result is a valid summary for
            # the next merge — the deterministic sketch-mode fallback.
            c = self.counts
            if len(c) % 2:
                c = np.concatenate([c, np.zeros(1, np.int64)])
            self.counts = c[0::2] + c[1::2]
            self.values = self.values[0::2]
            self.exact = False

    def edges(self, *, max_bins: int, binning: str) -> tuple:
        """(edges f32, quantized) — the ``bin_dataset`` edge selection
        restated over the summary (bit-identical while ``exact``)."""
        if binning == "exact" or (
            binning == "auto" and self.exact and self.n_unique <= max_bins
        ):
            if not self.exact:
                raise ValueError(
                    "binning='exact' on a stream that exceeded the sketch "
                    f"capacity ({self.capacity} unique values): exact "
                    "candidates are no longer recoverable — use "
                    "binning='auto'/'quantile' or raise the capacity "
                    f"({SKETCH_CAPACITY_ENV})"
                )
            return self.values[:-1].astype(np.float32), False
        n = self.n
        if n < 1 or not self.n_unique:
            return np.empty(0, np.float32), binning == "quantile"
        # The same host-f64 gather indices as bin_dataset; the sorted
        # column's value at rank i is values[searchsorted(cum, i, "right")].
        idx = _quantile_indices(n, max_bins)
        pos = np.searchsorted(np.cumsum(self.counts), idx, side="right")
        edges = np.unique(self.values[pos].astype(np.float32))
        return edges, True


class SketchSet:
    """Per-feature sketch bank for one stream (plus the row total)."""

    def __init__(self, n_features: int, *, capacity: int | None = None):
        cap = resolve_capacity(capacity)
        self.sketches = [
            FeatureSketch(capacity=cap) for _ in range(int(n_features))
        ]
        self.n_rows = 0

    @property
    def n_features(self) -> int:
        return len(self.sketches)

    @property
    def exact(self) -> bool:
        return all(s.exact for s in self.sketches)

    def update(self, X_chunk: np.ndarray) -> None:
        X_chunk = np.ascontiguousarray(X_chunk, np.float32)
        if X_chunk.shape[1] != self.n_features:
            raise ValueError(
                f"chunk has {X_chunk.shape[1]} features, stream started "
                f"with {self.n_features}"
            )
        Xt = np.ascontiguousarray(X_chunk.T)
        for f, sk in enumerate(self.sketches):
            sk.update(Xt[f])
        self.n_rows += X_chunk.shape[0]

    def merge(self, other: SketchSet) -> None:
        if other.n_features != self.n_features:
            raise ValueError("cannot merge sketch sets of different width")
        for mine, theirs in zip(self.sketches, other.sketches):
            mine.merge(theirs)
        self.n_rows += other.n_rows

    def merge_across_processes(self) -> None:
        """Fold every process's sketches into the same global summary.

        Each process streams only its shard (``chunks.shard_for_process``)
        then calls this once; afterwards all processes hold identical
        edges, so all bin identically — the multi-host twin of the
        single-process merge. No-op single-process.
        """
        import jax

        if jax.process_count() <= 1:
            return
        from jax.experimental import multihost_utils

        # Variable-length summaries allgather through one padded buffer:
        # +inf value padding with zero count is inert under merge.
        width = max((s.n_unique for s in self.sketches), default=0)
        width = int(multihost_utils.process_allgather(
            np.array([width], np.int64)
        ).max())
        vals = np.full((self.n_features, max(width, 1)), np.inf, np.float32)
        cnts = np.zeros((self.n_features, max(width, 1)), np.int64)
        for f, s in enumerate(self.sketches):
            vals[f, : s.n_unique] = s.values
            cnts[f, : s.n_unique] = s.counts
        all_vals = multihost_utils.process_allgather(vals)
        all_cnts = multihost_utils.process_allgather(cnts)
        exact = bool(multihost_utils.process_allgather(
            np.array([self.exact], bool)
        ).all())
        n_rows = int(multihost_utils.process_allgather(
            np.array([self.n_rows], np.int64)
        ).sum())
        cap = self.sketches[0].capacity if self.sketches else SKETCH_CAPACITY
        merged = [FeatureSketch(capacity=cap) for _ in range(self.n_features)]
        for p in range(all_vals.shape[0]):
            for f, sk in enumerate(merged):
                keep = all_cnts[p, f] > 0
                sk.merge(FeatureSketch(
                    values=all_vals[p, f][keep],
                    counts=all_cnts[p, f][keep],
                    capacity=cap,
                ))
                sk.exact = sk.exact and exact
        self.sketches = merged
        self.n_rows = n_rows

    def to_thresholds(self, *, max_bins: int, binning: str) -> tuple:
        """(thresholds, n_cand, n_bins, quantized) via the shared
        ``ops.binning.pack_edges`` packaging."""
        per_feature = []
        quantized = False
        for sk in self.sketches:
            e, q = sk.edges(max_bins=max_bins, binning=binning)
            quantized = quantized or q or not sk.exact
            per_feature.append(e)
        return pack_edges(per_feature, quantized=quantized)

    def nbytes(self) -> int:
        """Host bytes the summaries currently hold (the planner's
        ``sketch`` row reads the a-priori bound, this the realized)."""
        return sum(s.values.nbytes + s.counts.nbytes for s in self.sketches)
