"""Chunk-source protocol: how host-chunked input reaches the ingest tier.

A chunk source is anything whose :meth:`chunks` yields ``(X, y)`` or
``(X, y, w)`` tuples of aligned numpy arrays, REPEATABLY — the pipeline
streams the source twice (sketch pass, then bin+place pass), so one-shot
generators must come wrapped in a factory (:class:`IterChunks`). Sources
that know their shape up front (:class:`ArrayChunks`, :class:`NpyShards`)
expose ``n_features``/``n_rows`` so chunk sizing can be planner-derived
before the first chunk is read; iterator sources own their chunking.

``.npy`` shards open memory-mapped (``np.load(mmap_mode="r")``): slicing
``chunk_rows`` at a time faults in only those pages, so host residency
stays chunk-bounded no matter the shard size. ``.npz`` members cannot
mmap — each shard is one chunk there, so shard files must themselves be
chunk-sized.

Multi-host: :func:`shard_for_process` deals a shard list contiguously
across ``jax.process_count()`` processes — each process streams only its
slice, and the sketch/placement layers handle the global merge.
"""

from __future__ import annotations

import glob as glob_mod

import numpy as np


def _normalize(item, validate: bool = True) -> tuple:
    """One yielded item -> (X f32 (n, F), y (n,), w (n,)|None).

    ``validate=False`` skips the O(n*F) finiteness sweep — the pipeline
    streams every source twice, and the bin+place pass re-reads rows the
    sketch pass already proved finite (a second full scan of an
    out-of-core dataset would be pure overhead).
    """
    if not isinstance(item, (tuple, list)) or len(item) not in (2, 3):
        raise TypeError(
            "chunk sources must yield (X, y) or (X, y, sample_weight) "
            f"tuples, got {type(item).__name__}"
        )
    X = np.ascontiguousarray(item[0], dtype=np.float32)
    if X.ndim != 2:
        raise ValueError(f"chunk X must be 2-D, got shape {X.shape}")
    if validate and not np.isfinite(X).all():
        raise ValueError(
            "chunk X contains NaN/inf: streamed ingestion requires finite "
            "features (the sketch's sorted-unique merge has no NaN "
            "collapse; clean or impute before streaming)"
        )
    y = np.asarray(item[1])
    if y.shape != (X.shape[0],):
        raise ValueError(
            f"chunk y has shape {y.shape}, expected ({X.shape[0]},)"
        )
    w = None
    if len(item) == 3 and item[2] is not None:
        w = np.ascontiguousarray(item[2], dtype=np.float32)
        if w.shape != (X.shape[0],):
            raise ValueError(
                f"chunk sample_weight has shape {w.shape}, expected "
                f"({X.shape[0]},)"
            )
    return X, y, w


class ArrayChunks:
    """In-memory arrays re-chunked — the testing/identity-grid source."""

    def __init__(self, X, y, sample_weight=None, *, chunk_rows=None):
        self.X = np.asarray(X)
        self.y = np.asarray(y)
        self.w = None if sample_weight is None else np.asarray(sample_weight)
        self.chunk_rows = chunk_rows

    @property
    def n_rows(self) -> int:
        return self.X.shape[0]

    @property
    def n_features(self) -> int:
        return self.X.shape[1]

    def chunks(self, chunk_rows=None, *, validate=True):
        rows = int(chunk_rows or self.chunk_rows or max(self.n_rows, 1))
        for lo in range(0, self.n_rows, rows) or [0]:
            hi = min(lo + rows, self.n_rows)
            yield _normalize((
                self.X[lo:hi], self.y[lo:hi],
                None if self.w is None else self.w[lo:hi],
            ), validate)


class NpyShards:
    """Memory-mapped ``.npy`` shard pairs, sliced ``chunk_rows`` at a time."""

    def __init__(self, x_paths, y_paths, weight_paths=None, *,
                 chunk_rows=None):
        self.x_paths = _expand(x_paths)
        self.y_paths = _expand(y_paths)
        self.w_paths = None if weight_paths is None else _expand(weight_paths)
        if len(self.x_paths) != len(self.y_paths):
            raise ValueError(
                f"{len(self.x_paths)} X shards vs {len(self.y_paths)} "
                "y shards: shard lists must pair up"
            )
        if self.w_paths is not None and len(self.w_paths) != len(self.x_paths):
            raise ValueError("weight shard list must pair with X shards")
        if not self.x_paths:
            raise ValueError("no shards matched")
        self.chunk_rows = chunk_rows

    @property
    def n_features(self) -> int:
        return int(np.load(self.x_paths[0], mmap_mode="r").shape[1])

    @property
    def n_rows(self) -> int:
        return sum(
            int(np.load(p, mmap_mode="r").shape[0]) for p in self.x_paths
        )

    def chunks(self, chunk_rows=None, *, validate=True):
        rows = self.chunk_rows if chunk_rows is None else chunk_rows
        for i, xp in enumerate(self.x_paths):
            X = np.load(xp, mmap_mode="r")
            y = np.load(self.y_paths[i], mmap_mode="r")
            w = (None if self.w_paths is None
                 else np.load(self.w_paths[i], mmap_mode="r"))
            step = int(rows or len(X) or 1)
            for lo in range(0, len(X), step) or [0]:
                hi = min(lo + step, len(X))
                # np.array(...) faults in just this window's pages; the
                # mmap itself never materializes whole.
                yield _normalize((
                    np.array(X[lo:hi]), np.array(y[lo:hi]),
                    None if w is None else np.array(w[lo:hi]),
                ), validate)


class NpzShards:
    """``.npz`` shard files — one chunk per file (members cannot mmap)."""

    def __init__(self, paths, *, x_key="X", y_key="y", weight_key=None):
        self.paths = _expand(paths)
        if not self.paths:
            raise ValueError("no shards matched")
        self.x_key, self.y_key, self.w_key = x_key, y_key, weight_key

    @property
    def n_features(self) -> int:
        with np.load(self.paths[0]) as z:
            return int(z[self.x_key].shape[1])

    def chunks(self, chunk_rows=None, *, validate=True):
        for p in self.paths:
            with np.load(p) as z:
                yield _normalize((
                    z[self.x_key], z[self.y_key],
                    z[self.w_key] if self.w_key else None,
                ), validate)


class IterChunks:
    """A re-iterable wrapped as a source: a zero-arg FACTORY returning a
    fresh ``(X, y[, w])`` iterator per pass (the pipeline streams more
    than once), or a list/tuple of chunk tuples. A bare one-shot
    iterator (a generator, a cursor) is accepted too, flagged
    ``one_shot`` — the ingest pipeline then requires the spill rung
    (``MPITREE_TPU_SPILL_DIR``) so later passes replay from disk."""

    n_features = None  # discovered from the first chunk
    n_rows = None
    one_shot = False

    def __init__(self, chunks_or_factory):
        if callable(chunks_or_factory):
            self._factory = chunks_or_factory
        elif isinstance(chunks_or_factory, (list, tuple)):
            items = list(chunks_or_factory)
            self._factory = lambda: iter(items)
        elif hasattr(chunks_or_factory, "__next__"):
            self._iter = chunks_or_factory
            self.one_shot = True
            self._factory = self._drain_once
        else:
            raise TypeError(
                "from_chunks wants a zero-arg factory returning a fresh "
                "iterator, a list of (X, y[, w]) tuples, or a one-shot "
                "iterator (which needs MPITREE_TPU_SPILL_DIR set so the "
                "pipeline's later passes can replay it from disk)"
            )

    def _drain_once(self):
        it, self._iter = self._iter, None
        if it is None:
            raise RuntimeError(
                "one-shot chunk iterator already consumed — the ingest "
                "pipeline streams its source more than once; spill was "
                "expected to replay this pass (MPITREE_TPU_SPILL_DIR)"
            )
        return it

    def chunks(self, chunk_rows=None, *, validate=True):
        for item in self._factory():
            yield _normalize(item, validate)


def _expand(paths) -> list:
    """A glob string, one path, or a path list -> sorted path list."""
    if isinstance(paths, (str, bytes)):
        hits = sorted(glob_mod.glob(paths))
        return hits if hits else [paths]
    return [str(p) for p in paths]


def shard_for_process(items: list, process_index: int | None = None,
                      process_count: int | None = None) -> list:
    """This process's contiguous slice of a shard list (multi-host
    loading: each process reads ONLY its shard —
    ``parallel.distributed.initialize()`` first, then build the source
    from ``shard_for_process(all_paths)``)."""
    if process_index is None or process_count is None:
        import jax

        process_index = jax.process_index()
        process_count = jax.process_count()
    k, n = int(process_count), len(items)
    lo = (n * int(process_index)) // k
    hi = (n * (int(process_index) + 1)) // k
    return list(items[lo:hi])
