"""Device-mesh management — the TPU-native replacement for MPI communicators.

The reference bootstraps ``MPI.COMM_WORLD`` at import time and parallelizes by
recursively splitting communicators (reference:
``mpitree/tree/decision_tree.py:313-338``). Here the unit of distribution is a
``jax.sharding.Mesh``: a 1-D ``"data"`` axis shards rows (histogram
reductions ride ICI via ``lax.psum``); an optional 2-D ``(data, feature)``
mesh additionally shards the histogram's feature dimension (tensor
parallelism); a ``"tree"`` mesh shards whole ensemble members. Multi-host
(DCN) scaling uses the same code after ``jax.distributed.initialize`` — no
communicator tree, because the breadth-first builder turns the reference's
subtree task-parallelism into a batch dimension.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

# graftlint: partition-table — axis-generic placement helpers
# (shard_rows/replicate build rank-generic specs from axis names, not
# array names; every name-specific spec lives in parallel/partition.py).
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
TREE_AXIS = "tree"
FEATURE_AXIS = "feature"


def available_devices(backend: str | None = None) -> list:
    """Devices for ``backend`` (None = JAX default platform)."""
    return jax.devices() if backend is None else jax.devices(backend)


@lru_cache(maxsize=32)
def _cached_mesh(device_key: tuple, backend: str | None) -> Mesh:
    devs = available_devices(backend)
    picked = [devs[i] for i in device_key]
    return Mesh(np.array(picked), (DATA_AXIS,))


@lru_cache(maxsize=32)
def _cached_mesh_named(devices: tuple, axis: str) -> Mesh:
    return Mesh(np.array(list(devices)), (axis,))


def as_tree_mesh(mesh: Mesh) -> Mesh:
    """Same devices, ``tree`` axis — for ensemble (tree-axis) parallelism."""
    return _cached_mesh_named(tuple(mesh.devices.flat), TREE_AXIS)


def tree_data_shape(n_devices: int, n_trees: int, *, dataset_bytes: int = 0,
                    hbm_budget: int | None = None) -> tuple:
    """(tree_shards, data_shards) for the forest's 2-D ensemble mesh.

    Policy: give the tree axis the widest divisor of ``n_devices`` that the
    ensemble can fill (``<= n_trees``) — surplus devices become a data axis
    that row-shards each tree's build (psum inside the tree group), so a
    2-tree forest on 8 chips runs each tree data-parallel over 4 instead of
    idling 6. Then the HBM guard: while the replicated binned matrix would
    exceed ``hbm_budget`` per device, trade tree-axis width for more row
    sharding. With ``tree_shards < n_trees`` each device builds its tree
    batch sequentially (``lax.map``), exactly as before.
    """
    from mpitree_tpu.obs import memory as memory_lib

    d = max(int(n_devices), 1)
    divisors = [k for k in range(1, d + 1) if d % k == 0]
    t = max(k for k in divisors if k <= max(int(n_trees), 1))
    # The HBM guard's arithmetic lives in obs.memory (ISSUE 12: the
    # capacity planner and the shape policy read ONE pricing source;
    # pinned equal to the pre-refactor inline loop).
    t = memory_lib.tree_shards_for_budget(
        t, dataset_bytes, hbm_budget, divisors, d
    )
    return t, d // t


@lru_cache(maxsize=32)
def _cached_mesh_tree_data(devices: tuple, shape: tuple) -> Mesh:
    picked = np.array(list(devices)).reshape(shape)
    return Mesh(picked, (TREE_AXIS, DATA_AXIS))


def as_tree_data_mesh(mesh: Mesh, shape: tuple) -> Mesh:
    """Same devices on a 2-D ``(tree, data)`` mesh of the given shape."""
    return _cached_mesh_tree_data(tuple(mesh.devices.flat), tuple(shape))


@lru_cache(maxsize=32)
def _cached_mesh_2d(device_key: tuple, shape: tuple, backend: str | None) -> Mesh:
    devs = available_devices(backend)
    picked = np.array([devs[i] for i in device_key]).reshape(shape)
    return Mesh(picked, (DATA_AXIS, FEATURE_AXIS))


def resolve_mesh(*, backend: str | None = None, n_devices=None) -> Mesh:
    """Build the device mesh.

    ``n_devices=None`` -> single device (sequential semantics, like the
    reference's plain ``DecisionTreeClassifier``); ``n_devices="all"`` or
    ``-1`` -> every visible device (the ``mpirun -n <world>`` analogue);
    an int -> that many devices on a 1-D ``data`` axis; a ``(dr, df)``
    tuple -> a 2-D ``(data, feature)`` mesh — rows shard over ``dr``
    devices and the histogram's feature dimension over ``df`` (the
    tensor-parallel option; the reference scans features serially,
    ``decision_tree.py:411-416``).
    """
    devs = available_devices(backend)
    if isinstance(n_devices, tuple):
        dr, df = int(n_devices[0]), int(n_devices[1])
        if dr < 1 or df < 1 or dr * df > len(devs):
            raise ValueError(
                f"mesh shape {n_devices} needs {dr * df} devices but only "
                f"{len(devs)} are visible for backend={backend!r}"
            )
        if df == 1:
            return _cached_mesh(tuple(range(dr)), backend)
        return _cached_mesh_2d(tuple(range(dr * df)), (dr, df), backend)
    if n_devices in (None, 1):
        n = 1
    elif n_devices in ("all", -1):
        n = len(devs)
    else:
        n = int(n_devices)
        if n < 1 or n > len(devs):
            raise ValueError(
                f"n_devices={n} requested but only {len(devs)} devices are "
                f"visible for backend={backend!r}"
            )
    return _cached_mesh(tuple(range(n)), backend)


def data_feature_shape(n_devices: int, n_features: int, *,
                       hist_bytes: int = 0,
                       hist_budget: int | None = None) -> tuple:
    """(data_shards, feature_shards) for the 2-D single-tree build mesh.

    The mirror of :func:`tree_data_shape`'s policy, restated for the
    ``(data, feature)`` mesh: give the DATA axis the widest divisor of
    ``n_devices`` (histogram psums ride it, and row sharding is what the
    level loop scales by), then the histogram-budget guard — while one
    shard's per-chunk histogram slab (``hist_bytes / feature_shards``)
    would exceed ``hist_budget`` — trades data-axis width for feature
    shards, i.e. picks the widest feature divisor needed for the
    per-shard slab to fit (capped at ``n_features``: a shard with zero
    real columns does no work). When even the widest usable feature
    divisor cannot fit the budget, it is used anyway — the guard
    degrades, it never refuses.

    ``hist_bytes``: the feature-complete per-device histogram cost the
    caller sizes chunks from (``K * F * C * B * itemsize``, see
    ``core/builder._chunk_size``); ``hist_budget`` the same
    ``BuildConfig.hist_budget_bytes`` knob that sizes the live chunk.
    """
    from mpitree_tpu.obs import memory as memory_lib

    d = max(int(n_devices), 1)
    divisors = [k for k in range(1, d + 1) if d % k == 0]
    usable = [k for k in divisors if k <= max(int(n_features), 1)]
    # Feature-shard engagement threshold: obs.memory owns the arithmetic
    # (the ONE pricing source — pinned equal to the pre-refactor inline
    # loop on the existing test grid).
    f = memory_lib.feature_shards_for_budget(hist_bytes, hist_budget, usable)
    return d // f, f


def resolve_mesh_2d(*, n_features: int, hist_bytes: int = 0,
                    hist_budget: int | None = None,
                    backend: str | None = None, n_devices=None,
                    chunk_slots: int | None = None,
                    n_classes: int | None = None,
                    n_bins: int | None = None,
                    policy_evidence: str = "auto",
                    obs=None) -> Mesh:
    """2-D ``(data, feature)`` mesh factory with the shape policy applied.

    ``n_devices`` follows :func:`resolve_mesh`'s grammar for a TOTAL
    device count (None/int/"all"); the split between the two axes comes
    from :func:`data_feature_shape`. An explicit ``(dr, df)`` tuple
    bypasses the policy (same as :func:`resolve_mesh`).

    ``chunk_slots``/``n_classes``/``n_bins`` (optional): price
    ``hist_bytes`` from the workload shape via the obs.memory slab
    formula instead of passing pre-computed bytes — the planner-driven
    form (``hist_bytes`` wins when both are given).
    """
    if isinstance(n_devices, tuple):
        return resolve_mesh(backend=backend, n_devices=n_devices)
    if not hist_bytes and chunk_slots and n_bins:
        from mpitree_tpu.obs import memory as memory_lib

        hist_bytes = memory_lib.slab_bytes(
            chunk_slots, n_features, n_classes or 2, n_bins
        )
    devs = available_devices(backend)
    if n_devices in (None, 1):
        n = 1
    elif n_devices in ("all", -1):
        n = len(devs)
    else:
        n = int(n_devices)
    # Evidence consultation (obs/advisor.py, ISSUE 18): stored mesh2d_ab
    # A/Bs on this platform may override the budget-driven split — a
    # measured 1-D winner collapses the feature axis, a measured 2-D
    # winner keeps the policy split. An explicit (dr, df) tuple above
    # bypasses this like it bypasses the policy.
    if n > 1:
        from mpitree_tpu.obs import advisor

        adv = advisor.advise_mesh_2d(
            platform=devs[0].platform if devs else None,
            policy_evidence=policy_evidence,
            shape={"n_features": int(n_features), "n_devices": int(n)},
        )
        advisor.record_advice(obs, adv)
        if adv is not None and adv["value"] == "1d":
            return resolve_mesh(backend=backend, n_devices=(n, 1))
        if (adv is not None and adv["value"] == "2d"
                and n % 2 == 0 and n_features >= 2):
            # The A/B measured (D, 1) vs (D/2, 2); a 2-D verdict applies
            # the measured shape, not a deeper untested feature split.
            return resolve_mesh(backend=backend, n_devices=(n // 2, 2))
    shape = data_feature_shape(
        n, n_features, hist_bytes=hist_bytes, hist_budget=hist_budget
    )
    return resolve_mesh(backend=backend, n_devices=shape)


def feature_shards(mesh: Mesh) -> int:
    """Width of the mesh's feature axis (1 on a 1-D data mesh)."""
    return (
        mesh.shape[FEATURE_AXIS] if FEATURE_AXIS in mesh.axis_names else 1
    )


def data_shards(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS] if DATA_AXIS in mesh.axis_names else 1


def shard_rows(mesh: Mesh, *arrays):
    """device_put each (N, ...) array row-sharded over the mesh's data axis."""
    out = []
    for a in arrays:
        spec = P(DATA_AXIS, *([None] * (a.ndim - 1)))
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return out[0] if len(out) == 1 else tuple(out)


def replicate(mesh: Mesh, *arrays):
    """device_put each array fully replicated over the mesh."""
    out = [jax.device_put(a, NamedSharding(mesh, P())) for a in arrays]
    return out[0] if len(out) == 1 else tuple(out)


def pad_rows(n: int, n_devices: int) -> int:
    """Rows of padding needed so n divides evenly across devices."""
    return (-n) % n_devices


def pad_row_arrays(xb, y, w, nid, n_shards: int):
    """Pad (xb, y, w, nid) so rows divide ``n_shards`` evenly.

    THE one copy of the padding contract both the single-tree and forest
    engines rely on: padding rows carry ``node_id=-1`` and weight 0, so
    every kernel masks them out. ``w`` may be 1-D (N,) or a stacked
    (T, N) per-tree weight matrix — padding lands on the row axis either
    way. ``xb=None`` pads only the row-state arrays (the streamed-ingest
    path, whose matrix was assembled pre-padded on device).
    """
    pad = pad_rows(len(y), n_shards)
    if not pad:
        return xb, y, w, nid
    if xb is not None:
        # A device-binned matrix (ops/binning.bin_dataset_device) pads in
        # place on the accelerator; np.concatenate would silently
        # round-trip it to host through __array__.
        xp = jnp if isinstance(xb, jax.Array) else np
        xb = xp.concatenate([xb, xp.zeros((pad, xb.shape[1]), xb.dtype)])
    y = np.concatenate([y, np.zeros(pad, y.dtype)])
    if w.ndim == 1:
        w = np.concatenate([w, np.zeros(pad, np.float32)])
    else:
        w = np.concatenate(
            [w, np.zeros((w.shape[0], pad), np.float32)], axis=1
        )
    nid = np.concatenate([nid, np.full(pad, -1, np.int32)])
    return xb, y, w, nid


def shard_build_inputs(mesh: Mesh, binned, y, sample_weight):
    """One-time device placement shared by both build engines.

    Pads rows to the data-axis width (padding rows get ``node_id=-1`` /
    weight 0, so every kernel masks them out) and shards
    (x_binned, y, w, node_id) over the ``data`` axis. On a 2-D
    ``(data, feature)`` mesh the binned matrix and candidate mask also
    shard their feature dimension (padding features have zero candidates —
    inert). Returns the four sharded arrays plus the candidate mask.
    """
    # Placement rides the partition-rule table (parallel/partition.py):
    # every named array gets its spec from the one declarative map both
    # engines also derive their shard_map in_specs from. Lazy import —
    # partition reads this module's axis constants at load.
    from mpitree_tpu.parallel import partition

    # Real extents come from the dataclass, not the array: a streamed
    # matrix (ops/binning.StreamedBinnedData) arrives PRE-padded and
    # pre-placed by the ingest tier — its shape already carries the
    # mesh's axis padding, while n_samples/n_features stay real.
    from mpitree_tpu.ops.binning import StreamedBinnedData

    N, F = binned.n_samples, binned.n_features
    dr = data_shards(mesh)
    df = feature_shards(mesh)
    fpad = (-F) % df
    cand = binned.candidate_mask()
    w = (np.ones(N, np.float32) if sample_weight is None
         else sample_weight.astype(np.float32))
    prepadded = isinstance(binned, StreamedBinnedData)
    if prepadded and binned.x_binned.shape != (
        N + pad_rows(N, dr), F + fpad
    ):
        raise ValueError(
            f"pre-placed x_binned has shape {binned.x_binned.shape}; this "
            f"mesh pads ({N}, {F}) to ({N + pad_rows(N, dr)}, {F + fpad}) "
            "— the ingest assembly and the build must use the same mesh"
        )
    xb, yy, w, nid = pad_row_arrays(
        None if prepadded else binned.x_binned,
        y, w, np.zeros(N, np.int32), dr,
    )
    if prepadded:
        xb = binned.x_binned
    if fpad:
        if not prepadded:
            xp = jnp if isinstance(xb, jax.Array) else np
            xb = xp.concatenate(
                [xb, xp.zeros((len(xb), fpad), xp.int32)], axis=1
            )
        cand = np.concatenate(
            [cand, np.zeros((fpad, cand.shape[1]), bool)], axis=0
        )
    state = partition.shard_build_state(mesh, {
        "x_binned": xb, "y": yy, "weight": w, "node_id": nid,
        "cand_mask": cand,
    })
    return (state["x_binned"], state["y"], state["weight"],
            state["node_id"], state["cand_mask"])
