"""Device-mesh management — the TPU-native replacement for MPI communicators.

The reference bootstraps ``MPI.COMM_WORLD`` at import time and parallelizes by
recursively splitting communicators (reference:
``mpitree/tree/decision_tree.py:313-338``). Here the unit of distribution is a
``jax.sharding.Mesh``: a 1-D ``"data"`` axis shards rows (histogram
reductions ride ICI via ``lax.psum``); an optional 2-D ``(data, feature)``
mesh additionally shards the histogram's feature dimension (tensor
parallelism); a ``"tree"`` mesh shards whole ensemble members. Multi-host
(DCN) scaling uses the same code after ``jax.distributed.initialize`` — no
communicator tree, because the breadth-first builder turns the reference's
subtree task-parallelism into a batch dimension.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
TREE_AXIS = "tree"
FEATURE_AXIS = "feature"


def available_devices(backend: str | None = None) -> list:
    """Devices for ``backend`` (None = JAX default platform)."""
    return jax.devices() if backend is None else jax.devices(backend)


@lru_cache(maxsize=32)
def _cached_mesh(device_key: tuple, backend: str | None) -> Mesh:
    devs = available_devices(backend)
    picked = [devs[i] for i in device_key]
    return Mesh(np.array(picked), (DATA_AXIS,))


@lru_cache(maxsize=32)
def _cached_mesh_named(devices: tuple, axis: str) -> Mesh:
    return Mesh(np.array(list(devices)), (axis,))


def as_tree_mesh(mesh: Mesh) -> Mesh:
    """Same devices, ``tree`` axis — for ensemble (tree-axis) parallelism."""
    return _cached_mesh_named(tuple(mesh.devices.flat), TREE_AXIS)


@lru_cache(maxsize=32)
def _cached_mesh_2d(device_key: tuple, shape: tuple, backend: str | None) -> Mesh:
    devs = available_devices(backend)
    picked = np.array([devs[i] for i in device_key]).reshape(shape)
    return Mesh(picked, (DATA_AXIS, FEATURE_AXIS))


def resolve_mesh(*, backend: str | None = None, n_devices=None) -> Mesh:
    """Build the device mesh.

    ``n_devices=None`` -> single device (sequential semantics, like the
    reference's plain ``DecisionTreeClassifier``); ``n_devices="all"`` or
    ``-1`` -> every visible device (the ``mpirun -n <world>`` analogue);
    an int -> that many devices on a 1-D ``data`` axis; a ``(dr, df)``
    tuple -> a 2-D ``(data, feature)`` mesh — rows shard over ``dr``
    devices and the histogram's feature dimension over ``df`` (the
    tensor-parallel option; the reference scans features serially,
    ``decision_tree.py:411-416``).
    """
    devs = available_devices(backend)
    if isinstance(n_devices, tuple):
        dr, df = int(n_devices[0]), int(n_devices[1])
        if dr < 1 or df < 1 or dr * df > len(devs):
            raise ValueError(
                f"mesh shape {n_devices} needs {dr * df} devices but only "
                f"{len(devs)} are visible for backend={backend!r}"
            )
        if df == 1:
            return _cached_mesh(tuple(range(dr)), backend)
        return _cached_mesh_2d(tuple(range(dr * df)), (dr, df), backend)
    if n_devices in (None, 1):
        n = 1
    elif n_devices in ("all", -1):
        n = len(devs)
    else:
        n = int(n_devices)
        if n < 1 or n > len(devs):
            raise ValueError(
                f"n_devices={n} requested but only {len(devs)} devices are "
                f"visible for backend={backend!r}"
            )
    return _cached_mesh(tuple(range(n)), backend)


def feature_shards(mesh: Mesh) -> int:
    """Width of the mesh's feature axis (1 on a 1-D data mesh)."""
    return (
        mesh.shape[FEATURE_AXIS] if FEATURE_AXIS in mesh.axis_names else 1
    )


def data_shards(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS] if DATA_AXIS in mesh.axis_names else 1


def shard_rows(mesh: Mesh, *arrays):
    """device_put each (N, ...) array row-sharded over the mesh's data axis."""
    out = []
    for a in arrays:
        spec = P(DATA_AXIS, *([None] * (a.ndim - 1)))
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return out[0] if len(out) == 1 else tuple(out)


def replicate(mesh: Mesh, *arrays):
    """device_put each array fully replicated over the mesh."""
    out = [jax.device_put(a, NamedSharding(mesh, P())) for a in arrays]
    return out[0] if len(out) == 1 else tuple(out)


def pad_rows(n: int, n_devices: int) -> int:
    """Rows of padding needed so n divides evenly across devices."""
    return (-n) % n_devices


def shard_build_inputs(mesh: Mesh, binned, y, sample_weight):
    """One-time device placement shared by both build engines.

    Pads rows to the data-axis width (padding rows get ``node_id=-1`` /
    weight 0, so every kernel masks them out) and shards
    (x_binned, y, w, node_id) over the ``data`` axis. On a 2-D
    ``(data, feature)`` mesh the binned matrix and candidate mask also
    shard their feature dimension (padding features have zero candidates —
    inert). Returns the four sharded arrays plus the candidate mask.
    """
    N, F = binned.x_binned.shape
    dr = data_shards(mesh)
    df = feature_shards(mesh)
    pad = pad_rows(N, dr)
    xb, yy = binned.x_binned, y
    cand = binned.candidate_mask()
    w = (np.ones(N, np.float32) if sample_weight is None
         else sample_weight.astype(np.float32))
    nid = np.zeros(N, np.int32)
    if pad:
        xb = np.concatenate([xb, np.zeros((pad, F), np.int32)])
        yy = np.concatenate([yy, np.zeros(pad, yy.dtype)])
        w = np.concatenate([w, np.zeros(pad, np.float32)])
        nid = np.concatenate([nid, np.full(pad, -1, np.int32)])
    fpad = (-F) % df
    if fpad:
        xb = np.concatenate([xb, np.zeros((len(xb), fpad), np.int32)], axis=1)
        cand = np.concatenate(
            [cand, np.zeros((fpad, cand.shape[1]), bool)], axis=0
        )
    y_d, w_d, nid_d = shard_rows(mesh, yy, w, nid)
    if df == 1:
        xb_d = shard_rows(mesh, xb)
        cand_d = replicate(mesh, cand)
    else:
        xb_d = jax.device_put(
            xb, NamedSharding(mesh, P(DATA_AXIS, FEATURE_AXIS))
        )
        cand_d = jax.device_put(
            cand, NamedSharding(mesh, P(FEATURE_AXIS, None))
        )
    return xb_d, y_d, w_d, nid_d, cand_d
