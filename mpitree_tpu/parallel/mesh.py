"""Device-mesh management — the TPU-native replacement for MPI communicators.

The reference bootstraps ``MPI.COMM_WORLD`` at import time and parallelizes by
recursively splitting communicators (reference:
``mpitree/tree/decision_tree.py:313-338``). Here the unit of distribution is a
``jax.sharding.Mesh`` with a single ``"data"`` axis: rows are sharded across
it, histogram reductions ride ICI via ``lax.psum``, and multi-host (DCN)
scaling uses the same code after ``jax.distributed.initialize`` — no
communicator tree, because the breadth-first builder turns the reference's
subtree task-parallelism into a batch dimension.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
TREE_AXIS = "tree"


def available_devices(backend: str | None = None) -> list:
    """Devices for ``backend`` (None = JAX default platform)."""
    return jax.devices() if backend is None else jax.devices(backend)


@lru_cache(maxsize=32)
def _cached_mesh(device_key: tuple, backend: str | None) -> Mesh:
    devs = available_devices(backend)
    picked = [devs[i] for i in device_key]
    return Mesh(np.array(picked), (DATA_AXIS,))


@lru_cache(maxsize=32)
def _cached_mesh_named(devices: tuple, axis: str) -> Mesh:
    return Mesh(np.array(list(devices)), (axis,))


def as_tree_mesh(mesh: Mesh) -> Mesh:
    """Same devices, ``tree`` axis — for ensemble (tree-axis) parallelism."""
    return _cached_mesh_named(tuple(mesh.devices.flat), TREE_AXIS)


def resolve_mesh(*, backend: str | None = None, n_devices=None) -> Mesh:
    """Build a 1-D ``data`` mesh.

    ``n_devices=None`` -> single device (sequential semantics, like the
    reference's plain ``DecisionTreeClassifier``); ``n_devices="all"`` or
    ``-1`` -> every visible device (the ``mpirun -n <world>`` analogue).
    """
    devs = available_devices(backend)
    if n_devices in (None, 1):
        n = 1
    elif n_devices in ("all", -1):
        n = len(devs)
    else:
        n = int(n_devices)
        if n < 1 or n > len(devs):
            raise ValueError(
                f"n_devices={n} requested but only {len(devs)} devices are "
                f"visible for backend={backend!r}"
            )
    return _cached_mesh(tuple(range(n)), backend)


def shard_rows(mesh: Mesh, *arrays):
    """device_put each (N, ...) array row-sharded over the mesh's data axis."""
    out = []
    for a in arrays:
        spec = P(DATA_AXIS, *([None] * (a.ndim - 1)))
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return out[0] if len(out) == 1 else tuple(out)


def replicate(mesh: Mesh, *arrays):
    """device_put each array fully replicated over the mesh."""
    out = [jax.device_put(a, NamedSharding(mesh, P())) for a in arrays]
    return out[0] if len(out) == 1 else tuple(out)


def pad_rows(n: int, n_devices: int) -> int:
    """Rows of padding needed so n divides evenly across devices."""
    return (-n) % n_devices


def shard_build_inputs(mesh: Mesh, binned, y, sample_weight):
    """One-time device placement shared by both build engines.

    Pads rows to the mesh width (padding rows get ``node_id=-1`` / weight 0,
    so every kernel masks them out), shards (x_binned, y, w, node_id) over
    the ``data`` axis, and replicates the candidate mask. Returns the four
    sharded arrays plus the replicated mask.
    """
    N, F = binned.x_binned.shape
    pad = pad_rows(N, mesh.size)
    xb, yy = binned.x_binned, y
    w = (np.ones(N, np.float32) if sample_weight is None
         else sample_weight.astype(np.float32))
    nid = np.zeros(N, np.int32)
    if pad:
        xb = np.concatenate([xb, np.zeros((pad, F), np.int32)])
        yy = np.concatenate([yy, np.zeros(pad, yy.dtype)])
        w = np.concatenate([w, np.zeros(pad, np.float32)])
        nid = np.concatenate([nid, np.full(pad, -1, np.int32)])
    xb_d, y_d, w_d, nid_d = shard_rows(mesh, xb, yy, w, nid)
    cand_d = replicate(mesh, binned.candidate_mask())
    return xb_d, y_d, w_d, nid_d, cand_d
