"""Distribution layer: device mesh management and psum-based collectives."""
