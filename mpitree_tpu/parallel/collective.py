"""SPMD level-step kernels: sharded histogram + psum + replicated split search.

One fused ``shard_map`` program per frontier chunk replaces the reference's
entire MPI choreography (``Split`` / pickle-``allgather`` / ``Free``,
reference: ``mpitree/tree/decision_tree.py:446-477``):

- each device scatter-adds its local row shard into a
  (K, F, B, C) histogram chunk,
- ``lax.psum`` over the ``data`` ICI axis produces the identical global
  histogram on every device — fixed-shape array traffic, no pickled objects,
- split evaluation runs replicated on the psum'd histogram, so every device
  deterministically selects the same split (the reference's replicated-argmax
  invariant, ``decision_tree.py:408-419``, restated as XLA SPMD).

``update_node_id`` then advances each row's node assignment locally — rows
never move between devices; only O(K) histogram/decision data crosses ICI.

Compiled callables are cached per (mesh, static shape) key; chunk offsets are
traced scalars so every chunk and level reuses one executable.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mpitree_tpu.ops import histogram as hist_ops
from mpitree_tpu.ops import impurity as imp_ops
from mpitree_tpu.parallel import partition
from mpitree_tpu.parallel.mesh import DATA_AXIS, FEATURE_AXIS, feature_shards
from mpitree_tpu.resilience import chaos
from mpitree_tpu.utils import profiling


def _chaos_dispatch(site: str, fn):
    """Fault-injection seam on the host-side dispatch boundary of a jitted
    collective program (``resilience.chaos``). Always wrapped — the
    factories are lru-cached, so a conditional wrap would freeze whatever
    plan existed at first compile — but an empty plan costs one global
    read per *dispatch* (per chunk, not per row): nothing against a
    device launch."""

    def dispatch(*args):
        chaos.step(site)
        return fn(*args)

    # The compute ledger (obs/cost.py) prices fresh programs via
    # ``fn.lower(*args).cost_analysis()``; forward the jit's lower so the
    # wrapper stays transparent to it (no chaos step: pricing is a
    # host-side analysis, not a dispatch).
    dispatch.lower = fn.lower
    return dispatch


def split_psum_bytes(*, n_slots: int, n_features: int, n_bins: int,
                     n_channels: int, itemsize: int = 4) -> int:
    """Logical payload of one split-step histogram ``psum`` (bytes).

    The psum'd array IS the (n_slots, n_features, n_channels, n_bins)
    histogram chunk; computed from static shapes so the observability
    layer (``mpitree_tpu.obs``) can account collective traffic with zero
    device cost. ``itemsize=8`` for the gbdt scoped-f64 accumulation
    path (``resolve_gbdt_x64``). Wire traffic on a D-wide axis is
    ``(D-1)/D`` of this per all-reduce direction; the record keeps the
    logical payload (mesh width rides alongside in ``record.mesh``).
    """
    return n_slots * n_features * n_channels * n_bins * itemsize


def counts_psum_bytes(*, n_slots: int, n_channels: int,
                      itemsize: int = 4) -> int:
    """Logical payload of one terminal counts-step ``psum`` (bytes)."""
    return n_slots * n_channels * itemsize


# graftlint: wire=counts_psum
def node_counts_local(y, nid, w, chunk_lo, *, n_slots, n_classes, task,
                      axis=DATA_AXIS):
    """Per-slot class counts (or regression moments), psum'd over ``axis``.

    Shared by the levelwise counts step and the fused engine's terminal
    levels. ``axis=None`` skips the reduction (rows device-local, e.g. the
    tree-parallel forest build).
    """
    slot = nid - chunk_lo
    valid = (slot >= 0) & (slot < n_slots)
    wv = jnp.where(valid, w, 0.0)
    if task == "classification":
        ids = jnp.where(valid, slot * n_classes + y, 0)
        h = jax.ops.segment_sum(wv, ids, num_segments=n_slots * n_classes)
        h = h.reshape(n_slots, n_classes)
    elif task == "gbdt":
        # (count, G, H) per slot: y carries per-row gradients, w hessians
        # (h == 0 marks rows outside the round's subsample — no channel,
        # count included, sees them; see histogram.grad_hess_histogram).
        cnt = jnp.where(valid & (w > 0), 1.0, 0.0)
        data = jnp.stack(
            [cnt, jnp.where(valid, y.astype(jnp.float32), 0.0), wv], axis=-1
        )
        h = jax.ops.segment_sum(
            data, jnp.where(valid, slot, 0), num_segments=n_slots
        )
    else:
        y32 = y.astype(jnp.float32)
        data = jnp.stack([wv, wv * y32, wv * y32 * y32], axis=-1)
        h = jax.ops.segment_sum(
            data, jnp.where(valid, slot, 0), num_segments=n_slots
        )
    return lax.psum(h, axis) if axis is not None else h


# graftlint: wire=y_range_pminmax
def regression_y_range(y, nid, w, chunk_lo, *, n_slots, axis=DATA_AXIS):
    """Exact per-slot max(y)-min(y) purity signal over the mesh.

    The f32 moment variance cannot resolve near-zero spreads, so regression
    purity stops use this instead. Zero-weight rows (bootstrap out-of-bag)
    are excluded — they don't affect the fit. ``axis=None`` skips the
    cross-device reduction. Returns (ymin, ymax)."""
    slot = nid - chunk_lo
    valid = (slot >= 0) & (slot < n_slots) & (w > 0)
    s = jnp.clip(slot, 0, n_slots - 1)
    y32 = y.astype(jnp.float32)
    ymin = jax.ops.segment_min(
        jnp.where(valid, y32, jnp.inf), s, num_segments=n_slots
    )
    ymax = jax.ops.segment_max(
        jnp.where(valid, y32, -jnp.inf), s, num_segments=n_slots
    )
    if axis is None:
        return ymin, ymax
    return lax.pmin(ymin, axis), lax.pmax(ymax, axis)


# graftlint: wire=feature_merge_all_gather
def select_global(dec, feature_axis, f_local: int):
    """Merge per-feature-shard split winners into the global decision.

    THE one cross-(feature)-axis hop per level, shared verbatim by the
    fused while_loop body (``core/fused_builder``) and the levelwise
    ``make_split_fn`` program so the two engines cannot drift: each shard
    sweeps only its own (K, F/df, C, B) histogram slab, and this merges
    the per-shard winners with a tiny stacked all_gather + first-min.
    ``feature_axis=None`` (1-D mesh) is the identity. ``f_local`` is the
    per-shard feature-block width — contiguous blocks, so local winner
    ``f`` on shard ``j`` is global feature ``f + j * f_local``.

    Node-level statistics (``counts``/``n``/``impurity``/``y_range``)
    stay local: every row contributes to every feature column, so each
    shard's slab already carries the full node totals — only the
    candidate-dependent fields cross the axis.
    """
    if feature_axis is None:
        return dec
    j = lax.axis_index(feature_axis)
    f_global = (dec.feature + j * f_local).astype(jnp.int32)
    # One stacked gather instead of four: the level step is latency-bound
    # on tiny (df, K) payloads. n_left rides along so the
    # sibling-subtraction smaller-child pick sees the GLOBAL winner's
    # left weight, not the local shard's.
    packed = jnp.stack(
        [dec.cost, f_global.astype(jnp.float32),
         dec.bin.astype(jnp.float32),
         dec.n_left if dec.n_left is not None
         else jnp.zeros_like(dec.cost)]
    )  # (4, K)
    gathered = lax.all_gather(packed, feature_axis)  # (df, 4, K)
    costs = gathered[:, 0, :]
    # First-min over shards = lowest shard on cost ties = lowest global
    # feature (feature blocks are contiguous per shard) — the reference's
    # np.argmax tie-break (decision_tree.py:140).
    best = jnp.argmin(costs, axis=0)

    def take(c):
        return jnp.take_along_axis(
            gathered[:, c, :], best[None, :], axis=0
        )[0]

    nonconst = lax.psum(
        1.0 - dec.constant.astype(jnp.float32), feature_axis
    )
    return dec._replace(
        feature=take(1).astype(jnp.int32),
        bin=take(2).astype(jnp.int32),
        cost=take(0),
        constant=nonconst == 0,
        n_left=take(3),
    )


def select_global_bytes(*, n_slots: int) -> int:
    """Logical payload of one :func:`select_global` merge (bytes): the
    (4, K) f32 winner pack each feature shard contributes to the stacked
    all_gather, plus the (K,) f32 non-constant-candidate ``psum`` that
    decides the merged ``constant`` flag. Static shapes, same accounting
    contract as :func:`split_psum_bytes`.
    """
    return 5 * n_slots * 4


def gbdt_leaf_psum_bytes(*, n_slots: int, itemsize: int = 4) -> int:
    """Logical payload of one fused-rounds leaf refit + loss reduction
    (bytes): the per-round (M,) leaf G and H sums (``itemsize=8`` on the
    scoped-x64 path, ``resolve_gbdt_x64``) plus the two scalar f32
    training-loss terms. ``n_slots`` is the padded node-slot count
    M = 2*max_leaves - 1."""
    return 2 * n_slots * itemsize + 2 * 4


def replication_check_bytes() -> int:
    """Logical payload of one :func:`profiling.assert_replicated` probe
    (bytes): the scalar f32 participant count plus the scalar f32
    fingerprint psum the debug determinism check issues."""
    return 2 * 4


def _pack_decision(dec) -> jax.Array:
    """SplitDecision -> one (K, 10 + C) float32 buffer.

    The levelwise builder fetches the decision every level; a namedtuple
    fetch is one host transfer per field (11 round trips on a tunneled
    transport), a packed buffer is one. feature/bin/constant ride as f32 —
    exact below 2^24, far above any bin or feature count. ``n``,
    ``n_left`` and the class ``counts`` share that 2^24 integer-exactness
    ceiling: today they arrive as f32 device histograms anyway, so packing
    loses nothing, but a future f64-histogram path must widen this buffer
    or it would silently truncate node totals past 16.7M weighted rows
    (tree.count contract, min_samples_split tests). ``v_left``/``v_right``
    (monotonic constraints; zeros otherwise) feed the host's child-bound
    propagation; ``n_left`` feeds the sibling-subtraction frontier's
    smaller-child pick.
    """
    zeros = jnp.zeros_like(dec.n)
    head = jnp.stack(
        [dec.feature.astype(jnp.float32), dec.bin.astype(jnp.float32),
         dec.cost, dec.impurity, dec.n,
         dec.constant.astype(jnp.float32), dec.y_range,
         dec.v_left if dec.v_left is not None else zeros,
         dec.v_right if dec.v_right is not None else zeros,
         dec.n_left if dec.n_left is not None else zeros],
        axis=1,
    )
    return jnp.concatenate([head, dec.counts.astype(jnp.float32)], axis=1)


def unpack_decision(packed: np.ndarray) -> dict:
    """Host-side inverse of :func:`_pack_decision` (numpy dict)."""
    return {
        "feature": packed[:, 0].astype(np.int32),
        "bin": packed[:, 1].astype(np.int32),
        "cost": packed[:, 2],
        "impurity": packed[:, 3],
        "n": packed[:, 4],
        "constant": packed[:, 5] > 0,
        "y_range": packed[:, 6],
        "v_left": packed[:, 7],
        "v_right": packed[:, 8],
        "n_left": packed[:, 9],
        "counts": packed[:, 10:],
    }


@lru_cache(maxsize=64)
def make_split_fn(mesh, *, n_slots: int, n_bins: int, n_classes: int,
                  task: str, criterion: str, debug: bool = False,
                  use_pallas: bool = False, use_wide: bool = False,
                  wide_bf16: bool = False, wide_pallas: bool = False,
                  exact_ties: bool = False,
                  node_mask: bool = False,
                  random_split: bool = False, monotonic: bool = False,
                  gbdt_x64: bool = False,
                  subtraction: bool = False, keep_hist: bool = False):
    """Jitted (x_binned, y, node_id, weight, cand_mask, chunk_lo, mcw[, nmask])
    -> packed (n_slots, 10 + C) float32 decision buffer (see
    :func:`_pack_decision`, :func:`unpack_decision`). ``mcw`` is the
    min-child-weight floor as a RUNTIME scalar (a traced constant would
    recompile per distinct total fit weight).

    With ``debug=True`` the result is ``(packed, repl_err)`` where
    ``repl_err`` must be 0: the determinism check that every device computed
    the identical split (SURVEY.md §5 race-detection analogue).
    ``use_pallas`` routes the histogram (class counts or regression
    moments) through the Mosaic one-hot-matmul kernel; callers gate on
    platform/VMEM and on the exactness policy in
    :func:`mpitree_tpu.core.builder.resolve_hist_kernel`.
    ``node_mask=True`` adds a trailing (n_slots, F) bool input of per-node
    allowed features (sklearn per-node ``max_features``; ops/sampling.py).
    ``random_split=True`` adds a further (n_slots, F) uint32 input of
    per-(node, feature) candidate draws (ExtraTrees; the drawn bin replaces
    the per-feature argmin). ``monotonic=True`` adds three trailing inputs
    — (F,) int32 internal constraint signs and (n_slots,) f32 lower/upper
    node bounds (sklearn ``monotonic_cst``; ops/impurity.py).

    ``task="gbdt"`` (boosting rounds): ``y`` carries per-row gradients and
    ``w`` per-row hessians; the trailing operands are two runtime scalars
    ``(reg_lambda, min_samples_leaf)`` and ``mcw`` is the minimum hessian
    weight per child. ``gbdt_x64=True`` (CPU meshes) accumulates the
    non-integer (g, h) histogram in f64 inside a scoped ``enable_x64`` and
    rounds the psum'd result to f32 — what makes boosted trees identical
    across mesh sizes (histogram.grad_hess_histogram). Per-node feature
    masks / random splits / monotonic constraints are not supported for
    gbdt.

    On a 2-D ``(data, feature)`` mesh (ISSUE 10) the program
    feature-shards itself from the partition-rule table: each shard
    accumulates and psums only its ``(n_slots, F/df, C, B)`` slab over
    the data axis — per-level ICI payload independent of F — then the
    per-shard winners merge through :func:`select_global`, the one
    cross-axis hop (node-level stats are already complete per slab).
    Works for every task including the gbdt scoped-f64 path; per-node
    masks / random splits / monotonic constraints refuse (their host
    tables are feature-indexed and would straddle shards).

    ``subtraction=True`` (sibling-subtraction frontier,
    ``ops/histogram.sibling_accumulate_slots``): three trailing operands —
    the RESIDENT globally-reduced parent histogram of the previous level
    ((S_parent, F, C, B); f64 on the gbdt scoped-x64 path — on a feature
    mesh it stays a per-shard slab end to end: kept sharded in the
    output, fed back sharded, reconstructed feature-elementwise), a
    (n_slots,) int32 slot -> parent-slot map, and a (n_slots,) bool
    smaller-sibling mask. Only rows of small children accumulate, into a COMPACT
    ``n_slots // 2`` buffer, so the histogram psum payload halves; the
    large siblings are reconstructed from the parent after the reduction.
    Callers gate ``use_pallas``/``use_wide`` at the halved accumulate
    width. ``keep_hist=True`` additionally returns the full
    globally-reduced frontier histogram (after the reconstruction, before
    any f32 rounding on the gbdt path) so the next level can subtract
    against it — outputs become ``(packed, hist[, repl_err])``."""
    if task == "gbdt" and (node_mask or random_split or monotonic):
        raise ValueError(
            "task='gbdt' does not support per-node feature masks, random "
            "splits, or monotonic constraints"
        )
    # 2-D (data, feature) mesh: each shard accumulates and psums only its
    # own feature slab; the winner merge (select_global) is the one
    # cross-axis hop. Per-node masks/draws and monotonic bounds are
    # feature-indexed host tables that would straddle shards — the
    # builder refuses those configs on a feature mesh before reaching
    # here (builder.build_tree).
    feature_axis = FEATURE_AXIS if feature_shards(mesh) > 1 else None
    if feature_axis is not None and (node_mask or random_split or monotonic):
        raise ValueError(
            "per-node feature masks / random splits / monotonic "
            "constraints are not supported on a (data, feature) mesh"
        )
    hist_vma = (DATA_AXIS,) + (
        (FEATURE_AXIS,) if feature_axis is not None else ()
    )
    repl_axes = hist_vma
    n_acc = n_slots // 2 if subtraction else n_slots

    # Every histogram all-reduce in this step body is the split-step psum
    # the obs ledger prices as one site (split_psum_bytes).
    # graftlint: wire=split_hist_psum
    def local_step(xb, y, nid, w, cand_mask, chunk_lo, mcw, *nm):
        nm = list(nm)
        if subtraction:  # last three operands, popped in reverse
            is_small = nm.pop()
            parent_slot = nm.pop()
            parent_hist = nm.pop()
            acc_nid = hist_ops.sibling_accumulate_slots(
                nid, chunk_lo, is_small, n_slots=n_slots
            )
            acc_lo = jnp.int32(0)
        else:
            acc_nid, acc_lo = nid, chunk_lo
        mono = {}
        if monotonic:  # trailing operands: ..., cst, lo, hi
            hi = nm.pop()
            lo = nm.pop()
            mono = {"mono_cst": nm.pop(), "mono_lo": lo, "mono_hi": hi}
        nmask = nm[0] if nm else None
        draws = nm[1] if random_split else None

        def reconstruct(hs):
            if not subtraction:
                return hs
            return hist_ops.sibling_reconstruct(
                hs, parent_hist, parent_slot, is_small
            )

        if task == "classification":
            if use_pallas:
                from mpitree_tpu.ops import pallas_hist as ph

                h = ph.histogram_small(
                    xb, ph.class_payload(y, w, n_classes), acc_nid - acc_lo,
                    n_slots=n_acc, n_bins=n_bins, n_channels=n_classes,
                    vma=hist_vma,
                )
            elif use_wide:
                from mpitree_tpu.ops import pallas_hist as ph
                from mpitree_tpu.ops import wide_hist

                wide_fn = (wide_hist.histogram_wide_pallas if wide_pallas
                           else wide_hist.histogram_wide)
                h = wide_fn(
                    xb, ph.class_payload(y, w, n_classes), acc_nid - acc_lo,
                    n_slots=n_acc, n_bins=n_bins, n_channels=n_classes,
                    bf16_ok=wide_bf16, vma=hist_vma,
                )
            else:
                h = hist_ops.class_histogram(
                    xb, y, acc_nid, acc_lo,
                    n_slots=n_acc, n_bins=n_bins, n_classes=n_classes,
                    sample_weight=w,
                )
            h = reconstruct(lax.psum(h, DATA_AXIS))
            hist_keep = h
            dec = select_global(imp_ops.best_split_classification(
                h, cand_mask, criterion=criterion, node_mask=nmask,
                min_child_weight=mcw, forced_draw=draws,
                exact_ties=exact_ties, **mono,
            ), feature_axis, xb.shape[1])
        elif task == "gbdt":
            lam, msl = nm[0], nm[1]
            if gbdt_x64:
                h = hist_ops.grad_hess_histogram(
                    xb, y, w, acc_nid, acc_lo,
                    n_slots=n_acc, n_bins=n_bins,
                    acc_dtype=jnp.float64,
                )
                with jax.enable_x64(True):
                    h = reconstruct(lax.psum(h, DATA_AXIS))
                    hist_keep = h  # f64: the next level subtracts pre-round
                    h = h.astype(jnp.float32)
            else:
                if use_pallas or use_wide:
                    from mpitree_tpu.ops import pallas_hist as ph

                    payload = ph.gbdt_payload(y, w)
                    if use_pallas:
                        h = ph.histogram_small(
                            xb, payload, acc_nid - acc_lo,
                            n_slots=n_acc, n_bins=n_bins, n_channels=3,
                            vma=hist_vma,
                        )
                    else:
                        from mpitree_tpu.ops import wide_hist

                        wide_fn = (
                            wide_hist.histogram_wide_pallas if wide_pallas
                            else wide_hist.histogram_wide
                        )
                        h = wide_fn(
                            xb, payload, acc_nid - acc_lo,
                            n_slots=n_acc, n_bins=n_bins, n_channels=3,
                            bf16_ok=False, vma=hist_vma,
                        )
                else:
                    h = hist_ops.grad_hess_histogram(
                        xb, y, w, acc_nid, acc_lo,
                        n_slots=n_acc, n_bins=n_bins,
                    )
                h = reconstruct(lax.psum(h, DATA_AXIS))
                hist_keep = h
            dec = select_global(imp_ops.best_split_newton(
                h, cand_mask, reg_lambda=lam,
                min_child_weight=mcw, min_samples_leaf=msl,
            ), feature_axis, xb.shape[1])
        else:
            if use_pallas:
                from mpitree_tpu.ops import pallas_hist as ph

                h = ph.histogram_small(
                    xb, ph.moment_payload(y, w), acc_nid - acc_lo,
                    n_slots=n_acc, n_bins=n_bins, n_channels=3,
                    vma=hist_vma,
                )
            elif use_wide:
                from mpitree_tpu.ops import pallas_hist as ph
                from mpitree_tpu.ops import wide_hist

                wide_fn = (wide_hist.histogram_wide_pallas if wide_pallas
                           else wide_hist.histogram_wide)
                h = wide_fn(
                    xb, ph.moment_payload(y, w), acc_nid - acc_lo,
                    n_slots=n_acc, n_bins=n_bins, n_channels=3,
                    bf16_ok=False, vma=hist_vma,
                )
            else:
                h = hist_ops.moment_histogram(
                    xb, y, acc_nid, acc_lo, n_slots=n_acc, n_bins=n_bins,
                    sample_weight=w,
                )
            h = reconstruct(lax.psum(h, DATA_AXIS))
            hist_keep = h
            dec = select_global(imp_ops.best_split_regression(
                h, cand_mask, node_mask=nmask, min_child_weight=mcw,
                forced_draw=draws, **mono,
            ), feature_axis, xb.shape[1])
            # min/max are not linear — the y-range purity signal always
            # scans directly (an O(N) scatter, not the O(N*F) hot path).
            ymin, ymax = regression_y_range(
                y, nid, w, chunk_lo, n_slots=n_slots
            )
            y_range = jnp.where(ymax >= ymin, ymax - ymin, 0.0)
            dec = dec._replace(y_range=y_range)
        out = (_pack_decision(dec),)
        if keep_hist:
            out = out + (hist_keep,)
        if debug:
            fp = profiling.replication_fingerprint(dec.feature, dec.bin, dec.n)
            out = out + (profiling.assert_replicated(fp, repl_axes),)
        return out if len(out) > 1 else out[0]

    # Operand specs come from the ONE partition-rule table
    # (parallel/partition.py): named rules for the sharded operands, the
    # replicated catch-all for host tables and runtime scalars. On a 1-D
    # mesh the feature-axis entries trim to None — same tuple as before.
    names = ["x_binned", "y", "node_id", "weight", "cand_mask",
             ("chunk_lo", 0), ("mcw", 0)]
    if task == "gbdt":
        names += [("reg_lambda", 0), ("min_samples_leaf", 0)]
    if node_mask:
        names += ["node_mask"]
    if random_split:
        names += ["draws"]
    if monotonic:
        names += ["mono_cst", "mono_lo", "mono_hi"]
    if subtraction:
        names += ["parent_hist", "parent_slot", "is_small"]
    in_specs = partition.in_specs_for(mesh, names)
    # Outputs from the same table: the packed decision buffer and the
    # debug fingerprint replicate; the kept frontier histogram stays
    # feature-sharded on device — each shard's slab is all the next
    # level's reconstruction reads, so the carry never materializes
    # feature-complete.
    out_names = ["decision"]
    if keep_hist:
        out_names += ["hist_keep"]
    if debug:
        out_names += ["debug_fp"]
    out_specs = partition.out_specs_for(mesh, out_names)
    sharded = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs if len(out_specs) > 1 else out_specs[0],
        # vma tracking flags replicated-vs-varying mixes after the
        # feature-axis gather that are semantically fine (same stance as
        # the fused engine on a 2-D mesh).
        check_vma=feature_axis is None,
    )
    return _chaos_dispatch("split_dispatch", jax.jit(sharded))


# Pair-granularity histogram all-reduces — priced as the same
# split-step site as the levelwise program (split_psum_bytes).
# graftlint: wire=split_hist_psum
def pair_split_stats(xb, y, nid, w, cand_mask, base_id, is_small, phist,
                     mcw, lam, msl, *, task: str, criterion: str,
                     n_bins: int, n_classes: int, exact_ties: bool,
                     gbdt_x64: bool, subtraction: bool,
                     psum_axis=DATA_AXIS):
    """Histogram + split sweep for ONE sibling pair — the leaf-wise hot op.

    The best-first frontier expands one leaf at a time, so its unit of
    histogram work is the two-slot pair ``(base_id, base_id + 1)`` (the
    ROOT bootstrap rides the same code: ``base_id == 0`` with every row
    still assigned to node 0 puts the whole dataset in slot 0 and leaves
    slot 1 empty). Shared verbatim by the fused leaf-wise while_loop body
    (``core/leafwise_builder``) and the host-stepped expansion program
    (:func:`make_expand_fn`) so the two engines cannot drift.

    ``subtraction``: accumulate only the smaller sibling into a COMPACT
    one-slot buffer (``histogram.sibling_accumulate_slots`` at pair
    granularity — the per-expansion psum payload halves) and reconstruct
    the larger as ``parent - small`` from ``phist`` ((1, F, C, B), the
    expanded leaf's RESIDENT reduced histogram; f64 on the gbdt
    scoped-x64 path). Callers gate on the exactness policy
    (``builder.resolve_hist_subtraction``). Returns ``(dec, pure, keep)``
    where ``keep`` is the reduced pair histogram the children enter the
    pool with (pre-f32-rounding on the gbdt f64 path; ``None`` when
    subtraction is off — nothing needs to stay resident).
    """
    n_acc = 1 if subtraction else 2
    if subtraction:
        acc_nid = hist_ops.sibling_accumulate_slots(
            nid, base_id, is_small, n_slots=2
        )
        acc_lo = jnp.int32(0)
    else:
        acc_nid, acc_lo = nid, base_id

    def reconstruct(hs):
        if not subtraction:
            return hs
        # Pair-specialized (gather-free) reconstruction — see
        # histogram.sibling_reconstruct_pair for why not the general op.
        return hist_ops.sibling_reconstruct_pair(hs, phist, is_small)

    keep = None
    if task == "classification":
        h = hist_ops.class_histogram(
            xb, y, acc_nid, acc_lo, n_slots=n_acc, n_bins=n_bins,
            n_classes=n_classes, sample_weight=w,
        )
        h = reconstruct(lax.psum(h, psum_axis) if psum_axis is not None else h)
        keep = h
        dec = imp_ops.best_split_classification(
            h, cand_mask, criterion=criterion, min_child_weight=mcw,
            exact_ties=exact_ties,
        )
        pure = (dec.counts > 0).sum(axis=1) <= 1
    elif task == "gbdt":
        if gbdt_x64:
            h = hist_ops.grad_hess_histogram(
                xb, y, w, acc_nid, acc_lo, n_slots=n_acc, n_bins=n_bins,
                acc_dtype=jnp.float64,
            )
            with jax.enable_x64(True):
                h = lax.psum(h, psum_axis) if psum_axis is not None else h
                h = reconstruct(h)
                keep = h  # f64: children subtract pre-rounding
                h = h.astype(jnp.float32)
        else:
            h = hist_ops.grad_hess_histogram(
                xb, y, w, acc_nid, acc_lo, n_slots=n_acc, n_bins=n_bins,
            )
            h = reconstruct(lax.psum(h, psum_axis) if psum_axis is not None else h)
            keep = h
        dec = imp_ops.best_split_newton(
            h, cand_mask, reg_lambda=lam, min_child_weight=mcw,
            min_samples_leaf=msl,
        )
        pure = jnp.zeros(2, bool)
    else:
        h = hist_ops.moment_histogram(
            xb, y, acc_nid, acc_lo, n_slots=n_acc, n_bins=n_bins,
            sample_weight=w,
        )
        h = reconstruct(lax.psum(h, psum_axis) if psum_axis is not None else h)
        keep = h
        dec = imp_ops.best_split_regression(
            h, cand_mask, min_child_weight=mcw,
        )
        ymin, ymax = regression_y_range(
            y, nid, w, base_id, n_slots=2, axis=psum_axis
        )
        pure = ~(ymax > ymin)
        dec = dec._replace(
            y_range=jnp.where(ymax >= ymin, ymax - ymin, 0.0)
        )
    return dec, pure, (keep if subtraction else None)


@lru_cache(maxsize=64)
def make_expand_fn(mesh, *, n_bins: int, n_classes: int, task: str,
                   criterion: str, exact_ties: bool = False,
                   gbdt_x64: bool = False, subtraction: bool = False):
    """Jitted one-expansion step for the host-stepped leaf-wise frontier.

    ``(x_binned, y, node_id, weight, cand_mask, e_node, feat, bin,
    left_id, small_left, mcw, lam, msl[, parent_hist])`` ->
    ``(node_id', packed (2, 10 + C) decisions[, pair_hist])``: reroute
    the rows of node ``e_node`` through its recorded split
    ``(feat, bin)`` into children ``(left_id, left_id + 1)``, then run
    :func:`pair_split_stats` on the new pair — one dispatch per
    best-first expansion, the levelwise-engine counterpart of the fused
    leaf-wise program. The ROOT bootstrap passes ``e_node == -2`` (a
    sentinel no live or padding row carries, so the reroute is a no-op)
    with ``left_id == 0``: slot 0 of the pair then IS the root.
    ``small_left`` picks which child accumulates under subtraction;
    ``parent_hist`` is the expanded leaf's resident (1, F, C, B) reduced
    histogram (f64 on the gbdt scoped-x64 path). ``lam``/``msl`` are the
    gbdt Newton scalars (dead operands otherwise — uniform signature
    keeps one executable shape per task).
    """

    def local_expand(xb, y, nid, w, cand_mask, e_node, feat, bin_, left_id,
                     small_left, mcw, lam, msl, *sub_ops):
        R = nid.shape[0]
        xf = jnp.take_along_axis(
            xb, jnp.broadcast_to(jnp.maximum(feat, 0), (R,))[:, None],
            axis=1,
        )[:, 0]
        child = jnp.where(xf <= bin_, left_id, left_id + 1)
        nid = jnp.where(nid == e_node, child, nid)
        is_small = jnp.stack([small_left, ~small_left])
        dec, pure, keep = pair_split_stats(
            xb, y, nid, w, cand_mask, left_id, is_small,
            sub_ops[0] if subtraction else None, mcw, lam, msl,
            task=task, criterion=criterion, n_bins=n_bins,
            n_classes=n_classes, exact_ties=exact_ties, gbdt_x64=gbdt_x64,
            subtraction=subtraction,
        )
        out = (nid, _pack_decision(dec))
        if subtraction:
            out = out + (keep,)
        return out

    names = ["x_binned", "y", "node_id", "weight", "cand_mask",
             ("e_node", 0), ("feat", 0), ("bin", 0), ("left_id", 0),
             ("small_left", 0), ("mcw", 0), ("lam", 0), ("msl", 0)]
    if subtraction:
        names += ["parent_hist"]
    in_specs = partition.in_specs_for(mesh, names)
    # ``pair_keep`` (the reduced pair histogram re-entering the host-side
    # pool) replicates — unlike the fused carry's resident slabs it
    # leaves the program every expansion.
    out_names = ["node_id", "decision"] + (["pair_keep"] if subtraction else [])
    out_specs = partition.out_specs_for(mesh, out_names)
    sharded = jax.shard_map(
        local_expand,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
    )
    # node_id donated: the expansion loop's canonical
    # `nid_d = expand_fn(nid_d, ...)[0]` rebind consumes the old buffer
    # each call (GL08 holds callers to that shape); the chaos wrapper
    # raises BEFORE the jitted call, so a planned fault never
    # half-donates.
    return _chaos_dispatch(
        "expand_dispatch", jax.jit(sharded, donate_argnums=(2,))
    )


@lru_cache(maxsize=64)
def make_counts_fn(mesh, *, n_slots: int, n_classes: int, task: str):
    """Jitted (y, node_id, weight, chunk_lo) -> per-slot statistics only.

    Terminal tree levels (depth == max_depth) become leaves unconditionally,
    so the full (slot, feature, bin) split histogram is wasted there — this
    computes just the per-node class counts (or regression moments), an
    O(N) scatter instead of O(N*F).
    """

    def local_counts(y, nid, w, chunk_lo):
        return node_counts_local(
            y, nid, w, chunk_lo, n_slots=n_slots, n_classes=n_classes,
            task=task,
        )

    sharded = jax.shard_map(
        local_counts,
        mesh=mesh,
        in_specs=partition.in_specs_for(
            mesh, ("y", "node_id", "weight", ("chunk_lo", 0))
        ),
        out_specs=partition.spec_for("counts", mesh),
    )
    return _chaos_dispatch("counts_dispatch", jax.jit(sharded))


@lru_cache(maxsize=64)
def make_update_fn(mesh, *, n_slots: int):
    """Jitted node-assignment advance for one frontier chunk.

    (node_id, x_binned, chunk_lo, is_split, feat, bin, left_id, right_id)
    -> new node_id. Rows in non-splitting or out-of-chunk nodes are untouched;
    rows in splitting nodes route by ``x_binned[:, feat] <= bin`` — the
    on-device replacement for the reference's partition copies
    (``decision_tree.py:150-164``).

    On a 2-D ``(data, feature)`` mesh only the shard owning a node's
    split feature can read that column: it computes the child id and one
    ``psum`` over the feature axis delivers it to every shard (each
    active row has exactly one owner, others contribute zero) — the same
    owner-broadcast the fused engine's reroute uses.
    """
    feature_axis = FEATURE_AXIS if feature_shards(mesh) > 1 else None

    # The owner-broadcast child-id psum over the feature axis — the
    # routing hop the obs ledger prices as route_psum.
    # graftlint: wire=route_psum
    def local_update(nid, xb, chunk_lo, is_split, feat, bin_, left_id, right_id):
        slot = nid - chunk_lo
        in_chunk = (slot >= 0) & (slot < n_slots)
        s = jnp.clip(slot, 0, n_slots - 1)
        active = in_chunk & is_split[s]
        f = feat[s]
        local, owner = hist_ops.slab_local_features(
            f, feature_axis, xb.shape[1]
        )
        xf = jnp.take_along_axis(xb, local[:, None], axis=1)[:, 0]
        go_left = xf <= bin_[s]
        nxt = jnp.where(go_left, left_id[s], right_id[s])
        if feature_axis is None:
            return jnp.where(active, nxt, nid)
        child_all = lax.psum(
            jnp.where(active & owner, nxt, 0), feature_axis
        )
        return jnp.where(active, child_all, nid)

    sharded = jax.shard_map(
        local_update,
        mesh=mesh,
        in_specs=partition.in_specs_for(
            mesh, ("node_id", "x_binned", ("chunk_lo", 0), "is_split",
                   "feat", "bin", "left_id", "right_id")
        ),
        out_specs=partition.spec_for("node_id", mesh),
        check_vma=feature_axis is None,
    )
    # nid donated: the level loop's canonical `nid_d = update_fn(nid_d, ..)`
    # rebind consumes the old buffer each call — GL08 (donation-after-use)
    # holds every caller to that shape. The chaos wrapper raises (if at
    # all) BEFORE the jitted call, so a planned fault never half-donates.
    return _chaos_dispatch("update_dispatch", jax.jit(sharded, donate_argnums=(0,)))
