"""Multi-host (DCN) initialization — the ``mpirun`` replacement.

The reference scales across processes only via ``mpirun -n k`` + import-time
``MPI.COMM_WORLD`` bootstrap (reference: ``mpitree/tree/decision_tree.py:
313-317``). The TPU-native equivalent is ``jax.distributed.initialize``: each
host process joins a coordination service, after which ``jax.devices()``
spans every chip in the slice and the SAME mesh/psum build code runs
unchanged — histogram reductions ride ICI within a host and DCN across
hosts, with XLA choosing the hierarchical reduction.

Typical multi-host launch (one process per host, e.g. under a TPU pod
slice's launcher):

    import mpitree_tpu
    mpitree_tpu.parallel.distributed.initialize()   # env-driven on TPU pods
    clf = ParallelDecisionTreeClassifier().fit(X, y)  # n_devices="all"

Every process must call :func:`initialize` before touching devices; on
single-host runs it is a no-op by default.
"""

from __future__ import annotations

import jax

_initialized = False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               **timeouts) -> None:
    """Join the JAX distributed runtime (idempotent).

    With no arguments on a TPU pod, configuration is discovered from the
    environment (the standard ``jax.distributed.initialize()`` contract).
    On a single process with no coordinator this is a no-op.

    ``timeouts`` passes through the runtime's failure-detection knobs
    (``initialization_timeout``, ``heartbeat_timeout_seconds``, ...): a
    host that never arrives fails the join within the bound, and a host
    that dies mid-fit fails the survivors' next collective after the
    heartbeat window — a bounded, catchable error where the reference's
    MPI job deadlocks in ``comm.allgather`` (``decision_tree.py:456``;
    SURVEY §5 failure detection). Pinned by
    ``tests/test_distributed_failures.py``.
    """
    global _initialized
    if _initialized:
        return
    if coordinator_address is None and num_processes in (None, 1):
        import os

        if not os.environ.get("COORDINATOR_ADDRESS") and not os.environ.get(
            "JAX_COORDINATOR_ADDRESS"
        ):
            return  # single host, nothing to join
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **timeouts,
        )
    except RuntimeError as e:
        # Devices already touched (or runtime already up): surface the
        # ordering contract instead of crashing a single-host run.
        import warnings

        warnings.warn(f"distributed.initialize skipped: {e}", stacklevel=2)
        return
    _initialized = True


def process_info() -> dict:
    """Rank/size view mirroring the reference's WORLD_RANK/WORLD_SIZE."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
