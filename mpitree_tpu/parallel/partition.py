"""Partition-rule table: ONE declarative map from build-state array names
to ``PartitionSpec``s over the 2-D ``(data, feature)`` mesh.

Before this module every call site hand-wrote its ``shard_map`` in_specs
and ``device_put`` shardings, so adding the feature axis meant auditing a
dozen spec tuples for drift. The idiom here is the regex→spec table from
large-model training codebases (SNIPPETS.md [2] ``match_partition_rules``,
[3] ``shard_params``/``get_sharding_tree``): every array that crosses the
host/device boundary during a build has a NAME, the table maps names to
specs, and both device engines derive their ``shard_map`` in_specs and
initial placements from the one table — a new array gets a rule, not a
per-call-site audit.

Axis semantics (``parallel/mesh.py``):

- ``data`` shards rows: per-row state (``y``, ``weight``, ``node_id``)
  and the row axis of the binned matrix. Histogram reductions ``psum``
  over it.
- ``feature`` shards the histogram's feature dimension (tensor
  parallelism): the column axis of the binned matrix, the candidate
  mask's leading axis, and the F axis of every resident histogram slab
  (the sibling-subtraction carry keeps PER-SHARD slabs — the parent
  histogram never materializes feature-complete anywhere). The one
  cross-axis hop per level is the split-winner merge
  (``collective.select_global``).

On a mesh that lacks an axis (a 1-D data mesh, the single-device mesh)
the spec entries naming it are trimmed to ``None`` — one table serves
every mesh shape.
"""

from __future__ import annotations

import re

import jax
import numpy as np

# graftlint: partition-table — THE spec authority: the one module allowed
# to construct PartitionSpec literals (GL09 flags ad-hoc P(...) anywhere
# else in the package).
from jax.sharding import NamedSharding, PartitionSpec as P

from mpitree_tpu.parallel.mesh import DATA_AXIS, FEATURE_AXIS, TREE_AXIS

# name-pattern -> PartitionSpec over the (data, feature) mesh. First match
# wins; the terminal catch-all replicates, because everything else that
# crosses the boundary is a host-built table, a packed decision buffer, or
# a runtime scalar — all of which every device must see whole. Scalars
# (0-d operands like chunk offsets and leaf floors) are forced to P()
# before the table is consulted, the SNIPPETS [2] rule.
PARTITION_RULES: tuple = (
    # The binned matrix: rows x features, both axes sharded.
    (r"^x_binned$", P(DATA_AXIS, FEATURE_AXIS)),
    # Raw (unbinned) row blocks: inference inputs — rows sharded, the
    # feature axis rides whole with its rows.
    (r"^x_rows$", P(DATA_AXIS)),
    # Per-row state: targets/gradients, weights/hessians, node routing,
    # boosting margins.
    (r"^(y|weight|sample_weight|node_id|nid\w*|raw_margin)$", P(DATA_AXIS)),
    # (F, B) candidate mask: feature-major, bins replicated.
    (r"^cand_masks?$", P(FEATURE_AXIS, None)),
    # Resident (S, F, C, B) histogram slabs (the sibling-subtraction
    # carry): slots replicated, features sharded — each shard subtracts
    # against its own slab, so the carry's HBM cost also divides by the
    # feature-axis width.
    (r"^(parent_hist|hist_keep|pair_hist)$", P(None, FEATURE_AXIS, None, None)),
    # Forest ensemble state on the (tree, data) mesh (ISSUE 13
    # satellite): per-tree operand stacks shard their leading axis over
    # the tree axis — bootstrap weight rows additionally data-shard with
    # the rows they weight; candidate masks / node buffers / per-tree
    # scalars replicate within a tree group. The forest memory plan
    # (``obs.memory.plan_forest``) prices per-device bytes from exactly
    # these rules.
    (r"^tree_(weights|node_id)$", P(TREE_AXIS, DATA_AXIS)),
    (r"^tree_\w+$", P(TREE_AXIS)),
    # Per-node tables the host builds for the split/update/counts steps:
    # frontier maps, smaller-sibling masks, split routing, monotonic
    # bounds, per-node feature masks/draws. Replicated — they are O(K)
    # and every shard's decision logic reads all of them.
    (r"^(parent_slot|is_small|is_split|feat|bin|left_id|right_id)$", P()),
    (r"^(node_mask|draws|mono_(cst|lo|hi))$", P()),
    # Program OUTPUTS that replicate after the in-program psum/merge:
    # per-node result tables (counts/value vectors/parent links/depths),
    # packed decision buffers, replicated histogram keeps, boosting
    # per-leaf moments and loss accumulators, node-count scalars.
    (r"^(counts|n_vec|parent_id|depth|n_nodes|decision|pair_keep)$", P()),
    (r"^(grad_tot|hess_tot|loss_sum|loss_weight|debug_fp)$", P()),
    # Everything else (runtime scalars ride the scalar guard before this
    # table is consulted).
    (r".*", P()),
)


def match_partition_rules(name: str, *, rules=PARTITION_RULES,
                          ndim: int | None = None) -> P:
    """Spec for ``name`` from the rule table (SNIPPETS [2] shape).

    ``ndim=0`` (scalars) short-circuits to ``P()`` — don't partition
    scalar values. A spec longer than ``ndim`` raises: that is a table
    bug, not a caller problem.
    """
    if ndim == 0:
        return P()
    for pattern, spec in rules:
        if re.search(pattern, name) is not None:
            if ndim is not None and len(spec) > ndim:
                raise ValueError(
                    f"partition rule {pattern!r} yields rank-{len(spec)} "
                    f"spec {spec} for rank-{ndim} array {name!r}"
                )
            return spec
    raise ValueError(f"partition rule not found for array: {name!r}")


def trim_spec(spec: P, mesh) -> P:
    """Drop axis names the mesh does not carry (1-D meshes, host mesh).

    ``P('data', 'feature')`` on a 1-D data mesh becomes ``P('data', None)``
    — same placement semantics, valid on the narrower mesh — so the one
    table drives every mesh shape.
    """
    names = set(mesh.axis_names)
    return P(*[a if a in names else None for a in spec])


def spec_for(name: str, mesh=None, *, ndim: int | None = None) -> P:
    """Table spec for ``name``, trimmed to ``mesh``'s axes when given."""
    spec = match_partition_rules(name, ndim=ndim)
    return spec if mesh is None else trim_spec(spec, mesh)


def in_specs_for(mesh, names) -> tuple:
    """``shard_map`` in_specs for a named operand list — the one place
    both engines' spec tuples come from. Names must match the wrapped
    function's positional order; scalars may pass ``ndim`` via a
    ``(name, ndim)`` pair (plain names consult the table directly)."""
    specs = []
    for n in names:
        if isinstance(n, tuple):
            n, nd = n
            specs.append(spec_for(n, mesh, ndim=nd))
        else:
            specs.append(spec_for(n, mesh))
    return tuple(specs)


def out_specs_for(mesh, names) -> tuple:
    """``shard_map`` out_specs for a named result list — same contract as
    :func:`in_specs_for` (plain names consult the table, ``(name, 0)``
    pairs force the scalar ``P()``), so program OUTPUTS come from the one
    table too (graftlint GL09 holds engine code to exactly that)."""
    return in_specs_for(mesh, names)


def ingest_layout(mesh, n_rows: int, n_features: int) -> dict:
    """Mesh-slot layout for streaming ingestion (ISSUE 15) — where each
    chunk's rows/columns land, derived from the SAME ``x_binned`` rule
    the engines' in_specs come from (no second placement authority).

    Returns ``{"sharding", "rows_pad", "feat_pad", "shard_rows",
    "shard_cols", "grid"}``: ``grid`` is the mesh's device array
    reshaped ``(data_shards, feature_shards)`` so ``grid[di, fi]`` is
    the device owning row block ``di`` × feature block ``fi``; shard
    extents are the padded global extents divided by the axis widths
    (padding rows/columns are zeros — inert under the ``node_id=-1`` /
    zero-candidate contracts ``mesh.pad_row_arrays`` documents).
    """
    from mpitree_tpu.parallel import mesh as mesh_lib

    dr = mesh_lib.data_shards(mesh)
    df = mesh_lib.feature_shards(mesh)
    rows_pad = int(n_rows) + (-int(n_rows)) % dr
    feat_pad = int(n_features) + (-int(n_features)) % df
    return {
        "sharding": NamedSharding(mesh, spec_for("x_binned", mesh, ndim=2)),
        "rows_pad": rows_pad,
        "feat_pad": feat_pad,
        "shard_rows": max(rows_pad // dr, 1),
        "shard_cols": max(feat_pad // df, 1),
        "grid": mesh.devices.reshape(dr, df),
    }


def sharding_tree(mesh, state: dict) -> dict:
    """``{name: NamedSharding}`` for a named build-state tree (SNIPPETS
    [3] ``get_sharding_tree`` shape). Scalars map to replicated."""
    return {
        name: NamedSharding(
            mesh, spec_for(name, mesh, ndim=int(np.ndim(value)))
        )
        for name, value in state.items()
    }


def shard_build_state(mesh, state: dict) -> dict:
    """device_put every named array per the rule table (SNIPPETS [3]
    ``shard_params`` shape) — the one-time placement both build engines
    ride (``mesh.shard_build_inputs``). Values must already be padded to
    the mesh's axis widths (``mesh.pad_row_arrays`` / feature padding)."""
    tree = sharding_tree(mesh, state)
    return {
        name: jax.device_put(value, tree[name])
        for name, value in state.items()
    }
