"""Pallas (Mosaic) histogram kernel for small frontiers — the MXU tier.

The build's hot op is the per-(node, feature, class, bin) histogram
(``ops/histogram.py``; the TPU-first replacement for the reference's
per-candidate rescan, ``mpitree/tree/decision_tree.py:73-86``). The XLA path
lowers to a scatter-add (``segment_sum``), which a TPU executes on the scalar
unit — no vectorization. This kernel reformulates the histogram as dense
one-hot contractions on the MXU:

    hist[s, f, c, b] = sum_r  M1[r, s*C + c] * onehot_bin_f[r, b]
    M1[r, s*C + c]   = payload[r, c] * (slot[r] == s)

i.e. one ``(S*C, Rt) @ (Rt, B)`` matmul per feature per row tile, where
``payload`` is ``w * onehot(y)`` for classification and ``(w, w*y, w*y^2)``
for regression — so one kernel serves both tasks. The formulation carries a
dense ``S*C*B`` factor per row, so it only pays off while the frontier chunk
``S`` is small; that is exactly the regime where the fused builder's fixed
chunk width wastes the most (a depth-0..6 frontier occupies a handful of
slots of the K=4096 chunk). The fused builder therefore routes small
frontiers here (``fused_builder.py`` small-frontier branch, behind
``BuildConfig.hist_kernel``) and keeps the XLA scatter for wide frontiers.

Two layouts serve different ``S`` ranges: the one-block kernel keeps the
whole ``(F, S*C, Bp)`` histogram persistent in VMEM (fastest, but S <= ~8
at covtype shape), and a feature-gridded variant keeps one feature's
``(1, S*C, Bp)`` block persistent while the grid walks (feature, row-tile)
pairs — reaching the S=64..128 middle tiers that otherwise fell back to
the scatter. ``histogram_small`` picks the layout automatically; both are
bit-identical to the XLA path for integer-valued payloads.

Rows whose slot falls outside ``[0, S)`` (parked in leaves, padding, other
chunks) contribute nothing: their slot one-hot row is all zeros — the mask
is free.

Shapes are padded for TPU tiling: bins to a multiple of 128 (lanes), rows to
the tile size. ``S*C`` should be a multiple of 8 (sublanes); callers pick
``S`` accordingly (the default small-frontier width is 8).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fail on builds without TPU support
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _m1(slot_ref, payload_ref, n_slots):
    """M1[r, s*C+c] = payload[r, c] * (slot[r] == s).

    Rows outside [0, S) get an all-zero row — masking is free. Built
    reshape-free (Mosaic cannot shape-cast (Rt,S,C)->(Rt,S*C)): the slot
    one-hot comes from an iota divided by C, the payload from concatenating
    itself S times.
    """
    Rt, C = payload_ref.shape
    slot = slot_ref[:, 0]
    sc_iota = jax.lax.broadcasted_iota(jnp.int32, (Rt, n_slots * C), 1)
    mask_s = (sc_iota // C == slot[:, None]).astype(jnp.float32)
    tiled = jnp.concatenate([payload_ref[...]] * n_slots, axis=1)
    return mask_s * tiled  # (Rt, S*C)


def _hist_kernel(slot_ref, payload_ref, xb_ref, out_ref, *, n_slots, n_bins_pad):
    """One grid step = one row tile; accumulates into the persistent out block.

    slot_ref    : (Rt, 1) int32   — frontier slot per row (-1 = masked)
    payload_ref : (Rt, C) float32 — per-channel scatter payload
    xb_ref      : (Rt, F) int32   — bin ids
    out_ref     : (F, S*C, Bp) float32 — accumulated histogram
    """
    Rt = slot_ref.shape[0]
    F = xb_ref.shape[1]

    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    m1 = _m1(slot_ref, payload_ref, n_slots)
    b_iota = jax.lax.broadcasted_iota(jnp.int32, (Rt, n_bins_pad), 1)
    for f in range(F):  # unrolled: F static, each iteration one MXU matmul
        onehot_b = (xb_ref[:, f][:, None] == b_iota).astype(jnp.float32)
        out_ref[f] += jax.lax.dot_general(
            m1, onehot_b,
            dimension_numbers=(((0,), (0,)), ((), ())),  # contract rows
            preferred_element_type=jnp.float32,
        )


def _hist_kernel_fgrid(slot_ref, payload_ref, xb_ref, out_ref, *, n_slots,
                       n_bins_pad):
    """Feature-gridded variant: one grid step = (one feature, one row tile).

    The single-block kernel's persistent out block is (F, S*C, Bp) — at
    covtype shape (F=54, C=7, B=256) it exceeds the VMEM budget for any
    S > 8, so frontiers of 9..512 nodes fell back to the XLA scatter (the
    scalar-unit path this kernel exists to avoid). Gridding features out
    shrinks the persistent block to (1, S*C, Bp) — S=64 is ~460KB — at the
    cost of recomputing M1 once per feature (VPU-cheap next to the MXU
    contraction). Grid iterates (F outer, row tiles inner) so each
    feature's block accumulates across its row sweep.

    slot_ref    : (Rt, 1) int32   — frontier slot per row (-1 = masked)
    payload_ref : (Rt, C) float32 — per-channel scatter payload
    xb_ref      : (Rt, 1) int32   — bin ids, ONE feature column
    out_ref     : (1, S*C, Bp) float32 — this feature's histogram
    """
    Rt = slot_ref.shape[0]

    @pl.when(pl.program_id(1) == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    m1 = _m1(slot_ref, payload_ref, n_slots)
    b_iota = jax.lax.broadcasted_iota(jnp.int32, (Rt, n_bins_pad), 1)
    onehot_b = (xb_ref[:, 0][:, None] == b_iota).astype(jnp.float32)
    out_ref[0] += jax.lax.dot_general(
        m1, onehot_b,
        dimension_numbers=(((0,), (0,)), ((), ())),  # contract rows
        preferred_element_type=jnp.float32,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_slots", "n_bins", "n_channels", "row_tile", "interpret", "vma",
        "mode",
    ),
)
def histogram_small(
    x_binned: jax.Array,
    payload: jax.Array,
    slot: jax.Array,
    *,
    n_slots: int,
    n_bins: int,
    n_channels: int,
    row_tile: int | None = None,
    interpret: bool = False,
    vma: tuple = (),
    mode: str = "auto",
) -> jax.Array:
    """(N,F) bins + (N,C) payload + (N,) slot -> (S, F, C, B) histogram.

    ``interpret=True`` runs the kernel in the Pallas interpreter —
    ``tests/test_pallas_hist.py`` uses it to check exact equality against
    the XLA scatter histogram on CPU, without a TPU. ``vma`` names the
    shard_map mesh axes the output varies over (required when called inside
    ``shard_map``: the per-shard partial histogram varies over the data axis
    until the caller's psum).
    """
    N, F = x_binned.shape
    C, S = n_channels, n_slots
    Bp = _round_up(max(n_bins, 1), 128)
    if mode == "auto":
        if _fits_single(F, S, C, n_bins):
            mode = "single"
        elif _fgrid_eligible(S, C, n_bins):
            mode = "fgrid"
        else:
            raise ValueError(
                f"pallas histogram not eligible at F={F} S={S} C={C} "
                f"B={n_bins}; gate callers on fits_vmem()"
            )
    if row_tile is None:
        # fgrid trades one M1 recompute per feature for a per-feature
        # persistent block; a bigger row tile amortizes the extra grid
        # steps where the working set allows. An explicit row_tile is
        # always respected (test seam: small tiles exercise the
        # cross-row-tile accumulation on small N).
        if mode == "fgrid":
            row_tile = _fgrid_row_tile(S, C, n_bins)
            if row_tile is None:
                # A forced fgrid past the VMEM sizing would fail at
                # hardware allocation time with a Mosaic error; fail the
                # same way auto mode's ineligibility does instead.
                raise ValueError(
                    f"fgrid working set exceeds VMEM budget at S={S} "
                    f"C={C} B={n_bins}; gate callers on fits_vmem()"
                )
        else:
            row_tile = 512
    Np = _round_up(max(N, 1), row_tile)

    if Np != N:
        pad = Np - N
        x_binned = jnp.pad(x_binned, ((0, pad), (0, 0)))
        payload = jnp.pad(payload, ((0, pad), (0, 0)))
        slot = jnp.pad(slot, (0, pad), constant_values=-1)

    out_shape = jax.ShapeDtypeStruct((F, S * C, Bp), jnp.float32)
    if vma:
        out_shape = jax.ShapeDtypeStruct(
            (F, S * C, Bp), jnp.float32, vma=frozenset(vma)
        )
    if mode == "single":
        out = pl.pallas_call(
            functools.partial(_hist_kernel, n_slots=S, n_bins_pad=Bp),
            grid=(Np // row_tile,),
            in_specs=[
                pl.BlockSpec((row_tile, 1), lambda i: (i, 0)),
                pl.BlockSpec((row_tile, C), lambda i: (i, 0)),
                pl.BlockSpec((row_tile, F), lambda i: (i, 0)),
            ],
            # Constant index map: the block persists across the sequential
            # TPU grid, accumulating one row tile per step.
            out_specs=pl.BlockSpec((F, S * C, Bp), lambda i: (0, 0, 0)),
            out_shape=out_shape,
            interpret=interpret,
        )(slot[:, None], payload, x_binned)
    else:
        out = pl.pallas_call(
            functools.partial(_hist_kernel_fgrid, n_slots=S, n_bins_pad=Bp),
            # F outer, row tiles inner (TPU grids iterate the last axis
            # fastest): each feature's out block accumulates across its
            # full row sweep before the grid moves to the next feature.
            grid=(F, Np // row_tile),
            in_specs=[
                pl.BlockSpec((row_tile, 1), lambda f, i: (i, 0)),
                pl.BlockSpec((row_tile, C), lambda f, i: (i, 0)),
                pl.BlockSpec((row_tile, 1), lambda f, i: (i, f)),
            ],
            out_specs=pl.BlockSpec((1, S * C, Bp), lambda f, i: (f, 0, 0)),
            out_shape=out_shape,
            interpret=interpret,
        )(slot[:, None], payload, x_binned)
    # (F, S*C, Bp) -> (S, F, C, B)
    return out.reshape(F, S, C, Bp)[:, :, :, :n_bins].transpose(1, 0, 2, 3)


def class_payload(y: jax.Array, w: jax.Array, n_classes: int) -> jax.Array:
    """(N,) labels + weights -> (N, C) one-hot payload for classification."""
    c_iota = jax.lax.broadcasted_iota(jnp.int32, (y.shape[0], n_classes), 1)
    return (y[:, None] == c_iota).astype(jnp.float32) * w[:, None]


def moment_payload(y: jax.Array, w: jax.Array) -> jax.Array:
    """(N,) targets + weights -> (N, 3) ``(w, w*y, w*y^2)`` payload."""
    y32 = y.astype(jnp.float32)
    return jnp.stack([w, w * y32, w * y32 * y32], axis=1)


def gbdt_payload(g: jax.Array, h: jax.Array) -> jax.Array:
    """(N,) gradients + hessians -> (N, 3) ``(count, g, h)`` payload.

    ``h == 0`` marks rows outside the boosting round's subsample — they
    contribute to no channel, the count included (the kernels mask
    out-of-chunk rows by slot equality, so only the subsample mask needs
    to ride the payload)."""
    cnt = jnp.where(h > 0, 1.0, 0.0).astype(jnp.float32)
    return jnp.stack([cnt, g.astype(jnp.float32), h.astype(jnp.float32)],
                     axis=1)


def pallas_available(platform: str) -> bool:
    """True when the Mosaic TPU backend can compile this kernel ("axon" =
    the tunneled accelerator's backend name; its devices report "tpu" in
    practice, but the health probe accepts both — so does this)."""
    return _HAS_PLTPU and platform in ("tpu", "axon")


# Conservative VMEM ceiling for the kernel's persistent out block plus its
# per-tile working set (~16 MB/core physical).
_VMEM_BUDGET_BYTES = 10 << 20


def _fits_single(n_features: int, n_slots: int, n_channels: int,
                 n_bins: int) -> bool:
    """Whether the one-block kernel's (F, S*C, Bpad) f32 out fits budget."""
    bp = _round_up(max(n_bins, 1), 128)
    return n_features * n_slots * n_channels * bp * 4 <= _VMEM_BUDGET_BYTES


def _fgrid_row_tile(n_slots: int, n_channels: int,
                    n_bins: int) -> int | None:
    """Largest row tile whose fgrid working set fits budget, or None.

    Working set per grid step: the persistent (1, S*C, Bp) out block, the
    M1 construction's THREE (Rt, S*C) f32 intermediates (slot mask, tiled
    payload, product — counted materialized; Mosaic may fuse them, but
    VMEM-allocation failures on hardware are the one error the interpret-
    mode tests cannot catch, so the accounting stays conservative), and
    the (Rt, Bp) bin one-hot.
    """
    bp = _round_up(max(n_bins, 1), 128)
    out_b = n_slots * n_channels * bp * 4
    for rt in (2048, 1024, 512, 256):
        work = rt * (3 * n_slots * n_channels + bp) * 4
        if out_b + work <= _VMEM_BUDGET_BYTES:
            return rt
    return None


# The dense one-hot contraction carries an S*C*B factor per row; past this
# many S*C lanes its FLOPs catch up with the scatter wall-clock it replaces
# (covtype estimate: S*C=448 is ~7 TFLOP/level — well ahead of the ~1s XLA
# scatter; S*C~3600 is a wash). Pending the bench_tpu hist_tput tier sweep
# on real hardware, cap auto-eligibility where the win is unambiguous.
_FGRID_MAX_SLOT_CHANNELS = 1024


def _fgrid_eligible(n_slots: int, n_channels: int, n_bins: int) -> bool:
    return (n_slots * n_channels <= _FGRID_MAX_SLOT_CHANNELS
            and _fgrid_row_tile(n_slots, n_channels, n_bins) is not None)


def fits_vmem(n_features: int, n_slots: int, n_channels: int,
              n_bins: int) -> bool:
    """Whether SOME kernel variant is eligible at this shape.

    The one-block kernel holds (F, S*C, Bpad) persistent — S <= ~8 at
    covtype shape; the feature-gridded variant holds (1, S*C, Bpad) and
    reaches S=64..128, which is exactly the frontier range the fused
    crown's middle tiers occupy. histogram_small picks the variant by the
    same predicates, so gating on this function is always safe.
    """
    return (_fits_single(n_features, n_slots, n_channels, n_bins)
            or _fgrid_eligible(n_slots, n_channels, n_bins))
