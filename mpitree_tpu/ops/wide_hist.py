"""Sorted window-packed histogram for WIDE frontiers — the deep-level tier.

The build's hot op at shallow levels is served by the Pallas MXU kernel
(``pallas_hist.py``), but its dense one-hot contraction carries an ``S*C*B``
FLOP factor per row, so past a few hundred frontier slots it loses to the
XLA scatter — which a TPU executes on the *scalar* unit at ~30M updates/s
(round-4 ``BENCH_TPU.jsonl``: ~0.9 s/level on the covtype depth-20 build's
deep levels, single-digit percent of the HBM roofline). This module removes
the scatter from wide levels entirely:

1. **Sort** rows by frontier slot (one ``argsort`` per histogram call).
2. **Window-pack**: group sorted rows by slot *window* (``W`` consecutive
   slots) and pad each window's run to a multiple of the row tile, so every
   row tile intersects exactly ONE window. Pure gather construction — the
   packed source index per position is computed with ``searchsorted`` over
   the (tiny) per-window offset table; no scatter anywhere.
3. **Contract**: each tile is a dense ``(W*C, Rt) @ (Rt, Fc*B)`` one-hot
   contraction on the MXU, accumulated into its window's block of the
   ``(S/W, ...)`` histogram. Two executors share steps 1-2:

   - :func:`histogram_wide` — a ``lax.scan`` over tiles with in-place
     ``dynamic_update_slice`` accumulation. Pure XLA, runs anywhere; each
     tile pays a read-modify-write of its window block.
   - :func:`histogram_wide_pallas` — a Mosaic kernel whose *output block
     index* is scalar-prefetched from the per-tile window id (the
     grouped-matmul pattern): consecutive tiles of one window accumulate
     in VMEM and each window block is written to HBM exactly once. TPU
     only; ``bench_tpu.py``'s hist_tput section measures both so routing
     can follow hardware evidence.

FLOPs per row are ``W*C*B`` — independent of the frontier width ``S`` — so
a 4096-slot deep level costs the same per row as a 32-slot one. The
reference burns these levels in per-candidate Python rescans
(``mpitree/tree/decision_tree.py:73-86``); the shallow-tier story is in
``pallas_hist.py``.

Exactness: counts are sums of ``onehot * payload`` products. For
integer-valued payloads (unit/bootstrap weights — the ``integer_weights``
fast path) every product and partial sum is exactly representable in f32
below 2**24, so the result is bit-identical to the scatter path and
order-independent (the determinism-across-mesh-sizes contract,
``ops/histogram.py``). ``bf16_ok=True`` additionally runs the matmul inputs
in bfloat16 (2x MXU throughput): exact when payload values are integers
<= 256 (bf16 has an 8-bit mantissa) — callers gate it on that. Non-integer
float weights follow the same contract as the Pallas kernel: f32
accumulation whose summation order may differ from the scatter's by ulps.

Works on any backend (pure XLA): CPU tests pin bit-identity against
``ops/histogram.py``; inside ``shard_map`` each shard sorts and packs its
local rows and the caller's psum merges shards, exactly like the scatter.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu imports fail on builds without TPU support
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# Routing constants shared by both engines: below MIN_SLOTS the Pallas MXU
# tiers (or the scatter, off-TPU) win — the sort/pack overhead is fixed
# while the matmul saving shrinks with S. WINDOW must divide the slot
# width; 32 keeps the per-window block (W*C rows) within one MXU pass for
# every payload width the builders use (C <= 8 after sublane padding).
MIN_SLOTS = 256
WINDOW = 32


def _auto_row_tile(R: int, n_win: int) -> int:
    # Big tiles amortize per-tile overhead, but every (possibly) occupied
    # window pads to a tile multiple — bound the tile by occupancy
    # (R / n_win) so pad rows can't dominate live rows on small shards or
    # sparse chunks (8-way covtype shard at K=4096: a flat 1024 tile would
    # pack ~2 pad rows per live row).
    return min(1024, max(128, _round_up(R // max(n_win, 1), 128)))


def _sort_and_pack(x_binned, payload, slot, *, n_slots: int, window: int,
                   row_tile: int, f_pad: int):
    """Steps 1-2 shared by both executors.

    Returns ``(xb_p, pay_p, wl_p, wnd_tile, n_tiles, counts)``: packed
    inputs of ``n_tiles * row_tile`` rows where every tile's rows belong
    to ONE slot window (``wnd_tile[i]``), pad rows carry ``wl_p = -1``
    (their one-hot row is all zeros), ``xb_p`` is feature-padded to
    ``f_pad``, and ``counts`` is the (n_win,) live-row count per window
    (the Pallas executor masks never-visited blocks with it).
    """
    R, F = x_binned.shape
    S, W, Rt = n_slots, window, row_tile
    n_win = S // W
    # Worst-case packed length: every live row plus up to Rt-1 pad rows
    # per window. Static — grid/scan lengths must not depend on data.
    n_tiles = (R + n_win * (Rt - 1) + Rt - 1) // Rt
    Npad = n_tiles * Rt

    # --- 1. sort rows by slot (dead rows to the top) ---------------------
    live_mask = (slot >= 0) & (slot < S)
    sl = jnp.where(live_mask, slot, S).astype(jnp.int32)
    order = jnp.argsort(sl)
    sl_sorted = sl[order]
    win_sorted = sl_sorted // W  # dead rows -> n_win (== S // W)

    # --- 2. window-pack via gather-only index construction ---------------
    # bnd[k] = first sorted position of window k (bnd[n_win] = live total,
    # everything after it is dead rows sorted to the top).
    ks = jnp.arange(n_win + 1, dtype=jnp.int32)
    bnd = jnp.searchsorted(win_sorted, ks, side="left").astype(jnp.int32)
    starts = bnd[:n_win]
    counts = bnd[1:] - starts  # (n_win,) live rows per window
    padded = ((counts + Rt - 1) // Rt) * Rt
    pstart = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(padded).astype(jnp.int32)]
    )  # (n_win+1,) padded window starts; pstart[-1] = live packed total
    pos = jnp.arange(Npad, dtype=jnp.int32)
    k_of_p = (
        jnp.searchsorted(pstart, pos, side="right").astype(jnp.int32) - 1
    )
    in_range = k_of_p < n_win
    k_clip = jnp.minimum(k_of_p, n_win - 1)
    local = pos - pstart[k_clip]
    live = in_range & (local < counts[k_clip])
    src_sorted = jnp.where(live, starts[k_clip] + local, 0)
    src = order[src_sorted]  # (Npad,) original row index per packed pos

    xb_p = jnp.where(live[:, None], jnp.take(x_binned, src, axis=0), 0)
    pay_p = jnp.where(live[:, None], jnp.take(payload, src, axis=0), 0.0)
    # Local slot within the window; -1 kills the one-hot row for pad rows.
    wl_p = jnp.where(live, sl_sorted[src_sorted] - k_clip * W, -1)
    if f_pad != F:
        xb_p = jnp.pad(xb_p, ((0, 0), (0, f_pad - F)))
    wnd_tile = k_clip.reshape(n_tiles, Rt)[:, 0]
    return xb_p, pay_p, wl_p, wnd_tile, n_tiles, counts


def _finalize(hist, *, n_slots, n_bins, f_true, window, n_channels,
              feature_chunk, bp):
    """(n_win, n_fc, W*C, Fc*Bp) accumulator -> (S, F, C, B) histogram."""
    n_win = n_slots // window
    W, C, Fc = window, n_channels, feature_chunk
    n_fc = hist.shape[1]
    out = hist.reshape(n_win, n_fc, W, C, Fc, bp)
    out = out.transpose(0, 2, 1, 4, 3, 5)  # (n_win, W, n_fc, Fc, C, Bp)
    return out.reshape(n_slots, n_fc * Fc, C, bp)[:, :f_true, :, :n_bins]


# No donation on purpose: xb/payload/slot are level-loop invariants the
# builders reuse across every level and chunk of a build, and the scan
# carry (the packed histogram) has no input-aliasable shape. Re-audited
# under GL08 (donation-after-use): donating here would be the GL08 bug —
# every level's next histogram call re-reads all three inputs.
# graftlint: disable=GL05
@functools.partial(
    jax.jit,
    static_argnames=("n_slots", "n_bins", "n_channels", "window",
                     "row_tile", "feature_chunk", "bf16_ok", "vma"),
)
def histogram_wide(
    x_binned: jax.Array,
    payload: jax.Array,
    slot: jax.Array,
    *,
    n_slots: int,
    n_bins: int,
    n_channels: int,
    window: int = WINDOW,
    row_tile: int | None = None,
    feature_chunk: int = 8,
    bf16_ok: bool = False,
    vma: tuple = (),
) -> jax.Array:
    """(N,F) bins + (N,C) payload + (N,) slot -> (S, F, C, B) histogram.

    ``slot`` is the frontier slot per row; rows outside ``[0, n_slots)``
    (parked in leaves, padding, other chunks) contribute nothing.
    ``payload`` is ``class_payload``/``moment_payload`` from
    ``pallas_hist`` — one function serves both tasks. ``vma`` names the
    shard_map mesh axes this shard's partial histogram varies over (the
    scan carry's zero init must carry the same varying axes as the scanned
    row data or the carry types mismatch).
    """
    R, F = x_binned.shape
    C, S, W, Fc = n_channels, n_slots, window, feature_chunk
    if S % W:
        raise ValueError(f"window {W} must divide n_slots {S}")
    n_win = S // W
    Rt = row_tile if row_tile is not None else _auto_row_tile(R, n_win)
    Bp = _round_up(max(n_bins, 1), 128)
    Fp = _round_up(F, Fc)
    n_fc = Fp // Fc

    xb_p, pay_p, wl_p, wnd_tile, n_tiles, _counts = _sort_and_pack(
        x_binned, payload, slot, n_slots=S, window=W, row_tile=Rt, f_pad=Fp,
    )
    mm_dtype = jnp.bfloat16 if bf16_ok else jnp.float32

    # --- 3. scan of MXU contractions, window blocks updated in place -----
    def tile_body(hist, tile):
        xb_t, pay_t, wl_t, wnd = tile  # (Rt,Fp) (Rt,C) (Rt,) ()
        sc_iota = lax.broadcasted_iota(jnp.int32, (Rt, W * C), 1)
        m1 = jnp.where(
            sc_iota // C == wl_t[:, None], jnp.tile(pay_t, (1, W)), 0.0
        ).astype(mm_dtype)  # (Rt, W*C)
        b_iota = lax.broadcasted_iota(jnp.int32, (Rt, Fc, Bp), 2)

        def fc_body(fc, hist):
            xcols = lax.dynamic_slice(xb_t, (0, fc * Fc), (Rt, Fc))
            onehot = (xcols[:, :, None] == b_iota).astype(mm_dtype)
            blk = lax.dot_general(
                m1, onehot.reshape(Rt, Fc * Bp),
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (W*C, Fc*Bp)
            old = lax.dynamic_slice(
                hist, (wnd, fc, 0, 0), (1, 1, W * C, Fc * Bp)
            )
            return lax.dynamic_update_slice(
                hist, old + blk[None, None], (wnd, fc, 0, 0)
            )

        return lax.fori_loop(0, n_fc, fc_body, hist), None

    hist0 = jnp.zeros((n_win, n_fc, W * C, Fc * Bp), jnp.float32)
    if vma:
        hist0 = lax.pcast(hist0, tuple(vma), to="varying")
    xs = (
        xb_p.reshape(n_tiles, Rt, Fp),
        pay_p.reshape(n_tiles, Rt, C),
        wl_p.reshape(n_tiles, Rt),
        wnd_tile,
    )
    hist, _ = lax.scan(tile_body, hist0, xs)
    return _finalize(hist, n_slots=S, n_bins=n_bins, f_true=F, window=W,
                     n_channels=C, feature_chunk=Fc, bp=Bp)


def _wide_kernel(wnd_ref, wl_ref, pay_ref, xb_ref, out_ref, *, window,
                 n_channels, n_bins_pad, fc_width, mm_dtype):
    """Grouped-matmul grid step: one (feature chunk, row tile) pair.

    Grid is ``(n_fc, n_tiles)`` — tiles innermost, so each (fc, window)
    output block sees its tiles as one contiguous run: zero it when the
    run starts (first tile, or the prefetched window id changed) and let
    Mosaic's revisiting-block machinery keep it in VMEM until the id
    changes again, writing it to HBM exactly once per run.
    """
    W, C, Bp, Fc = window, n_channels, n_bins_pad, fc_width
    i = pl.program_id(1)
    wnd_prev = wnd_ref[jnp.maximum(i - 1, 0)]

    @pl.when(jnp.logical_or(i == 0, wnd_ref[i] != wnd_prev))
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    Rt = wl_ref.shape[0]
    sc_iota = lax.broadcasted_iota(jnp.int32, (Rt, W * C), 1)
    wl = wl_ref[:, 0]
    m1 = jnp.where(
        sc_iota // C == wl[:, None],
        jnp.concatenate([pay_ref[...]] * W, axis=1),
        0.0,
    ).astype(mm_dtype)  # (Rt, W*C)
    b_iota = lax.broadcasted_iota(jnp.int32, (Rt, Bp), 1)
    for f in range(Fc):  # unrolled: Fc static, one MXU matmul each
        onehot = (xb_ref[:, f][:, None] == b_iota).astype(mm_dtype)
        out_ref[0, 0, :, f * Bp:(f + 1) * Bp] += lax.dot_general(
            m1, onehot,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )


@functools.partial(
    jax.jit,
    static_argnames=("n_slots", "n_bins", "n_channels", "window",
                     "row_tile", "feature_chunk", "bf16_ok", "interpret",
                     "vma"),
)
def histogram_wide_pallas(
    x_binned: jax.Array,
    payload: jax.Array,
    slot: jax.Array,
    *,
    n_slots: int,
    n_bins: int,
    n_channels: int,
    window: int = WINDOW,
    row_tile: int | None = None,
    feature_chunk: int = 8,
    bf16_ok: bool = False,
    interpret: bool = False,
    vma: tuple = (),
) -> jax.Array:
    """Same contract as :func:`histogram_wide`, Mosaic executor.

    The per-tile window id rides as a scalar-prefetch operand; the output
    BlockSpec indexes on it, so window blocks accumulate in VMEM across
    their contiguous tile runs (guaranteed by the packing) instead of
    round-tripping HBM per tile. ``interpret=True`` runs the Pallas
    interpreter — the CPU exactness seam, like ``pallas_hist``'s.
    """
    R, F = x_binned.shape
    C, S, W, Fc = n_channels, n_slots, window, feature_chunk
    if S % W:
        raise ValueError(f"window {W} must divide n_slots {S}")
    n_win = S // W
    Rt = row_tile if row_tile is not None else _auto_row_tile(R, n_win)
    if not pallas_fits(C, n_bins, window=W, feature_chunk=Fc, row_tile=Rt):
        raise ValueError(
            f"wide Mosaic working set exceeds VMEM at W={W} C={C} "
            f"B={n_bins} Fc={Fc} Rt={Rt}; gate callers on pallas_fits()"
        )
    Bp = _round_up(max(n_bins, 1), 128)
    Fp = _round_up(F, Fc)
    n_fc = Fp // Fc

    xb_p, pay_p, wl_p, wnd_tile, n_tiles, counts = _sort_and_pack(
        x_binned, payload, slot, n_slots=S, window=W, row_tile=Rt, f_pad=Fp,
    )
    mm_dtype = jnp.bfloat16 if bf16_ok else jnp.float32

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_fc, n_tiles),
        in_specs=[
            pl.BlockSpec((Rt, 1), lambda fc, i, wnd: (i, 0)),
            pl.BlockSpec((Rt, C), lambda fc, i, wnd: (i, 0)),
            pl.BlockSpec((Rt, Fc), lambda fc, i, wnd: (i, fc)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, W * C, Fc * Bp), lambda fc, i, wnd: (wnd[i], fc, 0, 0)
        ),
    )
    out_shape = jax.ShapeDtypeStruct(
        (n_win, n_fc, W * C, Fc * Bp), jnp.float32
    )
    if vma:  # inside shard_map the per-shard partial varies over the mesh
        out_shape = jax.ShapeDtypeStruct(
            out_shape.shape, out_shape.dtype, vma=frozenset(vma)
        )
    hist = pl.pallas_call(
        functools.partial(
            _wide_kernel, window=W, n_channels=C, n_bins_pad=Bp,
            fc_width=Fc, mm_dtype=mm_dtype,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(wnd_tile, wl_p[:, None], pay_p, xb_p)
    # Blocks of EMPTY windows are never visited by any grid step, so they
    # come back uninitialized — mask them with the pack's window counts.
    hist = jnp.where(
        (counts > 0)[:, None, None, None], hist, 0.0
    )
    return _finalize(hist, n_slots=S, n_bins=n_bins, f_true=F, window=W,
                     n_channels=C, feature_chunk=Fc, bp=Bp)


def pallas_fits(n_channels: int, n_bins: int, *,
                window: int = WINDOW, feature_chunk: int = 8,
                row_tile: int = 1024) -> bool:
    """Whether the Mosaic executor's VMEM working set fits (~16 MB/core).

    Persistent out block (double-buffered) plus the per-step row-tile
    inputs and the (Rt, W*C) m1 intermediate. The block scales with
    ``n_channels`` unboundedly, so callers gate on this the way
    ``use_pallas`` gates on ``pallas_hist.fits_vmem`` — an unfittable
    forced request should fail at routing, not deep inside Mosaic.
    """
    bp = _round_up(max(n_bins, 1), 128)
    block = window * n_channels * feature_chunk * bp * 4 * 2
    # Per-step working set: m1 (Rt, W*C) counted twice (mask intermediate),
    # ONE per-feature (Rt, Bp) one-hot (the kernel's f-loop reuses it),
    # payload (Rt, C) and the xb column block (Rt, Fc).
    work = row_tile * (
        2 * window * n_channels + bp + n_channels + feature_chunk + 8
    ) * 4
    return block + work <= (10 << 20)


def wide_pallas_available(platform: str) -> bool:
    """True when the Mosaic grouped-matmul executor can compile.

    Accepts "axon" alongside "tpu": the tunneled accelerator registers
    under that backend name (its devices report platform "tpu" in the
    round-4 captures, but the health probe accepts both — so does this).
    """
    return _HAS_PLTPU and platform in ("tpu", "axon")
