"""Sorted window-packed histogram for WIDE frontiers — the deep-level tier.

The build's hot op at shallow levels is served by the Pallas MXU kernel
(``pallas_hist.py``), but its dense one-hot contraction carries an ``S*C*B``
FLOP factor per row, so past a few hundred frontier slots it loses to the
XLA scatter — which a TPU executes on the *scalar* unit at ~30M updates/s
(round-4 ``BENCH_TPU.jsonl``: ~0.9 s/level on the covtype depth-20 build's
deep levels, single-digit percent of the HBM roofline). This module removes
the scatter from wide levels entirely:

1. **Sort** rows by frontier slot (one ``argsort`` per histogram call).
2. **Window-pack**: group sorted rows by slot *window* (``W`` consecutive
   slots) and pad each window's run to a multiple of the row tile, so every
   row tile intersects exactly ONE window. Pure gather construction — the
   packed source index per position is computed with ``searchsorted`` over
   the (tiny) per-window offset table; no scatter anywhere.
3. **Contract**: a ``lax.scan`` over row tiles; each tile is a dense
   ``(W*C, Rt) @ (Rt, Fc*B)`` one-hot contraction on the MXU (features in
   chunks of ``Fc``), accumulated into its window's block of the
   ``(S/W, ...)`` histogram via in-place ``dynamic_update_slice``.

FLOPs per row are ``W*C*B`` — independent of the frontier width ``S`` — so
a 4096-slot deep level costs the same per row as a 32-slot one. The
reference burns these levels in per-candidate Python rescans
(``mpitree/tree/decision_tree.py:73-86``); the shallow-tier story is in
``pallas_hist.py``.

Exactness: counts are sums of ``onehot * payload`` products. For
integer-valued payloads (unit/bootstrap weights — the ``integer_weights``
fast path) every product and partial sum is exactly representable in f32
below 2**24, so the result is bit-identical to the scatter path and
order-independent (the determinism-across-mesh-sizes contract,
``ops/histogram.py``). ``bf16_ok=True`` additionally runs the matmul inputs
in bfloat16 (2x MXU throughput): exact when payload values are integers
<= 256 (bf16 has an 8-bit mantissa) — callers gate it on that. Non-integer
float weights follow the same contract as the Pallas kernel: f32
accumulation whose summation order may differ from the scatter's by ulps.

Works on any backend (pure XLA): CPU tests pin bit-identity against
``ops/histogram.py``; inside ``shard_map`` each shard sorts and packs its
local rows and the caller's psum merges shards, exactly like the scatter.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# Routing constants shared by both engines: below MIN_SLOTS the Pallas MXU
# tiers (or the scatter, off-TPU) win — the sort/pack overhead is fixed
# while the matmul saving shrinks with S. WINDOW must divide the slot
# width; 32 keeps the per-window block (W*C rows) within one MXU pass for
# every payload width the builders use (C <= 8 after sublane padding).
MIN_SLOTS = 256
WINDOW = 32


@functools.partial(
    jax.jit,
    static_argnames=("n_slots", "n_bins", "n_channels", "window",
                     "row_tile", "feature_chunk", "bf16_ok", "vma"),
)
def histogram_wide(
    x_binned: jax.Array,
    payload: jax.Array,
    slot: jax.Array,
    *,
    n_slots: int,
    n_bins: int,
    n_channels: int,
    window: int = WINDOW,
    row_tile: int | None = None,
    feature_chunk: int = 8,
    bf16_ok: bool = False,
    vma: tuple = (),
) -> jax.Array:
    """(N,F) bins + (N,C) payload + (N,) slot -> (S, F, C, B) histogram.

    ``slot`` is the frontier slot per row; rows outside ``[0, n_slots)``
    (parked in leaves, padding, other chunks) contribute nothing.
    ``payload`` is ``class_payload``/``moment_payload`` from
    ``pallas_hist`` — one function serves both tasks. ``vma`` names the
    shard_map mesh axes this shard's partial histogram varies over (the
    scan carry's zero init must carry the same varying axes as the scanned
    row data or the carry types mismatch).
    """
    R, F = x_binned.shape
    if row_tile is None:
        # Big tiles amortize the scan/DUS overhead, but every (possibly)
        # occupied window pads to a tile multiple — bound the tile by
        # occupancy (R / n_win) so pad rows can't dominate live rows on
        # small shards or sparse chunks (8-way covtype shard at K=4096:
        # a flat 1024 tile would pack ~2 pad rows per live row).
        row_tile = min(
            1024, max(128, _round_up(R // max(n_slots // window, 1), 128))
        )
    C, S, W, Rt, Fc = n_channels, n_slots, window, row_tile, feature_chunk
    if S % W:
        raise ValueError(f"window {W} must divide n_slots {S}")
    n_win = S // W
    Bp = _round_up(max(n_bins, 1), 128)
    Fp = _round_up(F, Fc)
    n_fc = Fp // Fc
    # Worst-case packed length: every live row plus up to Rt-1 pad rows per
    # window. Static — the scan length must not depend on data.
    n_tiles = (R + n_win * (Rt - 1) + Rt - 1) // Rt
    Npad = n_tiles * Rt

    # --- 1. sort rows by slot (dead rows to the top) ---------------------
    live_mask = (slot >= 0) & (slot < S)
    sl = jnp.where(live_mask, slot, S).astype(jnp.int32)
    order = jnp.argsort(sl)
    sl_sorted = sl[order]
    win_sorted = sl_sorted // W  # dead rows -> n_win (== S // W)

    # --- 2. window-pack via gather-only index construction ---------------
    # bnd[k] = first sorted position of window k (bnd[n_win] = live total,
    # everything after it is dead rows sorted to the top).
    ks = jnp.arange(n_win + 1, dtype=jnp.int32)
    bnd = jnp.searchsorted(win_sorted, ks, side="left").astype(jnp.int32)
    starts = bnd[:n_win]
    counts = bnd[1:] - starts  # (n_win,) live rows per window
    padded = ((counts + Rt - 1) // Rt) * Rt
    pstart = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(padded).astype(jnp.int32)]
    )  # (n_win+1,) padded window starts; pstart[-1] = live packed total
    pos = jnp.arange(Npad, dtype=jnp.int32)
    k_of_p = (
        jnp.searchsorted(pstart, pos, side="right").astype(jnp.int32) - 1
    )
    in_range = k_of_p < n_win
    k_clip = jnp.minimum(k_of_p, n_win - 1)
    local = pos - pstart[k_clip]
    live = in_range & (local < counts[k_clip])
    src_sorted = jnp.where(live, starts[k_clip] + local, 0)
    src = order[src_sorted]  # (Npad,) original row index per packed pos

    xb_p = jnp.where(live[:, None], jnp.take(x_binned, src, axis=0), 0)
    pay_p = jnp.where(live[:, None], jnp.take(payload, src, axis=0), 0.0)
    # Local slot within the window; -1 kills the one-hot row for pad rows.
    wl_p = jnp.where(live, sl_sorted[src_sorted] - k_clip * W, -1)
    if Fp != F:
        xb_p = jnp.pad(xb_p, ((0, 0), (0, Fp - F)))

    mm_dtype = jnp.bfloat16 if bf16_ok else jnp.float32

    # --- 3. scan of MXU contractions, window blocks updated in place -----
    def tile_body(hist, tile):
        xb_t, pay_t, wl_t, wnd = tile  # (Rt,Fp) (Rt,C) (Rt,) ()
        sc_iota = lax.broadcasted_iota(jnp.int32, (Rt, W * C), 1)
        m1 = jnp.where(
            sc_iota // C == wl_t[:, None], jnp.tile(pay_t, (1, W)), 0.0
        ).astype(mm_dtype)  # (Rt, W*C)
        b_iota = lax.broadcasted_iota(jnp.int32, (Rt, Fc, Bp), 2)

        def fc_body(fc, hist):
            xcols = lax.dynamic_slice(xb_t, (0, fc * Fc), (Rt, Fc))
            onehot = (xcols[:, :, None] == b_iota).astype(mm_dtype)
            blk = lax.dot_general(
                m1, onehot.reshape(Rt, Fc * Bp),
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (W*C, Fc*Bp)
            old = lax.dynamic_slice(
                hist, (wnd, fc, 0, 0), (1, 1, W * C, Fc * Bp)
            )
            return lax.dynamic_update_slice(
                hist, old + blk[None, None], (wnd, fc, 0, 0)
            )

        return lax.fori_loop(0, n_fc, fc_body, hist), None

    hist0 = jnp.zeros((n_win, n_fc, W * C, Fc * Bp), jnp.float32)
    if vma:
        hist0 = lax.pcast(hist0, tuple(vma), to="varying")
    xs = (
        xb_p.reshape(n_tiles, Rt, Fp),
        pay_p.reshape(n_tiles, Rt, C),
        wl_p.reshape(n_tiles, Rt),
        k_clip.reshape(n_tiles, Rt)[:, 0],
    )
    hist, _ = lax.scan(tile_body, hist0, xs)

    # (n_win, n_fc, W*C, Fc*Bp) -> (S, F, C, n_bins)
    out = hist.reshape(n_win, n_fc, W, C, Fc, Bp)
    out = out.transpose(0, 2, 1, 4, 3, 5)  # (n_win, W, n_fc, Fc, C, Bp)
    return out.reshape(S, Fp, C, Bp)[:, :F, :, :n_bins]
