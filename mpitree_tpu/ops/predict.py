"""Vectorized tree descent — inference without per-row Python recursion.

The reference predicts by a Python closure recursing per row under
``np.apply_along_axis`` (reference: ``mpitree/tree/decision_tree.py:208-227``)
— O(rows × depth) interpreter work. Here all rows descend in lockstep with a
``lax.fori_loop`` of gathers over the struct-of-arrays tree: rows parked on a
leaf keep their node id, so ``max_depth`` iterations land every row on its
leaf. Runs fully on device with static shapes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


@partial(jax.jit, static_argnames=("n_steps",))
def descend(
    X: jax.Array,
    feature: jax.Array,
    threshold: jax.Array,
    left: jax.Array,
    right: jax.Array,
    *,
    n_steps: int,
) -> jax.Array:
    """Route each row of ``X`` to its leaf; returns (N,) leaf node ids.

    Parameters
    ----------
    X : (N, F) float32 raw feature values.
    feature/threshold/left/right : tree arrays (``feature < 0`` marks leaves).
    n_steps : static descent depth (tree ``max_depth``).
    """
    n = X.shape[0]

    def body(_, node):
        f = feature[node]
        is_leaf = f < 0
        xf = jnp.take_along_axis(X, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
        go_left = xf <= threshold[node]
        nxt = jnp.where(go_left, left[node], right[node])
        return jnp.where(is_leaf, node, nxt)

    return lax.fori_loop(0, n_steps, body, jnp.zeros(n, dtype=jnp.int32))


def predict_leaf_ids(X, tree_dev, n_steps: int) -> jax.Array:
    """Convenience wrapper: ``tree_dev`` = (feature, threshold, left, right)."""
    feature, threshold, left, right = tree_dev
    return descend(X, feature, threshold, left, right, n_steps=max(n_steps, 1))
