"""Vectorized tree descent — inference without per-row Python recursion.

The reference predicts by a Python closure recursing per row under
``np.apply_along_axis`` (reference: ``mpitree/tree/decision_tree.py:208-227``)
— O(rows × depth) interpreter work. Here all rows descend in lockstep with a
``lax.fori_loop`` of gathers over the struct-of-arrays tree: rows parked on a
leaf keep their node id, so ``max_depth`` iterations land every row on its
leaf. Runs fully on device with static shapes.
"""

from __future__ import annotations

import weakref
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding

from mpitree_tpu.parallel import mesh as mesh_lib, partition
from mpitree_tpu.parallel.mesh import DATA_AXIS


class WeakIdCache:
    """id-keyed cache holding values alive only while the key object lives.

    Estimator predict paths use this instead of writing lazily-computed
    device arrays into ``self.__dict__`` (sklearn's conformance checks
    require predict to leave the estimator's ``__dict__`` untouched)."""

    def __init__(self):
        self._store: dict = {}

    def get_or_build(self, key_obj, build):
        k = id(key_obj)
        hit = self._store.get(k)
        if hit is not None and hit[0]() is key_obj:
            return hit[1]
        try:
            ref = weakref.ref(key_obj, lambda _r, k=k: self._store.pop(k, None))
        except TypeError:  # plain lists etc. aren't weakref-able: no caching
            return build()
        val = build()
        self._store[k] = (ref, val)
        return val


_tree_device_cache = WeakIdCache()


def device_tree_arrays(tree):
    """(feature, threshold, left, right) on device, cached per tree object."""
    return _tree_device_cache.get_or_build(
        tree,
        lambda: tuple(
            jax.device_put(a)
            for a in (tree.feature, tree.threshold, tree.left, tree.right)
        ),
    )


# No donation on purpose: X and the tree arrays are cached device buffers
# reused across predict calls (device_tree_arrays / stacked groups), and the
# fori_loop carry is one fresh (N,) id vector no input could alias anyway.
# Re-audited under GL08: every caller (predict_leaf_ids, the stacked vmap
# groups) re-reads X and the tree arrays after the call — donation would
# turn those reads into the garbage-read bug GL08 exists to catch.
@partial(jax.jit, static_argnames=("n_steps",))  # graftlint: disable=GL05
def descend(
    X: jax.Array,
    feature: jax.Array,
    threshold: jax.Array,
    left: jax.Array,
    right: jax.Array,
    *,
    n_steps: int,
) -> jax.Array:
    """Route each row of ``X`` to its leaf; returns (N,) leaf node ids.

    Parameters
    ----------
    X : (N, F) float32 raw feature values.
    feature/threshold/left/right : tree arrays (``feature < 0`` marks leaves).
    n_steps : static descent depth (tree ``max_depth``).
    """
    n = X.shape[0]

    def body(_, node):
        f = feature[node]
        is_leaf = f < 0
        xf = jnp.take_along_axis(X, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
        go_left = xf <= threshold[node]
        nxt = jnp.where(go_left, left[node], right[node])
        return jnp.where(is_leaf, node, nxt)

    return lax.fori_loop(0, n_steps, body, jnp.zeros(n, dtype=jnp.int32))


def predict_mesh(estimator):
    """The estimator's inference mesh, or None for the single-device path.

    Multi-device fits (``n_devices`` set) predict data-sharded over the
    same mesh; any resolution failure (e.g. an accelerator that vanished
    after fit) falls back to single-device inference rather than failing
    a predict that needs no collective.
    """
    nd = getattr(estimator, "n_devices", None)
    if nd in (None, 1):
        return None
    try:
        mesh = mesh_lib.resolve_mesh(
            backend=getattr(estimator, "backend", None), n_devices=nd
        )
        return mesh if mesh.size > 1 else None
    except Exception:  # noqa: BLE001 — inference must not die on mesh loss
        return None


def predict_leaf_ids(X, tree_dev, n_steps: int, mesh=None) -> jax.Array:
    """Convenience wrapper: ``tree_dev`` = (feature, threshold, left, right).

    ``mesh``: optional multi-device mesh — rows shard over its ``data``
    axis with the tree arrays replicated, so inference scales across chips
    instead of running on one. (The reference's MPI ranks each predict the
    FULL test set redundantly, ``decision_tree.py:227`` under §3.3 of the
    survey; data-sharded descent is the SPMD completion of that story.)
    Rows pad to the shard grid and the result trims back.
    """
    feature, threshold, left, right = tree_dev
    if mesh is not None and mesh.size > 1:
        Xd, n = shard_rows(X, mesh)
        ids = descend(
            Xd, feature, threshold, left, right, n_steps=max(n_steps, 1)
        )
        return ids[:n]
    if not isinstance(X, jax.Array):
        X = jax.device_put(X)
    return descend(X, feature, threshold, left, right, n_steps=max(n_steps, 1))


def shard_rows(X, mesh):
    """(X sharded over the mesh's data axis, original row count).

    Rows pad to the shard grid by repeating the last row (the caller trims
    results back to ``n``). The one copy of the pad-and-place recipe —
    single-tree inference and the forests' stacked descent both use it.
    """
    Xh = np.asarray(X)
    n = Xh.shape[0]
    shards = int(dict(mesh.shape).get(DATA_AXIS, 1))
    pad = (-n) % max(shards, 1)
    if pad:
        Xh = np.concatenate(
            [Xh, np.broadcast_to(Xh[-1:], (pad,) + Xh.shape[1:])]
        )
    return jax.device_put(
        Xh, NamedSharding(mesh, partition.spec_for("x_rows", mesh))
    ), n


# Device-memory ceiling for one ensemble descent group — kept as the
# public knob name; the flat serving tables (mpitree_tpu.serving.tables)
# now enforce it on a padding-free layout.
STACKED_GROUP_BYTES = 256 << 20


def stacked_leaf_ids(trees, X, *, mesh=None,
                     group_bytes: int = STACKED_GROUP_BYTES) -> np.ndarray:
    """(T, N) per-tree leaf ids for an ensemble — ONE traversal dispatch
    over the cached depth-packed serving table.

    The ONE ensemble-inference path — bagged forests and boosting both
    ride it. Since ISSUE 7 it descends the flat serving node table
    (``serving.tables``): no per-tree vmap axis, no ``(T, max_nodes)``
    padding, descent steps bound by the ensemble's TRUE depth, and —
    unlike the old per-call ``jax.device_put(a[sl])`` group uploads — the
    device-resident arrays are cached in the same weak-ref entry as the
    host table, so a warm predict transfers only the query batch.
    Ensembles whose tables exceed ``group_bytes`` split into multiple
    tables (one dispatch each), so deep forests cannot pin gigabytes of
    accelerator memory. ``mesh``: optional multi-device mesh — query rows
    shard over its data axis with the table replicated (GSPMD partitions
    the gather descent).
    """
    # Lazy import: serving.tables imports this module's WeakIdCache.
    from mpitree_tpu.serving.tables import tables_for
    from mpitree_tpu.serving.traversal import flat_leaf_ids

    tables = tables_for(trees, group_bytes=group_bytes)
    if mesh is not None:
        X_d, n = shard_rows(X, mesh)
    else:
        X_d = X if isinstance(X, jax.Array) else jax.device_put(X)
        n = X.shape[0]
    ids = np.empty((len(trees), n), np.int32)
    t0 = 0
    for tb in tables:
        # Single-table ensembles cache their device copy (warm predicts
        # transfer only X); a multi-table split uploads transiently so
        # peak device residency stays bounded by ONE group.
        feat, thr, left, right, root, orig = tb.dev_arrays(
            cache=len(tables) == 1
        )
        rel = flat_leaf_ids(
            X_d, feat, thr, left, right, root, orig, n_steps=tb.n_steps
        )
        ids[t0:t0 + tb.n_trees] = np.asarray(rel).T[:, :n]
        t0 += tb.n_trees
    return ids
