"""TPU compute ops: binning, histograms, impurity/gain, prediction kernels."""
