"""Per-node random feature subsampling — sklearn's ``max_features`` granularity.

The reference has no ensembles; sklearn's random forests draw a fresh
feature subset at every *node*. Reproducing that under this framework's
engine-identity contract (host numpy build == device build, any mesh size)
needs randomness that is a pure function of tree structure, not of engine
or visitation order.

One deliberate divergence from sklearn: a node whose k sampled features
admit no valid split becomes a leaf — sklearn keeps drawing features past
``max_features`` until it finds a valid partition. The no-redraw rule is
LightGBM's ``feature_fraction_bynode`` semantics, and it is what a
batched level-synchronous search can evaluate in one pass.

Scheme:

- every node carries a uint32 **key**; the root key hashes the tree seed,
  and children hash the parent key with side-distinct constants — keys are
  derived from the node's *path*, so any engine that walks the same tree
  computes the same keys;
- the node's feature subset is the first ``k`` entries of a permutation of
  features, obtained by a stable argsort of per-(node, feature) hash
  scores. ``numpy`` (host tier, level loops) and ``jnp`` (the fused
  in-jit variant: :func:`pcg_hash_jnp`, :func:`node_masks_jnp`,
  :func:`node_draws_jnp`, consumed inside the fused engine's
  ``lax.while_loop`` body) implement the identical uint32 arithmetic.

The hash is the 32-bit PCG output permutation (``pcg_hash``) — cheap,
well-avalanched, and exactly reproducible in wrap-around uint32 arithmetic
everywhere.
"""

from __future__ import annotations

import dataclasses

import numpy as np

def seed_from(random_state) -> int:
    """Accept sklearn's random_state idioms: None, int, Generator, RandomState.

    ``None`` reads as seed 0 — this framework never fits
    nondeterministically.
    """
    if random_state is None:
        return 0
    if isinstance(random_state, np.random.Generator):
        return int(random_state.integers(2**32))
    if isinstance(random_state, np.random.RandomState):
        return int(random_state.randint(2**32))
    try:
        return int(random_state)
    except (TypeError, ValueError):
        raise ValueError(
            f"random_state must be None, an int, or a numpy "
            f"Generator/RandomState, got {random_state!r}"
        ) from None


def sampler_for(max_features, random_state, n_features: int,
                splitter: str = "best"):
    """Estimator-side constructor: sampler for the params, or None.

    sklearn's single-tree estimators accept the same ``max_features``
    grammar.
    """
    if splitter not in ("best", "random"):
        raise ValueError(
            f"splitter must be 'best' or 'random', got {splitter!r}"
        )
    k = n_subspace_features(max_features, n_features)
    if k >= n_features and splitter == "best":
        return None
    return NodeFeatureSampler(
        k=min(k, n_features), n_features=n_features,
        seed=seed_from(random_state), random_split=(splitter == "random"),
    )


def n_subspace_features(max_features, n_features: int) -> int:
    """sklearn's ``max_features`` grammar -> a concrete subset size k.

    Invalid values raise (as sklearn's do) rather than silently disabling
    or over-tightening the sampling.
    """
    import math
    import numbers

    if max_features is None:
        return n_features
    if isinstance(max_features, str):
        if max_features == "sqrt":
            return max(1, int(math.sqrt(n_features)))
        if max_features == "log2":
            return max(1, int(math.log2(n_features)))
        raise ValueError(
            f"max_features must be 'sqrt', 'log2', an int, a float in "
            f"(0, 1], or None, got {max_features!r}"
        )
    if isinstance(max_features, numbers.Real) and not isinstance(
        max_features, numbers.Integral
    ):
        if not 0.0 < max_features <= 1.0:
            raise ValueError(
                f"float max_features must be in (0, 1], got {max_features!r}"
            )
        return max(1, int(max_features * n_features))
    k = int(max_features)
    if not 0 < k <= n_features:
        raise ValueError(
            f"int max_features must be in [1, n_features={n_features}], "
            f"got {max_features!r}"
        )
    return k


_MULT = np.uint32(747796405)
_INC = np.uint32(2891336453)
_FIN = np.uint32(277803737)
_LEFT_SALT = np.uint32(0x9E3779B9)
_RIGHT_SALT = np.uint32(0xC2B2AE35)
_FEAT_SALT = np.uint32(0x85EBCA6B)
_DRAW_SALT = np.uint32(0x27D4EB2F)  # random-split bin draws (ExtraTrees)
_ROW_SALT = np.uint32(0x51ED270B)  # per-round row subsampling (boosting)
_COL_SALT = np.uint32(0x6C62272E)  # per-round feature subsampling (boosting)
_BOOT_SALT = np.uint32(0x94D049BB)  # per-tree bootstrap draws (forests)


def pcg_hash(x: np.ndarray) -> np.ndarray:
    """Vectorized PCG-XSH-RR style u32 -> u32 hash (wrap-around arithmetic)."""
    with np.errstate(over="ignore"):
        x = (x.astype(np.uint32) * _MULT + _INC).astype(np.uint32)
        shift = ((x >> np.uint32(28)) + np.uint32(4)).astype(np.uint32)
        word = (((x >> shift) ^ x) * _FIN).astype(np.uint32)
        return ((word >> np.uint32(22)) ^ word).astype(np.uint32)


def row_subsample_mask(seed: int, round_idx: int, n_rows: int,
                       fraction: float) -> np.ndarray:
    """(n_rows,) bool mask of rows sampled into one boosting round.

    Stochastic gradient boosting's per-round row subsample, keyed like
    everything else in this module: each row's inclusion is
    ``pcg_hash(mix(seed, round) + row) < fraction * 2^32`` — a pure
    function of (seed, round, row), so refits, resumed fits, and every
    mesh size draw the identical subsample without materializing index
    permutations. Expected draw is Bernoulli(fraction) per row (LightGBM's
    ``bagging_fraction`` semantics, without replacement).
    """
    if not 0.0 < fraction <= 1.0:
        raise ValueError(f"subsample fraction must be in (0, 1], got {fraction!r}")
    if fraction >= 1.0:
        return np.ones(n_rows, bool)
    with np.errstate(over="ignore"):
        base = np.uint32(
            pcg_hash(np.uint32(seed))
            ^ pcg_hash((np.uint32(round_idx) + _ROW_SALT).astype(np.uint32))
        )
        keys = pcg_hash(base + np.arange(n_rows, dtype=np.uint32))
    return keys < subsample_threshold_u32(fraction)


def feature_subsample_mask(seed: int, round_idx: int, n_features: int,
                           fraction: float) -> np.ndarray:
    """(n_features,) bool mask of features sampled into one boosting round.

    XGBoost's ``colsample_bytree``, keyed like :func:`row_subsample_mask`:
    a pure function of (seed, round, feature), so refits, resumed fits,
    and every mesh size draw the identical subset. Unlike the Bernoulli
    row draw this selects EXACTLY ``k = max(1, floor(fraction * F))``
    features — a round with zero features cannot fit a tree, and a fixed
    k keeps the sliced binned matrix one compiled executable across
    rounds. Selection is the first k of a stable ascending argsort of
    per-(round, feature) PCG scores — hash-collision ties resolve to the
    lowest feature index, the same stability contract as
    :meth:`NodeFeatureSampler.node_masks`.
    """
    if not 0.0 < fraction <= 1.0:
        raise ValueError(
            f"colsample fraction must be in (0, 1], got {fraction!r}"
        )
    if fraction >= 1.0:
        return np.ones(n_features, bool)
    k = max(1, int(fraction * n_features))
    with np.errstate(over="ignore"):
        base = np.uint32(
            pcg_hash(np.uint32(seed))
            ^ pcg_hash((np.uint32(round_idx) + _COL_SALT).astype(np.uint32))
        )
        f = np.arange(n_features, dtype=np.uint32)
        scores = pcg_hash(base + (f + np.uint32(1)) * _COL_SALT)
    order = np.argsort(scores, kind="stable")
    mask = np.zeros(n_features, bool)
    mask[order[:k]] = True
    return mask


def _poisson1_cutoffs() -> np.ndarray:
    """u32 inverse-CDF cutoffs for Poisson(1) multiplicities.

    ``cutoffs[k] = round(CDF(k) * 2^32)``; a uniform u32 draw ``u`` maps
    to multiplicity ``searchsorted(cutoffs, u, side='right')``. The tail
    past k=12 carries < 1e-12 mass and the float64 CDF rounds to 2^32
    there, so multiplicities cap at the table length — exact for every
    representable draw.
    """
    pmf = np.empty(13, np.float64)
    pmf[0] = np.exp(-1.0)
    for k in range(1, 13):
        pmf[k] = pmf[k - 1] / k
    return np.minimum(
        np.round(np.cumsum(pmf) * 4294967296.0), 4294967296.0 - 1
    ).astype(np.uint64)


_POISSON1_CUTOFFS = _poisson1_cutoffs()


def bootstrap_weights(seed: int, tree_idx: int, n_rows: int) -> np.ndarray:
    """(n_rows,) f32 keyed bootstrap multiplicities for one forest tree.

    The streamed forest's bootstrap: each row's in-bag count is a
    Poisson(1) draw keyed by (seed, tree, row) — the online-bagging
    approximation of the with-replacement multinomial (Oza & Russell),
    and like :func:`row_subsample_mask` a pure function of global row
    index, so any chunking of the stream, any mesh, and a resumed fit
    all draw the identical bootstrap. In-memory fits opt in with
    ``MPITREE_TPU_KEYED_BOOTSTRAP=1`` to become a streamed fit's
    fingerprint twin (the default host-RNG multinomial draw is kept for
    backward-reproducibility).
    """
    with np.errstate(over="ignore"):
        base = np.uint32(
            pcg_hash(np.uint32(seed))
            ^ pcg_hash((np.uint32(tree_idx) + _BOOT_SALT).astype(np.uint32))
        )
        keys = pcg_hash(base + np.arange(n_rows, dtype=np.uint32))
    return np.searchsorted(
        _POISSON1_CUTOFFS, keys.astype(np.uint64), side="right"
    ).astype(np.float32)


def tree_seed(seed: int, tree_idx: int) -> int:
    """Per-tree u32 sampler seed in keyed-bootstrap mode.

    A pure function of (forest seed, tree index): the in-memory path
    draws sampler seeds from a stateful host RNG interleaved with the
    bootstrap draws, which a streamed fit cannot replay — keyed mode
    derives both from the same counter scheme instead.
    """
    with np.errstate(over="ignore"):
        return int(pcg_hash(
            pcg_hash(np.uint32(seed))
            ^ ((np.uint32(tree_idx) + np.uint32(1)) * _BOOT_SALT)
            .astype(np.uint32)
        ))


def feature_subset(seed: int, tree_idx: int, n_features: int,
                   k: int) -> np.ndarray:
    """Sorted k-feature subset for one tree in keyed-bootstrap mode.

    The keyed twin of ``rng.choice(F, k, replace=False)`` for
    ``max_features_mode="tree"``: per-feature hashed scores keyed by
    (seed, tree, feature), stable-argsorted, lowest k kept — without
    replacement by construction and, like every draw in this module, a
    pure function of its key tuple.
    """
    with np.errstate(over="ignore"):
        base = np.uint32(
            pcg_hash(np.uint32(seed))
            ^ pcg_hash((np.uint32(tree_idx) + _FEAT_SALT).astype(np.uint32))
        )
        scores = pcg_hash(base + np.arange(n_features, dtype=np.uint32))
    return np.sort(np.argsort(scores, kind="stable")[:k])


def subsample_threshold_u32(fraction: float) -> np.uint32:
    """The u32 acceptance threshold :func:`row_subsample_mask` compares
    against — shared with the jnp twin so the fused multi-round program
    and the host loop draw identical subsamples. Callers gate
    ``fraction < 1`` themselves (1.0 would wrap)."""
    return np.uint32(int(fraction * 4294967296.0))


def row_subsample_mask_jnp(seed, round_idx, row_ids, threshold):
    """jnp twin of :func:`row_subsample_mask` for in-dispatch rounds.

    ``round_idx`` may be TRACED (the fused multi-round GBDT program scans
    it); ``row_ids`` are GLOBAL row indices (shard offset + local iota —
    row shards are contiguous blocks, so global index == host row index);
    ``threshold`` from :func:`subsample_threshold_u32`. Bit-identical to
    the host mask for rows < N; padding rows (global id >= N) draw
    arbitrary bits but carry zero weight everywhere.
    """
    import jax.numpy as jnp

    base = pcg_hash_jnp(jnp.asarray(seed).astype(jnp.uint32)) ^ pcg_hash_jnp(
        jnp.asarray(round_idx).astype(jnp.uint32) + jnp.uint32(_ROW_SALT)
    )
    keys = pcg_hash_jnp(base + row_ids.astype(jnp.uint32))
    return keys < threshold


def pcg_hash_jnp(x):
    """jnp twin of :func:`pcg_hash` — identical uint32 wrap-around arithmetic.

    Runs inside jitted programs (the fused engine threads node keys through
    its ``lax.while_loop`` state); uint32 ops wrap silently under XLA, so no
    errstate dance is needed.
    """
    import jax.numpy as jnp

    x = x.astype(jnp.uint32) * jnp.uint32(_MULT) + jnp.uint32(_INC)
    shift = (x >> jnp.uint32(28)) + jnp.uint32(4)
    word = ((x >> shift) ^ x) * jnp.uint32(_FIN)
    return (word >> jnp.uint32(22)) ^ word


def node_masks_jnp(keys, k: int, n_features: int):
    """jnp twin of :meth:`NodeFeatureSampler.node_masks`.

    (S,) uint32 keys -> (S, F) bool of each node's k allowed features. Uses
    the same stable ascending argsort of per-(node, feature) hash scores, so
    ties at equal scores resolve to the lowest feature index exactly as the
    numpy tier does; membership is rank < k via the inverse permutation
    (argsort of a permutation is exact, no second stability requirement).
    """
    import jax.numpy as jnp

    if k >= n_features:
        return jnp.ones((keys.shape[0], n_features), bool)
    f = jnp.arange(n_features, dtype=jnp.uint32)
    scores = pcg_hash_jnp(
        keys.astype(jnp.uint32)[:, None] ^ ((f[None, :] + jnp.uint32(1))
                                            * jnp.uint32(_FEAT_SALT))
    )
    order = jnp.argsort(scores, axis=1, stable=True)
    rank = jnp.argsort(order, axis=1)
    return rank < k


def node_draws_jnp(keys, n_features: int):
    """jnp twin of :meth:`NodeFeatureSampler.node_draws` ((S, F) uint32)."""
    import jax.numpy as jnp

    f = jnp.arange(n_features, dtype=jnp.uint32)
    return pcg_hash_jnp(
        keys.astype(jnp.uint32)[:, None] ^ ((f[None, :] + jnp.uint32(1))
                                            * jnp.uint32(_DRAW_SALT))
    )


def child_keys_jnp(keys):
    """jnp twin of :meth:`NodeFeatureSampler.child_keys`."""
    import jax.numpy as jnp

    p = keys.astype(jnp.uint32)
    return (
        pcg_hash_jnp(p ^ jnp.uint32(_LEFT_SALT)),
        pcg_hash_jnp(p ^ jnp.uint32(_RIGHT_SALT)),
    )


@dataclasses.dataclass(frozen=True)
class NodeFeatureSampler:
    """Draws the per-node feature subset; engines thread keys alongside nodes.

    Parameters
    ----------
    k : int
        Features allowed per node (``1 <= k <= n_features``).
    n_features : int
    seed : int
        Tree-level seed (a forest derives one per tree).
    """

    k: int
    n_features: int
    seed: int
    root_key_value: int | None = None  # subtree builds start mid-path
    # ExtraTrees mode: per-(node, feature) uniform candidate draws replace
    # the exhaustive per-feature argmin (sklearn's splitter="random",
    # quantized to the candidate grammar: uniform over the node's VALID
    # candidate bins rather than the continuous value range).
    random_split: bool = False

    @property
    def active(self) -> bool:
        return self.k < self.n_features or self.random_split

    def root_key(self) -> np.uint32:
        if self.root_key_value is not None:
            return np.uint32(self.root_key_value)
        return pcg_hash(np.uint32(self.seed & 0xFFFFFFFF))

    def child_keys(self, parent_keys: np.ndarray):
        """(left_keys, right_keys) for an array of parent keys."""
        p = parent_keys.astype(np.uint32)
        return pcg_hash(p ^ _LEFT_SALT), pcg_hash(p ^ _RIGHT_SALT)

    def node_masks(self, keys: np.ndarray) -> np.ndarray:
        """(S,) keys -> (S, F) bool — True on the node's k allowed features.

        Stable ascending argsort of per-(node, feature) hash scores; the
        first k positions of the permutation win. Stability makes hash
        collisions resolve to the lowest feature index identically in every
        implementation. ``k >= n_features`` (splitter="random" with no
        subsetting — the ExtraTreesRegressor default) skips the scoring.
        """
        if self.k >= self.n_features:
            return np.ones((len(keys), self.n_features), bool)
        f = np.arange(self.n_features, dtype=np.uint32)
        with np.errstate(over="ignore"):
            scores = pcg_hash(
                keys.astype(np.uint32)[:, None]
                ^ ((f[None, :] + np.uint32(1)) * _FEAT_SALT).astype(np.uint32)
            )
        order = np.argsort(scores, axis=1, kind="stable")
        mask = np.zeros((len(keys), self.n_features), bool)
        np.put_along_axis(mask, order[:, : self.k], True, axis=1)
        return mask

    def node_draws(self, keys: np.ndarray) -> np.ndarray:
        """(S,) keys -> (S, F) uint32 — the per-(node, feature) draw used
        by splitter="random" (independent salt from the subset scores)."""
        f = np.arange(self.n_features, dtype=np.uint32)
        with np.errstate(over="ignore"):
            return pcg_hash(
                keys.astype(np.uint32)[:, None]
                ^ ((f[None, :] + np.uint32(1)) * _DRAW_SALT).astype(np.uint32)
            )

    def key_store(self, root_keys=None) -> KeyStore:
        return KeyStore(self, root_keys)

    def keys_for_tree(self, tree) -> np.ndarray:
        """Recompute every node's key from tree structure (parents first).

        Lets the hybrid refine seed its subtree roots with the crown
        leaves' keys — structural paths, not build order, define keys, so
        any engine that grew the same crown agrees.
        """
        keys = np.zeros(tree.n_nodes, np.uint32)
        keys[0] = self.root_key()
        # Breadth-first over depth levels: every level's parents hash in one
        # vectorized call (parents always precede children in id order).
        for d in range(int(tree.depth.max(initial=0)) + 1):
            parents = np.flatnonzero((tree.depth == d) & (tree.left >= 0))
            if not len(parents):
                continue
            lk, rk = self.child_keys(keys[parents])
            keys[tree.left[parents]] = lk
            keys[tree.right[parents]] = rk
        return keys


class KeyStore:
    """Growable per-node key array — the ONE key-threading bookkeeping.

    Every level-loop engine (device levelwise, host numpy/C++, batched
    refine) threads keys through this store so the engine-identity contract
    cannot be broken by divergent hand-rolled copies.
    """

    def __init__(self, sampler: NodeFeatureSampler, root_keys=None):
        self._sampler = sampler
        if root_keys is None:
            self.keys = np.zeros(256, np.uint32)
            self.keys[0] = sampler.root_key()
        else:
            self.keys = np.asarray(root_keys, np.uint32).copy()

    def slice(self, lo: int, hi: int) -> np.ndarray:
        return self.keys[lo:hi]

    def masks(self, lo: int, hi: int) -> np.ndarray:
        return self._sampler.node_masks(self.keys[lo:hi])

    def draws(self, lo: int, hi: int) -> np.ndarray:
        return self._sampler.node_draws(self.keys[lo:hi])

    def assign_children(self, parent_ids, left_ids, right_ids, n_total: int):
        """Hand children their path-derived keys (growing the store)."""
        if n_total > len(self.keys):
            grown = np.zeros(max(n_total, 2 * len(self.keys)), np.uint32)
            grown[: len(self.keys)] = self.keys
            self.keys = grown
        lk, rk = self._sampler.child_keys(self.keys[parent_ids])
        self.keys[left_ids] = lk
        self.keys[right_ids] = rk
