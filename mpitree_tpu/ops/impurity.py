"""Split evaluation from histograms: entropy / Gini / MSE gains and argmin.

Reproduces the reference's split selection semantics exactly
(reference: ``mpitree/tree/decision_tree.py:53-91,130-141``):

- cost of candidate ``(f, b)`` = weighted child impurity
  ``(n_l * H(left) + n_r * H(right)) / n`` — the reference's
  ``np.dot(weights, impurity)`` at ``decision_tree.py:86``;
- per feature, the best candidate is the cost argmin with ties broken toward
  the **lowest threshold** (reference ``np.argmin`` at ``:90``; our bins are
  threshold-ascending so ``jnp.argmin``'s first-minimum matches);
- across features, the winner is the gain argmax with ties broken toward the
  **lowest feature index** (reference ``np.argmax`` at ``:140``); since
  ``gain = H(parent) - cost`` with a shared parent term, first-max over gains
  equals first-min over costs, which is what we compute.

Candidates whose left or right partition would be empty are masked to ``+inf``
cost. In exact-binning mode this only removes the top-unique-value candidate,
which the reference can never select (cost == parent impurity >= the minimum,
and ties break toward lower thresholds), so parity is preserved. It also makes
the build robust where the reference would crash: a zero-gain tie won by a
constant feature sends the reference into an empty-partition recursion and a
``bincount([]).argmax()`` ValueError (``decision_tree.py:125``); we pick the
first *valid* candidate instead.

All reductions run replicated on identical psum'd histograms, so every device
selects the identical split — the XLA-SPMD restatement of the reference's
replicated-argmax correctness contract (``decision_tree.py:408-419``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SplitDecision(NamedTuple):
    """Per-frontier-slot split search result (all shapes (K,) unless noted).

    ``feature``/``bin`` identify the winning candidate; ``cost`` is its
    weighted child impurity (``+inf`` if no valid candidate exists);
    ``impurity`` and ``n`` describe the parent node; ``counts`` is the
    class-count vector (K, C) for classification or the
    ``(w, w*y, w*y^2)`` moment vector (K, 3) for regression; ``constant`` is
    True when every feature has at most one occupied bin (the reference's
    all-rows-identical stop, ``decision_tree.py:119``); ``y_range`` is the
    exact per-node max(y)-min(y) for regression purity detection (f32 moment
    variance cannot resolve near-zero spreads) and zeros for classification.
    """

    feature: jax.Array
    bin: jax.Array
    cost: jax.Array
    impurity: jax.Array
    n: jax.Array
    counts: jax.Array
    constant: jax.Array
    y_range: jax.Array
    # Winning candidate's child values (class-0 fraction for classification,
    # mean target for regression) — zeros unless monotonic constraints are
    # active. The builder derives children's bounds from their average
    # (sklearn's middle_value, sklearn/tree/_tree.pyx bound propagation).
    v_left: jax.Array = None
    v_right: jax.Array = None
    # Winning candidate's left-side total (weight for classification/
    # regression, subsampled row count for gbdt) — what the sibling-
    # subtraction frontier uses to pick the smaller child per pair
    # (``n_left * 2 <= n`` => left is small; ties go left). Exact integers
    # in f32 wherever the histogram channels are.
    n_left: jax.Array = None


def _entropy(counts: jax.Array, n: jax.Array) -> jax.Array:
    """Shannon entropy (bits) over trailing class axis; 0 for empty nodes."""
    safe_n = jnp.maximum(n, 1.0)
    p = counts / safe_n[..., None]
    terms = jnp.where(counts > 0, p * jnp.log2(jnp.maximum(p, 1e-38)), 0.0)
    return -terms.sum(axis=-1)


def _gini(counts: jax.Array, n: jax.Array) -> jax.Array:
    safe_n = jnp.maximum(n, 1.0)
    p = counts / safe_n[..., None]
    return jnp.where(n > 0, 1.0 - (p * p).sum(axis=-1), 0.0)


def class_impurity(counts: jax.Array, n: jax.Array, criterion: str) -> jax.Array:
    if criterion == "entropy":
        return _entropy(counts, n)
    if criterion == "gini":
        return _gini(counts, n)
    raise ValueError(f"unknown classification criterion: {criterion!r}")


def _cost_sweep_f64(hist, criterion: str):
    """(K,F,C,B) histogram -> (cost_hi, cost_lo, n_l, n_r) float32.

    The f64 cost leaves the scoped-x64 block as a two-float (hi, lo) pair
    — ``hi = f32(cost64)``, ``lo = f32(cost64 - f64(hi))`` — because any
    jnp op on an f64 array outside the scope silently canonicalizes back
    to f32, and jnp reductions (argmin/min) on f64 operands are broken
    even inside it (their cached inner jits build f32 init values).
    Lexicographic (hi, lo) order equals f64 order to ~2^-48 relative,
    so the caller ranks candidates in plain f32 ops with f64 fidelity.
    ``n_l``/``n_r`` come back as f32 (integer counts — exact).

    Mirrors ``host_builder._child_impurity_class`` op for op — division
    (not reciprocal-multiply), ``p * log2(max(p, 1e-300))`` terms, classes
    summed sequentially ascending (numpy's reduction order for C < 8) —
    inside a scoped ``jax.enable_x64`` so the f32-disabled default config
    still traces real f64 ops. Counts are integers (exact in f64), so the
    only rounding is in the division/log/product chain: ~1e-15 relative,
    vs ~1e-7 for the f32 sweep. Cost gaps the host's f64 resolves are now
    resolved identically on-device (the r4 seam workload holds identity
    to depth 20, tests/test_engine_identity.py).

    The residual, stated plainly: XLA CPU's fused codegen is NOT bitwise
    numpy — it keeps excess precision / reassociates inside fusions
    (measured: ``(l/d)*(l/d)`` summed = exactly 17/25 where numpy's
    twice-rounded ops give 1 ulp more; optimization_barrier and bitcast
    round-trips do not stop it). So an EXACT rational tie between two
    different count configurations (e.g. two gini costs both equal to
    13/35 — common at small integer-featured nodes) can compute equal on
    the host but ulps apart here, flipping the first-min pick; sub-ulp
    gaps likewise. Bounded by
    ``tests/test_engine_identity.py::test_exact_tie_residual_is_bounded``.
    CPU backends only — TPUs have no f64 unit; the hybrid's host tail
    owns deep small nodes there (``resolve_exact_ties``).
    """
    with jax.enable_x64(True):
        C = hist.shape[2]

        def l_of(c):  # per-class left cumsum, f64, transient
            return jnp.cumsum(hist[:, :, c, :].astype(jnp.float64), axis=2)

        # Pass A: side totals. The host's l.sum(axis=2) over per-class
        # cumsums is sequential-ascending for C < 8 (numpy's pairwise
        # blocking) — mirrored here; integer counts are exact either way.
        n_l = l_of(0)
        for c in range(1, C):
            n_l = n_l + l_of(c)
        n_tot = n_l[:, :, -1:]
        n_r = n_tot - n_l

        # Pass B: per-side impurity terms accumulated class by class in
        # the same ascending order the host's t.sum(axis=2) uses. Only
        # (K,F,B)-sized f64 buffers stay live (the (K,F,C,B) l/r stacks
        # the host materializes would multiply the working set by C).
        div_l = jnp.maximum(n_l, 1.0)
        div_r = jnp.maximum(n_r, 1.0)
        acc_l = acc_r = None
        for c in range(C):
            l_c = l_of(c)
            r_c = l_c[:, :, -1:] - l_c
            p_l = l_c / div_l  # division, not reciprocal-multiply (host op)
            p_r = r_c / div_r
            if criterion == "entropy":
                t_l = jnp.where(
                    l_c > 0, p_l * jnp.log2(jnp.maximum(p_l, 1e-300)), 0.0
                )
                t_r = jnp.where(
                    r_c > 0, p_r * jnp.log2(jnp.maximum(p_r, 1e-300)), 0.0
                )
            else:
                t_l = p_l * p_l
                t_r = p_r * p_r
            acc_l = t_l if acc_l is None else acc_l + t_l
            acc_r = t_r if acc_r is None else acc_r + t_r
        if criterion == "entropy":
            h_l, h_r = -acc_l, -acc_r
        else:
            h_l = jnp.where(n_l > 0, 1.0 - acc_l, 0.0)
            h_r = jnp.where(n_r > 0, 1.0 - acc_r, 0.0)

        cost = (n_l * h_l + n_r * h_r) / jnp.maximum(n_tot, 1.0)
        hi = cost.astype(jnp.float32)
        lo = (cost - hi.astype(jnp.float64)).astype(jnp.float32)
        return hi, lo, n_l.astype(jnp.float32), n_r.astype(jnp.float32)


def best_split_classification(
    hist: jax.Array, cand_mask: jax.Array, *, criterion: str = "entropy",
    node_mask: jax.Array | None = None, min_child_weight=None,
    forced_draw: jax.Array | None = None,
    mono_cst: jax.Array | None = None,
    mono_lo: jax.Array | None = None,
    mono_hi: jax.Array | None = None,
    exact_ties: bool = False,
) -> SplitDecision:
    """Pick the best (feature, bin) per frontier slot from a class histogram.

    Parameters
    ----------
    hist : (K, F, C, B) float32 — from :func:`histogram.class_histogram`
        (bins last for TPU lane alignment).
    cand_mask : (F, B) bool — valid candidate bins (from
        :meth:`BinnedData.candidate_mask`).
    node_mask : (K, F) bool, optional — per-node allowed features
        (``ops/sampling.py``); masked features cannot win but still feed
        the ``constant`` occupancy stop, matching the host tiers.
    mono_cst : (F,) int32, optional — INTERNAL monotonicity signs (the
        estimator flips user signs for classification, sklearn's
        class-0-fraction convention): a candidate on feature f with
        ``mono_cst[f] != 0`` is valid only when
        ``(v_l - v_r) * mono_cst[f] <= 0`` and both child values lie in
        the node's ``[mono_lo, mono_hi]`` (K,) bounds
        (sklearn/tree/_criterion.pyx ``_check_monotonicity``). Child
        values are ``f32(count_0) * f32(1/n)`` — the reciprocal-multiply
        form every engine reproduces bit-identically for integer weights.
        Requires binary classification (validated estimator-side).
    """
    # Memory-lean formulation: materializing left/right (K,F,B,C) cumsums and
    # per-side impurity stacks peaks at ~18 histogram-sized buffers under the
    # AOT allocator and OOMs at covtype scale. Instead accumulate the per-side
    # impurities class by class (unrolled — class counts are small): only
    # (K,F,B)-sized accumulators stay live, per-class cumsums are transient,
    # and the arithmetic on bounded p in [0,1] is float-identical to the
    # textbook -sum(p*log2 p) form, so reference tie-break parity survives.
    if criterion not in ("entropy", "gini"):
        raise ValueError(f"unknown classification criterion: {criterion!r}")
    hist_sum = hist.sum(axis=2)  # (K, F, B)
    if exact_ties:
        cost, cost_lo, n_l, n_r = _cost_sweep_f64(hist, criterion)
        inv_l = inv_r = None  # recomputed in f32 if the mono path needs them
    else:
        cost_lo = None
        n_l = jnp.cumsum(hist_sum, axis=2)
        n_tot = n_l[:, :, -1:]  # (K, F, 1)
        n_r = n_tot - n_l
        inv_l = 1.0 / jnp.maximum(n_l, 1.0)
        inv_r = 1.0 / jnp.maximum(n_r, 1.0)

        C = hist.shape[2]
        h_l = jnp.zeros_like(n_l)  # accumulates -sum_c p log2 p (or sum p^2)
        h_r = jnp.zeros_like(n_l)
        for c in range(C):
            l_c = jnp.cumsum(hist[:, :, c, :], axis=2)
            r_c = l_c[:, :, -1:] - l_c
            p_l = l_c * inv_l
            p_r = r_c * inv_r
            if criterion == "entropy":
                h_l -= jnp.where(
                    l_c > 0, p_l * jnp.log2(jnp.maximum(p_l, 1e-38)), 0.0
                )
                h_r -= jnp.where(
                    r_c > 0, p_r * jnp.log2(jnp.maximum(p_r, 1e-38)), 0.0
                )
            else:
                h_l += p_l * p_l
                h_r += p_r * p_r

        if criterion == "gini":
            h_l = 1.0 - h_l
            h_r = 1.0 - h_r
        cost = (n_l * h_l + n_r * h_r) / jnp.maximum(n_tot, 1.0)

    valid = cand_mask[None, :, :] & (n_l > 0) & (n_r > 0)
    if min_child_weight is not None:
        # accepts a traced scalar (0.0 is a no-op) — keeping it a runtime
        # operand avoids a recompile per distinct total fit weight
        valid = valid & (n_l >= min_child_weight) & (n_r >= min_child_weight)
    if node_mask is not None:
        valid = valid & node_mask[:, :, None]
    if mono_cst is not None:
        if inv_l is None:  # exact_ties path: f32 v-value contract regardless
            n_l32 = jnp.cumsum(hist_sum, axis=2)
            inv_l = 1.0 / jnp.maximum(n_l32, 1.0)
            inv_r = 1.0 / jnp.maximum(n_l32[:, :, -1:] - n_l32, 1.0)
        l0 = jnp.cumsum(hist[:, :, 0, :], axis=2)  # class-0 left mass
        v_l_all = l0 * inv_l
        v_r_all = (l0[:, :, -1:] - l0) * inv_r
        valid = valid & _monotonic_ok(
            v_l_all, v_r_all, mono_cst, mono_lo, mono_hi
        )
    cost = jnp.where(valid, cost, jnp.inf)
    if cost_lo is not None:
        cost_lo = jnp.where(valid, cost_lo, 0.0)  # inf - inf would be nan

    if forced_draw is None:
        if cost_lo is None:
            best_bin_f = jnp.argmin(cost, axis=2)  # first-min = lowest thr
        else:
            best_bin_f = _lex_argmin(cost, cost_lo, axis=2)
    else:
        best_bin_f = _drawn_bins(valid, forced_draw)
    best_cost_f = jnp.take_along_axis(cost, best_bin_f[:, :, None], axis=2)[:, :, 0]
    if cost_lo is None:
        best_feature = jnp.argmin(best_cost_f, axis=1)  # lowest feature
    else:
        best_lo_f = jnp.take_along_axis(
            cost_lo, best_bin_f[:, :, None], axis=2
        )[:, :, 0]
        best_feature = _lex_argmin(best_cost_f, best_lo_f, axis=1)
    best_bin = jnp.take_along_axis(best_bin_f, best_feature[:, None], axis=1)[:, 0]
    best_cost = jnp.take_along_axis(best_cost_f, best_feature[:, None], axis=1)[:, 0]

    parent_counts = hist[:, 0, :, :].sum(axis=-1)  # (K, C) — bins summed out
    parent_n = parent_counts.sum(axis=-1)
    parent_impurity = class_impurity(parent_counts, parent_n, criterion)

    occupied = (hist_sum > 0).sum(axis=2)  # (K, F) occupied bins
    constant = (occupied <= 1).all(axis=1)

    if mono_cst is not None:
        v_left, v_right = _winner_values(
            v_l_all, v_r_all, best_feature, best_bin
        )
    else:
        v_left = v_right = jnp.zeros_like(parent_n)

    return SplitDecision(
        feature=best_feature.astype(jnp.int32),
        bin=best_bin.astype(jnp.int32),
        cost=best_cost,
        impurity=parent_impurity,
        n=parent_n,
        counts=parent_counts,
        constant=constant,
        y_range=jnp.zeros_like(parent_n),
        v_left=v_left,
        v_right=v_right,
        # Winner's left weight from a plain f32 cumsum — exact for the
        # integer counts the subtraction frontier runs on, and crucially
        # NOT a read of the exact-ties f64 sweep's n_l: a new consumer
        # there changes XLA's fusion clustering, and the sweep's
        # excess-precision behavior (the _cost_sweep_f64 residual) is
        # fusion-sensitive — gathering from it flipped documented
        # host==device tie pins.
        n_left=_winner_gather(
            jnp.cumsum(hist_sum, axis=2), best_feature, best_bin
        ),
    )


def leaf_gain(n, impurity, cost, *, task: str):
    """Best-first expansion priority of an open leaf (numpy/jnp polymorphic).

    The ONE copy of the priority formula every leaf-wise engine ranks by,
    so the device-fused pool and the host-stepped pool can never drift:
    classification/regression use the weighted impurity decrease
    ``n * (impurity - cost)`` (sklearn's best-first ``max_leaf_nodes``
    criterion — the same quantity ``min_impurity_decrease`` gates on);
    gbdt uses the raw Newton gain ``impurity - cost`` (the
    LightGBM/XGBoost ``lossguide`` convention — ``best_split_newton``'s
    sign convention makes ``impurity - cost`` exactly the gain). All
    inputs are the f32 decision fields, and the arithmetic is one
    IEEE subtract (+ one multiply), so numpy and XLA rank identically.
    """
    gain = impurity - cost
    if task != "gbdt":
        gain = n * gain
    return gain


def best_leaf_slot(gain: jax.Array, node_id: jax.Array) -> jax.Array:
    """Pool slot of the best open leaf (leaf-wise frontier selection).

    ``gain`` is the (P,) padded pool priority (``-inf`` marks closed/empty
    slots); ``node_id`` the (P,) node id each slot holds. The winner is
    the max-gain slot, with ties broken toward the LOWEST node id —
    node ids are unique and creation-ordered, so the tie-break is
    engine- and slot-layout-independent (pool slots are reused by left
    children, so "first slot" would not be canonical). ``lax.top_k``
    extracts the max without any host sync (GL01-clean inside the fused
    while_loop); the masked argmin then resolves the tie canonically.
    """
    top, _ = jax.lax.top_k(gain, 1)
    eligible = gain == top[0]
    return jnp.argmin(
        jnp.where(eligible, node_id, jnp.int32(2**31 - 1))
    ).astype(jnp.int32)


def best_leaf_slot_np(gain, node_id) -> int:
    """numpy twin of :func:`best_leaf_slot` (host-stepped leaf-wise loop)."""
    import numpy as np

    top = np.max(gain)
    return int(np.argmin(np.where(gain == top, node_id, np.int32(2**31 - 1))))


def _lex_argmin(hi: jax.Array, lo: jax.Array, *, axis: int) -> jax.Array:
    """First index of the lexicographic (hi, lo) minimum along ``axis``.

    Two-float ranking: (hi, lo) pairs carry the f64 cost (see
    ``_cost_sweep_f64``), and lexicographic comparison on them reproduces
    the f64 order — so first-min tie-breaks (lower threshold / lower
    feature) resolve exactly as the host's f64 argmin does, using only f32
    ops the default config supports everywhere.
    """
    m_hi = jnp.min(hi, axis=axis, keepdims=True)
    cand = hi == m_hi
    lo_m = jnp.where(cand, lo, jnp.inf)
    m_lo = jnp.min(lo_m, axis=axis, keepdims=True)
    cand &= lo_m == m_lo
    ax = axis if axis >= 0 else hi.ndim + axis
    iota = jax.lax.broadcasted_iota(jnp.int32, hi.shape, ax)
    return jnp.min(jnp.where(cand, iota, hi.shape[ax]), axis=axis)


def _monotonic_ok(v_l, v_r, mono_cst, mono_lo, mono_hi) -> jax.Array:
    """sklearn's per-candidate monotonicity gate (_check_monotonicity).

    ``v_l``/``v_r`` are (K, F, B) child values; ``mono_cst`` (F,) internal
    signs; ``mono_lo``/``mono_hi`` (K,) node bounds. Unconstrained features
    (sign 0) pass unconditionally — sklearn only applies the check (bounds
    included) when the split feature carries a constraint.
    """
    cst = mono_cst.astype(v_l.dtype)[None, :, None]
    lo = mono_lo[:, None, None]
    hi = mono_hi[:, None, None]
    ok = (
        ((v_l - v_r) * cst <= 0)
        & (v_l >= lo) & (v_l <= hi)
        & (v_r >= lo) & (v_r <= hi)
    )
    return (cst == 0) | ok


def _winner_values(v_l, v_r, best_feature, best_bin):
    """Gather the winning candidate's (v_left, v_right) per slot."""
    return (
        _winner_gather(v_l, best_feature, best_bin),
        _winner_gather(v_r, best_feature, best_bin),
    )


def _winner_gather(a, best_feature, best_bin):
    """Winning candidate's entry of a (K, F, B) per-candidate array."""
    a_f = jnp.take_along_axis(a, best_bin[:, None, None], axis=2)[:, :, 0]
    return jnp.take_along_axis(a_f, best_feature[:, None], axis=1)[:, 0]


def _drawn_bins(valid: jax.Array, draw: jax.Array) -> jax.Array:
    """splitter="random": per (slot, feature), one uniform pick among the
    VALID candidate bins (sklearn's ExtraTrees threshold draw, quantized to
    the candidate grammar). ``draw`` is (K, F) uint32 from the path-derived
    node keys (ops/sampling.py), so every engine — and every mesh size —
    draws identically. Features with no valid candidate fall to bin 0,
    whose cost is already +inf."""
    cnt = valid.sum(axis=2)  # (K, F)
    j = (draw % jnp.maximum(cnt, 1).astype(jnp.uint32)).astype(jnp.int32)
    csum = jnp.cumsum(valid.astype(jnp.int32), axis=2)
    return jnp.argmax(csum > j[:, :, None], axis=2)


def best_split_newton(
    hist: jax.Array, cand_mask: jax.Array, *,
    reg_lambda,
    min_child_weight=None,
    min_samples_leaf=None,
) -> SplitDecision:
    """Pick the best Newton-gain split per frontier slot (GBDT rounds).

    Parameters
    ----------
    hist : (K, F, 3, B) float32 — from :func:`histogram.grad_hess_histogram`;
        channels are (count, gradient, hessian), bins last for TPU lane
        alignment.
    reg_lambda : traced scalar — L2 leaf regularization (XGBoost's lambda).
    min_child_weight : traced scalar, optional — minimum hessian weight per
        child (XGBoost semantics: the hessian IS the effective sample
        weight of the second-order fit).
    min_samples_leaf : traced scalar, optional — minimum subsampled row
        count per child.

    Candidate score is the XGBoost structure score
    ``G^2 / (H + lambda)`` per side; to slot into the builder's
    first-min cost ranking (lower threshold / lower feature tie-breaks)
    the decision carries ``cost = -1/2 (score_l + score_r)`` and
    ``impurity = -1/2 score_parent``, so ``impurity - cost`` is exactly
    the Newton gain ``1/2 (score_l + score_r - score_parent)`` the
    builder's min-gain gate reads. Leaf values (``-G / (H + lambda)``)
    are NOT computed here — the boosting loop refits them on host in f64
    from the final row assignments, which keeps them mesh-invariant.
    """
    c_l = jnp.cumsum(hist[:, :, 0, :], axis=2)  # (K, F, B)
    g_l = jnp.cumsum(hist[:, :, 1, :], axis=2)
    h_l = jnp.cumsum(hist[:, :, 2, :], axis=2)
    c_t, g_t, h_t = c_l[:, :, -1:], g_l[:, :, -1:], h_l[:, :, -1:]
    c_r, g_r, h_r = c_t - c_l, g_t - g_l, h_t - h_l

    def score(g, h):
        # Occupied sides have h > 0; the epsilon only guards the
        # empty/invalid candidates that the mask below discards anyway.
        return g * g / jnp.maximum(h + reg_lambda, 1e-12)

    cost = -0.5 * (score(g_l, h_l) + score(g_r, h_r))

    valid = cand_mask[None, :, :] & (c_l > 0) & (c_r > 0)
    if min_child_weight is not None:
        valid = valid & (h_l >= min_child_weight) & (h_r >= min_child_weight)
    if min_samples_leaf is not None:
        valid = valid & (c_l >= min_samples_leaf) & (c_r >= min_samples_leaf)
    cost = jnp.where(valid, cost, jnp.inf)

    best_bin_f = jnp.argmin(cost, axis=2)  # first-min = lowest threshold
    best_cost_f = jnp.take_along_axis(cost, best_bin_f[:, :, None], axis=2)[:, :, 0]
    best_feature = jnp.argmin(best_cost_f, axis=1)  # lowest feature
    best_bin = jnp.take_along_axis(best_bin_f, best_feature[:, None], axis=1)[:, 0]
    best_cost = jnp.take_along_axis(best_cost_f, best_feature[:, None], axis=1)[:, 0]

    parent = hist[:, 0, :, :].sum(axis=-1)  # (K, 3) — bins summed out
    parent_n = parent[..., 0]
    parent_impurity = -0.5 * (
        parent[..., 1] * parent[..., 1]
        / jnp.maximum(parent[..., 2] + reg_lambda, 1e-12)
    )

    occupied = (hist[:, :, 0, :] > 0).sum(axis=2)
    constant = (occupied <= 1).all(axis=1)

    zeros = jnp.zeros_like(parent_n)
    return SplitDecision(
        feature=best_feature.astype(jnp.int32),
        bin=best_bin.astype(jnp.int32),
        cost=best_cost,
        impurity=parent_impurity,
        n=parent_n,
        counts=parent,
        constant=constant,
        y_range=zeros,
        v_left=zeros,
        v_right=zeros,
        # Row count, not hessian: the subtraction frontier picks the child
        # with fewer rows to ACCUMULATE — the scatter cost is per row.
        n_left=_winner_gather(c_l, best_feature, best_bin),
    )


def best_split_regression(
    hist: jax.Array, cand_mask: jax.Array,
    node_mask: jax.Array | None = None, min_child_weight=None,
    forced_draw: jax.Array | None = None,
    mono_cst: jax.Array | None = None,
    mono_lo: jax.Array | None = None,
    mono_hi: jax.Array | None = None,
) -> SplitDecision:
    """Pick the best MSE split per frontier slot from a moment histogram.

    Parameters
    ----------
    hist : (K, F, 3, B) float32 — from :func:`histogram.moment_histogram`;
        channels are (weight, weight*y, weight*y^2), bins last for TPU lane
        alignment.

    Cost of a candidate is the weighted child variance
    ``(SSE_left + SSE_right) / n`` where ``SSE = sum(y^2) - sum(y)^2 / n`` —
    the histogram form of sklearn's ``squared_error`` improvement. Parent
    ``impurity`` is the node variance (MSE around the node mean).
    """
    w_l = jnp.cumsum(hist[:, :, 0, :], axis=2)  # (K, F, B)
    s_l = jnp.cumsum(hist[:, :, 1, :], axis=2)
    q_l = jnp.cumsum(hist[:, :, 2, :], axis=2)
    w_t, s_t, q_t = w_l[:, :, -1:], s_l[:, :, -1:], q_l[:, :, -1:]
    w_r, s_r, q_r = w_t - w_l, s_t - s_l, q_t - q_l

    def sse(w, s, q):
        return jnp.maximum(q - s * s / jnp.maximum(w, 1.0), 0.0)

    n = jnp.maximum(w_t, 1.0)
    cost = (sse(w_l, s_l, q_l) + sse(w_r, s_r, q_r)) / n

    valid = cand_mask[None, :, :] & (w_l > 0) & (w_r > 0)
    if min_child_weight is not None:
        valid = valid & (w_l >= min_child_weight) & (w_r >= min_child_weight)
    if node_mask is not None:
        valid = valid & node_mask[:, :, None]
    if mono_cst is not None:
        # child means via reciprocal-multiply (see the classification
        # docstring: the form every engine reproduces bit-identically)
        v_l_all = s_l * (1.0 / jnp.maximum(w_l, 1.0))
        v_r_all = s_r * (1.0 / jnp.maximum(w_r, 1.0))
        valid = valid & _monotonic_ok(
            v_l_all, v_r_all, mono_cst, mono_lo, mono_hi
        )
    cost = jnp.where(valid, cost, jnp.inf)

    if forced_draw is None:
        best_bin_f = jnp.argmin(cost, axis=2)
    else:
        best_bin_f = _drawn_bins(valid, forced_draw)
    best_cost_f = jnp.take_along_axis(cost, best_bin_f[:, :, None], axis=2)[:, :, 0]
    best_feature = jnp.argmin(best_cost_f, axis=1)
    best_bin = jnp.take_along_axis(best_bin_f, best_feature[:, None], axis=1)[:, 0]
    best_cost = jnp.take_along_axis(best_cost_f, best_feature[:, None], axis=1)[:, 0]

    parent_moments = hist[:, 0, :, :].sum(axis=-1)  # (K, 3)
    parent_n = parent_moments[..., 0]
    parent_impurity = (
        sse(parent_moments[..., 0], parent_moments[..., 1], parent_moments[..., 2])
        / jnp.maximum(parent_n, 1.0)
    )

    occupied = (hist[:, :, 0, :] > 0).sum(axis=2)
    constant = (occupied <= 1).all(axis=1)

    if mono_cst is not None:
        v_left, v_right = _winner_values(
            v_l_all, v_r_all, best_feature, best_bin
        )
    else:
        v_left = v_right = jnp.zeros_like(parent_n)

    return SplitDecision(
        feature=best_feature.astype(jnp.int32),
        bin=best_bin.astype(jnp.int32),
        cost=best_cost,
        impurity=parent_impurity,
        n=parent_n,
        counts=parent_moments,
        constant=constant,
        y_range=jnp.zeros_like(parent_n),
        v_left=v_left,
        v_right=v_right,
        n_left=_winner_gather(w_l, best_feature, best_bin),
    )
