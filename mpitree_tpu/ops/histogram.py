"""Per-(node, feature, bin) statistic histograms — the build's hot op.

This replaces the reference's per-candidate full-matrix rescan
(reference: ``mpitree/tree/decision_tree.py:73-86`` copies the entire feature
matrix twice per candidate threshold) with a single scatter-add pass over the
HBM-resident binned matrix per tree level: every row contributes one count per
feature into its frontier node's histogram, and split gains are then read off
cumulative sums (see ``impurity.py``).

Classification histograms carry per-class counts; regression histograms carry
``(weight, weight*y, weight*y^2)`` moment channels for MSE split evaluation.
Counts/weights are float32 but integer-valued, so sums are exact (< 2**24) and
order-independent — the foundation of the determinism-across-mesh-sizes
invariant the reference relies on for its replicated split search
(reference: ``decision_tree.py:408-419``).

Frontier nodes are addressed by *slot* ``node_id - chunk_lo``: node ids are
assigned level by level in creation order, so a level's frontier is a
contiguous id range and slot arithmetic replaces any remap table. Rows parked
in finished leaves (or padding rows with ``node_id == -1``) fall outside
``[0, n_slots)`` and are masked to weight zero.

Sibling subtraction (LightGBM's halved-histogram trick, Ke et al. 2017):
the two children of a split partition their parent exactly, and every
channel here is a sum, so ``hist(large) = hist(parent) - hist(small)``.
:func:`sibling_accumulate_slots` remaps rows so only SMALL children
accumulate — into a *compacted* ``n_slots // 2`` buffer addressed by pair
index ``slot >> 1`` (children are allocated left/right interleaved, so
siblings share a pair) — which also halves the cross-device ``psum``
payload; :func:`sibling_reconstruct` rebuilds the full frontier histogram
after the reduction from the resident parent histogram. Subtraction is
EXACT whenever the channel sums are: integer-valued f32 counts below
2**24, and the scoped-f64 (g, h) accumulation path. The remap composes
with every kernel tier (scatter, ``pallas_hist``, ``wide_hist``) because
they all address rows purely by slot.

On a 2-D ``(data, feature)`` mesh every kernel here operates on a
feature SLAB: ``x_binned`` arrives as the shard's ``(N_local, F/df)``
column block, so the accumulated histogram is the matching
``(n_slots, F/df, C, B)`` slab and the cross-device ``psum`` payload is
independent of the global feature count. Slot addressing, masking, and
sibling subtraction are all feature-elementwise, so the slab needs no
special casing — the only slab-aware operations are global-feature
re-basing (``parallel.collective.select_global`` merges per-slab
winners) and :func:`slab_local_features`, which routes a GLOBAL winning
feature id back to the one shard owning its column.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def class_histogram(
    x_binned: jax.Array,
    y: jax.Array,
    node_id: jax.Array,
    chunk_lo: jax.Array,
    *,
    n_slots: int,
    n_bins: int,
    n_classes: int,
    sample_weight: jax.Array | None = None,
) -> jax.Array:
    """Scatter-add class counts into a (n_slots, F, n_classes, n_bins) histogram.

    Layout note (TPU tiling): the last two physical dims are padded to
    (8, 128) tiles, so the bin axis — sized to a multiple of 128 in practice —
    must be last and the small class axis second-to-last. A (…, bins, classes)
    layout pads 7 classes to 128 lanes: 18x the HBM.

    Parameters
    ----------
    x_binned : (N, F) int32 — bin ids from :mod:`binning`.
    y : (N,) int32 — class indices in ``[0, n_classes)``.
    node_id : (N,) int32 — current tree-node assignment per row (-1 = padding).
    chunk_lo : () int32 — first node id of the frontier chunk being built.
    sample_weight : (N,) float32, optional — integer-valued weights
        (bootstrap multiplicities for bagging); default 1.
    """
    N, F = x_binned.shape
    slot = node_id - chunk_lo
    valid = (slot >= 0) & (slot < n_slots)
    w = jnp.where(valid, 1.0, 0.0) if sample_weight is None else jnp.where(
        valid, sample_weight, 0.0
    )
    feat = jnp.arange(F, dtype=jnp.int32)[None, :]
    ids = ((slot[:, None] * F + feat) * n_classes + y[:, None]) * n_bins + x_binned
    ids = jnp.where(valid[:, None], ids, 0)
    data = jnp.broadcast_to(w[:, None], (N, F)).astype(jnp.float32)
    hist = jax.ops.segment_sum(
        data.reshape(-1), ids.reshape(-1), num_segments=n_slots * F * n_classes * n_bins
    )
    return hist.reshape(n_slots, F, n_classes, n_bins)


def sibling_accumulate_slots(
    node_id: jax.Array,
    chunk_lo: jax.Array,
    is_small: jax.Array,
    *,
    n_slots: int,
) -> jax.Array:
    """Per-row pseudo node ids for small-child-only accumulation.

    ``is_small`` is (n_slots,) bool — True where the frontier slot holds
    the smaller sibling of its pair (exactly one True per live pair; pad
    slots are True so they read the zero-accumulated compact buffer in
    :func:`sibling_reconstruct`). Rows in small children map to their pair
    index ``slot >> 1`` (valid in a compact ``n_slots // 2``-slot
    histogram with ``chunk_lo == 0``); rows in large children — and rows
    outside the chunk — map to ``-1``, which every histogram kernel
    already masks to weight zero.
    """
    slot = node_id - chunk_lo
    in_chunk = (slot >= 0) & (slot < n_slots)
    small = in_chunk & is_small[jnp.clip(slot, 0, n_slots - 1)]
    return jnp.where(small, slot >> 1, -1)


def sibling_reconstruct(
    small_hist: jax.Array,
    parent_hist: jax.Array,
    parent_slot: jax.Array,
    is_small: jax.Array,
) -> jax.Array:
    """Full frontier histogram from the compact small-child histogram.

    ``small_hist`` is the globally-reduced (n_slots // 2, ...) compact
    buffer from :func:`sibling_accumulate_slots` rows; ``parent_hist`` the
    RESIDENT globally-reduced histogram of the previous level (any slot
    width >= the parent frontier); ``parent_slot`` (n_slots,) int32 maps
    each frontier slot to its parent's slot in ``parent_hist`` (pad slots
    may carry any value — they read their zero pair through the
    ``is_small`` mask). Runs AFTER the psum, so the subtraction is exact
    under the linearity of the allreduce: ``psum(parent) - psum(small) ==
    psum(parent - small)``. dtype follows the inputs (f32, or f64 on the
    scoped-x64 gbdt path).
    """
    S = is_small.shape[0]
    # This runs inside the gbdt path's scoped ``enable_x64``, where
    # (a) fill-mode gathers cannot lower for f64 operands (the fill
    # constant canonicalizes to f32) and (b) ``jnp.clip``'s cached inner
    # jit traces against the wrong scalar width on pre-shard_map wheels —
    # so indices are bounded with plain min/max ufuncs and both gathers
    # run clip-mode (lax clamps in HLO — no python-side jnp.clip, no fill
    # select; the indices are already in bounds: pair < S // 2 and
    # parent_slot is clamped).
    pair = jnp.right_shift(jnp.arange(S, dtype=jnp.int32), jnp.int32(1))
    ps = jnp.minimum(
        jnp.maximum(parent_slot, jnp.int32(0)),
        jnp.int32(parent_hist.shape[0] - 1),
    )
    small = jnp.take(small_hist, pair, axis=0, mode="clip")
    parent = jnp.take(parent_hist, ps, axis=0, mode="clip")
    mask = is_small.reshape((S,) + (1,) * (small.ndim - 1))
    return jnp.where(mask, small, parent - small)


def sibling_reconstruct_pair(
    small_hist: jax.Array,
    parent_hist: jax.Array,
    is_small: jax.Array,
) -> jax.Array:
    """:func:`sibling_reconstruct` specialized to ONE sibling pair.

    The leaf-wise frontier expands a single leaf per step, so its
    reconstruction reads exactly one compact slot against exactly one
    parent row — static slicing + broadcast, no gather at all. That is
    not just cheaper: ``jnp.take``'s cached inner jit mislowers for f64
    operands inside a ``lax.while_loop`` body on pre-shard_map wheels
    (the scoped-x64 gbdt pool), and a gather-free formulation sidesteps
    the whole class. ``small_hist`` is (1, ...) (the compact pair
    buffer), ``parent_hist`` (1, ...) (the expanded leaf's resident
    histogram), ``is_small`` (2,) bool; returns the (2, ...) pair
    histogram. Exactness contract identical to
    :func:`sibling_reconstruct`.
    """
    shape = (2,) + small_hist.shape[1:]
    small = jnp.broadcast_to(small_hist, shape)
    parent = jnp.broadcast_to(parent_hist, shape)
    mask = is_small.reshape((2,) + (1,) * (small_hist.ndim - 1))
    return jnp.where(mask, small, parent - small)


def slab_local_features(
    feature_global: jax.Array,
    feature_axis: str | None,
    n_local: int,
):
    """Route global feature ids onto a feature-sharded slab.

    ``feature_global`` holds GLOBAL winning feature ids (what
    ``select_global`` returns); on a feature mesh each shard owns the
    contiguous column block ``[j * n_local, (j + 1) * n_local)``.
    Returns ``(local, owner)``: the clamped slab-local column to gather
    (safe to read even off-owner — the ``owner`` mask gates the result)
    and the per-element ownership mask. The canonical consumer pattern
    is gather-then-``psum(where(owner, v, 0), feature_axis)`` — the
    owner-broadcast both engines' row reroute uses. On a 1-D mesh
    (``feature_axis=None``) features are device-complete: ``local`` is
    the id itself (clamped non-negative — leaf sentinels stay readable)
    and ``owner`` is ``None`` (everyone owns everything).
    """
    if feature_axis is None:
        return jnp.maximum(feature_global, 0), None
    j = lax.axis_index(feature_axis)
    local = feature_global - j * n_local
    owner = (local >= 0) & (local < n_local)
    return jnp.minimum(jnp.maximum(local, 0), n_local - 1), owner


def _flat_ids(x_binned: jax.Array, valid: jax.Array, slot: jax.Array,
              n_bins: int) -> jax.Array:
    """Flattened (N*F,) (slot, feature, bin) segment ids, masked to 0."""
    F = x_binned.shape[1]
    feat = jnp.arange(F, dtype=jnp.int32)[None, :]
    ids = (slot[:, None] * F + feat) * n_bins + x_binned
    return jnp.where(valid[:, None], ids, 0).reshape(-1)


def _channel_histogram(
    x_binned: jax.Array,
    payloads: tuple,
    ids: jax.Array,
    *,
    n_slots: int,
    n_bins: int,
    acc_dtype=jnp.float32,
) -> jax.Array:
    """Shared weighted-accumulation path: one scalar scatter per channel.

    ``payloads`` is a tuple of (N,) per-row channel values (already masked
    to zero on invalid rows); ``ids`` the :func:`_flat_ids` segment ids;
    the result is (n_slots, F, len(payloads), n_bins). One scalar scatter
    per channel on purpose: a vector-payload scatter of shape (N*F, C)
    would pad its trailing dim to 128 lanes (42x the bandwidth at C=3).
    ``acc_dtype`` is the accumulation dtype — float64 (under a scoped
    ``jax.enable_x64``; all inputs prepared OUTSIDE the scope, see
    grad_hess_histogram) makes non-integer payload sums
    row-partition-invariant to f32 resolution, the mesh-size identity
    story the GBDT path relies on (CPU only; TPUs have no f64 unit).
    """
    N, F = x_binned.shape
    f64 = acc_dtype == jnp.float64
    chans = []
    for payload in payloads:
        data = jnp.broadcast_to(payload[:, None], (N, F)).astype(acc_dtype)
        if f64:
            # f64 constants canonicalize to f32 at lowering time even when
            # the trace ran inside a scoped enable_x64 (the same breakage
            # ops/impurity.py::_cost_sweep_f64 documents for f64 inits) —
            # so neither segment_sum's cached init nor a direct f64 zeros
            # lowers; an f32 zeros CONVERTED to f64 does, and scatter-add
            # into it is the identical sum.
            acc = jnp.zeros(
                n_slots * F * n_bins, dtype=jnp.float32
            ).astype(acc_dtype)
            chans.append(
                acc.at[ids].add(data.reshape(-1)).reshape(n_slots, F, n_bins)
            )
        else:
            chans.append(
                jax.ops.segment_sum(
                    data.reshape(-1), ids, num_segments=n_slots * F * n_bins
                ).reshape(n_slots, F, n_bins)
            )
    return jnp.stack(chans, axis=2)  # (n_slots, F, C, n_bins)


def moment_histogram(
    x_binned: jax.Array,
    y: jax.Array,
    node_id: jax.Array,
    chunk_lo: jax.Array,
    *,
    n_slots: int,
    n_bins: int,
    sample_weight: jax.Array | None = None,
) -> jax.Array:
    """Scatter-add (w, w*y, w*y^2) into a (n_slots, F, 3, n_bins) histogram.

    Used for MSE split evaluation in :class:`DecisionTreeRegressor`.
    """
    slot = node_id - chunk_lo
    valid = (slot >= 0) & (slot < n_slots)
    w = jnp.where(valid, 1.0, 0.0) if sample_weight is None else jnp.where(
        valid, sample_weight, 0.0
    )
    y32 = y.astype(jnp.float32)
    return _channel_histogram(
        x_binned, (w, w * y32, w * y32 * y32),
        _flat_ids(x_binned, valid, slot, n_bins),
        n_slots=n_slots, n_bins=n_bins,
    )


def grad_hess_histogram(
    x_binned: jax.Array,
    g: jax.Array,
    h: jax.Array,
    node_id: jax.Array,
    chunk_lo: jax.Array,
    *,
    n_slots: int,
    n_bins: int,
    acc_dtype=jnp.float32,
) -> jax.Array:
    """Scatter-add (count, g, h) into a (n_slots, F, 3, n_bins) histogram.

    The Newton (GBDT) counterpart of :func:`moment_histogram`, riding the
    same weighted-accumulation path: per candidate bin the split sweep
    needs the left/right gradient total G, hessian total H (XGBoost-style
    Newton gain), and a row count for ``min_samples_leaf``. Rows outside
    the round's subsample carry ``h == 0`` and contribute to no channel —
    including the count. Gradients and hessians are non-integer f32, so
    unlike class counts their sums are NOT order-independent; on CPU the
    caller accumulates in f64 (``acc_dtype``) inside a scoped
    ``jax.enable_x64`` and rounds the psum'd result to f32, which restores
    mesh-size invariance (see ``_channel_histogram``).
    """
    slot = node_id - chunk_lo
    valid = (slot >= 0) & (slot < n_slots) & (h > 0)
    # Masking stays OUTSIDE any enable_x64 scope: a weak python constant
    # inside the scope promotes the f32 operands to f64 at trace time but
    # lowers as f32 — the mixed-dtype lowering failure _cost_sweep_f64's
    # docstring warns about. Only the convert/scatter run scoped.
    cnt = jnp.where(valid, 1.0, 0.0).astype(jnp.float32)
    gm = jnp.where(valid, g, 0.0).astype(jnp.float32)
    hm = jnp.where(valid, h, 0.0).astype(jnp.float32)
    ids = _flat_ids(x_binned, valid, slot, n_bins)
    if acc_dtype == jnp.float64:
        with jax.enable_x64(True):
            return _channel_histogram(
                x_binned, (cnt, gm, hm), ids,
                n_slots=n_slots, n_bins=n_bins, acc_dtype=acc_dtype,
            )
    return _channel_histogram(
        x_binned, (cnt, gm, hm), ids,
        n_slots=n_slots, n_bins=n_bins, acc_dtype=acc_dtype,
    )
