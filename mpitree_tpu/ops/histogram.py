"""Per-(node, feature, bin) statistic histograms — the build's hot op.

This replaces the reference's per-candidate full-matrix rescan
(reference: ``mpitree/tree/decision_tree.py:73-86`` copies the entire feature
matrix twice per candidate threshold) with a single scatter-add pass over the
HBM-resident binned matrix per tree level: every row contributes one count per
feature into its frontier node's histogram, and split gains are then read off
cumulative sums (see ``impurity.py``).

Classification histograms carry per-class counts; regression histograms carry
``(weight, weight*y, weight*y^2)`` moment channels for MSE split evaluation.
Counts/weights are float32 but integer-valued, so sums are exact (< 2**24) and
order-independent — the foundation of the determinism-across-mesh-sizes
invariant the reference relies on for its replicated split search
(reference: ``decision_tree.py:408-419``).

Frontier nodes are addressed by *slot* ``node_id - chunk_lo``: node ids are
assigned level by level in creation order, so a level's frontier is a
contiguous id range and slot arithmetic replaces any remap table. Rows parked
in finished leaves (or padding rows with ``node_id == -1``) fall outside
``[0, n_slots)`` and are masked to weight zero.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def class_histogram(
    x_binned: jax.Array,
    y: jax.Array,
    node_id: jax.Array,
    chunk_lo: jax.Array,
    *,
    n_slots: int,
    n_bins: int,
    n_classes: int,
    sample_weight: jax.Array | None = None,
) -> jax.Array:
    """Scatter-add class counts into a (n_slots, F, n_classes, n_bins) histogram.

    Layout note (TPU tiling): the last two physical dims are padded to
    (8, 128) tiles, so the bin axis — sized to a multiple of 128 in practice —
    must be last and the small class axis second-to-last. A (…, bins, classes)
    layout pads 7 classes to 128 lanes: 18x the HBM.

    Parameters
    ----------
    x_binned : (N, F) int32 — bin ids from :mod:`binning`.
    y : (N,) int32 — class indices in ``[0, n_classes)``.
    node_id : (N,) int32 — current tree-node assignment per row (-1 = padding).
    chunk_lo : () int32 — first node id of the frontier chunk being built.
    sample_weight : (N,) float32, optional — integer-valued weights
        (bootstrap multiplicities for bagging); default 1.
    """
    N, F = x_binned.shape
    slot = node_id - chunk_lo
    valid = (slot >= 0) & (slot < n_slots)
    w = jnp.where(valid, 1.0, 0.0) if sample_weight is None else jnp.where(
        valid, sample_weight, 0.0
    )
    feat = jnp.arange(F, dtype=jnp.int32)[None, :]
    ids = ((slot[:, None] * F + feat) * n_classes + y[:, None]) * n_bins + x_binned
    ids = jnp.where(valid[:, None], ids, 0)
    data = jnp.broadcast_to(w[:, None], (N, F)).astype(jnp.float32)
    hist = jax.ops.segment_sum(
        data.reshape(-1), ids.reshape(-1), num_segments=n_slots * F * n_classes * n_bins
    )
    return hist.reshape(n_slots, F, n_classes, n_bins)


def moment_histogram(
    x_binned: jax.Array,
    y: jax.Array,
    node_id: jax.Array,
    chunk_lo: jax.Array,
    *,
    n_slots: int,
    n_bins: int,
    sample_weight: jax.Array | None = None,
) -> jax.Array:
    """Scatter-add (w, w*y, w*y^2) into a (n_slots, F, 3, n_bins) histogram.

    Used for MSE split evaluation in :class:`DecisionTreeRegressor`. One
    scalar scatter per moment channel: a vector-payload scatter of shape
    (N*F, 3) would pad its trailing dim to 128 lanes (42x the bandwidth).
    """
    N, F = x_binned.shape
    slot = node_id - chunk_lo
    valid = (slot >= 0) & (slot < n_slots)
    w = jnp.where(valid, 1.0, 0.0) if sample_weight is None else jnp.where(
        valid, sample_weight, 0.0
    )
    feat = jnp.arange(F, dtype=jnp.int32)[None, :]
    ids = (slot[:, None] * F + feat) * n_bins + x_binned
    ids = jnp.where(valid[:, None], ids, 0).reshape(-1)
    y32 = y.astype(jnp.float32)
    chans = []
    for payload in (w, w * y32, w * y32 * y32):
        data = jnp.broadcast_to(payload[:, None], (N, F)).astype(jnp.float32)
        chans.append(
            jax.ops.segment_sum(
                data.reshape(-1), ids, num_segments=n_slots * F * n_bins
            ).reshape(n_slots, F, n_bins)
        )
    return jnp.stack(chans, axis=2)  # (n_slots, F, 3, n_bins)
