"""Feature binning: map raw feature columns to integer bin ids.

The reference evaluates every unique feature value as a split candidate with
``x <= t`` semantics (reference: ``mpitree/tree/decision_tree.py:73,77``). We
reproduce that exactly in *exact* mode, and add a *quantile* mode for
covtype-scale data where the candidate set is capped at ``max_bins`` per
feature (accuracy parity with sklearn rather than tree-identity).

Representation (per feature ``f``):

- ``thresholds[f, 0:n_cand[f]]`` — strictly increasing split values. Candidate
  ``b`` is the split ``x <= thresholds[f, b]``.
- ``bin(x) = searchsorted(thresholds[f], x, side="left")`` — the first
  candidate index whose threshold is ``>= x``; values above every threshold
  land in the terminal bucket ``n_cand[f]``. This gives the exact equivalence
  ``x <= thresholds[f, b]  <=>  bin(x) <= b``, so the on-device build never
  touches raw values after binning.
- In exact mode ``thresholds[f] = unique(col)[:-1]``: the top unique value is
  excluded as a candidate because its right partition is empty, and the
  reference can never select it — every candidate's weighted-child cost is
  bounded by the parent impurity and per-feature ties break toward the
  *lowest* threshold (reference ``np.argmin`` at ``decision_tree.py:90``).

Binning is host-side numpy preprocessing (one pass); the binned ``int32``
matrix is then device_put once and stays HBM-resident for the whole build.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class BinnedData:
    """Host-side product of preprocessing; consumed by the builder.

    Attributes
    ----------
    x_binned : (n_samples, n_features) int32
        Bin index per value; ``x <= thresholds[f, b] <=> x_binned[:, f] <= b``.
    thresholds : (n_features, n_bins - 1) float32
        Split value per candidate bin, padded with ``+inf`` past ``n_cand[f]``.
    n_cand : (n_features,) int32
        Number of valid split candidates per feature (0 for constant features).
    n_bins : int
        Bucket count ``B`` (max over features of ``n_cand[f] + 1``); bin ids
        live in ``[0, B)``.
    quantized : bool
        True when at least one feature's candidate set was capped by quantile
        binning (i.e. the exact unique-value candidates did not fit
        ``max_bins``). Deep-tail candidate starvation — the condition the
        hybrid refine exists for — is only possible when this is set.
    """

    x_binned: np.ndarray
    thresholds: np.ndarray
    n_cand: np.ndarray
    n_bins: int
    quantized: bool = False

    @property
    def n_samples(self) -> int:
        return self.x_binned.shape[0]

    @property
    def n_features(self) -> int:
        return self.x_binned.shape[1]

    def candidate_mask(self) -> np.ndarray:
        """(n_features, n_bins) bool — True where bin ``b`` is a valid candidate."""
        B = self.n_bins
        return np.arange(B)[None, :] < self.n_cand[:, None]


def _exact_edges(col: np.ndarray) -> np.ndarray:
    uniq = np.unique(col)
    return uniq[:-1]


def _quantile_edges(col: np.ndarray, max_bins: int) -> np.ndarray:
    # Edges are actual data values (method="lower") so predict-time `x <= t`
    # comparisons agree bit-for-bit with the training partition.
    return _quantile_edges_sorted(np.sort(col), max_bins)


def _quantile_edges_sorted(col_sorted: np.ndarray, max_bins: int) -> np.ndarray:
    # np.quantile(col, q, method="lower") == sorted[floor((n-1)*q)] — taking
    # the indices directly lets one sort serve both the uniqueness probe and
    # the edges (np.unique + np.quantile would each sort the column).
    qs = np.arange(1, max_bins, dtype=np.float64) / max_bins
    idx = np.floor((len(col_sorted) - 1) * qs).astype(np.int64)
    return np.unique(col_sorted[idx])


def bin_dataset(
    X: np.ndarray, *, max_bins: int = 256, binning: str = "auto"
) -> BinnedData:
    """Bin a (n_samples, n_features) float matrix.

    Parameters
    ----------
    max_bins : int
        Bucket cap per feature (quantile mode only).
    binning : {"auto", "exact", "quantile"}
        "exact" keeps every unique value as a candidate (reference parity);
        "quantile" caps candidates at ``max_bins - 1`` quantile edges;
        "auto" uses exact per-feature while the unique count fits in
        ``max_bins``, quantile otherwise.
    """
    if binning not in ("auto", "exact", "quantile"):
        raise ValueError(f"unknown binning mode: {binning!r}")
    X = np.ascontiguousarray(X, dtype=np.float32)
    n_samples, n_features = X.shape
    # One transpose up front: every per-feature op below (sort, unique
    # probe, searchsorted) runs on a contiguous column instead of a
    # 4*n_features-byte-strided view — strided reads/writes dominated this
    # function's profile at covtype scale, not the sorts.
    Xt = np.ascontiguousarray(X.T)

    per_feature_edges: list[np.ndarray] = []
    quantized = False
    for f in range(n_features):
        col = Xt[f]
        if binning == "exact":
            edges = _exact_edges(col)
        elif binning == "quantile":
            edges = _quantile_edges(col, max_bins)
            quantized = True
        else:  # auto
            # One sort answers both questions (np.unique + np.quantile
            # would each sort the full column; numpy's vectorized f32 sort
            # makes the sort itself nearly free — np.partition is slower).
            col_sorted = np.sort(col)
            n = len(col_sorted)
            new_val = np.empty(n, bool)
            if n:
                new_val[0] = True
                np.not_equal(
                    col_sorted[1:], col_sorted[:-1], out=new_val[1:]
                )
                # NaN != NaN would count every NaN as distinct; collapse
                # the trailing NaN run to one, like np.unique (NaNs sort
                # past +inf, so the run is the suffix). Estimator
                # entrypoints reject NaN, but bin_dataset is also a direct
                # API and the exact mode's np.unique already collapses.
                nan_start = np.searchsorted(col_sorted, np.inf, side="right")
                if nan_start < n - 1:
                    new_val[nan_start + 1:] = False
            if int(new_val.sum()) <= max_bins:
                edges = col_sorted[new_val][:-1]
            else:
                edges = _quantile_edges_sorted(col_sorted, max_bins)
                quantized = True
        per_feature_edges.append(edges.astype(np.float32))

    n_cand = np.array([len(e) for e in per_feature_edges], dtype=np.int32)
    n_bins = int(n_cand.max(initial=0)) + 1

    thresholds = np.full((n_features, max(n_bins - 1, 1)), np.inf, dtype=np.float32)
    xbt = np.empty((n_features, n_samples), dtype=np.int32)
    for f, edges in enumerate(per_feature_edges):
        thresholds[f, : len(edges)] = edges
        xbt[f] = np.searchsorted(edges, Xt[f], side="left")
    x_binned = np.ascontiguousarray(xbt.T)

    return BinnedData(
        x_binned=x_binned, thresholds=thresholds, n_cand=n_cand,
        n_bins=n_bins, quantized=quantized,
    )
