"""Feature binning: map raw feature columns to integer bin ids.

The reference evaluates every unique feature value as a split candidate with
``x <= t`` semantics (reference: ``mpitree/tree/decision_tree.py:73,77``). We
reproduce that exactly in *exact* mode, and add a *quantile* mode for
covtype-scale data where the candidate set is capped at ``max_bins`` per
feature (accuracy parity with sklearn rather than tree-identity).

Representation (per feature ``f``):

- ``thresholds[f, 0:n_cand[f]]`` — strictly increasing split values. Candidate
  ``b`` is the split ``x <= thresholds[f, b]``.
- ``bin(x) = searchsorted(thresholds[f], x, side="left")`` — the first
  candidate index whose threshold is ``>= x``; values above every threshold
  land in the terminal bucket ``n_cand[f]``. This gives the exact equivalence
  ``x <= thresholds[f, b]  <=>  bin(x) <= b``, so the on-device build never
  touches raw values after binning.
- In exact mode ``thresholds[f] = unique(col)[:-1]``: the top unique value is
  excluded as a candidate because its right partition is empty, and the
  reference can never select it — every candidate's weighted-child cost is
  bounded by the parent impurity and per-feature ties break toward the
  *lowest* threshold (reference ``np.argmin`` at ``decision_tree.py:90``).

Binning is host-side numpy preprocessing (one pass) for the host tier; the
device engines can instead bin ON the accelerator (``bin_dataset_device``):
the raw f32 matrix crosses the wire once (the same byte count as the binned
int32 it replaces) and the sort/quantile/compare work runs where the build
runs. Both paths produce bit-identical ``BinnedData`` — edges are *selected
data values* (gathers of sorted columns), never arithmetic on them, so
device parity is by construction; the engine-identity contract
(device tree == host tree) depends on this and
``tests/test_binning_device.py`` pins it.
"""

from __future__ import annotations

import dataclasses

import numpy as np
from mpitree_tpu.config import knobs


@dataclasses.dataclass(frozen=True)
class BinnedData:
    """Host-side product of preprocessing; consumed by the builder.

    Attributes
    ----------
    x_binned : (n_samples, n_features) int32
        Bin index per value; ``x <= thresholds[f, b] <=> x_binned[:, f] <= b``.
    thresholds : (n_features, n_bins - 1) float32
        Split value per candidate bin, padded with ``+inf`` past ``n_cand[f]``.
    n_cand : (n_features,) int32
        Number of valid split candidates per feature (0 for constant features).
    n_bins : int
        Bucket count ``B`` (max over features of ``n_cand[f] + 1``); bin ids
        live in ``[0, B)``.
    quantized : bool
        True when at least one feature's candidate set was capped by quantile
        binning (i.e. the exact unique-value candidates did not fit
        ``max_bins``). Deep-tail candidate starvation — the condition the
        hybrid refine exists for — is only possible when this is set.
    """

    x_binned: np.ndarray
    thresholds: np.ndarray
    n_cand: np.ndarray
    n_bins: int
    quantized: bool = False

    @property
    def n_samples(self) -> int:
        return self.x_binned.shape[0]

    @property
    def n_features(self) -> int:
        return self.x_binned.shape[1]

    def candidate_mask(self) -> np.ndarray:
        """(n_features, n_bins) bool — True where bin ``b`` is a valid candidate."""
        B = self.n_bins
        return np.arange(B)[None, :] < self.n_cand[:, None]


@dataclasses.dataclass(frozen=True)
class StreamedBinnedData(BinnedData):
    """BinnedData whose matrix was assembled chunk-at-a-time on device.

    ``x_binned`` is a GLOBAL device array, already padded to the mesh's
    axis widths (rows to the data-axis width, features to the
    feature-axis width — padding rows/columns are zeros, made inert by
    the ``node_id=-1``/zero-candidate contracts) and already placed per
    ``parallel/partition.py``'s ``x_binned`` rule. The raw feature
    matrix never existed on any host (ISSUE 15): ``mpitree_tpu.ingest``
    binned each host chunk against sketch-derived edges and
    ``device_put`` it straight onto its mesh slot.

    ``n_rows`` is the REAL row count (``len(y)``); the ``n_samples`` /
    ``n_features`` properties report real extents so consumers that
    size against the dataset (weight totals, ledger pricing, padding
    arithmetic in ``mesh.shard_build_inputs``) never see the padding.
    """

    n_rows: int = 0
    # The chunk size the stream ACTUALLY used — threaded into the build
    # ledger's streamed host pricing (``plan_fit(streamed_chunk_rows=)``)
    # so the recorded bound matches the run, not the default budget.
    chunk_rows: int = 0

    @property
    def n_samples(self) -> int:
        return self.n_rows

    @property
    def n_features(self) -> int:
        return self.thresholds.shape[0]


def _exact_edges(col: np.ndarray) -> np.ndarray:
    uniq = np.unique(col)
    return uniq[:-1]


def _quantile_edges(col: np.ndarray, max_bins: int) -> np.ndarray:
    # Edges are actual data values (method="lower") so predict-time `x <= t`
    # comparisons agree bit-for-bit with the training partition.
    return _quantile_edges_sorted(np.sort(col), max_bins)


def _quantile_indices(n: int, max_bins: int) -> np.ndarray:
    """Sorted-column gather indices for the quantile edges — f64 on HOST.

    Parity-critical and therefore the ONE copy: both the host path and
    ``bin_dataset_device`` gather at exactly these indices (f32 products of
    ``(n-1)*q`` on device would round differently and break the
    bit-identity contract between the two paths).
    """
    qs = np.arange(1, max_bins, dtype=np.float64) / max_bins
    return np.floor((n - 1) * qs).astype(np.int64)


def _quantile_edges_sorted(col_sorted: np.ndarray, max_bins: int) -> np.ndarray:
    # np.quantile(col, q, method="lower") == sorted[floor((n-1)*q)] — taking
    # the indices directly lets one sort serve both the uniqueness probe and
    # the edges (np.unique + np.quantile would each sort the column).
    return np.unique(col_sorted[_quantile_indices(len(col_sorted), max_bins)])


def pack_edges(
    per_feature_edges: list, *, quantized: bool = False
) -> tuple:
    """Pack per-feature edge arrays into the ``BinnedData`` threshold table.

    The ONE copy of the edge→(thresholds, n_cand, n_bins) packaging both
    :func:`bin_dataset` and the streaming ingest tier
    (``mpitree_tpu.ingest``) ride: edges computed from a full column and
    edges computed from a merged quantile sketch package identically, so
    the two paths can only diverge in edge SELECTION (which the sketch
    makes bit-identical on shared sizes — see ``ingest/sketch.py``).
    Returns ``(thresholds, n_cand, n_bins, quantized)``.
    """
    n_features = len(per_feature_edges)
    n_cand = np.array([len(e) for e in per_feature_edges], dtype=np.int32)
    n_bins = int(n_cand.max(initial=0)) + 1
    thresholds = np.full(
        (n_features, max(n_bins - 1, 1)), np.inf, dtype=np.float32
    )
    for f, edges in enumerate(per_feature_edges):
        thresholds[f, : len(edges)] = edges
    return thresholds, n_cand, n_bins, quantized


def bin_with_thresholds(
    X: np.ndarray, thresholds: np.ndarray, n_cand: np.ndarray
) -> np.ndarray:
    """Bin a raw (N, F) f32 chunk against an existing threshold table.

    Identical arithmetic to :func:`bin_dataset`'s binning pass
    (``searchsorted(edges, col, side="left")`` per feature), factored
    out so the streaming ingest tier bins chunk-at-a-time against
    sketch-derived edges with bit-identical ids.
    """
    X = np.ascontiguousarray(X, dtype=np.float32)
    n_samples, n_features = X.shape
    Xt = np.ascontiguousarray(X.T)
    xbt = np.empty((n_features, n_samples), dtype=np.int32)
    for f in range(n_features):
        xbt[f] = np.searchsorted(
            thresholds[f, : n_cand[f]], Xt[f], side="left"
        )
    return np.ascontiguousarray(xbt.T)


def bin_dataset(
    X: np.ndarray, *, max_bins: int = 256, binning: str = "auto"
) -> BinnedData:
    """Bin a (n_samples, n_features) float matrix.

    Parameters
    ----------
    max_bins : int
        Bucket cap per feature (quantile mode only).
    binning : {"auto", "exact", "quantile"}
        "exact" keeps every unique value as a candidate (reference parity);
        "quantile" caps candidates at ``max_bins - 1`` quantile edges;
        "auto" uses exact per-feature while the unique count fits in
        ``max_bins``, quantile otherwise.
    """
    if binning not in ("auto", "exact", "quantile"):
        raise ValueError(f"unknown binning mode: {binning!r}")
    X = np.ascontiguousarray(X, dtype=np.float32)
    n_samples, n_features = X.shape
    # One transpose up front: every per-feature op below (sort, unique
    # probe, searchsorted) runs on a contiguous column instead of a
    # 4*n_features-byte-strided view — strided reads/writes dominated this
    # function's profile at covtype scale, not the sorts.
    Xt = np.ascontiguousarray(X.T)

    per_feature_edges: list[np.ndarray] = []
    quantized = False
    for f in range(n_features):
        col = Xt[f]
        if binning == "exact":
            edges = _exact_edges(col)
        elif binning == "quantile":
            edges = _quantile_edges(col, max_bins)
            quantized = True
        else:  # auto
            # One sort answers both questions (np.unique + np.quantile
            # would each sort the full column; numpy's vectorized f32 sort
            # makes the sort itself nearly free — np.partition is slower).
            col_sorted = np.sort(col)
            n = len(col_sorted)
            new_val = np.empty(n, bool)
            if n:
                new_val[0] = True
                np.not_equal(
                    col_sorted[1:], col_sorted[:-1], out=new_val[1:]
                )
                # NaN != NaN would count every NaN as distinct; collapse
                # the trailing NaN run to one, like np.unique (NaNs sort
                # past +inf, so the run is the suffix). Estimator
                # entrypoints reject NaN, but bin_dataset is also a direct
                # API and the exact mode's np.unique already collapses.
                nan_start = np.searchsorted(col_sorted, np.inf, side="right")
                if nan_start < n - 1:
                    new_val[nan_start + 1:] = False
            if int(new_val.sum()) <= max_bins:
                edges = col_sorted[new_val][:-1]
            else:
                edges = _quantile_edges_sorted(col_sorted, max_bins)
                quantized = True
        per_feature_edges.append(edges.astype(np.float32))

    thresholds, n_cand, n_bins, quantized = pack_edges(
        per_feature_edges, quantized=quantized
    )
    xbt = np.empty((n_features, n_samples), dtype=np.int32)
    for f, edges in enumerate(per_feature_edges):
        xbt[f] = np.searchsorted(edges, Xt[f], side="left")
    x_binned = np.ascontiguousarray(xbt.T)

    return BinnedData(
        x_binned=x_binned, thresholds=thresholds, n_cand=n_cand,
        n_bins=n_bins, quantized=quantized,
    )


# --------------------------------------------------------------------------
# Device-side binning (the TPU path's preprocessing, HBM-resident output)
# --------------------------------------------------------------------------

def _device_bin_kernel(Xt, qidx, max_bins, force_quantile=False):
    """(F, N) f32 -> (xbt (F, N) int32, thresholds (F, max_bins-1), n_cand).

    The jnp twin of ``bin_dataset``'s "auto" mode, static-shaped for jit:

    - per-feature sort; uniqueness mask; unique count
    - exact edges (unique values minus the top one) compacted into a fixed
      (F, max_bins-1) buffer by GATHERS: the i-th unique sits at the first
      sorted position whose uniqueness-rank reaches i+1 (binary search over
      the monotone rank vector — a scatter compaction here would be another
      N*F-update scalar pass, the exact cost device binning exists to avoid)
    - quantile edges = gathers of the sorted column at host-precomputed
      ``qidx`` (f64 index arithmetic happens on host — f32 products of
      ``(n-1)*q`` would round differently and break host parity), deduped
      by the same rank-gather trick
    - per-feature select: exact while the unique count fits ``max_bins``
    - bin ids by candidate counting: ``xb = sum_e(thr[f, e] < x)`` —
      identical to ``searchsorted(edges, x, side="left")`` with the +inf
      padding inert, and a pure broadcast-compare-reduce on device (no
      per-row scalar binary-search gathers)

    Known non-contract: a column holding both -0.0 and 0.0 may yield a
    bitwise -0.0/+0.0 threshold difference vs the host path (equal-value
    sort order is algorithm-specific); every predicate (``x <= t``) and
    bin id is unaffected.
    """
    import jax.numpy as jnp

    F, N = Xt.shape
    Q = max_bins - 1
    import jax

    srt = jnp.sort(Xt, axis=1)
    new_val = jnp.concatenate(
        [jnp.ones((F, 1), bool), srt[:, 1:] != srt[:, :-1]], axis=1
    )
    n_uniq = new_val.sum(axis=1).astype(jnp.int32)

    def compact(vals, mask, keep_n):
        """Gather the first ``Q`` mask-marked values of each ascending row.

        ``rank[n] = #marked positions <= n`` is monotone, so the i-th
        marked value sits at the first position where rank reaches i+1 —
        one vmapped binary search instead of an N-wide scatter. Positions
        at/after ``keep_n`` pad with +inf (inert for candidate counting).
        """
        M = vals.shape[1]
        rank = jnp.cumsum(mask, axis=1, dtype=jnp.int32)
        want = jnp.arange(1, Q + 1, dtype=jnp.int32)
        tgt = jax.vmap(
            lambda r: jnp.searchsorted(r, want, side="left")
        )(rank)
        got = jnp.take_along_axis(
            vals, jnp.minimum(tgt, M - 1), axis=1
        ).astype(jnp.float32)
        pos = jnp.arange(Q, dtype=jnp.int32)[None, :]
        return jnp.where(pos < keep_n[:, None], got, jnp.inf)

    # the top unique value is never a candidate (reference
    # decision_tree.py:73,90 semantics, see module docstring): keep n-1
    exact_thr = compact(srt, new_val, n_uniq - 1)

    qcand = jnp.take_along_axis(srt, qidx[None, :].repeat(F, 0), axis=1)
    new_q = jnp.concatenate(
        [jnp.ones((F, 1), bool), qcand[:, 1:] != qcand[:, :-1]], axis=1
    )
    n_q = new_q.sum(axis=1).astype(jnp.int32)
    # quantile edges keep ALL deduped values (host np.unique of the
    # gathered candidates keeps every one)
    quant_thr = compact(qcand, new_q, n_q)

    use_exact = (
        jnp.zeros_like(n_uniq, bool) if force_quantile
        else n_uniq <= max_bins
    )
    thresholds = jnp.where(use_exact[:, None], exact_thr, quant_thr)
    n_cand = jnp.where(use_exact, n_uniq - 1, n_q)
    xbt = (thresholds[:, :, None] < Xt[:, None, :]).sum(
        axis=1, dtype=jnp.int32
    )
    return xbt, thresholds, n_cand, use_exact


def bin_dataset_device(
    X: np.ndarray, *, max_bins: int = 256, binning: str = "auto",
    assume_finite: bool = False,
) -> BinnedData:
    """``bin_dataset`` computed on the default device; bit-identical output.

    ``x_binned`` comes back as a DEVICE-resident (N, F) int32 array (the
    shard step re-places it under the mesh sharding without a host round
    trip); ``thresholds``/``n_cand`` are pulled to host (a few KB) where
    predict/export need them. Only "auto" and "quantile" modes exist here:
    "exact" mode's candidate count is data-dependent (unbounded), which has
    no static shape — callers keep host binning for it. NaN input (which
    would corrupt the sort-based dedup) routes to the host path, which
    collapses NaN runs — the bit-identity contract holds either way.
    """
    if binning not in ("auto", "quantile"):
        raise ValueError(
            "bin_dataset_device supports binning='auto'|'quantile' "
            f"(got {binning!r}); exact mode is host-only"
        )
    import jax
    import jax.numpy as jnp

    X = np.ascontiguousarray(X, dtype=np.float32)
    n_samples, n_features = X.shape
    if not assume_finite and np.isnan(X).any():
        # NaN != NaN breaks the device kernel's sort-based dedup; the host
        # path collapses NaN runs, so falling back keeps the documented
        # bit-identity contract for direct callers. Estimator entrypoints
        # already validate finiteness and skip this O(N*F) host scan via
        # assume_finite=True (bin_for_engine).
        return bin_dataset(X, max_bins=max_bins, binning=binning)
    if max_bins < 2 or n_samples < 1:
        # Degenerate: zero candidates everywhere (max_bins=1), or an empty
        # row axis whose quantile gather indices would be -1. The device
        # kernel's dedup seeds a first-occurrence column that would
        # miscount a 0-wide candidate set; host handles both (and is
        # bit-identical by definition of "no work").
        return bin_dataset(X, max_bins=max_bins, binning=binning)
    # Host f64 index arithmetic — the ONE shared copy (_quantile_indices).
    qidx = jnp.asarray(
        _quantile_indices(n_samples, max_bins).astype(np.int32)
    )
    kernel = jax.jit(
        _device_bin_kernel, static_argnames=("max_bins", "force_quantile")
    )
    xbt, thr_d, n_cand_d, use_exact_d = kernel(
        jnp.asarray(X.T), qidx, max_bins=max_bins,
        force_quantile=binning == "quantile",
    )
    thresholds = np.asarray(thr_d)
    n_cand = np.asarray(n_cand_d)
    use_exact = np.asarray(use_exact_d)
    n_bins = int(n_cand.max(initial=0)) + 1
    quantized = bool((~use_exact).any())
    # Trim the threshold pad to the realized bin width, like the host path.
    thresholds = np.ascontiguousarray(thresholds[:, : max(n_bins - 1, 1)])
    return BinnedData(
        x_binned=xbt.T, thresholds=thresholds, n_cand=n_cand,
        n_bins=n_bins, quantized=quantized,
    )


def bin_for_engine(
    X: np.ndarray, *, max_bins: int, binning: str, device: bool,
    backend: str | None = None,
) -> BinnedData:
    """Route binning to where the build will run (the one routing point).

    ``device=True`` (a device engine will consume the result) bins on the
    accelerator when that accelerator is a real TPU — measured on XLA-CPU
    the sort/compare-reduce program is ~26x slower than the numpy path
    (100k x 54: 25.9s vs 1.0s), so the CPU backend (tests, bench fallback)
    keeps host binning. "exact" mode is host-only (dynamic candidate
    count). ``MPITREE_TPU_DEVICE_BIN=1`` forces the device path whenever a
    device engine will consume the result — it has no effect on host-tier
    fits (``device=False``), which have no device build to feed; ``=0``
    disables device binning everywhere.
    Any device FAILURE falls back to host binning — the elastic principle:
    a flaky accelerator costs wall-clock, never the fit (bit-identical
    outputs) — but a device HANG blocks here exactly as the subsequent
    build would.
    """

    flag = knobs.raw("MPITREE_TPU_DEVICE_BIN")
    if device and binning != "exact" and flag != "0":
        if flag == "1":
            # Forced: raise on failure — the identity tests ride this flag,
            # and a silent host fallback would make them compare
            # host-vs-host and pass vacuously.
            return bin_dataset_device(
                X, max_bins=max_bins, binning=binning, assume_finite=True
            )
        if backend == "tpu":
            on_tpu = True
        elif backend in ("cpu", "host"):
            on_tpu = False
        else:  # backend auto: ask jax (blocks on a hung tunnel, like the build)
            import jax

            on_tpu = jax.default_backend() in ("tpu", "axon")
        if on_tpu:
            try:
                return bin_dataset_device(
                    X, max_bins=max_bins, binning=binning,
                    assume_finite=True,
                )
            except Exception as e:  # noqa: BLE001
                # Same policy as device_failover (resilience.retry):
                # transport failures are survivable (host output is
                # bit-identical), everything else is a real bug the caller
                # must see.
                import warnings

                from mpitree_tpu.resilience import is_device_failure

                if not is_device_failure(e):
                    raise
                warnings.warn(
                    f"device binning failed ({type(e).__name__}: {e}); "
                    f"falling back to host binning",
                    stacklevel=2,
                )
    return bin_dataset(X, max_bins=max_bins, binning=binning)


def ensure_host_binned(
    binned: BinnedData, X: np.ndarray, *, max_bins: int, binning: str
) -> BinnedData:
    """Host-resident BinnedData for the elastic failover path.

    A device-binned fit whose accelerator just died cannot pull
    ``x_binned`` back; re-binning on host is safe because both paths are
    bit-identical (tests/test_binning_device.py).
    """
    if isinstance(binned.x_binned, np.ndarray):
        return binned
    return bin_dataset(X, max_bins=max_bins, binning=binning)
