"""JAX API compatibility shims — imported for side effect by the package root.

This codebase targets the modern top-level spellings ``jax.shard_map`` and
``jax.enable_x64``; older jax wheels (e.g. the 0.4.x line some containers
bake) still carry both only under ``jax.experimental`` — with
``shard_map``'s replication-check kwarg spelled ``check_rep`` instead of
``check_vma``. Aliasing them here (a no-op on newer jax) lets one source
tree run on both, instead of every device-engine entry point dying with
``AttributeError`` on the older wheel.
"""

from __future__ import annotations

import jax

# True on wheels predating the top-level aliases. Beyond steering the shims
# below, this gates the scoped-f64 exact-ties cost sweep off
# (core/builder.resolve_exact_ties): those wheels canonicalize inlined f64
# scalar constants back to f32 at lowering, so the sweep's weak-constant
# arithmetic cannot lower — the device/host tie seam stays open there,
# exactly the pre-closure behavior. (The gbdt f64 histogram closure is
# unaffected: it uses only converted operands and lifted array constants.)
LEGACY_JAX = not hasattr(jax, "shard_map")

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    def _shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=True,
                          **kwargs):
        # check_rep stays False regardless of check_vma: the old
        # replication checker has no rule for lax.while_loop (it raises
        # NotImplementedError on the fused builders), while the modern
        # vma checker — the validation this codebase actually targets —
        # runs natively wherever the new API exists and this shim doesn't.
        del check_vma
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False, **kwargs,
        )

    jax.shard_map = _shard_map_compat

if not hasattr(jax, "enable_x64"):
    from jax.experimental import enable_x64 as _enable_x64

    jax.enable_x64 = _enable_x64
