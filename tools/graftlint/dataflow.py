"""Interprocedural traced-value dataflow over the Project call graph.

The PR-1 rules tracked tracedness with a single straight-line pass per
function (``astutil.propagate_traced``), which meant a traced value escaped
the moment it crossed a function boundary: into a ``lax.cond`` branch
closure, out of a helper's ``return``, or into a lambda body. This engine
replaces that pass with a flow-insensitive fixpoint over every function in
the lint set:

Seeds
    - device-function parameters (``FuncInfo.traced_params``: everything
      not statically-known or heuristically static),
    - results of ``jnp.*`` / ``lax.*`` / ``jax.random.*`` calls — any
      value a JAX primitive produces is an array under trace,
    - parameters of functions passed to ``lax.cond`` / ``while_loop`` /
      ``scan`` / ``fori_loop`` / ``switch`` / ``map`` (branch operands and
      loop carries are traced by construction, whatever their names
      suggest).

Propagation
    - assignments (including tuple-unpacking, element-wise when both sides
      are literal tuples, ``AugAssign``, walrus),
    - ``for`` targets of a traced iterable and comprehension variables,
    - ``return``: a function whose return expression is traced marks
      ``returns_traced``; call sites then taint their targets —
      the interprocedural edge,
    - closures: a free name in a nested def or lambda resolves through the
      lexical parent chain; if the binding scope holds it traced, the
      inner function does too — the ``lax.cond`` branch-closure edge.

Laundering: ``x.shape`` / ``len(x)`` / ``x.ndim`` subtrees never carry
tracedness out (``astutil.strip_static_contexts``), so the pervasive
``N, F = xb.shape`` idiom stays static.

Flow-insensitive on purpose: statement order and branch structure are
ignored, so a name is traced if ANY binding in the function taints it.
That over-approximates per-path truth in the one direction rules can
tolerate — a spurious traced mark surfaces as a finding a human reviews,
never as a silently skipped check.

Call arguments propagate per POSITION: a call of a project function with
a traced value in argument slot ``i`` (or keyword ``k=``) taints the
callee's matching parameter — the edge that lets tracedness enter
non-device helpers the way it enters device functions through their
seeded params. ``*args``/``**kwargs`` at either end conservatively taint
nothing (a starred call site cannot be matched to slots statically).
"""

from __future__ import annotations

import ast

from tools.graftlint import astutil

# Canonical-name prefixes whose call results are traced arrays. jax.jit /
# jax.vmap / shard_map results are CALLABLES, not arrays — none of these
# prefixes cover the wrapper namespaces.
_TRACED_PREFIXES = (
    "jax.numpy.",
    "jax.lax.",
    "jax.random.",
    "jax.nn.",
    "jax.scipy.",
    "jax.ops.",
)
# Exceptions inside those namespaces whose results are Python values.
_UNTRACED_CALLS = frozenset({
    "jax.numpy.shape", "jax.numpy.ndim", "jax.numpy.size",
    "jax.numpy.result_type", "jax.numpy.dtype", "jax.numpy.iinfo",
    "jax.numpy.finfo",
})
# Control-flow combinators: every function-valued argument's parameters are
# traced (operands, carries, loop indices), regardless of name heuristics.
_CONTROL_FLOW = frozenset({
    "jax.lax.cond", "jax.lax.switch", "jax.lax.while_loop",
    "jax.lax.scan", "jax.lax.fori_loop", "jax.lax.map",
    "jax.lax.associative_scan",
})

_MAX_PASSES = 50  # >> any real closure-nesting depth; fixpoint guard


class Dataflow:
    """Per-function traced-name sets, shared by every rule family.

    ``traced(fn)`` is the set of local names holding (possibly) traced
    values inside ``fn``; ``returns_traced(fn)`` whether a call of ``fn``
    produces one. Sets exist for host functions too (a host-held jnp
    result is a device array a closure can smuggle into device code) —
    rules decide which functions' sets they consult.
    """

    def __init__(self, project):
        self.project = project
        self._sets: dict = {}      # id(FuncInfo) -> set[str]
        self._returns: dict = {}   # id(FuncInfo) -> bool
        self._bound: dict = {}     # id(FuncInfo) -> frozenset[str]
        self._free: dict = {}      # id(FuncInfo) -> frozenset[str]
        self._fns: list = []
        self._facts: dict = {}     # id(expr) -> (names, prefix?, targets)
        self._work: dict = {}      # id(FuncInfo) -> precomputed body facts
        for mod in project.modules:
            for fn in mod.functions.values():
                self._fns.append(fn)
                self._sets[id(fn)] = (
                    set(fn.traced_params()) if fn.is_device else set()
                )
                self._returns[id(fn)] = False
                self._bound[id(fn)] = astutil.bound_names(fn.node)
                self._free[id(fn)] = astutil.free_names(fn.node)
        for fn in self._fns:
            self._work[id(fn)] = self._body_facts(fn)
        self._calls = self._collect_calls()
        self._seed_control_flow_params()
        self._run()

    # -- public view -------------------------------------------------------
    def traced(self, fn) -> frozenset:
        return frozenset(self._sets.get(id(fn), ()))

    def returns_traced(self, fn) -> bool:
        return self._returns.get(id(fn), False)

    def free(self, fn) -> frozenset:
        """Free (closure-captured) names of ``fn`` — GL06's leak check."""
        return self._free.get(id(fn), frozenset())

    def captured_traced(self, fn) -> frozenset:
        """Free names of ``fn`` that are traced in their binding scope."""
        out = set()
        for name in self._free.get(id(fn), ()):
            anc = fn.parent
            while anc is not None:
                if name in self._bound.get(id(anc), ()):
                    if name in self._sets.get(id(anc), ()):
                        out.add(name)
                    break
                anc = anc.parent
        return frozenset(out)

    # -- tracedness of one expression --------------------------------------
    def expr_traced(self, mod, scope, expr: ast.AST, traced) -> bool:
        """Whether ``expr`` carries a traced value, given the scope's set.

        A Name in ``traced`` outside shape/len laundering, or a call whose
        result is traced (jnp/lax primitive, or a project function with
        ``returns_traced``). Facts per expression are extracted once and
        cached — the fixpoint re-queries the same expressions every pass.
        """
        names, has_prefix, targets = self._expr_facts(mod, scope, expr)
        if has_prefix or names & traced:
            return True
        return any(self._returns[id(t)] for t in targets)

    def _expr_facts(self, mod, scope, expr: ast.AST):
        facts = self._facts.get(id(expr))
        if facts is not None:
            return facts
        names: set = set()
        has_prefix = False
        targets: list = []
        for n in astutil.strip_static_contexts(expr):
            if isinstance(n, ast.Name):
                names.add(n.id)
            elif isinstance(n, ast.Call):
                cname = mod.canonical(n.func)
                if (cname is not None and cname not in _UNTRACED_CALLS
                        and any(cname.startswith(p)
                                for p in _TRACED_PREFIXES)):
                    has_prefix = True
                t = self.project.resolve_function(mod, scope, n.func)
                if t is not None:
                    targets.append(t)
        facts = (frozenset(names), has_prefix, tuple(targets))
        self._facts[id(expr)] = facts
        return facts

    # -- fixpoint ----------------------------------------------------------
    def _seed_control_flow_params(self) -> None:
        for mod in self.project.modules:
            for scope, call in self.project._walk_calls(mod):
                if mod.canonical(call.func) not in _CONTROL_FLOW:
                    continue
                for arg in call.args:
                    target = self.project.resolve_function(mod, scope, arg)
                    if target is not None:
                        self._sets[id(target)].update(target.params)

    def _collect_calls(self) -> dict:
        """id(caller FuncInfo) -> [(call, callee FuncInfo, eligible), ...]
        for every call of a resolvable project function — the per-argument
        tracedness edges ``_pass_args`` replays each pass. ``eligible`` is
        the callee's ``traced_params()``, precomputed once."""
        out: dict = {id(fn): [] for fn in self._fns}
        for mod in self.project.modules:
            for scope, call in self.project._walk_calls(mod):
                if id(scope) not in out:
                    continue
                target = self.project.resolve_function(mod, scope, call.func)
                if target is not None:
                    out[id(scope)].append(
                        (call, target, target.traced_params())
                    )
        return out

    def _pass_args(self, fn) -> bool:
        """Taint callee params from this function's traced call arguments.

        Positional args map to ``target.params`` by slot; keywords map by
        name. Starred args / ``**kwargs`` are skipped — no static slot.
        Mutates CALLEE sets, so the fixpoint driver treats any growth here
        as a change like its own-set growth.

        Only parameters the callee's OWN seed policy deems traced-eligible
        (``traced_params()``: known statics excluded, else the name/default
        heuristics) accept taint. Flow-insensitive caller sets
        over-approximate — a ``lax.switch`` tier index, a tuple-unpacked
        config string — and an unfiltered edge would push that noise into
        slots the callee declares static by convention (``n_slots``-style
        names, defaulted flags), surfacing as spurious GL02s on config
        branches. The filter keeps the edge exactly as strong as device-fn
        seeding: it adds the interprocedural hop, not a new taint policy.
        """
        mod = fn.module
        traced = self._sets[id(fn)]
        changed = False
        for call, target, eligible in self._calls.get(id(fn), ()):
            params = target.params
            tset = self._sets[id(target)]
            for i, arg in enumerate(call.args):
                if isinstance(arg, ast.Starred) or i >= len(params):
                    break
                if (params[i] in eligible and params[i] not in tset
                        and self.expr_traced(mod, fn, arg, traced)):
                    tset.add(params[i])
                    changed = True
            for kw in call.keywords:
                if (kw.arg is not None and kw.arg in eligible
                        and kw.arg not in tset
                        and self.expr_traced(mod, fn, kw.value, traced)):
                    tset.add(kw.arg)
                    changed = True
        return changed

    def _run(self) -> None:
        for _ in range(_MAX_PASSES):
            changed = False
            for fn in self._fns:
                if self._pass_one(fn):
                    changed = True
                if self._pass_args(fn):
                    changed = True
            if not changed:
                return

    def _body_facts(self, fn) -> list:
        """One-time statement scan -> (kind, target-names, expr) work items
        the fixpoint replays each pass without re-walking the AST.
        """
        items: list = []
        for stmt in astutil.own_statements(fn.node):
            targets: list = []
            value = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AugAssign):
                targets, value = [stmt.target], stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                items.append((
                    frozenset(astutil.target_names(stmt.target)), stmt.iter
                ))
                continue
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                items.append((None, stmt.value))  # None = returns marker
                continue
            else:
                continue
            if value is None:
                continue
            # element-wise when both sides are same-length literal tuples:
            # `a, b = x * 2, 3` taints a but not b
            if (len(targets) == 1
                    and isinstance(targets[0], (ast.Tuple, ast.List))
                    and isinstance(value, (ast.Tuple, ast.List))
                    and len(targets[0].elts) == len(value.elts)):
                for t, v in zip(targets[0].elts, value.elts):
                    items.append((
                        frozenset(astutil.target_names(t)), v
                    ))
                continue
            names = frozenset(
                n for t in targets for n in astutil.target_names(t)
            )
            items.append((names, value))
        # walrus and comprehension variables (expression-level bindings)
        for n in astutil.own_nodes(fn.node):
            if isinstance(n, ast.NamedExpr):
                items.append((
                    frozenset(astutil.target_names(n.target)), n.value
                ))
            elif isinstance(n, ast.comprehension):
                items.append((
                    frozenset(astutil.target_names(n.target)), n.iter
                ))
        return items

    def _pass_one(self, fn) -> bool:
        mod = fn.module
        traced = self._sets[id(fn)]
        before = len(traced)
        returns_before = self._returns[id(fn)]

        # closure capture from the lexical parent chain
        traced.update(self.captured_traced(fn))

        for targets, value in self._work[id(fn)]:
            if targets is None:  # a Return expression
                if not self._returns[id(fn)] and self.expr_traced(
                    mod, fn, value, traced
                ):
                    self._returns[id(fn)] = True
            elif not targets <= traced and self.expr_traced(
                mod, fn, value, traced
            ):
                traced.update(targets)

        # lambda bodies are separate units, but a lambda's Return-wrapped
        # body contributes to THIS function's returns only via calls, which
        # resolve_function already handles.
        return (len(traced) != before
                or self._returns[id(fn)] != returns_before)
