"""graftlint engine: module model, jit-reachability, suppressions, runner.

The analyzer answers one question the rule modules all depend on: *which
functions execute under a JAX trace?* Roots are found five ways —

- ``@jax.jit`` / ``@partial(jax.jit, static_argnames=...)`` decorators,
- wrapper calls whose first argument resolves to a known function OR a
  lambda: ``jax.jit(f, ...)``, ``jax.shard_map(f, ...)``, ``jax.vmap(f)``,
  ``jax.vmap(lambda ...)``, ``pl.pallas_call(kernel_or_partial(kernel))``
  — every lambda is rooted as a synthetic FuncInfo (body wrapped in a
  Return), addressed by node identity,
- the annotation convention: a ``# graftlint: device-fn`` comment on (or
  directly above) a ``def`` marks functions whose jit wrapping is indirect
  (e.g. ``fused_builder._make_build_body``'s inner ``build``, which reaches
  ``jax.shard_map`` only as a factory return value),
- transitively: any project function referenced (called OR passed as a
  function value, covering ``lax.scan``/``fori_loop`` bodies) from a
  device function is itself device code,
- and by containment: a lambda lexically inside a device function
  (BlockSpec index maps, inline thunks) evaluates under the same trace.

``# graftlint: host-fn`` marks a deliberate host boundary: the function is
never treated as device code and reachability does not descend into it.
Functions handed to ``io_callback``/``pure_callback``/``debug.callback``
are host implicitly (they run in Python — GL06 polices the call sites).

On top of reachability the Project builds a :class:`~tools.graftlint.
dataflow.Dataflow` — interprocedural traced-value sets every value-
sensitive rule (GL01/GL02/GL06) shares.

Suppressions: ``# graftlint: disable=GL01[,GL03]`` on the finding's line or
the line directly above; ``# graftlint: disable-file=GL01`` anywhere
disables a rule for the whole file. Every suppression must earn its keep:
one that matches no finding is itself flagged (GL00).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize

from tools.graftlint import astutil


class GraftlintError(Exception):
    """Usage/input error (bad path, unparseable file) — CLI exit code 2."""

JIT_WRAPPERS = frozenset({"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"})
SHARD_MAP = frozenset({"jax.shard_map", "jax.experimental.shard_map.shard_map"})
MAP_WRAPPERS = frozenset({"jax.vmap", "jax.pmap"})
PALLAS_CALL = frozenset({"jax.experimental.pallas.pallas_call"})
PARTIAL = frozenset({"functools.partial", "partial"})
# Host-callback entry points: the function handed to these runs on HOST —
# reachability must not descend into it (GL01 inside a callback body would
# cry wolf), and GL06 polices the call sites instead.
CALLBACKS = frozenset({
    "jax.experimental.io_callback", "jax.experimental.pure_callback",
    "jax.pure_callback", "jax.debug.callback",
})

_DIRECTIVE = re.compile(r"#\s*graftlint:\s*([\w-]+)\s*(?:=\s*([\w,\s]+))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def format_human(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class FuncInfo:
    """One ``def`` or ``lambda`` (possibly nested), addressed by
    (module, qualname). Lambdas carry a synthetic FunctionDef node whose
    body is their expression wrapped in a Return, so every body-walking
    helper treats both forms identically."""

    module: "ModuleInfo"
    qualname: str
    node: ast.FunctionDef
    parent: "FuncInfo | None"
    is_lambda: bool = False
    # filled by Project:
    is_device: bool = False
    is_host: bool = False
    statics: frozenset | None = None  # known static_argnames, else None
    statics_known: bool = False
    lambda_children: list = dataclasses.field(default_factory=list)

    @property
    def params(self) -> list:
        return astutil.param_names(self.node.args)

    def traced_params(self) -> frozenset:
        """Parameter names treated as traced values inside this function.

        With known ``static_argnames`` everything else is traced. Without
        (shard_map roots, device-fn annotations, transitively reached
        helpers), keyword-only and static-annotated/static-defaulted
        parameters are assumed static — the convention every factory in
        ops/ and core/ follows — and the rest traced.
        """
        a = self.node.args
        if self.statics_known:
            return frozenset(p for p in self.params
                             if p not in (self.statics or frozenset()))
        traced = set()
        defaults = astutil.param_defaults(a)
        for p in a.posonlyargs + a.args:
            if not astutil.looks_shape_static(
                p.arg, p.annotation, defaults.get(p.arg)
            ):
                traced.add(p.arg)
        return frozenset(traced)


class ModuleInfo:
    def __init__(self, path: str, name: str, source: str):
        self.path = path
        self.name = name
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.aliases: dict = {}
        self.functions: dict = {}  # qualname -> FuncInfo
        self.lambda_infos: dict = {}  # id(ast.Lambda) -> FuncInfo
        self.constants: dict = {}  # module-level NAME -> str constant
        self.file_disabled: dict = {}  # rule -> directive line
        self.line_disabled: dict = {}  # line -> set of rules
        self.directive_lines: dict = {}  # line -> (directive, values)
        self.suppression_hits: set = set()  # (line|'file', rule) that fired
        self._collect_directives()
        self._collect_imports()
        self._collect_functions()
        self._collect_constants()

    # -- source directives -------------------------------------------------
    def _comment_tokens(self):
        """(line, text) per COMMENT token — raw-line regexes would honor
        directive text quoted inside docstrings (e.g. documentation OF the
        suppression syntax), silently disabling rules."""
        try:
            for tok in tokenize.generate_tokens(
                io.StringIO(self.source).readline
            ):
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.string
        except tokenize.TokenizeError:  # pragma: no cover — ast parsed it
            return

    def _collect_directives(self) -> None:
        for i, text in self._comment_tokens():
            m = _DIRECTIVE.search(text)
            if not m:
                continue
            kind, val = m.group(1), m.group(2)
            rules = {
                r.strip().upper() for r in (val or "").split(",") if r.strip()
            }
            if kind == "disable":
                self.line_disabled.setdefault(i, set()).update(rules)
            elif kind == "disable-file":
                for r in rules:
                    self.file_disabled.setdefault(r, i)
            else:
                self.directive_lines[i] = (kind, rules)

    def _directive_at_def(self, node: ast.FunctionDef, kind: str) -> bool:
        """Directive on the def line, or anywhere in the contiguous comment
        block directly above it (or above its first decorator)."""
        starts = [node.lineno]
        starts.extend(dec.lineno for dec in node.decorator_list)
        for start in starts:
            d = self.directive_lines.get(start)
            if d and d[0] == kind:
                return True
            line = start - 1
            while line >= 1 and self.lines[line - 1].lstrip().startswith("#"):
                d = self.directive_lines.get(line)
                if d and d[0] == kind:
                    return True
                line -= 1
        return False

    def suppressed(self, f: Finding) -> bool:
        """Whether a suppression covers ``f`` — and which one: every match
        is recorded in ``suppression_hits`` so the GL00 audit can flag the
        directives that suppressed nothing."""
        if f.rule in self.file_disabled:
            self.suppression_hits.add(("file", f.rule))
            return True
        for line in (f.line, f.line - 1):
            rules = self.line_disabled.get(line)
            if rules and (f.rule in rules or "ALL" in rules):
                # a directive on the line above only applies if that line is
                # a standalone comment (not trailing on unrelated code)
                if line == f.line - 1 and not self.lines[
                    line - 1
                ].lstrip().startswith("#"):
                    continue
                self.suppression_hits.add(
                    (line, f.rule if f.rule in rules else "ALL")
                )
                return True
        return False

    def directive_at(self, lineno: int, kind: str) -> bool:
        """Directive of ``kind`` on ``lineno`` or in the contiguous
        standalone-comment block directly above it (the GL06
        ``host-callback`` convention, mirroring ``_directive_at_def``)."""
        d = self.directive_lines.get(lineno)
        if d and d[0] == kind:
            return True
        line = lineno - 1
        while line >= 1 and self.lines[line - 1].lstrip().startswith("#"):
            d = self.directive_lines.get(line)
            if d and d[0] == kind:
                return True
            line -= 1
        return False

    # -- imports / functions / constants -----------------------------------
    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.level:
                    continue  # relative imports: out of scope
                for a in node.names:
                    self.aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )

    def _collect_functions(self) -> None:
        mod = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.stack: list = []

            def visit_FunctionDef(self, node):
                parent = self.stack[-1] if self.stack else None
                qual = (
                    f"{parent.qualname}.{node.name}" if parent else node.name
                )
                info = FuncInfo(mod, qual, node, parent)
                mod.functions[qual] = info
                self.stack.append(info)
                self.generic_visit(node)
                self.stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                # Root every lambda as a synthetic FuncInfo: body wrapped
                # in a Return so all body-walking helpers apply unchanged.
                # Closes the ROADMAP "jax.vmap(lambda ...) isn't rooted"
                # gap — resolve_function finds these by node identity.
                parent = self.stack[-1] if self.stack else None
                tag = f"<lambda:{node.lineno}:{node.col_offset}>"
                qual = f"{parent.qualname}.{tag}" if parent else tag
                ret = ast.Return(value=node.body)
                ast.copy_location(ret, node.body)
                fd = ast.FunctionDef(
                    name="<lambda>", args=node.args, body=[ret],
                    decorator_list=[],
                )
                ast.copy_location(fd, node)
                info = FuncInfo(mod, qual, fd, parent, is_lambda=True)
                mod.functions[qual] = info
                mod.lambda_infos[id(node)] = info
                if parent is not None:
                    parent.lambda_children.append(info)
                self.stack.append(info)
                self.generic_visit(node)
                self.stack.pop()

            def visit_ClassDef(self, node):
                # methods index under the class name; scope chain unaffected
                parent = self.stack[-1] if self.stack else None
                fake = FuncInfo(
                    mod,
                    f"{parent.qualname}.{node.name}" if parent else node.name,
                    ast.FunctionDef(
                        name=node.name,
                        args=ast.arguments(
                            posonlyargs=[], args=[], kwonlyargs=[],
                            kw_defaults=[], defaults=[],
                        ),
                        body=[], decorator_list=[],
                    ),
                    parent,
                )
                self.stack.append(fake)
                self.generic_visit(node)
                self.stack.pop()

        V().visit(self.tree)

    def _collect_constants(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                s = astutil.str_const(node.value)
                if isinstance(t, ast.Name) and s is not None:
                    self.constants[t.id] = s

    def canonical(self, node: ast.AST) -> str | None:
        return astutil.canonical(node, self.aliases)


class Project:
    """All modules under the lint paths plus the derived device-code facts."""

    def __init__(self, paths: list):
        self.modules: list = []
        self.by_name: dict = {}
        for path in _discover(paths):
            try:
                with open(path, encoding="utf-8") as fh:
                    src = fh.read()
                mod = ModuleInfo(path, _module_name(path), src)
            except OSError as e:
                raise GraftlintError(f"cannot read {path}: {e}") from e
            except SyntaxError as e:
                raise GraftlintError(f"cannot parse {path}: {e}") from e
            self.modules.append(mod)
            self.by_name[mod.name] = mod
        self.jit_sites: list = []  # (FuncInfo, wrapper_kind)
        self._mark_annotations()
        self._mark_callback_targets()
        self._find_jit_roots()
        self._propagate_reachability()
        self.mesh_axes = self._collect_mesh_axes()
        from tools.graftlint.dataflow import Dataflow

        self.dataflow = Dataflow(self)

    # -- resolution --------------------------------------------------------
    def resolve_function(self, mod: ModuleInfo, scope: FuncInfo | None,
                         node: ast.AST) -> FuncInfo | None:
        """Function a Name/Attribute/Lambda refers to at a call site."""
        if isinstance(node, ast.Lambda):
            return mod.lambda_infos.get(id(node))
        if isinstance(node, ast.Name):
            # lexical scope chain: nested defs of each enclosing function
            cur = scope
            while cur is not None:
                hit = mod.functions.get(f"{cur.qualname}.{node.id}")
                if hit is not None:
                    return hit
                cur = cur.parent
            hit = mod.functions.get(node.id)
            if hit is not None:
                return hit
        dotted = mod.canonical(node)
        if dotted is None:
            return None
        # longest known-module prefix + top-level function name
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            m = self.by_name.get(".".join(parts[:cut]))
            if m is not None:
                return m.functions.get(".".join(parts[cut:]))
        return None

    def resolve_str(self, mod: ModuleInfo, node: ast.AST) -> str | None:
        """String value of a literal or a resolvable module-level constant."""
        s = astutil.str_const(node)
        if s is not None:
            return s
        dotted = mod.canonical(node)
        if dotted is None:
            return None
        parts = dotted.split(".")
        if len(parts) == 1:
            return mod.constants.get(parts[0])
        owner = self.by_name.get(".".join(parts[:-1]))
        return owner.constants.get(parts[-1]) if owner else None

    # -- jit-root discovery ------------------------------------------------
    def _mark_annotations(self) -> None:
        for mod in self.modules:
            for fn in mod.functions.values():
                if fn.is_lambda:
                    continue  # synthetic defs carry no real comment lines
                if mod._directive_at_def(fn.node, "device-fn"):
                    fn.is_device = True
                if mod._directive_at_def(fn.node, "host-fn"):
                    fn.is_host = True

    def _mark_callback_targets(self) -> None:
        """Functions handed to io_callback/pure_callback/debug.callback run
        on HOST — mark them host so reachability never descends into them
        (their np.asarray/.item() bodies are the point, not a finding).
        GL06 polices the call sites instead."""
        for mod in self.modules:
            for scope, call in self._walk_calls(mod):
                if mod.canonical(call.func) not in CALLBACKS or not call.args:
                    continue
                target = self.resolve_function(mod, scope, call.args[0])
                if target is not None:
                    target.is_host = True

    def _jit_target(self, mod: ModuleInfo, scope: FuncInfo | None,
                    call: ast.Call):
        """(FuncInfo, statics, kind) for a wrapper call, or None."""
        fn = mod.canonical(call.func)
        if fn is None or not call.args:
            return None
        if fn in JIT_WRAPPERS or fn in SHARD_MAP or fn in MAP_WRAPPERS:
            target = self.resolve_function(mod, scope, call.args[0])
            if target is None:
                return None
            statics = astutil.str_tuple(
                astutil.keyword_arg(call, "static_argnames") or ast.Tuple(
                    elts=[], ctx=ast.Load()
                )
            )
            known = fn in JIT_WRAPPERS
            return target, (frozenset(statics or ()) if known else None), fn
        if fn in PALLAS_CALL:
            kernel = call.args[0]
            if isinstance(kernel, ast.Call) and (
                mod.canonical(kernel.func) in PARTIAL
            ) and kernel.args:
                kernel = kernel.args[0]
            target = self.resolve_function(mod, scope, kernel)
            if target is None:
                return None
            return target, None, "pallas_call"
        return None

    def _decorator_jit(self, mod: ModuleInfo, fn: FuncInfo):
        for dec in fn.node.decorator_list:
            name = mod.canonical(dec if not isinstance(dec, ast.Call)
                                 else dec.func)
            if name in JIT_WRAPPERS:
                statics: frozenset = frozenset()
                if isinstance(dec, ast.Call):
                    statics = frozenset(astutil.str_tuple(
                        astutil.keyword_arg(dec, "static_argnames")
                        or ast.Tuple(elts=[], ctx=ast.Load())
                    ) or ())
                return statics
            if (isinstance(dec, ast.Call) and name in PARTIAL and dec.args
                    and mod.canonical(dec.args[0]) in JIT_WRAPPERS):
                statics = frozenset(astutil.str_tuple(
                    astutil.keyword_arg(dec, "static_argnames")
                    or ast.Tuple(elts=[], ctx=ast.Load())
                ) or ())
                return statics
        return None

    def _find_jit_roots(self) -> None:
        for mod in self.modules:
            for fn in mod.functions.values():
                statics = self._decorator_jit(mod, fn)
                if statics is not None and not fn.is_host:
                    fn.is_device = True
                    fn.statics = statics
                    fn.statics_known = True
                    self.jit_sites.append((fn, "decorator"))
            for scope, call in self._walk_calls(mod):
                hit = self._jit_target(mod, scope, call)
                if hit is None:
                    continue
                target, statics, kind = hit
                if target.is_host:
                    continue
                target.is_device = True
                if statics is not None and not target.statics_known:
                    target.statics = statics
                    target.statics_known = True
                    self.jit_sites.append((target, kind))

    def _walk_calls(self, mod: ModuleInfo):
        """(enclosing FuncInfo | None, Call) pairs across the module.

        Materialized once per ModuleInfo: root discovery, mesh-axis
        collection, dataflow seeding and four rule families all replay it.
        """
        cached = getattr(mod, "_call_sites", None)
        if cached is not None:
            return cached

        def visit(node, scope):
            for child in ast.iter_child_nodes(node):
                child_scope = scope
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = (
                        f"{scope.qualname}.{child.name}" if scope
                        else child.name
                    )
                    child_scope = mod.functions.get(qual, scope)
                elif isinstance(child, ast.Lambda):
                    child_scope = mod.lambda_infos.get(id(child), scope)
                if isinstance(child, ast.Call):
                    yield scope, child
                yield from visit(child, child_scope)

        mod._call_sites = list(visit(mod.tree, None))
        return mod._call_sites

    def _propagate_reachability(self) -> None:
        queue = [
            fn for mod in self.modules for fn in mod.functions.values()
            if fn.is_device
        ]
        seen = set(id(f) for f in queue)

        def enqueue(target):
            if target.is_host or id(target) in seen:
                return
            target.is_device = True
            seen.add(id(target))
            queue.append(target)

        while queue:
            fn = queue.pop()
            # a lambda lexically inside a device function evaluates under
            # the same trace (BlockSpec index maps, sort keys, inline
            # branch thunks) — device by containment
            for lam in fn.lambda_children:
                enqueue(lam)
            for node in astutil.own_nodes(fn.node):
                # any resolvable function reference counts — called, passed
                # to lax.scan/cond/fori_loop, or returned (tier factories)
                if not isinstance(node, (ast.Name, ast.Attribute)):
                    continue
                target = self.resolve_function(fn.module, fn, node)
                if target is not None:
                    enqueue(target)

    def device_functions(self):
        for mod in self.modules:
            for fn in mod.functions.values():
                if fn.is_device:
                    yield fn

    # -- mesh axes ---------------------------------------------------------
    def _collect_mesh_axes(self) -> frozenset:
        """Axis names declared anywhere in the lint set.

        Sources: module-level ``*_AXIS = "name"`` constants, and literal
        axis tuples handed to ``Mesh(...)`` constructors (names resolve
        through module constants). GL03 checks collective axis names against
        this set; when the set is empty the check is skipped (linting a
        single file without its mesh module must not cry wolf).
        """
        axes: set = set()
        for mod in self.modules:
            for name, val in mod.constants.items():
                if "AXIS" in name.upper():
                    axes.add(val)
            for _scope, call in self._walk_calls(mod):
                fn = mod.canonical(call.func)
                if fn is None or fn.rsplit(".", 1)[-1] != "Mesh":
                    continue
                if len(call.args) < 2:
                    axis_arg = astutil.keyword_arg(call, "axis_names")
                else:
                    axis_arg = call.args[1]
                if not isinstance(axis_arg, (ast.Tuple, ast.List)):
                    continue
                for el in axis_arg.elts:
                    s = self.resolve_str(mod, el)
                    if s is not None:
                        axes.add(s)
        return frozenset(axes)


def _discover(paths: list) -> list:
    """Python files under ``paths``; bad inputs are hard errors.

    A typo'd path must NOT exit 0-clean — a green CI run that linted
    nothing is the worst failure mode a lint gate can have.
    """
    files: list = []
    for p in paths:
        if os.path.isdir(p):
            found = []
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".ruff_cache")
                )
                found.extend(
                    os.path.join(root, n) for n in sorted(names)
                    if n.endswith(".py")
                )
            if not found:
                raise GraftlintError(f"no Python files under {p!r}")
            files.extend(found)
        elif os.path.isfile(p) and p.endswith(".py"):
            files.append(p)
        else:
            raise GraftlintError(
                f"path {p!r} is not a directory or existing .py file"
            )
    return files


def _module_name(path: str) -> str:
    """Dotted module name by walking up through ``__init__.py`` packages."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    if parts[0] == "__init__":
        parts = parts[1:]
    return ".".join(reversed(parts))


def _unused_suppressions(project, selected_ids, rules_filter):
    """GL00 — the RUF100 audit: a suppression that suppressed nothing is
    itself a finding (dead directives read as load-bearing and rot).

    A directive for rule R is only auditable when R actually ran this
    invocation (R in the selected set, or no ``--select`` filter at all —
    in which case a directive naming an unknown rule id is dead by
    definition and flagged too). ``ALL`` suppressions are never audited.
    GL00 findings are not themselves suppressible: the fix is deleting a
    comment, never adding one.
    """
    for mod in project.modules:
        entries = [
            (line, r, (line, r))
            for line, rs in mod.line_disabled.items() for r in rs
        ] + [
            (line, r, ("file", r))
            for r, line in mod.file_disabled.items()
        ]
        for line, r, key in sorted(entries, key=lambda e: (e[0], e[1])):
            if r == "ALL":
                continue
            if rules_filter is not None and r not in selected_ids:
                continue  # rule didn't run — can't judge its suppressions
            if key in mod.suppression_hits:
                continue
            scope = "file-wide " if key[0] == "file" else ""
            yield Finding(
                "GL00", mod.path, line, 0,
                f"unused {scope}suppression: no {r} finding is silenced "
                "by this directive — delete it",
            )


def run_lint(paths: list, rules: list | None = None) -> tuple:
    """Lint ``paths``; returns (findings, suppressed_count).

    ``rules``: optional rule-id filter (e.g. ["GL01"]). Findings are sorted
    by (path, line, col, rule) and deduplicated. The GL00 unused-suppression
    audit runs after suppression resolution (it needs the hit accounting)
    unless filtered out.
    """
    from tools.graftlint.rules import ALL_RULES

    project = Project(paths)
    selected = [
        r for r in ALL_RULES if rules is None or r.rule_id in rules
    ]
    raw: set = set()
    for rule in selected:
        for f in rule.check(project):
            raw.add(f)
    findings, suppressed = [], 0
    mods = {m.path: m for m in project.modules}
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.col, f.rule)):
        mod = mods.get(f.path)
        if mod is not None and mod.suppressed(f):
            suppressed += 1
        else:
            findings.append(f)
    if rules is None or "GL00" in rules:
        selected_ids = {r.rule_id for r in selected}
        findings.extend(_unused_suppressions(project, selected_ids, rules))
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, suppressed


def load_baseline(path: str) -> list:
    """Parse a baseline file into a list of (rule, path, message) keys.

    Line/col are deliberately NOT part of the key — unrelated edits shift
    them, and a baseline that churns on every diff is a baseline nobody
    regenerates honestly.
    """
    import json

    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except OSError as e:
        raise GraftlintError(f"cannot read baseline {path}: {e}") from e
    except ValueError as e:
        raise GraftlintError(f"cannot parse baseline {path}: {e}") from e
    if not isinstance(data, dict) or "findings" not in data:
        raise GraftlintError(
            f"baseline {path}: expected an object with a 'findings' list"
        )
    return [
        (f["rule"], f["path"].replace(os.sep, "/"), f["message"])
        for f in data["findings"]
    ]


def apply_baseline(findings: list, baseline: list) -> tuple:
    """Split ``findings`` into (new, known) against baseline keys.

    Multiset matching: two identical findings in one file consume two
    baseline entries — a third is new.
    """
    budget: dict = {}
    for key in baseline:
        budget[key] = budget.get(key, 0) + 1
    new, known = [], []
    for f in findings:
        key = (f.rule, f.path.replace(os.sep, "/"), f.message)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            known.append(f)
        else:
            new.append(f)
    return new, known
