"""graftlint — JAX-aware static analysis for the mpitree_tpu framework.

Enforces the device-boundary, recompile, collective, dtype, donation,
host-callback and Pallas invariants the TPU engines depend on (see each
``rules/glXX_*`` module), on every CPU-only CI run, over an
interprocedural traced-value dataflow (``dataflow.py``). Public API:
:func:`run_lint`, :class:`Finding`, plus the baseline helpers the CLI's
``--baseline`` CI gate is built on.
"""

from tools.graftlint.engine import (
    Finding,
    GraftlintError,
    Project,
    apply_baseline,
    load_baseline,
    run_lint,
)

__all__ = [
    "Finding", "GraftlintError", "Project", "apply_baseline",
    "load_baseline", "run_lint",
]
__version__ = "0.2.0"
