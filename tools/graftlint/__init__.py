"""graftlint — JAX-aware static analysis for the mpitree_tpu framework.

Enforces the device-boundary, recompile, collective and dtype invariants
the TPU engines depend on (see each ``rules/glXX_*`` module), on every
CPU-only CI run. Public API: :func:`run_lint`, :class:`Finding`.
"""

from tools.graftlint.engine import (
    Finding,
    GraftlintError,
    Project,
    run_lint,
)

__all__ = ["Finding", "GraftlintError", "Project", "run_lint"]
__version__ = "0.1.0"
