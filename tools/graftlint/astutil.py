"""Shared AST helpers: dotted-name resolution and traced-value tracking.

Everything here is pure ``ast`` — graftlint never imports the code it lints
(the package targets a newer JAX than some lint hosts carry), so every fact
is derived from source text. Resolution is deliberately conservative: a name
that cannot be resolved is *skipped*, never guessed, because a lint that
cries wolf on the builders' factory closures would be suppressed into
uselessness within a week.
"""

from __future__ import annotations

import ast

# Attribute/call forms whose result is trace-time static even when computed
# from a traced array: shapes, ranks and dtypes are Python values under jit.
STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size"})
STATIC_CALLS = frozenset({"len", "range", "isinstance", "type"})


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def canonical(node: ast.AST, aliases: dict) -> str | None:
    """Dotted name with its first segment rewritten through import aliases.

    ``pl.BlockSpec`` with ``from jax.experimental import pallas as pl``
    becomes ``jax.experimental.pallas.BlockSpec``; an unaliased head is
    returned as spelled (builtins, locals).
    """
    name = dotted_name(node)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    target = aliases.get(head)
    if target is None:
        return name
    return f"{target}.{rest}" if rest else target


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def int_tuple(node: ast.AST) -> tuple | None:
    """Tuple/list-of-int-constants literal, a single int, or None.

    The ``donate_argnums=(0, 2)`` / ``grid=(4,)`` literal shapes GL07/GL08
    resolve; bools are not ints here (``True`` is not an argument index).
    """
    def one(n: ast.AST) -> int | None:
        if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                and not isinstance(n.value, bool):
            return n.value
        return None

    v = one(node)
    if v is not None:
        return (v,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            v = one(el)
            if v is None:
                return None
            out.append(v)
        return tuple(out)
    return None


def str_tuple(node: ast.AST) -> tuple | None:
    """Tuple/list-of-string-constants literal, a single string, or None."""
    s = str_const(node)
    if s is not None:
        return (s,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            s = str_const(el)
            if s is None:
                return None
            out.append(s)
        return tuple(out)
    return None


def keyword_arg(call: ast.Call, name: str) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def param_names(args: ast.arguments) -> list:
    return [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]


def param_defaults(args: ast.arguments) -> dict:
    """name -> default expr, for every parameter that has one.

    ``args.defaults`` aligns with the TAIL of posonly+args combined (a
    posonly parameter can carry a default too); kw_defaults align 1:1 with
    kwonlyargs, None meaning required.
    """
    out: dict = {}
    positional = args.posonlyargs + args.args
    for p, d in zip(positional[len(positional) - len(args.defaults):],
                    args.defaults):
        out[p.arg] = d
    for p, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None:
            out[p.arg] = d
    return out


def positional_arity(args: ast.arguments) -> int | None:
    """Count of positionally-fillable params; None when *args/**kw make the
    arity open (e.g. ``local_step(..., *nm)`` in parallel/collective.py)."""
    if args.vararg is not None or args.kwarg is not None:
        return None
    return len(args.posonlyargs) + len(args.args)


def _ann_static(ann: ast.AST | None) -> bool:
    """Whether an annotation names a trace-time-static Python type.

    Matches ``int``, ``bool``, ``str``, ``tuple``/``tuple[...]`` and their
    ``X | None`` unions — the types jit cannot trace and must either hash as
    static or recompile on. Array annotations (``jax.Array``) return False.
    """
    if ann is None:
        return False
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        return _ann_static(ann.left) or _ann_static(ann.right)
    if isinstance(ann, ast.Subscript):
        return _ann_static(ann.value)
    if isinstance(ann, ast.Constant):
        if not isinstance(ann.value, str):  # e.g. the None in `int | None`
            return False
        try:  # quoted annotation
            return _ann_static(ast.parse(ann.value, mode="eval").body)
        except SyntaxError:
            return False
    return isinstance(ann, ast.Name) and ann.id in (
        "int", "bool", "str", "tuple"
    )


# Parameter-name shapes that in this codebase always determine array shapes
# or compiled control flow (the GL02 heuristic's second leg alongside type
# annotations). Deliberately NOT matched: runtime scalars the builders trace
# on purpose — chunk_lo, mcw, mid, root_key.
_STATIC_NAME_SUFFIXES = (
    "_bins", "_slots", "_size", "_tile", "_chunk", "_depth", "_width",
    "_channels", "_steps", "_classes", "_features", "_samples",
)
_STATIC_NAME_EXACT = frozenset({"window", "mode", "interpret", "task",
                                "criterion", "axis_name"})


def looks_shape_static(name: str, ann: ast.AST | None,
                       default: ast.AST | None) -> bool:
    """GL02's "should this jitted parameter be static?" heuristic."""
    if _ann_static(ann):
        return True
    if isinstance(default, ast.Constant) and isinstance(
        default.value, (bool, int, str)
    ) and not isinstance(default.value, float):
        return True
    if name.startswith(("n_", "num_", "max_", "min_")):
        return True
    return name.endswith(_STATIC_NAME_SUFFIXES) or name in _STATIC_NAME_EXACT


def strip_static_contexts(expr: ast.AST) -> list:
    """Nodes of ``expr`` excluding subtrees that are static under tracing.

    ``x.shape``, ``len(x)``, ``x.ndim`` never carry tracedness out — a name
    referenced only inside such a subtree is not a traced use (the pervasive
    ``N, F = xb.shape`` idiom in ops/). Lambda subtrees are excluded too: a
    lambda *expression* is a function value, never a traced array — its body
    is analyzed as a synthetic FuncInfo, not in place.
    """
    out: list = []

    def visit(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
            return
        if isinstance(n, ast.Lambda):
            return
        if isinstance(n, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops
        ):
            # identity tests never read a value: `x is None` on a traced
            # array is a concrete Python bool (the pervasive optional-
            # operand idiom in ops/impurity.py), not a concretization
            return
        if isinstance(n, ast.Call):
            fn = dotted_name(n.func)
            if fn in STATIC_CALLS:
                return
        out.append(n)
        for child in ast.iter_child_nodes(n):
            visit(child)

    visit(expr)
    return out


def target_names(target: ast.AST):
    """Name ids assigned by a (possibly tuple/starred) assignment target."""
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            yield n.id


def bound_names(func: ast.AST) -> frozenset:
    """Names the function binds locally: params, assignment/loop/with
    targets, walrus targets, comprehension variables, nested def names,
    and imports. Everything referenced but not bound is a *free* name —
    the closure-capture edge the dataflow engine propagates through.
    """
    out: set = set()
    a = getattr(func, "args", None)
    if a is not None:
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            out.add(p.arg)
        if a.vararg is not None:
            out.add(a.vararg.arg)
        if a.kwarg is not None:
            out.add(a.kwarg.arg)
    for stmt in own_statements(func):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            out.add(stmt.name)
        elif isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                out.update(target_names(t))
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            out.update(target_names(stmt.target))
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            out.update(target_names(stmt.target))
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    out.update(target_names(item.optional_vars))
        elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for alias in stmt.names:
                out.add((alias.asname or alias.name).split(".")[0])
    for n in own_nodes(func):
        if isinstance(n, ast.NamedExpr):
            out.update(target_names(n.target))
        elif isinstance(n, ast.comprehension):
            out.update(target_names(n.target))
    return frozenset(out)


def free_names(func: ast.AST) -> frozenset:
    """Load-context names referenced in ``func`` but bound elsewhere."""
    bound = bound_names(func)
    return frozenset(
        n.id for n in own_nodes(func)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
        and n.id not in bound
    )


def own_statements(func: ast.AST):
    """Every statement in ``func`` excluding nested function bodies."""
    stack = list(getattr(func, "body", []))
    while stack:
        stmt = stack.pop(0)
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for _field, val in ast.iter_fields(stmt):
            if isinstance(val, list):
                stack.extend(v for v in val if isinstance(v, ast.stmt))


def own_nodes(func: ast.AST):
    """Every AST node lexically in ``func``, excluding nested ``def`` AND
    ``lambda`` bodies — both are separate analysis units (lambdas are
    rooted as synthetic FuncInfos by the engine). The lambda node itself
    is still yielded (it is an expression in this scope)."""
    def visit(n: ast.AST):
        yield n
        # any FunctionDef reaching here is a NESTED def (the root's body
        # statements are dispatched below, never the root itself) — its
        # body belongs to its own FuncInfo, stop descending
        if isinstance(n, (ast.Lambda, ast.FunctionDef,
                          ast.AsyncFunctionDef)):
            return
        for child in ast.iter_child_nodes(n):
            yield from visit(child)

    for stmt in getattr(func, "body", []):
        yield from visit(stmt)
