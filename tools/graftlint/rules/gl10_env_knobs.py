"""GL10 — env-knob registry: one typed read path for project knobs.

``mpitree_tpu/config/knobs.py`` is the single ``os.environ`` read path
for every ``MPITREE_TPU_*`` knob: each entry carries its type, default,
parse rule, and the doc line the README table is generated from. A direct
``os.environ.get("MPITREE_TPU_...")`` anywhere else re-opens the drift
this registry closed — an undocumented knob with ad-hoc parsing and no
default discipline. Two legs:

1. **Read siting.** Any ``os.environ.get`` / ``os.getenv`` /
   ``os.environ[...]`` access whose key literal starts with
   ``MPITREE_TPU_``, in a module not carrying the
   ``# graftlint: knob-registry`` directive, is a finding. Foreign keys
   (``COORDINATOR_ADDRESS``, ``JAX_PLATFORMS``) are out of jurisdiction;
   non-literal keys are never guessed.
2. **Doc drift.** Inside a registry module, every ``Knob("MPITREE_TPU_*",
   ...)`` registration must appear in the nearest ``README.md`` (walking
   up from the module) — the generated knob table is part of the
   contract, and ``python -m mpitree_tpu.config --write`` regenerates it.
"""

from __future__ import annotations

import ast
from pathlib import Path

from tools.graftlint import astutil
from tools.graftlint.engine import Finding

rule_id = "GL10"

_PREFIX = "MPITREE_TPU_"
_ENV_CALLS = (
    "os.environ.get", "os.getenv", "os.environ.pop",
    "os.environ.setdefault",
)


def _is_registry_module(mod) -> bool:
    return any(
        kind == "knob-registry"
        for kind, _vals in mod.directive_lines.values()
    )


def _project_key(node) -> str | None:
    s = astutil.str_const(node)
    return s if s is not None and s.startswith(_PREFIX) else None


def _nearest_readme(path: str) -> Path | None:
    for parent in Path(path).resolve().parents:
        cand = parent / "README.md"
        if cand.is_file():
            return cand
    return None


def check(project):
    readme_cache: dict = {}
    for mod in project.modules:
        if _is_registry_module(mod):
            yield from _check_registry(mod, readme_cache)
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                if mod.canonical(node.func) not in _ENV_CALLS:
                    continue
                key = _project_key(node.args[0]) if node.args else None
            elif isinstance(node, ast.Subscript):
                if astutil.dotted_name(node.value) != "os.environ":
                    continue
                key = _project_key(node.slice)
            else:
                continue
            if key is None:
                continue
            yield Finding(
                rule_id, mod.path, node.lineno, node.col_offset,
                f"direct environ access for '{key}' outside the knob "
                "registry — read it through mpitree_tpu.config.knobs "
                "(value()/raw()) so the knob stays typed and documented",
            )


def _check_registry(mod, readme_cache):
    """Doc-drift leg: registered knobs must appear in the nearest README."""
    readme = readme_cache.get(mod.path)
    if readme is None:
        path = _nearest_readme(mod.path)
        readme = path.read_text() if path is not None else ""
        readme_cache[mod.path] = readme
    if not readme:
        return
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        fname = (astutil.dotted_name(node.func) or "").rsplit(".", 1)[-1]
        if fname != "Knob":
            continue
        key = _project_key(node.args[0])
        if key is not None and key not in readme:
            yield Finding(
                rule_id, mod.path, node.lineno, node.col_offset,
                f"registered knob '{key}' is missing from the README "
                "knob table — regenerate it with "
                "`python -m mpitree_tpu.config --write`",
            )
