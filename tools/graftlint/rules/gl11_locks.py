"""GL11 — lock discipline: the guarded-attribute contract, statically.

The serving tier is genuinely multithreaded (scheduler worker thread,
registry swap-under-load, lock-safe metrics), and its classes follow one
convention: shared mutable attributes are touched only inside ``with
self._lock:`` blocks. A single forgotten lock is the bug class unit
tests are worst at catching — the race reproduces under production
concurrency and never under a single-threaded test. This rule recovers
the convention from source and holds every class to it:

1. **Guarded-set inference + unlocked access.** A class's lock attributes
   are the instance attributes holding ``threading.Lock``/``RLock``/
   ``Condition`` objects (by constructor, or by name for locks injected
   through a parameter — the metrics ``lock=`` idiom). The guarded set is
   every attribute *written* inside a with-lock region (plain/augmented/
   subscript assignment or a mutating method call: ``append``, ``pop``,
   ``setdefault``, ...), plus every attribute *read* under the lock that
   is also written anywhere outside ``__init__`` — the read-under-lock
   half of a torn read/write pair. Any touch of a guarded attribute
   outside a with-lock region is a finding. ``__init__``/``__post_init__``
   run before the object is shared and are exempt; a private method whose
   every intra-class call site is inside a locked region (or another
   lock-held method) inherits the lock.
2. **Acquisition-order inversion.** Acquiring lock B inside a region that
   holds lock A records the order (A, B); a site elsewhere acquiring them
   as (B, A) is the classic ABBA deadlock shape and is flagged at the
   sites of the later-introduced order.
3. **Condition discipline.** ``wait``/``wait_for``/``notify``/
   ``notify_all`` on a lock attribute require that same lock held —
   calling them unlocked raises at run time only when the race timing
   cooperates.
4. **Contract modules.** A module whose docstring declares a
   ``Concurrency:`` contract but starts ``threading.Thread``s while
   constructing no lock anywhere has documented an intent the code does
   not implement.
5. **The escape.** ``# graftlint: lock-free — <why>`` on the access line,
   the comment block above it, or the enclosing ``def`` silences leg 1/3
   for deliberate lock-free touches (monitoring reads, single-writer
   fields) — but only with a non-empty justification; a bare escape is
   itself a finding. An intentional race must say why it is benign.

Everything is per-class and name-based — graftlint never imports the
linted code. Module-level locks guarding module globals, cross-thread
happens-before through queue handoff, and RLock reentrancy depth are
deliberate non-goals (see ROADMAP).
"""

from __future__ import annotations

import ast
import re

from tools.graftlint.engine import Finding

rule_id = "GL11"

_LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
})
_THREAD_CTORS = frozenset({"threading.Thread", "threading.Timer"})
# method calls that mutate the container an attribute holds
_MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popleft", "popitem",
    "clear", "update", "setdefault", "add", "discard", "appendleft",
})
_CONDITION_OPS = frozenset({"wait", "wait_for", "notify", "notify_all"})
_EXEMPT_METHODS = frozenset({"__init__", "__post_init__"})
_LOCK_NAME = re.compile(r"lock|cond|mutex", re.IGNORECASE)
_CONTRACT = re.compile(r"\bconcurrency\s*:", re.IGNORECASE)
_JUSTIFICATION = re.compile(r"lock-free[\s—\-–:]*(.*)")


class _Access:
    __slots__ = ("attr", "write", "held", "node", "method")

    def __init__(self, attr, write, held, node, method):
        self.attr = attr
        self.write = write
        self.held = held      # lock attr held at the site, or None
        self.node = node
        self.method = method  # enclosing method name


class _ClassReport:
    """One class's lock model: lock attrs, classified attribute accesses,
    condition-op sites, nested acquisition orders, intra-class call sites."""

    def __init__(self, node):
        self.node = node
        self.locks: set = set()
        self.accesses: list = []
        self.cond_ops: list = []   # (lock_attr, held, node, method)
        self.pairs: dict = {}      # (outer, inner) -> [node, ...]
        self.calls: dict = {}      # method -> [(caller, held), ...]
        self.methods: set = set()


def _self_attr(node, self_name):
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == self_name):
        return node.attr
    return None


def _find_locks(mod, cls_node, self_name, report):
    """Lock attributes: ``self.X = threading.Lock()`` anywhere, class-level
    ``X = threading.Lock()``, or a lock-named attr bound from a lock-named
    parameter (the injected ``self._lock = lock`` idiom in obs/metrics)."""
    for stmt in cls_node.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Call)
                and mod.canonical(stmt.value.func) in _LOCK_CTORS):
            report.locks.add(stmt.targets[0].id)
    for node in ast.walk(cls_node):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        attr = _self_attr(node.targets[0], self_name)
        if attr is None:
            continue
        v = node.value
        if isinstance(v, ast.Call) and mod.canonical(v.func) in _LOCK_CTORS:
            report.locks.add(attr)
        elif (_LOCK_NAME.search(attr) and isinstance(v, ast.Name)
              and _LOCK_NAME.search(v.id)):
            report.locks.add(attr)


def _classify(report, method_node, method_name, self_name, parents):
    """Walk one method, tracking the innermost held lock; nested def/lambda
    bodies are separate execution contexts and are skipped (conservative:
    their accesses are neither flagged nor used for inference)."""

    def base_write(attr_node):
        """Climb subscript chains: ``self._heaps[k][j] = v`` writes the
        base attribute for discipline purposes."""
        cur = attr_node
        while True:
            p = parents.get(id(cur))
            if isinstance(p, ast.Subscript) and p.value is cur:
                if isinstance(p.ctx, (ast.Store, ast.Del)):
                    return True
                cur = p
                continue
            return False

    def visit(node, held):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            h = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    la = _self_attr(item.context_expr, self_name)
                    if la in report.locks:
                        if held is not None and la != held:
                            report.pairs.setdefault(
                                (held, la), []
                            ).append(item.context_expr)
                        h = la
            elif isinstance(child, ast.Call):
                fa = _self_attr(child.func, self_name)
                if fa is not None:
                    report.calls.setdefault(fa, []).append(
                        (method_name, held)
                    )
            elif isinstance(child, ast.Attribute):
                attr = _self_attr(child, self_name)
                if attr is not None and attr not in report.locks:
                    if isinstance(child.ctx, (ast.Store, ast.Del)):
                        kind = True
                    elif base_write(child):
                        kind = True
                    else:
                        kind = False
                        p = parents.get(id(child))
                        if (isinstance(p, ast.Attribute)
                                and p.value is child):
                            gp = parents.get(id(p))
                            if (isinstance(gp, ast.Call)
                                    and gp.func is p
                                    and p.attr in _MUTATORS):
                                kind = True
                    report.accesses.append(
                        _Access(attr, kind, held, child, method_name)
                    )
                elif attr in report.locks:
                    p = parents.get(id(child))
                    if (isinstance(p, ast.Attribute) and p.value is child
                            and p.attr in _CONDITION_OPS):
                        gp = parents.get(id(p))
                        if isinstance(gp, ast.Call) and gp.func is p:
                            report.cond_ops.append(
                                (attr, held, child, method_name)
                            )
            visit(child, h)

    visit(method_node, None)


def _analyze_class(mod, cls_node):
    report = _ClassReport(cls_node)
    methods = [
        stmt for stmt in cls_node.body
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
        and stmt.args.args and stmt.args.args[0].arg == "self"
    ]
    if not methods:
        return report
    _find_locks(mod, cls_node, "self", report)
    if not report.locks:
        return report
    for m in methods:
        report.methods.add(m.name)
        parents = {}
        for n in ast.walk(m):
            for c in ast.iter_child_nodes(n):
                parents[id(c)] = n
        _classify(report, m, m.name, "self", parents)
    return report


def _held_methods(report):
    """Methods whose every recorded intra-class call site runs with a lock
    held (directly or through another held caller) inherit the lock."""
    held: set = set()
    changed = True
    while changed:
        changed = False
        for name, sites in report.calls.items():
            if name in held or name not in report.methods or not sites:
                continue
            if all(h is not None or caller in held for caller, h in sites):
                held.add(name)
                changed = True
    return held


def _lock_free_line(mod, lineno):
    """Line carrying a ``lock-free`` directive covering ``lineno``: the
    line itself or the contiguous standalone-comment block above it."""
    d = mod.directive_lines.get(lineno)
    if d and d[0] == "lock-free":
        return lineno
    line = lineno - 1
    while line >= 1 and mod.lines[line - 1].lstrip().startswith("#"):
        d = mod.directive_lines.get(line)
        if d and d[0] == "lock-free":
            return line
        line -= 1
    return None


def _lock_free_at(mod, node, method_node):
    """('ok'|'bare', line) when a lock-free escape covers this access —
    on its line, above it, or on/above the enclosing def — else None."""
    lines = [node.lineno]
    if method_node is not None:
        lines.append(method_node.lineno)
        lines.extend(d.lineno for d in method_node.decorator_list)
    for lineno in lines:
        hit = _lock_free_line(mod, lineno)
        if hit is None:
            continue
        m = _JUSTIFICATION.search(mod.lines[hit - 1])
        text = (m.group(1) if m else "").strip()
        return ("ok" if text else "bare"), hit
    return None


def _method_node(report, name):
    for stmt in report.node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and stmt.name == name:
            return stmt
    return None


def _class_findings(mod, report):
    locked_writes: set = set()
    locked_reads: set = set()
    outside_init_writes: set = set()
    guard_lock: dict = {}
    for a in report.accesses:
        if a.held is not None:
            (locked_writes if a.write else locked_reads).add(a.attr)
            guard_lock.setdefault(a.attr, a.held)
        if a.write and a.method not in _EXEMPT_METHODS:
            outside_init_writes.add(a.attr)
    guarded = locked_writes | (locked_reads & outside_init_writes)
    held = _held_methods(report)
    cls = report.node.name

    def covered(node, method):
        esc = _lock_free_at(mod, node, _method_node(report, method))
        if esc is None:
            return False
        kind, line = esc
        if kind == "bare":
            yield_bare.add(line)
        return True

    yield_bare: set = set()
    for a in report.accesses:
        if (a.attr not in guarded or a.held is not None
                or a.method in _EXEMPT_METHODS or a.method in held):
            continue
        lock = guard_lock.get(a.attr, sorted(report.locks)[0])
        if covered(a.node, a.method):
            continue
        verb = "written" if a.write else "read"
        yield Finding(
            rule_id, mod.path, a.node.lineno, a.node.col_offset,
            f"'{cls}.{a.attr}' is guarded by 'self.{lock}' (touched under "
            f"the lock elsewhere in the class) but {verb} here without it "
            "— wrap the access in the lock or annotate the deliberate "
            "race: `# graftlint: lock-free — <why it is benign>`",
        )
    for lock, h, node, method in report.cond_ops:
        if h == lock or method in held:
            continue
        if covered(node, method):
            continue
        yield Finding(
            rule_id, mod.path, node.lineno, node.col_offset,
            f"condition operation on 'self.{lock}' outside `with "
            f"self.{lock}:` — wait/notify require the underlying lock "
            "held and raise RuntimeError only when the race timing "
            "cooperates",
        )
    for line in sorted(yield_bare):
        yield Finding(
            rule_id, mod.path, line, 0,
            "bare `# graftlint: lock-free` escape — an intentional "
            "unlocked access must say why it is benign: "
            "`# graftlint: lock-free — <justification>`",
        )
    # acquisition-order inversions: the direction introduced later (by
    # first-occurrence line) is the inversion and carries the findings
    for (a, b), nodes in sorted(report.pairs.items()):
        if (b, a) not in report.pairs or a >= b:
            continue
        fwd = min(n.lineno for n in nodes)
        rev = min(n.lineno for n in report.pairs[(b, a)])
        bad = nodes if fwd > rev else report.pairs[(b, a)]
        first, second = (b, a) if fwd > rev else (a, b)
        for n in bad:
            yield Finding(
                rule_id, mod.path, n.lineno, n.col_offset,
                f"acquires 'self.{second}' while holding "
                f"'self.{first}', but the class elsewhere acquires them "
                "in the opposite order — the ABBA deadlock shape; pick "
                "one acquisition order",
            )


def _module_contract_findings(mod):
    doc = ast.get_docstring(mod.tree) or ""
    if not _CONTRACT.search(doc):
        return
    has_lock = any(
        isinstance(n, ast.Call) and mod.canonical(n.func) in _LOCK_CTORS
        for n in ast.walk(mod.tree)
    )
    if has_lock:
        return
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Call) and \
                mod.canonical(n.func) in _THREAD_CTORS:
            yield Finding(
                rule_id, mod.path, n.lineno, n.col_offset,
                "module docstring declares a Concurrency: contract and "
                "this starts a thread, but no lock is constructed "
                "anywhere in the module — the documented discipline is "
                "not implemented",
            )


def _module_reports(mod):
    """Per-class lock reports, memoized on the ModuleInfo (the lock-scope
    cache: the GL00 audit re-runs rule families, and re-walking every
    method body would double the full-lint wall time)."""
    cached = getattr(mod, "_lock_reports", None)
    if cached is not None:
        return cached
    reports = [
        _analyze_class(mod, node)
        for node in ast.walk(mod.tree) if isinstance(node, ast.ClassDef)
    ]
    mod._lock_reports = reports
    return reports


def check(project):
    for mod in project.modules:
        yield from _module_contract_findings(mod)
        for report in _module_reports(mod):
            if report.locks:
                yield from _class_findings(mod, report)
