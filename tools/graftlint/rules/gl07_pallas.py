"""GL07 — Pallas kernel hygiene at ``pallas_call`` sites.

The Mosaic failure modes this rule front-runs all share one property: they
surface only at *hardware compile time* (or worse, as silent padding), so
CPU CI never sees them. ``ops/pallas_hist.py`` and ``ops/wide_hist.py`` are
the live targets; their dims are mostly symbolic (row_tile, S*C). Symbolic
dims resolve through :mod:`tools.graftlint.symdim` — interval/divisibility
facts recovered from single-assignment bindings, ``*round_up`` calls, and
``if <cmp>: raise`` guards — and every check fires only on conclusions the
facts *entail* (a lower-bound working set already over budget, an
upper-bound coverage already short). A dim with no provable fact stays
silent, the same conservative stance as the rest of graftlint; a scope
that already runtime-gates itself with ``if not fits_vmem(...): raise``
suppresses the static VMEM bound (the runtime check subsumes it).

1. **Dtype-aware sublane tiling.** GL04 checks the dtype-agnostic f32
   floor — last dim % 128, second-to-last % 8. But packed dtypes tile
   taller: bf16 needs sublane multiples of 16, int8/fp8 of 32. When the
   ``out_shape``'s ``ShapeDtypeStruct`` names a literal dtype, out-spec
   block dims are held to the real multiple. Only values that PASS the
   GL04 floor are flagged here (no double findings).
2. **Grid×block bounds coverage.** For literal grids, literal block dims,
   literal array dims and ``lambda i, ...: (...)`` index maps made of grid
   names and constants: every array dim must be covered — a grid axis
   mapping a block dim must satisfy ``grid[j] * block[d] >= dim``; an
   unmapped (constant-indexed) dim needs ``block[d] >= dim``. An
   under-covered output comes back partially uninitialized.
3. **Static VMEM budget.** When every block dim of every spec is literal,
   the per-grid-step working set (sum of block sizes × dtype width, out
   counted double for Mosaic's double buffering) is estimated against a
   conservative budget; exceeding it is the one error interpret-mode
   tests cannot catch.

``grid_spec=pltpu.PrefetchScalarGridSpec(...)`` resolves through a local
single-assignment binding (the ``wide_hist`` idiom).
"""

from __future__ import annotations

import ast
import math

from tools.graftlint import astutil, symdim
from tools.graftlint.engine import PALLAS_CALL, Finding

rule_id = "GL07"

# conservative per-core VMEM budget for one grid step's working set —
# mirrors ops/pallas_hist._VMEM_BUDGET_BYTES (~16 MB physical, headroom
# for Mosaic's own spills)
VMEM_BUDGET_BYTES = 10 << 20

# dtype suffix -> (itemsize bytes, required sublane multiple)
_DTYPES = {
    "float64": (8, 8), "int64": (8, 8),
    "float32": (4, 8), "int32": (4, 8), "uint32": (4, 8),
    "bfloat16": (2, 16), "float16": (2, 16), "int16": (2, 16),
    "uint16": (2, 16),
    "int8": (1, 32), "uint8": (1, 32), "float8_e4m3fn": (1, 32),
    "float8_e5m2": (1, 32),
    "bool_": (1, 32), "bool": (1, 32),
}


def _dtype_info(mod, node):
    """(itemsize, sublane_multiple) for a dtype expression, or None."""
    name = mod.canonical(node)
    if name is None:
        s = astutil.str_const(node)
        name = s if s is not None else None
    if name is None:
        return None
    return _DTYPES.get(name.rsplit(".", 1)[-1])


def _local_call_binding(scope, name_node):
    """The single ``v = SomeCall(...)`` assignment binding a Name, if any."""
    if not isinstance(name_node, ast.Name) or scope is None:
        return None
    hit = None
    for stmt in astutil.own_statements(scope.node):
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == name_node.id
                and isinstance(stmt.value, ast.Call)):
            if hit is not None:
                return None  # multiple assignments: don't guess
            hit = stmt.value
    return hit


def _spec_list(node):
    """BlockSpec call nodes inside an in_specs/out_specs expression."""
    if node is None:
        return []
    if isinstance(node, (ast.Tuple, ast.List)):
        items = node.elts
    else:
        items = [node]
    out = []
    for item in items:
        if isinstance(item, ast.Call):
            out.append(item)
    return out


def _block_dims(spec_call):
    """(shape_node, literal dims list-with-Nones) of a BlockSpec call."""
    shape = spec_call.args[0] if spec_call.args else astutil.keyword_arg(
        spec_call, "block_shape"
    )
    if not isinstance(shape, (ast.Tuple, ast.List)):
        return None, None
    dims = []
    for el in shape.elts:
        v = astutil.int_tuple(el)
        dims.append(v[0] if v is not None and len(v) == 1 else None)
    return shape, dims


def _index_map(spec_call):
    """Per-dim mapping of a literal ``lambda g0, g1, ...: (...)`` index map:
    each entry is ('grid', axis) | ('const', value) | None (unresolvable).
    """
    lam = None
    if len(spec_call.args) >= 2 and isinstance(spec_call.args[1], ast.Lambda):
        lam = spec_call.args[1]
    else:
        kw = astutil.keyword_arg(spec_call, "index_map")
        if isinstance(kw, ast.Lambda):
            lam = kw
    if lam is None:
        return None
    params = [a.arg for a in lam.args.args]
    body = lam.body
    elts = body.elts if isinstance(body, (ast.Tuple, ast.List)) else [body]
    out = []
    for el in elts:
        if isinstance(el, ast.Name) and el.id in params:
            out.append(("grid", params.index(el.id)))
        elif (v := astutil.int_tuple(el)) is not None and len(v) == 1:
            out.append(("const", v[0]))
        else:
            out.append(None)
    return out


def _shape_dtype(mod, scope, node):
    """(shape node, literal dims, dtype info) from jax.ShapeDtypeStruct."""
    if not isinstance(node, ast.Call):
        return None, None, None
    name = mod.canonical(node.func)
    if name is None or name.rsplit(".", 1)[-1] != "ShapeDtypeStruct":
        return None, None, None
    shape = node.args[0] if node.args else astutil.keyword_arg(node, "shape")
    dtype = (node.args[1] if len(node.args) > 1
             else astutil.keyword_arg(node, "dtype"))
    dims = None
    if not isinstance(shape, (ast.Tuple, ast.List)):
        shape = None
    else:
        dims = []
        for el in shape.elts:
            v = astutil.int_tuple(el)
            dims.append(v[0] if v is not None and len(v) == 1 else None)
    return shape, dims, (
        _dtype_info(mod, dtype) if dtype is not None else None
    )


def _dim_facts(mod, shape, dims, facts):
    """Per-dim Facts: literal dims exact, symbolic dims evaluated."""
    return [
        symdim.exact(lit) if lit is not None
        else symdim.eval_expr(mod, el, facts)
        for el, lit in zip(shape.elts, dims)
    ]


def check(project):
    for mod in project.modules:
        for scope, call in project._walk_calls(mod):
            if mod.canonical(call.func) not in PALLAS_CALL:
                continue
            yield from _check_site(project, mod, scope, call)


def _gather(mod, scope, call):
    """(grid, in_spec calls, out_spec calls, out shape/dims/dtype)."""
    grid_node = astutil.keyword_arg(call, "grid")
    in_specs = astutil.keyword_arg(call, "in_specs")
    out_specs = astutil.keyword_arg(call, "out_specs")
    gs = astutil.keyword_arg(call, "grid_spec")
    if gs is not None:
        if isinstance(gs, ast.Name):
            gs = _local_call_binding(scope, gs)
        if isinstance(gs, ast.Call):
            grid_node = grid_node or astutil.keyword_arg(gs, "grid")
            in_specs = in_specs or astutil.keyword_arg(gs, "in_specs")
            out_specs = out_specs or astutil.keyword_arg(gs, "out_specs")
    grid = astutil.int_tuple(grid_node) if grid_node is not None else None
    out_shape = astutil.keyword_arg(call, "out_shape")
    out_node, out_dims, out_dt = _shape_dtype(mod, scope, out_shape)
    return (grid, _spec_list(in_specs), _spec_list(out_specs),
            out_node, out_dims, out_dt)


def _check_site(project, mod, scope, call):
    grid, in_specs, out_specs, out_node, out_dims, out_dt = _gather(
        mod, scope, call
    )
    facts = symdim.scope_facts(mod, scope) if scope is not None else {}

    # 1. dtype-aware sublane tiling on out specs (dtype provable there).
    # Symbolic dims participate only with an exact fact — divisibility
    # alone cannot prove a violation (a multiple of 8 may still be a
    # multiple of 16).
    if out_dt is not None:
        _itemsize, sublane = out_dt
        for spec in out_specs:
            shape, dims = _block_dims(spec)
            if not dims or len(dims) < 2:
                continue
            v = _dim_facts(mod, shape, dims, facts)[-2].exact_value
            if (v is not None and v != 1 and v % 8 == 0 and v % sublane):
                yield Finding(
                    rule_id, mod.path, spec.lineno, spec.col_offset,
                    f"BlockSpec sublane block dim {v} breaks the "
                    f"{sublane}-row tiling this out dtype needs "
                    "(packed dtypes tile taller than f32's 8)",
                )

    # 2. grid x block coverage of the out array: flag when the MOST the
    # grid can cover (upper bound) is short of the LEAST the array can be
    # (lower bound) — exact facts reduce this to the literal check
    if grid is not None and out_node is not None:
        afacts = _dim_facts(mod, out_node, out_dims, facts)
        for spec in out_specs:
            shape, dims = _block_dims(spec)
            imap = _index_map(spec)
            if not dims or imap is None or len(dims) != len(imap):
                continue
            if len(dims) != len(afacts):
                continue
            bfacts = _dim_facts(mod, shape, dims, facts)
            for d, (bf, entry, af) in enumerate(
                zip(bfacts, imap, afacts)
            ):
                if bf.hi is None or af.lo is None or entry is None:
                    continue
                if entry[0] == "grid":
                    j = entry[1]
                    if j >= len(grid):
                        continue
                    covered = grid[j] * bf.hi
                else:
                    # a constant index writes exactly ONE block; anything
                    # at a nonzero offset leaves the prefix uncovered
                    covered = bf.hi if entry[1] == 0 else 0
                if covered < af.lo:
                    how = ("only" if bf.exact_value is not None
                           and af.exact_value is not None else "at most")
                    yield Finding(
                        rule_id, mod.path, spec.lineno, spec.col_offset,
                        f"grid x block covers {how} {covered} of "
                        f"{af.lo} along out dim {d} — the uncovered "
                        "tail comes back uninitialized",
                    )

    # 3. static VMEM budget: sum each block's LOWER-bound size (symbolic
    # dims contribute their provable lo, or 1); if even that floor blows
    # the budget the site cannot fit on hardware. A fits_vmem raise-guard
    # in scope means the site runtime-gates itself — stay quiet.
    specs = [(s, False) for s in in_specs] + [(s, True) for s in out_specs]
    if not specs or symdim.has_vmem_guard(mod, scope):
        return
    total = 0
    all_exact = True
    for spec, is_out in specs:
        shape, dims = _block_dims(spec)
        if not dims:
            return  # no literal block tuple: rank unknown, no estimate
        fs = _dim_facts(mod, shape, dims, facts)
        cells = math.prod(max(f.lo or 1, 1) for f in fs)
        all_exact = all_exact and all(
            f.exact_value is not None for f in fs
        )
        itemsize = (out_dt[0] if is_out and out_dt is not None else 4)
        total += cells * itemsize * (2 if is_out else 1)  # out dbl-buffers
    if total > VMEM_BUDGET_BYTES:
        kind = "estimate" if all_exact else "lower bound"
        yield Finding(
            rule_id, mod.path, call.lineno, call.col_offset,
            f"static VMEM {kind} {total >> 20} MiB exceeds the "
            f"{VMEM_BUDGET_BYTES >> 20} MiB per-step budget — Mosaic "
            "will fail allocation on hardware (shrink blocks or grid "
            "the dominant axis)",
        )
