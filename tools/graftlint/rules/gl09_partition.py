"""GL09 — partition-spec conformance: placements come from the one table.

``parallel/partition.py`` is the project's placement authority: a single
first-match rule table (``PARTITION_RULES``) that every engine resolves
through ``spec_for`` / ``in_specs_for`` / ``out_specs_for``. An ad-hoc
``PartitionSpec(...)`` literal in engine code forks that authority — the
table changes, the literal doesn't, and the divergence ships silently
(CPU meshes trim every axis away, so tests pass either way). This rule
holds the package to the contract statically:

1. **Construction siting.** ``jax.sharding.PartitionSpec`` may only be
   constructed in modules carrying the ``# graftlint: partition-table``
   directive (the table itself and the axis-generic mesh helpers).
   Anywhere else, placement must be *derived*, not spelled.
2. **Name conformance.** A literal name passed to ``spec_for`` /
   ``in_specs_for`` / ``out_specs_for`` must match a non-catch-all
   pattern of some module-level ``PARTITION_RULES`` table in the lint
   set. A name that only the ``.*`` catch-all accepts resolves to
   replicate — which is exactly how a placement typo (``"x_binnedd"``)
   ships as a silent full-copy. ``(name, 0)`` scalar pairs are the
   sanctioned replicate spelling and are skipped; non-literal name lists
   resolve at runtime and are never guessed.
3. **Axis conformance.** Inside the sanctioned table modules, every
   axis name a ``PartitionSpec(...)`` spells — literally or through a
   module constant (``DATA_AXIS``) — must be declared by the lint set's
   static mesh metadata (``*_AXIS`` constants and literal ``Mesh``
   axis tuples, the same set GL03 checks collective axes against). A
   spec naming an axis no mesh declares shards nothing: the name
   silently trims away on every real mesh and the placement ships as
   replicate. Skipped when no mesh metadata is in the lint set.
"""

from __future__ import annotations

import ast
import re

from tools.graftlint import astutil
from tools.graftlint.engine import Finding

rule_id = "GL09"

_SPEC_FNS = ("spec_for", "in_specs_for", "out_specs_for")


def _is_table_module(mod) -> bool:
    return any(
        kind == "partition-table"
        for kind, _vals in mod.directive_lines.values()
    )


def _table_patterns(project) -> list:
    """Compiled non-catch-all patterns of every module-level
    ``PARTITION_RULES`` table in the lint set."""
    pats = []
    for mod in project.modules:
        for stmt in mod.tree.body:
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "PARTITION_RULES"
                    and isinstance(stmt.value, (ast.List, ast.Tuple))):
                continue
            for el in stmt.value.elts:
                if isinstance(el, (ast.Tuple, ast.List)) and el.elts:
                    s = astutil.str_const(el.elts[0])
                    if s is not None and s != ".*":
                        try:
                            pats.append(re.compile(s))
                        except re.error:
                            continue
    return pats


def _literal_names(call):
    """(name, node) pairs this table call resolves statically.

    ``spec_for("name", ...)`` checks its first argument;
    ``in_specs_for(mesh, (...))`` / ``out_specs_for`` check every plain
    string in a literal name tuple — ``(name, 0)`` pairs force the scalar
    ``P()`` by contract and are skipped.
    """
    short = (astutil.dotted_name(call.func) or "").rsplit(".", 1)[-1]
    if short == "spec_for":
        if call.args:
            s = astutil.str_const(call.args[0])
            if s is not None:
                yield s, call.args[0]
        return
    names = (call.args[1] if len(call.args) > 1
             else astutil.keyword_arg(call, "names"))
    if not isinstance(names, (ast.Tuple, ast.List)):
        return
    for el in names.elts:
        s = astutil.str_const(el)
        if s is not None:
            yield s, el


def _check_spec_axes(project, mod, call):
    """Axis-conformance leg: every axis name this spec resolves statically
    must be declared by the lint set's mesh metadata. A tuple element
    shards one dim over several axes — each member is checked; names that
    resolve only at runtime are never guessed."""
    operands = list(call.args) + [kw.value for kw in call.keywords]
    for el in operands:
        members = el.elts if isinstance(el, (ast.Tuple, ast.List)) else [el]
        for member in members:
            s = project.resolve_str(mod, member)
            if s is not None and s not in project.mesh_axes:
                yield Finding(
                    rule_id, mod.path, member.lineno, member.col_offset,
                    f"PartitionSpec axis '{s}' is not declared by any "
                    "static mesh metadata in the lint set "
                    f"({', '.join(sorted(project.mesh_axes))}) — an "
                    "undeclared axis trims away on every real mesh, so "
                    "this spec silently replicates",
                )


def check(project):
    patterns = _table_patterns(project)
    for mod in project.modules:
        table_mod = _is_table_module(mod)
        for scope, call in project._walk_calls(mod):
            name = mod.canonical(call.func)
            if name is None:
                continue
            if name.endswith(".PartitionSpec"):
                if not table_mod:
                    yield Finding(
                        rule_id, mod.path, call.lineno, call.col_offset,
                        "ad-hoc PartitionSpec(...) outside the partition "
                        "table — derive the placement via partition."
                        "spec_for/in_specs_for/out_specs_for so the rule "
                        "table stays the one authority",
                    )
                elif project.mesh_axes:
                    yield from _check_spec_axes(project, mod, call)
                continue
            if not patterns:
                continue  # no table in the lint set: nothing to conform to
            short = name.rsplit(".", 1)[-1]
            if short not in _SPEC_FNS or not name.endswith(
                f"partition.{short}"
            ):
                continue
            for s, node in _literal_names(call):
                if not any(p.match(s) for p in patterns):
                    yield Finding(
                        rule_id, mod.path, node.lineno, node.col_offset,
                        f"placement name '{s}' matches no PARTITION_RULES "
                        "pattern — it falls to the catch-all replicate "
                        "rule, which is how placement typos ship; add a "
                        "table entry or fix the name",
                    )
