"""GL08 — donation-after-use: a donated buffer is dead after the call.

``donate_argnums``/``donate_argnames`` hands the input buffer to XLA for
reuse; on TPU the caller's array object now aliases memory the program is
free to overwrite. Reading it afterwards is not an error Python can see —
it is a silent garbage read (CPU/interpret runs usually still pass, which
is exactly why a static rule exists). GL05 asks fused-state jits to donate;
this rule polices the other side of that contract at every call site of a
donating callable.

Donating callables are recognized three ways:

- local/module bindings: ``step = jax.jit(f, donate_argnums=(0,))``
  (including ``jax.jit(sharded)`` where ``sharded`` wraps via shard_map —
  donation indices are positional, so no unwrapping is needed),
- factory returns: a function whose ``return`` is such a ``jax.jit`` call
  marks every ``fn = factory(...)`` binding in callers — the
  ``_make_fused_fn`` / ``make_update_fn`` idiom,
- decorator form: ``@jax.jit(donate_argnames=...)`` /
  ``@partial(jax.jit, donate_argnums=...)`` on a def, checked at direct
  call sites (argnames map through the def's positional parameters).

A call site is clean when the donated argument is a fresh expression, is
rebound by the call's own assignment (``nid = step(nid, ...)`` — the level
loop's canonical shape), or is re-Stored before any later Load. Analysis
is PATH-SENSITIVE at the statement level: a forward scan walks from the
call site outward through its enclosing blocks, and every ``if`` forks the
{donated, rebound} state per branch — a read on the branch that kept the
dead buffer fires on that branch, while a read behind a rebind (or on a
sibling path that never made the call) stays silent. Branches ending in
``return``/``raise`` terminate their path and do not pollute the join; a
join stays *donated* if any surviving path is. Calls inside a loop
additionally require the donated name to be Stored somewhere in that loop
body — otherwise iteration 2 re-donates a buffer iteration 1 already
consumed.
"""

from __future__ import annotations

import ast

from tools.graftlint import astutil
from tools.graftlint.engine import JIT_WRAPPERS, PARTIAL, Finding

rule_id = "GL08"

_DONATE_KW = ("donate_argnums", "donate_argnames")


def _donated_positions(project, mod, scope, call):
    """Donated positional indices of a ``jax.jit(...)`` call, or None.

    ``donate_argnames`` resolves through the wrapped function's positional
    parameter list when the target is resolvable; an unresolvable names
    form is skipped (never guessed).
    """
    nums = astutil.keyword_arg(call, "donate_argnums")
    if nums is not None:
        t = astutil.int_tuple(nums)
        return frozenset(t) if t else None
    names = astutil.keyword_arg(call, "donate_argnames")
    if names is None:
        return None
    strs = astutil.str_tuple(names)
    if not strs or not call.args:
        return None
    target = project.resolve_function(mod, scope, call.args[0])
    if target is None:
        return None
    a = target.node.args
    positional = [p.arg for p in a.posonlyargs + a.args]
    hits = frozenset(
        positional.index(s) for s in strs if s in positional
    )
    return hits or None


def _decorator_donations(project, mod, fn):
    """Donated positions declared by a @jit decorator on ``fn``."""
    for dec in fn.node.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        name = mod.canonical(dec.func)
        is_partial_jit = (
            name in PARTIAL and dec.args
            and mod.canonical(dec.args[0]) in JIT_WRAPPERS
        )
        if name not in JIT_WRAPPERS and not is_partial_jit:
            continue
        nums = astutil.keyword_arg(dec, "donate_argnums")
        if nums is not None:
            t = astutil.int_tuple(nums)
            if t:
                return frozenset(t)
        names = astutil.keyword_arg(dec, "donate_argnames")
        if names is not None:
            strs = astutil.str_tuple(names)
            if strs:
                a = fn.node.args
                positional = [p.arg for p in a.posonlyargs + a.args]
                hits = frozenset(
                    positional.index(s) for s in strs if s in positional
                )
                if hits:
                    return hits
    return None


def _collect_donors(project):
    """Maps the three donating-callable spellings across the project.

    Returns (bindings, factories, decorated):
      bindings:  (module path, scope qualname|None, varname) -> positions
      factories: id(FuncInfo) -> positions (functions returning a donating
                 jit)
      decorated: id(FuncInfo) -> positions
    """
    bindings: dict = {}
    factories: dict = {}
    decorated: dict = {}
    for mod in project.modules:
        for fn in mod.functions.values():
            pos = _decorator_donations(project, mod, fn)
            if pos:
                decorated[id(fn)] = (fn, pos)
            for stmt in astutil.own_statements(fn.node):
                if (isinstance(stmt, ast.Return)
                        and isinstance(stmt.value, ast.Call)
                        and mod.canonical(stmt.value.func) in JIT_WRAPPERS):
                    pos = _donated_positions(project, mod, fn, stmt.value)
                    if pos:
                        factories[id(fn)] = pos
        for scope, call in project._walk_calls(mod):
            if mod.canonical(call.func) not in JIT_WRAPPERS:
                continue
            pos = _donated_positions(project, mod, scope, call)
            if pos is None:
                continue
            parent = _assign_target(scope, call)
            if parent is not None:
                key = (mod.path, scope.qualname if scope else None, parent)
                bindings[key] = pos
    return bindings, factories, decorated


def _assign_target(scope, call):
    """Varname when ``call`` is the whole RHS of a single-Name assignment
    in ``scope`` (module level included via scope None callers)."""
    if scope is None:
        return None
    for stmt in astutil.own_statements(scope.node):
        if (isinstance(stmt, ast.Assign) and stmt.value is call
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            return stmt.targets[0].id
    return None


class _Caller:
    """Per-caller AST facts: statement parents, loops, name occurrences."""

    def __init__(self, fn):
        self.fn = fn
        self.parent: dict = {}
        for node in ast.walk(fn.node):
            for child in ast.iter_child_nodes(node):
                self.parent[id(child)] = node

    def enclosing_loops(self, node):
        out = []
        cur = self.parent.get(id(node))
        while cur is not None and cur is not self.fn.node:
            if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                out.append(cur)
            cur = self.parent.get(id(cur))
        return out

    def assign_of(self, call):
        cur = self.parent.get(id(call))
        if isinstance(cur, ast.Assign) and cur.value is call:
            return cur
        return None

    def is_metadata_read(self, name_node):
        """Whether a Load only touches aval metadata, which survives
        donation: ``donated.shape`` / ``donated.ndim`` / ``len(donated)``
        read the retained abstract value, never the released buffer."""
        cur = self.parent.get(id(name_node))
        if isinstance(cur, ast.Attribute) and (
            cur.attr in astutil.STATIC_ATTRS
        ):
            return True
        if isinstance(cur, ast.Call) and name_node in cur.args:
            return astutil.dotted_name(cur.func) in astutil.STATIC_CALLS
        return False


class _PathScan:
    """Forward scan of ONE donated name from its call site, per path.

    Two states per path: DONATED (the name still aliases the released
    buffer) and REBOUND (a Store gave it a fresh value). ``if`` statements
    recurse per branch with a copy of the state; a branch that terminates
    (``return``/``raise``) drops out of the join, and the join is DONATED
    iff any surviving branch is. The first garbage read lands in
    ``finding_at`` and ends the scan — one finding per (call, name), like
    the rest of graftlint.
    """

    DONATED, REBOUND = 0, 1

    def __init__(self, caller, var, call):
        self.caller = caller
        self.var = var
        self.skip = {id(n) for n in ast.walk(call)}
        self.finding_at = None  # (line, col) of the first garbage read

    def events(self, node):
        """``var`` Name nodes under ``node``, outside the call subtree."""
        if node is None:
            return []
        return [
            n for n in ast.walk(node)
            if isinstance(n, ast.Name) and n.id == self.var
            and id(n) not in self.skip
        ]

    def feed(self, nodes, state):
        """Apply Name events in source order to one path's state."""
        for n in sorted(nodes, key=lambda n: (n.lineno, n.col_offset)):
            if self.finding_at is not None:
                return state
            if isinstance(n.ctx, ast.Load):
                if state == self.DONATED and not \
                        self.caller.is_metadata_read(n):
                    self.finding_at = (n.lineno, n.col_offset)
            else:  # Store (fresh binding) or Del (name gone either way)
                state = self.REBOUND
        return state

    def scan_block(self, stmts, state):
        """(state, terminated) after running a statement list."""
        for stmt in stmts:
            state, term = self.scan_stmt(stmt, state)
            if term:
                return state, True
            if self.finding_at is not None or state == self.REBOUND:
                return state, False  # nothing later can change the verdict
        return state, False

    def scan_stmt(self, stmt, state):
        if isinstance(stmt, (ast.Return, ast.Raise)):
            self.feed(self.events(stmt), state)
            return state, True
        if isinstance(stmt, ast.If):
            state = self.feed(self.events(stmt.test), state)
            s1, t1 = self.scan_block(stmt.body, state)
            s2, t2 = self.scan_block(stmt.orelse, state)
            if t1 and t2:
                return state, True
            if t1:
                return s2, False
            if t2:
                return s1, False
            joined = (self.DONATED if self.DONATED in (s1, s2)
                      else self.REBOUND)
            return joined, False
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            if isinstance(stmt, ast.While):
                state = self.feed(self.events(stmt.test), state)
                entry = state
            else:
                state = self.feed(self.events(stmt.iter), state)
                entry = state
                state = self.feed(self.events(stmt.target), state)
            body_state, _term = self.scan_block(
                stmt.body + stmt.orelse, state
            )
            # the zero-iteration path keeps the entry state: a rebind
            # inside the body does not sanitize the fall-through
            joined = (self.DONATED if self.DONATED in (entry, body_state)
                      else self.REBOUND)
            return joined, False
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                state = self.feed(self.events(item.context_expr), state)
                state = self.feed(self.events(item.optional_vars), state)
            return self.scan_block(stmt.body, state)
        if isinstance(stmt, ast.Assign):
            state = self.feed(self.events(stmt.value), state)
            tgt = [n for t in stmt.targets for n in self.events(t)]
            return self.feed(tgt, state), False
        if isinstance(stmt, ast.AugAssign):
            # read-modify-write: the READ hits the dead buffer first
            state = self.feed(self.events(stmt.value), state)
            return self.feed(self.events(stmt.target), state), False
        if isinstance(stmt, ast.AnnAssign):
            state = self.feed(self.events(stmt.value), state)
            return self.feed(self.events(stmt.target), state), False
        # Expr / Assert / Try / nested defs / Delete / ...: positional
        # feed of every contained event (conservative, like the old rule)
        return self.feed(self.events(stmt), state), False


def _blocks_up(caller, fn, call):
    """(following statements) per enclosing block, innermost first.

    Walks from the statement containing ``call`` up to the function body,
    yielding at each level the statements that execute AFTER the current
    one in its block — the path the donated value actually flows along.
    """
    node = call
    while not isinstance(node, ast.stmt):
        node = caller.parent[id(node)]
    stmt = node
    first = True
    while stmt is not fn.node:
        parent = caller.parent.get(id(stmt))
        if parent is None:
            break
        for _field, val in ast.iter_fields(parent):
            if isinstance(val, list) and stmt in val:
                yield stmt, val[val.index(stmt) + 1:], parent, first
                first = False
                break
        node = parent
        while not isinstance(node, ast.stmt) and node is not fn.node:
            node = caller.parent.get(id(node))
            if node is None:
                return
        stmt = node


def check(project):
    bindings, factories, decorated = _collect_donors(project)
    for mod in project.modules:
        for fn in mod.functions.values():
            if fn.is_lambda:
                continue
            caller = None
            for node in astutil.own_nodes(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                pos = _site_positions(
                    project, mod, fn, node, bindings, factories, decorated
                )
                if not pos:
                    continue
                if caller is None:
                    caller = _Caller(fn)
                yield from _check_call(mod, fn, caller, node, pos)


def _site_positions(project, mod, fn, call, bindings, factories, decorated):
    """Donated positions if ``call`` invokes a donating callable."""
    # direct call of a decorated donating def
    target = project.resolve_function(mod, fn, call.func)
    if target is not None and id(target) in decorated:
        return decorated[id(target)][1]
    # call through a local binding of jax.jit(...) or a donating factory
    if isinstance(call.func, ast.Name):
        cur = fn
        while True:
            key = (mod.path, cur.qualname if cur else None, call.func.id)
            if key in bindings:
                return bindings[key]
            if cur is None:
                break
            cur = cur.parent
        # `v = factory(...)` in this scope?
        src = _local_factory(project, mod, fn, call.func.id)
        if src is not None and id(src) in factories:
            return factories[id(src)]
    return None


def _local_factory(project, mod, scope, varname):
    """FuncInfo of F when ``varname = F(...)`` binds in ``scope`` (single
    assignment — the lru_cache factory idiom every builder uses)."""
    hit = None
    cur = scope
    while cur is not None and hit is None:
        for stmt in astutil.own_statements(cur.node):
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == varname
                    and isinstance(stmt.value, ast.Call)):
                hit = project.resolve_function(mod, cur, stmt.value.func)
        cur = cur.parent
    return hit


def _check_call(mod, fn, caller, call, positions):
    assign = caller.assign_of(call)
    rebound = set()
    if assign is not None:
        for t in assign.targets:
            rebound.update(astutil.target_names(t))
    for p in sorted(positions):
        if p >= len(call.args):
            continue
        arg = call.args[p]
        if not isinstance(arg, ast.Name):
            continue  # fresh expressions donate safely
        var = arg.id
        if var in rebound:
            continue  # nid = step(nid, ...): the canonical loop shape
        scan = _PathScan(caller, var, call)
        state = scan.DONATED
        call_pos = (call.lineno, call.col_offset)
        term = False
        for stmt, following, _parent, first in _blocks_up(
            caller, fn, call
        ):
            if first:
                # the call's own statement may read the name after the
                # call expression (``step(buf) + buf``): positional feed
                # of the tail, call subtree excluded
                tail = [
                    n for n in scan.events(stmt)
                    if (n.lineno, n.col_offset) > call_pos
                ]
                state = scan.feed(tail, state)
            if (term or scan.finding_at is not None
                    or state == scan.REBOUND):
                break
            state, term = scan.scan_block(following, state)
        if scan.finding_at is not None:
            yield Finding(
                rule_id, mod.path, scan.finding_at[0], scan.finding_at[1],
                f"'{var}' is read after being donated to "
                f"'{_callee_label(call)}' at line {call.lineno} — a "
                "donated buffer aliases memory XLA reuses; on TPU this "
                "is a silent garbage read",
            )
        loops = caller.enclosing_loops(call)
        if loops and not _stored_in(loops[0], var):
            yield Finding(
                rule_id, mod.path, call.lineno, call.col_offset,
                f"'{var}' is donated inside a loop but never rebound in "
                "the loop body — iteration 2 re-donates the buffer "
                "iteration 1 already consumed",
            )


def _stored_in(loop, var):
    return any(
        isinstance(n, ast.Name) and n.id == var
        and isinstance(n.ctx, ast.Store)
        for n in ast.walk(loop)
    )


def _callee_label(call):
    return astutil.dotted_name(call.func) or "<callable>"
