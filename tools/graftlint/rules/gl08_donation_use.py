"""GL08 — donation-after-use: a donated buffer is dead after the call.

``donate_argnums``/``donate_argnames`` hands the input buffer to XLA for
reuse; on TPU the caller's array object now aliases memory the program is
free to overwrite. Reading it afterwards is not an error Python can see —
it is a silent garbage read (CPU/interpret runs usually still pass, which
is exactly why a static rule exists). GL05 asks fused-state jits to donate;
this rule polices the other side of that contract at every call site of a
donating callable.

Donating callables are recognized three ways:

- local/module bindings: ``step = jax.jit(f, donate_argnums=(0,))``
  (including ``jax.jit(sharded)`` where ``sharded`` wraps via shard_map —
  donation indices are positional, so no unwrapping is needed),
- factory returns: a function whose ``return`` is such a ``jax.jit`` call
  marks every ``fn = factory(...)`` binding in callers — the
  ``_make_fused_fn`` / ``make_update_fn`` idiom,
- decorator form: ``@jax.jit(donate_argnames=...)`` /
  ``@partial(jax.jit, donate_argnums=...)`` on a def, checked at direct
  call sites (argnames map through the def's positional parameters).

A call site is clean when the donated argument is a fresh expression, is
rebound by the call's own assignment (``nid = step(nid, ...)`` — the level
loop's canonical shape), or is re-Stored before any later Load. Analysis
is per-caller and line-ordered (flow-insensitive, like the dataflow core):
a Load after the call in ANY syntactic path fires. Calls inside a loop
additionally require the donated name to be Stored somewhere in that loop
body — otherwise iteration 2 re-donates a buffer iteration 1 already
consumed.
"""

from __future__ import annotations

import ast

from tools.graftlint import astutil
from tools.graftlint.engine import JIT_WRAPPERS, PARTIAL, Finding

rule_id = "GL08"

_DONATE_KW = ("donate_argnums", "donate_argnames")


def _donated_positions(project, mod, scope, call):
    """Donated positional indices of a ``jax.jit(...)`` call, or None.

    ``donate_argnames`` resolves through the wrapped function's positional
    parameter list when the target is resolvable; an unresolvable names
    form is skipped (never guessed).
    """
    nums = astutil.keyword_arg(call, "donate_argnums")
    if nums is not None:
        t = astutil.int_tuple(nums)
        return frozenset(t) if t else None
    names = astutil.keyword_arg(call, "donate_argnames")
    if names is None:
        return None
    strs = astutil.str_tuple(names)
    if not strs or not call.args:
        return None
    target = project.resolve_function(mod, scope, call.args[0])
    if target is None:
        return None
    a = target.node.args
    positional = [p.arg for p in a.posonlyargs + a.args]
    hits = frozenset(
        positional.index(s) for s in strs if s in positional
    )
    return hits or None


def _decorator_donations(project, mod, fn):
    """Donated positions declared by a @jit decorator on ``fn``."""
    for dec in fn.node.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        name = mod.canonical(dec.func)
        is_partial_jit = (
            name in PARTIAL and dec.args
            and mod.canonical(dec.args[0]) in JIT_WRAPPERS
        )
        if name not in JIT_WRAPPERS and not is_partial_jit:
            continue
        nums = astutil.keyword_arg(dec, "donate_argnums")
        if nums is not None:
            t = astutil.int_tuple(nums)
            if t:
                return frozenset(t)
        names = astutil.keyword_arg(dec, "donate_argnames")
        if names is not None:
            strs = astutil.str_tuple(names)
            if strs:
                a = fn.node.args
                positional = [p.arg for p in a.posonlyargs + a.args]
                hits = frozenset(
                    positional.index(s) for s in strs if s in positional
                )
                if hits:
                    return hits
    return None


def _collect_donors(project):
    """Maps the three donating-callable spellings across the project.

    Returns (bindings, factories, decorated):
      bindings:  (module path, scope qualname|None, varname) -> positions
      factories: id(FuncInfo) -> positions (functions returning a donating
                 jit)
      decorated: id(FuncInfo) -> positions
    """
    bindings: dict = {}
    factories: dict = {}
    decorated: dict = {}
    for mod in project.modules:
        for fn in mod.functions.values():
            pos = _decorator_donations(project, mod, fn)
            if pos:
                decorated[id(fn)] = (fn, pos)
            for stmt in astutil.own_statements(fn.node):
                if (isinstance(stmt, ast.Return)
                        and isinstance(stmt.value, ast.Call)
                        and mod.canonical(stmt.value.func) in JIT_WRAPPERS):
                    pos = _donated_positions(project, mod, fn, stmt.value)
                    if pos:
                        factories[id(fn)] = pos
        for scope, call in project._walk_calls(mod):
            if mod.canonical(call.func) not in JIT_WRAPPERS:
                continue
            pos = _donated_positions(project, mod, scope, call)
            if pos is None:
                continue
            parent = _assign_target(scope, call)
            if parent is not None:
                key = (mod.path, scope.qualname if scope else None, parent)
                bindings[key] = pos
    return bindings, factories, decorated


def _assign_target(scope, call):
    """Varname when ``call`` is the whole RHS of a single-Name assignment
    in ``scope`` (module level included via scope None callers)."""
    if scope is None:
        return None
    for stmt in astutil.own_statements(scope.node):
        if (isinstance(stmt, ast.Assign) and stmt.value is call
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            return stmt.targets[0].id
    return None


class _Caller:
    """Per-caller AST facts: statement parents, loops, name occurrences."""

    def __init__(self, fn):
        self.fn = fn
        self.parent: dict = {}
        for node in ast.walk(fn.node):
            for child in ast.iter_child_nodes(node):
                self.parent[id(child)] = node

    def enclosing_loops(self, node):
        out = []
        cur = self.parent.get(id(node))
        while cur is not None and cur is not self.fn.node:
            if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                out.append(cur)
            cur = self.parent.get(id(cur))
        return out

    def assign_of(self, call):
        cur = self.parent.get(id(call))
        if isinstance(cur, ast.Assign) and cur.value is call:
            return cur
        return None

    def is_metadata_read(self, name_node):
        """Whether a Load only touches aval metadata, which survives
        donation: ``donated.shape`` / ``donated.ndim`` / ``len(donated)``
        read the retained abstract value, never the released buffer."""
        cur = self.parent.get(id(name_node))
        if isinstance(cur, ast.Attribute) and (
            cur.attr in astutil.STATIC_ATTRS
        ):
            return True
        if isinstance(cur, ast.Call) and name_node in cur.args:
            return astutil.dotted_name(cur.func) in astutil.STATIC_CALLS
        return False


def _name_uses(root, var, skip_subtree):
    """(pos, node, is_store) for ``var`` Names outside ``skip_subtree``."""
    skip_ids = {id(n) for n in ast.walk(skip_subtree)}
    for n in ast.walk(root):
        if id(n) in skip_ids or not isinstance(n, ast.Name) or n.id != var:
            continue
        yield (n.lineno, n.col_offset), n, isinstance(n.ctx, ast.Store)


def check(project):
    bindings, factories, decorated = _collect_donors(project)
    for mod in project.modules:
        for fn in mod.functions.values():
            if fn.is_lambda:
                continue
            caller = None
            for node in astutil.own_nodes(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                pos = _site_positions(
                    project, mod, fn, node, bindings, factories, decorated
                )
                if not pos:
                    continue
                if caller is None:
                    caller = _Caller(fn)
                yield from _check_call(mod, fn, caller, node, pos)


def _site_positions(project, mod, fn, call, bindings, factories, decorated):
    """Donated positions if ``call`` invokes a donating callable."""
    # direct call of a decorated donating def
    target = project.resolve_function(mod, fn, call.func)
    if target is not None and id(target) in decorated:
        return decorated[id(target)][1]
    # call through a local binding of jax.jit(...) or a donating factory
    if isinstance(call.func, ast.Name):
        cur = fn
        while True:
            key = (mod.path, cur.qualname if cur else None, call.func.id)
            if key in bindings:
                return bindings[key]
            if cur is None:
                break
            cur = cur.parent
        # `v = factory(...)` in this scope?
        src = _local_factory(project, mod, fn, call.func.id)
        if src is not None and id(src) in factories:
            return factories[id(src)]
    return None


def _local_factory(project, mod, scope, varname):
    """FuncInfo of F when ``varname = F(...)`` binds in ``scope`` (single
    assignment — the lru_cache factory idiom every builder uses)."""
    hit = None
    cur = scope
    while cur is not None and hit is None:
        for stmt in astutil.own_statements(cur.node):
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == varname
                    and isinstance(stmt.value, ast.Call)):
                hit = project.resolve_function(mod, cur, stmt.value.func)
        cur = cur.parent
    return hit


def _check_call(mod, fn, caller, call, positions):
    assign = caller.assign_of(call)
    rebound = set()
    if assign is not None:
        for t in assign.targets:
            rebound.update(astutil.target_names(t))
    for p in sorted(positions):
        if p >= len(call.args):
            continue
        arg = call.args[p]
        if not isinstance(arg, ast.Name):
            continue  # fresh expressions donate safely
        var = arg.id
        if var in rebound:
            continue  # nid = step(nid, ...): the canonical loop shape
        call_pos = (call.lineno, call.col_offset)
        uses = sorted(
            (u for u in _name_uses(fn.node, var, call)
             if u[0] > call_pos),
            key=lambda u: u[0],
        )
        for pos_, node_, is_store in uses:
            if is_store:
                break  # re-Stored before any read: later Loads see the
                # fresh binding (flow-insensitive approximation)
            if caller.is_metadata_read(node_):
                continue  # .shape/.ndim/len() read the aval, not the buffer
            yield Finding(
                rule_id, mod.path, pos_[0], pos_[1],
                f"'{var}' is read after being donated to "
                f"'{_callee_label(call)}' at line {call.lineno} — a "
                "donated buffer aliases memory XLA reuses; on TPU this "
                "is a silent garbage read",
            )
            break
        loops = caller.enclosing_loops(call)
        if loops and not _stored_in(loops[0], var):
            yield Finding(
                rule_id, mod.path, call.lineno, call.col_offset,
                f"'{var}' is donated inside a loop but never rebound in "
                "the loop body — iteration 2 re-donates the buffer "
                "iteration 1 already consumed",
            )


def _stored_in(loop, var):
    return any(
        isinstance(n, ast.Name) and n.id == var
        and isinstance(n.ctx, ast.Store)
        for n in ast.walk(loop)
    )


def _callee_label(call):
    return astutil.dotted_name(call.func) or "<callable>"
