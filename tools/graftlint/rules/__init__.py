"""graftlint rule registry — one module per rule family."""

from tools.graftlint.rules import (
    gl01_host_sync,
    gl02_recompile,
    gl03_collectives,
    gl04_dtype,
    gl05_donation,
)

ALL_RULES = (gl01_host_sync, gl02_recompile, gl03_collectives, gl04_dtype,
             gl05_donation)

RULE_DOCS = {
    r.rule_id: (r.__doc__ or "").strip().splitlines()[0] for r in ALL_RULES
}
