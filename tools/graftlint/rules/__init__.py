"""graftlint rule registry — one module per rule family.

GL00 (unused-suppression audit) lives in the engine itself: it needs the
suppression-hit accounting that only exists after finding/suppression
resolution, so it cannot be a ``check(project)`` rule. It is registered in
``RULE_DOCS`` so ``--select``/``--list-rules`` treat it uniformly.
"""

from tools.graftlint.rules import (
    gl01_host_sync,
    gl02_recompile,
    gl03_collectives,
    gl04_dtype,
    gl05_donation,
    gl06_callbacks,
    gl07_pallas,
    gl08_donation_use,
)

ALL_RULES = (gl01_host_sync, gl02_recompile, gl03_collectives, gl04_dtype,
             gl05_donation, gl06_callbacks, gl07_pallas, gl08_donation_use)

RULE_DOCS = {
    r.rule_id: (r.__doc__ or "").strip().splitlines()[0] for r in ALL_RULES
}
RULE_DOCS["GL00"] = (
    "GL00 — unused suppression: a disable directive that silences nothing."
)
