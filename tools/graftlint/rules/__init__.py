"""graftlint rule registry — one module per rule family.

GL00 (unused-suppression audit) lives in the engine itself: it needs the
suppression-hit accounting that only exists after finding/suppression
resolution, so it cannot be a ``check(project)`` rule. It is registered in
``RULE_DOCS`` so ``--select``/``--list-rules`` treat it uniformly.
"""

from tools.graftlint.rules import (
    gl01_host_sync,
    gl02_recompile,
    gl03_collectives,
    gl04_dtype,
    gl05_donation,
    gl06_callbacks,
    gl07_pallas,
    gl08_donation_use,
    gl09_partition,
    gl10_env_knobs,
    gl11_locks,
    gl12_ledger,
)

ALL_RULES = (gl01_host_sync, gl02_recompile, gl03_collectives, gl04_dtype,
             gl05_donation, gl06_callbacks, gl07_pallas, gl08_donation_use,
             gl09_partition, gl10_env_knobs, gl11_locks, gl12_ledger)

RULE_DOCS = {
    r.rule_id: (r.__doc__ or "").strip().splitlines()[0] for r in ALL_RULES
}
RULE_DOCS["GL00"] = (
    "GL00 — unused suppression: a disable directive that silences nothing."
)

# full module docstrings double as the ``--explain GLnn`` text
RULE_EXPLAIN = {r.rule_id: (r.__doc__ or "").strip() for r in ALL_RULES}
RULE_EXPLAIN["GL00"] = (
    "GL00 — unused suppression: a disable directive that silences "
    "nothing.\n\n"
    "Every ``# graftlint: disable=RULE`` directive must pay rent: if no\n"
    "finding of that rule would have fired on the directive's line, the\n"
    "directive itself becomes a GL00 finding. This keeps suppressions\n"
    "from outliving the code they excused — delete the stale directive\n"
    "or re-justify it. GL00 lives in the engine (it needs the\n"
    "suppression-hit accounting that only exists after resolution), so\n"
    "``--select GL00`` alone is rejected: it audits the suppressions of\n"
    "rules that actually ran."
)
