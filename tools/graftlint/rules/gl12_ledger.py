"""GL12 — ledger congruence: collectives are priced, event names are real.

The obs wire and compute ledgers (cost explains, advisor evidence) are
only honest if every collective a device program actually issues maps to
a priced site, and the structured event stream is only greppable if
event names cannot drift from the registry the docs are generated from.
Both halves are congruence checks between code and a declarative
authority, the GL09/GL10 stance applied to the ledgers:

1. **Wire pricing.** Every byte-moving collective call site reachable
   from device code (``lax.psum``/``pmean``/``pmin``/``pmax``/
   ``all_gather``/``psum_scatter``/``ppermute``/``pshuffle`` —
   ``axis_index``/``axis_size``/``pcast`` move no payload; ``pcast``
   only retags varying-manual-axes metadata) must carry a
   ``# graftlint: wire=<site>`` annotation (on the call line, the
   comment block above it, or the enclosing ``def`` chain) naming a
   priced site. The priced-site vocabulary is derived statically from
   the ledger authorities in the lint set: keys of the module-level
   ``COLLECTIVE_AXES`` table (obs/record.py), the ``<site>_bytes``
   payload helpers (parallel/collective.py), and literal sites handed
   to ``.collective("<site>", ...)`` recorders. An unannotated device
   collective is invisible fabric traffic — the cost explain
   undercounts and the advisor reasons from wrong evidence. The check
   activates only when a ``COLLECTIVE_AXES`` authority is in the lint
   set (linting a single file must not cry wolf).
2. **Event-name congruence.** Every literal event kind passed to
   ``warn_event(obs, "<kind>", ...)`` or ``<obs>.event("<kind>", ...)``
   and every literal decision key passed to ``<obs>.decision("<key>",
   ...)`` must be registered in the central event registry — a module
   carrying the ``# graftlint: event-registry`` directive whose
   ``Event("<kind>", ...)`` / ``Decision("<key>", ...)`` entries are the
   single source the README events table is generated from (the
   knob-registry idiom, GL10's twin). An unregistered name is exactly
   how a misspelled event kind ships: it traces, logs, and never
   matches the documented schema. Dynamic names are never guessed;
   the check activates only when a registry module is in the lint set.
"""

from __future__ import annotations

import ast

from tools.graftlint import astutil
from tools.graftlint.engine import Finding

rule_id = "GL12"

# byte-moving collectives (GL03's set minus the payload-free members:
# axis_index/axis_size are index queries and pcast only retags vma
# metadata — none of them put a byte on the wire)
_PRICED = frozenset({
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmin", "jax.lax.pmax",
    "jax.lax.all_gather", "jax.lax.psum_scatter", "jax.lax.ppermute",
    "jax.lax.pshuffle",
})


def _is_registry_module(mod) -> bool:
    return any(
        kind == "event-registry"
        for kind, _vals in mod.directive_lines.values()
    )


def _wire_vocabulary(project):
    """(has_authority, site names) — the priced-site vocabulary.

    Authority: a module-level ``COLLECTIVE_AXES`` dict literal. The
    vocabulary joins its keys with ``<site>_bytes`` helper stems and
    literal ``.collective("<site>", ...)`` recorder arguments, uppercased
    (directive values arrive uppercased from the engine).
    """
    has_authority = False
    vocab: set = set()
    for mod in project.modules:
        for stmt in mod.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "COLLECTIVE_AXES"
                    and isinstance(stmt.value, ast.Dict)):
                has_authority = True
                for key in stmt.value.keys:
                    s = astutil.str_const(key)
                    if s is not None:
                        vocab.add(s.upper())
            elif (isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and stmt.name.endswith("_bytes")):
                vocab.add(stmt.name[: -len("_bytes")].upper())
        for _scope, call in project._walk_calls(mod):
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr == "collective" and call.args):
                s = astutil.str_const(call.args[0])
                if s is not None:
                    vocab.add(s.upper())
    return has_authority, vocab


def _wire_values_at(mod, lineno):
    """Uppercased ``wire=`` directive values on ``lineno`` or the
    contiguous standalone-comment block directly above it."""
    out: set = set()
    d = mod.directive_lines.get(lineno)
    if d and d[0] == "wire":
        out |= d[1]
    line = lineno - 1
    while line >= 1 and mod.lines[line - 1].lstrip().startswith("#"):
        d = mod.directive_lines.get(line)
        if d and d[0] == "wire":
            out |= d[1]
        line -= 1
    return out


def _wire_sites(mod, call, scope):
    """All ``wire=`` values covering a call: its own line/comment block,
    then each enclosing ``def`` (and decorators) outward — a factory
    whose every collective belongs to one site annotates once."""
    out = _wire_values_at(mod, call.lineno)
    cur = scope
    while cur is not None:
        if not cur.is_lambda:
            for lineno in [cur.node.lineno] + [
                d.lineno for d in cur.node.decorator_list
            ]:
                out |= _wire_values_at(mod, lineno)
        cur = cur.parent
    return out


def _check_wire(project):
    has_authority, vocab = _wire_vocabulary(project)
    if not has_authority:
        return
    for mod in project.modules:
        for scope, call in project._walk_calls(mod):
            if scope is None or not scope.is_device:
                continue
            name = mod.canonical(call.func)
            if name not in _PRICED:
                continue
            sites = _wire_sites(mod, call, scope)
            short = name.rsplit(".", 1)[-1]
            if not sites:
                yield Finding(
                    rule_id, mod.path, call.lineno, call.col_offset,
                    f"device-reachable {short} has no `# graftlint: "
                    "wire=<site>` annotation — every byte-moving "
                    "collective must map to a priced site "
                    "(COLLECTIVE_AXES / *_bytes helpers) or the wire "
                    "ledger undercounts fabric traffic",
                )
                continue
            for site in sorted(sites - vocab):
                yield Finding(
                    rule_id, mod.path, call.lineno, call.col_offset,
                    f"wire={site.lower()} names no priced site — known "
                    "sites come from COLLECTIVE_AXES keys, *_bytes "
                    "helpers and .collective(...) recorders; add the "
                    "pricing entry or fix the site name",
                )


def _registry_names(project):
    """(event kinds, decision keys) registered across every
    event-registry module in the lint set."""
    events: set = set()
    decisions: set = set()
    for mod in project.modules:
        if not _is_registry_module(mod):
            continue
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            ctor = (astutil.dotted_name(node.func) or "").rsplit(".", 1)[-1]
            s = astutil.str_const(node.args[0])
            if s is None:
                continue
            if ctor == "Event":
                events.add(s)
            elif ctor == "Decision":
                decisions.add(s)
    return events, decisions


def _check_events(project):
    events, decisions = _registry_names(project)
    if not events and not decisions:
        return  # no registry in the lint set: nothing to conform to
    for mod in project.modules:
        if _is_registry_module(mod):
            continue
        for _scope, call in project._walk_calls(mod):
            short = (astutil.dotted_name(call.func) or "").rsplit(".", 1)[-1]
            if short == "warn_event" and len(call.args) > 1:
                kind = astutil.str_const(call.args[1])
                if kind is not None and kind not in events:
                    yield Finding(
                        rule_id, mod.path, call.args[1].lineno,
                        call.args[1].col_offset,
                        f"event kind '{kind}' is not in the central event "
                        "registry — register it (obs/events.py) so the "
                        "README events table and log consumers can't "
                        "drift from the code",
                    )
            elif (isinstance(call.func, ast.Attribute)
                  and call.func.attr in ("event", "decision")
                  and call.args):
                lit = astutil.str_const(call.args[0])
                if lit is None:
                    continue
                known = events if call.func.attr == "event" else decisions
                if lit not in known:
                    what = ("event kind" if call.func.attr == "event"
                            else "decision key")
                    yield Finding(
                        rule_id, mod.path, call.args[0].lineno,
                        call.args[0].col_offset,
                        f"{what} '{lit}' is not in the central event "
                        "registry — register it (obs/events.py) so the "
                        "README events table and log consumers can't "
                        "drift from the code",
                    )


def check(project):
    yield from _check_wire(project)
    yield from _check_events(project)
