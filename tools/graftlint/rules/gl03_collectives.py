"""GL03 — collective/mesh coherence.

1. Axis names handed to ``lax.psum``/``pmean``/``pmin``/``pmax``/
   ``all_gather``/``axis_index``/``pcast``/... must be declared by a mesh in
   the lint set (``parallel/mesh.py``'s ``*_AXIS`` constants or literal
   ``Mesh(..., (names,))`` tuples). A typo'd axis name traces fine and
   fails only at run time on multi-device hardware — exactly the error
   class CPU-only CI cannot catch dynamically. Dynamic axis arguments
   (parameters like ``node_counts_local``'s ``axis=``) are skipped.
2. ``shard_map`` in_specs must cover the wrapped function's positional
   arity — a short tuple raises at trace time on hardware, a long one
   silently drops a spec. Specs passed as a local variable resolve through
   its literal-tuple assignments in the enclosing function; functions with
   ``*args`` (e.g. ``collective.make_split_fn``'s ``local_step``) are
   skipped.
"""

from __future__ import annotations

import ast

from tools.graftlint import astutil
from tools.graftlint.engine import SHARD_MAP, Finding

rule_id = "GL03"

# canonical name -> index of the axis-name argument
_COLLECTIVES = {
    "jax.lax.psum": 1, "jax.lax.pmean": 1, "jax.lax.pmin": 1,
    "jax.lax.pmax": 1, "jax.lax.all_gather": 1, "jax.lax.psum_scatter": 1,
    "jax.lax.ppermute": 1, "jax.lax.pshuffle": 1, "jax.lax.pcast": 1,
    "jax.lax.axis_index": 0, "jax.lax.axis_size": 0,
}


def _axis_arg(call: ast.Call, idx: int) -> ast.AST | None:
    if len(call.args) > idx:
        return call.args[idx]
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis", "axes"):
            return kw.value
    return None


def _axis_names(project, mod, node):
    """Resolvable axis-name strings in an axis argument (non-strings skip)."""
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    for el in elts:
        s = project.resolve_str(mod, el)
        if s is not None:
            yield s, el


def check(project):
    declared = project.mesh_axes
    for mod in project.modules:
        for scope, call in project._walk_calls(mod):
            name = mod.canonical(call.func)
            if name in _COLLECTIVES and declared:
                axis_arg = _axis_arg(call, _COLLECTIVES[name])
                if axis_arg is None:
                    continue
                for axis, el in _axis_names(project, mod, axis_arg):
                    if axis not in declared:
                        yield Finding(
                            rule_id, mod.path, el.lineno, el.col_offset,
                            f"{name.rsplit('.', 1)[-1]} over axis "
                            f"'{axis}' which no declared mesh provides "
                            f"(declared: {', '.join(sorted(declared))})",
                        )
            elif name in SHARD_MAP and call.args:
                yield from _check_shard_map(project, mod, scope, call)


def _check_shard_map(project, mod, scope, call):
    target = project.resolve_function(mod, scope, call.args[0])
    if target is None:
        return
    arity = astutil.positional_arity(target.node.args)
    if arity is None:
        return
    specs = astutil.keyword_arg(call, "in_specs")
    if specs is None and len(call.args) > 2:
        specs = call.args[2]
    for tup in _spec_tuples(scope, specs):
        n = len(tup.elts)
        if n != arity:
            yield Finding(
                rule_id, mod.path, tup.lineno, tup.col_offset,
                f"shard_map in_specs has {n} entries but "
                f"'{target.qualname}' takes {arity} positional args — "
                "every array operand needs a PartitionSpec",
            )


def _spec_tuples(scope, specs):
    """Literal tuples an in_specs argument denotes (direct or via a local)."""
    if isinstance(specs, (ast.Tuple, ast.List)):
        yield specs
    elif isinstance(specs, ast.Name) and scope is not None:
        for stmt in astutil.own_statements(scope.node):
            if not isinstance(stmt, ast.Assign):
                continue
            for t in stmt.targets:
                if (isinstance(t, ast.Name) and t.id == specs.id
                        and isinstance(stmt.value, (ast.Tuple, ast.List))):
                    yield stmt.value
