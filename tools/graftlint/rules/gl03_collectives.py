"""GL03 — collective/mesh coherence.

1. Axis names handed to ``lax.psum``/``pmean``/``pmin``/``pmax``/
   ``all_gather``/``axis_index``/``pcast``/... must be declared by a mesh in
   the lint set (``parallel/mesh.py``'s ``*_AXIS`` constants or literal
   ``Mesh(..., (names,))`` tuples). A typo'd axis name traces fine and
   fails only at run time on multi-device hardware — exactly the error
   class CPU-only CI cannot catch dynamically. Dynamic axis arguments
   (parameters like ``node_counts_local``'s ``axis=``) are skipped.
2. ``shard_map`` in_specs must cover the wrapped function's positional
   arity — a short tuple raises at trace time on hardware, a long one
   silently drops a spec. Specs passed as a local variable resolve through
   its literal-tuple assignments in the enclosing function; functions with
   ``*args`` (e.g. ``collective.make_split_fn``'s ``local_step``) are
   skipped.
3. Collectives INSIDE a ``shard_map`` body must name an axis the
   enclosing call's PartitionSpecs bind (the 2-D ``(data, feature)`` mesh
   lesson): a ``psum`` over ``"feature"`` inside a body mapped on a 1-D
   data mesh traces fine on CPU and mis-reduces (or dies) only on
   multi-device hardware. Checked only when the wrapped function and both
   spec tuples resolve to literals (``P(...)`` calls over string
   constants); dynamic axis arguments and closure-parameterized bodies
   (``psum_axis=...``) are skipped, same stance as rule 1.
"""

from __future__ import annotations

import ast

from tools.graftlint import astutil
from tools.graftlint.engine import SHARD_MAP, Finding

rule_id = "GL03"

# canonical name -> index of the axis-name argument
_COLLECTIVES = {
    "jax.lax.psum": 1, "jax.lax.pmean": 1, "jax.lax.pmin": 1,
    "jax.lax.pmax": 1, "jax.lax.all_gather": 1, "jax.lax.psum_scatter": 1,
    "jax.lax.ppermute": 1, "jax.lax.pshuffle": 1, "jax.lax.pcast": 1,
    "jax.lax.axis_index": 0, "jax.lax.axis_size": 0,
}


def _axis_arg(call: ast.Call, idx: int) -> ast.AST | None:
    if len(call.args) > idx:
        return call.args[idx]
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis", "axes"):
            return kw.value
    return None


def _axis_names(project, mod, node):
    """Resolvable axis-name strings in an axis argument (non-strings skip)."""
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    for el in elts:
        s = project.resolve_str(mod, el)
        if s is not None:
            yield s, el


def check(project):
    declared = project.mesh_axes
    for mod in project.modules:
        for scope, call in project._walk_calls(mod):
            name = mod.canonical(call.func)
            if name in _COLLECTIVES and declared:
                axis_arg = _axis_arg(call, _COLLECTIVES[name])
                if axis_arg is None:
                    continue
                for axis, el in _axis_names(project, mod, axis_arg):
                    if axis not in declared:
                        yield Finding(
                            rule_id, mod.path, el.lineno, el.col_offset,
                            f"{name.rsplit('.', 1)[-1]} over axis "
                            f"'{axis}' which no declared mesh provides "
                            f"(declared: {', '.join(sorted(declared))})",
                        )
            elif name in SHARD_MAP and call.args:
                yield from _check_shard_map(project, mod, scope, call)


def _check_shard_map(project, mod, scope, call):
    target = project.resolve_function(mod, scope, call.args[0])
    if target is None:
        return
    specs = astutil.keyword_arg(call, "in_specs")
    if specs is None and len(call.args) > 2:
        specs = call.args[2]
    arity = astutil.positional_arity(target.node.args)
    if arity is not None:
        for tup in _spec_tuples(scope, specs):
            n = len(tup.elts)
            if n != arity:
                yield Finding(
                    rule_id, mod.path, tup.lineno, tup.col_offset,
                    f"shard_map in_specs has {n} entries but "
                    f"'{target.qualname}' takes {arity} positional args — "
                    "every array operand needs a PartitionSpec",
                )
    out_specs = astutil.keyword_arg(call, "out_specs")
    if out_specs is None and len(call.args) > 3:
        out_specs = call.args[3]
    bound: set = set()
    for group in (specs, out_specs):
        axes = _bound_axes(project, mod, scope, group)
        if axes is None:
            return  # dynamic spec construction — body check unavailable
        bound |= axes
    if not bound:
        # fully replicated specs bind no axis; a collective inside such a
        # body is unusual but not provably wrong — skip, same stance as
        # dynamic axis arguments.
        return
    yield from _check_body_axes(project, mod, target, bound)


def _bound_axes(project, mod, scope, specs):
    """Axis names a specs argument binds, or None when not fully literal.

    Accepts a literal tuple/list of ``P(...)`` calls, a single ``P(...)``
    (out_specs of one output), or a local Name resolving to a literal
    tuple (the ``_spec_tuples`` contract — augmented ``specs + (P(),)``
    rebinds make the tuple partial, so those sites resolve to None via
    the element walk below when they carry non-spec elements).
    """
    if specs is None:
        return None
    if isinstance(specs, ast.Name):
        tups = list(_spec_tuples(scope, specs))
        if len(tups) != 1:
            return None
        specs = tups[0]
    elts = (
        specs.elts if isinstance(specs, (ast.Tuple, ast.List)) else [specs]
    )
    axes: set = set()
    for el in elts:
        got = _p_axes(project, mod, el)
        if got is None:
            return None
        axes |= got
    return axes


def _p_axes(project, mod, el):
    """Axis names in one ``PartitionSpec(...)`` literal (None = not one)."""
    if not isinstance(el, ast.Call):
        return None
    name = mod.canonical(el.func)
    if name is None or name.rsplit(".", 1)[-1] != "PartitionSpec":
        return None
    axes: set = set()
    stack = list(el.args)
    while stack:
        a = stack.pop()
        if isinstance(a, ast.Constant) and a.value is None:
            continue
        if isinstance(a, (ast.Tuple, ast.List)):
            stack.extend(a.elts)
            continue
        s = project.resolve_str(mod, a)
        if s is None:
            return None
        axes.add(s)
    return axes


def _check_body_axes(project, mod, target, bound):
    """Collectives lexically inside the wrapped body (nested defs and
    closures included — they run in the same shard_map program) must name
    a spec-bound axis. Dynamic axis arguments skip, as everywhere."""
    for node in ast.walk(target.node):
        if not isinstance(node, ast.Call):
            continue
        name = mod.canonical(node.func)
        if name not in _COLLECTIVES:
            continue
        axis_arg = _axis_arg(node, _COLLECTIVES[name])
        if axis_arg is None:
            continue
        for axis, el in _axis_names(project, mod, axis_arg):
            if axis not in bound:
                yield Finding(
                    rule_id, mod.path, el.lineno, el.col_offset,
                    f"{name.rsplit('.', 1)[-1]} over axis '{axis}' inside "
                    f"'{target.qualname}', but the enclosing shard_map's "
                    "specs bind only "
                    f"{{{', '.join(sorted(bound))}}} — a collective over "
                    "an unbound axis traces on CPU and mis-reduces only "
                    "on multi-device hardware",
                )


def _spec_tuples(scope, specs):
    """Literal tuples an in_specs argument denotes (direct or via a local)."""
    if isinstance(specs, (ast.Tuple, ast.List)):
        yield specs
    elif isinstance(specs, ast.Name) and scope is not None:
        for stmt in astutil.own_statements(scope.node):
            if not isinstance(stmt, ast.Assign):
                continue
            for t in stmt.targets:
                if (isinstance(t, ast.Name) and t.id == specs.id
                        and isinstance(stmt.value, (ast.Tuple, ast.List))):
                    yield stmt.value
