"""GL04 — dtype and tiling contracts in device code.

1. ``jnp.zeros``/``ones``/``full``/``empty`` inside device functions must
   pass an explicit dtype. JAX's weak-type promotion makes an undtyped
   accumulator inherit whatever the first addend carries — a histogram
   seeded ``jnp.zeros(shape)`` silently accumulates in f64-weak on CPU
   tests and f32 on TPU, breaking the bit-identity contracts
   ``ops/histogram.py`` documents.
2. ``lax.dot_general`` (the MXU contraction both histogram kernels are
   built on) must pin ``preferred_element_type`` — without it a bf16
   operand pair accumulates in bf16 and the integer-exactness argument
   (exact counts below 2**24) is void.
3. ``pl.BlockSpec`` block shapes: literal trailing dims must respect TPU
   tiling — last dim a multiple of 128 (lanes), second-to-last a multiple
   of 8 (sublanes); 1 is allowed for degenerate dims (the ``(Rt, 1)`` slot
   column idiom). Name-valued dims are checked at their call sites by the
   kernels' own ``_round_up`` guards, not here.
"""

from __future__ import annotations

import ast

from tools.graftlint import astutil
from tools.graftlint.engine import Finding

rule_id = "GL04"

_ALLOCS = {
    "jax.numpy.zeros": 1, "jax.numpy.ones": 1, "jax.numpy.empty": 1,
    "jax.numpy.full": 2,
}
_CONTRACTIONS = frozenset({"jax.lax.dot_general"})


def _literal_int(node: ast.AST) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def check(project):
    for fn in project.device_functions():
        mod = fn.module
        for node in astutil.own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = mod.canonical(node.func)
            if name in _ALLOCS:
                dtype_pos = _ALLOCS[name]
                if (len(node.args) <= dtype_pos
                        and astutil.keyword_arg(node, "dtype") is None):
                    yield Finding(
                        rule_id, mod.path, node.lineno, node.col_offset,
                        f"{name.replace('jax.numpy', 'jnp')} without an "
                        f"explicit dtype in device function '{fn.qualname}' "
                        "— weak-type promotion makes the accumulator dtype "
                        "platform-dependent",
                    )
            elif name in _CONTRACTIONS:
                if astutil.keyword_arg(
                    node, "preferred_element_type"
                ) is None:
                    yield Finding(
                        rule_id, mod.path, node.lineno, node.col_offset,
                        f"dot_general in '{fn.qualname}' without "
                        "preferred_element_type — MXU accumulation dtype "
                        "follows the (possibly bf16) operands",
                    )
    # BlockSpec tiling is checked module-wide: kernels build specs in host
    # factory code (grid_spec construction) as often as in device fns.
    for mod in project.modules:
        for _scope, call in project._walk_calls(mod):
            name = mod.canonical(call.func)
            if name is None or name.rsplit(".", 1)[-1] != "BlockSpec":
                continue
            shape = call.args[0] if call.args else astutil.keyword_arg(
                call, "block_shape"
            )
            if not isinstance(shape, (ast.Tuple, ast.List)):
                continue
            dims = shape.elts
            checks = []
            if dims:
                checks.append((dims[-1], 128, "last (lane)"))
            if len(dims) >= 2:
                checks.append((dims[-2], 8, "second-to-last (sublane)"))
            for dim, mult, which in checks:
                v = _literal_int(dim)
                if v is not None and v != 1 and v % mult:
                    yield Finding(
                        rule_id, mod.path, dim.lineno, dim.col_offset,
                        f"BlockSpec {which} block dim {v} is not a "
                        f"multiple of {mult} — Mosaic pads or rejects "
                        "off-tile blocks on TPU",
                    )
