"""GL04 — dtype and tiling contracts in device code.

1. ``jnp.zeros``/``ones``/``full``/``empty`` inside device functions must
   pass an explicit dtype. JAX's weak-type promotion makes an undtyped
   accumulator inherit whatever the first addend carries — a histogram
   seeded ``jnp.zeros(shape)`` silently accumulates in f64-weak on CPU
   tests and f32 on TPU, breaking the bit-identity contracts
   ``ops/histogram.py`` documents.
2. ``lax.dot_general`` (the MXU contraction both histogram kernels are
   built on) must pin ``preferred_element_type`` — without it a bf16
   operand pair accumulates in bf16 and the integer-exactness argument
   (exact counts below 2**24) is void.
3. ``pl.BlockSpec`` block shapes: literal trailing dims must respect TPU
   tiling — last dim a multiple of 128 (lanes), second-to-last a multiple
   of 8 (sublanes); 1 is allowed for degenerate dims (the ``(Rt, 1)`` slot
   column idiom). Name-valued dims are checked at their call sites by the
   kernels' own ``_round_up`` guards, not here.
4. Host-``numpy`` accumulator allocations feeding device code:
   ``np.zeros``/``np.empty`` without an explicit dtype default to float64,
   and a variable so allocated that is later handed to a ``jax.*`` call
   (or a project device function) either silently doubles the transfer
   and accumulates a dtype the device path never tested, or — with x64
   disabled — truncates back to f32 after the host math already rounded
   differently. Scoped to allocations whose VARIABLE later appears as an
   argument of a jax/device call in the same function, so plain host
   accumulators (predict vote buffers, the host builder's own f64
   histograms) stay silent.
"""

from __future__ import annotations

import ast

from tools.graftlint import astutil
from tools.graftlint.engine import Finding

rule_id = "GL04"

_ALLOCS = {
    "jax.numpy.zeros": 1, "jax.numpy.ones": 1, "jax.numpy.empty": 1,
    "jax.numpy.full": 2,
}
# Host-numpy accumulators (ROADMAP deferred GL04 family): zeros/empty are
# the accumulator idioms; ones/full are almost always explicit-valued
# fills whose dtype the fill literal documents.
_NP_ALLOCS = {"numpy.zeros": 1, "numpy.empty": 1}
_CONTRACTIONS = frozenset({"jax.lax.dot_general"})


def _literal_int(node: ast.AST) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def check(project):
    for fn in project.device_functions():
        mod = fn.module
        for node in astutil.own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = mod.canonical(node.func)
            if name in _ALLOCS:
                dtype_pos = _ALLOCS[name]
                if (len(node.args) <= dtype_pos
                        and astutil.keyword_arg(node, "dtype") is None):
                    yield Finding(
                        rule_id, mod.path, node.lineno, node.col_offset,
                        f"{name.replace('jax.numpy', 'jnp')} without an "
                        f"explicit dtype in device function '{fn.qualname}' "
                        "— weak-type promotion makes the accumulator dtype "
                        "platform-dependent",
                    )
            elif name in _CONTRACTIONS:
                if astutil.keyword_arg(
                    node, "preferred_element_type"
                ) is None:
                    yield Finding(
                        rule_id, mod.path, node.lineno, node.col_offset,
                        f"dot_general in '{fn.qualname}' without "
                        "preferred_element_type — MXU accumulation dtype "
                        "follows the (possibly bf16) operands",
                    )
    # Host-numpy accumulators feeding device code, per function: collect
    # undtyped np.zeros/np.empty assignments, then flag any whose variable
    # later rides into a jax.* call or a resolvable project device
    # function. Conservative on purpose: an alloc consumed only by host
    # numpy (bincounts, vote buffers) never fires.
    for mod in project.modules:
        for fn in mod.functions.values():
            allocs: dict = {}
            fed: dict = {}  # name -> latest device-feed lineno
            for node in astutil.own_nodes(fn.node):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)):
                    call = node.value
                    name = mod.canonical(call.func)
                    dtype_pos = _NP_ALLOCS.get(name)
                    if (dtype_pos is not None
                            and len(call.args) <= dtype_pos
                            and astutil.keyword_arg(call, "dtype") is None):
                        allocs.setdefault(node.targets[0].id, (name, call))
                if not isinstance(node, ast.Call):
                    continue
                cname = mod.canonical(node.func)
                target = project.resolve_function(mod, fn, node.func)
                if not ((cname or "").startswith("jax.")
                        or (target is not None and target.is_device)):
                    continue
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name):
                            fed[sub.id] = max(
                                fed.get(sub.id, 0), node.lineno
                            )
            for var, (name, call) in allocs.items():
                # Statement order matters: a device use of the same NAME
                # that precedes the allocation is a different binding
                # (e.g. `a = jnp.sum(x); ...; a = np.zeros(n)` host
                # buffer) — only a feed BELOW the alloc line fires.
                if fed.get(var, 0) <= call.lineno:
                    continue
                yield Finding(
                    rule_id, mod.path, call.lineno, call.col_offset,
                    f"{name.replace('numpy', 'np')} without an explicit "
                    f"dtype allocates float64 on host, and '{var}' feeds a "
                    f"device call in '{fn.qualname}' — pin the dtype the "
                    "device path actually accumulates",
                )
    # BlockSpec tiling is checked module-wide: kernels build specs in host
    # factory code (grid_spec construction) as often as in device fns.
    for mod in project.modules:
        for _scope, call in project._walk_calls(mod):
            name = mod.canonical(call.func)
            if name is None or name.rsplit(".", 1)[-1] != "BlockSpec":
                continue
            shape = call.args[0] if call.args else astutil.keyword_arg(
                call, "block_shape"
            )
            if not isinstance(shape, (ast.Tuple, ast.List)):
                continue
            dims = shape.elts
            checks = []
            if dims:
                checks.append((dims[-1], 128, "last (lane)"))
            if len(dims) >= 2:
                checks.append((dims[-2], 8, "second-to-last (sublane)"))
            for dim, mult, which in checks:
                v = _literal_int(dim)
                if v is not None and v != 1 and v % mult:
                    yield Finding(
                        rule_id, mod.path, dim.lineno, dim.col_offset,
                        f"BlockSpec {which} block dim {v} is not a "
                        f"multiple of {mult} — Mosaic pads or rejects "
                        "off-tile blocks on TPU",
                    )
