"""GL06 — host-callback policing inside device code.

``io_callback`` / ``pure_callback`` / ``jax.debug.callback`` punch a hole
through the device program back to Python. The engine already treats the
callback *target* as host code (reachability never descends into it — its
``np.asarray`` body is the point); this rule polices the hole itself, for
every callback call reachable from a jit root:

1. The call must carry an explicit ``# graftlint: host-callback`` directive
   (same line or the standalone comment block above): a host round trip in
   a device program is always a deliberate design decision and must read
   as one — an undirected callback is indistinguishable from a leftover
   debug hook.
2. ``result_shape_dtypes`` must be present (io/pure_callback; debug.callback
   returns nothing) and static: an expression that reads a traced value
   (outside shape/len laundering) would concretize at trace time — the
   result contract has to be computable before the program runs.
3. The callback function must not close over traced values it doesn't
   declare: a traced free variable in the callback body is dead at call
   time on TPU (callbacks receive their operands as explicit arguments;
   closures capture tracers, which hold garbage by the time the host runs).
   Pass the value as an operand instead.
"""

from __future__ import annotations

from tools.graftlint import astutil
from tools.graftlint.engine import CALLBACKS, Finding

rule_id = "GL06"

# callbacks whose second positional argument is result_shape_dtypes
_HAS_RESULT_SHAPES = frozenset({
    "jax.experimental.io_callback", "jax.experimental.pure_callback",
    "jax.pure_callback",
})


def _result_shapes_arg(call):
    kw = astutil.keyword_arg(call, "result_shape_dtypes")
    if kw is not None:
        return kw
    if len(call.args) >= 2:
        return call.args[1]
    return None


def check(project):
    for mod in project.modules:
        for fn, call in project._walk_calls(mod):
            if fn is None or not fn.is_device:
                continue
            name = mod.canonical(call.func)
            if name not in CALLBACKS or not call.args:
                continue
            traced = project.dataflow.traced(fn)
            short = name.rsplit(".", 1)[-1]
            if not mod.directive_at(call.lineno, "host-callback"):
                yield Finding(
                    rule_id, mod.path, call.lineno, call.col_offset,
                    f"{short} in device function '{fn.qualname}' without a "
                    "'# graftlint: host-callback' directive — host round "
                    "trips in device programs must be declared deliberate",
                )
            if name in _HAS_RESULT_SHAPES:
                shapes = _result_shapes_arg(call)
                if shapes is None:
                    yield Finding(
                        rule_id, mod.path, call.lineno, call.col_offset,
                        f"{short} in '{fn.qualname}' without "
                        "result_shape_dtypes — the result contract must be "
                        "static before the program runs",
                    )
                elif project.dataflow.expr_traced(mod, fn, shapes, traced):
                    yield Finding(
                        rule_id, mod.path, shapes.lineno, shapes.col_offset,
                        f"{short} result_shape_dtypes in '{fn.qualname}' "
                        "reads a traced value — shapes/dtypes must be "
                        "trace-time static (derive them from .shape/.dtype)",
                    )
            target = project.resolve_function(mod, fn, call.args[0])
            if target is None:
                continue
            # free names resolve through the CALLBACK's own lexical chain
            # (captured_traced), not the caller's namespace — a module-
            # level callback whose free `x` is a global must not collide
            # with a caller parameter that happens to share the name
            leaked = sorted(project.dataflow.captured_traced(target))
            if leaked:
                yield Finding(
                    rule_id, mod.path, call.lineno, call.col_offset,
                    f"{short} callback '{target.qualname}' closes over "
                    f"traced value(s) {', '.join(leaked)} — a captured "
                    "tracer is garbage when the host runs; pass them as "
                    "explicit operands",
                )
