"""GL02 — recompile hazards at jit boundaries.

Checks, for functions wrapped by ``jax.jit`` with a statically-known
``static_argnames`` (decorator form or ``jax.jit(f, ...)`` call form):

1. Every parameter that *looks* shape-determining (a static type
   annotation — ``int``/``bool``/``str``/``tuple`` —, a Python-scalar
   default, or one of the codebase's shape-parameter name patterns:
   ``n_*``/``max_*``/``*_bins``/``*_tile``/...) must appear in
   ``static_argnames``. A traced Python scalar does not crash — it
   recompiles the program on every distinct value, which on a tunneled TPU
   is tens of seconds per miss.
2. Every name listed in ``static_argnames`` must actually be a parameter
   (typo guard — a stale name silently makes the REAL parameter traced).
3. Python ``if``/``while`` on a traced value inside ANY device function —
   not just direct jit roots. Tracedness comes from the interprocedural
   dataflow engine, so data-dependent Python control flow inside a
   ``lax.cond`` branch closure, a helper reached from a jit root, or a
   rooted lambda no longer escapes. Data-dependent Python branches either
   fail to trace (``ConcretizationTypeError``) or bake one branch in per
   compile. Deliberately-traced runtime scalars (``chunk_lo``, ``mcw``)
   carry none of the static name/annotation markers, so they do not fire
   check 1; branching on them in Python still (correctly) fires check 3.
"""

from __future__ import annotations

import ast

from tools.graftlint import astutil
from tools.graftlint.engine import Finding

rule_id = "GL02"


def check(project):
    for fn, _kind in project.jit_sites:
        if not fn.statics_known:
            continue
        mod, node = fn.module, fn.node
        statics = fn.statics or frozenset()
        params = fn.params
        a = node.args
        defaults = astutil.param_defaults(a)
        anns = {
            p.arg: p.annotation
            for p in a.posonlyargs + a.args + a.kwonlyargs
        }
        for p in params:
            if p in statics:
                continue
            if astutil.looks_shape_static(p, anns.get(p), defaults.get(p)):
                yield Finding(
                    rule_id, mod.path, node.lineno, node.col_offset,
                    f"jitted '{fn.qualname}': parameter '{p}' looks "
                    "shape-determining but is not in static_argnames — "
                    "every distinct value recompiles",
                )
        for s in statics:
            if s not in params:
                yield Finding(
                    rule_id, mod.path, node.lineno, node.col_offset,
                    f"jitted '{fn.qualname}': static_argnames entry '{s}' "
                    "is not a parameter (typo leaves the real one traced)",
                )
    # check 3 covers every device function (dataflow-backed): nested branch
    # closures and transitively-reached helpers included
    for fn in project.device_functions():
        mod = fn.module
        traced = project.dataflow.traced(fn)
        if not traced:
            continue
        for stmt in astutil.own_statements(fn.node):
            if not isinstance(stmt, (ast.If, ast.While)):
                continue
            if project.dataflow.expr_traced(mod, fn, stmt.test, traced):
                kw = "while" if isinstance(stmt, ast.While) else "if"
                yield Finding(
                    rule_id, mod.path, stmt.lineno, stmt.col_offset,
                    f"Python `{kw}` on a traced value in device function "
                    f"'{fn.qualname}' — use lax.cond/jnp.where, or mark "
                    "the driving parameter static",
                )
