"""GL01 — host-device synchronization inside device code paths.

Two checks:

1. Inside jit-reachable functions: ``.item()``, ``.block_until_ready()``,
   ``jax.device_get``, ``np.asarray``/``np.array`` on traced values, and
   ``float()``/``int()``/``bool()`` coercion of traced values. Under a
   trace these either raise ``ConcretizationTypeError`` at runtime or —
   worse, when the value happens to be concrete — silently insert a
   blocking transfer into what profiles as a device-only hot path
   (VERDICT.md round 5's regression class). Tracedness comes from the
   interprocedural dataflow engine (``tools/graftlint/dataflow.py``), so
   a value smuggled into a ``lax.cond`` branch closure, returned from a
   helper, or captured by a vmapped lambda no longer escapes.

2. Anywhere: ``.item()`` / ``.block_until_ready()`` inside a loop or
   comprehension body. A per-element sync turns one device fetch into N
   round trips — the exact shape of the ``tree_struct.to_nodes`` hotspot
   this rule was seeded from. Genuine per-scalar host boundaries (numpy
   generics, post-``device_get`` code) carry a suppression.
"""

from __future__ import annotations

import ast

from tools.graftlint import astutil
from tools.graftlint.engine import Finding

rule_id = "GL01"

_COERCIONS = frozenset({"float", "int", "bool", "complex"})
_NP_PULLS = frozenset({"numpy.asarray", "numpy.array"})
_SYNC_ATTRS = frozenset({"item", "block_until_ready"})


def _device_findings(project):
    for fn in project.device_functions():
        mod = fn.module
        traced = project.dataflow.traced(fn)
        for node in astutil.own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_ATTRS and not node.args):
                yield Finding(
                    rule_id, mod.path, node.lineno, node.col_offset,
                    f".{node.func.attr}() inside device function "
                    f"'{fn.qualname}' forces a host sync under jit",
                )
                continue
            name = mod.canonical(node.func)
            if name == "jax.device_get":
                yield Finding(
                    rule_id, mod.path, node.lineno, node.col_offset,
                    f"jax.device_get inside device function '{fn.qualname}' "
                    "blocks the trace on a device fetch",
                )
            elif name in _NP_PULLS and node.args and project.dataflow.expr_traced(
                mod, fn, node.args[0], traced
            ):
                yield Finding(
                    rule_id, mod.path, node.lineno, node.col_offset,
                    f"{name.replace('numpy', 'np')} on traced value inside "
                    f"device function '{fn.qualname}' round-trips to host "
                    "(use jnp, or suppress if this is a real host boundary)",
                )
            elif (name in _COERCIONS and len(node.args) == 1
                  and project.dataflow.expr_traced(
                      mod, fn, node.args[0], traced
                  )):
                yield Finding(
                    rule_id, mod.path, node.lineno, node.col_offset,
                    f"{name}() coerces a traced value to a Python scalar in "
                    f"device function '{fn.qualname}' (host sync / "
                    "ConcretizationTypeError under jit)",
                )


_LOOPS = (ast.For, ast.While, ast.AsyncFor,
          ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)


def _loop_findings(project):
    for mod in project.modules:
        stack: list = []

        def visit(node):
            in_loop = bool(stack)
            if (in_loop and isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_ATTRS and not node.args):
                yield Finding(
                    rule_id, mod.path, node.lineno, node.col_offset,
                    f".{node.func.attr}() inside a loop: a per-element host "
                    "sync — materialize the array once (np.asarray / "
                    ".tolist()) before iterating",
                )
            if isinstance(node, _LOOPS):
                stack.append(node)
            for child in ast.iter_child_nodes(node):
                yield from visit(child)
            if isinstance(node, _LOOPS):
                stack.pop()

        yield from visit(mod.tree)


def check(project):
    seen: set = set()
    for f in _device_findings(project):
        seen.add((f.path, f.line, f.col))
        yield f
    for f in _loop_findings(project):
        if (f.path, f.line, f.col) not in seen:
            yield f
