"""GL05 — donation hygiene: fused-state jits must donate (or opt out).

A jitted program whose body drives a ``lax`` control-flow loop
(``while_loop`` / ``scan`` / ``fori_loop`` / ``map``) is a *fused-state*
program: the whole multi-step computation compiles into one executable, so
XLA holds every un-donated input buffer alive for the program's full
duration while also allocating the loop state — state-sized arrays
double-buffer in HBM exactly where the working set is largest (the fused
tree builder's row vectors at covtype scale). Such a jit must either pass
``donate_argnums``/``donate_argnames`` for the inputs it consumes, or
carry an explicit ``# graftlint: disable=GL05`` with a rationale where
donation is genuinely wrong (inputs reused across calls, e.g. a binned
matrix shared by every tree of a forest).

Covered jit spellings:

- ``jax.jit(f, ...)`` with a resolvable function first argument,
- ``jax.jit(sharded, ...)`` where ``sharded = jax.shard_map(f, ...)`` was
  bound earlier in the same (or an enclosing) function — the factory
  idiom every ``parallel/collective.py`` kernel uses,
- ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorators.
"""

from __future__ import annotations

import ast

from tools.graftlint import astutil
from tools.graftlint.engine import (
    JIT_WRAPPERS,
    PARTIAL,
    SHARD_MAP,
    Finding,
)

rule_id = "GL05"

_LOOPS = frozenset({
    "jax.lax.while_loop",
    "jax.lax.scan",
    "jax.lax.fori_loop",
    "jax.lax.map",
})
_DONATE = ("donate_argnums", "donate_argnames")


def _has_fused_loop(mod, fn) -> bool:
    for node in astutil.own_nodes(fn.node):
        if isinstance(node, ast.Call) and mod.canonical(node.func) in _LOOPS:
            return True
    return False


def _donates(call: ast.Call) -> bool:
    return any(
        astutil.keyword_arg(call, k) is not None for k in _DONATE
    )


def _shard_map_bindings(project, mod) -> dict:
    """(scope-qualname, varname) -> FuncInfo for ``v = jax.shard_map(f, ..)``.

    ``jax.jit(sharded)`` hides its real target behind a local variable;
    one assignment-tracking pass recovers it (single-assignment factory
    code — the only form the package uses).
    """
    out: dict = {}

    def visit(node, scope):
        for child in ast.iter_child_nodes(node):
            child_scope = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = (
                    f"{scope.qualname}.{child.name}" if scope else child.name
                )
                child_scope = mod.functions.get(qual, scope)
            if (
                isinstance(child, ast.Assign)
                and len(child.targets) == 1
                and isinstance(child.targets[0], ast.Name)
                and isinstance(child.value, ast.Call)
                and mod.canonical(child.value.func) in SHARD_MAP
                and child.value.args
            ):
                target = project.resolve_function(
                    mod, scope, child.value.args[0]
                )
                if target is not None:
                    key = (scope.qualname if scope else None,
                           child.targets[0].id)
                    out[key] = target
            visit(child, child_scope)

    visit(mod.tree, None)
    return out


def _finding(mod, line, col, target, spelled) -> Finding:
    return Finding(
        rule_id, mod.path, line, col,
        f"{spelled} of fused-state program '{target.qualname}' (drives a "
        "lax loop) without donate_argnums/donate_argnames — un-donated "
        "inputs double-buffer in HBM for the whole fused program; donate "
        "consumed inputs or suppress with a rationale",
    )


def check(project):
    for mod in project.modules:
        bindings = _shard_map_bindings(project, mod)
        for scope, call in project._walk_calls(mod):
            if mod.canonical(call.func) not in JIT_WRAPPERS:
                continue
            if not call.args or _donates(call):
                continue
            target = project.resolve_function(mod, scope, call.args[0])
            if target is None and isinstance(call.args[0], ast.Name):
                # jit(sharded): look the variable up through the scope chain
                cur = scope
                while target is None:
                    key = (cur.qualname if cur else None, call.args[0].id)
                    target = bindings.get(key)
                    if cur is None:
                        break
                    cur = cur.parent
            if target is None or target.is_host:
                continue
            if _has_fused_loop(target.module, target):
                yield _finding(
                    mod, call.lineno, call.col_offset, target, "jax.jit",
                )
        # decorator spellings: @jax.jit / @partial(jax.jit, ...)
        for fn in mod.functions.values():
            if fn.is_host or not _has_fused_loop(mod, fn):
                continue
            for dec in fn.node.decorator_list:
                name = mod.canonical(
                    dec.func if isinstance(dec, ast.Call) else dec
                )
                is_partial_jit = (
                    isinstance(dec, ast.Call) and name in PARTIAL
                    and dec.args
                    and mod.canonical(dec.args[0]) in JIT_WRAPPERS
                )
                if name not in JIT_WRAPPERS and not is_partial_jit:
                    continue
                if isinstance(dec, ast.Call) and _donates(dec):
                    continue
                yield _finding(
                    mod, dec.lineno, dec.col_offset, fn, "@jit decorator",
                )
