"""Symbolic-dimension facts — the interval/divisibility domain GL07 reads.

Pallas call sites in this repo mostly size their blocks from *symbolic*
dims (``row_tile``, ``S * C``, ``_round_up(n_bins, 128)``), which the
literal-only checks skipped wholesale. This module recovers what IS
provable about such dims from three pure-AST sources, so the tiling /
coverage / VMEM checks can fire on symbolic shapes instead of bailing:

1. **Single-assignment bindings.** A name bound exactly once in a scope
   takes the fact of its value expression, evaluated over int literals,
   other facts, ``+ - * //``, ``max``/``min``, and ``*round_up(x, K)``
   (result ``>= x``, ``<= x + K - 1`` rounded, and a multiple of ``K`` —
   the one contract every ``_round_up`` helper in ops/ shares). A
   same-length literal tuple unpack (``a, b = x * 2, 3``) is element-wise
   single assignment. A name initialized once outside a loop and rebound
   inside ``for``/``while`` bodies gets a bounded widening fixpoint:
   join (interval hull, gcd of divisors) the init fact with each loop
   rebind until stable, widening bounds that keep moving to unknown
   while a settled divisor survives. Everything else is unknown — no
   guessing across branches.
2. **Guard seeding.** A ``raise``-only ``if`` body whose test compares a
   name against an int literal proves the complement for all surviving
   code: ``if row_tile < 2048: raise`` means ``row_tile >= 2048`` below.
   ``if x % 8: raise`` proves divisibility. Flow-insensitive like the
   dataflow engine: the guard must dominate in practice, and a raise-only
   body is exactly the shape that does.
3. **Lexical chaining.** A free name resolves through enclosing scopes
   (the kernel-factory closure idiom), outermost facts first.

Every fact field is a PROOF, not an estimate: ``lo``/``hi`` are inclusive
bounds, ``mult`` a known positive divisor. Checks must only fire on
conclusions these entail (a lower-bound working set already over budget,
an upper-bound coverage already short) — unknown stays unknown.

``if not fits_vmem(...): raise`` guards are recognized separately
(:func:`has_vmem_guard`): a scope that runtime-gates its working set
already subsumes the static VMEM bound, so GL07 stays quiet there.
"""

from __future__ import annotations

import ast
import dataclasses
import math

from tools.graftlint import astutil


@dataclasses.dataclass(frozen=True)
class Fact:
    """What is provable about one non-negative integer value."""

    lo: int | None = None   # inclusive lower bound
    hi: int | None = None   # inclusive upper bound
    mult: int = 1           # value is a positive multiple of this

    @property
    def exact_value(self) -> int | None:
        return self.lo if self.lo is not None and self.lo == self.hi else None


UNKNOWN = Fact()

# Join-fixpoint pass budget for loop-carried bindings; chains that have
# not stabilized by then widen their bounds away (soundness over reach).
_LOOP_PASSES = 4


def exact(v: int) -> Fact:
    return Fact(v, v, abs(v) if v else 1)


def _gcd(a: int, b: int) -> int:
    return math.gcd(a, b) or 1


def _add(a: Fact, b: Fact) -> Fact:
    return Fact(
        a.lo + b.lo if a.lo is not None and b.lo is not None else None,
        a.hi + b.hi if a.hi is not None and b.hi is not None else None,
        _gcd(a.mult, b.mult),
    )


def _sub(a: Fact, b: Fact) -> Fact:
    return Fact(
        a.lo - b.hi if a.lo is not None and b.hi is not None else None,
        a.hi - b.lo if a.hi is not None and b.lo is not None else None,
        _gcd(a.mult, b.mult),
    )


def _mul(a: Fact, b: Fact) -> Fact:
    # sound only on the non-negative domain dims live in
    neg = (a.lo is not None and a.lo < 0) or (b.lo is not None and b.lo < 0)
    if neg:
        return UNKNOWN
    return Fact(
        a.lo * b.lo if a.lo is not None and b.lo is not None else None,
        a.hi * b.hi if a.hi is not None and b.hi is not None else None,
        a.mult * b.mult,
    )


def _floordiv(a: Fact, k: int) -> Fact:
    if k <= 0:
        return UNKNOWN
    return Fact(
        a.lo // k if a.lo is not None else None,
        a.hi // k if a.hi is not None else None,
        a.mult // k if a.mult % k == 0 else 1,
    )


def _round_up(x: Fact, k: int) -> Fact:
    """Fact of ``round_up(x, k)``: >= x, < x + k, multiple of k."""
    if k <= 0:
        return UNKNOWN
    ceil = (lambda v: -(-v // k) * k)
    return Fact(
        ceil(x.lo) if x.lo is not None else None,
        ceil(x.hi) if x.hi is not None else None,
        k,
    )


def _intersect(a: Fact, b: Fact) -> Fact:
    """Both facts hold for the same value."""
    los = [v for v in (a.lo, b.lo) if v is not None]
    his = [v for v in (a.hi, b.hi) if v is not None]
    return Fact(
        max(los) if los else None,
        min(his) if his else None,
        a.mult * b.mult // _gcd(a.mult, b.mult),  # lcm
    )


def _join(a: Fact, b: Fact) -> Fact:
    """Either fact may hold (the loop-carried union): interval hull, gcd
    of divisors — the dual of :func:`_intersect`."""
    return Fact(
        min(a.lo, b.lo) if a.lo is not None and b.lo is not None else None,
        max(a.hi, b.hi) if a.hi is not None and b.hi is not None else None,
        _gcd(a.mult, b.mult),
    )


def _is_round_up(mod, func_node) -> bool:
    name = mod.canonical(func_node)
    if name is None and isinstance(func_node, ast.Name):
        name = func_node.id
    if name is None and isinstance(func_node, ast.Attribute):
        name = func_node.attr
    return name is not None and name.rsplit(".", 1)[-1].lstrip("_") in (
        "round_up", "ceil_to",
    )


def eval_expr(mod, expr: ast.AST, facts: dict) -> Fact:
    """Fact of ``expr`` under ``facts`` (name -> Fact)."""
    v = astutil.int_tuple(expr)
    if v is not None and len(v) == 1:
        return exact(v[0])
    if isinstance(expr, ast.Name):
        return facts.get(expr.id, UNKNOWN)
    if isinstance(expr, ast.BinOp):
        left = eval_expr(mod, expr.left, facts)
        right = eval_expr(mod, expr.right, facts)
        if isinstance(expr.op, ast.Add):
            return _add(left, right)
        if isinstance(expr.op, ast.Sub):
            return _sub(left, right)
        if isinstance(expr.op, ast.Mult):
            return _mul(left, right)
        if isinstance(expr.op, ast.FloorDiv) and right.exact_value:
            return _floordiv(left, right.exact_value)
        return UNKNOWN
    if isinstance(expr, ast.Call):
        fname = expr.func.id if isinstance(expr.func, ast.Name) else None
        if fname in ("max", "min") and expr.args and not expr.keywords:
            fs = [eval_expr(mod, a, facts) for a in expr.args]
            mult = fs[0].mult
            for f in fs[1:]:
                mult = _gcd(mult, f.mult)
            if fname == "max":
                los = [f.lo for f in fs if f.lo is not None]
                his = [f.hi for f in fs]
                return Fact(
                    max(los) if los else None,
                    max(his) if all(h is not None for h in his) else None,
                    mult,
                )
            his = [f.hi for f in fs if f.hi is not None]
            los = [f.lo for f in fs]
            return Fact(
                min(los) if all(lo is not None for lo in los) else None,
                min(his) if his else None,
                mult,
            )
        if _is_round_up(mod, expr.func) and len(expr.args) == 2:
            x = eval_expr(mod, expr.args[0], facts)
            k = eval_expr(mod, expr.args[1], facts)
            if k.exact_value:
                return _round_up(x, k.exact_value)
            # unknown alignment still preserves the lower bound (>= x)
            return Fact(x.lo, None, 1)
    return UNKNOWN


def _raise_only(body: list) -> bool:
    return len(body) == 1 and isinstance(body[0], ast.Raise)


def _guard_fact(test: ast.AST):
    """(name, Fact proved when the raise does NOT fire) or None."""
    if not isinstance(test, ast.Compare) or len(test.ops) != 1:
        return None
    left, op, right = test.left, test.ops[0], test.comparators[0]
    # name % k [!= 0] -> divisibility (`if x % 8:` and `if x % 8 != 0:`)
    mod_node = None
    if (isinstance(left, ast.BinOp) and isinstance(left.op, ast.Mod)
            and isinstance(op, ast.NotEq)
            and astutil.int_tuple(right) == (0,)):
        mod_node = left
    if mod_node is None:
        lit = astutil.int_tuple(right)
        if lit is None or len(lit) != 1:
            # mirrored literal-on-the-left compare
            lit = astutil.int_tuple(left)
            if lit is None or len(lit) != 1 or not isinstance(
                right, ast.Name
            ):
                return None
            flip = {ast.Lt: ast.Gt, ast.LtE: ast.GtE,
                    ast.Gt: ast.Lt, ast.GtE: ast.LtE}
            op_t = flip.get(type(op), type(op))
            left, lit_v = right, lit[0]
        else:
            if not isinstance(left, ast.Name):
                return None
            op_t, lit_v = type(op), lit[0]
        name = left.id
        # the fact holds on the path where the guard does NOT raise
        if op_t is ast.Lt:          # if name < C: raise  ->  name >= C
            return name, Fact(lo=lit_v)
        if op_t is ast.LtE:         # -> name > C
            return name, Fact(lo=lit_v + 1)
        if op_t is ast.Gt:          # -> name <= C
            return name, Fact(hi=lit_v)
        if op_t is ast.GtE:         # -> name < C
            return name, Fact(hi=lit_v - 1)
        if op_t is ast.NotEq:       # -> name == C
            return name, exact(lit_v)
        return None
    inner = mod_node.left
    k = astutil.int_tuple(mod_node.right)
    if isinstance(inner, ast.Name) and k is not None and len(k) == 1:
        return inner.id, Fact(mult=max(k[0], 1))
    return None


def _bool_guard_fact(test: ast.AST):
    """``if name % k: raise`` — truthiness form of the divisibility guard."""
    if (isinstance(test, ast.BinOp) and isinstance(test.op, ast.Mod)
            and isinstance(test.left, ast.Name)):
        k = astutil.int_tuple(test.right)
        if k is not None and len(k) == 1:
            return test.left.id, Fact(mult=max(k[0], 1))
    return None


def scope_facts(mod, scope) -> dict:
    """name -> Fact for one function scope, lexical parents included.

    Parents are folded in first so inner bindings shadow; a guard on an
    already-bound name intersects with its binding fact.
    """
    facts: dict = {}
    if scope is None:
        return facts
    if scope.parent is not None:
        facts.update(scope_facts(mod, scope.parent))

    # bindings (three fact-producing shapes; everything else is unknown):
    # single-assignment names, same-length literal tuple unpacks
    # (element-wise single assignment), and loop-carried rebinds of a
    # singly-initialized name (widening fixpoint below)
    counts: dict = {}
    values: dict = {}
    loop_values: dict = {}  # name -> [rebind exprs inside for/while bodies]

    def bind(name, value, in_loop):
        if in_loop:
            counts.setdefault(name, 0)
            loop_values.setdefault(name, []).append(value)
        else:
            counts[name] = counts.get(name, 0) + 1
            values[name] = value

    def collect(stmts, in_loop):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.Assign):
                tgt = stmt.targets[0] if len(stmt.targets) == 1 else None
                if isinstance(tgt, ast.Name):
                    bind(tgt.id, stmt.value, in_loop)
                elif (isinstance(tgt, ast.Tuple)
                      and all(isinstance(e, ast.Name) for e in tgt.elts)
                      and isinstance(stmt.value, ast.Tuple)
                      and len(stmt.value.elts) == len(tgt.elts)):
                    for e, v in zip(tgt.elts, stmt.value.elts):
                        bind(e.id, v, in_loop)
                else:
                    for t in stmt.targets:
                        for name in astutil.target_names(t):
                            counts[name] = counts.get(name, 0) + 99
            elif (isinstance(stmt, ast.AugAssign)
                  and isinstance(stmt.target, ast.Name) and in_loop):
                # `tile *= 2` in a loop: desugar to the equivalent rebind
                bind(stmt.target.id, ast.BinOp(
                    left=ast.Name(id=stmt.target.id, ctx=ast.Load()),
                    op=stmt.op, right=stmt.value,
                ), in_loop)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                for name in astutil.target_names(stmt.target):
                    counts[name] = counts.get(name, 0) + 99
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    for name in astutil.target_names(stmt.target):
                        counts[name] = counts.get(name, 0) + 99
                collect(stmt.body, True)
                collect(stmt.orelse, True)
                continue
            for _field, sub in ast.iter_fields(stmt):
                if isinstance(sub, list):
                    collect(
                        [s for s in sub if isinstance(s, ast.stmt)], in_loop
                    )

    collect(list(getattr(scope.node, "body", [])), False)
    single = {n for n, c in counts.items()
              if c == 1 and n not in loop_values}
    carried = {n for n in loop_values
               if counts.get(n) == 1 and n in values}
    for name in set(facts) & (set(counts) - single):
        facts[name] = UNKNOWN  # rebound locally: parent fact is stale

    # guard seeding — BEFORE bindings (a binding like `tile =
    # round_up(row_tile, 8)` needs row_tile's guard fact), and
    # re-intersected after (a guard on a bound name refines its binding;
    # _intersect is idempotent so the double application is safe)
    guards: dict = {}
    for stmt in astutil.own_statements(scope.node):
        if not isinstance(stmt, ast.If) or not _raise_only(stmt.body):
            continue
        hit = _guard_fact(stmt.test) or _bool_guard_fact(stmt.test)
        if hit is not None:
            name, f = hit
            guards[name] = _intersect(guards.get(name, UNKNOWN), f)
    for name, g in guards.items():
        facts[name] = _intersect(facts.get(name, UNKNOWN), g)
    for _ in range(2):
        for name in single:
            f = eval_expr(mod, values[name], facts)
            if f != UNKNOWN:
                facts[name] = f

    # loop-carried bindings: ascending join fixpoint from the init fact,
    # each loop rebind evaluated under the current candidate. On early
    # stabilization the candidate is an inductive invariant; past the
    # pass budget the still-moving bounds widen to unknown and only the
    # divisor chain — monotone under gcd, so guaranteed to settle — is
    # iterated to ITS fixpoint (`tile = 8` then `tile = _round_up(tile,
    # 128)` keeps mult 8 and gains the 8..128 hull).
    for name in carried:
        f = eval_expr(mod, values[name], facts)

        def step(cur):
            facts[name] = cur
            nxt = cur
            for expr in loop_values[name]:
                nxt = _join(nxt, eval_expr(mod, expr, facts))
            return nxt

        for _ in range(_LOOP_PASSES):
            nxt = step(f)
            if nxt == f:
                break
            f = nxt
        else:
            f = Fact(None, None, f.mult)
            while True:
                nxt = Fact(None, None, step(f).mult)
                if nxt == f:
                    break
                f = nxt
        facts[name] = f

    # one more settle pass: singles downstream of a loop-carried name
    for name in single:
        f = eval_expr(mod, values[name], facts)
        if f != UNKNOWN:
            facts[name] = f
    for name, g in guards.items():
        facts[name] = _intersect(facts.get(name, UNKNOWN), g)
    return facts


def has_vmem_guard(mod, scope) -> bool:
    """A ``if not *fits_vmem(...): raise`` guard in scope or a lexical
    parent — the site runtime-gates its working set already."""
    cur = scope
    while cur is not None:
        for stmt in astutil.own_statements(cur.node):
            if not isinstance(stmt, ast.If) or not _raise_only(stmt.body):
                continue
            test = stmt.test
            if isinstance(test, ast.UnaryOp) and isinstance(
                test.op, ast.Not
            ):
                test = test.operand
            if isinstance(test, ast.Call):
                name = mod.canonical(test.func)
                if name is None and isinstance(test.func, ast.Name):
                    name = test.func.id
                if name is not None and "fits_vmem" in name:
                    return True
        cur = cur.parent
    return False
