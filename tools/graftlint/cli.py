"""graftlint command line: ``python -m tools.graftlint [paths...]``.

Exit status: 0 clean, 1 findings, 2 usage error — the same contract as
ruff's, so CI treats both lint steps identically.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.graftlint.engine import GraftlintError, run_lint


def main(argv: list | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description=(
            "JAX-aware static analysis for mpitree_tpu: host-sync (GL01), "
            "recompile (GL02), collective (GL03) and dtype/tiling (GL04) "
            "invariants."
        ),
    )
    parser.add_argument(
        "paths", nargs="*", default=["mpitree_tpu"],
        help="files or package directories to lint (default: mpitree_tpu)",
    )
    parser.add_argument(
        "--format", choices=("human", "json"), default="human",
    )
    parser.add_argument(
        "--select", metavar="RULES",
        help="comma-separated rule ids to run (e.g. GL01,GL03)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print rule ids and one-line docs, then exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        from tools.graftlint.rules import RULE_DOCS

        for rid, doc in sorted(RULE_DOCS.items()):
            print(f"{rid}  {doc}")
        return 0

    rules = None
    if args.select:
        from tools.graftlint.rules import RULE_DOCS

        rules = [r.strip().upper() for r in args.select.split(",")]
        unknown = [r for r in rules if r not in RULE_DOCS]
        if unknown:
            print(
                f"graftlint: unknown rule id(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(RULE_DOCS))})",
                file=sys.stderr,
            )
            return 2

    try:
        findings, suppressed = run_lint(args.paths, rules)
    except GraftlintError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps(
            {
                "findings": [f.as_dict() for f in findings],
                "suppressed": suppressed,
            },
            indent=2,
        ))
    else:
        for f in findings:
            print(f.format_human())
        tail = f" ({suppressed} suppressed)" if suppressed else ""
        print(
            f"graftlint: {len(findings)} finding"
            f"{'' if len(findings) == 1 else 's'}{tail}",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
