"""graftlint command line: ``python -m tools.graftlint [paths...]``.

Exit status: 0 clean, 1 findings, 2 usage error — the same contract as
ruff's, so CI treats both lint steps identically.

Output formats:

- ``human`` (default): one ``path:line:col: RULE message`` line per
  finding, summary on stderr — the ``make lint-graft`` view.
- ``json``: a version-pinned object (``tests/test_graftlint.py`` holds the
  golden schema) for tooling.
- ``github``: GitHub Actions annotation lines (``::error file=...``) so CI
  findings land inline on the PR diff.

Baseline workflow: ``--baseline [FILE]`` diffs findings against a
checked-in snapshot (default ``tools/graftlint/baseline.json``) and fails
only on NEW findings — a strict rule family can land while pre-existing
annotated sites are burned down. ``--write-baseline [FILE]`` regenerates
the snapshot from the current findings (``make lint-baseline``).

``--explain GLnn`` prints a rule's full rationale and fix guidance (the
rule module's docstring) without linting anything.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.graftlint.engine import (
    GraftlintError,
    apply_baseline,
    load_baseline,
    run_lint,
)

DEFAULT_BASELINE = "tools/graftlint/baseline.json"
JSON_SCHEMA_VERSION = 1


def _print_json(findings, suppressed, known_count):
    print(json.dumps(
        {
            "version": JSON_SCHEMA_VERSION,
            "findings": [f.as_dict() for f in findings],
            "suppressed": suppressed,
            "baselined": known_count,
        },
        indent=2,
    ))


def _print_github(findings):
    for f in findings:
        # the message is a single line by construction; commas/colons are
        # legal in the free-text part of an annotation
        print(
            f"::error file={f.path},line={f.line},col={f.col},"
            f"title=graftlint {f.rule}::{f.message}"
        )


def main(argv: list | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description=(
            "JAX-aware static analysis for mpitree_tpu: host-sync (GL01), "
            "recompile (GL02), collective (GL03), dtype/tiling (GL04), "
            "donation (GL05/GL08), host-callback (GL06) and Pallas (GL07) "
            "invariants, project contracts — partition-spec conformance "
            "(GL09), the env-knob registry (GL10), lock discipline (GL11) "
            "and ledger congruence (GL12) — plus the GL00 "
            "unused-suppression audit."
        ),
    )
    parser.add_argument(
        "paths", nargs="*", default=["mpitree_tpu"],
        help="files or package directories to lint (default: mpitree_tpu)",
    )
    parser.add_argument(
        "--format", choices=("human", "json", "github"), default="human",
    )
    parser.add_argument(
        "--select", metavar="RULES",
        help="comma-separated rule ids to run (e.g. GL01,GL03)",
    )
    parser.add_argument(
        "--baseline", nargs="?", const=DEFAULT_BASELINE, metavar="FILE",
        help=(
            "diff findings against a baseline snapshot and fail only on "
            f"new ones (default file: {DEFAULT_BASELINE})"
        ),
    )
    parser.add_argument(
        "--write-baseline", nargs="?", const=DEFAULT_BASELINE,
        metavar="FILE",
        help="write the current findings as the new baseline, then exit 0",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print rule ids and one-line docs, then exit",
    )
    parser.add_argument(
        "--explain", metavar="RULE",
        help="print a rule's full rationale and fix guidance, then exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        from tools.graftlint.rules import RULE_DOCS

        for rid, doc in sorted(RULE_DOCS.items()):
            print(f"{rid}  {doc}")
        return 0

    if args.explain:
        from tools.graftlint.rules import RULE_EXPLAIN

        rid = args.explain.strip().upper()
        text = RULE_EXPLAIN.get(rid)
        if text is None:
            print(
                f"graftlint: unknown rule id: {rid} "
                f"(known: {', '.join(sorted(RULE_EXPLAIN))})",
                file=sys.stderr,
            )
            return 2
        print(text)
        return 0

    rules = None
    if args.select:
        from tools.graftlint.rules import RULE_DOCS

        rules = [r.strip().upper() for r in args.select.split(",")]
        unknown = [r for r in rules if r not in RULE_DOCS]
        if unknown:
            print(
                f"graftlint: unknown rule id(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(RULE_DOCS))})",
                file=sys.stderr,
            )
            return 2
        if rules == ["GL00"]:
            # GL00 audits the suppressions of rules that RAN — alone it
            # could only report a guaranteed-empty (misleadingly green)
            # result
            print(
                "graftlint: --select GL00 needs the rules whose "
                "suppressions it audits — add them (e.g. GL00,GL01) or "
                "drop --select",
                file=sys.stderr,
            )
            return 2

    try:
        findings, suppressed = run_lint(args.paths, rules)

        if args.write_baseline:
            payload = {
                "version": JSON_SCHEMA_VERSION,
                "findings": [f.as_dict() for f in findings],
            }
            with open(args.write_baseline, "w", encoding="utf-8") as fh:
                json.dump(payload, fh, indent=2)
                fh.write("\n")
            print(
                f"graftlint: baseline {args.write_baseline} written "
                f"({len(findings)} finding"
                f"{'' if len(findings) == 1 else 's'})",
                file=sys.stderr,
            )
            return 0

        known_count = 0
        if args.baseline:
            findings, known = apply_baseline(
                findings, load_baseline(args.baseline)
            )
            known_count = len(known)
    except GraftlintError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.format == "json":
        _print_json(findings, suppressed, known_count)
    elif args.format == "github":
        _print_github(findings)
        print(
            f"graftlint: {len(findings)} new finding"
            f"{'' if len(findings) == 1 else 's'}"
            f" ({known_count} baselined, {suppressed} suppressed)",
            file=sys.stderr,
        )
    else:
        for f in findings:
            print(f.format_human())
        parts = []
        if args.baseline:
            parts.append(f"{known_count} baselined")
        if suppressed:
            parts.append(f"{suppressed} suppressed")
        tail = f" ({', '.join(parts)})" if parts else ""
        print(
            f"graftlint: {len(findings)}"
            f"{' new' if args.baseline else ''} finding"
            f"{'' if len(findings) == 1 else 's'}{tail}",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
