"""Dev tooling (graftlint, TPU watcher, MPI-baseline measurement)."""
