"""Retry missing BENCH_TPU.jsonl sections whenever the tunnel is healthy.

The tunneled accelerator drops without warning mid-run (round 3: down all
round; round 4: hung 20 minutes into the first capture). This watcher probes
the device in a bounded subprocess and, on a healthy window, runs ONE
missing bench_tpu.py section at a time (each run appends its own line;
bench_tpu.latest_line merges per-section newest-wins). A hang costs one
section budget, not the whole capture.

Usage:  python tools/tpu_watcher.py [--sections a,b,c] [--deadline-s N]
Log:    TPU_WATCHER.log at the repo root — committed as evidence of tunnel
        health over the round either way.
While a section is measuring, flag file /tmp/tpu_bench_running exists —
long CPU-heavy jobs in the same box should wait on it to avoid distorting
the host-side phases of the measurement.
"""

import argparse
import glob
import os
import shutil
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TPU_WATCHER.log")
JSONL = os.path.join(REPO, "BENCH_TPU.jsonl")
FLAG = "/tmp/tpu_bench_running"
TRACE_DIR = os.path.join(REPO, "traces")

if REPO not in sys.path:
    sys.path.insert(0, REPO)

PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices();"
    "assert d and d[0].platform in ('tpu', 'axon'), d;"
    "x = jnp.ones((512, 512));"
    "(x @ x).block_until_ready();"
    "print('PROBE_OK', d[0].device_kind)"
)

# STATIC per-section wall budgets (s) — the fallback for sections that
# have never landed a capture. Once BENCH_TPU.jsonl carries a genuine
# line for a section, derive_budget() supersedes this table with a budget
# computed from the observed duration (the rc=-15 triage: one flat
# SECTION_TIMEOUT_S both starved the compile-heavy sections and wasted
# whole healthy windows waiting on hung cheap ones).
# engine_levelwise is dispatch-bound on the tunnel (2-4 round trips x 20
# levels + per-tier compiles); refine_sweep is 4 configs x (cold + warm)
# fits.
BUDGET = {
    "engine_levelwise": 1500,
    # 20 rounds x 7 softmax trees of levelwise gbdt dispatch on the tunnel.
    "boosting": 1500,
    # ~18 separately-compiled entries since round 5 (wide executors ×2
    # dtypes, level-op microbenches); the persistent compile cache makes
    # retries resume, but give the first attempt room to land whole.
    "hist_tput": 1200,
    "device_bin": 600,
    "forest": 1800,
    "refine_sweep": 1800,
    "north_star": 900,
    "north_star_fused": 900,
    "engine_fused": 900,
    "predict": 900,
    # The ~500-tree GBDT fit (72 levelwise softmax rounds at a 40k-row
    # cap) dominates; the serving latency sweep itself is seconds.
    "serving": 1800,
    # Two full-depth device fits (cold+warm each) + a sklearn exact-split
    # reference fit on the full training split.
    "leafwise_ab": 1800,
    # 2x16 shallow binary-logistic rounds; the host-loop side pays 16
    # levelwise dispatch rounds on the tunnel.
    "gbdt_fusedK": 1200,
    # Two streaming passes over the covtype training split (host sketch +
    # chunked bin/placement) plus one streamed and one in-memory fit for
    # the identity pin.
    "ingest": 1200,
}


# Derived-budget envelope: observed in-section seconds miss subprocess
# overhead (interpreter + data load + recompiles after code changes), so
# scale generously and add slack; clamp so a one-off outlier capture can
# neither starve a section nor let one hang eat a whole healthy window.
BUDGET_HEADROOM = 2.5
BUDGET_SLACK_S = 180
BUDGET_MIN_S = 420
BUDGET_MAX_S = 3600


def derive_budget(sec: str, path: str = JSONL) -> tuple[int, str]:
    """(budget_s, why): evidence-derived per-section budget.

    Uses the max observed in-section wall from genuine BENCH_TPU.jsonl
    captures (bench_tpu.observed_section_seconds — the one copy of the
    line predicate) scaled by HEADROOM + SLACK; falls back to the static
    BUDGET table for never-captured sections. The ``why`` string lands in
    the committed log so every timeout verdict carries its budget's
    provenance.
    """
    static = BUDGET.get(sec, 1200)
    try:
        from bench_tpu import observed_section_seconds

        observed = observed_section_seconds(sec, path)
    except Exception as e:  # noqa: BLE001 — a broken jsonl must not stop
        return static, f"static table ({type(e).__name__} reading captures)"
    if not observed:
        return static, "static table (no capture yet)"
    derived = int(
        min(max(BUDGET_HEADROOM * observed + BUDGET_SLACK_S, BUDGET_MIN_S),
            BUDGET_MAX_S)
    )
    return derived, f"derived from observed {observed:.0f}s"


def _obs_module(name: str):
    """An obs module (trace/flight/diff) loaded BY FILE PATH — all three
    are stdlib-only by contract, so the watcher works without importing
    the mpitree_tpu package (and its jax dependency) on the babysitting
    host. One shared sys.modules-cached loader (bench_tpu's) — the
    watcher already imports bench_tpu helpers."""
    from bench_tpu import _obs_module as load

    return load(name)


def _trace_module():
    return _obs_module("trace")


def merge_section_trace(sec: str) -> str | None:
    """Merge the section's per-fit trace files (written by the child via
    MPITREE_TPU_TRACE_DIR) into ONE Perfetto-loadable file next to
    BENCH_TPU.jsonl — the rc=-15 diagnosability satellite: whatever a
    killed section managed to trace survives the kill, and the committed
    log points at it. Returns the merged path, or None when the section
    wrote no trace (never raises — a broken trace must not stop the
    capture loop)."""
    try:
        files = glob.glob(
            os.path.join(TRACE_DIR, sec, "trace_*.json")
        )
        if not files:
            return None
        return _trace_module().merge_trace_files(
            files, os.path.join(REPO, f"TRACE_{sec}.trace.json")
        )
    except Exception as e:  # noqa: BLE001 — telemetry, not the capture
        log(f"{sec}: trace merge failed ({type(e).__name__}: {e})")
        return None


def tail_lines(path: str, n: int) -> list:
    """Last n non-empty lines of a (possibly still-growing) text file."""
    try:
        with open(path, errors="replace") as f:
            lines = [ln.strip() for ln in f if ln.strip()]
        return lines[-n:]
    except OSError:
        return []


def log(msg: str) -> None:
    line = f"{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())} {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe_ok(timeout_s: int = 75) -> bool:
    try:
        r = subprocess.run(
            [sys.executable, "-c", PROBE_SRC], capture_output=True,
            text=True, timeout=timeout_s,
        )
        return r.returncode == 0 and "PROBE_OK" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def section_done(sec: str, path: str = JSONL) -> bool:
    """True if the merged FULL-WORKLOAD TPU picture carries this section.

    Delegates to bench_tpu.latest_line so the watcher's notion of "done"
    can never drift from what the embed actually includes (same accelerator
    filter, same workload-key grouping). full_only: an operator's --rows
    smoke line must neither satisfy a section nor re-key the merge away
    from the full workload this watcher exists to capture.
    """
    from bench_tpu import latest_line

    return sec in (latest_line(path, full_only=True) or {})


def capture_count(sec: str, path: str = JSONL) -> int:
    """How many genuine full-workload lines in the FILE carry this section.

    Counts raw lines, NOT latest_line's merge: a --redo run must produce a
    NEW line (the pre-existing capture would make a plain done-check claim
    success for a failed rerun), and a run whose line re-keys the merge's
    workload group must still count as captured. A concurrent operator run
    appending the same section is indistinguishable here — acceptable for
    a babysitting tool whose worst case is one redundant re-measure.
    The line predicate and the tolerant parse are bench_tpu's (the one
    copy — see is_genuine_capture).
    """
    from bench_tpu import is_genuine_capture, read_capture_lines

    return sum(
        1 for r in read_capture_lines(path)
        if is_genuine_capture(r, full_only=True) and sec in r
    )


def build_todo(sections: str, redo: str, path: str = JSONL) -> list:
    """Capture queue: --sections order IS the priority (healthy windows
    are short — highest-evidence first). A section that is already
    captured is skipped unless also named in --redo, in which case it
    KEEPS its position; redo-only names append at the end."""
    redo_set = {s for s in redo.split(",") if s}
    todo = [s for s in sections.split(",")
            if s and (s in redo_set or not section_done(s, path))]
    todo += [s for s in redo.split(",") if s and s not in todo]
    return todo


def run_section(sec: str) -> bool:
    budget, why = derive_budget(sec)
    before = capture_count(sec)
    # Per-section span timeline (ISSUE 9): the child's fits auto-trace
    # into traces/<sec>/ via MPITREE_TPU_TRACE_DIR; merged next to
    # BENCH_TPU.jsonl afterwards — so the next rc=-15 verdict shows WHERE
    # inside the section time went, not just that it died.
    sec_trace_dir = os.path.join(TRACE_DIR, sec)
    # Fresh per run: a --redo or retry-after-NOT-captured must not merge
    # a previous round's trace files into this run's timeline (and a
    # recycled pid could even silently overwrite one).
    shutil.rmtree(sec_trace_dir, ignore_errors=True)
    log(f"run {sec} (budget {budget}s, {why}; trace -> {sec_trace_dir})")
    open(FLAG, "w").close()
    outpath = f"/tmp/tpu_watcher_{sec}.out"
    # The flight store (ISSUE 13): the CHILD appends the section envelope
    # (bench_tpu.flight_append_section — it knows the resolved platform
    # and the workload config; the watcher appending too would split the
    # lineage across two config digests). The watcher only injects the
    # store location and logs the verdict afterwards.
    child_env = {
        **os.environ,
        "MPITREE_TPU_TRACE_DIR": sec_trace_dir,
        "MPITREE_TPU_RUN_DIR": (
            os.environ.get("MPITREE_TPU_RUN_DIR")
            or os.path.join(REPO, "runs")
        ),
    }
    try:
        # Child stdout goes to a FILE, not a pipe: a hung child cannot
        # deadlock on a full pipe buffer, and — the rc=-15 diagnosability
        # fix — the parent can read everything the section printed BEFORE
        # deciding to kill it, so a timeout verdict in the committed log
        # always says where the section died.
        # Own process group: on parent timeout the section-worker
        # GRANDCHILD must die too, or an orphan keeps holding the flaky
        # TPU while the next section starts (device contention on exactly
        # the tunnel this tool babysits).
        with open(outpath, "w") as outf:
            proc = subprocess.Popen(
                [sys.executable, os.path.join(REPO, "bench_tpu.py"),
                 "--sections", sec, "--timeout", str(budget),
                 "--platform", "tpu"],
                stdout=outf, stderr=subprocess.STDOUT, text=True,
                cwd=REPO, start_new_session=True, env=child_env,
            )
            t0 = time.time()
            try:
                proc.wait(timeout=budget + 300)
                tail = tail_lines(outpath, 3)
                log(f"{sec}: rc={proc.returncode} | " + " / ".join(tail))
            except subprocess.TimeoutExpired:
                # Partial-section progress BEFORE the kill — the evidence
                # of WHERE the section died and how far it got, with the
                # budget's provenance and the trace file carrying the
                # intra-section timeline of everything that completed.
                partial = tail_lines(outpath, 6)
                merged = merge_section_trace(sec)
                log(f"{sec}: parent timeout after {time.time() - t0:.0f}s "
                    f"(budget {budget}+300s, {why}); trace "
                    f"{merged or f'<none in {sec_trace_dir}>'}; "
                    "progress before kill | "
                    + (" / ".join(partial) if partial else "<no output>"))
                log(f"{sec}: killing process group")
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                # A child stuck in uninterruptible device I/O can survive
                # SIGKILL for a while; never let that crash the watcher.
                try:
                    proc.wait(timeout=30)
                except (subprocess.TimeoutExpired, OSError, ValueError):
                    log(f"{sec}: child unreaped after SIGKILL "
                        f"(uninterruptible device I/O?) — moving on")
    finally:
        try:
            os.remove(FLAG)
        except OSError:
            pass
    done = capture_count(sec) > before
    log(f"{sec}: {'captured' if done else 'NOT captured'}")
    if done:
        merged = merge_section_trace(sec)
        if merged:
            log(f"{sec}: trace | {merged}")
        # One-line run-record digest next to the capture verdict: the next
        # slow-section mystery (rounds 3-4 cost whole windows to exactly
        # this) arrives with its engine decision, recompile count, psum
        # payload, and (v6) the obs.memory ledger's predicted per-device
        # peak HBM (hbm_peak=...) already attributed in the committed log
        # — an on-hardware RESOURCE_EXHAUSTED kill reads its suspect
        # straight off this line.
        from bench_tpu import section_record_digest

        digest = section_record_digest(sec)
        if digest:
            log(f"{sec}: record | {digest}")
        flight_section(sec)
    return done


def flight_section(sec: str) -> None:
    """Log the just-captured section's regression verdict vs its stored
    history (ISSUE 13): the next hardware round produces its own
    trajectory analysis in the committed log instead of a bare JSONL
    line. The flight-store APPEND itself happened in the bench_tpu child
    (run_section injects ``MPITREE_TPU_RUN_DIR``); appending here too
    would store every capture twice under two lineages. Best-effort —
    telemetry never stops the capture loop."""
    try:
        from bench_tpu import read_capture_lines

        payloads = [
            rec[sec] for rec in read_capture_lines(JSONL)
            if isinstance(rec.get(sec), dict)
        ]
        if not payloads:
            return
        diffm = _obs_module("diff")
        if len(payloads) >= 2:
            d = diffm.diff_payloads(
                payloads[-2], payloads[-1], history=payloads[:-1]
            )
            log(f"{sec}: verdict | " + diffm.summary_line(d, label=sec))
        else:
            log(f"{sec}: verdict | first capture of this section — "
                "stored as the baseline")
    except Exception as e:  # noqa: BLE001 — telemetry, not the capture
        log(f"{sec}: flight append failed ({type(e).__name__}: {e})")


def stage_round_artifacts() -> None:
    """Stage the round's committed evidence — including the flight
    store's verdict trajectories (ISSUE 15 satellite, the PR-13
    follow-up): ``run_section`` injects ``runs/`` as the default
    ``MPITREE_TPU_RUN_DIR``, so every capture's envelope lands there,
    but nothing put the store into the round commit — after a round the
    operator committed BENCH_TPU.jsonl + the log while the lineage
    history (what ``--baseline`` and ``flight_section`` verdict against
    next round) stayed untracked on one box. Best-effort ``git add`` of
    the four artifact paths; the operator still reviews and commits.
    """
    run_dir = os.environ.get("MPITREE_TPU_RUN_DIR") or os.path.join(
        REPO, "runs"
    )
    paths = [JSONL, LOG, TRACE_DIR, run_dir]
    stage = [p for p in paths if os.path.exists(p)
             and os.path.abspath(p).startswith(REPO + os.sep)]
    if not stage:
        return
    try:
        r = subprocess.run(
            ["git", "add", "--"] + stage,
            cwd=REPO, capture_output=True, text=True, timeout=30,
        )
        if r.returncode == 0:
            log("round artifacts staged for commit: "
                + ", ".join(os.path.relpath(s, REPO) for s in stage))
        else:
            log(f"git add skipped (rc={r.returncode}): "
                f"{(r.stderr or '').strip()[:200]}")
    except (OSError, subprocess.SubprocessError) as e:
        log(f"git add skipped ({type(e).__name__}: {e})")


def main() -> int:
    p = argparse.ArgumentParser()
    # Value-ranked queue (the --sections order IS the priority): the
    # highest-evidence sections first — hist_tput (kernel go/no-go
    # numbers), north_star (the headline), engine_fused (crossover),
    # boosting (the new workload) — then the rest.
    p.add_argument("--sections",
                   default="hist_tput,north_star,engine_fused,boosting,"
                           "leafwise_ab,gbdt_fusedK,mesh2d_ab,serving,"
                           "ingest,device_bin,north_star_fused,"
                           "engine_levelwise,forest,refine_sweep")
    p.add_argument("--redo", default="",
                   help="comma-separated sections to re-measure even if "
                        "already captured (appended after the missing "
                        "ones; latest_line merges newest-wins, so a redo "
                        "under improved code supersedes the old number)")
    p.add_argument("--deadline-s", type=int, default=6 * 3600)
    p.add_argument("--probe-every-s", type=int, default=150)
    args = p.parse_args()

    todo = build_todo(args.sections, args.redo)
    t_end = time.time() + args.deadline_s
    log(f"watcher start, todo={todo}")
    while todo and time.time() < t_end:
        if not probe_ok():
            log("probe: tunnel down/hung")
            time.sleep(args.probe_every_s)
            continue
        log("probe: healthy")
        sec = todo[0]
        if run_section(sec):
            todo.pop(0)
        else:
            # Rotate so one persistently-failing section cannot starve the
            # rest for the whole deadline; a hang mid-section usually means
            # the tunnel dropped again, so back off before reprobing.
            todo.append(todo.pop(0))
            time.sleep(args.probe_every_s)
    stage_round_artifacts()
    log(f"watcher exit, remaining={todo}")
    return 0 if not todo else 1


if __name__ == "__main__":
    sys.exit(main())
