"""benchdiff — the regression gate over stored run/bench artifacts.

Thin CLI over ``mpitree_tpu/obs/diff.py`` + ``obs/flight.py`` (both
loaded BY FILE PATH — stdlib-only by contract, so this runs on any CPU
box with no jax install: the graftlint/tpu_watcher precedent). Three
comparison sources, one verdict grammar:

- ``--bench A.json B.json ...`` — committed ``BENCH_rNN.json`` driver
  artifacts (CPU baselines): the NEWEST file is the candidate, the
  previous parseable one the baseline, everything earlier the history
  that seeds noise thresholds. ``make bench-diff`` / CI gate.
- ``--jsonl BENCH_TPU.jsonl --section north_star`` — the newest stored
  section payload vs the previous capture of the same section.
- ``--store <run_dir> [--kind fit] [--section S]`` — the newest flight
  envelope vs its lineage baseline (``obs.flight.FlightStore``). With
  ``--cross-platform tpu``: vs its sibling lineage on another backend
  instead — structural metrics only (psum/wire/nodes/fingerprint),
  advisory warnings, always exit 0.
- two positional paths — ``dump_report(path)`` JSON files (full
  BuildRecords): digest metrics compare AND fingerprint divergence
  bisects to the first divergent (tree, level, channel).

Exit code: 0 for ok/changed/improved, 1 for regression/diverged (the
gate), 2 for usage/IO problems. ``--format github`` emits workflow
annotations (the graftlint idiom).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# flight.py reaches env through mpitree_tpu.config.knobs (the GL10 single
# read path — itself stdlib-only, no jax); keep the script-entry form
# (``python tools/benchdiff.py``) working alongside ``-m tools.benchdiff``.
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _load(name: str):
    """Load an obs module by file path (no package import, no jax).

    Registered in ``sys.modules`` BEFORE exec: record.py defines a
    dataclass, and dataclass field resolution looks the defining module
    up by name — an unregistered module crashes it."""
    modname = f"_benchdiff_{name}"
    if modname in sys.modules:
        return sys.modules[modname]
    spec = importlib.util.spec_from_file_location(
        modname,
        os.path.join(REPO, "mpitree_tpu", "obs", f"{name}.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    return mod


# The curated BENCH_rNN comparison set: our build's wall/accuracy/
# throughput and the headline speedup. Reference-side walls (sklearn_s,
# mpi8_*) are environment measurements, not ours — gating on them would
# fail CI on a slow runner with zero code change.
BENCH_METRICS = (
    "value", "vs_baseline", "ours_test_acc", "acc_delta_vs_sklearn",
    "throughput_cells_per_s", "tree_n_nodes", "tree_depth",
)


def bench_metrics(path: str) -> dict | None:
    """{metric: value} from one BENCH_rNN.json driver artifact, or None
    when its ``parsed`` payload is missing (a failed round — skipped,
    the tolerant-history contract)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    parsed = doc.get("parsed") if isinstance(doc, dict) else None
    if not isinstance(parsed, dict):
        return None
    flat = dict(parsed)
    detail = parsed.get("detail")
    if isinstance(detail, dict):
        for k, v in detail.items():
            flat.setdefault(k, v)
    return {
        k: flat[k] for k in BENCH_METRICS
        if isinstance(flat.get(k), (int, float))
        and not isinstance(flat.get(k), bool)
    }


def _env(metrics: dict | None = None, digest: dict | None = None,
         record: dict | None = None) -> dict:
    return {"metrics": metrics or {}, "digest": digest or {},
            "record": record}


def diff_bench(paths: list, diff_mod) -> tuple:
    """(diff, label) over BENCH_rNN artifacts, newest = candidate."""
    rows = [(p, bench_metrics(p)) for p in paths]
    usable = [(p, m) for p, m in rows if m]
    if len(usable) < 2:
        return None, (
            f"need >= 2 parseable BENCH artifacts, got {len(usable)} of "
            f"{len(paths)} (rounds with parsed=null are skipped)"
        )
    (bp, bm), (cp, cm) = usable[-2], usable[-1]
    history = [_env(metrics=m) for _p, m in usable[:-1]]
    d = diff_mod.diff_envelopes(
        _env(metrics=bm), _env(metrics=cm), history=history
    )
    return d, f"{os.path.basename(bp)} -> {os.path.basename(cp)}"


def diff_jsonl(path: str, section: str, diff_mod) -> tuple:
    """Newest vs previous stored payload of one BENCH_TPU.jsonl section."""
    payloads = []
    try:
        with open(path) as f:
            for ln in f:
                if not ln.strip():
                    continue
                try:
                    rec = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                p = rec.get(section) if isinstance(rec, dict) else None
                if isinstance(p, dict):
                    payloads.append(p)
    except OSError as e:
        return None, f"cannot read {path}: {e}"
    if len(payloads) < 2:
        return None, (
            f"section {section!r} has {len(payloads)} stored payload(s) "
            "in the jsonl; need >= 2 to diff"
        )
    d = diff_mod.diff_payloads(
        payloads[-2], payloads[-1], history=payloads[:-1]
    )
    return d, f"{section} (jsonl history n={len(payloads)})"


def diff_store(root: str, diff_mod, flight_mod, *, kind=None,
               section=None, platform=None) -> tuple:
    """Newest flight envelope vs its lineage baseline.

    One store read: entries() parses the whole JSONL (envelopes can
    embed full BuildRecords), so latest/baseline/history derive from a
    single pass instead of three."""
    store = flight_mod.FlightStore(root)
    rows = store.entries(kind=kind, section=section, platform=platform)
    if not rows:
        return None, f"no entries in {store.path} match the filters"
    cand = rows[-1]
    lineage_key = tuple(cand.get(k) for k in flight_mod.LINEAGE_KEYS)
    history = [
        e for e in rows[:-1]
        if tuple(e.get(k) for k in flight_mod.LINEAGE_KEYS) == lineage_key
    ]
    if not history:
        return None, (
            "newest entry has no lineage baseline yet (first run of this "
            f"config on {cand.get('platform')}) — nothing to diff"
        )
    d = diff_mod.diff_envelopes(history[-1], cand, history=history)
    label = (
        f"{cand.get('kind')}:{cand.get('section') or cand.get('config_digest')}"
        f" @ {cand.get('platform')}"
    )
    return d, label


def _structural_env(env: dict, diff_mod) -> dict:
    """The envelope with every non-structural metric stripped. Across
    platforms only deterministic channels compare (psum/wire bytes, node
    counts, fingerprints); walls and rates measure different silicon."""
    def keep(d: dict | None) -> dict:
        return {
            k: v for k, v in (d or {}).items()
            if k == "fingerprint"
            or (diff_mod.spec_for(k) or {}).get("kind") == "structural"
        }
    return {"metrics": keep(env.get("metrics")),
            "digest": keep(env.get("digest")),
            "record": env.get("record")}


def diff_cross_platform(root: str, diff_mod, flight_mod, *, kind=None,
                        section=None, platform=None, other: str) -> tuple:
    """Newest flight envelope vs its sibling lineage on ``other``
    (same kind/section/config digest, different backend). Structural
    metrics only — advisory, never the gate: a CPU-smoke lineage warns
    about wire/psum/fingerprint drift before TPU hardware sees it."""
    store = flight_mod.FlightStore(root)
    rows = store.entries(kind=kind, section=section, platform=platform)
    if not rows:
        return None, f"no entries in {store.path} match the filters"
    cand = rows[-1]
    if cand.get("platform") == other:
        return None, (
            f"newest entry is already on {other!r}; pass --platform to "
            "pick the candidate side"
        )
    siblings = store.sibling_lineage(cand, platform=other)
    if not siblings:
        return None, (
            f"no {other!r} sibling lineage for the newest "
            f"{cand.get('platform')!r} entry "
            f"(kind={cand.get('kind')}, section={cand.get('section')}) "
            "— capture the same config there first"
        )
    d = diff_mod.diff_envelopes(
        _structural_env(siblings[-1], diff_mod),
        _structural_env(cand, diff_mod),
        history=[_structural_env(e, diff_mod) for e in siblings],
    )
    label = (
        f"{cand.get('kind')}:{cand.get('section') or cand.get('config_digest')}"
        f" @ {other} -> {cand.get('platform')} (structural only)"
    )
    return d, label


def diff_reports(base_path: str, cand_path: str, diff_mod) -> tuple:
    """Two dump_report(path) JSON files — full BuildRecord diff."""
    try:
        with open(base_path) as f:
            base = json.load(f)
        with open(cand_path) as f:
            cand = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return None, f"cannot read reports: {e}"
    # The record digest needs the obs digest function — record.py is
    # stdlib-only too, so it loads the same way.
    record_mod = _load("record")
    d = diff_mod.diff_envelopes(
        _env(digest=record_mod.digest(base), record=base),
        _env(digest=record_mod.digest(cand), record=cand),
    )
    return d, (
        f"{os.path.basename(base_path)} -> {os.path.basename(cand_path)}"
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="benchdiff", description=__doc__.splitlines()[0]
    )
    p.add_argument("reports", nargs="*",
                   help="two dump_report JSON files (base, candidate)")
    p.add_argument("--bench", nargs="+", metavar="BENCH_rNN.json",
                   help="committed driver artifacts, oldest first; "
                        "newest = candidate, earlier = history")
    p.add_argument("--jsonl", help="BENCH_TPU.jsonl to read --section from")
    p.add_argument("--section", help="section name (with --jsonl/--store)")
    p.add_argument("--store", metavar="RUN_DIR",
                   help="flight run dir (obs.flight store)")
    p.add_argument("--kind", default=None,
                   help="flight envelope kind filter (fit/serve/bench)")
    p.add_argument("--platform", default=None)
    p.add_argument("--cross-platform", metavar="PLATFORM", default=None,
                   help="with --store: compare the newest envelope "
                        "against its sibling lineage on PLATFORM "
                        "(structural metrics only; warns, exit 0)")
    p.add_argument("--format", choices=("human", "github"),
                   default="human")
    p.add_argument("--json", action="store_true",
                   help="print the full diff dict as JSON")
    args = p.parse_args(argv)

    diff_mod = _load("diff")
    if args.bench:
        d, label = diff_bench(args.bench, diff_mod)
    elif args.jsonl:
        if not args.section:
            print("benchdiff: --jsonl needs --section", file=sys.stderr)
            return 2
        d, label = diff_jsonl(args.jsonl, args.section, diff_mod)
    elif args.store and args.cross_platform:
        d, label = diff_cross_platform(
            args.store, diff_mod, _load("flight"), kind=args.kind,
            section=args.section, platform=args.platform,
            other=args.cross_platform,
        )
        if d is None:
            print(f"benchdiff: {label}", file=sys.stderr)
            return 2
        print(f"benchdiff {label}")
        print(diff_mod.format_diff(d, args.format))
        if args.json:
            print(json.dumps(d, indent=2, sort_keys=True))
        if diff_mod.exit_code(d):
            # Advisory by contract: cross-backend divergence is a heads-up
            # for the hardware run, not a CI failure.
            print(
                "benchdiff: cross-platform divergence is advisory "
                "(warning, not a gate)"
            )
        return 0
    elif args.store:
        d, label = diff_store(
            args.store, diff_mod, _load("flight"), kind=args.kind,
            section=args.section, platform=args.platform,
        )
    elif len(args.reports) == 2:
        d, label = diff_reports(args.reports[0], args.reports[1], diff_mod)
    else:
        p.print_usage(sys.stderr)
        print(
            "benchdiff: pass two report files, --bench, --jsonl, or "
            "--store", file=sys.stderr,
        )
        return 2

    if d is None:
        print(f"benchdiff: {label}", file=sys.stderr)
        return 2
    print(f"benchdiff {label}")
    print(diff_mod.format_diff(d, args.format))
    if args.json:
        print(json.dumps(d, indent=2, sort_keys=True))
    return diff_mod.exit_code(d)


if __name__ == "__main__":
    sys.exit(main())
