"""Measure the reference's ParallelDecisionTreeClassifier at 8 ranks, for real.

SURVEY.md §6 requires the 8-rank MPI baseline to be *measured*, not inferred
from ``time_data.csv`` ratios. This launcher runs the reference's own
unmodified parallel code (``/root/reference``, imported read-only) at 8
ranks over the mpi4py shim in ``tools/mpi_shim.py``, on growing subsamples
of the bench dataset, under a wall-clock budget — plus the reference's
sequential class on the same grid for the measured parallel/sequential
shape. Results land in ``MPI8_BASELINE.json`` at the repo root, which
``bench.py`` embeds as the ``mpi8_observed_s`` source (replacing the old
/1.6 heuristic).

Honesty notes recorded in the artifact:

- This box has ONE CPU core: 8 ranks timeshare it, so the measured 8-rank
  wall-clock is an upper bound on what the reference would cost on real
  8-way hardware. ``bench.py``'s headline ``vs_baseline`` therefore keeps
  using the *ideal* variant (oracle sequential cost / 8), which is strictly
  generous to the reference; the measured curve is reported alongside.
- The reference validates with ``dtype=object`` (``decision_tree.py:184``),
  so its real cost is far above the numpy oracle's — that is the actual
  code a user of the reference runs.

Usage: ``python tools/measure_mpi8.py [--budget-s 900] [--seq-budget-s 600]``
(or ``--worker`` internally).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
_REFERENCE = "/root/reference"

N_FULL = 531012  # bench.py's training-split row count (581012 - 50k test)
DEPTH = 20
GRID = (100, 300, 1000, 3000, 10_000, 30_000)
RANKS = 8


def _power_law(ns, ts):
    b, log_a = np.polyfit(np.log(ns), np.log(ts), 1)
    resid = np.log(ts) - (log_a + b * np.log(ns))
    return {
        "exponent": round(float(b), 3),
        "rms_log_residual": round(float(np.sqrt((resid**2).mean())), 4),
        "extrapolated_full_s": round(float(np.exp(log_a) * N_FULL**b), 1),
        "measured_decades": round(float(np.log10(ns[-1] / ns[0])), 2),
        "extrapolated_decades": round(float(np.log10(N_FULL / ns[-1])), 2),
    }


# ---------------------------------------------------------------------------
# Worker (one rank; also the sequential single-process mode)
# ---------------------------------------------------------------------------


def run_worker() -> None:
    sys.path.insert(0, _REPO)
    from tools import mpi_shim

    pkg = mpi_shim.fake_mpi4py()
    sys.modules["mpi4py"] = pkg
    sys.modules["mpi4py.MPI"] = pkg.MPI
    sys.path.insert(0, _REFERENCE)
    from mpitree.tree import (  # noqa: E501 — reference import, post-shim
        DecisionTreeClassifier,
        ParallelDecisionTreeClassifier,
    )

    world = pkg.MPI.COMM_WORLD
    data = np.load(os.environ["MPI_SHIM_DATA"])
    X, y = data["X"], data["y"]
    budget = float(os.environ["MPI_SHIM_BUDGET_S"])
    seq_mode = os.environ.get("MPI_SHIM_SEQ") == "1"
    cls = DecisionTreeClassifier if seq_mode else ParallelDecisionTreeClassifier

    ns: list[int] = []
    ts: list[float] = []
    spent = 0.0
    for n in GRID:
        if n > len(X):
            break
        if len(ns) >= 2:
            b = (np.log(ts[-1]) - np.log(ts[0])) / (np.log(ns[-1]) - np.log(ns[0]))
            pred = ts[-1] * (n / ns[-1]) ** max(b, 1.0)
            if spent + pred > budget:
                break
        world.barrier()
        t0 = time.perf_counter()
        cls(max_depth=DEPTH).fit(X[:n], y[:n])
        dt = time.perf_counter() - t0
        # max over ranks = the collective completion time; identical on
        # every rank, so the adaptive grid decisions stay in lockstep
        t = max(world.allgather(dt))
        ns.append(n)
        ts.append(t)
        spent += t
        if spent > budget and len(ns) >= 2:
            break
    if world.Get_rank() == 0:
        print("MPI8_WORKER_JSON:" + json.dumps(
            {"grid": ns, "times_s": [round(t, 3) for t in ts]}
        ), flush=True)


# ---------------------------------------------------------------------------
# Launcher
# ---------------------------------------------------------------------------


def _parse_worker_json(text: str):
    for line in reversed(text.splitlines()):
        if line.startswith("MPI8_WORKER_JSON:"):
            return json.loads(line[len("MPI8_WORKER_JSON:"):])
    return None


def run_sequential(npz: str, budget_s: float, timeout_s: float):
    env = dict(os.environ, MPI_SHIM_DATA=npz, MPI_SHIM_SEQ="1",
               MPI_SHIM_BUDGET_S=str(budget_s))
    env.pop("MPI_SHIM_SOCKET", None)
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker"],
        env=env, capture_output=True, text=True, timeout=timeout_s,
    )
    return _parse_worker_json(out.stdout), out.stderr[-2000:]


def run_parallel(npz: str, budget_s: float, timeout_s: float):
    from tools import mpi_shim

    sock_path = os.path.join(
        tempfile.mkdtemp(prefix="mpi8_"), "router.sock"
    )
    router = mpi_shim.Router(sock_path, RANKS)
    accept_t = threading.Thread(target=router.accept_all, daemon=True)
    accept_t.start()
    procs = []
    try:
        for r in range(RANKS):
            env = dict(
                os.environ, MPI_SHIM_DATA=npz, MPI_SHIM_SOCKET=sock_path,
                MPI_SHIM_RANK=str(r), MPI_SHIM_SIZE=str(RANKS),
                MPI_SHIM_BUDGET_S=str(budget_s),
            )
            env.pop("MPI_SHIM_SEQ", None)
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--worker"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            ))
        deadline = time.time() + timeout_s
        outs = []
        for p in procs:
            left = max(5.0, deadline - time.time())
            try:
                outs.append(p.communicate(timeout=left))
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate())
        res = _parse_worker_json(outs[0][0] or "")
        err = (outs[0][1] or "")[-2000:]
        return res, err
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        router.close()


def main() -> None:
    budget_s = 900.0
    seq_budget_s = 600.0
    args = sys.argv[1:]
    if "--budget-s" in args:
        budget_s = float(args[args.index("--budget-s") + 1])
    if "--seq-budget-s" in args:
        seq_budget_s = float(args[args.index("--seq-budget-s") + 1])

    sys.path.insert(0, _REPO)
    from mpitree_tpu.utils.datasets import load_covtype

    X, y, name = load_covtype(40_000)
    with tempfile.NamedTemporaryFile(suffix=".npz", delete=False) as f:
        npz = f.name
    np.savez(npz, X=X, y=y)

    result = {
        "dataset": name,
        "max_depth": DEPTH,
        "n_full": N_FULL,  # the row count extrapolated_full_s refers to
        "ranks": RANKS,
        "cpu_cores": os.cpu_count(),
        "transport": "tools/mpi_shim.py unix-socket router "
                     "(mpi4py API; no mpirun/mpi4py in this environment)",
        "code_under_test": "/root/reference mpitree.tree."
                           "ParallelDecisionTreeClassifier, unmodified",
        "note": (
            f"{RANKS} ranks timeshare {os.cpu_count()} CPU core(s): the "
            "parallel wall-clock is an UPPER bound on real 8-way hardware; "
            "bench.py's headline vs_baseline keeps the ideal (sequential/8) "
            "variant and reports this measured curve as mpi8_observed"
        ),
        "captured_unix": int(time.time()),
    }
    try:
        seq, seq_err = run_sequential(npz, seq_budget_s, seq_budget_s * 3)
        if seq and len(seq["grid"]) >= 2:
            result["sequential"] = {
                **seq, **_power_law(seq["grid"], seq["times_s"]),
            }
        elif seq_err:
            result["sequential_error"] = seq_err
    except Exception as e:  # noqa: BLE001
        result["sequential_error"] = f"{type(e).__name__}: {e}"
    try:
        par, par_err = run_parallel(npz, budget_s, budget_s * 3)
        if par and len(par["grid"]) >= 2:
            result["mpi8"] = {
                **par, **_power_law(par["grid"], par["times_s"]),
            }
        elif par_err:
            result["mpi8_error"] = par_err
    except Exception as e:  # noqa: BLE001
        result["mpi8_error"] = f"{type(e).__name__}: {e}"
    finally:
        os.unlink(npz)

    if "sequential" in result and "mpi8" in result:
        shared = [
            (n, s, p)
            for (n, s) in zip(result["sequential"]["grid"],
                              result["sequential"]["times_s"])
            for (m, p) in zip(result["mpi8"]["grid"],
                              result["mpi8"]["times_s"])
            if n == m
        ]
        if shared:
            result["par_over_seq_at_shared_n"] = {
                str(n): round(p / s, 2) for n, s, p in shared
            }

    out_path = os.path.join(_REPO, "MPI8_BASELINE.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result, indent=1))


if __name__ == "__main__":
    if "--worker" in sys.argv:
        run_worker()
    else:
        main()
