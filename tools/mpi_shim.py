"""Minimal mpi4py-compatible shim: run the reference's parallel path for real.

This environment has OpenMPI's shared libraries but no launcher (``mpirun``)
and no mpi4py, so the reference's ``ParallelDecisionTreeClassifier``
(reference: ``mpitree/tree/decision_tree.py:310-479``) could never be
*measured* at 8 ranks — its baseline was a heuristic. This shim implements
the exact mpi4py surface that class touches — ``MPI.COMM_WORLD``,
``Get_rank``, ``Get_size``, ``Split(color, key)``, pickle-based
``allgather``, ``Free`` (``decision_tree.py:315-317,338,456,477``) — over
local unix-domain sockets to a router in the launcher process
(``tools/measure_mpi8.py``), with MPI's collective semantics:

- ``Split`` is collective on the communicator: the router matches the k-th
  collective call per member, partitions by color, orders each group by
  (key, parent rank), and assigns a fresh communicator id.
- ``allgather`` is collective and pickle-framed exactly like mpi4py's
  lowercase path: the payload bytes are opaque to the router, so whole
  pickled ``Node`` subtrees travel just as they do over real MPI.

The transport is local sockets rather than OpenMPI's shared-memory BTL —
the same single-node transport class the reference's own published numbers
used (``time_data.csv`` rows were captured over OpenMPI ``sm`` on one
laptop, per the notebook's stream output).

Workers install the shim before importing the reference:
``sys.modules["mpi4py"] = mpi_shim.fake_mpi4py()``. With no
``MPI_SHIM_SOCKET`` in the env, ``COMM_WORLD`` degrades to a size-1
self-communicator so the reference module (whose class body initializes
MPI at import) stays importable for sequential timing.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import types


def _sendmsg(sock: socket.socket, obj) -> None:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(data)) + data)


def _recvn(sock: socket.socket, n: int) -> bytes:
    parts = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise EOFError("router connection closed")
        parts.append(chunk)
        got += len(chunk)
    return b"".join(parts)


def _recvmsg(sock: socket.socket):
    (n,) = struct.unpack("<Q", _recvn(sock, 8))
    return pickle.loads(_recvn(sock, n))


class _Client:
    """One socket to the launcher's router; one in-flight call at a time."""

    def __init__(self) -> None:
        path = os.environ["MPI_SHIM_SOCKET"]
        self.rank = int(os.environ["MPI_SHIM_RANK"])
        self.size = int(os.environ["MPI_SHIM_SIZE"])
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.connect(path)
        self._lock = threading.Lock()
        _sendmsg(self._sock, {"op": "hello", "rank": self.rank})

    def call(self, msg: dict) -> dict:
        with self._lock:
            _sendmsg(self._sock, msg)
            return _recvmsg(self._sock)


class Intracomm:
    """The slice of mpi4py's Intracomm the reference exercises."""

    def __init__(self, client, cid: int, rank: int, size: int) -> None:
        self._client = client
        self._cid = cid
        self._rank = rank
        self._size = size

    def Get_rank(self) -> int:  # noqa: N802 — mpi4py surface
        return self._rank

    def Get_size(self) -> int:  # noqa: N802
        return self._size

    def Split(self, color: int, key: int = 0) -> "Intracomm":  # noqa: N802
        if self._client is None:  # size-1 degenerate comm
            return Intracomm(None, self._cid + 1, 0, 1)
        r = self._client.call({
            "op": "split", "cid": self._cid, "rank": self._rank,
            "color": int(color), "key": int(key),
        })
        return Intracomm(self._client, r["cid"], r["rank"], r["size"])

    def allgather(self, obj) -> list:
        if self._client is None:
            return [obj]
        r = self._client.call({
            "op": "allgather", "cid": self._cid, "rank": self._rank,
            "payload": pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
        })
        return [pickle.loads(b) for b in r["payloads"]]

    def barrier(self) -> None:
        self.allgather(None)

    def Free(self) -> None:  # noqa: N802
        if self._client is not None:
            self._client.call({
                "op": "free", "cid": self._cid, "rank": self._rank,
            })


def _make_world() -> Intracomm:
    if "MPI_SHIM_SOCKET" in os.environ:
        c = _Client()
        return Intracomm(c, 0, c.rank, c.size)
    return Intracomm(None, 0, 0, 1)


def fake_mpi4py() -> types.ModuleType:
    """A module object satisfying ``from mpi4py import MPI``."""
    mpi = types.ModuleType("mpi4py.MPI")
    mpi.COMM_WORLD = _make_world()
    mpi.Intracomm = Intracomm
    pkg = types.ModuleType("mpi4py")
    pkg.MPI = mpi
    return pkg


# ---------------------------------------------------------------------------
# Router (runs in the launcher process)
# ---------------------------------------------------------------------------


class Router:
    """Collective matcher: thread per worker connection, state per comm id.

    Communicator state: ``members`` maps comm rank -> connection; matching
    uses per-(cid, member) arrival counters — every member issues the same
    collectives in the same order on a given communicator (the SPMD
    contract the reference itself relies on), so the k-th call per member
    belongs to the k-th collective on that communicator.
    """

    def __init__(self, path: str, size: int) -> None:
        self.path = path
        self.size = size
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(path)
        self._listener.listen(size)
        self._lock = threading.Lock()
        self._conns: dict[int, socket.socket] = {}
        self._comms: dict[int, list[int]] = {}  # cid -> world rank per comm rank
        self._arrivals: dict[tuple[int, int], int] = {}
        self._pending: dict[tuple[int, int], dict[int, dict]] = {}
        self._next_cid = 1
        self._threads: list[threading.Thread] = []

    def accept_all(self) -> None:
        for _ in range(self.size):
            conn, _ = self._listener.accept()
            hello = _recvmsg(conn)
            assert hello["op"] == "hello"
            self._conns[hello["rank"]] = conn
        self._comms[0] = list(range(self.size))
        for rank, conn in self._conns.items():
            t = threading.Thread(
                target=self._serve, args=(rank, conn), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve(self, world_rank: int, conn: socket.socket) -> None:
        try:
            while True:
                msg = _recvmsg(conn)
                if msg["op"] == "free":
                    _sendmsg(conn, {"ok": True})
                    continue
                with self._lock:
                    self._collect(world_rank, msg)
        except EOFError:
            pass

    def _collect(self, world_rank: int, msg: dict) -> None:
        cid = msg["cid"]
        comm_rank = msg["rank"]
        idx = self._arrivals.get((cid, comm_rank), 0)
        self._arrivals[(cid, comm_rank)] = idx + 1
        slot = self._pending.setdefault((cid, idx), {})
        slot[comm_rank] = msg
        if len(slot) < len(self._comms[cid]):
            return
        del self._pending[(cid, idx)]
        ops = {m["op"] for m in slot.values()}
        assert len(ops) == 1, f"mismatched collectives on comm {cid}: {ops}"
        members = self._comms[cid]
        if ops == {"allgather"}:
            payloads = [slot[r]["payload"] for r in range(len(members))]
            for r, wr in enumerate(members):
                _sendmsg(self._conns[wr], {"payloads": payloads})
        else:  # split
            by_color: dict[int, list[tuple[int, int]]] = {}
            for r in range(len(members)):
                m = slot[r]
                by_color.setdefault(m["color"], []).append((m["key"], r))
            replies: dict[int, dict] = {}
            for color in sorted(by_color):
                group = sorted(by_color[color])  # (key, parent rank) order
                cid_new = self._next_cid
                self._next_cid += 1
                self._comms[cid_new] = [members[r] for _, r in group]
                for new_rank, (_, r) in enumerate(group):
                    replies[r] = {
                        "cid": cid_new, "rank": new_rank, "size": len(group),
                    }
            for r, wr in enumerate(members):
                _sendmsg(self._conns[wr], replies[r])

    def close(self) -> None:
        for conn in self._conns.values():
            try:
                conn.close()
            except OSError:
                pass
        self._listener.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass
