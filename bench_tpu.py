"""Durable TPU perf capture: append one timestamped JSON line per run.

The round-1/round-2 lesson (VERDICT.md round 2, "What's missing" #1): the
driver's end-of-round ``bench.py`` run is hostage to bench-time tunnel
health, so after two rounds no committed artifact contained a TPU number.
This script is the fix — run it whenever the accelerator is reachable
(``make bench-tpu``) and it appends a self-contained measurement line to
``BENCH_TPU.jsonl``, which is committed. ``bench.py`` embeds the newest
line as ``tpu_last_known`` whenever its own live probe fails, so the
repo's perf story survives tunnel death.

Sections (each an isolated bounded subprocess, like bench.py's fit worker,
because a mid-fit tunnel hang blocks in native code where signal timeouts
cannot fire; a section timing out costs that section, not the line):

- ``north_star``   — the BASELINE.json workload: covtype-scale depth-20
                     fit through the DEVICE engine (no host fallback; the
                     hybrid C++ tail still runs, itemized under ``refine``),
                     cold + warm, per-phase breakdown, held-out accuracy.
- ``engine_fused`` / ``engine_levelwise`` — the same workload forced
                     through each device engine with no refine tail: the
                     measured input for re-deriving the fused-vs-levelwise
                     engine crossover (core/builder.py's engine
                     resolution) on the live transport.
- ``boosting``     — histogram gradient-boosted trees (mpitree_tpu.
                     boosting) at covtype scale: the sequential Newton
                     outer loop over the same engine.
- ``hist_tput``    — the K-slot histogram op at covtype shape: achieved
                     G updates/s and HBM GB/s vs the chip roofline, so
                     bandwidth efficiency is judgeable from the artifact.
- ``refine_sweep`` — (``--sweep-refine``) warm fits at refine_depth
                     {7,8,9,10}: the measured input for bench.py's
                     REFINE_DEPTH constant.

Usage::

    python bench_tpu.py                # all default sections, append line
    python bench_tpu.py --sweep-refine # include the refine_depth sweep
    python bench_tpu.py --rows 100000  # smaller workload (smoke)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

OUT_PATH = os.path.join(_HERE, "BENCH_TPU.jsonl")
DEPTH = 20
REFINE_DEPTH = 7  # measured: see bench.py's REFINE_DEPTH sweep note
SECTION_TIMEOUT_S = 1500

# Public per-chip HBM bandwidth rooflines (GB/s), for the efficiency line.
# Source: vendor-published specs (v5e: 819 GB/s, v4: 1228 GB/s).
HBM_ROOFLINE_GBPS = {"tpu v5 lite": 819.0, "tpu v5e": 819.0, "tpu v4": 1228.0}


def _git_head() -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=_HERE,
            capture_output=True, text=True, timeout=10,
        )
        return out.stdout.strip() or "unknown"
    except OSError:
        return "unknown"


def _obs_module(name: str):
    """An obs module (flight/diff) loaded BY FILE PATH — stdlib-only by
    contract, so the PARENT orchestrator (which deliberately never
    imports jax; sections run in pinned subprocesses) can diff and store
    runs (the obs/trace.py precedent the watcher set). Cached in
    sys.modules: per-call re-exec would re-probe git for every append
    (flight's sha cache lives on the module) and crash dataclass field
    resolution for modules that define one."""
    import importlib.util

    modname = f"_bench_obs_{name}"
    if modname in sys.modules:
        return sys.modules[modname]
    spec = importlib.util.spec_from_file_location(
        modname,
        os.path.join(_HERE, "mpitree_tpu", "obs", f"{name}.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    return mod


def section_history(sec: str, lines: list) -> list:
    """The section's stored payload trajectory (oldest→newest) from
    already-parsed capture lines — the --baseline diff's evidence base."""
    return [
        rec[sec] for rec in lines
        if isinstance(rec, dict) and isinstance(rec.get(sec), dict)
    ]


def baseline_verdict(sec: str, payload: dict, prior_lines: list):
    """(diff, one-line summary) of this capture vs the newest stored
    capture of the same section — the ``--baseline`` regression
    sentinel (obs.diff: noise thresholds seeded from the section's own
    stored dispersion). (None, reason) with no stored baseline."""
    history = section_history(sec, prior_lines)
    if not history:
        return None, "no stored baseline for this section yet"
    diff_mod = _obs_module("diff")
    d = diff_mod.diff_payloads(history[-1], payload, history=history)
    # Threshold provenance in the self-report line: whether the verdict
    # was gated by this lineage's own measured dispersion or (thin
    # history) by the documented floors — a floor-gated "ok" is a weaker
    # claim and should read like one.
    n = len(history)
    prov = (
        f"thresholds from stored dispersion (n={n})"
        if n >= diff_mod.MIN_HISTORY else
        f"thin history (n={n} < {diff_mod.MIN_HISTORY}): floor "
        "thresholds only"
    )
    return d, f"{diff_mod.summary_line(d, label=sec)} [{prov}]"


def flight_append_section(sec: str, payload: dict, platform: str) -> None:
    """Append one captured section to the flight store when
    ``MPITREE_TPU_RUN_DIR`` is set (kind="bench" envelopes; the fit
    records inside the section workers append their own kind="fit"
    lines). Best-effort — the capture must never die on telemetry."""
    try:
        flight = _obs_module("flight")
        if not flight.enabled():
            return
        diff_mod = _obs_module("diff")
        store = flight.FlightStore()
        store.append(
            kind="bench", section=sec,
            metrics=diff_mod.scalar_metrics(payload),
            digest=(payload.get("record") or {}),
            config={"section": sec, "depth": DEPTH,
                    "refine_depth": REFINE_DEPTH},
            platform=platform, git=_git_head(),
        )
        # The north-star sections embed their sibling-subtraction A/B as
        # a NESTED dict, which scalar_metrics (top-level only) cannot
        # see — append it as its own section="subtraction_ab" envelope
        # so the advisor (obs/advisor.py) has a queryable lineage. The
        # parent payload's shape keys ride along for nearest-workload
        # matching.
        sub = payload.get("subtraction_ab")
        if isinstance(sub, dict):
            shape = {
                k: payload[k]
                for k in ("n_samples", "n_features", "n_bins")
                if isinstance(payload.get(k), (int, float))
            }
            store.append(
                kind="bench", section="subtraction_ab",
                metrics={**diff_mod.scalar_metrics(sub), **shape},
                digest=(
                    (sub.get("main") or {}).get("record") or {}
                ),
                config={"section": "subtraction_ab", "depth": DEPTH,
                        "refine_depth": REFINE_DEPTH},
                platform=platform, git=_git_head(),
            )
    except Exception as e:  # noqa: BLE001 — telemetry, not the capture
        print(f"[bench-tpu] {sec}: flight append failed "
              f"({type(e).__name__}: {e})", file=sys.stderr)


# --------------------------------------------------------------------------
# Section workers (run in subprocesses; each prints one tagged JSON line)
# --------------------------------------------------------------------------

def _load(npz_path: str):
    data = np.load(npz_path)
    return data["Xtr"], data["ytr"], data["Xte"], data["yte"]


def enable_compile_cache(platform: str | None = None) -> str | None:
    """Point JAX at a persistent on-disk compilation cache and return its path.

    Cold bench runs previously paid 25-70 s of XLA compilation *per process*
    through the remote-compile tunnel (round-4 BENCH_TPU.jsonl: north star
    93.2 s cold vs 20.5 s warm) because nothing persisted executables across
    processes. With the cache, a second cold process on the same commit
    reuses the serialized executables and cold_s approaches warm_s. Must run
    before the first jax operation (config is read at backend init).
    ``MPITREE_TPU_COMPILE_CACHE`` overrides the location; gitignored.

    NOT enabled for CPU workers on legacy (pre-shard_map) wheels: there a
    cache-DESERIALIZED executable mishandles input-output aliasing — any
    donating program (the level loop's ``update_fn(nid, ...)``, serving's
    accumulator traversal) returns garbage through the donated buffer.
    Measured on this container's 0.4.37 wheel: a cold-cache gbdt fit is
    correct, the identical warm-cache rerun yields out-of-range leaf ids
    (PR-7 triage; accelerator workers keep the cache — every prior TPU
    capture's accuracy checks pass warm).
    """
    import jax

    from mpitree_tpu import _compat

    if platform is None:
        # Callers that don't know their platform (bench.py's workers):
        # read the sitecustomize pin without initializing a backend —
        # tunnel containers pin "axon" (cache stays, it's the whole
        # point); an unset pin on a legacy wheel means the worker will
        # land on CPU, where the cache is poison.
        platform = jax.config.jax_platforms or None
    if _compat.LEGACY_JAX and platform not in ("tpu", "axon"):
        return None

    from mpitree_tpu.config import knobs

    path = (knobs.raw("MPITREE_TPU_COMPILE_CACHE")
            or os.path.join(_HERE, ".jax_cache"))
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache every executable (default skips small/fast ones): tunnel
    # round trips make even sub-second compiles worth persisting.
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return path


def _pin_platform(platform: str) -> None:
    """Pin the JAX platform in-process before any jax op runs.

    This environment's sitecustomize registers the tunneled accelerator
    and sets ``jax_platforms`` via jax.config at interpreter startup —
    overriding the JAX_PLATFORMS env var — so a CPU-targeted worker that
    merely sets the env var still tries (and, tunnel down, hangs) to
    initialize the accelerator client on its first op. Only
    ``jax.config.update`` sticks (same lesson as bench.py's probe).
    Accelerator platforms keep the environment default untouched.
    """
    import jax

    if platform not in ("tpu", "axon"):
        jax.config.update("jax_platforms", platform)


def _device_platform() -> str:
    import jax

    return jax.devices()[0].platform


# The digest fields every fit-bearing section line must embed (the
# artifact contract tests/test_bench_contract.py enforces). Values come
# from mpitree_tpu.obs.digest(fit_report_) — ~10 scalars, so a line
# carrying one per section stays inside the driver's tail window.
RECORD_DIGEST_KEYS = (
    "engine", "reason", "n_nodes", "depth", "levels", "compile_new",
    "psum_bytes", "sub_frac", "expansions", "rounds_per_dispatch",
    "events", "wire_bytes", "wire_shard_bytes", "feature_shards",
    "hbm_peak_bytes", "host_peak_bytes", "fingerprint",
    "level_retries", "oom_rescues",
    "util_pct", "roofline",
    "wall_s",
)


def record_digest(report) -> dict | None:
    """Compact attribution summary of a ``fit_report_`` (or None)."""
    if not report:
        return None
    from mpitree_tpu.obs import digest

    return digest(report)


def format_record_digest(d: dict) -> str:
    """One-line rendering of a stored digest dict — pure string work, no
    mpitree import, so the watcher can log it even on a jax-less host."""
    mb = (d.get("psum_bytes") or 0) / 1e6
    line = (
        f"engine={d.get('engine')} nodes={d.get('n_nodes')} "
        f"depth={d.get('depth')} levels={d.get('levels')} "
        f"compile_new={d.get('compile_new')} psum={mb:.1f}MB "
        f"events={d.get('events')} wall={d.get('wall_s')}s"
    )
    if d.get("wire_bytes"):
        # Nonzero only on a real multi-shard axis: actual ICI fabric
        # traffic (ring-allreduce estimate), vs psum's logical payload.
        line += f" wire={(d['wire_bytes'] or 0) / 1e6:.1f}MB"
    if d.get("sub_frac") is not None:
        line += f" sub_frac={d['sub_frac']}"
    if d.get("expansions") is not None:
        line += f" expansions={d['expansions']}"
    if d.get("rounds_per_dispatch") is not None:
        line += f" rpd={d['rounds_per_dispatch']}"
    if (d.get("feature_shards") or 1) > 1:
        # 2-D (data, feature) mesh: psum_bytes above is per feature slab
        line += f" fshards={d['feature_shards']}"
    if d.get("hbm_peak_bytes"):
        # The obs.memory ledger's predicted per-device peak (v6) — the
        # number the watcher sanity-checks captured sections against.
        line += f" hbm_peak={(d['hbm_peak_bytes'] or 0) / 1e6:.1f}MB"
    if d.get("fingerprint"):
        # The whole-fit build-state fingerprint (v7): two lineage lines
        # with different fp= built DIFFERENT trees — obs.diff bisects.
        line += f" fp={d['fingerprint']}"
    if d.get("level_retries") or d.get("oom_rescues"):
        # Resilience v2 (v8): this capture SURVIVED fine-grained
        # recovery — sub-build re-dispatches and/or on-device OOM
        # rescues — so its wall clock carries retry time and its plan
        # may have been shrunk mid-fit.
        line += (
            f" level_retries={d.get('level_retries') or 0}"
            f" oom_rescues={d.get('oom_rescues') or 0}"
        )
    if d.get("reason"):
        line += f" reason={d['reason']!r}"
    return line


def section_record_digest(sec: str, path: str = OUT_PATH) -> str | None:
    """Newest stored record digest for ``sec``, formatted for one log line
    (the watcher's per-section attribution — TPU_WATCHER.log)."""
    for rec in reversed(read_capture_lines(path)):
        payload = rec.get(sec)
        if isinstance(payload, dict) and isinstance(
            payload.get("record"), dict
        ):
            return format_record_digest(payload["record"])
    return None


def _timed_fit(Xtr, ytr, *, backend, refine_depth, engine_env=None,
               warm=True, max_leaf_nodes=None):
    """One (optionally cold+warm) timed fit through the device path."""
    from mpitree_tpu import DecisionTreeClassifier

    if engine_env:
        os.environ["MPITREE_TPU_ENGINE"] = engine_env

    def once():
        clf = DecisionTreeClassifier(
            max_depth=DEPTH, max_bins=256, backend=backend,
            refine_depth=refine_depth, max_leaf_nodes=max_leaf_nodes,
        )
        t0 = time.perf_counter()
        clf.fit(Xtr, ytr)
        return time.perf_counter() - t0, clf

    cold_s, clf = once()
    out = {"cold_s": round(cold_s, 3)}
    if warm:
        warm_s, clf = once()
        out["warm_s"] = round(warm_s, 3)
    out["tree_depth"] = clf.tree_.max_depth
    out["tree_n_nodes"] = clf.tree_.n_nodes
    out["phases"] = clf.fit_stats_
    # Embedded run-record digest: the section line carries its own
    # attribution (engine decision + reason, recompiles, psum bytes), so
    # the next slow-section mystery is explained by the artifact itself.
    out["record"] = record_digest(clf.fit_report_)
    return out, clf


def _predict_tput(clf, Xte) -> float:
    """Warm rows/s of the vectorized gather-descent predict (the
    reference's per-row Python recursion, decision_tree.py:208-227, is the
    parity point)."""
    clf.predict(Xte)  # warm any lazy device program
    t0 = time.perf_counter()
    clf.predict(Xte)
    return round(len(Xte) / (time.perf_counter() - t0))


def _north_star(npz_path: str, engine_env: str | None) -> dict:
    Xtr, ytr, Xte, yte = _load(npz_path)
    platform = _device_platform()
    out, clf = _timed_fit(
        Xtr, ytr, backend=platform, refine_depth=REFINE_DEPTH,
        engine_env=engine_env,
    )
    out["platform"] = platform
    # Workload shape keys: land in the flight envelope's metrics, where
    # the advisor's nearest-workload matching reads them.
    out["n_samples"] = int(Xtr.shape[0])
    out["n_features"] = int(Xtr.shape[1])
    if engine_env:
        out["engine"] = engine_env
    out["test_acc"] = round(float((clf.predict(Xte) == yte).mean()), 4)
    out["predict_rows_per_s"] = _predict_tput(clf, Xte)
    n_cells = Xtr.shape[0] * Xtr.shape[1]
    levels = max(out["tree_depth"], 1)
    out["throughput_cells_per_s"] = round(n_cells * levels / out["warm_s"])
    # Sibling-subtraction A/B on the same platform in the same run
    # (ISSUE 5): the main fit above ran the default ("auto" — ON for this
    # integer-weight classification workload on a TPU; auto resolves OFF
    # on CPU dryruns), so one env-toggled OFF fit closes the comparison.
    # Rides the same bounded-section protocol: the extra cold compile is
    # a different executable set, charged to this section. Each side
    # carries its RESOLVED hist_subtraction decision, and the speedup is
    # labeled honestly when the main fit resolved off (off-vs-off would
    # otherwise read as "the trick gained nothing").
    main_resolved = (
        clf.fit_report_.get("decisions", {})
        .get("hist_subtraction", {}).get("value")
    )
    os.environ["MPITREE_TPU_HIST_SUBTRACTION"] = "off"
    try:
        off_out, off_clf = _timed_fit(
            Xtr, ytr, backend=platform, refine_depth=REFINE_DEPTH,
            engine_env=engine_env,
        )
    finally:
        os.environ.pop("MPITREE_TPU_HIST_SUBTRACTION", None)
    out["subtraction_ab"] = {
        "main": {
            "resolved": main_resolved,
            "warm_s": out["warm_s"], "record": out["record"],
        },
        "off": {
            "resolved": (
                off_clf.fit_report_.get("decisions", {})
                .get("hist_subtraction", {}).get("value")
            ),
            "cold_s": off_out["cold_s"], "warm_s": off_out["warm_s"],
            "phases": off_out["phases"], "record": off_out["record"],
        },
        (
            "warm_speedup_on_vs_off" if main_resolved == "on"
            else "warm_speedup_off_vs_off"  # auto resolved off: no A in A/B
        ): round(off_out["warm_s"] / out["warm_s"], 3),
    }
    return out


def worker_north_star(npz_path: str) -> dict:
    return _north_star(npz_path, None)


def worker_north_star_fused(npz_path: str) -> dict:
    """North-star config with the crown pinned to the fused engine.

    Round-4 TPU line: fused full-depth (17.5s warm) beat the levelwise
    crown + refine hybrid (20.5s) on tunnel transport — per-level dispatch
    costs ~1.8s there (north_star split phase: 12.9s / 7 levels). This
    section measures the remaining candidate routing: one fused program for
    the depth-7 crown, C++ exact refine for the tail.
    """
    return _north_star(npz_path, "fused")


def worker_engine(npz_path: str, engine: str) -> dict:
    Xtr, ytr, _, _ = _load(npz_path)
    platform = _device_platform()
    from mpitree_tpu.core.builder import BuildConfig, resolve_wide_hist

    wide_on, _ = resolve_wide_hist(
        BuildConfig(), platform, "classification", integer_ok=True
    )
    try:
        out, _ = _timed_fit(
            Xtr, ytr, backend=platform, refine_depth=None, engine_env=engine
        )
    except Exception as e:  # noqa: BLE001
        # The wide tier (ops/wide_hist.py) sits in this section's critical
        # path; until a real-hardware run exists, a full-build failure
        # WITH the tier active burns the failure into the record and
        # still captures the scatter-path number in the same healthy
        # window. Failures with the tier already off are not its fault —
        # re-raise rather than record a false verdict.
        if not wide_on:
            raise
        os.environ["MPITREE_TPU_WIDE_HIST"] = "0"
        out, _ = _timed_fit(
            Xtr, ytr, backend=platform, refine_depth=None, engine_env=engine
        )
        out["wide_hist_failed"] = f"{type(e).__name__}: {e}"[:500]
        out["wide_hist"] = "disabled-after-failure"
    out["engine"] = engine
    out["n_cells"] = int(Xtr.shape[0] * Xtr.shape[1])
    return out


def worker_refine_sweep(npz_path: str) -> dict:
    Xtr, ytr, Xte, yte = _load(npz_path)
    platform = _device_platform()
    from mpitree_tpu import DecisionTreeClassifier

    rows = []
    for rd in (7, 8, 9, 10):
        clf = DecisionTreeClassifier(
            max_depth=DEPTH, max_bins=256, backend=platform,
            refine_depth=rd,
        )
        clf.fit(Xtr, ytr)  # compile warm-up for this config
        t0 = time.perf_counter()
        clf.fit(Xtr, ytr)
        warm_s = time.perf_counter() - t0
        rows.append({
            "refine_depth": rd, "warm_s": round(warm_s, 3),
            "test_acc": round(float((clf.predict(Xte) == yte).mean()), 4),
            "record": record_digest(clf.fit_report_),
        })
    return {"sweep": rows}


def worker_predict(npz_path: str) -> dict:
    """Inference throughput at covtype scale (verdict r4 #6).

    The reference predicts with a per-row Python recursion and every rank
    predicts the full set redundantly (``mpitree/tree/decision_tree.py:
    208-227``); our path is the lockstep gather-descent
    (``ops/predict.py``). Reports rows/s for ``predict_proba`` and
    ``predict`` on the held-out set and on a ~1M-row tiling of it (the
    covtype-scale number the artifact was missing).
    """
    from mpitree_tpu import DecisionTreeClassifier

    Xtr, ytr, Xte, _ = _load(npz_path)
    platform = _device_platform()
    clf = DecisionTreeClassifier(
        max_depth=DEPTH, max_bins=256, backend=platform,
        refine_depth=REFINE_DEPTH,
    )
    clf.fit(Xtr, ytr)
    out: dict = {"platform": platform, "tree_n_nodes": clf.tree_.n_nodes}

    reps = max(1, 1_000_000 // len(Xte))
    Xbig = np.tile(Xte, (reps, 1))
    for name, X in (("test", Xte), ("1m", Xbig)):
        for meth in ("predict", "predict_proba"):
            fn = getattr(clf, meth)
            fn(X)  # warm the compiled descent for this shape
            t0 = time.perf_counter()
            fn(X)
            dt = time.perf_counter() - t0
            out[f"{meth}_{name}_rows_per_s"] = round(len(X) / dt)
            out[f"{meth}_{name}_s"] = round(dt, 4)
    out["rows_test"] = len(Xte)
    out["rows_1m"] = len(Xbig)
    return out


def worker_device_bin(npz_path: str) -> dict:
    """Host numpy vs on-device binning at the full workload shape.

    The go/no-go for bin_for_engine's TPU default: measured on XLA-CPU the
    device program is ~26x SLOWER than numpy (100k x 54), so it is gated
    to real TPUs on the strength of this section's numbers.
    """
    import jax

    from mpitree_tpu.ops.binning import bin_dataset, bin_dataset_device

    Xtr, _, _, _ = _load(npz_path)
    t0 = time.perf_counter()
    host = bin_dataset(Xtr)
    host_s = time.perf_counter() - t0
    bin_dataset_device(Xtr)  # compile + transfer warm-up
    t0 = time.perf_counter()
    dev = bin_dataset_device(Xtr)
    dev_s = time.perf_counter() - t0
    same = bool(
        np.array_equal(np.asarray(dev.x_binned), host.x_binned)
        and np.array_equal(dev.thresholds, host.thresholds)
    )
    return {
        "platform": jax.devices()[0].platform,
        "host_s": round(host_s, 3),
        "device_s": round(dev_s, 3),
        "speedup_vs_host": round(host_s / dev_s, 2),
        "identical": same,
    }


def worker_hist_tput(npz_path: str) -> dict:
    """K-slot and small-frontier histogram throughput at covtype shape."""
    import jax
    import jax.numpy as jnp

    from mpitree_tpu.ops import histogram as hist_ops
    from mpitree_tpu.ops import pallas_hist as ph

    Xtr, ytr, _, _ = _load(npz_path)
    N, F = Xtr.shape
    B, C, K = 256, int(ytr.max()) + 1, 4096
    rng = np.random.default_rng(0)
    xb = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.int32))
    y = jnp.asarray(ytr.astype(np.int32))
    w1 = jnp.ones(N, jnp.float32)
    platform = jax.devices()[0].platform
    kind = jax.devices()[0].device_kind.lower()

    def timed(fn, *args, reps=5):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps

    res: dict = {"platform": platform, "device_kind": kind}

    nid = jnp.asarray(rng.integers(0, K, size=N, dtype=np.int32))

    @jax.jit
    def big_hist(xb, y, nid):
        return hist_ops.class_histogram(
            xb, y, nid, jnp.int32(0), n_slots=K, n_bins=B, n_classes=C,
            sample_weight=w1,
        )

    s = timed(big_hist, xb, y, nid)
    # The op reads the (N, F) int32 matrix once; write traffic (K*F*C*B f32
    # accumulator) is the same order — count read-side only, conservative.
    gbps = N * F * 4 / s / 1e9
    res["hist_K4096"] = {
        "seconds": round(s, 5),
        "g_updates_per_s": round(N * F / s / 1e9, 3),
        "read_gb_per_s": round(gbps, 1),
    }

    # Sibling-subtraction accumulate at the same K shape: only the smaller
    # sibling of each pair scatters, into the compact K/2-slot buffer
    # (ops/histogram.sibling_accumulate_slots) — the shape both engines
    # run on every single-chunk interior level when hist_subtraction is
    # on. sub_frac is the realized scan fraction (~0.5 on this uniform
    # nid draw; real trees do better — small children average well under
    # half their parent's rows).
    cnt_slots = np.bincount(np.asarray(nid), minlength=K).astype(np.int64)
    pair_cnt = cnt_slots.reshape(K // 2, 2)
    left_small = pair_cnt[:, 0] <= pair_cnt[:, 1]
    is_small_h = np.zeros(K, bool)
    is_small_h[0::2] = left_small
    is_small_h[1::2] = ~left_small
    sub_frac = float(cnt_slots[is_small_h].sum()) / max(
        float(cnt_slots.sum()), 1.0
    )
    is_small_d = jnp.asarray(is_small_h)

    @jax.jit
    def big_hist_sub(xb, y, nid, is_small_d):
        acc = hist_ops.sibling_accumulate_slots(
            nid, jnp.int32(0), is_small_d, n_slots=K
        )
        return hist_ops.class_histogram(
            xb, y, acc, jnp.int32(0), n_slots=K // 2, n_bins=B,
            n_classes=C, sample_weight=w1,
        )

    try:
        s_sub = timed(big_hist_sub, xb, y, nid, is_small_d)
        res["hist_K4096_sub"] = {
            "seconds": round(s_sub, 5),
            "sub_frac": round(sub_frac, 4),
            "psum_slots": K // 2,
            "speedup_vs_full_scatter": round(s / s_sub, 2),
        }
    except Exception as e:  # noqa: BLE001 — diagnostic section only
        res["hist_K4096_sub"] = {"error": f"{type(e).__name__}: {e}"}

    # Candidate big-path variant: sort rows by node id once per level, then
    # the SAME scatter — writes then cluster per slot region of the huge
    # accumulator (better locality for the scatter unit), at the price of
    # the sort + 3 gathers. (indices_are_sorted would be a lie: fine ids
    # jumble by class/bin within a slot.) If this wins on hardware, the
    # fused builder's deep levels get the same treatment.
    @jax.jit
    def big_hist_sorted(xb, y, nid):
        order = jnp.argsort(nid)
        # The weight gather rides along so this stays a faithful template
        # for the fused builder (whose sample_weight is non-uniform under
        # bagging) and its cost is charged to the variant.
        return hist_ops.class_histogram(
            xb[order], y[order], nid[order], jnp.int32(0), n_slots=K,
            n_bins=B, n_classes=C, sample_weight=w1[order],
        )

    try:
        s_sorted = timed(big_hist_sorted, xb, y, nid)
        res["hist_K4096_sorted"] = {
            "seconds": round(s_sorted, 5),
            "g_updates_per_s": round(N * F / s_sorted / 1e9, 3),
            "speedup_vs_scatter": round(s / s_sorted, 2),
        }
    except Exception as e:  # noqa: BLE001 — diagnostic section only
        res["hist_K4096_sorted"] = {"error": f"{type(e).__name__}: {e}"}

    # The production deep-level path: sorted window-packed MXU contraction
    # (ops/wide_hist.py) at the same K=4096 shape, f32 and bf16 payloads.
    # This is the number that justifies (or retunes) wide_hist.MIN_SLOTS.
    from mpitree_tpu.ops import wide_hist as wh

    payload_k = ph.class_payload(y, w1, C)
    for bf16 in (False, True):
        def wide_fn(xb, payload_k, nid, bf16=bf16):
            return wh.histogram_wide(
                xb, payload_k, nid, n_slots=K, n_bins=B, n_channels=C,
                bf16_ok=bf16,
            )

        try:
            s_wide = timed(wide_fn, xb, payload_k, nid)
            res[f"hist_K4096_wide_{'bf16' if bf16 else 'f32'}"] = {
                "seconds": round(s_wide, 5),
                "g_updates_per_s": round(N * F / s_wide / 1e9, 3),
                "read_gb_per_s": round(N * F * 4 / s_wide / 1e9, 1),
                "speedup_vs_scatter": round(s / s_wide, 2),
            }
        except Exception as e:  # noqa: BLE001 — diagnostic section only
            res[f"hist_K4096_wide_{'bf16' if bf16 else 'f32'}"] = {
                "error": f"{type(e).__name__}: {e}"
            }

    # The Mosaic grouped-matmul executor of the same tier: window blocks
    # accumulate in VMEM across their tile runs (scalar-prefetched output
    # index) instead of a read-modify-write per tile. Both dtypes, so the
    # comparison against the scan entries above is apples-to-apples (the
    # builders' regression path runs f32); this number decides
    # MPITREE_TPU_WIDE_KERNEL's default (resolve_wide_pallas).
    if not (wh.wide_pallas_available(platform) and wh.pallas_fits(C, B)):
        skip = {
            "skipped": (
                f"available={wh.wide_pallas_available(platform)} "
                f"pallas_fits={wh.pallas_fits(C, B)} at C={C} B={B}"
            )
        }
        res["hist_K4096_wide_pallas_f32"] = skip
        res["hist_K4096_wide_pallas_bf16"] = skip
    else:
        for bf16 in (False, True):
            def wide_pl_fn(xb, payload_k, nid, bf16=bf16):
                return wh.histogram_wide_pallas(
                    xb, payload_k, nid, n_slots=K, n_bins=B, n_channels=C,
                    bf16_ok=bf16,
                )

            key = f"hist_K4096_wide_pallas_{'bf16' if bf16 else 'f32'}"
            try:
                s_wpl = timed(wide_pl_fn, xb, payload_k, nid)
                res[key] = {
                    "seconds": round(s_wpl, 5),
                    "g_updates_per_s": round(N * F / s_wpl / 1e9, 3),
                    "read_gb_per_s": round(N * F * 4 / s_wpl / 1e9, 1),
                    "speedup_vs_scatter": round(s / s_wpl, 2),
                }
            except Exception as e:  # noqa: BLE001
                res[key] = {"error": f"{type(e).__name__}: {e}"}
    roof = next(
        (v for k, v in HBM_ROOFLINE_GBPS.items() if k in kind), None
    )
    if roof:
        res["hist_K4096"]["hbm_roofline_gbps"] = roof
        res["hist_K4096"]["roofline_frac"] = round(gbps / roof, 3)

    # Per-level non-histogram ops of the fused loop, isolated: once the
    # wide tier removes the histogram scatter, these bound the next
    # attack (row-reroute gathers, child-allocation scatters). Shapes
    # mirror a covtype deep level (N rows, M~1M node capacity).
    M = 1 << 20
    tbl = jnp.asarray(rng.integers(-1, 54, size=M, dtype=np.int32))
    node = jnp.asarray(rng.integers(0, M, size=N, dtype=np.int32))
    bins_t = jnp.asarray(rng.integers(0, B, size=M, dtype=np.int32))

    @jax.jit
    def reroute(xb, tbl, bins_t, node):
        f = tbl[node]                      # (N,) gather from M-table
        xf = jnp.take_along_axis(
            xb, jnp.maximum(f, 0)[:, None], axis=1
        )[:, 0]                            # (N,) row gather
        go_left = xf <= bins_t[node]       # second M-table gather
        return jnp.where(go_left, node * 2, node * 2 + 1)

    s_r = timed(reroute, xb, tbl, bins_t, node)
    res["level_op_reroute"] = {
        "seconds": round(s_r, 5),
        "g_gathers_per_s": round(3 * N / s_r / 1e9, 3),
    }

    scat_idx = jnp.asarray(rng.integers(0, M, size=M, dtype=np.int32))
    vals = jnp.asarray(rng.integers(0, M, size=M, dtype=np.int32))

    @jax.jit
    def child_alloc_scatter(scat_idx, vals):
        pad = jnp.full(M + 2, -1, jnp.int32)
        pad = pad.at[scat_idx].set(vals)
        pad = pad.at[scat_idx + 1].set(vals)
        return pad[:M]

    s_a = timed(child_alloc_scatter, scat_idx, vals)
    res["level_op_alloc_scatter"] = {
        "seconds": round(s_a, 5),
        "g_scatters_per_s": round(2 * M / s_a / 1e9, 3),
    }

    # Tier sweep: XLA scatter vs the Pallas kernel (whichever layout its
    # auto-dispatch picks — one-block at S=8, feature-gridded above) at the
    # frontier widths the builders actually route (frontier_tiers plus the
    # capped-out 512 for the scatter side). This is the measurement the
    # tier set and the _FGRID_MAX_SLOT_CHANNELS cap must cite.
    for S in (8, 64, 128, 256, 512):
        nid_s = jnp.asarray(rng.integers(0, S, size=N, dtype=np.int32))

        @jax.jit
        def small_hist(xb, y, nid_s, S=S):
            return hist_ops.class_histogram(
                xb, y, nid_s, jnp.int32(0), n_slots=S, n_bins=B,
                n_classes=C, sample_weight=w1,
            )

        s_xla = timed(small_hist, xb, y, nid_s)
        res[f"hist_S{S}_xla"] = {
            "seconds": round(s_xla, 5),
            "g_updates_per_s": round(N * F / s_xla / 1e9, 3),
        }
        if S >= wh.MIN_SLOTS:
            def wide_s_fn(xb, payload_k, nid_s, S=S):
                return wh.histogram_wide(
                    xb, payload_k, nid_s, n_slots=S, n_bins=B,
                    n_channels=C, bf16_ok=True,
                )

            try:
                s_w = timed(wide_s_fn, xb, payload_k, nid_s)
                res[f"hist_S{S}_wide"] = {
                    "seconds": round(s_w, 5),
                    "g_updates_per_s": round(N * F / s_w / 1e9, 3),
                    "speedup_vs_xla": round(s_xla / s_w, 2),
                }
            except Exception as e:  # noqa: BLE001
                res[f"hist_S{S}_wide"] = {"error": f"{type(e).__name__}: {e}"}
        if ph.pallas_available(platform) and ph.fits_vmem(F, S, C, B):
            payload = ph.class_payload(y, w1, C)

            def pallas_hist_fn(xb, payload, nid_s, S=S):
                return ph.histogram_small(
                    xb, payload, nid_s, n_slots=S, n_bins=B, n_channels=C
                )

            s_pl = timed(pallas_hist_fn, xb, payload, nid_s)
            res[f"hist_S{S}_pallas"] = {
                "seconds": round(s_pl, 5),
                "layout": ("single" if ph._fits_single(F, S, C, B)
                           else "fgrid"),
                "g_updates_per_s": round(N * F / s_pl / 1e9, 3),
                "speedup_vs_xla": round(s_xla / s_pl, 2),
            }
    return res


def worker_boosting(npz_path: str) -> dict:
    """The boosting workload section (mpitree_tpu.boosting) at covtype scale.

    20 Newton rounds of one-tree-per-class softmax GBDT at depth 6 through
    the levelwise gbdt engine — the sequential residual-fitting outer loop
    no single-tree section represents. Reports total and per-round fit
    wall, held-out accuracy, and warm predict throughput.
    """
    from mpitree_tpu import GradientBoostingClassifier

    Xtr, ytr, Xte, yte = _load(npz_path)
    platform = _device_platform()
    t0 = time.perf_counter()
    clf = GradientBoostingClassifier(
        max_iter=20, max_depth=6, max_bins=256, backend=platform,
        random_state=0,
    ).fit(Xtr, ytr)
    fit_s = time.perf_counter() - t0
    out = {
        "platform": platform,
        "max_iter": 20,
        "max_depth": 6,
        "n_trees": len(clf.trees_),
        "fit_s": round(fit_s, 3),
        "round_s": round(fit_s / max(clf.n_iter_, 1), 3),
        "test_acc": round(float((clf.predict(Xte) == yte).mean()), 4),
        "record": record_digest(clf.fit_report_),
    }
    # The test_acc predict above already compiled/warmed the stacked
    # descent for this shape — time the next call directly.
    t0 = time.perf_counter()
    clf.predict(Xte)
    out["predict_rows_per_s"] = round(len(Xte) / (time.perf_counter() - t0))
    return out


def worker_leafwise_ab(npz_path: str) -> dict:
    """Leaf-wise vs level-wise A/B at the north-star depth (ISSUE 8).

    Two full-depth single-engine device fits of the same covtype
    workload — the level-synchronous frontier at ``max_depth=20`` vs the
    best-first frontier at ``max_leaf_nodes=255`` — with the always-on
    ``rows_scanned`` accounting deciding the headline: histogram cells
    actually scanned per finished tree (``rows_scanned * n_features``;
    the psum payload ratio rides the embedded record digests). The
    acceptance bar is >=2x fewer cells at equal accuracy (+-0.002
    against the sklearn best-first reference at the same leaf budget),
    measured from the records rather than asserted.
    """
    Xtr, ytr, Xte, yte = _load(npz_path)
    platform = _device_platform()
    F = Xtr.shape[1]
    out: dict = {
        "platform": platform, "max_depth": DEPTH, "max_leaf_nodes": 255,
    }

    def side(mln):
        # refine_depth=None: the host refine tail would hide the device
        # frontier's scan counters — both sides build full-depth on the
        # device engines (the leaf-wise path is single-engine anyway).
        sec, clf = _timed_fit(
            Xtr, ytr, backend=platform, refine_depth=None,
            max_leaf_nodes=mln,
        )
        counters = clf.fit_report_.get("counters", {})
        scanned = counters.get("rows_scanned")
        sec["test_acc"] = round(float((clf.predict(Xte) == yte).mean()), 4)
        sec["rows_scanned"] = None if scanned is None else int(scanned)
        sec["cells_scanned"] = (
            None if scanned is None else int(scanned * F)
        )
        return sec

    out["levelwise"] = side(None)
    out["leafwise"] = side(255)
    lvl_cells = out["levelwise"]["cells_scanned"]
    lw_cells = out["leafwise"]["cells_scanned"]
    if lvl_cells and lw_cells:
        out["scan_reduction_x"] = round(lvl_cells / lw_cells, 2)
    lvl_psum = (out["levelwise"].get("record") or {}).get("psum_bytes")
    lw_psum = (out["leafwise"].get("record") or {}).get("psum_bytes")
    if lvl_psum and lw_psum:
        out["psum_reduction_x"] = round(lvl_psum / lw_psum, 2)
    out["warm_speedup_x"] = round(
        out["levelwise"]["warm_s"] / out["leafwise"]["warm_s"], 3
    )
    # The "equal accuracy" reference: sklearn's own best-first grower at
    # the identical leaf budget (it switches to a priority frontier
    # whenever max_leaf_nodes is set), exact splits on the raw floats.
    from sklearn.tree import DecisionTreeClassifier as SkTree

    t0 = time.perf_counter()
    sk = SkTree(
        max_leaf_nodes=255, max_depth=DEPTH, random_state=0
    ).fit(Xtr, ytr)
    sk_acc = round(float((sk.predict(Xte) == yte).mean()), 4)
    out["sklearn"] = {
        "fit_s": round(time.perf_counter() - t0, 3), "test_acc": sk_acc,
    }
    out["acc_delta_vs_sklearn"] = round(
        out["leafwise"]["test_acc"] - sk_acc, 4
    )
    return out


def worker_gbdt_fusedK(npz_path: str) -> dict:
    """Fused multi-round GBDT dispatch A/B (ISSUE 8).

    Binary covtype (class 2 vs rest, ~49/51) because the fused program
    requires one tree per round; 16 logistic rounds at depth 4 through
    the host per-round loop (``rounds_per_dispatch=1``) vs the K=8 fused
    ``lax.scan`` program — the evidence ROADMAP item 2 asked for:
    per-round dispatch count cut to 1/K (the ``fused_round_dispatches``
    counter) with <=1 new compile cache-key per (K, shape) bucket (the
    ``fused_rounds_fn`` registry entry), plus the documented f32-margin
    divergence measured as a max-abs-proba delta.
    """
    from mpitree_tpu import GradientBoostingClassifier
    from mpitree_tpu.obs import REGISTRY

    Xtr, ytr, Xte, yte = _load(npz_path)
    platform = _device_platform()
    ytr2 = (ytr == 2).astype(np.int64)
    yte2 = (yte == 2).astype(np.int64)
    iters, K = 16, 8
    out: dict = {
        "platform": platform, "max_iter": iters, "max_depth": 4, "K": K,
        "n_samples": int(Xtr.shape[0]), "n_features": int(Xtr.shape[1]),
    }

    def side(rpd):
        keys0 = REGISTRY.count("fused_rounds_fn")
        t0 = time.perf_counter()
        clf = GradientBoostingClassifier(
            max_iter=iters, max_depth=4, max_bins=256, backend=platform,
            random_state=0, rounds_per_dispatch=rpd,
        ).fit(Xtr, ytr2)
        fit_s = time.perf_counter() - t0
        counters = clf.fit_report_.get("counters", {})
        sec = {
            "fit_s": round(fit_s, 3),
            "round_s": round(fit_s / max(clf.n_iter_, 1), 3),
            # Host loop: one build dispatch per round; fused: the counted
            # K-round dispatches.
            "dispatches": int(
                counters.get("fused_round_dispatches") or iters
            ),
            "new_compile_keys": REGISTRY.count("fused_rounds_fn") - keys0,
            "test_acc": round(
                float((clf.predict(Xte) == yte2).mean()), 4
            ),
            "record": record_digest(clf.fit_report_),
        }
        return sec, clf

    out["host_loop"], host_clf = side(1)
    out["fused"], fused_clf = side(K)
    out["dispatch_reduction_x"] = round(
        out["host_loop"]["dispatches"] / out["fused"]["dispatches"], 2
    )
    out["fit_speedup_x"] = round(
        out["host_loop"]["fit_s"] / out["fused"]["fit_s"], 3
    )
    # Documented divergence (f64 host margins vs the fused f32 carry):
    # quantify it so "bit-identical across mesh sizes, NOT across
    # rounds_per_dispatch" stays an honest, measured statement.
    sample = Xte[:10_000]
    out["max_abs_proba_delta"] = round(float(np.max(np.abs(
        host_clf.predict_proba(sample) - fused_clf.predict_proba(sample)
    ))), 6)
    return out


def worker_serving(npz_path: str) -> dict:
    """The compiled-serving section (mpitree_tpu.serving, ISSUE 7).

    Publishes a ~500-tree GBDT (72 softmax rounds x 7 covtype classes at
    a bounded fit-row cap — the section measures PREDICT; the fit is
    setup) into a bucket-warmed registry, then reports the request-path
    numbers ROADMAP item 1 asked for: p50/p99 latency at batch sizes
    1/64/4096, sustained rows/s on a large tiled batch, and the speedup
    over the estimator predict path (stacked descent + host-side value
    application) on the same model.
    """
    from mpitree_tpu import GradientBoostingClassifier
    from mpitree_tpu.obs import REGISTRY
    from mpitree_tpu.serving import ModelRegistry

    Xtr, ytr, Xte, yte = _load(npz_path)
    platform = _device_platform()
    fit_rows = min(len(Xtr), 40_000)
    # x n_classes trees — ~500 for covtype's 7 classes on the full
    # workload; --rows smoke captures (the CPU evidence runs) fit a
    # >=100-tree ensemble instead so the section bounds on laptop CPUs.
    rounds = 72 if len(Xtr) > 100_000 else 18
    t0 = time.perf_counter()
    clf = GradientBoostingClassifier(
        max_iter=rounds, max_depth=4, max_bins=256, backend=platform,
        random_state=0,
    ).fit(Xtr[:fit_rows], ytr[:fit_rows])
    fit_s = time.perf_counter() - t0
    out: dict = {
        "platform": platform,
        "n_trees": len(clf.trees_),
        "fit_rows": fit_rows,
        "n_features": int(Xtr.shape[1]),
        "fit_s": round(fit_s, 3),
        "record": record_digest(clf.fit_report_),
    }

    # Estimator path first (it would warm the serving leaf-id table
    # anyway): stacked descent + host value application.
    reps = max(1, 500_000 // len(Xte))
    Xbig = np.tile(Xte, (reps, 1))
    clf.predict(Xbig)
    t0 = time.perf_counter()
    clf.predict(Xbig)
    est_rows = len(Xbig) / (time.perf_counter() - t0)
    out["estimator_rows_per_s"] = round(est_rows)

    reg = ModelRegistry()
    t0 = time.perf_counter()
    model = reg.publish("bench", clf)
    out["publish_warm_s"] = round(time.perf_counter() - t0, 3)
    out["serving_exact"] = bool(model.exact)
    out["kernel"] = "pallas" if model._use_kernel else "xla"
    # Numeric twin of the kernel string: strings never reach the flight
    # envelope's metrics (scalar_metrics skips them), and the advisor's
    # serving consultation groups rows by this 0/1.
    out["kernel_pallas"] = int(model._use_kernel)

    lowerings0 = REGISTRY.count("serving_traverse")
    rng = np.random.default_rng(0)
    for bucket, req in ((1, 300), (64, 150), (4096, 30)):
        lat = []
        served = 0
        for _ in range(req):
            lo = int(rng.integers(0, max(len(Xte) - bucket, 1)))
            batch = Xte[lo:lo + bucket]
            t0 = time.perf_counter()
            reg.predict("bench", batch)
            lat.append(time.perf_counter() - t0)
            # Rows ACTUALLY served: on --rows smoke captures the test
            # split can be smaller than the 4096 bucket — crediting the
            # bucket width would inflate the throughput number.
            served += len(batch)
        lat.sort()
        out[f"b{bucket}_p50_ms"] = round(lat[len(lat) // 2] * 1e3, 3)
        out[f"b{bucket}_p99_ms"] = round(
            lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3, 3
        )
        out[f"b{bucket}_rows_per_s"] = round(served / sum(lat))
    t0 = time.perf_counter()
    reg.predict("bench", Xbig)
    sus = len(Xbig) / (time.perf_counter() - t0)
    out["sustained_rows_per_s"] = round(sus)
    out["rows_sustained"] = len(Xbig)
    out["speedup_vs_estimator"] = round(sus / max(est_rows, 1e-9), 2)
    # The registry swap contract: zero new traversal lowerings on the
    # request path after the bucket warmup.
    out["request_path_lowerings"] = (
        REGISTRY.count("serving_traverse") - lowerings0
    )
    out["test_acc"] = round(
        float((reg.predict("bench", Xte) == yte).mean()), 4
    )

    # Quantized serving (ISSUE 17): the same ensemble behind int8-delta
    # value tables + bf16 thresholds. The publish is exactness-gated (the
    # report lands here); the capacity claim is priced through the ONE
    # VMEM source (obs.memory.serve_kernel_row_tile) — max nodes/tree the
    # Pallas tier can hold at a fixed row tile, quantized vs f32.
    from mpitree_tpu.obs import memory as memory_lib
    from mpitree_tpu.serving.quantize import QuantizationError

    try:
        # Margin accumulation sums one int8 half-step (~2e-3 for
        # lr-scaled covtype leaves) PER TREE, so the worst-case logit
        # delta grows linearly in the ensemble — gate at that analytic
        # bound, not the single-model default. The report still records
        # the actual delta; argmax agreement below is the honest signal.
        model_q = reg.publish("bench_q", clf, quantize="int8",
                              quantize_tol=max(5e-2,
                                               2.5e-3 * len(clf.trees_)))
    except QuantizationError as e:
        out["quantized"] = {"refused": dict(e.report)}
        return out
    q: dict = {"report": dict(model_q.serve_report_["quantization"])}
    lowerings_q0 = REGISTRY.count("serving_traverse")
    reg.predict("bench_q", Xbig)
    t0 = time.perf_counter()
    reg.predict("bench_q", Xbig)
    q["sustained_rows_per_s"] = round(len(Xbig) / (time.perf_counter() - t0))
    q["request_path_lowerings"] = (
        REGISTRY.count("serving_traverse") - lowerings_q0
    )
    q["test_acc"] = round(
        float((reg.predict("bench_q", Xte) == yte).mean()), 4
    )
    q["agrees_with_f32"] = round(float(
        (reg.predict("bench_q", Xte) == reg.predict("bench", Xte)).mean()
    ), 4)

    # VMEM capacity, both table forms, same (features, kv, n_out) shape:
    # largest nodes/tree the kernel row-tile search still prices into
    # the budget. The quantized tables halve the dominant term, so the
    # ratio must clear 2x.
    def _max_nodes(quantized: bool) -> int:
        lo, hi = 128, 1 << 22
        while lo < hi:
            mid = (lo + hi + 1) // 2
            tile = memory_lib.serve_kernel_row_tile(
                mid, Xte.shape[1], 1, len(clf.classes_),
                quantized=quantized,
            )
            lo, hi = (mid, hi) if tile is not None else (lo, mid - 1)
        return lo

    cap_f32, cap_q = _max_nodes(False), _max_nodes(True)
    q["vmem_max_nodes_f32"] = cap_f32
    q["vmem_max_nodes_int8"] = cap_q
    q["vmem_capacity_ratio"] = round(cap_q / max(cap_f32, 1), 2)
    out["quantized"] = q
    return out


def worker_mesh2d_ab(npz_path: str) -> dict:
    """1-D vs 2-D (data, feature) mesh A/B (ISSUE 10).

    Same bounded-section protocol as ``subtraction_ab``: two cold+warm
    timed full-depth device fits of the same workload — an ``(n, 1)``
    data mesh vs an ``(n/2, 2)`` rows-x-features mesh — comparing wall
    clock and the wire ledger's recorded payloads. The headline is the
    ``split_hist_psum`` logical-payload ratio (the feature-sharded slab
    should be ~1/2 the 1-D payload, independent of wall clock) plus the
    per-axis wire breakdown; structural identity (node/depth/accuracy
    equality — the mesh-invariance pin on the real workload) rides along.
    CPU workers force a virtual 8-device mesh; a single-device worker
    skips honestly.
    """
    import jax

    # Must precede first device use; harmless after (the config update
    # refuses once the backend is up — fall back to whatever exists).
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception:  # noqa: BLE001 — older wheels / initialized backend
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    from mpitree_tpu import DecisionTreeClassifier

    Xtr, ytr, Xte, yte = _load(npz_path)
    platform = _device_platform()
    D = len(jax.devices())
    if D < 2:
        return {"skipped": f"needs >= 2 devices, have {D}",
                "platform": platform}
    D = D if D % 2 == 0 else D - 1
    out: dict = {"platform": platform, "n_devices": D, "depth": DEPTH,
                 "n_samples": int(Xtr.shape[0]),
                 "n_features": int(Xtr.shape[1])}
    for name, shape in (("mesh_1d", (D, 1)), ("mesh_2d", (D // 2, 2))):
        def once():
            clf = DecisionTreeClassifier(
                max_depth=DEPTH, max_bins=256, backend=platform,
                n_devices=shape, refine_depth=None,
            )
            t0 = time.perf_counter()
            clf.fit(Xtr, ytr)
            return time.perf_counter() - t0, clf

        cold_s, clf = once()
        warm_s, clf = once()
        rep = clf.fit_report_
        out[name] = {
            "shape": list(shape),
            "cold_s": round(cold_s, 3), "warm_s": round(warm_s, 3),
            "test_acc": round(float((clf.predict(Xte) == yte).mean()), 4),
            "tree_n_nodes": clf.tree_.n_nodes,
            "tree_depth": clf.tree_.max_depth,
            "split_psum_bytes": int(
                rep["collectives"].get("split_hist_psum", {})
                .get("bytes", 0)
            ),
            "wire": {
                k: rep.get("wire", {}).get(k)
                for k in ("axes", "wire_bytes", "data_bytes",
                          "feature_bytes")
            },
            "record": record_digest(rep),
        }
    p1 = out["mesh_1d"]["split_psum_bytes"]
    p2 = out["mesh_2d"]["split_psum_bytes"]
    if p1 and p2:
        out["split_psum_reduction_x"] = round(p1 / p2, 3)
    out["warm_speedup_2d_vs_1d"] = round(
        out["mesh_1d"]["warm_s"] / out["mesh_2d"]["warm_s"], 3
    )
    out["same_structure"] = bool(
        out["mesh_1d"]["tree_n_nodes"] == out["mesh_2d"]["tree_n_nodes"]
        and out["mesh_1d"]["tree_depth"] == out["mesh_2d"]["tree_depth"]
        and out["mesh_1d"]["test_acc"] == out["mesh_2d"]["test_acc"]
    )
    return out


def worker_forest(npz_path: str) -> dict:
    """BASELINE configs[4] on the live platform (core shared with bench.py:
    one-program tree-sharded forest vs T sequential fused builds)."""
    import jax

    from bench import forest_compare

    # forest_compare's cpu branch must set jax_num_cpu_devices BEFORE any
    # backend initializes — read the pinned platform from config (set by
    # _pin_platform for cpu) instead of jax.devices(), which would
    # initialize the backend and make that update raise.
    platform = jax.config.jax_platforms or _device_platform()
    Xtr, ytr, _, _ = _load(npz_path)
    return forest_compare(Xtr, ytr, platform)


def worker_ingest(npz_path: str) -> dict:
    """Out-of-core streaming ingestion at the full workload shape
    (ISSUE 15): planner-derived chunks, streamed sketch+bin+place, one
    streamed fit pinned fingerprint-identical to the in-memory fit, and
    the headline the ROADMAP asks for — rows/s/host plus peak host RSS
    while the raw matrix never materializes whole in the fit path."""
    import jax

    from mpitree_tpu import DecisionTreeClassifier
    from mpitree_tpu.ingest import StreamedDataset
    from mpitree_tpu.obs import memory as memory_lib

    Xtr, ytr, Xte, yte = _load(npz_path)
    platform = _device_platform()
    N, F = Xtr.shape
    chunk_rows = memory_lib.ingest_chunk_rows(F)
    ds = StreamedDataset.from_arrays(Xtr, ytr, chunk_rows=chunk_rows)

    rss0 = memory_lib.host_rss_bytes() or 0
    t0 = time.perf_counter()
    clf = DecisionTreeClassifier(
        max_depth=DEPTH, max_bins=256, backend=platform,
        n_devices="all",
    ).fit(ds)
    streamed_s = time.perf_counter() - t0
    rss1 = memory_lib.host_rss_bytes() or 0
    stats = clf.ingest_stats_

    # The identity pin: the in-memory fit of the same rows must build
    # the same tree — refine included, since the streamed tail now
    # replays the chunk stream for its raw rows (ISSUE 20).
    t0 = time.perf_counter()
    ref = DecisionTreeClassifier(
        max_depth=DEPTH, max_bins=256, backend=platform,
        n_devices="all",
    ).fit(Xtr, ytr)
    inmem_s = time.perf_counter() - t0

    fp_s = (clf.fit_report_.get("fingerprints") or {}).get("fit")
    fp_m = (ref.fit_report_.get("fingerprints") or {}).get("fit")
    out = {
        "platform": jax.devices()[0].platform,
        "rows": int(N), "features": int(F),
        "chunk_rows": int(chunk_rows),
        "n_chunks": -(-int(N) // int(chunk_rows)),
        "streamed_fit_s": round(streamed_s, 3),
        "inmem_fit_s": round(inmem_s, 3),
        "sketch_s": stats.get("sketch_s"),
        "bin_place_s": stats.get("bin_place_s"),
        "ingest_rows_per_s_host": stats.get("rows_per_s_host"),
        "host_rss_peak_bytes": int(max(rss0, rss1)),
        "host_rss_delta_bytes": int(max(rss1 - rss0, 0)),
        "host_budget_bytes": memory_lib.host_ingest_budget(),
        "fingerprint_identical": bool(fp_s and fp_s == fp_m),
        "test_acc": round(float((clf.predict(Xte) == yte).mean()), 4),
        "record": record_digest(clf.fit_report_),
    }

    # ISSUE 20: the whole estimator surface streams — time the GBDT
    # round loop and the keyed-bootstrap forest over the same stream,
    # each pinned fingerprint-identical to its in-memory twin (the
    # forest twin opts in to the keyed draws the streamed path uses).
    from mpitree_tpu import GradientBoostingClassifier, RandomForestClassifier

    def ab(name, make, ref_env=None):
        t0 = time.perf_counter()
        s = make().fit(
            StreamedDataset.from_arrays(Xtr, ytr, chunk_rows=chunk_rows)
        )
        sec = {"streamed_fit_s": round(time.perf_counter() - t0, 3)}
        sec["host_rss_peak_bytes"] = memory_lib.host_rss_bytes() or 0
        old = {k: os.environ.get(k) for k in (ref_env or {})}
        os.environ.update(ref_env or {})
        try:
            t0 = time.perf_counter()
            m = make().fit(Xtr, ytr)
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        sec["inmem_fit_s"] = round(time.perf_counter() - t0, 3)
        a = (s.fit_report_.get("fingerprints") or {}).get("fit")
        b = (m.fit_report_.get("fingerprints") or {}).get("fit")
        sec["fingerprint_identical"] = bool(a and a == b)
        sec["test_acc"] = round(float((s.predict(Xte) == yte).mean()), 4)
        out[name] = sec

    ab("gbdt", lambda: GradientBoostingClassifier(
        max_iter=10, max_depth=6, max_bins=256, backend=platform,
        random_state=0,
    ))
    ab("forest", lambda: RandomForestClassifier(
        n_estimators=8, max_depth=DEPTH, max_bins=256, backend=platform,
        n_devices="all", random_state=0, refine_depth=None,
    ), ref_env={"MPITREE_TPU_KEYED_BOOTSTRAP": "1"})
    return out


WORKERS = {
    "north_star": worker_north_star,
    "north_star_fused": worker_north_star_fused,
    "engine_fused": lambda p: worker_engine(p, "fused"),
    "engine_levelwise": lambda p: worker_engine(p, "levelwise"),
    "hist_tput": worker_hist_tput,
    "device_bin": worker_device_bin,
    "refine_sweep": worker_refine_sweep,
    "forest": worker_forest,
    "predict": worker_predict,
    "boosting": worker_boosting,
    "leafwise_ab": worker_leafwise_ab,
    "gbdt_fusedK": worker_gbdt_fusedK,
    "mesh2d_ab": worker_mesh2d_ab,
    "serving": worker_serving,
    "ingest": worker_ingest,
}


# --------------------------------------------------------------------------
# Parent orchestration
# --------------------------------------------------------------------------

def run_tagged_subprocess(argv: list, timeout_s: int,
                          tag: str = "SECTION_JSON:") -> tuple:
    """(parsed-dict-or-None, error-or-None) for one bounded worker.

    The one copy of the tempfile/subprocess/tagged-JSON-line/timeout
    scaffold — bench.py's fit workers use it too, so a protocol fix lands
    once. Bounded because a mid-fit tunnel hang blocks in native code
    where in-process signal timeouts cannot fire.
    """
    try:
        out = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout_s,
        )
        for line in out.stdout.splitlines():
            if line.startswith(tag):
                return json.loads(line[len(tag):]), None
        return None, f"rc={out.returncode}; stderr: {out.stderr[-1500:]}"
    except subprocess.TimeoutExpired:
        return None, f"timed out after {timeout_s}s"
    except OSError as e:
        return None, f"OSError: {e}"


def run_section(name: str, npz_path: str, timeout_s: int,
                platform: str) -> tuple:
    """(result-dict-or-None, error-or-None) for one bounded section."""
    return run_tagged_subprocess(
        [sys.executable, os.path.abspath(__file__), "--section-worker",
         name, npz_path, platform],
        timeout_s,
    )


def read_capture_lines(path: str = OUT_PATH) -> list:
    """Parse the jsonl tolerantly: a SIGKILL mid-append (the watcher's own
    timeout path) can leave one truncated line, which must not discard the
    whole file's history."""
    records: list = []
    try:
        f = open(path)
    except OSError:
        return records
    with f:
        for ln in f:
            if not ln.strip():
                continue
            try:
                records.append(json.loads(ln))
            except json.JSONDecodeError:
                continue
    return records


def is_genuine_capture(rec: dict, *, full_only: bool = False) -> bool:
    """The ONE copy of the 'real accelerator measurement' predicate.

    Shared by latest_line's merge and the watcher's done/success checks so
    they can never drift. full_only additionally rejects --rows smoke
    lines (records predating the rows_cap field were all full-workload).
    """
    return (
        rec.get("platform_probe") in ("tpu", "axon")
        and any(k in rec for k in WORKERS)
        and not (full_only and rec.get("rows_cap") is not None)
    )


def observed_section_seconds(sec: str, path: str = OUT_PATH) -> float | None:
    """Max observed in-section wall (s) for ``sec`` across genuine
    full-workload capture lines — the evidence base for the watcher's
    per-section budgets (tools/tpu_watcher.derive_budget).

    Sums every ``*_s`` DURATION scalar in the section payload,
    RECURSIVELY — sections nest real wall-clock (north_star's
    ``subtraction_ab`` off-fit is ~half the section's wall;
    refine_sweep's timings live entirely under ``sweep: [...]``), and a
    top-level-only sum would derive budgets from a fraction of the true
    duration. ``phases`` subtrees are skipped (their seconds are a
    breakdown of cold_s/warm_s, not additional wall), and rate keys
    (``*_per_s``: throughput_cells_per_s, ...) also end in ``_s`` but
    would inflate a budget by seven orders of magnitude, so both are
    excluded explicitly. Takes the max across lines so a budget derived
    under a fast tunnel still covers the slow days. None when the
    section has never been captured (the watcher then falls back to its
    static table). The A/B mirror of the main fit's warm_s under
    ``subtraction_ab.main`` double-counts one warm fit — a deliberate
    safe-high bias for a timeout budget, bounded by the clamp.
    """

    def walk(node) -> float:
        # "phases" (span breakdown of cold_s/warm_s) and "record" (obs
        # digest, carries wall_s) restate durations already counted.
        if isinstance(node, dict):
            return sum(
                float(v) if (
                    k.endswith("_s") and not k.endswith("per_s")
                    and isinstance(v, (int, float))
                    and not isinstance(v, bool)
                ) else walk(v)
                for k, v in node.items() if k not in ("phases", "record")
            )
        if isinstance(node, list):
            return sum(walk(v) for v in node)
        return 0.0

    best = None
    for rec in read_capture_lines(path):
        if not is_genuine_capture(rec, full_only=True) or sec not in rec:
            continue
        payload = rec.get(sec)
        if not isinstance(payload, dict):
            continue
        t = walk(payload)
        if t > 0:
            best = t if best is None else max(best, t)
    return best


def latest_line(path: str = OUT_PATH, *, full_only: bool = False) -> dict | None:
    """Newest genuine TPU data, merged per-section — bench.py's tpu_last_known.

    The tunnel is flaky mid-run: one line may carry north_star while a later
    retry line carries only the sections that hung the first time (each
    watcher retry appends its own line). Requiring ``ok`` (every section
    succeeded) would discard all of them. Instead, merge section payloads
    newest-wins across records that measured on an accelerator platform.
    Only records sharing the NEWEST record's workload key (dataset, depth,
    refine_depth) participate — a ``--rows`` smoke run must never be fused
    with (or mislabeled as) full-workload numbers. CPU-fallback lines
    (``platform_probe`` != tpu/axon) and lines with no successful section
    contribute nothing.
    """
    genuine = [
        rec for rec in read_capture_lines(path)
        if is_genuine_capture(rec, full_only=full_only)
    ]
    if not genuine:
        return None

    def workload(rec):
        return (rec.get("dataset"), rec.get("depth"),
                rec.get("refine_depth"))

    key = workload(genuine[-1])
    merged: dict = {"dataset": key[0], "depth": key[1],
                    "refine_depth": key[2], "merged_from": []}
    for rec in genuine:  # oldest -> newest, so later updates win
        if workload(rec) != key:
            continue
        secs = {k: rec[k] for k in WORKERS if k in rec}
        merged.update(secs)
        merged["ts"] = rec.get("ts")
        merged["git"] = rec.get("git")
        merged["platform_probe"] = rec.get("platform_probe")
        merged["merged_from"].append(
            {"ts": rec.get("ts"), "git": rec.get("git"),
             "sections": sorted(secs)})
    return merged


def serving_headline(path: str = OUT_PATH) -> str | None:
    """One-line serving summary from the newest captured serving section —
    the bench headline `record` consumer (ROADMAP carried follow-up):
    p50/p99 per bucket, sustained rows/s, speedup over the estimator
    path, kernel tier, and the request-path compile count. Pure string
    work over stored payloads (no jax import) so the watcher can log it."""
    for rec in reversed(read_capture_lines(path)):
        s = rec.get("serving")
        if not isinstance(s, dict):
            continue
        buckets = " ".join(
            f"b{b}: p50={s.get(f'b{b}_p50_ms')}ms "
            f"p99={s.get(f'b{b}_p99_ms')}ms"
            for b in (1, 64, 4096) if f"b{b}_p50_ms" in s
        )
        return (
            f"serving[{s.get('platform')}] {s.get('n_trees')} trees "
            f"{buckets} sustained={s.get('sustained_rows_per_s')} rows/s "
            f"({s.get('speedup_vs_estimator')}x vs estimator) "
            f"kernel={s.get('kernel')} "
            f"request_compiles={s.get('request_path_lowerings')}"
        )
    return None


def print_report(path: str = OUT_PATH) -> int:
    """`make report`: pretty-print the newest capture line with its
    embedded record digests — the artifact-side view of fit_report_."""
    lines = read_capture_lines(path)
    if not lines:
        print(f"no capture lines in {path}")
        return 1
    rec = lines[-1]
    head = {k: rec.get(k) for k in
            ("ts", "git", "platform_probe", "dataset", "rows_cap", "depth",
             "refine_depth", "ok") if k in rec}
    print(json.dumps(head, indent=2))
    for sec in WORKERS:
        payload = rec.get(sec)
        if not isinstance(payload, dict):
            continue
        keys = {k: v for k, v in payload.items()
                if isinstance(v, (int, float, str)) and k != "record"}
        print(f"\n[{sec}] " + json.dumps(keys))
        if isinstance(payload.get("record"), dict):
            print("  record | " + format_record_digest(payload["record"]))
    head_line = serving_headline(path)
    if head_line:
        print("\n" + head_line)
    if rec.get("errors"):
        print("\nerrors: " + json.dumps(rec["errors"]))
    return 0


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=None,
                   help="cap training rows (default: full dataset)")
    p.add_argument("--out", default=OUT_PATH)
    p.add_argument("--report", action="store_true",
                   help="pretty-print the newest capture line (with its "
                        "embedded record digests) and exit")
    p.add_argument("--sweep-refine", action="store_true")
    # Value-ranked: healthy tunnel windows are short, so the sections with
    # the most evidence per second come first (hist_tput -> north_star ->
    # engine_fused -> boosting -> the rest).
    p.add_argument("--sections", default="hist_tput,north_star,"
                   "engine_fused,boosting,leafwise_ab,gbdt_fusedK,"
                   "mesh2d_ab,serving,ingest,engine_levelwise,forest")
    p.add_argument("--timeout", type=int, default=SECTION_TIMEOUT_S)
    p.add_argument("--platform", default="auto",
                   help="jax platform for every section (auto = probe, "
                        "falling back to cpu when the accelerator hangs)")
    p.add_argument("--baseline", action="store_true", default=True,
                   help="diff each captured section against its newest "
                        "stored capture (obs.diff; noise thresholds from "
                        "the section's stored dispersion) and self-report "
                        "regressions per section (DEFAULT ON since "
                        "ISSUE 18 — a perf harness that does not read "
                        "its own history is a logger, not a sentinel)")
    p.add_argument("--no-baseline", dest="baseline", action="store_false",
                   help="capture without the self-diff (the pre-18 "
                        "default)")
    args = p.parse_args()

    if args.report:
        return print_report(args.out)

    sections = [s for s in args.sections.split(",") if s]
    if args.sweep_refine:
        sections.append("refine_sweep")

    if args.platform == "auto":
        from bench import probe_backend

        platform = probe_backend()
    else:
        platform = args.platform
    print(f"[bench-tpu] platform: {platform}", file=sys.stderr)

    from sklearn.model_selection import train_test_split

    from mpitree_tpu.utils.datasets import load_covtype

    X, y, name = load_covtype(args.rows)
    test_size = min(50_000, len(X) // 5)
    Xtr, Xte, ytr, yte = train_test_split(
        X, y, test_size=test_size, random_state=0
    )

    record: dict = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git": _git_head(),
        "platform_probe": platform,
        "dataset": f"{name} ({len(Xtr)}x{X.shape[1]})",
        "rows_cap": args.rows,  # None = the full dataset (watcher's target)
        "depth": DEPTH,
        "refine_depth": REFINE_DEPTH,
    }
    errors: dict = {}

    # Parsed BEFORE this run appends anything: the --baseline diff must
    # compare against prior captures, not this run's own partial lines.
    prior_lines = read_capture_lines(args.out) if args.baseline else []
    baseline_report: dict = {}

    with tempfile.NamedTemporaryFile(suffix=".npz", delete=False) as f:
        npz_path = f.name
    try:
        np.savez(npz_path, Xtr=Xtr, ytr=ytr, Xte=Xte, yte=yte)
        for sec in sections:
            t0 = time.perf_counter()
            res, err = run_section(sec, npz_path, args.timeout, platform)
            took = round(time.perf_counter() - t0, 1)
            if res is not None:
                if args.baseline:
                    d, line = baseline_verdict(sec, res, prior_lines)
                    print(f"[bench-tpu] {line}", file=sys.stderr)
                    if d is not None:
                        baseline_report[sec] = {
                            "verdict": d["verdict"],
                            "regressions": d["regressions"],
                            "changed": d["changed"],
                        }
                flight_append_section(sec, res, platform)
                record[sec] = res
                # Checkpoint the section to the jsonl AS IT COMPLETES: a
                # killed window (watcher timeout, tunnel death, operator
                # ctrl-C) still yields committed evidence for everything
                # that finished. latest_line merges these per-section
                # partial lines with the final summary record; the
                # "partial" marker just keeps the file honest to read.
                with open(args.out, "a") as f:
                    f.write(json.dumps(
                        {**record, "partial": True, "ok": False}
                    ) + "\n")
                print(f"[bench-tpu] {sec}: ok in {took}s", file=sys.stderr)
            else:
                errors[sec] = err
                print(f"[bench-tpu] {sec}: FAILED ({err})", file=sys.stderr)
    finally:
        try:
            os.unlink(npz_path)
        except OSError:
            pass

    if errors:
        record["errors"] = errors
    if baseline_report:
        # Per-section regression verdicts ride the committed line, so
        # the capture artifact itself says whether the round regressed.
        record["baseline"] = baseline_report
    record["ok"] = not errors
    with open(args.out, "a") as f:
        f.write(json.dumps(record) + "\n")
    print(json.dumps(record))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--section-worker":
        os.environ["MPITREE_TPU_PROFILE"] = "1"
        enable_compile_cache(sys.argv[4] if len(sys.argv) >= 5 else None)
        if len(sys.argv) >= 5:
            _pin_platform(sys.argv[4])
        result = WORKERS[sys.argv[2]](sys.argv[3])
        print("SECTION_JSON:" + json.dumps(result))
    else:
        sys.exit(main())
