"""North-star benchmark: depth-20 tree build on covtype-scale data.

Prints the full JSON record, then a compact (<=1000 char) headline as the
FINAL stdout line (the driver parses only a ~2000-char tail):
  {"metric": ..., "value": <our warm fit seconds>, "unit": "s",
   "vs_baseline": <estimated 8-rank MPI reference seconds / ours>, ...}

Robustness contract (this file must never die without emitting JSON):

- The accelerator backend is probed in a *subprocess* with a timeout and
  retries before the parent ever imports jax — a hung or crashing TPU init
  (both observed: UNAVAILABLE at round 1, a hang in the judge environment)
  downgrades the run to the CPU platform instead of erasing the result.
- Every section (our fit, sklearn anchor, reference baseline) is
  independently guarded; failures land in an ``errors`` field and whatever
  partial numbers exist are still emitted.

Baseline methodology (the reference never published covtype numbers, and
this environment has no mpi4py, so the 8-rank baseline is estimated — see
BASELINE.md):

1. A faithful numpy implementation of the reference's algorithm
   (`tests/oracle.py` semantics: exhaustive unique-value threshold scan with
   the full-matrix copies of ``decision_tree.py:73-86``) is timed on growing
   subsamples of the same dataset under a wall-clock budget — the grid runs
   as far past 10k rows as the budget allows (>= 1.5 measured decades in
   practice) instead of extrapolating from a 300-2400 toy range.
2. A power law ``t = a * n^b`` is fit over the measured points and
   extrapolated to the full row count (the *sequential* reference cost).
3. Two 8-rank variants are reported: ``ideal`` divides by 8 (strictly more
   generous to the reference than its own published scaling) and
   ``observed`` divides by 1.6x — the measured k=8-over-k=2 speedup in
   ``/root/reference/time_data.csv:1,3``, treating k=2 as sequential-equal,
   which time_data's near-flat k=2 curve supports. ``vs_baseline`` uses the
   conservative ideal variant.

Accuracy parity is checked against sklearn's DecisionTreeClassifier on a
held-out split and reported alongside.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)
os.environ.setdefault("MPITREE_TPU_PROFILE", "1")  # per-phase fit_stats_

N_ROWS = 581012


def cpu_fallback_rows() -> int:
    """No-TPU fallback size: the FULL north-star workload when the C++ host
    tier is available (fits 581k x 54 depth-20 in ~10-15 s single-threaded),
    else a 200k cap — the numpy fallback has no other wall-clock bound."""
    from mpitree_tpu import native

    return N_ROWS if native.lib() is not None else 200_000


DEPTH = 20
# Hybrid crossover: device engines grow the data-parallel crown to this
# depth, the C++ tier finishes subtrees with exact local candidates —
# recovers the deep-tail accuracy quantile bins lose (measured: delta vs
# sklearn -0.016 -> -0.004 at covtype scale). Round-3 host-tier sweep at
# the full workload (warm_s / test_acc): 7 -> 10.5/0.7445, 8 -> 14.0/0.7424,
# 9 -> 9.7/0.7407, 10 -> 10.5/0.7403 — the shallower crown hands the
# exact-candidate tail more rows and wins on accuracy at equal time, so 7.
# (TPU-transport crossover re-measurement still owed: bench_tpu.py
# --sweep-refine appends it to BENCH_TPU.jsonl when the tunnel is up.)
REFINE_DEPTH = 7
# 750 s reaches the 30k grid point (measured r02: grid to 10k spent ~116 s,
# exponent 1.269 predicts ~380 s for 30k) — >= 2.5 measured decades, so the
# extrapolation to 531k spans <= 1.3 decades (round-2 verdict asked for this).
ORACLE_BUDGET_S = float(os.environ.get("BENCH_ORACLE_BUDGET_S", "750"))
ORACLE_GRID = (100, 300, 1000, 3000, 10_000, 30_000)
PROBE_TIMEOUT_S = 150  # first TPU compile can take ~40s; hang needs a bound
PROBE_RETRIES = 3


def probe_backend() -> str:
    """Decide the JAX platform without risking the parent process.

    Runs ``jax.devices()`` in a subprocess, bounded by a timeout. ERRORS
    are retried (the tunneled backend is flaky-by-default — round 1 died
    on a transient UNAVAILABLE); a HANG aborts the retries immediately
    (an unresponsive tunnel stays down for hours — observed all of round
    3 — and re-probing it costs ~300 s for nothing). Returns the platform
    of the first device on success, or downgrades this process to the CPU
    backend.

    The downgrade must use ``jax.config.update``: this environment's
    sitecustomize pins ``JAX_PLATFORMS`` at interpreter startup, so setting
    the env var here is too late to stick.
    """
    code = "import jax; print(jax.devices()[0].platform)"
    for attempt in range(PROBE_RETRIES):
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
            )
            if out.returncode == 0 and out.stdout.strip():
                return out.stdout.strip().splitlines()[-1]
        except subprocess.TimeoutExpired:
            # A HANG is a down tunnel, not a flaky init — observed to stay
            # down for hours; burning the remaining retries costs ~300 s
            # of every tunnel-down bench for nothing. Errors (UNAVAILABLE
            # at round 1) do resolve on retry and keep theirs.
            break
        time.sleep(5 * (attempt + 1))
    import jax

    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def compact_headline(result: dict, limit: int = 1000) -> str:
    """The <=``limit``-char final stdout line the driver's tail parses.

    Round-4 lesson (`BENCH_r04.json` ``parsed: null``): the full record is
    ~4KB, the driver keeps a ~2000-char tail and parses the LAST line —
    so the headline must be its own short line, whatever the record grew
    to. Selected detail keys first; if still over budget, detail shrinks
    to the two load-bearing fields. Pinned by
    ``tests/test_bench_contract.py``.
    """
    detail = result.get("detail", {}) or {}
    errors = detail.get("errors", {}) or {}
    compact = {k: result.get(k) for k in
               ("metric", "value", "unit", "vs_baseline")}
    cd = {}
    for k in ("platform", "ours_test_acc", "acc_delta_vs_sklearn",
              "tree_depth", "tree_n_nodes", "throughput_cells_per_s",
              "sklearn_s", "mpi8_ideal_s", "vs_baseline_observed"):
        if k in detail:
            cd[k] = detail[k]
    tpu = detail.get("tpu_last_known")
    if isinstance(tpu, dict):
        tcd = {k: tpu.get(k) for k in ("ts", "git", "platform_probe")
               if k in tpu}
        for sec in ("north_star", "north_star_fused", "engine_fused"):
            s = tpu.get(sec)
            if isinstance(s, dict) and "warm_s" in s:
                tcd[sec + "_warm_s"] = s["warm_s"]
        cd["tpu_last_known"] = tcd
    if errors:
        cd["error_keys"] = sorted(errors)
    compact["detail"] = cd
    line = json.dumps(compact)
    if len(line) > limit:  # hard contract: the driver tail must hold it
        compact["detail"] = {k: cd[k] for k in ("platform",
                             "ours_test_acc") if k in cd}
        line = json.dumps(compact)
    if len(line) > limit:
        # Enforce, don't assume — but never at the cost of parseability
        # (a truncated JSON line is as unparseable as an overflowed one):
        # drop detail and bound EVERY field. Non-scalar or oversize values
        # coerce through str() so no type can smuggle unbounded content.
        compact = {
            k: (v if isinstance(v, (int, float, type(None)))
                and len(repr(v)) <= 100 else str(v)[:100])
            for k, v in compact.items()
        }
        compact["detail"] = {}  # after the coercion: stays a JSON object
        line = json.dumps(compact)
    return line


FIT_TIMEOUT_S = 1200  # cold tunnel compile ~40-65s; hang needs a hard bound


def fit_and_summarize(Xtr, ytr, Xte, yte, *, backend=None) -> dict:
    """Cold+warm timed fits and the measurement-protocol summary dict.

    The single source of the protocol — the TPU subprocess worker and the
    host-tier fallback both call it, so the two rows cannot diverge.
    """
    from mpitree_tpu import DecisionTreeClassifier

    def fit_once():
        clf = DecisionTreeClassifier(
            max_depth=DEPTH, max_bins=256, backend=backend,
            refine_depth=REFINE_DEPTH,
        )
        t0 = time.perf_counter()
        clf.fit(Xtr, ytr)
        return time.perf_counter() - t0, clf

    cold_s, _ = fit_once()
    ours_s, clf = fit_once()
    return {
        "ours_s": round(ours_s, 3),
        "ours_cold_s": round(cold_s, 3),
        "ours_test_acc": round(float((clf.predict(Xte) == yte).mean()), 4),
        "tree_depth": clf.tree_.max_depth,
        "tree_n_nodes": clf.tree_.n_nodes,
        "refine_depth": clf.refine_depth,
        "phases": clf.fit_stats_,
    }


def run_fit_worker(npz_path: str) -> None:
    """Subprocess body: the TPU fit, emitted as one JSON line on stdout.

    Runs isolated because a mid-fit tunnel hang blocks in native code where
    signal-based timeouts cannot fire (observed: backend init hung for
    hours this round); the parent kills the whole process instead.
    """
    data = np.load(npz_path)
    out = fit_and_summarize(
        data["Xtr"], data["ytr"], data["Xte"], data["yte"]
    )
    print("BENCH_WORKER_JSON:" + json.dumps(out))


def run_tpu_fit(Xtr, ytr, Xte, yte) -> tuple[dict | None, str | None]:
    """TPU fit in a bounded subprocess; (summary, error-detail-on-failure)."""
    import tempfile

    from bench_tpu import run_tagged_subprocess

    with tempfile.NamedTemporaryFile(suffix=".npz", delete=False) as f:
        npz_path = f.name
    try:
        np.savez(npz_path, Xtr=Xtr, ytr=ytr, Xte=Xte, yte=yte)
        return run_tagged_subprocess(
            [sys.executable, os.path.abspath(__file__), "--fit-worker",
             npz_path],
            FIT_TIMEOUT_S, tag="BENCH_WORKER_JSON:",
        )
    finally:
        try:
            os.unlink(npz_path)
        except OSError:
            pass


DEVICE_ENGINE_ROWS = 100_000
DEVICE_ENGINE_TIMEOUT_S = 900


def run_device_engine_worker(npz_path: str, platform: str) -> None:
    """Subprocess body: one fit forced through the device (XLA) engine.

    ``backend=platform`` bypasses ``prefer_host_path`` and
    ``refine_depth=None`` keeps the C++ tail out, so the recorded phases
    are purely the shard/psum/fused device path — the round-2 verdict
    (Weak #1) requires this number to exist in the artifact on every
    platform, not only when a TPU happens to be up.
    """
    from bench_tpu import _pin_platform

    _pin_platform(platform)
    from mpitree_tpu import DecisionTreeClassifier

    data = np.load(npz_path)
    Xtr, ytr = data["Xtr"], data["ytr"]

    def fit_once():
        clf = DecisionTreeClassifier(
            max_depth=DEPTH, max_bins=256, backend=platform,
            refine_depth=None,
        )
        t0 = time.perf_counter()
        clf.fit(Xtr, ytr)
        return time.perf_counter() - t0, clf

    cold_s, _ = fit_once()
    warm_s, clf = fit_once()
    out = {
        "rows": int(len(Xtr)),
        "backend": platform,
        "warm_s": round(warm_s, 3),
        "cold_s": round(cold_s, 3),
        "tree_n_nodes": clf.tree_.n_nodes,
        "phases": clf.fit_stats_,
    }
    print("BENCH_WORKER_JSON:" + json.dumps(out))


def run_device_engine_fit(Xtr, ytr, platform) -> tuple[dict | None, str | None]:
    """Bounded-subprocess device-engine fit; (summary, error-on-failure)."""
    import tempfile

    from bench_tpu import run_tagged_subprocess

    with tempfile.NamedTemporaryFile(suffix=".npz", delete=False) as f:
        npz_path = f.name
    try:
        n = min(len(Xtr), DEVICE_ENGINE_ROWS)
        np.savez(npz_path, Xtr=Xtr[:n], ytr=ytr[:n])
        return run_tagged_subprocess(
            [sys.executable, os.path.abspath(__file__), "--device-worker",
             npz_path, platform],
            DEVICE_ENGINE_TIMEOUT_S, tag="BENCH_WORKER_JSON:",
        )
    finally:
        try:
            os.unlink(npz_path)
        except OSError:
            pass


# BASELINE configs[4]-shaped forest measurement ("bagged random forest,
# trees sharded across chips"). The device comparison pits the SAME fused
# build body run two ways — T trees as ONE tree-sharded program
# (build_forest_fused) vs T sequential single-tree programs — so the
# speedup isolates exactly the one-program orchestration claim. Workload
# scales by platform: XLA-on-CPU histogram scatters are ~50x slower than
# the C++ host tier, so the CPU fallback shrinks the workload rather than
# blowing the bench budget (recorded in the artifact as scaled_down).
FOREST_SHAPES = {
    "tpu": {"trees": 50, "rows": 200_000, "depth": 12},
    "cpu": {"trees": 16, "rows": 20_000, "depth": 8},
}
# The host tier runs the full configs[4] shape regardless of platform so
# the host-vs-device comparison stays like-for-like with the TPU shape.
FOREST_HOST_SHAPE = FOREST_SHAPES["tpu"]
FOREST_TIMEOUT_S = 1800


def _forest_shape(platform: str) -> dict:
    # Anything that is not the CPU fallback is accelerator-class — the
    # tunneled TPU registers as platform "axon", not "tpu".
    shape = dict(FOREST_SHAPES["cpu" if platform == "cpu" else "tpu"])
    for key in shape:
        env = os.environ.get(f"BENCH_FOREST_{key.upper()}")
        if env:
            shape[key] = int(env)
    return shape


def run_forest_worker(npz_path: str, platform: str) -> None:
    """Subprocess body: the one-program-vs-T-sequential device comparison."""
    from bench_tpu import _pin_platform

    _pin_platform(platform)
    data = np.load(npz_path)
    out = forest_compare(data["Xtr"], data["ytr"], platform)
    print("BENCH_WORKER_JSON:" + json.dumps(out))


def forest_compare(Xtr, ytr, platform: str) -> dict:
    """BASELINE configs[4] measurement core (shared with bench_tpu.py)."""
    if platform == "cpu":
        # 8 virtual devices: the comparison then runs the real tree-sharded
        # program (trees distributed over the mesh), not a 1-device lax.map.
        # No wall-clock parallelism on one core — the honest CPU story is
        # the orchestration delta, recorded as such via scaled_down.
        import jax

        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except RuntimeError:
            # Backend already initialized (a caller touched jax.devices()):
            # proceed on however many devices exist — the comparison still
            # runs, n_devices in the artifact records the actual width.
            pass
    from mpitree_tpu.core.builder import BuildConfig
    from mpitree_tpu.core.fused_builder import (
        build_forest_fused,
        build_tree_fused,
    )
    from mpitree_tpu.ops.binning import bin_dataset
    from mpitree_tpu.parallel import mesh as mesh_lib
    from mpitree_tpu.utils.profiling import PhaseTimer

    Xtr, ytr = np.asarray(Xtr), np.asarray(ytr).astype(np.int32)
    shape = _forest_shape(platform)
    T, n, depth = shape["trees"], min(shape["rows"], len(Xtr)), shape["depth"]
    Xtr, ytr = Xtr[:n], ytr[:n]
    n_classes = int(ytr.max()) + 1

    binned = bin_dataset(Xtr, max_bins=256)
    rng = np.random.default_rng(0)
    weights = rng.multinomial(n, np.full(n, 1.0 / n), size=T).astype(
        np.float32
    )
    masks = np.broadcast_to(
        binned.candidate_mask(), (T,) + binned.candidate_mask().shape
    ).copy()
    cfg = BuildConfig(task="classification", criterion="entropy",
                      max_depth=depth)
    mesh_all = mesh_lib.resolve_mesh(backend=platform, n_devices="all")
    mesh_one = mesh_lib.resolve_mesh(backend=platform, n_devices=1)

    def one_program():
        timer = PhaseTimer(enabled=True)
        t0 = time.perf_counter()
        trees = build_forest_fused(
            binned, ytr, config=cfg, mesh=mesh_all, weights=weights,
            cand_masks=masks, n_classes=n_classes, timer=timer,
        )
        return time.perf_counter() - t0, trees, timer.summary()

    def one_tree(t):
        return build_tree_fused(
            binned, ytr, config=cfg, mesh=mesh_one,
            n_classes=n_classes, sample_weight=weights[t],
        )

    def sequential():
        t0 = time.perf_counter()
        trees = [one_tree(t) for t in range(T)]
        return time.perf_counter() - t0, trees

    cold_one_s, _, _ = one_program()
    one_s, trees_one, phases = one_program()
    # One build warms the single-tree executable; timing all T twice would
    # double the dominant cost of the bench for no extra information.
    t0 = time.perf_counter()
    one_tree(0)
    cold_seq_s = time.perf_counter() - t0
    seq_s, trees_seq = sequential()
    identical = all(
        np.array_equal(a.feature, b.feature)
        and np.array_equal(a.count, b.count)
        for a, b in zip(trees_one, trees_seq)
    )
    out = {
        "trees": T,
        "rows": n,
        "depth": depth,
        "backend": platform,
        "n_devices": int(mesh_all.size),
        "scaled_down": platform == "cpu",
        "one_program": {
            "cold_s": round(cold_one_s, 3),
            "warm_s": round(one_s, 3),
            "phases": phases,
        },
        "t_sequential": {
            "cold_s": round(cold_seq_s, 3),
            "warm_s": round(seq_s, 3),
        },
        "one_program_speedup": round(seq_s / one_s, 2),
        "trees_identical": bool(identical),
    }
    if platform == "cpu":
        out["note"] = (
            "virtual devices timeshare one core: no wall-clock parallelism "
            "is possible here, so this row validates orchestration overhead "
            "and bit-identity; the speedup column is meaningful on real "
            "multi-chip hardware (tree axis = concurrent chips)"
        )
    return out


def run_forest_bench(Xtr, ytr, platform) -> tuple[dict | None, str | None]:
    """Bounded-subprocess forest comparison; (summary, error-on-failure)."""
    import tempfile

    from bench_tpu import run_tagged_subprocess

    with tempfile.NamedTemporaryFile(suffix=".npz", delete=False) as f:
        npz_path = f.name
    try:
        shape = _forest_shape(platform)
        n = min(len(Xtr), shape["rows"])
        np.savez(npz_path, Xtr=Xtr[:n], ytr=ytr[:n])
        return run_tagged_subprocess(
            [sys.executable, os.path.abspath(__file__), "--forest-worker",
             npz_path, platform],
            FOREST_TIMEOUT_S, tag="BENCH_WORKER_JSON:",
        )
    finally:
        try:
            os.unlink(npz_path)
        except OSError:
            pass


def run_forest_host(Xtr, ytr) -> dict:
    """The C++ host tier fitting a configs[4]-scale forest (in process)."""
    from mpitree_tpu import RandomForestClassifier

    shape = FOREST_HOST_SHAPE
    n = min(shape["rows"], len(Xtr))
    t0 = time.perf_counter()
    f = RandomForestClassifier(
        n_estimators=shape["trees"], max_depth=shape["depth"],
        max_bins=256, backend="host", refine_depth=None, random_state=0,
    ).fit(Xtr[:n], ytr[:n])
    fit_s = time.perf_counter() - t0
    return {
        "trees": shape["trees"],
        "rows": n,
        "depth": shape["depth"],
        "backend": "host (C++ tier, per-tree builds)",
        "fit_s": round(fit_s, 3),
        "s_per_tree": round(fit_s / shape["trees"], 3),
        "mean_n_nodes": round(
            float(np.mean([t.n_nodes for t in f.trees_])), 1
        ),
    }


def time_reference_semantics(X, y, n, depth=DEPTH):
    """One fit of the reference algorithm (oracle semantics) on n rows."""
    sys.path.insert(0, os.path.join(_HERE, "tests"))
    import oracle

    t0 = time.perf_counter()
    oracle.grow(X[:n], y[:n], int(y.max()) + 1, max_depth=depth)
    return time.perf_counter() - t0


def load_mpi8_measured(n_full: int) -> dict | None:
    """The measured 8-rank baseline (tools/measure_mpi8.py artifact), if any.

    ``MPI8_BASELINE.json`` holds wall-clock of the reference's UNMODIFIED
    ``ParallelDecisionTreeClassifier`` at 8 ranks over the mpi4py shim
    (``tools/mpi_shim.py``) on this machine — a real run of the parallel
    path (``decision_tree.py:310-479``), not a ratio from time_data.csv.
    Rescales its full-size extrapolation to ``n_full`` with the measured
    exponent when the row counts differ.
    """
    path = os.path.join(_HERE, "MPI8_BASELINE.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        art = json.load(f)
    m = art.get("mpi8")
    if not m or len(m.get("grid", [])) < 2:
        return None
    # Cap the power law at its measured decade span (round-4 verdict #6:
    # a 2.25-decade extrapolation of a 1-core timeshared curve is noise —
    # the 1.888 exponent reflects 8-rank contention on one core, which
    # cannot keep compounding on real hardware). Within the measured span
    # the fit extrapolates as measured; the remaining decades grow
    # LINEARLY in n — the most conservative tail that still favors the
    # reference (real tree builds are superlinear).
    grid_max = max(m["grid"])
    t_last = m["times_s"][m["grid"].index(grid_max)]
    measured_decades = m.get(
        "measured_decades",
        float(np.log10(grid_max / min(m["grid"]))),
    )
    n_cap = min(n_full, int(grid_max * 10 ** measured_decades))
    t_cap = t_last * (n_cap / grid_max) ** m["exponent"]
    observed_s = t_cap * max(n_full / n_cap, 1.0)
    return {
        "mpi8_observed_s": round(observed_s, 1),
        "mpi8_observed_source": {
            "artifact": "MPI8_BASELINE.json",
            "grid": m["grid"],
            "times_s": m["times_s"],
            "exponent": m["exponent"],
            "rms_log_residual": m["rms_log_residual"],
            "extrapolation_cap_rows": n_cap,
            "uncapped_power_law_s": round(
                m["extrapolated_full_s"]
                * (n_full / art.get("n_full", 531012)) ** m["exponent"], 1,
            ),
            "cpu_cores": art.get("cpu_cores"),
            "par_over_seq_at_shared_n": art.get("par_over_seq_at_shared_n"),
            "note": (
                "power-law fit applied only over its measured decade span "
                f"(to {n_cap} rows), linear in n beyond — "
            ) + (art.get("note") or ""),
        },
    }


def measure_baseline(Xtr, ytr, n_full: int) -> dict:
    """Budget-adaptive oracle timing grid + power-law extrapolation."""
    ns, ts = [], []
    spent = 0.0
    for n in ORACLE_GRID:
        if n > len(Xtr):
            break
        if ns and len(ns) >= 2:
            # Predict the next point from the running power law; skip it if
            # it would blow the budget (keeps the driver's bench run bounded).
            b = (np.log(ts[-1]) - np.log(ts[0])) / (np.log(ns[-1]) - np.log(ns[0]))
            pred = ts[-1] * (n / ns[-1]) ** max(b, 1.0)
            if spent + pred > ORACLE_BUDGET_S:
                break
        t = time_reference_semantics(Xtr, ytr, n)
        ns.append(n)
        ts.append(t)
        spent += t
        # The power-law fit needs two points minimum, budget notwithstanding.
        if spent > ORACLE_BUDGET_S and len(ns) >= 2:
            break
    b, log_a = np.polyfit(np.log(ns), np.log(ts), 1)
    seq_est_s = float(np.exp(log_a) * n_full**b)
    resid = np.log(ts) - (log_a + b * np.log(ns))
    out = {
        "ref_subsample_grid": ns,
        "ref_subsample_s": [round(t, 3) for t in ts],
        "ref_measured_max_n": ns[-1],
        "ref_measured_decades": round(float(np.log10(ns[-1] / ns[0])), 2),
        "ref_extrapolated_decades": round(float(np.log10(n_full / ns[-1])), 2),
        "ref_fit_rms_log_residual": round(float(np.sqrt((resid**2).mean())), 4),
        "ref_power_law_exponent": round(float(b), 3),
        "ref_seq_extrapolated_s": round(seq_est_s, 1),
        "mpi8_ideal_s": round(seq_est_s / 8.0, 1),
    }
    measured = load_mpi8_measured(n_full)
    if measured is not None:
        out.update(measured)
        out["baseline_note"] = (
            "ideal = oracle sequential power-law extrapolation / 8 "
            "(generous: the oracle is a numpy reimplementation, faster than "
            "the reference's object-dtype code, and /8 assumes perfect "
            "scaling the reference's own time_data.csv contradicts); "
            "observed = power-law extrapolation of MEASURED 8-rank runs of "
            "the unmodified reference over tools/mpi_shim.py on this "
            "machine (MPI8_BASELINE.json; 8 ranks timesharing "
            f"{measured['mpi8_observed_source'].get('cpu_cores')} core(s) — "
            "an upper bound on real 8-way hardware). vs_baseline uses ideal."
        )
    else:
        # No measured artifact (tools/measure_mpi8.py not yet run here):
        # fall back to the labeled time_data.csv ratio.
        out["mpi8_observed_s"] = round(seq_est_s / 1.6, 1)
        out["baseline_note"] = (
            "reference never published covtype numbers; sequential cost is a "
            "power-law fit over the measured grid above, extrapolated to the "
            "full row count; ideal = /8 (generous to the reference), "
            "observed = /1.6 (time_data.csv k=8-over-k=2 speedup; "
            "MPI8_BASELINE.json absent)"
        )
    return out


def main():
    detail: dict = {}
    errors: dict = {}
    result = {
        "metric": "covtype-scale depth-20 tree build",
        "value": None,
        "unit": "s",
        "vs_baseline": None,
        "detail": detail,
    }
    try:
        platform = probe_backend()
        detail["platform"] = platform

        from sklearn.model_selection import train_test_split

        from mpitree_tpu.utils.datasets import load_covtype

        def load_and_split(n_rows):
            """One split protocol for the primary row and every fallback."""
            X, y, name = load_covtype(n_rows)
            test_size = min(50_000, len(X) // 5)
            Xtr, Xte, ytr, yte = train_test_split(
                X, y, test_size=test_size, random_state=0
            )
            result["metric"] = (
                f"{name} ({len(Xtr)}x{X.shape[1]}) depth-{DEPTH} tree build"
            )
            return X, Xtr, Xte, ytr, yte

        # The tunneled accelerator registers as platform "axon" — every
        # TPU-vs-fallback routing decision must treat it as TPU-class.
        is_accel = platform in ("tpu", "axon")
        n_rows = N_ROWS if is_accel else cpu_fallback_rows()
        X, Xtr, Xte, ytr, yte = load_and_split(n_rows)

        # --- ours: warm-timed depth-20 build --------------------------------
        # TPU fits run in a bounded subprocess (a mid-fit tunnel hang blocks
        # in native code where no signal can fire); a timeout or crash
        # downgrades to the in-process C++ host tier on fewer rows.
        ours_s = None
        try:
            worker = None
            if is_accel:
                worker, tpu_err = run_tpu_fit(Xtr, ytr, Xte, yte)
                if worker is None:
                    errors["tpu_fit"] = (
                        f"TPU fit subprocess failed ({tpu_err}); "
                        f"falling back to the host tier"
                    )
                    # The parent has not touched a device yet (the probe and
                    # fit ran in subprocesses) — pin the CPU platform before
                    # predict-time jax ops can try the hung tunnel.
                    import jax

                    jax.config.update("jax_platforms", "cpu")
                    platform = "cpu"
                    detail["platform"] = "cpu (tpu fit fell back)"
                    if cpu_fallback_rows() != n_rows:
                        X, Xtr, Xte, ytr, yte = load_and_split(
                            cpu_fallback_rows()
                        )

            if worker is None:
                # No TPU -> the C++ host tier (native/split_kernel.cpp),
                # 20x+ faster than XLA-on-CPU scatter at this scale.
                worker = fit_and_summarize(
                    Xtr, ytr, Xte, yte, backend="host"
                )

            ours_s = worker["ours_s"]
            result["value"] = ours_s
            for k in ("ours_cold_s", "ours_test_acc", "tree_depth",
                      "tree_n_nodes", "refine_depth"):
                detail[k] = worker[k]
            if worker.get("phases"):
                detail["phases"] = worker["phases"]
            tree_depth = worker["tree_depth"]
            # Effective throughput of the warm build: every level streams the
            # whole binned matrix once for the histogram pass.
            n_cells = len(Xtr) * X.shape[1]
            levels = max(tree_depth, 1)
            detail["throughput_cells_per_s"] = round(
                n_cells * levels / ours_s
            )
            detail["hist_read_gb_per_s"] = round(
                n_cells * levels * 4 / ours_s / 1e9, 2
            )
        except Exception as e:  # noqa: BLE001 — partial JSON beats a traceback
            errors["ours"] = f"{type(e).__name__}: {e}"

        # --- device-engine fit (never absent from the artifact) -------------
        # On the CPU fallback the north-star number above came from the C++
        # host tier; this section forces one fit through the device (XLA)
        # engine on whatever platform this run landed, so the shard/psum
        # path always has a measured number here (round-2 verdict, Weak #1).
        try:
            dev_sum, dev_err = run_device_engine_fit(Xtr, ytr, platform)
            if dev_sum is not None:
                detail["device_engine"] = dev_sum
            else:
                errors["device_engine"] = dev_err
        except Exception as e:  # noqa: BLE001
            errors["device_engine"] = f"{type(e).__name__}: {e}"

        # --- forest section (BASELINE configs[4]) ---------------------------
        # One-program tree-sharded build vs T sequential builds of the same
        # fused body (bounded subprocess), plus the C++ host tier fitting a
        # 50-tree forest in-process (round-3 verdict, Weak #5).
        try:
            forest: dict = {}
            detail["forest"] = forest  # keep partial results on late errors
            f_dev, f_err = run_forest_bench(Xtr, ytr, platform)
            if f_dev is not None:
                forest["device"] = f_dev
            else:
                errors["forest_device"] = f_err
            forest["host"] = run_forest_host(Xtr, ytr)
        except Exception as e:  # noqa: BLE001
            errors["forest"] = f"{type(e).__name__}: {e}"

        # --- last committed TPU measurement (BENCH_TPU.jsonl) ---------------
        # Embed the merged committed capture unconditionally: on a CPU
        # fallback it is the round's only TPU number; on a live accelerator
        # it still carries sections this run does not measure (tier-swept
        # histogram throughput, refine sweep, watcher retries).
        try:
            from bench_tpu import latest_line

            # Prefer the full-workload merge: a trailing --rows smoke line
            # would otherwise re-key the merge and displace every
            # full-workload section from the round artifact.
            last = latest_line(full_only=True) or latest_line()
            if last is not None:
                detail["tpu_last_known"] = last
        except Exception as e:  # noqa: BLE001
            errors["tpu_last_known"] = f"{type(e).__name__}: {e}"

        # --- sklearn parity anchor ------------------------------------------
        try:
            from sklearn.tree import DecisionTreeClassifier as SkTree

            t0 = time.perf_counter()
            sk = SkTree(max_depth=DEPTH, random_state=0).fit(Xtr, ytr)
            detail["sklearn_s"] = round(time.perf_counter() - t0, 3)
            sk_acc = float(sk.score(Xte, yte))
            detail["sklearn_test_acc"] = round(sk_acc, 4)
            if "ours_test_acc" in detail:
                detail["acc_delta_vs_sklearn"] = round(
                    detail["ours_test_acc"] - sk_acc, 4
                )
        except Exception as e:  # noqa: BLE001
            errors["sklearn"] = f"{type(e).__name__}: {e}"

        # --- reference baseline (measured grid + extrapolation) -------------
        try:
            base = measure_baseline(Xtr, ytr, len(Xtr))
            detail.update(base)
            if ours_s is not None:
                result["vs_baseline"] = round(base["mpi8_ideal_s"] / ours_s, 1)
                detail["vs_baseline_observed"] = round(
                    base["mpi8_observed_s"] / ours_s, 1
                )
                if "mpi8_observed_source" in base:
                    detail["vs_baseline_observed_note"] = (
                        "observed = measured 8-rank reference runs "
                        "timesharing this box's single core — an upper "
                        "bound on real 8-way hardware; quote vs_baseline "
                        "(ideal variant) as the headline"
                    )
        except Exception as e:  # noqa: BLE001
            errors["baseline"] = f"{type(e).__name__}: {e}"
    except Exception as e:  # noqa: BLE001
        errors["setup"] = f"{type(e).__name__}: {e}"
    finally:
        if errors:
            detail["errors"] = errors
        # Full record first (for humans / logs), then a compact headline as
        # the FINAL stdout line: the driver keeps only a ~2000-char tail and
        # parses the last JSON line, so the ~4KB full record alone gets its
        # head (value, vs_baseline) truncated away (round-4 BENCH_r04.json
        # landed `parsed: null` exactly this way).
        print(json.dumps(result))
        print(compact_headline(result))


if __name__ == "__main__":
    try:  # persistent XLA executable cache (see bench_tpu.enable_compile_cache)
        from bench_tpu import enable_compile_cache

        enable_compile_cache()
    except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
        pass
    if len(sys.argv) >= 3 and sys.argv[1] == "--fit-worker":
        os.environ["MPITREE_TPU_PROFILE"] = "1"
        run_fit_worker(sys.argv[2])
    elif len(sys.argv) >= 4 and sys.argv[1] == "--device-worker":
        os.environ["MPITREE_TPU_PROFILE"] = "1"
        run_device_engine_worker(sys.argv[2], sys.argv[3])
    elif len(sys.argv) >= 4 and sys.argv[1] == "--forest-worker":
        run_forest_worker(sys.argv[2], sys.argv[3])
    else:
        main()
