"""North-star benchmark: depth-20 tree build on covtype-scale data.

Prints ONE JSON line:
  {"metric": ..., "value": <our warm fit seconds>, "unit": "s",
   "vs_baseline": <estimated 8-rank MPI reference seconds / ours>, ...}

Baseline methodology (the reference never published covtype numbers, and this
environment has no mpi4py, so the 8-rank baseline is estimated — see
BASELINE.md):

1. A faithful numpy implementation of the reference's algorithm
   (`tests/oracle.py` semantics: exhaustive unique-value threshold scan with
   the full-matrix copies of ``decision_tree.py:73-86``) is timed on
   subsamples of the same dataset.
2. A power law ``t = a * n^b`` is fit and extrapolated to the full row count.
   This extrapolates the *sequential* reference cost.
3. The 8-rank estimate divides by 8 — the *ideal* speedup, strictly more
   generous than the reference's published scaling (k=8 beat k=2 by only
   1.6x at n=241, time_data.csv), so ``vs_baseline`` is an underestimate.

Accuracy parity is checked against sklearn's DecisionTreeClassifier on a
held-out split and reported alongside.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

N_ROWS = 581012
DEPTH = 20
SUBSAMPLE_GRID = (300, 600, 1200, 2400)


def time_reference_semantics(X, y, n, depth=DEPTH):
    """One fit of the reference algorithm (oracle semantics) on n rows."""
    sys.path.insert(0, os.path.join(_HERE, "tests"))
    import oracle

    t0 = time.time()
    oracle.grow(X[:n], y[:n], int(y.max()) + 1, max_depth=depth)
    return time.time() - t0


def main():
    from sklearn.model_selection import train_test_split
    from sklearn.tree import DecisionTreeClassifier as SkTree

    from mpitree_tpu import DecisionTreeClassifier
    from mpitree_tpu.utils.datasets import load_covtype

    X, y, name = load_covtype(N_ROWS)
    Xtr, Xte, ytr, yte = train_test_split(X, y, test_size=50_000, random_state=0)

    # --- ours: warm-timed depth-20 build on the TPU ------------------------
    def fit_once():
        clf = DecisionTreeClassifier(max_depth=DEPTH, max_bins=256)
        t0 = time.time()
        clf.fit(Xtr, ytr)
        return time.time() - t0, clf

    cold_s, _ = fit_once()
    ours_s, clf = fit_once()
    ours_acc = float((clf.predict(Xte) == yte).mean())

    # --- sklearn parity anchor --------------------------------------------
    t0 = time.time()
    sk = SkTree(max_depth=DEPTH, random_state=0).fit(Xtr, ytr)
    sk_s = time.time() - t0
    sk_acc = float(sk.score(Xte, yte))

    # --- reference baseline extrapolation ---------------------------------
    ts = [time_reference_semantics(Xtr, ytr, n) for n in SUBSAMPLE_GRID]
    b, log_a = np.polyfit(np.log(SUBSAMPLE_GRID), np.log(ts), 1)
    seq_est_s = float(np.exp(log_a) * len(Xtr) ** b)
    mpi8_est_s = seq_est_s / 8.0  # ideal speedup — generous to the reference

    result = {
        "metric": f"{name} ({len(Xtr)}x{X.shape[1]}) depth-{DEPTH} tree build",
        "value": round(ours_s, 3),
        "unit": "s",
        "vs_baseline": round(mpi8_est_s / ours_s, 1),
        "detail": {
            "ours_cold_s": round(cold_s, 3),
            "ours_test_acc": round(ours_acc, 4),
            "sklearn_s": round(sk_s, 3),
            "sklearn_test_acc": round(sk_acc, 4),
            "acc_delta_vs_sklearn": round(ours_acc - sk_acc, 4),
            "ref_seq_extrapolated_s": round(seq_est_s, 1),
            "ref_subsample_grid": list(SUBSAMPLE_GRID),
            "ref_subsample_s": [round(t, 3) for t in ts],
            "ref_power_law_exponent": round(float(b), 3),
            "mpi8_baseline_estimate_s": round(mpi8_est_s, 1),
            "baseline_note": "reference never published covtype numbers; "
            "estimate = sequential extrapolation / ideal 8x (see BASELINE.md)",
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
