"""Reference-notebook workflow as a script — the experiments entry point.

Reproduces every experiment in the reference's ``experiments.ipynb`` (the
repo's only entry point, SURVEY.md §2 item 6) on the TPU framework, with no
``mpirun``:

1. parallel iris tree + ``export_text`` (notebook cell 1 — whose ``!mpirun``
   line actually failed in bash; here the parallel path really runs, over
   every visible device),
2. decision-boundary grids for depth 2/5 (cell 3's plot data; rendered to
   PNG when matplotlib is available, saved as npz otherwise),
3. depth-5 iris text export (cell 4),
4. the sequential timing sweep over ``n_samples = arange(1, 250, 10)`` on the
   degenerate all-distinct-labels dataset (cell 5),
5. a parallel sweep at mesh sizes analogous to the reference's k=2/5/8 rank
   counts, written to ``time_data.csv`` in the reference's 3-row format
   (cells 6-7 / time_data.csv),
6. the covtype-scale run the reference never had (BASELINE north star).

Run: ``python examples/experiments.py [--quick] [--outdir OUT]``
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def iris_trees(outdir: str) -> None:
    from sklearn.datasets import load_iris

    from mpitree_tpu.tree import (
        DecisionTreeClassifier,
        ParallelDecisionTreeClassifier,
    )

    iris = load_iris()
    X, y = iris.data[:, :2], iris.target

    # Notebook cell 1: depth-3 parallel tree. The reference prints on rank 0
    # only; with a device mesh there is one process, so we just print.
    clf = ParallelDecisionTreeClassifier(max_depth=3).fit(X, y)
    print(f"# parallel depth-3 iris tree ({clf.WORLD_SIZE} device(s)):")
    print(
        clf.export_text(
            feature_names=iris.feature_names, class_names=iris.target_names
        )
    )

    # Notebook cell 4: sequential depth-5 tree at precision=1.
    clf5 = DecisionTreeClassifier(max_depth=5).fit(X, y)
    print("# sequential depth-5 iris tree:")
    print(
        clf5.export_text(
            feature_names=iris.feature_names,
            class_names=iris.target_names,
            precision=1,
        )
    )


def decision_boundaries(outdir: str) -> None:
    """Notebook cell 3: depth-2 vs depth-5 decision boundaries."""
    from sklearn.datasets import load_iris

    from mpitree_tpu.tree import DecisionTreeClassifier

    iris = load_iris()
    X, y = iris.data[:, :2], iris.target
    xx, yy = np.meshgrid(
        np.linspace(X[:, 0].min() - 0.5, X[:, 0].max() + 0.5, 200),
        np.linspace(X[:, 1].min() - 0.5, X[:, 1].max() + 0.5, 200),
    )
    grid = np.c_[xx.ravel(), yy.ravel()].astype(np.float32)
    fields = {}
    for depth in (2, 5):
        clf = DecisionTreeClassifier(max_depth=depth).fit(X, y)
        fields[f"depth{depth}"] = clf.predict(grid).reshape(xx.shape)

    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        from matplotlib.colors import ListedColormap

        fig, axs = plt.subplots(
            ncols=2, sharex="col", sharey="row", figsize=(12, 4.5),
            gridspec_kw={"wspace": 0, "hspace": 0},
        )
        cmap = ListedColormap(["#97c477", "#fd9177", "#9791dd"])
        for ax, depth in zip(axs, (2, 5)):
            ax.pcolormesh(xx, yy, fields[f"depth{depth}"], cmap=cmap)
            ax.scatter(X[:, 0], X[:, 1], c=y, edgecolor="k", s=18)
            ax.set_title(f"max_depth={depth}")
        path = os.path.join(outdir, "decision_boundaries.png")
        fig.savefig(path, dpi=120, bbox_inches="tight")
        print(f"# decision boundaries -> {path}")
    except Exception:
        path = os.path.join(outdir, "decision_boundaries.npz")
        np.savez(path, xx=xx, yy=yy, **fields)
        print(f"# matplotlib unavailable; boundary fields -> {path}")


def timing_sweeps(outdir: str, quick: bool = False) -> None:
    """Notebook cells 5-7: degenerate-data fit sweeps, time_data.csv format."""
    import jax

    from mpitree_tpu.tree import DecisionTreeClassifier

    x_dim = np.arange(1, 250, 10)

    def sweep(n_devices) -> np.ndarray:
        out = np.empty(len(x_dim))
        for i, n in enumerate(x_dim):
            X = np.arange(n, dtype=np.float64).reshape(-1, 1)
            y = np.arange(n)
            clf = DecisionTreeClassifier(n_devices=n_devices)
            if i == 0:
                clf.fit(X, y)  # pay per-shape compile outside the clock
            start = time.time()
            clf.fit(X, y)
            out[i] = (time.time() - start) * 1000
        return out

    seq_ms = sweep(None)
    print("# sequential sweep (ms):", np.round(seq_ms, 2).tolist())

    # The reference's k=2/5/8 MPI rank counts, capped at what's visible.
    n_dev = len(jax.devices())
    rows = []
    for k in (2, 5, 8):
        if quick or n_dev < k:
            rows.append(seq_ms)  # fewer devices than ranks: sequential stand-in
        else:
            rows.append(sweep(k))
    path = os.path.join(outdir, "time_data.csv")
    np.savetxt(path, np.array(rows), delimiter=",", fmt="%.2f")
    print(f"# parallel sweeps (k=2,5,8 analogue) -> {path}")


def covtype_run(outdir: str, quick: bool = False) -> None:
    from mpitree_tpu import DecisionTreeClassifier
    from mpitree_tpu.utils.datasets import load_covtype

    n = 50_000 if quick else 581_012
    X, y, name = load_covtype(n)
    depth = 12 if quick else 20
    clf = DecisionTreeClassifier(max_depth=depth, max_bins=256)
    clf.fit(X, y)  # warm the compile cache
    start = time.time()
    clf.fit(X, y)
    dt = time.time() - start
    acc = float((clf.predict(X) == y).mean())
    print(
        f"# {name} ({len(X)}x{X.shape[1]}) depth-{depth}: "
        f"fit {dt:.2f}s, train acc {acc:.4f}, "
        f"{clf.tree_.n_nodes} nodes, {clf.tree_.n_leaves} leaves"
    )


def boosting_run(outdir: str, quick: bool = False) -> None:
    """The boosting workload (mpitree_tpu.boosting): histogram GBDT with
    early stopping and a staged-loss curve — the experiment the reference
    (single trees only) never had."""
    from sklearn.model_selection import train_test_split

    from mpitree_tpu import GradientBoostingClassifier
    from mpitree_tpu.utils.datasets import load_covtype

    n = 20_000 if quick else 200_000
    X, y, name = load_covtype(n)
    Xtr, Xte, ytr, yte = train_test_split(X, y, test_size=0.2, random_state=0)
    clf = GradientBoostingClassifier(
        max_iter=10 if quick else 50, max_depth=6, learning_rate=0.2,
        subsample=0.8, early_stopping=True, n_iter_no_change=8,
        random_state=0,
    )
    start = time.time()
    clf.fit(Xtr, ytr)
    dt = time.time() - start
    acc = float((clf.predict(Xte) == yte).mean())
    print(
        f"# boosting {name} ({len(Xtr)}x{X.shape[1]}): "
        f"{clf.n_iter_} rounds x {clf.n_trees_per_iteration_} trees in "
        f"{dt:.2f}s, test acc {acc:.4f}"
    )
    # staged loss curve: the per-round generalization trajectory
    stage_acc = [
        float((p == yte).mean()) for p in clf.staged_predict(Xte)
    ]
    path = os.path.join(outdir, "boosting_staged_acc.csv")
    np.savetxt(path, np.array(stage_acc), delimiter=",", fmt="%.5f")
    print(f"# staged test accuracy per round -> {path}")


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--quick", action="store_true", help="small sizes only")
    p.add_argument("--outdir", default="examples/out")
    p.add_argument(
        "--skip-covtype", action="store_true", help="omit the covtype-scale run"
    )
    p.add_argument(
        "--platform", default="auto",
        help="JAX platform: 'auto' probes the accelerator with a bounded "
             "timeout and falls back to cpu when it hangs (this "
             "environment's sitecustomize overrides JAX_PLATFORMS, so only "
             "an in-process pin sticks); or an explicit name (cpu, tpu)",
    )
    args = p.parse_args()
    if args.platform == "auto":
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from bench import probe_backend

        probe_backend()  # pins cpu in-process when the accelerator hangs
    elif args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    os.makedirs(args.outdir, exist_ok=True)

    iris_trees(args.outdir)
    decision_boundaries(args.outdir)
    timing_sweeps(args.outdir, quick=args.quick)
    if not args.skip_covtype:
        covtype_run(args.outdir, quick=args.quick)
        boosting_run(args.outdir, quick=args.quick)


if __name__ == "__main__":
    main()
