"""obs.cost + obs.advisor smoke: price a fit -> roofline -> close the loop.

The CI gate for the observability-v5 contract (ISSUE 18, wired as
``make cost-smoke``), mirroring ``obs_flight_run``'s role for the
flight-recorder schema. Checks, each exiting nonzero on failure:

1. **priced fit** — with the peak knobs set, a device-engine fit carries
   ``record.compute``: per-entry flops/bytes from the XLA cost model,
   dispatch counts joined from the record's own channels, achieved
   utilization against the optimal-seconds floor, and a roofline
   verdict; the digest carries ``util_pct``/``roofline``.
2. **honest unknown** — without peak knobs on this CPU smoke box the
   ledger prices to ``None`` everywhere (source="unknown"), never a
   guessed number and never a crash.
3. **util trace track** — the priced record synthesizes a ``util``
   counter track that passes the golden Chrome-trace validation.
4. **evidence loop** — a flight store seeded with ``subtraction_ab``
   A/B history (measured winner: on) flips the CPU ``auto`` policy to
   ``hist_subtraction=on`` with a typed ``advisor_hist_subtraction``
   decision; ``policy_evidence="off"`` restores the static resolution
   with no consultation recorded.

Run:  python examples/obs_cost_run.py  (CPU-safe, ~seconds)
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Force the device engine: the auto router sends this smoke workload to
# the pure-host tier, which dispatches no XLA program to price.
os.environ.setdefault("MPITREE_TPU_ENGINE", "levelwise")
os.environ.setdefault("MPITREE_TPU_PROFILE", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

FAILURES: list[str] = []


def check(ok: bool, what: str) -> None:
    tag = "ok" if ok else "FAIL"
    print(f"[{tag}] {what}")
    if not ok:
        FAILURES.append(what)


def _data(n=800, f=8, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = ((X[:, 0] > 0) + (X[:, 1] > 0.5)).astype(np.int64)
    return X, y


def priced_fit_checks(tmp: str) -> None:
    from mpitree_tpu.models.classifier import DecisionTreeClassifier
    from mpitree_tpu.obs import digest
    from mpitree_tpu.obs import trace as trace_mod

    # Modest synthetic peaks: a real part's peak would round this smoke
    # workload's utilization to 0.00 at two decimals.
    os.environ["MPITREE_TPU_PEAK_FLOPS"] = "1e9"
    os.environ["MPITREE_TPU_PEAK_HBM_GBPS"] = "1"
    trace_path = os.path.join(tmp, "cost.trace.json")
    try:
        clf = DecisionTreeClassifier(
            max_depth=4, max_bins=32, backend="cpu"
        ).fit(*_data(), trace_to=trace_path)
    finally:
        del os.environ["MPITREE_TPU_PEAK_FLOPS"]
        del os.environ["MPITREE_TPU_PEAK_HBM_GBPS"]

    comp = clf.fit_report_.get("compute") or {}
    entries = comp.get("entries") or {}
    check(bool(entries), "priced fit carries record.compute entries")
    split = entries.get("split_fn") or {}
    check(
        (split.get("flops") or 0) > 0 and (split.get("bytes") or 0) > 0,
        "split_fn carries XLA cost-model flops + bytes",
    )
    check(
        isinstance(split.get("util_pct"), float)
        and split["util_pct"] > 0
        and split.get("dispatches"),
        "split_fn joins dispatches x floor against its measured wall",
    )
    check(
        comp.get("roofline") in ("compute", "hbm", "ici"),
        f"roofline verdict present ({comp.get('roofline')!r})",
    )
    d = digest(clf.fit_report_)
    check(
        d.get("util_pct") == comp.get("util_pct")
        and d.get("roofline") == comp.get("roofline"),
        "digest carries util_pct + roofline",
    )

    with open(trace_path) as f:
        tr = json.load(f)
    check(
        trace_mod.validate_trace(tr) == [],
        "priced trace passes the golden Chrome-trace validation",
    )
    utils = [
        e for e in tr["traceEvents"]
        if e.get("ph") == "C" and e.get("name") == "util_pct"
    ]
    check(len(utils) >= 2, "util counter track synthesized in the trace")


def honest_unknown_checks() -> None:
    from mpitree_tpu.models.classifier import DecisionTreeClassifier
    from mpitree_tpu.obs import platform_peaks

    peaks = platform_peaks("Strange Accelerator 9000")
    check(
        peaks["source"] == "unknown" and peaks["flops"] is None,
        "unknown platform prices to honest None",
    )
    clf = DecisionTreeClassifier(
        max_depth=3, max_bins=16, backend="cpu"
    ).fit(*_data(400, 6))
    comp = clf.fit_report_.get("compute") or {}
    check(
        comp.get("util_pct") is None and comp.get("roofline") is None,
        "unpriced CPU fit keeps util/roofline None (no guessing)",
    )


def evidence_loop_checks(run_dir: str) -> None:
    from mpitree_tpu.models.classifier import DecisionTreeClassifier
    from mpitree_tpu.obs import FlightStore

    X, y = _data()
    store = FlightStore(run_dir)
    shape = {"n_samples": X.shape[0], "n_features": X.shape[1],
             "n_bins": 32}
    for v in (1.38, 1.42, 1.40, 1.45):
        store.append(
            kind="bench", section="subtraction_ab", platform="cpu",
            metrics={"warm_speedup_on_vs_off": v, **shape},
        )

    os.environ["MPITREE_TPU_RUN_DIR"] = run_dir
    try:
        clf = DecisionTreeClassifier(
            max_depth=4, max_bins=32, backend="cpu"
        ).fit(X, y)
    finally:
        del os.environ["MPITREE_TPU_RUN_DIR"]
    dec = clf.fit_report_["decisions"]
    adv = dec.get("advisor_hist_subtraction") or {}
    check(
        adv.get("value") == "on"
        and (adv.get("inputs") or {}).get("fallback") is None,
        "seeded A/B evidence picks the measured winner (typed decision)",
    )
    check(
        dec.get("hist_subtraction", {}).get("value") == "on",
        "evidence flips the CPU static policy to subtraction=on",
    )

    # the off gate restores the static resolution, no consultation
    os.environ["MPITREE_TPU_RUN_DIR"] = run_dir
    os.environ["MPITREE_TPU_POLICY_EVIDENCE"] = "off"
    try:
        clf_off = DecisionTreeClassifier(
            max_depth=4, max_bins=32, backend="cpu"
        ).fit(X, y)
    finally:
        del os.environ["MPITREE_TPU_RUN_DIR"]
        del os.environ["MPITREE_TPU_POLICY_EVIDENCE"]
    dec_off = clf_off.fit_report_["decisions"]
    check(
        "advisor_hist_subtraction" not in dec_off
        and dec_off.get("hist_subtraction", {}).get("value") == "off",
        "policy_evidence=off restores the static policy bit-for-bit",
    )


def main() -> int:
    with tempfile.TemporaryDirectory() as tmp:
        priced_fit_checks(tmp)
        honest_unknown_checks()
        evidence_loop_checks(os.path.join(tmp, "runs"))
    if FAILURES:
        print(f"\n{len(FAILURES)} check(s) failed:")
        for f in FAILURES:
            print(f"  - {f}")
        return 1
    print("\nall obs.cost / obs.advisor checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
