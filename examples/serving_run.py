"""Asyncio micro-batching serving loop over the warm model registry.

The request-path shape a production front-end would run (ISSUE 7 / ROADMAP
item 1): clients submit single rows (or small bursts), a micro-batcher
coalesces everything that arrives within a short window — up to the
serving bucket size — and ONE traversal dispatch answers the whole batch.
The registry keeps the model bucket-warmed, so no request ever waits on an
XLA compile; a background "trainer" republishes a refreshed model mid-run
to demonstrate the swap-without-recompile contract.

Run:  python examples/serving_run.py  (CPU-safe, ~seconds)
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MAX_BATCH = 64             # the middle serving bucket
MAX_WAIT_MS = 2.0          # micro-batch coalescing window
DEFAULT_DEADLINE_MS = 50.0  # per-request latency budget (batching fairness)
DISPATCH_MARGIN_MS = 5.0   # window slack reserved for the dispatch itself
N_CLIENTS = 8
REQUESTS_PER_CLIENT = 40


def fit_models():
    """A small GBDT 'generation 1' and a refreshed 'generation 2'."""
    from sklearn.datasets import make_classification

    from mpitree_tpu import GradientBoostingClassifier

    X, y = make_classification(
        n_samples=2000, n_features=12, n_informative=8, n_classes=3,
        random_state=0,
    )
    X = X.astype(np.float32)
    gen1 = GradientBoostingClassifier(
        max_iter=12, max_depth=3, random_state=0
    ).fit(X, y)
    gen2 = GradientBoostingClassifier(
        max_iter=16, max_depth=3, random_state=1
    ).fit(X, y)
    return X, gen1, gen2


class MicroBatcher:
    """Coalesce concurrent requests into bucket-sized registry dispatches.

    Batching fairness (ROADMAP item 1 follow-up): the original FIFO
    coalescer let a large burst occupy every consecutive dispatch, so a
    single-row request arriving just behind it waited ``burst/MAX_BATCH``
    full dispatches — starved of its latency budget by other tenants'
    traffic. Every request now carries a DEADLINE and the batcher serves
    strictly in earliest-deadline order (a heap, not a FIFO): a
    tight-deadline request jumps a loose burst's backlog and rides the
    very next dispatch. The coalescing window also closes early when the
    head request's deadline (minus a dispatch margin) would otherwise be
    blown, and ``deadline_misses`` counts requests whose reply landed
    past their budget — the SLO signal a front-end would alert on.
    """

    def __init__(self, registry, name: str, *, max_batch: int = MAX_BATCH,
                 max_wait_ms: float = MAX_WAIT_MS):
        self.registry = registry
        self.name = name
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self._heap: list = []  # (deadline, seq, row, future)
        self._seq = itertools.count()
        self._arrived = asyncio.Event()
        self.batch_sizes: list[int] = []
        self.deadline_misses = 0

    async def serve_forever(self):
        while True:
            while not self._heap:
                self._arrived.clear()
                await self._arrived.wait()
            # Coalesce up to max_batch, but never hold the HEAD (earliest
            # deadline) past its budget minus the dispatch margin.
            window_end = min(
                time.perf_counter() + self.max_wait_ms / 1e3,
                self._heap[0][0] - DISPATCH_MARGIN_MS / 1e3,
            )
            while len(self._heap) < self.max_batch:
                timeout = window_end - time.perf_counter()
                if timeout <= 0:
                    break
                self._arrived.clear()
                try:
                    await asyncio.wait_for(self._arrived.wait(), timeout)
                except asyncio.TimeoutError:
                    break
            take = min(self.max_batch, len(self._heap))
            items = [heapq.heappop(self._heap) for _ in range(take)]
            batch = np.stack([row for _, _, row, _ in items])
            futures = [f for _, _, _, f in items]
            self.batch_sizes.append(take)
            # One bucket-shaped dispatch for the coalesced batch; the
            # executor keeps the event loop responsive while it runs.
            # A dispatch failure must land on the waiting futures — an
            # exception escaping this loop would kill the batcher task
            # and leave every awaiting client hung forever.
            try:
                preds = await asyncio.get_running_loop().run_in_executor(
                    None, self.registry.predict, self.name, batch
                )
            except Exception as exc:
                for fut in futures:
                    if not fut.done():
                        fut.set_exception(exc)
                continue
            done_t = time.perf_counter()
            misses = 0
            for (deadline, _, _, fut), p in zip(items, preds):
                if done_t > deadline:
                    misses += 1
                if not fut.done():  # a client may have been cancelled
                    fut.set_result(p)
            if misses:
                self.deadline_misses += misses
                # Promote the SLO signal into obs.metrics (ISSUE 12
                # satellite / carried ROADMAP obs follow-up): the model's
                # private registry exposes it under the model label via
                # registry.metrics_text(), next to the latency histograms
                # a front-end alerts on.
                try:
                    self.registry.get(self.name).note_deadline_miss(misses)
                except KeyError:
                    pass  # slot dropped mid-flight; the local count stands

    async def request(self, row, *,
                      deadline_ms: float = DEFAULT_DEADLINE_MS) -> object:
        """Submit one row; served within ``deadline_ms`` when capacity
        allows (earliest-deadline-first — a tighter budget means earlier
        service relative to looser concurrent traffic)."""
        fut = asyncio.get_running_loop().create_future()
        heapq.heappush(
            self._heap,
            (time.perf_counter() + deadline_ms / 1e3, next(self._seq),
             row, fut),
        )
        self._arrived.set()
        return await fut


async def start_metrics_exporter(registry, host="127.0.0.1", port=0):
    """Minimal asyncio Prometheus scrape endpoint (ISSUE 9 metrics half).

    Serves ``ModelRegistry.metrics_text()`` — per-model request counters
    and log-bucketed latency histograms with ``model=<slot>`` labels — as
    a plain-text HTTP response on every connection. Zero dependencies;
    ``port=0`` picks a free port (returned via ``server.sockets``). A
    production front-end would point its Prometheus scrape job here.
    """

    async def handle(reader, writer):
        try:
            # Drain the request head through the blank line: closing a
            # socket with unread received bytes can RST and discard the
            # queued response before the scraper reads it.
            while (await reader.readline()) not in (b"\r\n", b"\n", b""):
                pass
            body = registry.metrics_text().encode()
            writer.write(
                b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: text/plain; version=0.0.4\r\n"
                b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                b"Connection: close\r\n\r\n" + body
            )
            await writer.drain()
        finally:
            writer.close()

    return await asyncio.start_server(handle, host, port)


async def scrape_once(host: str, port: int) -> str:
    """One GET against the exporter (the demo's self-scrape)."""
    reader, writer = await asyncio.open_connection(host, port)
    writer.write(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
    await writer.drain()
    raw = await reader.read()
    writer.close()
    return raw.decode().split("\r\n\r\n", 1)[1]


async def main():
    from mpitree_tpu.obs import REGISTRY
    from mpitree_tpu.serving import ModelRegistry

    X, gen1, gen2 = fit_models()
    registry = ModelRegistry(buckets=(1, MAX_BATCH, 4096))
    print("publishing generation 1 (compiles + bucket warmup)...")
    model1 = registry.publish("clicks", gen1)
    batcher = MicroBatcher(registry, "clicks")
    server = asyncio.ensure_future(batcher.serve_forever())
    exporter = await start_metrics_exporter(registry)
    ex_port = exporter.sockets[0].getsockname()[1]
    print(f"metrics exporter on 127.0.0.1:{ex_port}/metrics")

    latencies: list[float] = []

    async def client(cid: int):
        rng = np.random.default_rng(cid)
        for _ in range(REQUESTS_PER_CLIENT):
            row = X[int(rng.integers(0, len(X)))]
            t0 = time.perf_counter()
            await batcher.request(row)
            latencies.append(time.perf_counter() - t0)
            await asyncio.sleep(float(rng.uniform(0, 0.004)))

    async def trainer():
        # Mid-traffic model swap: publish() warms every bucket BEFORE the
        # slot flips, so the request path never sees a compile. Off the
        # event loop (executor) — publishing compiles for seconds, and a
        # stalled loop would freeze every in-flight request's future.
        await asyncio.sleep(0.15)
        before = REGISTRY.count("serving_traverse")
        await asyncio.get_running_loop().run_in_executor(
            None, registry.publish, "clicks", gen2
        )
        print(
            f"swapped to generation 2 under load "
            f"(+{REGISTRY.count('serving_traverse') - before} lowerings, "
            "all during publish warmup)"
        )

    t0 = time.perf_counter()
    await asyncio.gather(*(client(i) for i in range(N_CLIENTS)), trainer())
    wall = time.perf_counter() - t0
    server.cancel()

    lat_ms = np.sort(np.asarray(latencies)) * 1e3
    n = len(lat_ms)
    print(
        f"\n{n} requests in {wall:.2f}s "
        f"({n / wall:.0f} req/s) | "
        f"p50 {lat_ms[n // 2]:.2f}ms  p99 {lat_ms[int(n * 0.99)]:.2f}ms | "
        f"mean batch {np.mean(batcher.batch_sizes):.1f} rows "
        f"(max {max(batcher.batch_sizes)}) | "
        f"{batcher.deadline_misses} past the {DEFAULT_DEADLINE_MS:.0f}ms "
        "budget"
    )
    print("registry:", registry.models())

    # Scrape the exporter once: the Prometheus view of the same traffic —
    # request counters plus per-bucket log-histogram latency series.
    text = await scrape_once("127.0.0.1", ex_port)
    served = [
        ln for ln in text.splitlines()
        if ln.startswith(("mpitree_serving_requests_total",
                          "mpitree_serving_request_seconds_count",
                          "mpitree_serving_deadline_misses_total",
                          "mpitree_registry_publish_total"))
    ]
    print("scraped metrics:")
    for ln in served:
        print("  " + ln)
    # Per-generation latency quantiles (log-bucketed histograms; warmup
    # compiles are excluded by design): gen1 carried the pre-swap bulk.
    for gen, m in (("gen1", model1), ("gen2", registry.get("clicks"))):
        for bucket, row in m.latency_summary()["buckets"].items():
            print(
                f"{gen} bucket {bucket}: p50 {row['p50_ms']}ms "
                f"p99 {row['p99_ms']}ms ({row['count']} requests)"
            )
    exporter.close()
    await exporter.wait_closed()


if __name__ == "__main__":
    asyncio.run(main())
