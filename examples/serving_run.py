"""Continuous-batching serving loop over the warm model registry.

The request-path shape a production front-end would run (ISSUE 7 /
ISSUE 17, ROADMAP item 1): clients submit single rows, the serving
:class:`Scheduler` coalesces everything that arrives within a short
window — earliest-deadline-first, up to the serving bucket size — and
ONE traversal dispatch answers the whole batch. Admission control sheds
overload with typed reasons instead of queueing forever; QoS classes
give interactive traffic a tighter deadline than bulk scoring. The
registry keeps the model bucket-warmed, so no request ever waits on an
XLA compile; a background "trainer" republishes a refreshed model
mid-run to demonstrate the swap-without-recompile contract.

The scheduler owns the batching loop in its own worker thread; the
asyncio side here is just the front-end — clients await
``asyncio.wrap_future`` around the scheduler's concurrent future, and
the metrics exporter serves the MERGED scheduler + registry exposition.

Run:  python examples/serving_run.py  (CPU-safe, ~seconds)
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MAX_BATCH = 64             # the middle serving bucket
N_CLIENTS = 8
REQUESTS_PER_CLIENT = 40
# CPU-scale QoS ladder: interactive requests get the tight budget, batch
# scoring the loose one. (The knob default targets accelerator latency;
# an example that must finish on a shared CPU runner picks its own.)
QOS_SPEC = "interactive:500:256;batch:5000:4096"


def fit_models():
    """A small GBDT 'generation 1' and a refreshed 'generation 2'."""
    from sklearn.datasets import make_classification

    from mpitree_tpu import GradientBoostingClassifier

    X, y = make_classification(
        n_samples=2000, n_features=12, n_informative=8, n_classes=3,
        random_state=0,
    )
    X = X.astype(np.float32)
    gen1 = GradientBoostingClassifier(
        max_iter=12, max_depth=3, random_state=0
    ).fit(X, y)
    gen2 = GradientBoostingClassifier(
        max_iter=16, max_depth=3, random_state=1
    ).fit(X, y)
    return X, gen1, gen2


async def start_metrics_exporter(metrics_text, host="127.0.0.1", port=0):
    """Minimal asyncio Prometheus scrape endpoint (ISSUE 9 metrics half).

    Serves ``metrics_text()`` — the scheduler's merged exposition:
    shed/queue-depth/class-latency series next to every model's request
    counters and log-bucketed latency histograms — as a plain-text HTTP
    response on every connection. Zero dependencies; ``port=0`` picks a
    free port (returned via ``server.sockets``). A production front-end
    would point its Prometheus scrape job here.
    """

    async def handle(reader, writer):
        try:
            # Drain the request head through the blank line: closing a
            # socket with unread received bytes can RST and discard the
            # queued response before the scraper reads it.
            while (await reader.readline()) not in (b"\r\n", b"\n", b""):
                pass
            body = metrics_text().encode()
            writer.write(
                b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: text/plain; version=0.0.4\r\n"
                b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                b"Connection: close\r\n\r\n" + body
            )
            await writer.drain()
        finally:
            writer.close()

    return await asyncio.start_server(handle, host, port)


async def scrape_once(host: str, port: int) -> str:
    """One GET against the exporter (the demo's self-scrape)."""
    reader, writer = await asyncio.open_connection(host, port)
    writer.write(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
    await writer.drain()
    raw = await reader.read()
    writer.close()
    return raw.decode().split("\r\n\r\n", 1)[1]


async def main():
    from mpitree_tpu.obs import REGISTRY
    from mpitree_tpu.serving import (
        ModelRegistry,
        RejectedRequest,
        Scheduler,
    )

    X, gen1, gen2 = fit_models()
    registry = ModelRegistry(buckets=(1, MAX_BATCH, 4096))
    print("publishing generation 1 (compiles + bucket warmup)...")
    model1 = registry.publish("clicks", gen1)
    sched = Scheduler(registry, qos=QOS_SPEC)
    exporter = await start_metrics_exporter(sched.metrics_text)
    ex_port = exporter.sockets[0].getsockname()[1]
    print(f"metrics exporter on 127.0.0.1:{ex_port}/metrics")

    latencies: list[float] = []
    shed = 0

    async def client(cid: int):
        nonlocal shed
        rng = np.random.default_rng(cid)
        qos = "batch" if cid % 4 == 0 else "interactive"
        for _ in range(REQUESTS_PER_CLIENT):
            row = X[int(rng.integers(0, len(X)))]
            t0 = time.perf_counter()
            try:
                fut = sched.submit("clicks", row, qos=qos)
            except RejectedRequest:
                # Typed shed: a real client would back off / fail over.
                shed += 1
                continue
            await asyncio.wrap_future(fut)
            latencies.append(time.perf_counter() - t0)
            await asyncio.sleep(float(rng.uniform(0, 0.004)))

    async def trainer():
        # Mid-traffic model swap: publish() warms every bucket BEFORE the
        # slot flips, so the request path never sees a compile. Off the
        # event loop (executor) — publishing compiles for seconds, and a
        # stalled loop would freeze every in-flight request's future.
        await asyncio.sleep(0.15)
        before = REGISTRY.count("serving_traverse")
        await asyncio.get_running_loop().run_in_executor(
            None, registry.publish, "clicks", gen2
        )
        print(
            f"swapped to generation 2 under load "
            f"(+{REGISTRY.count('serving_traverse') - before} lowerings, "
            "all during publish warmup)"
        )

    t0 = time.perf_counter()
    await asyncio.gather(*(client(i) for i in range(N_CLIENTS)), trainer())
    wall = time.perf_counter() - t0

    lat_ms = np.sort(np.asarray(latencies)) * 1e3
    n = len(lat_ms)
    st = sched.stats()
    print(
        f"\n{n} requests in {wall:.2f}s "
        f"({n / wall:.0f} req/s) | "
        f"p50 {lat_ms[n // 2]:.2f}ms  p99 {lat_ms[int(n * 0.99)]:.2f}ms | "
        f"{st['dispatches']} dispatches, {shed} shed, "
        f"{st['deadline_misses']} deadline misses"
    )
    print("per-class latency:", st["class_latency_ms"])
    print("registry:", registry.models())

    # Scrape the exporter once: the Prometheus view of the same traffic —
    # scheduler series merged with per-model request counters.
    text = await scrape_once("127.0.0.1", ex_port)
    served = [
        ln for ln in text.splitlines()
        if ln.startswith(("mpitree_serving_requests_total",
                          "mpitree_serving_request_seconds_count",
                          "mpitree_sched_dispatches_total",
                          "mpitree_sched_shed_total",
                          "mpitree_registry_publish_total"))
    ]
    print("scraped metrics:")
    for ln in served:
        print("  " + ln)
    # Per-generation latency quantiles (log-bucketed histograms; warmup
    # compiles are excluded by design): gen1 carried the pre-swap bulk.
    for gen, m in (("gen1", model1), ("gen2", registry.get("clicks"))):
        for bucket, row in m.latency_summary()["buckets"].items():
            print(
                f"{gen} bucket {bucket}: p50 {row['p50_ms']}ms "
                f"p99 {row['p99_ms']}ms ({row['count']} requests)"
            )
    sched.close()
    exporter.close()
    await exporter.wait_closed()


if __name__ == "__main__":
    asyncio.run(main())
