"""obs.memory smoke: plan -> fit -> ledger + live watermarks -> refusal.

The CI gate for the memory-observability contract (ISSUE 12, wired as
``make mem-smoke``), mirroring ``obs_trace_run``'s role for the timeline
schema. Four checks, each exiting nonzero on failure:

1. **preflight planning** — ``plan_fit`` on the covtype-like bench shape
   prices a per-device peak and names its binding array, with nothing
   but shapes (no device touched);
2. **the ledger rides the fit** — a real (CPU) fit's ``fit_report_``
   carries ``record.memory`` with per-phase watermarks and the same
   schema ``tests/test_obs_memory.py`` pins;
3. **live watermarks** — with ``MPITREE_TPU_MEM_SAMPLE=1`` the observer
   samples span-boundary memory and the ledger-vs-live delta stays
   inside the documented bracket (estimate >= live resident within
   25%, and under ``DRIFT_TOL`` x on the memory_stats source);
4. **planner refusal** — an absurd budget (``MPITREE_TPU_HBM_BYTES``)
   refuses BEFORE any device dispatch with a typed ``oom_predicted``
   event naming the binding array.

Run:  python examples/obs_memory_run.py  (CPU-safe, ~seconds)
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MPITREE_TPU_MEM_SAMPLE"] = "1"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

FAILURES: list[str] = []


def check(ok: bool, what: str) -> None:
    tag = "ok" if ok else "FAIL"
    print(f"[{tag}] {what}")
    if not ok:
        FAILURES.append(what)


def main() -> int:
    from mpitree_tpu import DecisionTreeClassifier
    from mpitree_tpu.obs import memory

    # -- 1. preflight planning on the bench headline shape ---------------
    plan = memory.plan_fit(
        rows=531_000, features=54, classes=7, bins=256, max_depth=20,
        mesh_axes={"data": 8},
    )
    binding = plan.binding_array()
    print(
        f"covtype-like plan: peak {plan.hbm_peak_bytes >> 20} MiB/device "
        f"in phase {plan.peak_phase!r}, binding array {binding['name']!r} "
        f"({binding['bytes_per_device'] >> 20} MiB); host "
        f"{plan.host_peak_bytes >> 20} MiB"
    )
    check(plan.hbm_peak_bytes > 0, "plan_fit predicts a positive peak")
    check(
        binding["name"] == "split_hist_chunk",
        "the depth-20 peak is the split chunk working set",
    )

    # -- 2 + 3. a real fit carries the ledger and live watermarks --------
    rng = np.random.default_rng(0)
    X = rng.normal(size=(20_000, 12)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64) + (X[:, 1] > 0.5)
    # refine_depth=None: one engine end to end, so the recorded plan
    # covers every allocation the live sampler sees (the hybrid tail
    # would re-plan for its crown only).
    clf = DecisionTreeClassifier(
        max_depth=6, backend="cpu", max_bins=64, refine_depth=None
    )
    clf.fit(X, y)
    mem = clf.fit_report_.get("memory") or {}
    check(bool(mem.get("arrays")), "fit_report_ carries the memory ledger")
    check(
        mem.get("hbm_peak_bytes", 0) > 0 and mem.get("phases"),
        "ledger has per-phase watermarks and a peak",
    )
    live = mem.get("live") or {}
    check(
        live.get("samples", 0) > 0 and live.get("source") != "none",
        "live watermark sampling ran at span boundaries",
    )
    est = mem.get("hbm_peak_bytes", 0)
    delta = live.get("hbm_peak_delta_bytes", 0)
    print(
        f"fit ledger: est {est} B vs live delta {delta} B "
        f"(source {live.get('source')}, {live.get('samples')} samples; "
        f"host peak {live.get('host_peak_bytes', 0) >> 20} MiB)"
    )
    # The documented bracket (see README): the analytical peak must not
    # UNDERestimate live resident bytes by more than 25% — transients the
    # sampler cannot see make overestimates expected and benign.
    check(delta > 0, "live sampling observed this fit's allocations")
    check(est >= delta * 0.8, "ledger does not underestimate live resident")
    drift_events = [
        e for e in clf.fit_report_.get("events", [])
        if e.get("kind") == "mem_estimate_drift"
    ]
    check(not drift_events, "no drift event on the healthy CPU fit")

    # -- 4. planner refusal fires before dispatch ------------------------
    os.environ[memory.HBM_BUDGET_ENV] = str(1 << 16)  # 64 KiB: absurd
    try:
        try:
            DecisionTreeClassifier(
                max_depth=6, backend="cpu", max_bins=64,
                refine_depth=None,
            ).fit(X, y)
        except memory.MemoryPlanError as e:
            print(f"refusal: {e}")
            check(
                bool(e.binding_array),
                f"oom_predicted names the binding array "
                f"({e.binding_array!r})",
            )
        else:
            check(False, "absurd budget must raise MemoryPlanError")
    finally:
        del os.environ[memory.HBM_BUDGET_ENV]

    if FAILURES:
        print(f"\n{len(FAILURES)} memory-smoke failures")
        return 1
    print("\nmemory smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
