"""Resilience v2 smoke: one fit surviving a level-kill, one surviving a
clearing OOM — exit-code-validated (ISSUE 14, wired as
``make chaos-smoke``).

The CI gate for the fine-grained recovery rungs, mirroring
``obs_flight_run``'s role for the flight-recorder contract. Checks,
each exiting nonzero on failure:

1. **level-kill survival** — a chaos-injected transient UNAVAILABLE at
   level 2 of a level-wise fit recovers via the SUB-BUILD rung: one
   typed ``level_retry`` (granularity="level", resume_at=2), exactly
   one extra per-level dispatch (levels >= 2 re-ran, levels < 2 did
   not), zero host failovers, and the recovered tree's whole-fit
   fingerprint equals the uninterrupted twin's;
2. **clearing-OOM survival** — a chaos-injected RESOURCE_EXHAUSTED that
   clears after one shrink is rescued ON DEVICE: one typed
   ``oom_rescue`` naming the binding array (``split_hist_chunk``) and
   the halved ``max_frontier_chunk``, the re-dispatch re-prices the
   shrunk plan (the recorded ledger carries the halved chunk), zero
   ``device_failover`` events, and the tree is still bit-identical
   (chunk width is batching, not arithmetic).

Run:  python examples/resilience_run.py  (CPU-safe, ~seconds)
"""

from __future__ import annotations

import os
import sys
import warnings

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Deterministic, fast recovery: no backoff sleeps, levelwise engine (the
# snapshot-granular loop the smoke exercises).
os.environ["MPITREE_TPU_BACKOFF_S"] = "0"
os.environ["MPITREE_TPU_ENGINE"] = "levelwise"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

FAILURES: list[str] = []


def check(ok: bool, what: str) -> None:
    tag = "ok" if ok else "FAIL"
    print(f"[{tag}] {what}")
    if not ok:
        FAILURES.append(what)


def main() -> int:
    from mpitree_tpu import DecisionTreeClassifier
    from mpitree_tpu.resilience import chaos
    from mpitree_tpu.resilience.chaos import Fault

    rng = np.random.default_rng(0)
    X = rng.normal(size=(600, 6)).astype(np.float32)
    y = rng.integers(0, 4, size=600)  # noise target -> full-depth tree
    kw = dict(max_depth=5, refine_depth=None, backend="cpu")

    healthy = DecisionTreeClassifier(**kw).fit(X, y)
    h_rep = healthy.fit_report_
    levels = h_rep["counters"]["level_dispatches"]
    h_fp = h_rep["fingerprints"]["fit"]
    print(f"-- healthy fit: {levels} level dispatches, fp={h_fp}")

    # -- 1. transient kill at level 2: sub-build retry -----------------
    chaos.install([Fault("level", 1, "unavailable", at_level=2)])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        survived = DecisionTreeClassifier(**kw).fit(X, y)
    chaos.clear()
    rep = survived.fit_report_
    check(rep["counters"].get("level_retries") == 1,
          "level-kill: one sub-build retry")
    check(rep["counters"]["level_dispatches"] == levels + 1,
          "level-kill: only levels >= 2 re-dispatched")
    check("device_failovers" not in rep["counters"],
          "level-kill: no host failover")
    evs = [e for e in rep["events"] if e["kind"] == "level_retry"]
    check(bool(evs) and evs[0]["granularity"] == "level"
          and evs[0]["resume_at"] == 2,
          "level-kill: typed level_retry event (granularity + position)")
    check(rep["fingerprints"]["fit"] == h_fp,
          "level-kill: recovered fingerprint fold equals uninterrupted")
    check(survived.export_text() == healthy.export_text(),
          "level-kill: recovered tree bit-identical")

    # -- 2. clearing OOM: on-device rescue ladder ----------------------
    chunk0 = h_rep["memory"]["inputs"]["chunk_slots"]
    chaos.install([Fault("level", 1, "oom", at_level=1, clears_after=1)])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rescued = DecisionTreeClassifier(**kw).fit(X, y)
    chaos.clear()
    rep = rescued.fit_report_
    check(rep["counters"].get("oom_rescues") == 1,
          "oom: one rescue rung")
    check("device_failover" not in [e["kind"] for e in rep["events"]],
          "oom: the fit stayed on device (zero failover events)")
    evs = [e for e in rep["events"] if e["kind"] == "oom_rescue"]
    check(bool(evs) and evs[0]["knob"] == "max_frontier_chunk"
          and evs[0]["binding_array"] == "split_hist_chunk"
          and evs[0]["old_bytes"] > evs[0]["new_bytes"],
          "oom: typed oom_rescue names knob, binding array, bytes")
    check(rep["memory"]["inputs"]["chunk_slots"] == chunk0 // 2,
          "oom: preflight re-priced the shrunk plan (chunk halved)")
    check(rescued.export_text() == healthy.export_text()
          and rep["fingerprints"]["fit"] == h_fp,
          "oom: rescued tree bit-identical")

    if FAILURES:
        print(f"\n{len(FAILURES)} check(s) FAILED:")
        for f in FAILURES:
            print(f"  - {f}")
        return 1
    print("\nall resilience-v2 checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
