"""Serving v2 smoke — scheduler + quantized tables, exit-code-validated.

The ``make serve-smoke`` CI rung (ISSUE 17): publish a QUANTIZED model
through the registry, drive a mixed-QoS burst through the
continuous-batching scheduler, and ASSERT the contract rather than
print-and-hope —

- the quantize exactness report accepted the tables (and its numbers
  land in ``serve_report_``);
- scheduled results match the model's direct ``raw`` outputs;
- an overload burst SHEDS with typed reasons while every admitted
  request still resolves (shed-don't-starve);
- both QoS classes flow after the burst;
- a chaos blip (transient UNAVAILABLE on the ``sched_dispatch`` seam)
  is requeued once and recovered;
- the merged Prometheus exposition carries the scheduler families next
  to the per-model serving series, one ``# TYPE`` line per family.

Any broken assertion exits non-zero — CI-friendly. CPU-safe, ~seconds.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-scale QoS ladder (the knob default targets accelerator latency).
QOS_SPEC = "interactive:500:256;batch:5000:4096"
BURST = 600


def main() -> int:
    from sklearn.datasets import make_classification

    from mpitree_tpu.models.forest import RandomForestClassifier
    from mpitree_tpu.resilience import chaos
    from mpitree_tpu.resilience.chaos import Fault
    from mpitree_tpu.serving import (
        ModelRegistry,
        RejectedRequest,
        Scheduler,
    )

    X, y = make_classification(
        n_samples=400, n_features=12, n_informative=8, random_state=0
    )
    X = X.astype(np.float32)
    rf = RandomForestClassifier(
        n_estimators=4, max_depth=4, random_state=0
    ).fit(X, y)

    registry = ModelRegistry()
    print("publishing quantized model (int8 tables, exactness-gated)...")
    model = registry.publish("clicks", rf, quantize="int8")
    qrep = model.serve_report_["quantization"]
    assert qrep["mode"] == "int8" and qrep["ok"], qrep
    print(
        f"  accepted: max calibration delta {qrep['max_abs_delta']:.2e} "
        f"<= tol {qrep['tolerance']:.0e}, "
        f"{qrep['rerouted_rows']} rerouted rows"
    )
    direct = np.asarray(model.raw(X[:16]))

    with Scheduler(registry, qos=QOS_SPEC) as sched:
        # Scheduled results == direct dispatch results.
        futs = [sched.submit("clicks", X[i]) for i in range(16)]
        got = np.stack([f.result(timeout=30) for f in futs])
        assert np.allclose(got, direct, atol=1e-6), (
            np.abs(got - direct).max()
        )
        print("scheduled results match direct raw dispatch")

        # Overload burst under a hang fault: admission sheds with typed
        # reasons, every ADMITTED request still resolves.
        shed = 0
        with chaos.active(
            Fault("sched_dispatch", at=1, kind="hang", arg=0.3)
        ):
            futs = []
            for i in range(BURST):
                try:
                    futs.append(
                        sched.submit(
                            "clicks", X[i % len(X)], qos="interactive"
                        )
                    )
                except RejectedRequest as e:
                    assert e.reason in (
                        "queue_full", "deadline_infeasible"
                    ), e.reason
                    shed += 1
            for f in futs:
                assert np.asarray(f.result(timeout=30)).shape == (2,)
        assert shed > 0 and futs, (shed, len(futs))
        print(
            f"burst: {len(futs)} admitted+served, {shed} shed "
            "(typed, no starvation)"
        )

        # Both QoS classes flow after the burst (the feasibility EWMA
        # recovers — no permanent lockout from one slow window).
        for qos in ("interactive", "batch"):
            fs = [sched.submit("clicks", X[i], qos=qos) for i in range(8)]
            for f in fs:
                f.result(timeout=30)
        print("both QoS classes served after the burst")

        # Chaos blip: transient UNAVAILABLE on dispatch -> requeued
        # once, request still answered.
        with chaos.active(Fault("sched_dispatch", at=1, kind="unavailable")):
            out = np.asarray(
                sched.submit("clicks", X[0]).result(timeout=30)
            )
            assert out.shape == (2,)
        st = sched.stats()
        assert st["requeues"] >= 1, st
        print(f"chaos blip recovered via requeue (requeues={st['requeues']})")

        text = sched.metrics_text()
        for needle in (
            "mpitree_sched_shed_total",
            "mpitree_sched_queue_depth",
            "mpitree_sched_class_latency_seconds",
            "mpitree_sched_dispatches_total",
            "mpitree_serving_request_seconds",
        ):
            assert needle in text, needle
        assert text.count("# TYPE mpitree_sched_shed_total") == 1
        assert st["shed"].get("queue_full", 0) \
            + st["shed"].get("deadline_infeasible", 0) == shed, \
            (st["shed"], shed)
        print(
            "metrics: dispatches="
            f"{st['dispatches']} requeues={st['requeues']} "
            f"deadline_misses={st['deadline_misses']} shed={st['shed']}"
        )

    # Closed scheduler refuses with the shutdown reason.
    try:
        sched.submit("clicks", X[0])
        raise AssertionError("expected shutdown reject")
    except RejectedRequest as e:
        assert e.reason == "shutdown", e.reason
    print("closed scheduler sheds with reason='shutdown'")
    print("serve-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
