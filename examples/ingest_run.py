"""Out-of-core ingest smoke: sketch-merge -> chunked bin -> bounded-RSS
fit -> identity check (ISSUE 15, wired as ``make ingest-smoke``).

Five exit-code-validated checks on an 8-device CPU mesh:

1. **sketch merge** — chunked sketches produce thresholds bit-identical
   to ``bin_dataset``'s on the same rows, across chunk sizes and modes;
2. **chunked bin** — per-chunk ``bin_with_thresholds`` ids equal the
   in-memory ``x_binned``;
3. **bounded host residency** — a warm streamed fit from memory-mapped
   ``.npy`` shards keeps its numpy working set bounded by chunk + capped
   sketch (tracemalloc: python-side allocations stay under the
   full-matrix bytes) and its planner chunk size derives from the host
   budget;
4. **identity** — the streamed fit is fingerprint-identical to the
   in-memory fit of the same rows on (8,) and (4, 2) meshes;
5. **planner pricing** — ``plan_ingest`` rides the fit record and the
   streamed ``plan_fit`` host peak undercuts the in-memory pricing.

Run:  python examples/ingest_run.py  (CPU-safe, ~a minute)
"""

from __future__ import annotations

import os
import sys
import tempfile
import tracemalloc

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MPITREE_TPU_MEM_SAMPLE"] = "1"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

FAILURES: list[str] = []


def check(ok: bool, what: str) -> None:
    tag = "ok" if ok else "FAIL"
    print(f"[{tag}] {what}")
    if not ok:
        FAILURES.append(what)


def main() -> int:
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception:  # noqa: BLE001 — legacy wheels
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )

    from mpitree_tpu import DecisionTreeClassifier, StreamedDataset
    from mpitree_tpu.ingest import SketchSet
    from mpitree_tpu.obs import memory
    from mpitree_tpu.ops.binning import bin_dataset, bin_with_thresholds

    rng = np.random.default_rng(0)
    N, F = 48_000, 16
    X = rng.normal(size=(N, F)).astype(np.float32)
    X[:, 3] = np.round(X[:, 3], 1)   # low-cardinality feature
    X[:, 5] = 1.25                   # constant feature
    y = ((X[:, 0] + X[:, 3] > 0).astype(int)
         + (X[:, 1] > 1).astype(int))

    # -- 1 + 2: sketch merge and chunked bin are bit-identical ------------
    for mode in ("auto", "quantile"):
        ref = bin_dataset(X, max_bins=64, binning=mode)
        for rows in (N, 7777):
            sk = SketchSet(F)
            for lo in range(0, N, rows):
                sk.update(X[lo:lo + rows])
            thr, nc, nb, q = sk.to_thresholds(max_bins=64, binning=mode)
            check(
                np.array_equal(thr, ref.thresholds)
                and np.array_equal(nc, ref.n_cand)
                and nb == ref.n_bins and q == ref.quantized,
                f"sketch thresholds identical ({mode}, chunk={rows})",
            )
            xb = np.concatenate([
                bin_with_thresholds(X[lo:lo + rows], thr, nc)
                for lo in range(0, N, rows)
            ])
            check(
                np.array_equal(xb, ref.x_binned),
                f"chunked bin ids identical ({mode}, chunk={rows})",
            )

    # -- 3: bounded-RSS fit from memory-mapped shards ---------------------
    budget = 1 << 20  # 1 MiB host budget -> planner-derived small chunks
    os.environ[memory.HOST_BUDGET_ENV] = str(budget)
    try:
        chunk_rows = memory.ingest_chunk_rows(F)
        check(
            chunk_rows * memory.ingest_row_bytes(F) <= budget,
            f"chunk size derives from the host budget ({chunk_rows} rows "
            f"under {budget >> 20} MiB)",
        )
        with tempfile.TemporaryDirectory() as td:
            shards = []
            for i, lo in enumerate(range(0, N, N // 3 + 1)):
                xp = os.path.join(td, f"x_{i}.npy")
                yp = os.path.join(td, f"y_{i}.npy")
                np.save(xp, X[lo:lo + N // 3 + 1])
                np.save(yp, y[lo:lo + N // 3 + 1])
                shards.append((xp, yp))
            # A capped sketch bounds the per-feature summaries (the
            # documented approximate fallback for high-cardinality
            # streams); exact-sketch bit-identity is check 4's job.
            ds = StreamedDataset.from_npy(
                [s[0] for s in shards], [s[1] for s in shards],
                sketch_capacity=1024,
            )
            # Warm pass: XLA compilation allocates through the python
            # allocator and would dominate the measurement; the bound
            # under test is the steady-state ingest working set.
            clf = DecisionTreeClassifier(
                max_depth=8, max_bins=64, backend="cpu", n_devices=8,
            ).fit(dataset=ds)
            tracemalloc.start()
            clf = DecisionTreeClassifier(
                max_depth=8, max_bins=64, backend="cpu", n_devices=8,
            ).fit(dataset=ds)
            _, py_peak = tracemalloc.get_traced_memory()
            tracemalloc.stop()
        full_matrix = N * F * 8  # raw f32 + binned i32, never held whole
        plan_bound = memory.plan_ingest(
            rows=N, features=F, chunk_rows=chunk_rows,
            sketch_capacity=1024, mesh_axes={"data": 8},
        ).host_peak_bytes
        print(f"python-side peak {py_peak >> 10} KiB vs planner bound "
              f"{plan_bound >> 10} KiB vs full-matrix "
              f"{full_matrix >> 10} KiB (chunk_rows={chunk_rows})")
        check(
            py_peak < full_matrix,
            "warm streamed fit's numpy working set stays under the "
            "full-matrix bytes (chunk+sketch-bounded, not matrix-bounded)",
        )
        check(
            py_peak < 2 * plan_bound,
            "measured peak within 2x the planner-derived chunk bound "
            "(plan_ingest host_peak_bytes prices the real working set)",
        )
        check(
            clf.ingest_stats_["chunk_rows"] == chunk_rows,
            "fit streamed at the planner-derived chunk size",
        )
        live = (clf.fit_report_.get("memory") or {}).get("live") or {}
        check(
            int(live.get("host_peak_bytes") or 0) > 0,
            "live host watermark sampled under MPITREE_TPU_MEM_SAMPLE=1",
        )
    finally:
        del os.environ[memory.HOST_BUDGET_ENV]

    # -- 4: streamed == in-memory, across mesh shapes ---------------------
    ref_fit = DecisionTreeClassifier(
        max_depth=8, max_bins=64, backend="cpu", n_devices=8,
        refine_depth=None,
    ).fit(X, y)
    fp_ref = ref_fit.fit_report_["fingerprints"]["fit"]
    for mesh_shape in (8, (4, 2)):
        ds = StreamedDataset.from_arrays(X, y, chunk_rows=997)
        s = DecisionTreeClassifier(
            max_depth=8, max_bins=64, backend="cpu", n_devices=mesh_shape,
        ).fit(ds)
        check(
            s.fit_report_["fingerprints"]["fit"] == fp_ref,
            f"streamed fit fingerprint-identical on mesh {mesh_shape!r}",
        )
        check(
            bool((s.predict(X) == ref_fit.predict(X)).all()),
            f"streamed predictions identical on mesh {mesh_shape!r}",
        )

    # -- 5: planner pricing ----------------------------------------------
    plans = [
        p for p in [clf.fit_report_.get("memory") or {}]
        if p.get("kind") in ("fit", "fit_aggregate")
    ]
    check(bool(plans), "the streamed fit record carries a memory plan")
    streamed_host = memory.plan_fit(
        rows=N, features=F, bins=64, max_depth=8, streamed=True,
        streamed_chunk_rows=997,
    ).host_peak_bytes
    inmem_host = memory.plan_fit(
        rows=N, features=F, bins=64, max_depth=8,
    ).host_peak_bytes
    check(
        streamed_host < inmem_host,
        f"streamed plan_fit host peak ({streamed_host >> 10} KiB) "
        f"undercuts in-memory pricing ({inmem_host >> 10} KiB)",
    )

    if FAILURES:
        print(f"\n{len(FAILURES)} ingest-smoke failures")
        return 1
    print("\ningest smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
