"""Streamed-ensemble smoke: out-of-core boosting + forests (ISSUE 20,
wired as ``make stream-smoke``).

Exit-code-validated checks on an 8-device CPU mesh:

1. **streamed boosting identity** — a GBDT fit from a chunk stream is
   tree- and fingerprint-identical to the in-memory fit, through both
   the per-round host loop (K=1) and the fused multi-round scan (K=3);
2. **bounded working set** — the warm streamed boosting fit's
   python-side allocations stay under the full-matrix bytes and within
   a small multiple of the ``obs.memory`` chunk-derived plan, while the
   in-memory twin's working set exceeds the streamed one;
3. **streamed forest identity** — a bootstrap forest fit from the
   stream equals the keyed in-memory twin
   (``MPITREE_TPU_KEYED_BOOTSTRAP=1``), masks drawn per chunk;
4. **refine tail** — a streamed single-tree fit with a hybrid refine
   tail replays the chunk stream for its candidates' raw rows and
   commits identical subtrees;
5. **spill rung** — a one-shot chunk iterator is refused with a typed
   error unless ``MPITREE_TPU_SPILL_DIR`` is set, in which case later
   passes replay from the spill store and the fit is identical.

Run:  python examples/stream_gbdt_run.py  (CPU-safe, ~a minute)
"""

from __future__ import annotations

import os
import sys
import tempfile
import tracemalloc

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

FAILURES: list[str] = []


def check(ok: bool, what: str) -> None:
    tag = "ok" if ok else "FAIL"
    print(f"[{tag}] {what}")
    if not ok:
        FAILURES.append(what)


def main() -> int:
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception:  # noqa: BLE001 — legacy wheels
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )

    from mpitree_tpu import (
        DecisionTreeClassifier,
        GradientBoostingClassifier,
        StreamedDataset,
    )
    from mpitree_tpu.models.forest import RandomForestClassifier
    from mpitree_tpu.obs import memory

    rng = np.random.default_rng(0)
    N, F = 40_000, 12
    X = rng.normal(size=(N, F)).astype(np.float32)
    X[:, 3] = np.round(X[:, 3], 1)   # low-cardinality feature
    X[:, 5] = 1.25                   # constant feature
    y = ((X[:, 0] + X[:, 3] > 0) & (X[:, 1] < 1)).astype(int)

    def fp(est):
        return est.fit_report_["fingerprints"]

    def trees_equal(a, b):
        # leaf thresholds are NaN, so the float compare must be NaN-safe
        return len(a.trees_) == len(b.trees_) and all(
            np.array_equal(ta.feature, tb.feature)
            and np.array_equal(ta.threshold, tb.threshold, equal_nan=True)
            and np.array_equal(ta.count, tb.count)
            for ta, tb in zip(a.trees_, b.trees_)
        )

    # -- 1: streamed boosting == in-memory, host loop and fused scan ------
    gb_kw = dict(max_iter=6, max_depth=4, max_bins=64, backend="cpu",
                 n_devices=8, random_state=0)
    for rpd in (1, 3):
        ref = GradientBoostingClassifier(
            rounds_per_dispatch=rpd, **gb_kw,
        ).fit(X, y)
        clf = GradientBoostingClassifier(
            rounds_per_dispatch=rpd, **gb_kw,
        ).fit(dataset=StreamedDataset.from_arrays(X, y, chunk_rows=4096))
        check(
            trees_equal(ref, clf) and fp(clf) == fp(ref),
            f"streamed GBDT == in-memory GBDT (rounds_per_dispatch={rpd})",
        )

    # -- 2: the streamed working set is chunk-bounded ---------------------
    # A capped sketch bounds the per-feature summaries (the documented
    # approximate fallback for high-cardinality streams); exact-sketch
    # identity is check 1's job, bounded residency is this one's.
    budget = 1 << 21  # 2 MiB host budget -> planner-derived small chunks
    os.environ[memory.HOST_BUDGET_ENV] = str(budget)
    try:
        chunk_rows = memory.ingest_chunk_rows(F)
        ds = StreamedDataset.from_arrays(  # planner-sized chunks
            X, y, sketch_capacity=1024,
        )
        fit_streamed = lambda: GradientBoostingClassifier(  # noqa: E731
            **gb_kw
        ).fit(dataset=ds)
        fit_streamed()  # warm: XLA compilation allocates via python
        tracemalloc.start()
        clf = fit_streamed()
        _, peak_streamed = tracemalloc.get_traced_memory()
        tracemalloc.stop()

        GradientBoostingClassifier(**gb_kw).fit(X, y)  # warm twin
        tracemalloc.start()
        GradientBoostingClassifier(**gb_kw).fit(X, y)
        _, peak_inmem = tracemalloc.get_traced_memory()
        tracemalloc.stop()

        full_matrix = N * F * 8  # raw f32 + binned i32, never held whole
        plan_bound = memory.plan_ingest(
            rows=N, features=F, chunk_rows=chunk_rows,
            sketch_capacity=1024, mesh_axes={"data": 8},
        ).host_peak_bytes
        print(f"streamed peak {peak_streamed >> 10} KiB vs in-memory peak "
              f"{peak_inmem >> 10} KiB vs planner bound "
              f"{plan_bound >> 10} KiB (chunk_rows={chunk_rows})")
        check(
            clf.ingest_stats_["chunk_rows"] == chunk_rows,
            "streamed GBDT ingests at the planner-derived chunk size",
        )
        check(
            peak_streamed < full_matrix,
            "streamed GBDT working set stays under the full-matrix bytes",
        )
        check(
            peak_streamed < peak_inmem,
            "in-memory twin's working set exceeds the streamed fit's",
        )
    finally:
        del os.environ[memory.HOST_BUDGET_ENV]

    # -- 3: streamed forest == keyed in-memory twin -----------------------
    rf_kw = dict(n_estimators=6, max_depth=5, max_bins=64, backend="cpu",
                 n_devices=8, random_state=3, refine_depth=None)
    os.environ["MPITREE_TPU_KEYED_BOOTSTRAP"] = "1"
    try:
        rf_ref = RandomForestClassifier(**rf_kw).fit(X, y)
    finally:
        del os.environ["MPITREE_TPU_KEYED_BOOTSTRAP"]
    rf = RandomForestClassifier(**rf_kw).fit(
        dataset=StreamedDataset.from_arrays(X, y, chunk_rows=4096)
    )
    check(
        trees_equal(rf_ref, rf) and fp(rf) == fp(rf_ref),
        "streamed forest == keyed in-memory forest "
        f"(bootstrap={rf.fit_report_['decisions']['bootstrap']['value']})",
    )

    # -- 4: the hybrid refine tail replays the chunk stream ---------------
    tr_kw = dict(max_depth=8, max_bins=32, backend="cpu", n_devices=8,
                 refine_depth=3)
    tr_ref = DecisionTreeClassifier(**tr_kw).fit(X, y)
    tr = DecisionTreeClassifier(**tr_kw).fit(
        StreamedDataset.from_arrays(X, y, chunk_rows=4096)
    )
    check(
        np.array_equal(tr.tree_.feature, tr_ref.tree_.feature)
        and np.array_equal(
            tr.tree_.threshold, tr_ref.tree_.threshold, equal_nan=True
        )
        and fp(tr) == fp(tr_ref),
        "streamed refine tail commits identical subtrees",
    )

    # -- 5: one-shot iterators ride the spill rung ------------------------
    def one_shot():
        for lo in range(0, N, 8192):
            yield X[lo:lo + 8192], y[lo:lo + 8192]

    try:
        DecisionTreeClassifier(
            max_depth=4, max_bins=32, backend="cpu", n_devices=8,
        ).fit(StreamedDataset.from_chunks(one_shot()))
        check(False, "one-shot iterator refused without a spill dir")
    except ValueError as e:
        check(
            "MPITREE_TPU_SPILL_DIR" in str(e),
            "one-shot iterator refusal names the spill knob",
        )
    with tempfile.TemporaryDirectory() as td:
        os.environ["MPITREE_TPU_SPILL_DIR"] = td
        try:
            sp = DecisionTreeClassifier(
                max_depth=4, max_bins=32, backend="cpu", n_devices=8,
            ).fit(StreamedDataset.from_chunks(one_shot()))
        finally:
            del os.environ["MPITREE_TPU_SPILL_DIR"]
        tw = DecisionTreeClassifier(
            max_depth=4, max_bins=32, backend="cpu", n_devices=8,
        ).fit(StreamedDataset.from_arrays(X, y, chunk_rows=8192))
        check(
            sp.fit_report_["decisions"]["ingest_spill"]["value"] == "spill"
            and sp.ingest_stats_["spill_bytes"] > 0
            and fp(sp) == fp(tw),
            "one-shot fit spilled to disk and matches the re-iterable fit",
        )

    print()
    if FAILURES:
        print(f"{len(FAILURES)} check(s) FAILED:")
        for f in FAILURES:
            print(f"  - {f}")
        return 1
    print("all streamed-ensemble checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
