"""obs.flight smoke: two fits -> store -> diff -> clean verdict ->
injected-regression refusal.

The CI gate for the flight-recorder contract (ISSUE 13, wired as
``make flight-smoke``), mirroring ``obs_memory_run``'s role for the
memory schema. Checks, each exiting nonzero on failure:

1. **ambient store** — with ``MPITREE_TPU_RUN_DIR`` set, two identical
   fits append two ``kind="fit"`` envelopes stamped with platform /
   mesh axes / config digest, and both land in ONE lineage;
2. **clean twin diffs green** — ``obs.diff`` on the two envelopes:
   identical configs on identical data carry IDENTICAL whole-fit
   fingerprints (the bit-identity pin, now observable) and no
   regression verdicts;
3. **injected perf regression refuses** — a doctored candidate whose
   wall is 3x the lineage baseline yields ``verdict="regression"`` and
   a nonzero sentinel exit code;
4. **injected divergence localizes** — a fit whose gradient payload is
   finitely skewed (the ``grad_hess`` chaos seam, kind="skew") builds a
   DIFFERENT tree: the diff says ``diverged`` and the fingerprint
   bisect names the first divergent (tree, level, channel).

Run:  python examples/obs_flight_run.py  (CPU-safe, ~seconds)
"""

from __future__ import annotations

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

FAILURES: list[str] = []


def check(ok: bool, what: str) -> None:
    tag = "ok" if ok else "FAIL"
    print(f"[{tag}] {what}")
    if not ok:
        FAILURES.append(what)


def main() -> int:
    with tempfile.TemporaryDirectory() as run_dir:
        os.environ["MPITREE_TPU_RUN_DIR"] = run_dir
        try:
            return run_checks(run_dir)
        finally:
            del os.environ["MPITREE_TPU_RUN_DIR"]


def run_checks(run_dir: str) -> int:
    from mpitree_tpu import GradientBoostingClassifier
    from mpitree_tpu.obs import diff as obs_diff
    from mpitree_tpu.obs import flight
    from mpitree_tpu.resilience import chaos

    rng = np.random.default_rng(0)
    X = rng.normal(size=(3000, 8)).astype(np.float32)
    y = ((X[:, 0] + 0.5 * X[:, 1] > 0)).astype(np.int32)

    def fit():
        return GradientBoostingClassifier(
            max_iter=3, max_depth=3, max_bins=32, backend="cpu",
        ).fit(X, y)

    # -- 1. ambient store: two identical fits, one lineage ---------------
    fit()
    fit()
    store = flight.FlightStore(run_dir)
    fits = store.entries(kind="fit")
    check(len(fits) == 2, f"two fit envelopes stored ({len(fits)})")
    a, b = fits[0], fits[1]
    check(
        a["config_digest"] == b["config_digest"]
        and a["platform"] == b["platform"],
        "identical configs share one lineage "
        f"(config_digest {b['config_digest']})",
    )
    check(
        store.baseline_for(b) is not None,
        "the second run resolves the first as its lineage baseline",
    )

    # -- 2. clean twin diffs green ---------------------------------------
    d = obs_diff.diff_envelopes(a, b, history=[a])
    print(obs_diff.format_diff(d))
    check(
        (d["fingerprint"]["match"] is True),
        "identical fits carry identical whole-fit fingerprints",
    )
    check(
        d["verdict"] in ("ok", "improved"),
        f"clean twin verdict is green ({d['verdict']})",
    )
    check(obs_diff.exit_code(d) == 0, "clean sentinel exit code is 0")

    # -- 3. injected perf regression refuses -----------------------------
    import copy

    slow = copy.deepcopy(b)
    slow["digest"]["wall_s"] = round(
        (b["digest"].get("wall_s") or 0.1) * 3.0 + 1.0, 3
    )
    d_slow = obs_diff.diff_envelopes(a, slow, history=[a, b])
    check(
        d_slow["verdict"] == "regression"
        and "wall_s" in d_slow["regressions"],
        f"3x wall injects a named regression ({d_slow['regressions']})",
    )
    check(obs_diff.exit_code(d_slow) == 1, "regression exit code is 1")
    print("regression: " + obs_diff.summary_line(d_slow, label="slow-twin"))

    # -- 4. injected divergence localizes --------------------------------
    with chaos.active(chaos.Fault("grad_hess", 2, "skew", 4.0)):
        fit()
    fits = store.entries(kind="fit")
    check(len(fits) == 3, "the corrupted twin stored a third envelope")
    corrupt = fits[-1]
    d_div = obs_diff.diff_envelopes(b, corrupt, history=[a, b])
    dv = d_div["fingerprint"]["divergence"]
    check(d_div["verdict"] == "diverged", "corrupted twin diverges")
    check(
        dv is not None and dv.get("tree") is not None
        and dv.get("channel") in ("hist", "winner", "alloc"),
        f"bisect names the first divergent point ({dv})",
    )
    if dv:
        print(
            f"divergence localized: round {dv['tree']}, level "
            f"{dv['level']}, channel {dv['channel']} (all: "
            f"{dv.get('channels')})"
        )
    check(obs_diff.exit_code(d_div) == 1, "divergence exit code is 1")

    if FAILURES:
        print(f"\n{len(FAILURES)} flight-smoke failures")
        return 1
    print("\nflight smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
