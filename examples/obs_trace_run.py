"""One Perfetto-loadable timeline covering fit AND serve (ISSUE 9).

Runs the telemetry layer end to end into a single Chrome-trace JSON:

1. a **level-wise** device build (live per-level split/counts/update
   spans + the synthesized per-level replay track),
2. a **fused-engine** build (one ``lax.while_loop`` dispatch — its
   per-level spans are synthesized post-hoc from ``obs/accounting``'s
   exact realized-work rows, laid inside the live ``fused_build`` span),
3. a **gradient-boosting** fit (per-round replay spans + compile
   attribution for every entry point that lowered),
4. a **serving dispatch** through a :class:`CompiledModel` with one
   chaos-injected transient blip, so the **resilience retry rung** lands
   as a ``device_retry`` instant on the serving events track,

then validates the file against the golden trace-event schema
(``mpitree_tpu.obs.trace.validate_trace``) and prints the serving
latency quantiles from the log-bucketed metrics histograms.

Run:   python examples/obs_trace_run.py [--out PATH] [--smoke]
Load:  https://ui.perfetto.dev  (or chrome://tracing) -> open the JSON.

``--smoke`` shrinks the workload to seconds — ``make trace-smoke`` runs
exactly that as the CI-side tiny-fit -> trace -> schema-validation gate.
Exit status is non-zero if validation fails or any required span family
is missing, so the Makefile target IS the acceptance check.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="/tmp/mpitree_fit_serve.trace.json")
    p.add_argument("--smoke", action="store_true",
                   help="tiny workload (the make trace-smoke gate)")
    args = p.parse_args()

    # Keep the injected-blip retry fast; never disable the ladder itself.
    os.environ.setdefault("MPITREE_TPU_BACKOFF_S", "0.01")

    import numpy as np

    from mpitree_tpu import (
        DecisionTreeClassifier,
        GradientBoostingClassifier,
    )
    from mpitree_tpu.obs.trace import TraceSink, validate_trace
    from mpitree_tpu.resilience import chaos
    from mpitree_tpu.serving.model import compile_model

    n = 600 if args.smoke else 4000
    rng = np.random.default_rng(7)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    y = ((X[:, 0] + 0.5 * X[:, 1] > 0) + (X[:, 2] > 0.8)).astype(np.int64)

    sink = TraceSink(args.out)

    # 1) level-wise build: live per-level split/counts/update spans.
    os.environ["MPITREE_TPU_ENGINE"] = "levelwise"
    DecisionTreeClassifier(max_depth=4, backend="cpu").fit(
        X, y, trace_to=sink
    )

    # 2) fused engine: ONE compiled dispatch; its level spans are
    # synthesized post-hoc from the realized-work replay rows.
    os.environ["MPITREE_TPU_ENGINE"] = "fused"
    DecisionTreeClassifier(max_depth=4, backend="cpu").fit(
        X, y, trace_to=sink
    )
    del os.environ["MPITREE_TPU_ENGINE"]

    # 3) boosting rounds (per-round replay spans + compile attribution).
    gb = GradientBoostingClassifier(
        max_iter=2 if args.smoke else 5, max_depth=3, random_state=0
    ).fit(X, y, trace_to=sink)

    # 4) serving: warm dispatch, then one with an injected transient blip
    # — the retry rung recovers and its device_retry instant hits the
    # timeline with a real timestamp.
    model = compile_model(gb)
    model.trace_to(sink)
    model.predict(X[:64])
    with chaos.active(
        chaos.Fault("serving_dispatch", at=1, kind="unavailable")
    ):
        model.predict(X[:64])
    report = model.serve_report_

    path = sink.write()
    with open(path) as f:
        trace = json.load(f)
    problems = validate_trace(trace)
    names = {e["name"] for e in trace["traceEvents"]}
    required = {
        "level-wise build span": "split" in names,
        "fused-engine replay span": any(
            n_.startswith("level ") for n_ in names
        ) and "fused_build" in names,
        "boosting round span": any(n_.startswith("round ") for n_ in names),
        "resilience retry rung": "device_retry" in names,
        "serving dispatch span": "serving_dispatch" in names,
        "compile attribution span": any(
            n_.startswith("compile:") for n_ in names
        ),
    }

    print(f"trace: {path} ({len(trace['traceEvents'])} events)")
    for what, ok in required.items():
        print(f"  [{'ok' if ok else 'MISSING'}] {what}")
    if problems:
        print(f"  schema problems: {problems[:5]}")
    lat = report["latency"]
    for bucket, row in lat["buckets"].items():
        print(
            f"serving bucket {bucket}: p50 {row['p50_ms']}ms "
            f"p99 {row['p99_ms']}ms over {row['count']} requests"
        )
    print(
        "retries recovered on the device tier:",
        report["counters"].get("device_retries", 0),
    )
    print("load it in https://ui.perfetto.dev")
    return 0 if not problems and all(required.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
