"""Primitive micro-benchmarks that justify the histogram-kernel design.

Measures, on the current default JAX platform:

1. scatter-add (segment_sum) throughput at covtype-level sizes — the op the
   v0 builder leans on;
2. row-gather bandwidth (permutation reorder of the binned matrix / one-hot);
3. int8 tile matmul throughput (the A @ OH segment-histogram formulation);
4. sort / cumsum costs for the per-level row reordering.

Run: ``python examples/microbench.py [--n 531012] [--features 54] [--bins 256]``
Prints one JSON line per measurement.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timed(fn, *args, reps=3):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=531012)
    p.add_argument("--features", type=int, default=54)
    p.add_argument("--bins", type=int, default=256)
    p.add_argument("--slots", type=int, default=4096)
    p.add_argument("--classes", type=int, default=8)
    p.add_argument("--platform", default="auto",
                   help="jax platform ('auto' probes the accelerator in a "
                        "bounded subprocess and falls back to cpu — a dead "
                        "tunnel HANGS backend init rather than raising)")
    args = p.parse_args()

    if args.platform == "auto":
        from bench import probe_backend

        platform = probe_backend()  # downgrades this process on failure
    else:
        from bench_tpu import _pin_platform

        platform = args.platform
        _pin_platform(platform)  # the ONE copy of the tpu/axon-skip rule
    print(json.dumps({"platform": platform}))

    import jax
    import jax.numpy as jnp

    N, F, B, K, C = args.n, args.features, args.bins, args.slots, args.classes
    rng = np.random.default_rng(0)
    xb = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.int32))
    y = jnp.asarray(rng.integers(0, C, size=N, dtype=np.int32))
    nid = jnp.asarray(rng.integers(0, K, size=N, dtype=np.int32))
    dev = jax.devices()[0].platform
    results = []

    def report(name, seconds, work, unit):
        row = {
            "bench": name, "platform": dev, "seconds": round(seconds, 5),
            "rate": round(work / seconds / 1e9, 3), "unit": unit,
        }
        results.append(row)
        print(json.dumps(row), flush=True)

    # 1. flattened scatter-add, the v0 histogram op ------------------------
    @jax.jit
    def scatter_full(xb, y, nid):
        feat = jnp.arange(F, dtype=jnp.int32)[None, :]
        ids = ((nid[:, None] * F + feat) * C + y[:, None]) * B + xb
        return jax.ops.segment_sum(
            jnp.ones((N, F), jnp.float32).reshape(-1), ids.reshape(-1),
            num_segments=K * F * C * B,
        )

    report("scatter_NxF_to_KFCB", timed(scatter_full, xb, y, nid),
           N * F, "G updates/s")

    # small table variant: does destination size matter?
    K2 = 64

    @jax.jit
    def scatter_small(xb, y, nid):
        feat = jnp.arange(F, dtype=jnp.int32)[None, :]
        ids = ((jnp.mod(nid, K2)[:, None] * F + feat) * C + y[:, None]) * B + xb
        return jax.ops.segment_sum(
            jnp.ones((N, F), jnp.float32).reshape(-1), ids.reshape(-1),
            num_segments=K2 * F * C * B,
        )

    report("scatter_NxF_to_64FCB", timed(scatter_small, xb, y, nid),
           N * F, "G updates/s")

    # single-column scatter (the node_id/perm-sized op)
    @jax.jit
    def scatter_1col(y, nid):
        return jax.ops.segment_sum(
            jnp.ones(N, jnp.float32), nid * C + y, num_segments=K * C
        )

    report("scatter_N_to_KC", timed(scatter_1col, y, nid), N, "G updates/s")

    # 2. row gather (permutation reorder) ----------------------------------
    perm = jnp.asarray(rng.permutation(N).astype(np.int32))

    @jax.jit
    def row_gather(xb, perm):
        return jnp.take(xb, perm, axis=0)

    s = timed(row_gather, xb, perm)
    report("row_gather_NxF_int32", s, N * F * 4 * 2, "GB/s")

    oh_cols = F * B

    try:
        oh = jnp.asarray(
            rng.integers(0, 2, size=(N // 4, oh_cols), dtype=np.int8)
        )
        perm4 = perm[: N // 4] % (N // 4)

        @jax.jit
        def oh_gather(oh, p):
            return jnp.take(oh, p, axis=0)

        s = timed(oh_gather, oh, perm4)
        report("row_gather_onehot_int8", s, (N // 4) * oh_cols * 2, "GB/s")
        del oh
    except Exception as e:  # OOM on small hosts
        print(json.dumps({"bench": "row_gather_onehot_int8", "skipped": str(e)}))

    # 3. int8 segment-matmul tiles (A @ OH) --------------------------------
    T = 256
    n_tiles = 64
    A = jnp.asarray(rng.integers(0, 2, size=(n_tiles, T, T), dtype=np.int8))
    OH = jnp.asarray(rng.integers(0, 2, size=(n_tiles, T, oh_cols), dtype=np.int8))

    @jax.jit
    def tile_matmul(A, OH):
        return jax.lax.dot_general(
            A, OH, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.int32,
        )

    s = timed(tile_matmul, A, OH)
    report("int8_tile_matmul_AxOH", s, 2 * n_tiles * T * T * oh_cols, "GFLOP/s")

    Abf = A.astype(jnp.bfloat16)
    OHbf = OH.astype(jnp.bfloat16)

    @jax.jit
    def tile_matmul_bf(A, OH):
        return jax.lax.dot_general(
            A, OH, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )

    s = timed(tile_matmul_bf, Abf, OHbf)
    report("bf16_tile_matmul_AxOH", s, 2 * n_tiles * T * T * oh_cols, "GFLOP/s")

    # 3b. Pallas one-hot-matmul histogram vs XLA scatter (small frontier) --
    # The measured justification for ops/pallas_hist.py: both ops compute
    # the identical (S, F, C, B) histogram a small-frontier level needs.
    from mpitree_tpu.ops import histogram as hist_ops
    from mpitree_tpu.ops import pallas_hist as ph

    S_small = 8
    nid_s = jnp.asarray(rng.integers(0, S_small, size=N, dtype=np.int32))
    w1 = jnp.ones(N, jnp.float32)

    @jax.jit
    def xla_small_hist(xb, y, nid_s):
        return hist_ops.class_histogram(
            xb, y, nid_s, jnp.int32(0), n_slots=S_small, n_bins=B,
            n_classes=C, sample_weight=w1,
        )

    s = timed(xla_small_hist, xb, y, nid_s)
    report("hist_small_xla_scatter", s, N * F, "G updates/s")

    if ph.pallas_available(dev):
        payload = ph.class_payload(y, w1, C)

        def pallas_small_hist(xb, payload, nid_s):
            return ph.histogram_small(
                xb, payload, nid_s, n_slots=S_small, n_bins=B, n_channels=C
            )

        s2 = timed(pallas_small_hist, xb, payload, nid_s)
        report("hist_small_pallas_mxu", s2, N * F, "G updates/s")
        same = bool(
            np.allclose(
                np.asarray(xla_small_hist(xb, y, nid_s)),
                np.asarray(pallas_small_hist(xb, payload, nid_s)),
            )
        )
        print(json.dumps({
            "bench": "hist_small_identity", "match": same,
            "pallas_speedup_x": round(s / s2, 2),
        }), flush=True)
    else:
        print(json.dumps(
            {"bench": "hist_small_pallas_mxu", "skipped": f"platform={dev}"}
        ), flush=True)

    # 4. reorder bookkeeping: sort and cumsum ------------------------------
    @jax.jit
    def argsort_n(nid):
        return jnp.argsort(nid, stable=True)

    report("argsort_N_int32", timed(argsort_n, nid), N, "G keys/s")

    @jax.jit
    def cumsum_n(x):
        return jnp.cumsum(x)

    report("cumsum_N_int32", timed(cumsum_n, nid), N, "G elems/s")

    # one-hot expansion cost (the thing precompute amortizes)
    @jax.jit
    def expand_onehot(xb):
        return (xb[:, :, None] == jnp.arange(B, dtype=jnp.int32)).astype(jnp.int8)

    xb_small = xb[: N // 8]
    s = timed(expand_onehot, xb_small)
    report("onehot_expand_int8", s, (N // 8) * F * B, "G cmp/s")

    host_tier(report, n=min(args.n, 200_000))

    print(json.dumps({"bench": "ALL", "results": len(results)}))


def host_tier(report, n: int):
    """C++ host-tier primitives: the incremental sweep and the hybrid tail."""
    from mpitree_tpu import DecisionTreeClassifier, native
    from mpitree_tpu.utils.datasets import load_covtype

    if native.lib() is None:
        print(json.dumps({"bench": "host_tier", "skipped": "no g++"}))
        return
    X, y, _ = load_covtype(n)
    F = X.shape[1]

    for criterion in ("entropy", "gini"):
        clf = DecisionTreeClassifier(
            max_depth=12, max_bins=256, backend="host", refine_depth=None,
            criterion=criterion,
        )
        t0 = time.perf_counter()
        clf.fit(X, y)
        dt = time.perf_counter() - t0
        # ~rows*features of sweep work per level
        report(
            f"host_cpp_sweep_{criterion}", dt,
            n * F * max(clf.tree_.max_depth, 1), "G cell/s",
        )

    clf = DecisionTreeClassifier(
        max_depth=20, max_bins=256, backend="host", refine_depth=7,
    )
    t0 = time.perf_counter()
    clf.fit(X, y)
    dt = time.perf_counter() - t0
    report("host_hybrid_depth20", dt, n * F * 20, "G cell/s")


if __name__ == "__main__":
    main()
