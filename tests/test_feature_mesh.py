"""2-D (data, feature) mesh: tensor parallelism over the histogram's F axis.

The determinism contract extends to the second mesh axis: the fitted tree
must be identical for mesh shapes (8,1), (4,2), (2,4), (1,8) — rows and
features shard differently but the psum'd histograms, the all_gather'd
split winners, and the owner-broadcast row routing reproduce the exact
single-device decisions (SURVEY.md §2.3 TP row; the reference scans features
serially, ``mpitree/tree/decision_tree.py:411-416``).
"""

from __future__ import annotations

import numpy as np
import pytest

from mpitree_tpu import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
)

MESH_SHAPES = [(8, 1), (4, 2), (2, 4), (1, 8)]


def _data(seed=0, n=300, f=10):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = ((X[:, 0] > 0) + 2 * (X[:, 3] + X[:, 7] > 0.5)).astype(np.int64)
    return X, y


@pytest.mark.parametrize("shape", MESH_SHAPES)
def test_classifier_identical_across_mesh_shapes(shape):
    X, y = _data()
    base = DecisionTreeClassifier(max_depth=6, backend="cpu").fit(X, y)
    meshed = DecisionTreeClassifier(max_depth=6, n_devices=shape).fit(X, y)
    assert meshed.export_text() == base.export_text()
    np.testing.assert_array_equal(meshed.tree_.count, base.tree_.count)


@pytest.mark.parametrize("shape", [(4, 2), (2, 4)])
def test_regressor_identical_across_mesh_shapes(shape):
    X, _ = _data(seed=1)
    rng = np.random.default_rng(2)
    yr = (2 * X[:, 0] - X[:, 3] + 0.1 * rng.normal(size=len(X))).astype(
        np.float64
    )
    base = DecisionTreeRegressor(max_depth=5, backend="cpu").fit(X, yr)
    meshed = DecisionTreeRegressor(max_depth=5, n_devices=shape).fit(X, yr)
    assert meshed.export_text() == base.export_text()
    np.testing.assert_allclose(
        meshed.tree_.count[:, 0], base.tree_.count[:, 0], rtol=0, atol=0
    )


def test_feature_padding_inert():
    """F=10 over 4 feature shards pads to 12 columns; padding must never
    be selected and the tree must match the unpadded single-device fit."""
    X, y = _data(n=257, f=10)  # odd row count: data padding path too
    base = DecisionTreeClassifier(max_depth=5, backend="cpu").fit(X, y)
    meshed = DecisionTreeClassifier(max_depth=5, n_devices=(2, 4)).fit(X, y)
    assert meshed.export_text() == base.export_text()
    assert int(meshed.tree_.feature.max()) < 10


def test_levelwise_rejects_feature_mesh():
    X, y = _data(n=200)
    clf = DecisionTreeClassifier(max_depth=3, n_devices=(2, 2))
    import mpitree_tpu.core.builder as b

    with pytest.raises(ValueError, match="levelwise"):
        from mpitree_tpu.core.builder import BuildConfig, build_tree
        from mpitree_tpu.ops.binning import bin_dataset
        from mpitree_tpu.parallel import mesh as mesh_lib

        binned = bin_dataset(X)
        build_tree(
            binned, y.astype(np.int32),
            config=BuildConfig(engine="levelwise", max_depth=3),
            mesh=mesh_lib.resolve_mesh(n_devices=(2, 2)), n_classes=4,
        )
