"""2-D (data, feature) mesh: tensor parallelism over the histogram's F axis.

The determinism contract extends to the second mesh axis: the fitted tree
must be identical for mesh shapes (8,1), (4,2), (2,4), (1,8) — rows and
features shard differently but the psum'd histograms, the all_gather'd
split winners, and the owner-broadcast row routing reproduce the exact
single-device decisions (SURVEY.md §2.3 TP row; the reference scans features
serially, ``mpitree/tree/decision_tree.py:411-416``).
"""

from __future__ import annotations

import numpy as np
import pytest

from mpitree_tpu import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
)

MESH_SHAPES = [(8, 1), (4, 2), (2, 4), (1, 8)]


def _data(seed=0, n=300, f=10):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = ((X[:, 0] > 0) + 2 * (X[:, 3] + X[:, 7] > 0.5)).astype(np.int64)
    return X, y


@pytest.mark.parametrize("shape", MESH_SHAPES)
def test_classifier_identical_across_mesh_shapes(shape):
    X, y = _data()
    base = DecisionTreeClassifier(max_depth=6, backend="cpu").fit(X, y)
    meshed = DecisionTreeClassifier(max_depth=6, n_devices=shape).fit(X, y)
    assert meshed.export_text() == base.export_text()
    np.testing.assert_array_equal(meshed.tree_.count, base.tree_.count)


@pytest.mark.parametrize("shape", [(4, 2), (2, 4)])
def test_regressor_identical_across_mesh_shapes(shape):
    X, _ = _data(seed=1)
    rng = np.random.default_rng(2)
    yr = (2 * X[:, 0] - X[:, 3] + 0.1 * rng.normal(size=len(X))).astype(
        np.float64
    )
    base = DecisionTreeRegressor(max_depth=5, backend="cpu").fit(X, yr)
    meshed = DecisionTreeRegressor(max_depth=5, n_devices=shape).fit(X, yr)
    assert meshed.export_text() == base.export_text()
    np.testing.assert_allclose(
        meshed.tree_.count[:, 0], base.tree_.count[:, 0], rtol=0, atol=0
    )


def test_feature_padding_inert():
    """F=10 over 4 feature shards pads to 12 columns; padding must never
    be selected and the tree must match the unpadded single-device fit."""
    X, y = _data(n=257, f=10)  # odd row count: data padding path too
    base = DecisionTreeClassifier(max_depth=5, backend="cpu").fit(X, y)
    meshed = DecisionTreeClassifier(max_depth=5, n_devices=(2, 4)).fit(X, y)
    assert meshed.export_text() == base.export_text()
    assert int(meshed.tree_.feature.max()) < 10


# ---------------------------------------------------------------------------
# ISSUE 10: the mesh-identity pin — 1-D (n,) vs 2-D (n/f, f), BOTH device
# engines, hist_subtraction on and off. The levelwise engine now shards
# its histogram feature slabs too (collective.make_split_fn +
# select_global), so the old levelwise-rejects test is replaced by the
# stronger identity contract.
# ---------------------------------------------------------------------------

def _build(X, y, *, engine, shape, sub, max_depth=5):
    from mpitree_tpu.core.builder import BuildConfig, build_tree
    from mpitree_tpu.ops.binning import bin_dataset
    from mpitree_tpu.parallel import mesh as mesh_lib

    binned = bin_dataset(X)
    return build_tree(
        binned, y.astype(np.int32),
        config=BuildConfig(
            engine=engine, max_depth=max_depth, hist_subtraction=sub,
        ),
        mesh=mesh_lib.resolve_mesh(n_devices=shape),
        n_classes=int(y.max()) + 1,
    )


def _tree_key(t):
    return (t.feature.tobytes(), t.threshold.tobytes(), t.left.tobytes(),
            t.count.tobytes())


_REF_KEYS: dict = {}


@pytest.mark.parametrize("engine", ["fused", "levelwise"])
@pytest.mark.parametrize("f", [2, 4])
@pytest.mark.parametrize("sub", ["on", "off"])
def test_mesh_identity_both_engines_sub_toggle(engine, f, sub):
    X, y = _data(n=240)
    # one (8, 1) reference build per (engine, sub) — the f=2 and f=4
    # params compare against the same memoized key (wall budget: every
    # distinct mesh shape is its own compile set)
    if (engine, sub) not in _REF_KEYS:
        _REF_KEYS[engine, sub] = _tree_key(
            _build(X, y, engine=engine, shape=(8, 1), sub=sub)
        )
    two_d = _build(X, y, engine=engine, shape=(8 // f, f), sub=sub)
    assert _tree_key(two_d) == _REF_KEYS[engine, sub]


@pytest.mark.parametrize("f", [2, 4])
def test_gbdt_identity_across_feature_shards(f):
    """Boosted ensembles (scoped-f64 (g, h) path) are bit-identical
    between the 1-D data mesh and a feature-sharded mesh — the Newton
    rounds now ride the feature-sharded levelwise split program."""
    from mpitree_tpu import GradientBoostingClassifier

    X, y = _data(n=240)
    ref = GradientBoostingClassifier(
        max_iter=4, max_depth=3, random_state=0, n_devices=8
    ).fit(X, y)
    two_d = GradientBoostingClassifier(
        max_iter=4, max_depth=3, random_state=0, n_devices=(8 // f, f)
    ).fit(X, y)
    np.testing.assert_array_equal(
        ref.predict_proba(X), two_d.predict_proba(X)
    )


@pytest.mark.parametrize("sub", ["on", "off"])
def test_gbdt_subtraction_toggle_on_feature_mesh(sub, monkeypatch):
    from mpitree_tpu import GradientBoostingClassifier

    monkeypatch.setenv("MPITREE_TPU_HIST_SUBTRACTION", sub)
    X, y = _data(n=240)
    ref = GradientBoostingClassifier(
        max_iter=3, max_depth=4, random_state=0, n_devices=8
    ).fit(X, y)
    two_d = GradientBoostingClassifier(
        max_iter=3, max_depth=4, random_state=0, n_devices=(4, 2)
    ).fit(X, y)
    np.testing.assert_array_equal(
        ref.predict_proba(X), two_d.predict_proba(X)
    )


@pytest.mark.parametrize("engine", ["fused", "levelwise"])
def test_wire_ledger_feature_sharding_evidence(engine, monkeypatch):
    """The ISSUE-10 wire-ledger acceptance: on a 2-D mesh the recorded
    per-fit ``split_hist_psum`` logical payload is exactly 1/f of the 1-D
    mesh's on the same fit (f divides the padded feature count), and
    ``select_global``'s winner gather (plus the update step's
    owner-broadcast) are the only feature-axis collectives."""
    monkeypatch.setenv("MPITREE_TPU_ENGINE", engine)
    X, y = _data(n=240, f=10)  # pads to 12 over 4 shards; exact /2 at f=2
    c1 = DecisionTreeClassifier(max_depth=5, n_devices=8).fit(X, y)
    c2 = DecisionTreeClassifier(max_depth=5, n_devices=(4, 2)).fit(X, y)
    s1 = c1.fit_report_["collectives"]["split_hist_psum"]["bytes"]
    s2 = c2.fit_report_["collectives"]["split_hist_psum"]["bytes"]
    assert s1 == 2 * s2
    wire = c2.fit_report_["wire"]
    assert wire["axes"] == {"data": 4, "feature": 2}
    feature_sites = {
        site for site, v in wire["sites"].items() if v["axis"] == "feature"
    }
    assert "feature_merge_all_gather" in feature_sites
    assert feature_sites <= {"feature_merge_all_gather", "route_psum"}
    assert wire["feature_bytes"] > 0 and wire["data_bytes"] > 0
    # digest surfaces the mesh shape (bench section lines embed this)
    from mpitree_tpu.obs import digest

    assert digest(c2.fit_report_)["feature_shards"] == 2
    assert digest(c1.fit_report_)["feature_shards"] == 1
    # 1-D fits record no feature-axis collective at all
    assert all(
        v["axis"] == "data"
        for v in c1.fit_report_["wire"]["sites"].values()
    )


def test_leafwise_refuses_feature_mesh_with_typed_event():
    """ISSUE-10 satellite: the best-first frontier (no feature-axis
    select_global twin yet) must refuse a 2-D mesh loudly — typed
    ``mesh2d_unsupported`` event + recorded decision — not mis-build."""
    from mpitree_tpu.core.builder import BuildConfig, build_tree
    from mpitree_tpu.obs import BuildObserver
    from mpitree_tpu.ops.binning import bin_dataset
    from mpitree_tpu.parallel import mesh as mesh_lib

    X, y = _data(n=200)
    binned = bin_dataset(X)
    obs = BuildObserver(timing=False)
    with pytest.raises(ValueError, match="mesh2d_unsupported"):
        build_tree(
            binned, y.astype(np.int32),
            config=BuildConfig(max_leaf_nodes=15, max_depth=5),
            mesh=mesh_lib.resolve_mesh(n_devices=(4, 2)), n_classes=4,
            timer=obs,
        )
    kinds = [e["kind"] for e in obs.record.events]
    assert "mesh2d_unsupported" in kinds
    assert obs.record.decisions["leafwise_mesh"]["value"] == "refused"


def test_fused_rounds_refuses_feature_mesh():
    """rounds_per_dispatch > 1 has no feature-axis winner merge either:
    explicit K raises, auto resolves to the host loop with the blocker
    in the recorded reason."""
    from mpitree_tpu import GradientBoostingClassifier

    X, y = _data(n=200)
    yb = (y > 0).astype(np.int64)
    with pytest.raises(ValueError, match="mesh2d_unsupported"):
        GradientBoostingClassifier(
            max_iter=4, max_depth=3, rounds_per_dispatch=4,
            n_devices=(4, 2), random_state=0,
        ).fit(X, yb)
    b = GradientBoostingClassifier(
        max_iter=2, max_depth=3, n_devices=(4, 2), random_state=0,
    ).fit(X, yb)
    assert b.fit_report_["decisions"]["rounds_per_dispatch"]["value"] == 1


def test_validate_max_leaf_nodes_refuses_feature_mesh_request():
    """The estimator-level twin: param validation fails before any
    sharding work when n_devices itself requests feature shards."""
    clf = DecisionTreeClassifier(max_leaf_nodes=15, n_devices=(4, 2))
    X, y = _data(n=120)
    with pytest.raises(ValueError, match="mesh2d_unsupported"):
        clf.fit(X, y)
