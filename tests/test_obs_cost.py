"""ISSUE 18 — obs.cost + obs.advisor: the compute ledger and the
evidence loop.

The contracts this file pins:

- **golden ``record.compute`` schema**: the section's top-level and
  per-entry field sets are frozen (consumers: digest, bench
  RECORD_DIGEST_KEYS, the Perfetto util track), and the arithmetic is
  the documented join — optimal = max(flops/peak, bytes/bw) per
  dispatch, util = 100 * floor / measured wall, roofline = slowest leg.
- **honesty**: unknown platforms price to ``None`` everywhere (never a
  guess), env knobs override field-wise, and a wheel that cannot
  ``cost_analysis()`` degrades to ONE typed ``cost_unavailable`` event
  per entry while the fit completes.
- **advisor grid**: against a synthetic flight store, ``auto`` policies
  pick the measured winner when the lineage clears MIN_HISTORY and the
  MAD noise gate, and fall back to the static policy — bit-for-bit —
  on thin or noisy history or when the gate (config or knob) is off.
- **trace**: a priced record synthesizes a ``util`` counter track that
  passes the golden Chrome-trace validation.
"""

import json

import numpy as np
import pytest

from mpitree_tpu.obs import advisor as advisor_mod
from mpitree_tpu.obs import cost as cost_mod
from mpitree_tpu.obs import flight as obs_flight
from mpitree_tpu.obs import trace as trace_mod
from mpitree_tpu.obs import BuildObserver, digest
from mpitree_tpu.obs.flight import FlightStore


# ---------------------------------------------------------------------------
# compute_section: golden schema + join arithmetic
# ---------------------------------------------------------------------------

# The frozen field sets (schema v9). Growing them is fine — remove or
# rename only with a schema bump and a digest/bench sweep.
COMPUTE_FIELDS = {
    "peak", "n_shards", "entries", "levels", "optimal_s", "measured_s",
    "util_pct", "roofline", "bounds_s",
}
ENTRY_FIELDS = {
    "flops", "bytes", "flops_per_shard", "bytes_per_shard", "variants",
    "optimal_s", "dispatches", "measured_s", "util_pct", "bound",
}


def _report(n_shards=1):
    return {
        "phases": {"split": {"seconds": 0.2, "calls": 6},
                   "fused_build": {"seconds": 0.1, "calls": 1}},
        "collectives": {"split_hist_psum": {"calls": 6, "bytes": 4096}},
        "counters": {"expansions": 30},
        "levels": [
            {"level": 0, "hist_bytes": 1e6, "psum_bytes": 1e5,
             "seconds": 0.05},
            {"level": 1, "hist_bytes": 2e6, "psum_bytes": 2e5,
             "seconds": None},
        ],
        "wire": {"n_shards": n_shards, "wire_bytes_per_shard": 0},
        "mesh": {"axes": {"data": n_shards}},
    }


PEAKS = {"flops": 1e12, "hbm_gbps": 100.0, "ici_gbps": 50.0,
         "device_kind": "test", "source": "env"}


def test_compute_section_golden_schema_and_join():
    caps = {"split_fn": {"flops": 2e9, "bytes": 1e9, "variants": 2}}
    sec = cost_mod.compute_section(_report(), caps, PEAKS)
    assert set(sec) == COMPUTE_FIELDS
    e = sec["entries"]["split_fn"]
    assert set(e) == ENTRY_FIELDS
    # one dispatch: t_compute = 2e9/1e12 = 2ms, t_hbm = 1e9/1e11 = 10ms
    # -> hbm-bound, optimal 10ms; 6 dispatches vs the 0.2s split wall
    assert e["bound"] == "hbm"
    assert e["optimal_s"] == pytest.approx(0.01)
    assert e["dispatches"] == 6
    assert e["util_pct"] == pytest.approx(100 * 0.06 / 0.2, abs=0.01)
    assert sec["util_pct"] == e["util_pct"]
    assert sec["roofline"] == "hbm"
    # per-level floors price from hist (HBM) bytes; seconds=None rows
    # (fused replay) get a floor but honestly no utilization
    lv0, lv1 = sec["levels"]
    assert lv0["floor_s"] == pytest.approx(1e6 / 1e11)
    assert lv0["util_pct"] is not None
    assert lv1["util_pct"] is None and lv1["floor_s"] is not None


def test_compute_section_divides_per_shard_and_prices_ici():
    caps = {"split_fn": {"flops": 8e9, "bytes": 4e9, "variants": 1}}
    sec = cost_mod.compute_section(_report(n_shards=4), caps, PEAKS)
    e = sec["entries"]["split_fn"]
    assert e["flops_per_shard"] == pytest.approx(2e9)
    assert e["bytes_per_shard"] == pytest.approx(1e9)
    # per-level ICI leg: psum ring bytes over the data axis (dr=4)
    lv0 = sec["levels"][0]
    t_h = 1e6 / 1e11
    t_i = 1e5 * 3 / 4 / 50e9
    assert lv0["floor_s"] == pytest.approx(max(t_h, t_i))


def test_compute_section_unknown_platform_prices_none():
    peaks = cost_mod.platform_peaks("Strange Accelerator 9000")
    assert peaks["source"] == "unknown"
    assert peaks["flops"] is None and peaks["hbm_gbps"] is None
    caps = {"split_fn": {"flops": 2e9, "bytes": 1e9, "variants": 1}}
    sec = cost_mod.compute_section(_report(), caps, peaks)
    e = sec["entries"]["split_fn"]
    assert e["optimal_s"] is None and e["util_pct"] is None
    assert e["bound"] is None
    assert sec["util_pct"] is None and sec["roofline"] is None
    # ...but the raw captured costs still land (priceable later)
    assert e["flops"] == 2e9 and e["bytes"] == 1e9


def test_platform_peaks_env_overrides_fieldwise(monkeypatch):
    monkeypatch.setenv(cost_mod.PEAK_FLOPS_ENV, "5e12")
    peaks = cost_mod.platform_peaks("Strange Accelerator 9000")
    assert peaks["source"] == "env"
    assert peaks["flops"] == 5e12
    assert peaks["hbm_gbps"] is None  # the un-overridden leg stays honest


def test_digest_carries_util_and_roofline():
    caps = {"split_fn": {"flops": 2e9, "bytes": 1e9, "variants": 1}}
    sec = cost_mod.compute_section(_report(), caps, PEAKS)
    d = digest({"schema": 9, "compute": sec})
    assert d["util_pct"] == sec["util_pct"]
    assert d["roofline"] == "hbm"
    # unpriced record: keys present, honestly None
    d0 = digest({"schema": 9})
    assert d0["util_pct"] is None and d0["roofline"] is None


def test_entry_join_covers_every_priced_dispatch_site():
    assert set(cost_mod.ENTRY_JOIN) == {
        "split_fn", "counts_fn", "update_fn", "fused_fn", "forest_fn",
        "leafwise_fn", "expand_fn", "fused_rounds_fn", "serving_traverse",
    }


# ---------------------------------------------------------------------------
# cost_unavailable degrade path (legacy wheels / unpriceable backends)
# ---------------------------------------------------------------------------

def test_cost_unavailable_degrades_to_one_typed_event():
    obs = BuildObserver(timing=False)
    obs.compile_note("split_fn", "kX")

    class LegacyLowered:  # no cost_analysis attribute at all
        pass

    obs.price_compile("split_fn", lambda: LegacyLowered())
    obs.price_compile("split_fn", lambda: LegacyLowered())  # deduped
    evs = [e for e in obs.record.events if e["kind"] == "cost_unavailable"]
    assert len(evs) == 1
    assert evs[0]["entry"] == "split_fn"
    # ...and a lower that itself raises is equally survivable
    def boom():
        raise RuntimeError("legacy wheel")
    obs.price_compile("counts_fn", boom)
    rep = obs.report()  # the fit completes; compute stays honest
    assert "compute" in rep


def test_capture_handles_list_shaped_analysis():
    class Lowered:
        def cost_analysis(self):
            return [{"flops": 12.0, "bytes accessed": 34.0}]

    assert cost_mod.capture(lambda: Lowered()) == {
        "flops": 12.0, "bytes": 34.0,
    }
    class Empty:
        def cost_analysis(self):
            return []
    assert cost_mod.capture(lambda: Empty()) is None


# ---------------------------------------------------------------------------
# advisor: synthetic-store unit grid
# ---------------------------------------------------------------------------

SHAPE = {"n_samples": 4000, "n_features": 16, "n_bins": 64}


def _seed(store, section, metric, values, *, platform="cpu", extra=None):
    for v in values:
        store.append(
            kind="bench", section=section, platform=platform,
            metrics={metric: v, **SHAPE, **(extra or {})},
        )


@pytest.fixture
def evidence(tmp_path, monkeypatch):
    # advisor gates on the ambient store being configured (flight.enabled)
    monkeypatch.setenv(obs_flight.RUN_DIR_ENV, str(tmp_path))
    return FlightStore(str(tmp_path))


def test_advisor_picks_measured_winner(evidence):
    _seed(evidence, "subtraction_ab", "warm_speedup_on_vs_off",
          [1.38, 1.42, 1.40, 1.45])
    adv = advisor_mod.advise_hist_subtraction(
        platform="cpu", shape=SHAPE, store=evidence,
    )
    assert adv["value"] == "on"
    assert adv["fallback"] is None
    assert adv["evidence_n"] == 4
    assert adv["margin"] > adv["gate"]
    # the inverse evidence picks the other side
    _seed(evidence, "mesh2d_ab", "warm_speedup_2d_vs_1d",
          [0.71, 0.69, 0.70, 0.72])
    adv2 = advisor_mod.advise_mesh_2d(
        platform="cpu", shape=SHAPE, store=evidence,
    )
    assert adv2["value"] == "1d" and adv2["fallback"] is None


def test_advisor_thin_history_falls_back(evidence):
    _seed(evidence, "subtraction_ab", "warm_speedup_on_vs_off", [1.4, 1.4])
    adv = advisor_mod.advise_hist_subtraction(
        platform="cpu", shape=SHAPE, store=evidence,
    )
    assert adv["value"] is None
    assert adv["fallback"] == "thin_history"
    # wrong platform: same store, zero matched rows
    adv2 = advisor_mod.advise_hist_subtraction(
        platform="tpu", shape=SHAPE, store=evidence,
    )
    assert adv2["value"] is None and adv2["evidence_n"] == 0


def test_advisor_noise_gate_falls_back(evidence):
    # a lineage that wobbles across 1.0: big MAD -> gate > margin
    _seed(evidence, "subtraction_ab", "warm_speedup_on_vs_off",
          [0.7, 1.5, 0.8, 1.4])
    adv = advisor_mod.advise_hist_subtraction(
        platform="cpu", shape=SHAPE, store=evidence,
    )
    assert adv["value"] is None
    assert adv["fallback"] == "noise_gate"
    assert adv["gate"] > adv["margin"]


def test_advisor_off_gates_consultation(evidence, monkeypatch):
    _seed(evidence, "subtraction_ab", "warm_speedup_on_vs_off",
          [1.4, 1.4, 1.4, 1.4])
    assert advisor_mod.advise_hist_subtraction(
        platform="cpu", shape=SHAPE, store=evidence,
        policy_evidence="off",
    ) is None
    monkeypatch.setenv(advisor_mod.POLICY_ENV, "off")
    assert advisor_mod.advise_hist_subtraction(
        platform="cpu", shape=SHAPE, store=evidence,
    ) is None


def test_advisor_rounds_carries_measured_k(evidence):
    _seed(evidence, "gbdt_fusedK", "fit_speedup_x", [2.1, 2.0, 2.2],
          extra={"K": 6})
    adv = advisor_mod.advise_rounds_per_dispatch(
        platform="cpu", shape=SHAPE, store=evidence,
    )
    assert adv["value"] == "fused"
    assert adv["K"] == 6


def test_advisor_serving_kernel_groups_by_kernel(evidence):
    _seed(evidence, "serving", "sustained_rows_per_s",
          [1.0e5, 1.1e5, 1.05e5], extra={"kernel_pallas": 0})
    _seed(evidence, "serving", "sustained_rows_per_s",
          [2.0e5, 2.1e5, 2.05e5], extra={"kernel_pallas": 1})
    adv = advisor_mod.advise_serving_kernel(
        platform="cpu", shape={"n_features": 16}, store=evidence,
    )
    assert adv["value"] == "pallas"
    assert adv["fallback"] is None
    assert adv["median"] == pytest.approx(2.0, abs=0.1)


def test_advisor_nearest_shape_outvotes_foreign_workloads(evidence):
    # 8 rows from a foreign (1000x larger) workload say "off"; 8 matched
    # rows say "on" — the NEAREST_K window must read the matched ones.
    far = {"n_samples": 4_000_000, "n_features": 16, "n_bins": 64}
    for v in [0.7] * 8:
        evidence.append(kind="bench", section="subtraction_ab",
                        platform="cpu",
                        metrics={"warm_speedup_on_vs_off": v, **far})
    _seed(evidence, "subtraction_ab", "warm_speedup_on_vs_off",
          [1.4] * 8)
    adv = advisor_mod.advise_hist_subtraction(
        platform="cpu", shape=SHAPE, store=evidence,
    )
    assert adv["value"] == "on"


def test_host_tier_and_refine_emit_unpriced_compute():
    """The numpy/C++ builders and the refine tail show up in
    record.compute as priced-to-None entries with dispatch counts — a
    visible coverage gap, not a silent one (ISSUE 20 satellite)."""
    from mpitree_tpu import DecisionTreeClassifier

    rng = np.random.default_rng(3)
    X = rng.normal(size=(400, 5)).astype(np.float64)
    y = (X[:, 0] > 0).astype(np.int64)
    host = DecisionTreeClassifier(
        max_depth=4, max_bins=16, backend="host", refine_depth=None,
    ).fit(X, y)
    comp = host.fit_report_["compute"]
    row = comp["entries"]["host_build"]
    assert row["dispatches"] == 1
    assert row["optimal_s"] is None and row["util_pct"] is None
    assert "unpriced" in row
    assert comp["optimal_s"] is None and comp["roofline"] is None
    json.dumps(comp)
    # a refined device fit merges the refine_tail row next to the
    # (possibly priced) device entries
    X2 = X.copy()
    X2[:, 0] = np.where(X2[:, 0] > 0, X2[:, 0] * 100, X2[:, 0])
    y2 = ((np.abs(X2[:, 0]) < 0.3).astype(int)
          + (X2[:, 1] > 0.2).astype(int)).astype(np.int64)
    refined = DecisionTreeClassifier(
        max_depth=8, max_bins=8, backend="cpu", refine_depth=2,
    ).fit(X2, y2)
    comp2 = refined.fit_report_["compute"]
    tail = comp2["entries"]["refine_tail"]
    assert tail["dispatches"] >= 1
    assert tail["optimal_s"] is None and "unpriced" in tail
    json.dumps(comp2)


def test_advisor_engine_consults_leafwise_ab(evidence):
    _seed(evidence, "leafwise_ab", "warm_speedup_x", [1.5, 1.6, 1.55, 1.5])
    adv = advisor_mod.advise_engine(
        platform="cpu", shape=SHAPE, store=evidence,
    )
    assert adv["value"] == "leafwise" and adv["fallback"] is None
    # the inverse lineage prefers the level-wise engines (static pick)
    _seed(evidence, "leafwise_ab", "warm_speedup_x",
          [0.6, 0.62, 0.61, 0.6, 0.6, 0.61, 0.62, 0.6])
    adv2 = advisor_mod.advise_engine(
        platform="cpu", shape=SHAPE, store=evidence,
    )
    assert adv2["value"] == "levelwise"


def test_advisor_engine_routes_fit_bit_identical(evidence, monkeypatch):
    """Measured leafwise_ab wins route an engine='auto' fit through the
    best-first frontier at the 2^max_depth budget — same tree, and the
    advisor_engine decision explains the flip."""
    from mpitree_tpu import DecisionTreeClassifier

    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 6)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.int64)
    kw = dict(max_depth=4, max_bins=16, backend="cpu", refine_depth=None)
    _seed(evidence, "leafwise_ab", "warm_speedup_x",
          [1.5, 1.6, 1.55, 1.5],
          extra={"n_samples": 500, "n_features": 6, "max_depth": 4})
    routed = DecisionTreeClassifier(**kw).fit(X, y)
    dec = routed.fit_report_["decisions"]
    assert dec["advisor_engine"]["value"] == "leafwise"
    assert dec["frontier"]["value"] == "leafwise"
    monkeypatch.setenv(advisor_mod.POLICY_ENV, "off")
    static = DecisionTreeClassifier(**kw).fit(X, y)
    assert "advisor_engine" not in static.fit_report_["decisions"]
    np.testing.assert_array_equal(routed.tree_.feature, static.tree_.feature)
    np.testing.assert_array_equal(
        routed.tree_.threshold, static.tree_.threshold
    )
    np.testing.assert_array_equal(routed.tree_.count, static.tree_.count)


def test_record_advice_emits_typed_decision(evidence):
    _seed(evidence, "subtraction_ab", "warm_speedup_on_vs_off",
          [1.4, 1.4, 1.4, 1.4])
    adv = advisor_mod.advise_hist_subtraction(
        platform="cpu", shape=SHAPE, store=evidence,
    )
    obs = BuildObserver(timing=False)
    advisor_mod.record_advice(obs, adv)
    advisor_mod.record_advice(obs, None)  # consultation never ran: no-op
    d = obs.record.decisions["advisor_hist_subtraction"]
    assert d["value"] == "on"
    assert d["inputs"]["evidence_n"] == 4
    assert d["inputs"]["fallback"] is None
    assert "measured winner" in d["reason"]


def test_advisor_no_store_is_cheap_none(monkeypatch):
    monkeypatch.delenv(obs_flight.RUN_DIR_ENV, raising=False)
    assert advisor_mod.advise_hist_subtraction(
        platform="cpu", shape=SHAPE,
    ) is None


# ---------------------------------------------------------------------------
# utilization counter track (Perfetto, next to ici/mem)
# ---------------------------------------------------------------------------

def test_util_track_synthesized_and_valid(tmp_path):
    caps = {"split_fn": {"flops": 2e9, "bytes": 1e9, "variants": 1}}
    rep = _report()
    rep["compute"] = cost_mod.compute_section(rep, caps, PEAKS)
    sink = trace_mod.TraceSink(str(tmp_path / "u.trace.json"))
    n = trace_mod.synthesize_record_tracks(sink, "owner", "fit", rep)
    assert n > 0
    path = sink.write()
    tr = json.load(open(path))
    assert trace_mod.validate_trace(tr) == []
    utils = [e for e in tr["traceEvents"]
             if e.get("ph") == "C" and e.get("name") == "util_pct"]
    assert len(utils) >= 2  # window-edge samples + priced levels
    assert all(isinstance(e["args"]["pct"], float) for e in utils)
    util_tids = {e["tid"] for e in utils}
    named = {e["tid"]: e["args"]["name"] for e in tr["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    assert all(named.get(t) == "util" for t in util_tids)


def test_unpriced_record_adds_no_util_track(tmp_path):
    rep = _report()  # no compute section at all
    sink = trace_mod.TraceSink(str(tmp_path / "n.trace.json"))
    trace_mod.synthesize_record_tracks(sink, "owner", "fit", rep)
    assert not [e for e in sink.events() if e.get("name") == "util_pct"]


# ---------------------------------------------------------------------------
# live end-to-end: a priced fit carries record.compute
# ---------------------------------------------------------------------------

def test_live_fit_records_compute_with_env_peaks(monkeypatch):
    from mpitree_tpu.models.classifier import DecisionTreeClassifier

    monkeypatch.setenv("MPITREE_TPU_ENGINE", "levelwise")
    monkeypatch.setenv("MPITREE_TPU_PROFILE", "1")
    # Deliberately modest synthetic peaks so this smoke workload's floor
    # is non-negligible against its measured wall (a real peak on a CPU
    # smoke run rounds utilization to 0.00 at 2 decimals).
    monkeypatch.setenv(cost_mod.PEAK_FLOPS_ENV, "1e9")
    monkeypatch.setenv(cost_mod.PEAK_HBM_ENV, "1")
    rng = np.random.default_rng(5)
    X = rng.normal(size=(600, 8)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    clf = DecisionTreeClassifier(
        max_depth=3, max_bins=16, backend="cpu"
    ).fit(X, y)
    comp = clf.fit_report_["compute"]
    assert comp, "device-engine fit must carry a priced compute section"
    assert set(comp) == COMPUTE_FIELDS
    assert "split_fn" in comp["entries"]
    e = comp["entries"]["split_fn"]
    assert e["flops"] > 0 and e["bytes"] > 0
    assert e["util_pct"] is not None and e["util_pct"] > 0
    assert comp["roofline"] in ("compute", "hbm", "ici")
    assert comp["peak"]["source"] == "env"
