"""ISSUE 9 — obs.trace + obs.metrics: timelines, quantiles, attribution.

The four contracts this file pins:

- **golden trace schema**: a real fit's ``trace_to`` output is a valid
  Chrome-trace-event JSON (required per-event fields, whitelisted
  phases, non-negative microsecond timestamps monotonic per (pid, tid)
  track, ``thread_name`` metadata for every used track) — the
  Perfetto-loadability gate ``make trace-smoke`` runs in CI;
- **quantile oracle**: the log-bucketed histogram's p50/p95/p99 track
  ``numpy.percentile`` within the geometric-bucket error bound;
- **request-path pins with metrics on**: latency observation + counters
  add ZERO new compile cache-keys and ZERO explicit device_put calls to
  the warmed serving path;
- **attribution + ledger**: fresh cache-key registrations carry
  cold-dispatch wall per entry point, and the record's ``wire`` block /
  digest carry the per-fit and per-shard ICI wire estimates.
"""

import json
import os

import numpy as np
import pytest

import jax

from mpitree_tpu.models.classifier import DecisionTreeClassifier
from mpitree_tpu.obs import (
    REGISTRY,
    BuildObserver,
    digest,
    wire_estimate,
)
from mpitree_tpu.obs import metrics as metrics_mod
from mpitree_tpu.obs import trace as trace_mod


def _cls_data(n=400, f=6, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = ((X[:, 0] > 0) + (X[:, 1] > 0.6)).astype(np.int64)
    return X, y


# ---------------------------------------------------------------------------
# golden Chrome trace schema
# ---------------------------------------------------------------------------

def _fit_trace(tmp_path, engine, monkeypatch, name):
    # backend="cpu" forces the device path (auto would route this smoke
    # workload to the host tier, which has no engine spans to trace)
    monkeypatch.setenv("MPITREE_TPU_ENGINE", engine)
    path = tmp_path / f"{name}.trace.json"
    clf = DecisionTreeClassifier(
        max_depth=3, max_bins=16, backend="cpu"
    ).fit(*_cls_data(), trace_to=path)
    with open(path) as f:
        return clf, json.load(f)


def test_trace_schema_golden_levelwise(tmp_path, monkeypatch):
    """The pinned trace-event contract: valid fields, monotonic ts per
    track, pid/tid -> thread_name mapping — on a live level-wise fit."""
    clf, tr = _fit_trace(tmp_path, "levelwise", monkeypatch, "lw")
    assert trace_mod.validate_trace(tr) == []
    evs = tr["traceEvents"]
    assert all(e["ph"] in ("X", "i", "C", "M") for e in evs)
    for e in evs:
        assert {"ph", "pid", "tid", "name"} <= set(e)
        if e["ph"] != "M":
            assert e["ts"] >= 0
        if e["ph"] == "X":
            assert e["dur"] >= 0
    # every used track is named, and ts is monotonic per track
    named = {(e["pid"], e["tid"]) for e in evs
             if e["ph"] == "M" and e["name"] == "thread_name"}
    last = {}
    for e in evs:
        if e["ph"] == "M":
            continue
        key = (e["pid"], e["tid"])
        assert key in named
        assert e["ts"] >= last.get(key, 0.0)
        last[key] = e["ts"]
    names = {e["name"] for e in evs}
    # live engine spans + synthesized per-level replay + ICI counters
    assert "split" in names and "update" in names
    assert any(n.startswith("level ") for n in names)
    assert any(e["ph"] == "C" for e in evs)


def test_trace_fused_replay_spans_inside_build_window(tmp_path, monkeypatch):
    """The fused engine has no per-level host clock: its level spans are
    synthesized from the realized-work replay rows and must land inside
    the live fused_build span's window."""
    _clf, tr = _fit_trace(tmp_path, "fused", monkeypatch, "fz")
    assert trace_mod.validate_trace(tr) == []
    evs = tr["traceEvents"]
    build = [e for e in evs if e["name"] == "fused_build"]
    assert len(build) == 1
    lo, hi = build[0]["ts"], build[0]["ts"] + build[0]["dur"]
    replay = [e for e in evs if e.get("cat") == "replay"
              and e["name"].startswith("level ")]
    assert replay  # at least the root level
    for e in replay:
        assert lo - 1 <= e["ts"] and e["ts"] + e["dur"] <= hi + 1
    # replay rows carry the accounting fields as args
    assert all("frontier" in e["args"] for e in replay)


def test_trace_shared_sink_no_duplication_on_rereport(tmp_path):
    """Repeated report() re-synthesizes (owner-keyed) instead of
    duplicating replay spans — forests call report() again after OOB."""
    sink = trace_mod.TraceSink(str(tmp_path / "s.json"))
    obs = BuildObserver(timing=False)
    obs.trace_to(sink)
    with obs.span("split"):
        pass
    obs.level(level=0, frontier=1, psum_bytes=10, seconds=0.001)
    obs.level(level=1, frontier=2, psum_bytes=20, seconds=None)
    obs.round(round=0, trees=1)
    n1 = len(sink.events())
    obs.report()
    n2 = len(sink.events())
    assert n2 > n1  # synthesis added replay spans
    obs.report()
    assert len(sink.events()) == n2  # replaced, not duplicated
    path = sink.write()
    assert trace_mod.validate_trace(json.load(open(path))) == []


def test_trace_env_dir_ambient(tmp_path, monkeypatch):
    """MPITREE_TPU_TRACE_DIR traces estimator-internal observers with no
    API change (the bench/watcher capture hook)."""
    monkeypatch.setenv(trace_mod.TRACE_DIR_ENV, str(tmp_path))
    DecisionTreeClassifier(max_depth=3, max_bins=16).fit(*_cls_data())
    files = list(tmp_path.glob("trace_*.json"))
    assert files
    assert trace_mod.validate_trace(json.load(open(files[0]))) == []


def test_trace_unwritable_sink_degrades(tmp_path):
    """An unwritable trace path must never abort a fit: typed
    trace_failed event, fit completes (the checkpoint-sink contract)."""
    blocker = tmp_path / "blocker"
    blocker.write_text("")
    clf = DecisionTreeClassifier(max_depth=3, max_bins=16).fit(
        *_cls_data(), trace_to=blocker / "sub" / "t.json"
    )
    assert hasattr(clf, "tree_")
    assert any(
        e["kind"] == "trace_failed" for e in clf.fit_report_["events"]
    )


def test_merge_trace_files(tmp_path):
    import time

    s1 = trace_mod.TraceSink(str(tmp_path / "a.json"))
    s1.complete("t", "x", time.perf_counter(), 0.001)
    s1.write()
    s2 = trace_mod.TraceSink(str(tmp_path / "b.json"))
    s2.instant("t", "y")
    s2.write()
    (tmp_path / "broken.json").write_text("{nope")
    out = trace_mod.merge_trace_files(
        [str(tmp_path / p) for p in ("a.json", "b.json", "broken.json")],
        str(tmp_path / "merged.json"),
    )
    merged = json.load(open(out))
    assert trace_mod.validate_trace(merged) == []
    # each source got its own pid
    pids = {e["pid"] for e in merged["traceEvents"]}
    assert pids == {1, 2}


def test_dump_report_makedirs_and_degrades(tmp_path):
    clf = DecisionTreeClassifier(max_depth=2, max_bins=16).fit(*_cls_data())
    # parent dirs created up front
    dest = tmp_path / "deep" / "nested" / "report.json"
    assert clf.dump_report(dest) == str(dest)
    assert json.load(open(dest)) == clf.fit_report_
    # unwritable: degrade with a typed event, not an OSError
    blocker = tmp_path / "blocker"
    blocker.write_text("")
    with pytest.warns(UserWarning, match="dump_report sink unwritable"):
        out = clf.dump_report(blocker / "sub" / "r.json")
    assert out is None
    assert any(
        e["kind"] == "trace_failed" for e in clf.fit_report_["events"]
    )


# ---------------------------------------------------------------------------
# metrics: histogram quantile oracle + exposition
# ---------------------------------------------------------------------------

def test_histogram_quantile_oracle_vs_numpy():
    """Log-bucketed quantiles track numpy.percentile within the bucket
    bound (~9% geometric-midpoint error; 12% asserted for slack) on a
    latency-shaped lognormal population."""
    rng = np.random.default_rng(0)
    xs = np.exp(rng.normal(-6.0, 1.3, 20000))
    reg = metrics_mod.MetricsRegistry()
    h = reg.histogram("lat")
    for x in xs:
        h.observe(float(x))
    for q in (0.5, 0.9, 0.95, 0.99):
        est = h.quantile(q)
        ref = float(np.percentile(xs, q * 100))
        assert abs(est - ref) / ref < 0.12, (q, est, ref)
    # extremes clamp to observed min/max
    assert h.quantile(0.0) == pytest.approx(float(xs.min()))
    assert h.quantile(1.0) == pytest.approx(float(xs.max()))


def test_histogram_small_population_and_zero_bucket():
    reg = metrics_mod.MetricsRegistry()
    h = reg.histogram("h")
    assert h.quantile(0.5) is None
    h.observe(0.0)
    h.observe(5.0)
    assert h.quantile(0.0) == 0.0
    assert h.quantile(1.0) == 5.0


def test_metrics_text_exposition_format():
    reg = metrics_mod.MetricsRegistry()
    reg.counter("req_total", kind="a").inc(3)
    reg.gauge("depth").set(2)
    h = reg.histogram("lat_seconds", bucket="64")
    for v in (0.001, 0.002, 0.004, 0.2):
        h.observe(v)
    text = reg.metrics_text(extra_labels={"model": "m"})
    lines = text.splitlines()
    assert '# TYPE req_total counter' in lines
    assert 'req_total{kind="a",model="m"} 3' in lines
    assert 'depth{model="m"} 2' in lines
    # histogram: cumulative buckets ending at +Inf, plus _sum/_count
    bkt = [ln for ln in lines if ln.startswith("lat_seconds_bucket")]
    assert bkt[-1].startswith('lat_seconds_bucket{bucket="64",le="+Inf"')
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in bkt]
    assert counts == sorted(counts) and counts[-1] == 4
    assert 'lat_seconds_count{bucket="64",model="m"} 4' in lines
    # type conflicts are refused, not silently merged
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("req_total")


def test_histogram_exemplar_reservoir(monkeypatch):
    """ISSUE 18 satellite: MPITREE_TPU_METRICS_EXEMPLARS=K keeps the K
    most recent raw values per bucket, surfaced as exposition comments;
    off (default) allocates nothing and changes no output shape."""
    # off: no reservoir, no snapshot key, no comment lines
    reg = metrics_mod.MetricsRegistry()
    h = reg.histogram("lat_seconds")
    h.observe(1.05)
    assert h._exemplars is None
    assert "exemplars" not in h.snapshot()
    assert "# exemplars" not in reg.metrics_text()

    monkeypatch.setenv("MPITREE_TPU_METRICS_EXEMPLARS", "2")
    reg2 = metrics_mod.MetricsRegistry()
    h2 = reg2.histogram("lat_seconds", bucket="64")
    # 1.05/1.1/1.15 share the (1, 1.19] bucket: the K=2 ring keeps the
    # two most recent; 5.0 and the zero bucket get their own rings
    for v in (1.05, 1.1, 1.15, 5.0, 0.0):
        h2.observe(v)
    ex = h2.snapshot()["exemplars"]
    rings = sorted(v for ring in ex.values() for v in ring)
    assert rings == [0.0, 1.1, 1.15, 5.0]  # 1.05 evicted, ring bounded
    text = reg2.metrics_text()
    assert "# exemplars lat_seconds_bucket" in text
    # comment lines never break the exposition grammar
    for ln in text.splitlines():
        if not ln.startswith("#"):
            assert len(ln.rsplit(" ", 1)) == 2


def test_counter_monotonic_and_mirror():
    reg = metrics_mod.MetricsRegistry()
    c = reg.counter("c_total")
    c.inc(2)
    with pytest.raises(ValueError):
        c.inc(-1)
    c.set_total(1)  # mirror can never run a counter backwards
    assert c.value == 2
    c.set_total(7)
    assert c.value == 7


# ---------------------------------------------------------------------------
# serving: latency block + request-path pins with metrics on
# ---------------------------------------------------------------------------

def test_serving_latency_quantiles_and_zero_compile_with_metrics(
    monkeypatch,
):
    """serve_report_ exposes per-bucket p50/p95/p99 from the log-bucketed
    histograms, and the metrics-on request path still pins ZERO new
    compile cache-keys and ZERO explicit device_put transfers."""
    from mpitree_tpu.boosting.gradient_boosting import (
        GradientBoostingClassifier,
    )
    from mpitree_tpu.serving.model import compile_model

    X, y = _cls_data(300)
    gb = GradientBoostingClassifier(
        max_iter=3, max_depth=3, random_state=0
    ).fit(X, y)
    model = compile_model(gb, buckets=(1, 16, 64))
    model.warmup()
    n0 = REGISTRY.count("serving_traverse")
    calls = []
    real = jax.device_put
    monkeypatch.setattr(
        jax, "device_put", lambda *a, **k: calls.append(a) or real(*a, **k)
    )
    for n in (1, 3, 16, 40, 64, 100):
        model.predict(X[:n] if n <= len(X) else X)
    assert REGISTRY.count("serving_traverse") == n0
    assert calls == []  # metrics observation is pure host work
    rep = model.serve_report_
    lat = rep["latency"]
    assert lat["requests"] >= 6
    for row in lat["buckets"].values():
        assert row["count"] > 0
        assert 0 < row["p50_ms"] <= row["p95_ms"] <= row["p99_ms"]
    assert lat["rows_per_s_sustained"] > 0
    # warmup stays OFF the latency clock (cold compiles would poison p99)
    assert sum(r["count"] for r in lat["buckets"].values()) == 6
    # the 100-row request chunk-loops past the largest bucket: its loop
    # total lands in 'oversize', not the 64 bucket's p99
    assert lat["buckets"]["oversize"]["count"] == 1
    # sustained rows/s divides CLOCKED rows only — warmup's 81 padded
    # rows are served but never timed
    assert lat["rows_latency_clocked"] == 1 + 3 + 16 + 40 + 64 + 100
    assert lat["rows"] > lat["rows_latency_clocked"]  # warmup counted
    text = model.metrics_text()
    assert "mpitree_serving_requests_total" in text
    assert "mpitree_serving_request_seconds_bucket" in text


def test_stream_stage_queue_depth_gauge():
    from mpitree_tpu.models.forest import RandomForestRegressor
    from mpitree_tpu.serving.model import compile_model
    from mpitree_tpu.serving.staging import StreamStage

    X, y = _cls_data(200)
    fr = RandomForestRegressor(
        n_estimators=3, max_depth=3, random_state=0
    ).fit(X, y.astype(np.float64))
    model = compile_model(fr, buckets=(1, 64))
    stage = StreamStage(model, depth=2)
    stage.submit(X[:8])
    stage.submit(X[8:16])
    assert model.metrics.gauge("mpitree_serving_inflight").value == 2
    stage.drain()
    assert model.metrics.gauge("mpitree_serving_inflight").value == 0
    assert (
        model.metrics.counter(
            "mpitree_serving_staged_batches_total"
        ).value == 2
    )


def test_registry_metrics_text_aggregates_slots():
    """Two published slots merge into ONE exposition with a single
    # TYPE line per family — the Prometheus parser rejects duplicates,
    so naive per-slot concatenation would fail the whole scrape."""
    from mpitree_tpu.models.forest import RandomForestClassifier
    from mpitree_tpu.serving.registry import ModelRegistry

    X, y = _cls_data(200)
    f1 = RandomForestClassifier(
        n_estimators=3, max_depth=3, random_state=0
    ).fit(X, y)
    f2 = RandomForestClassifier(
        n_estimators=3, max_depth=3, random_state=1
    ).fit(X, y)
    reg = ModelRegistry(buckets=(1, 16))
    reg.publish("slot_a", f1)
    reg.publish("slot_b", f2)
    reg.predict("slot_a", X[:4])
    text = reg.metrics_text()
    assert 'mpitree_registry_publish_total{model="slot_a"} 1' in text
    assert 'model="slot_a"' in text and 'model="slot_b"' in text
    assert "mpitree_serving_requests_total" in text
    type_lines = [
        ln for ln in text.splitlines() if ln.startswith("# TYPE ")
    ]
    assert len(type_lines) == len(set(type_lines))
    # samples group under their one TYPE header: every non-comment line
    # between a header and the next belongs to that family
    fam = None
    for ln in text.splitlines():
        if ln.startswith("# TYPE "):
            fam = ln.split()[2]
        else:
            assert fam is not None and ln.startswith(fam)


# ---------------------------------------------------------------------------
# cold-compile attribution + the collective wire ledger
# ---------------------------------------------------------------------------

def test_compile_attribution_records_seconds():
    obs = BuildObserver(timing=False)
    entry = f"attr_test_{os.getpid()}_{id(obs)}"
    fresh = obs.compile_note(entry, ("k",))
    assert fresh
    before = REGISTRY.seconds(entry)
    with obs.compile_attribution(entry, fresh):
        pass
    assert REGISTRY.seconds(entry) >= before
    assert "seconds" in obs.record.compile[entry]
    # warm keys attribute nothing
    warm = obs.compile_note(entry, ("k",))
    assert not warm
    s0 = obs.record.compile[entry]["seconds"]
    with obs.compile_attribution(entry, warm):
        pass
    assert obs.record.compile[entry]["seconds"] == s0


def test_fit_report_carries_compile_seconds(monkeypatch):
    """A fit whose entry points lower fresh attributes cold-dispatch wall
    in fit_report_['compile'][entry]['seconds'] (ROADMAP follow-up 1)."""
    monkeypatch.setenv("MPITREE_TPU_ENGINE", "levelwise")
    # a never-seen max_bins forces fresh split/counts/update keys even
    # when earlier tests warmed the common configurations
    clf = DecisionTreeClassifier(
        max_depth=3, max_bins=23, backend="cpu"
    ).fit(*_cls_data())
    comp = clf.fit_report_["compile"]
    fresh_entries = [k for k, v in comp.items() if v.get("new")]
    assert fresh_entries
    assert any(v.get("seconds", 0) > 0 for v in comp.values())


def test_wire_estimate_math_and_digest_keys():
    coll = {"split_hist_psum": {"calls": 4, "bytes": 1000},
            "counts_psum": {"calls": 1, "bytes": 24}}
    w = wire_estimate(coll, 8)
    assert w["bytes"] == 1024
    assert w["wire_bytes"] == 1024 * 7
    assert w["wire_bytes_per_shard"] == 1024 * 7 // 8
    assert w["sites"]["split_hist_psum"]["wire_bytes"] == 7000
    # one device: no ICI hop, honestly zero
    w1 = wire_estimate(coll, 1)
    assert w1["wire_bytes"] == 0 and w1["wire_bytes_per_shard"] == 0
    # report + digest carry the ledger
    obs = BuildObserver(timing=False)
    obs.record.mesh = {"platform": "cpu", "n_devices": 8, "axes": {}}
    obs.collective("split_hist_psum", calls=2, nbytes=512)
    rep = obs.report()
    assert rep["wire"]["wire_bytes"] == 512 * 7
    d = digest(rep)
    assert d["wire_bytes"] == 512 * 7
    assert d["wire_shard_bytes"] == 512 * 7 // 8


def test_fit_report_wire_block_present():
    clf = DecisionTreeClassifier(
        max_depth=3, max_bins=16, backend="cpu"
    ).fit(*_cls_data())
    wire = clf.fit_report_["wire"]
    assert wire["n_shards"] == clf.fit_report_["mesh"]["n_devices"]
    assert set(wire["sites"]) == set(clf.fit_report_["collectives"])
    if wire["n_shards"] > 1:
        assert wire["wire_bytes"] > 0
