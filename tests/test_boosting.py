"""Boosting subsystem: conformance, parity, and mesh-identity contracts.

The acceptance surface of the `mpitree_tpu.boosting` subsystem:

- sklearn estimator mechanics (clone / get_params / set_params round-trip,
  NotFittedError before fit);
- ``staged_predict`` whose training loss is monotone non-increasing on a
  toy set (squared error + shrinkage can only descend);
- logistic-loss parity with ``sklearn.ensemble.HistGradientBoosting
  Classifier`` on breast-cancer at matched depth/learning-rate;
- serialize round-trip through ``save_model``/``load_model``;
- the mesh-identity contract: a CPU 8-device data-sharded fit is
  bit-identical to the single-device fit (the f64 (g, h) accumulation
  closure, ``core/builder.resolve_gbdt_x64``);
- the Newton sweep against a brute-force numpy oracle.
"""

import os
import warnings

import numpy as np
import pytest
from sklearn.base import clone
from sklearn.datasets import load_breast_cancer, load_iris
from sklearn.exceptions import NotFittedError
from sklearn.model_selection import train_test_split

from mpitree_tpu import (
    GradientBoostingClassifier,
    GradientBoostingRegressor,
    load_model,
    save_model,
)


@pytest.fixture(scope="module")
def cancer_split():
    X, y = load_breast_cancer(return_X_y=True)
    return train_test_split(X, y, test_size=0.25, random_state=0)


@pytest.fixture(scope="module")
def toy_regression():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(400, 5)).astype(np.float32)
    y = X[:, 0] * 2.0 + np.sin(3.0 * X[:, 1]) + 0.1 * rng.normal(size=400)
    return X, y


# ---------------------------------------------------------------------------
# sklearn estimator mechanics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "est",
    [
        GradientBoostingClassifier(max_iter=7, max_depth=3, reg_lambda=0.5,
                                   subsample=0.9, random_state=3),
        GradientBoostingRegressor(max_iter=7, max_depth=3,
                                  min_child_weight=0.1, random_state=3),
    ],
    ids=lambda e: type(e).__name__,
)
def test_clone_and_params_round_trip(est):
    c = clone(est)
    assert c.get_params() == est.get_params()
    fresh = type(est)()
    fresh.set_params(**est.get_params())
    assert fresh.get_params() == est.get_params()


def test_min_samples_leaf_shared_grammar(toy_regression):
    """Boosting resolves min_samples_leaf through the same validated
    grammar as every other estimator: fractional = ceil(frac * n) rows,
    invalid values raise (never silently truncate to 0)."""
    X, y = toy_regression
    with pytest.raises(ValueError, match="min_samples_leaf"):
        GradientBoostingRegressor(min_samples_leaf=0).fit(X, y)
    with pytest.raises(ValueError, match="min_samples_leaf"):
        GradientBoostingRegressor(min_samples_leaf=1.5).fit(X, y)
    # a large fractional floor really constrains growth
    loose = GradientBoostingRegressor(
        max_iter=2, max_depth=5, min_samples_leaf=1
    ).fit(X, y)
    tight = GradientBoostingRegressor(
        max_iter=2, max_depth=5, min_samples_leaf=0.25
    ).fit(X, y)
    assert sum(t.n_nodes for t in tight.trees_) < sum(
        t.n_nodes for t in loose.trees_
    )


def test_not_fitted_raises():
    with pytest.raises(NotFittedError):
        GradientBoostingRegressor().predict(np.zeros((3, 2)))


def test_param_validation_errors():
    X = np.zeros((10, 2))
    y = np.arange(10) % 2
    with pytest.raises(ValueError, match="learning_rate"):
        GradientBoostingClassifier(learning_rate=0.0).fit(X, y)
    with pytest.raises(ValueError, match="subsample"):
        GradientBoostingClassifier(subsample=1.5).fit(X, y)
    with pytest.raises(ValueError, match="reg_lambda"):
        GradientBoostingClassifier(reg_lambda=-1.0).fit(X, y)
    with pytest.raises(ValueError, match="loss"):
        GradientBoostingRegressor(loss="absolute_error").fit(X, y.astype(float))
    with pytest.raises(ValueError, match="classes"):
        GradientBoostingClassifier(max_iter=2).fit(X, np.zeros(10))


# ---------------------------------------------------------------------------
# staged predictions
# ---------------------------------------------------------------------------

def test_staged_predict_monotone_train_loss(toy_regression):
    X, y = toy_regression
    reg = GradientBoostingRegressor(max_iter=25, max_depth=3).fit(X, y)
    losses = [
        float(np.mean((y - p) ** 2)) for p in reg.staged_predict(X)
    ]
    assert len(losses) == reg.n_iter_
    assert all(b <= a + 1e-12 for a, b in zip(losses, losses[1:])), losses
    # the recorded train curve agrees: scores are negative losses
    assert len(reg.train_score_) == reg.n_iter_ + 1  # + baseline entry
    assert reg.train_score_[-1] > reg.train_score_[0]


def test_staged_predict_proba_final_stage_matches(cancer_split):
    Xtr, Xte, ytr, _ = cancer_split
    clf = GradientBoostingClassifier(max_iter=8, max_depth=3).fit(Xtr, ytr)
    stages = list(clf.staged_predict_proba(Xte))
    assert len(stages) == clf.n_iter_
    np.testing.assert_allclose(stages[-1], clf.predict_proba(Xte))
    preds = list(clf.staged_predict(Xte))
    assert np.array_equal(preds[-1], clf.predict(Xte))


# ---------------------------------------------------------------------------
# accuracy parity with sklearn
# ---------------------------------------------------------------------------

def test_logistic_parity_with_sklearn_hist_gbdt(cancer_split):
    """Acceptance: max_iter=100 on breast-cancer within 0.01 accuracy of
    sklearn's HistGradientBoostingClassifier at matched depth/lr."""
    from sklearn.ensemble import HistGradientBoostingClassifier

    Xtr, Xte, ytr, yte = cancer_split
    sk = HistGradientBoostingClassifier(
        max_iter=100, max_depth=4, learning_rate=0.1, early_stopping=False,
        min_samples_leaf=20,
    ).fit(Xtr, ytr)
    ours = GradientBoostingClassifier(
        max_iter=100, max_depth=4, learning_rate=0.1, min_samples_leaf=20,
    ).fit(Xtr, ytr)
    acc_sk = float((sk.predict(Xte) == yte).mean())
    acc_us = float((ours.predict(Xte) == yte).mean())
    assert acc_us >= acc_sk - 0.01, (acc_us, acc_sk)


def test_multiclass_softmax_one_tree_per_class():
    X, y = load_iris(return_X_y=True)
    clf = GradientBoostingClassifier(
        max_iter=12, max_depth=3, random_state=0
    ).fit(X, y)
    assert clf.n_trees_per_iteration_ == 3
    assert len(clf.trees_) == 3 * clf.n_iter_
    assert (clf.predict(X) == y).mean() > 0.93
    proba = clf.predict_proba(X)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-12)


def test_regression_quality(toy_regression):
    X, y = toy_regression
    reg = GradientBoostingRegressor(max_iter=60, max_depth=4).fit(X, y)
    assert reg.score(X, y) > 0.9


# ---------------------------------------------------------------------------
# serialize round-trip
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["clf", "reg"])
def test_serialize_round_trip(tmp_path, cancer_split, toy_regression, kind):
    Xtr, Xte, ytr, _ = cancer_split
    if kind == "clf":
        est = GradientBoostingClassifier(
            max_iter=6, max_depth=3, random_state=1
        ).fit(Xtr, ytr)
    else:
        Xtr, _ = toy_regression[0], None
        ytr = toy_regression[1]
        Xte = Xtr
        est = GradientBoostingRegressor(
            max_iter=6, max_depth=3, random_state=1
        ).fit(Xtr, ytr)
    path = tmp_path / f"gb_{kind}.npz"
    save_model(est, path)
    loaded = load_model(path)
    assert loaded.n_iter_ == est.n_iter_
    assert loaded.n_trees_per_iteration_ == est.n_trees_per_iteration_
    np.testing.assert_array_equal(loaded._baseline_raw, est._baseline_raw)
    if kind == "clf":
        np.testing.assert_allclose(
            loaded.predict_proba(Xte), est.predict_proba(Xte)
        )
    np.testing.assert_array_equal(loaded.predict(Xte), est.predict(Xte))


# ---------------------------------------------------------------------------
# mesh identity: sharded fit == single-device fit, bit for bit
# ---------------------------------------------------------------------------

def _trees_identical(a, b):
    for ta, tb in zip(a, b):
        for f in ("feature", "left", "right", "n_node_samples"):
            if not np.array_equal(getattr(ta, f), getattr(tb, f)):
                return False
        if not np.array_equal(ta.threshold, tb.threshold, equal_nan=True):
            return False
        # count AND impurity: every serialized per-node number must be
        # mesh-invariant (the f64 host refit owns them all).
        if not np.array_equal(ta.count, tb.count):
            return False
        if not np.array_equal(ta.impurity, tb.impurity):
            return False
    return True


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_fit_bit_identical(cancer_split, n_devices):
    Xtr, _, ytr, _ = cancer_split
    kw = dict(max_iter=10, max_depth=4, subsample=0.8, random_state=0)
    one = GradientBoostingClassifier(n_devices=1, **kw).fit(Xtr, ytr)
    many = GradientBoostingClassifier(n_devices=n_devices, **kw).fit(Xtr, ytr)
    assert len(one.trees_) == len(many.trees_)
    assert _trees_identical(one.trees_, many.trees_)


def test_sharded_regressor_bit_identical(toy_regression):
    X, y = toy_regression
    kw = dict(max_iter=8, max_depth=3, random_state=0)
    one = GradientBoostingRegressor(n_devices=1, **kw).fit(X, y)
    many = GradientBoostingRegressor(n_devices=8, **kw).fit(X, y)
    assert _trees_identical(one.trees_, many.trees_)
    np.testing.assert_array_equal(one.predict(X), many.predict(X))


def test_same_seed_same_ensemble(toy_regression):
    X, y = toy_regression
    kw = dict(max_iter=5, max_depth=3, subsample=0.6, random_state=7)
    a = GradientBoostingRegressor(**kw).fit(X, y)
    b = GradientBoostingRegressor(**kw).fit(X, y)
    assert _trees_identical(a.trees_, b.trees_)


# ---------------------------------------------------------------------------
# early stopping / subsampling / regularization behavior
# ---------------------------------------------------------------------------

def test_early_stopping_stops_and_records():
    X, y = load_iris(return_X_y=True)
    clf = GradientBoostingClassifier(
        max_iter=200, max_depth=3, early_stopping=True, n_iter_no_change=5,
        random_state=0,
    ).fit(X, y)
    assert clf.n_iter_ < 200
    assert clf.validation_score_ is not None
    assert len(clf.validation_score_) == clf.n_iter_ + 1
    assert len(clf.trees_) == clf.n_iter_ * clf.n_trees_per_iteration_


def test_row_subsample_mask_properties():
    from mpitree_tpu.ops.sampling import row_subsample_mask

    m1 = row_subsample_mask(3, 0, 100_000, 0.7)
    m2 = row_subsample_mask(3, 0, 100_000, 0.7)
    m3 = row_subsample_mask(3, 1, 100_000, 0.7)
    assert np.array_equal(m1, m2)  # pure function of (seed, round, row)
    assert not np.array_equal(m1, m3)  # rounds draw differently
    assert abs(m1.mean() - 0.7) < 0.01  # Bernoulli(fraction)
    assert row_subsample_mask(0, 0, 10, 1.0).all()
    with pytest.raises(ValueError):
        row_subsample_mask(0, 0, 10, 0.0)


def test_reg_lambda_shrinks_leaf_values(toy_regression):
    X, y = toy_regression
    kw = dict(max_iter=3, max_depth=3, random_state=0)
    small = GradientBoostingRegressor(reg_lambda=0.0, **kw).fit(X, y)
    big = GradientBoostingRegressor(reg_lambda=100.0, **kw).fit(X, y)
    mag = lambda m: float(np.mean([np.abs(t.count[:, 0]).max()  # noqa: E731
                                   for t in m.trees_]))
    assert mag(big) < mag(small)


def test_min_split_gain_prunes_growth(toy_regression):
    X, y = toy_regression
    kw = dict(max_iter=3, max_depth=5, random_state=0)
    free = GradientBoostingRegressor(min_split_gain=0.0, **kw).fit(X, y)
    gated = GradientBoostingRegressor(min_split_gain=1e9, **kw).fit(X, y)
    assert sum(t.n_nodes for t in gated.trees_) < sum(
        t.n_nodes for t in free.trees_
    )
    # an impossible gain threshold leaves every tree a stump
    assert all(t.n_nodes == 1 for t in gated.trees_)


def test_gbdt_rejects_fused_engine_but_builds_on_feature_mesh(toy_regression):
    """The fused-engine refusal stands; the old feature-mesh refusal is
    GONE (ISSUE 10): a Newton round on a (data, feature) mesh now sweeps
    per-shard (g, h) slabs and merges winners through select_global,
    bit-identical to the 1-D mesh build."""
    from mpitree_tpu.core.builder import BuildConfig, build_tree
    from mpitree_tpu.ops.binning import bin_dataset
    from mpitree_tpu.parallel import mesh as mesh_lib

    X, y = toy_regression
    binned = bin_dataset(X[:64], max_bins=16)
    g = np.ascontiguousarray(y[:64], np.float32)
    h = np.ones(64, np.float32)
    with pytest.raises(ValueError, match="fused"):
        build_tree(
            binned, g, config=BuildConfig(task="gbdt", engine="fused",
                                          max_depth=2),
            mesh=mesh_lib.resolve_mesh(n_devices=1), sample_weight=h,
        )
    cfg = BuildConfig(task="gbdt", max_depth=2)
    ref = build_tree(
        binned, g, config=cfg,
        mesh=mesh_lib.resolve_mesh(n_devices=8), sample_weight=h,
    )
    two_d = build_tree(
        binned, g, config=cfg,
        mesh=mesh_lib.resolve_mesh(n_devices=(4, 2)), sample_weight=h,
    )
    np.testing.assert_array_equal(ref.feature, two_d.feature)
    np.testing.assert_array_equal(ref.threshold, two_d.threshold)


# ---------------------------------------------------------------------------
# Newton sweep vs a brute-force numpy oracle
# ---------------------------------------------------------------------------

def test_best_split_newton_matches_bruteforce():
    import jax.numpy as jnp

    from mpitree_tpu.ops.impurity import best_split_newton

    rng = np.random.default_rng(1)
    K, F, B = 3, 4, 8
    cnt = rng.integers(0, 5, size=(K, F, B)).astype(np.float32)
    g = rng.normal(size=(K, F, B)).astype(np.float32) * (cnt > 0)
    h = (rng.uniform(0.1, 1.0, size=(K, F, B)).astype(np.float32)) * (cnt > 0)
    hist = np.stack([cnt, g, h], axis=2)  # (K, F, 3, B)
    cand = np.ones((F, B), bool)
    cand[:, -1] = False
    lam = 0.3
    dec = best_split_newton(
        jnp.asarray(hist), jnp.asarray(cand),
        reg_lambda=jnp.float32(lam), min_child_weight=jnp.float32(0.0),
        min_samples_leaf=jnp.float32(0.0),
    )

    def score(gs, hs):
        return gs * gs / (hs + lam)

    for k in range(K):
        best = (np.inf, -1, -1)
        for f in range(F):
            cl = np.cumsum(cnt[k, f])
            gl = np.cumsum(g[k, f])
            hl = np.cumsum(h[k, f])
            for b in range(B):
                if not cand[f, b]:
                    continue
                cr = cl[-1] - cl[b]
                if cl[b] <= 0 or cr <= 0:
                    continue
                cost = -0.5 * (
                    score(gl[b], hl[b])
                    + score(gl[-1] - gl[b], hl[-1] - hl[b])
                )
                if cost < best[0]:  # strict < = first-min, like the sweep
                    best = (cost, f, b)
        if best[1] >= 0:
            assert int(dec.feature[k]) == best[1], k
            assert int(dec.bin[k]) == best[2], k
        else:
            assert np.isinf(float(dec.cost[k]))


def test_grad_hess_histogram_totals():
    import jax.numpy as jnp

    from mpitree_tpu.ops.histogram import grad_hess_histogram

    rng = np.random.default_rng(2)
    N, F, B, S = 200, 3, 6, 4
    xb = rng.integers(0, B, size=(N, F)).astype(np.int32)
    g = rng.normal(size=N).astype(np.float32)
    h = rng.uniform(0.1, 1.0, size=N).astype(np.float32)
    h[::5] = 0.0  # subsample-excluded rows
    nid = rng.integers(-1, S, size=N).astype(np.int32)
    hist = np.asarray(grad_hess_histogram(
        jnp.asarray(xb), jnp.asarray(g), jnp.asarray(h), jnp.asarray(nid),
        jnp.int32(0), n_slots=S, n_bins=B,
    ))
    assert hist.shape == (S, F, 3, B)
    live = (nid >= 0) & (h > 0)
    for s in range(S):
        rows = live & (nid == s)
        np.testing.assert_allclose(
            hist[s, 0, 0].sum(), rows.sum(), rtol=1e-6
        )
        np.testing.assert_allclose(
            hist[s, 0, 1].sum(), g[rows].sum(), rtol=1e-4, atol=1e-4
        )
        np.testing.assert_allclose(
            hist[s, 0, 2].sum(), h[rows].sum(), rtol=1e-4, atol=1e-4
        )


def test_f32_fallback_env_still_fits(cancer_split, monkeypatch):
    """MPITREE_TPU_GBDT_X64=0 (the f32 escape hatch) stays functional —
    the accuracy story cannot silently depend on the f64 closure."""
    monkeypatch.setenv("MPITREE_TPU_GBDT_X64", "0")
    Xtr, Xte, ytr, yte = cancer_split
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        clf = GradientBoostingClassifier(
            max_iter=10, max_depth=3, random_state=0
        ).fit(Xtr, ytr)
    assert float((clf.predict(Xte) == yte).mean()) > 0.9
    assert os.environ["MPITREE_TPU_GBDT_X64"] == "0"
