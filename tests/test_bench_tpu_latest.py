"""latest_line merges TPU capture lines per-section, newest-wins.

The tunnel drops mid-run, so one BENCH_TPU.jsonl line can carry north_star
while a later watcher retry carries only the sections that hung the first
time. bench.py's tpu_last_known embed must see the union, not just the
newest (or the newest fully-``ok``) line.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from bench_tpu import latest_line  # noqa: E402


def _write(tmp_path, records):
    p = tmp_path / "BENCH_TPU.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in records))
    return str(p)


def test_missing_file_is_none(tmp_path):
    assert latest_line(str(tmp_path / "nope.jsonl")) is None


def test_cpu_fallback_lines_contribute_nothing(tmp_path):
    p = _write(tmp_path, [
        {"ts": "t1", "platform_probe": "cpu",
         "north_star": {"warm_s": 99.0}, "ok": True},
    ])
    assert latest_line(p) is None


def test_partial_ok_false_line_still_counts(tmp_path):
    # The real round-4 shape: north_star + engine_fused succeeded, three
    # sections died when the tunnel hung -> ok=false. The data is genuine.
    p = _write(tmp_path, [
        {"ts": "t1", "git": "abc", "platform_probe": "tpu",
         "dataset": "covtype_like", "depth": 20, "refine_depth": 7,
         "north_star": {"warm_s": 20.5}, "engine_fused": {"warm_s": 17.5},
         "errors": {"engine_levelwise": "rc=-15"}, "ok": False},
    ])
    got = latest_line(p)
    assert got is not None
    assert got["north_star"]["warm_s"] == 20.5
    assert got["engine_fused"]["warm_s"] == 17.5
    assert got["depth"] == 20


def test_sections_merge_newest_wins(tmp_path):
    p = _write(tmp_path, [
        {"ts": "t1", "git": "abc", "platform_probe": "tpu",
         "north_star": {"warm_s": 20.5}, "engine_fused": {"warm_s": 17.5},
         "ok": False},
        # all-failed retry: contributes nothing, must not reset anything
        {"ts": "t2", "git": "abc", "platform_probe": "tpu",
         "errors": {"forest": "rc=-15"}, "ok": False},
        # single-section retry succeeds later
        {"ts": "t3", "git": "def", "platform_probe": "tpu",
         "engine_levelwise": {"warm_s": 30.0}, "ok": True},
        # re-measured north_star supersedes the older one
        {"ts": "t4", "git": "def", "platform_probe": "tpu",
         "north_star": {"warm_s": 19.0}, "ok": True},
    ])
    got = latest_line(p)
    assert got["north_star"]["warm_s"] == 19.0        # t4 wins over t1
    assert got["engine_fused"]["warm_s"] == 17.5      # only t1 had it
    assert got["engine_levelwise"]["warm_s"] == 30.0  # from t3
    assert got["ts"] == "t4" and got["git"] == "def"
    assert [m["ts"] for m in got["merged_from"]] == ["t1", "t3", "t4"]


FULL = {"dataset": "covtype_like (531012x54)", "depth": 20,
        "refine_depth": 7}
SMOKE = {"dataset": "covtype_like (100000x54)", "depth": 20,
         "refine_depth": 7}


def test_smoke_run_never_fuses_with_full_workload(tmp_path):
    # An older --rows smoke line must not contribute sections to (or be
    # mislabeled as) the full-workload merge.
    p = _write(tmp_path, [
        {"ts": "t1", "platform_probe": "tpu", **SMOKE,
         "north_star": {"warm_s": 4.0}, "engine_fused": {"warm_s": 3.0}},
        {"ts": "t2", "platform_probe": "tpu", **FULL,
         "north_star": {"warm_s": 20.5}},
    ])
    got = latest_line(p)
    assert got["dataset"] == FULL["dataset"]
    assert got["north_star"]["warm_s"] == 20.5
    assert "engine_fused" not in got  # smoke section stays out
    assert [m["ts"] for m in got["merged_from"]] == ["t2"]


def test_full_only_ignores_smoke_lines_entirely(tmp_path):
    # The watcher's done-check: a newest smoke line must neither satisfy a
    # section nor re-key the merge away from the full workload.
    p = _write(tmp_path, [
        {"ts": "t1", "platform_probe": "tpu", **FULL, "rows_cap": None,
         "north_star": {"warm_s": 20.5}},
        {"ts": "t2", "platform_probe": "tpu", **SMOKE, "rows_cap": 100000,
         "north_star": {"warm_s": 4.0}, "hist_tput": {"x": 1}},
    ])
    got = latest_line(p, full_only=True)
    assert got["dataset"] == FULL["dataset"]
    assert got["north_star"]["warm_s"] == 20.5
    assert "hist_tput" not in got
    # records predating the rows_cap field count as full-workload
    q = _write(tmp_path, [
        {"ts": "t1", "platform_probe": "tpu", **FULL,
         "north_star": {"warm_s": 20.5}},
    ])
    assert latest_line(q, full_only=True)["north_star"]["warm_s"] == 20.5


def test_newest_smoke_run_defines_its_own_group(tmp_path):
    # If the newest genuine line IS a smoke run, the merge is that smoke
    # run, honestly labeled — never full numbers stamped with smoke ts.
    p = _write(tmp_path, [
        {"ts": "t1", "platform_probe": "tpu", **FULL,
         "north_star": {"warm_s": 20.5}},
        {"ts": "t2", "platform_probe": "tpu", **SMOKE,
         "north_star": {"warm_s": 4.0}},
    ])
    got = latest_line(p)
    assert got["dataset"] == SMOKE["dataset"]
    assert got["north_star"]["warm_s"] == 4.0
