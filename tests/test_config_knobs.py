"""Knob-registry contract tests: typed reads, the single-read-path AST
pin, and the README drift gate.

The registry (``mpitree_tpu/config/knobs.py``) is the package's ONE
``os.environ`` read path for ``MPITREE_TPU_*`` knobs. graftlint GL10
enforces that on every lint run; the AST pin here enforces it
independently of the linter, so disabling graftlint cannot silently
reopen scattered ``getenv`` calls. The registry module itself is
stdlib-only, so everything except the CLI subprocess tests runs without
jax.
"""

import ast
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "mpitree_tpu"
REGISTRY_FILE = PACKAGE / "config" / "knobs.py"

from mpitree_tpu.config import knobs  # noqa: E402


# ---------------------------------------------------------------------------
# registry hygiene


def test_registry_names_are_unique_and_project_prefixed():
    names = [k.name for k in knobs.KNOBS]
    assert len(names) == len(set(names))
    assert all(n.startswith("MPITREE_TPU_") for n in names)
    assert set(knobs.REGISTRY) == set(names)


def test_registry_entries_are_fully_described():
    for k in knobs.KNOBS:
        assert k.kind in ("bool", "str", "int", "float", "path"), k.name
        assert k.doc and "\n" not in k.doc, k.name  # one README row each
        if k.kind == "bool":
            assert k.parse is not None, k.name
        if k.choices is not None:
            # a str default must be a member of its documented domain
            if isinstance(k.default, str):
                assert k.default in k.choices, k.name


# ---------------------------------------------------------------------------
# typed reads


def test_value_returns_default_when_unset_or_empty(monkeypatch):
    monkeypatch.delenv("MPITREE_TPU_RETRIES", raising=False)
    assert knobs.value("MPITREE_TPU_RETRIES") == 2
    monkeypatch.setenv("MPITREE_TPU_RETRIES", "")
    assert knobs.value("MPITREE_TPU_RETRIES") == 2


def test_value_parses_by_kind(monkeypatch):
    monkeypatch.setenv("MPITREE_TPU_RETRIES", "7")
    assert knobs.value("MPITREE_TPU_RETRIES") == 7
    monkeypatch.setenv("MPITREE_TPU_BACKOFF_S", "0.25")
    assert knobs.value("MPITREE_TPU_BACKOFF_S") == 0.25
    # bool convention: everything but "0" enables…
    monkeypatch.setenv("MPITREE_TPU_PROFILE", "1")
    assert knobs.value("MPITREE_TPU_PROFILE") is True
    monkeypatch.setenv("MPITREE_TPU_PROFILE", "0")
    assert knobs.value("MPITREE_TPU_PROFILE") is False
    monkeypatch.setenv("MPITREE_TPU_PROFILE", "yes")
    assert knobs.value("MPITREE_TPU_PROFILE") is True
    # …except strict opt-ins, where only the literal "1" does
    monkeypatch.setenv("MPITREE_TPU_MEM_SAMPLE", "yes")
    assert knobs.value("MPITREE_TPU_MEM_SAMPLE") is False
    monkeypatch.setenv("MPITREE_TPU_MEM_SAMPLE", "1")
    assert knobs.value("MPITREE_TPU_MEM_SAMPLE") is True


def test_raw_passes_the_string_through(monkeypatch):
    monkeypatch.setenv("MPITREE_TPU_WIDE_HIST", "1")
    assert knobs.raw("MPITREE_TPU_WIDE_HIST") == "1"
    monkeypatch.delenv("MPITREE_TPU_CHAOS", raising=False)
    assert knobs.raw("MPITREE_TPU_CHAOS") is None


def test_unregistered_knob_is_a_loud_keyerror():
    with pytest.raises(KeyError, match="unregistered env knob"):
        knobs.value("MPITREE_TPU_NO_SUCH_KNOB")
    with pytest.raises(KeyError, match="knobs.py"):
        knobs.raw("MPITREE_TPU_NO_SUCH_KNOB")


# ---------------------------------------------------------------------------
# the single-read-path AST pin

_ENV_CALL_HEADS = {
    "os.environ.get", "os.getenv", "os.environ.pop",
    "os.environ.setdefault", "environ.get", "getenv", "environ.pop",
    "environ.setdefault",
}
_ENV_SUBSCRIPT_HEADS = {"os.environ", "environ"}


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _project_env_reads(tree):
    """Yield nodes reading a literal MPITREE_TPU_* key from os.environ."""
    for node in ast.walk(tree):
        key = None
        if isinstance(node, ast.Call):
            if _dotted(node.func) in _ENV_CALL_HEADS and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    key = arg.value
        elif isinstance(node, ast.Subscript):
            if _dotted(node.value) in _ENV_SUBSCRIPT_HEADS:
                sl = node.slice
                if isinstance(sl, ast.Constant) and isinstance(
                    sl.value, str
                ):
                    key = sl.value
        if key is not None and key.startswith("MPITREE_TPU_"):
            yield node, key


def test_environ_reads_live_only_in_the_registry():
    """The contract GL10 lints for, pinned independently of the linter:
    every literal MPITREE_TPU_* environ read in the package lives in
    config/knobs.py."""
    offenders = []
    for path in sorted(PACKAGE.rglob("*.py")):
        if path == REGISTRY_FILE:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        for node, key in _project_env_reads(tree):
            offenders.append(
                f"{path.relative_to(REPO)}:{node.lineno}: {key}"
            )
    assert offenders == [], (
        "MPITREE_TPU_* environ reads outside mpitree_tpu/config/knobs.py "
        "(route them through knobs.value()/knobs.raw()):\n"
        + "\n".join(offenders)
    )


def test_registry_file_actually_reads_environ():
    """Sanity for the pin above: the scanner recognizes the read idiom the
    registry itself uses, so an all-clean sweep means 'centralized', not
    'scanner blind'."""
    tree = ast.parse(REGISTRY_FILE.read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _dotted(node.func) in (
            "os.environ.get", "environ.get"
        ):
            return
    raise AssertionError(
        "knobs.py no longer reads os.environ via .get — update the "
        "AST pin's recognized idioms alongside it"
    )


# ---------------------------------------------------------------------------
# the README drift gate (CI contract)
#
# main() is exercised in-process (a `python -m mpitree_tpu.config`
# subprocess imports the whole package — seconds each); one subprocess
# smoke below pins the real CLI entry point CI invokes.

from mpitree_tpu.config.__main__ import main as config_main  # noqa: E402


def test_checked_in_readme_table_matches_registry(capsys):
    assert config_main(["--check"]) == 0
    assert "matches" in capsys.readouterr().err


def test_markdown_output_is_the_generated_table(capsys):
    assert config_main(["--markdown"]) == 0
    out = capsys.readouterr().out
    assert out == knobs.markdown_table()
    for k in knobs.KNOBS:
        assert f"`{k.name}`" in out


def test_check_fails_on_drift_and_write_repairs_it(tmp_path, capsys):
    doc = tmp_path / "README.md"
    doc.write_text(
        "# doc\n\n<!-- knob-table:begin -->\n| stale |\n"
        "<!-- knob-table:end -->\ntail prose survives\n"
    )
    assert config_main(["--check", str(doc)]) == 1
    assert "drifted" in capsys.readouterr().err

    assert config_main(["--write", str(doc)]) == 0
    text = doc.read_text()
    assert knobs.markdown_table().strip() in text
    assert "tail prose survives" in text
    assert "| stale |" not in text

    assert config_main(["--check", str(doc)]) == 0


def test_missing_markers_are_a_loud_failure(tmp_path, capsys):
    doc = tmp_path / "README.md"
    doc.write_text("# no markers here\n")
    assert config_main(["--check", str(doc)]) == 1
    assert "markers" in capsys.readouterr().err


def test_cli_entry_point_smoke():
    """The exact invocation CI runs, as a real subprocess."""
    proc = subprocess.run(
        [sys.executable, "-m", "mpitree_tpu.config", "--check"],
        cwd=REPO, capture_output=True, text=True,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": str(REPO), "HOME": "/tmp"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
