"""Seeded GL04 violations: dtype and TPU-tiling contract breaks."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl


@jax.jit
def alloc_no_dtype(x):
    acc = jnp.zeros((8, 128))  # expect: GL04
    return acc + x


@jax.jit
def full_weak_fill(x):
    base = jnp.full((8, 128), 0)  # expect: GL04
    return base + x


@jax.jit
def dot_no_pet(a, b):
    return lax.dot_general(  # expect: GL04
        a, b, dimension_numbers=(((0,), (0,)), ((), ())),
    )


def off_lane_blockspec(row_tile):
    return pl.BlockSpec((row_tile, 100), lambda i: (i, 0))  # expect: GL04


def off_sublane_blockspec():
    return pl.BlockSpec((12, 128), lambda i: (i, 0))  # expect: GL04


@jax.jit
def device_sum(acc):
    return acc.sum()


def host_acc_feeds_device_fn():
    acc = np.zeros((8, 128))  # expect: GL04
    return device_sum(acc)


def host_empty_feeds_jax_call():
    buf = np.empty((4, 4))  # expect: GL04
    return jnp.asarray(buf).sum()
