"""GL10 negative cases: the sanctioned registry read path.

Carries the ``knob-registry`` directive — environ reads here ARE the
single read path, and its registered knob is documented in the real
README knob table.
"""

# graftlint: knob-registry
import os

from mpitree_tpu.config.knobs import Knob

KNOBS = (
    Knob("MPITREE_TPU_PROFILE", "bool", False,
         "fixture mirror of a documented knob"),
)


def registry_reads_are_sanctioned():
    return os.environ.get("MPITREE_TPU_PROFILE")
