"""Lambda negative cases: rooted lambdas with clean bodies, inert lambdas."""

import jax
import jax.numpy as jnp

scale_rows = jax.vmap(lambda row: row / jnp.maximum(row.sum(), 1.0))

shift = jax.jit(lambda x, lo: x - lo)


def host_lambdas(pairs):
    # lambdas in plain host code stay host: sort keys may coerce freely
    return sorted(pairs, key=lambda p: float(p[1]))


def index_maps(row_tile):
    # BlockSpec-style index lambdas are device by containment but inert
    return (lambda i: (i, 0))(row_tile)
