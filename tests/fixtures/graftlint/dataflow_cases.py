"""Dataflow-engine fixture: propagation cases asserting exact traced sets.

No ``# expect:`` markers on purpose — every function here is CLEAN under
all rules. ``tests/test_graftlint_dataflow.py`` builds a Project over this
file and asserts the exact per-function traced-name sets, so a rule
regression is attributable to propagation vs. matching.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def tuple_unpack(x, y):
    a, b = x * 2, 3        # element-wise: a traced, b static
    n, f = x.shape         # laundered: neither traced
    c = a + n
    return b + c


@jax.jit
def cond_closure(x, flag01):
    total = x.sum()

    def on_true(op):
        return op + total  # total rides in through the closure

    def on_false(op):
        return op

    return lax.cond(flag01 == 1, on_true, on_false, x)


@partial(jax.jit, donate_argnames=("xs",))
def scan_carry(xs):
    def body(carry, row):
        nxt = carry + row.sum()
        return nxt, nxt * 0

    out, hist = lax.scan(body, jnp.float32(0.0), xs)
    return out + hist.sum()


@jax.jit
def lambda_capture(x):
    shift = x.mean()
    f = lambda v: v - shift  # noqa: E731 — the capture under test
    return f(x)


def helper(z):
    return jnp.exp(z)


@jax.jit
def through_call(x):
    e = helper(x)
    s = e.shape[0]         # laundered back to static
    return e * s


@jax.jit
def comp_case(xs):
    parts = [p * 2 for p in (xs, xs)]
    return parts[0]


def host_sink(arr, n_slots=8):
    # NON-device helper (no jit root reaches it): tracedness can only
    # enter through the per-argument call edge. `arr` is traced-eligible;
    # `n_slots` (defaulted) is heuristically static and must stay clean
    # even though the call below fills the slot.
    doubled = arr * 2
    return doubled


def host_driver():
    dev = jnp.ones((4,))           # a host-held device array
    out = host_sink(dev, 16)       # slot 0 taints `arr`; slot 1 is static
    size = len(out)                # laundered
    return out, size
