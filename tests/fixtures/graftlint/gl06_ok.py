"""GL06 negative cases: disciplined host callbacks produce no findings."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback


def log_host(x):
    print(np.asarray(x).sum())


def fetch_host(x):
    return np.asarray(x, np.float32).sum()


@jax.jit
def directed_callback(x):
    # graftlint: host-callback — training-loop progress sink
    jax.debug.callback(log_host, x)
    return x * 2


@jax.jit
def static_result_shapes(x):
    # shapes derived through .shape/.dtype laundering are static
    out_spec = jax.ShapeDtypeStruct((), x.dtype)
    # graftlint: host-callback — deliberate host reduction
    y = jax.pure_callback(fetch_host, out_spec, x)
    return x + y


@jax.jit
def operands_not_closures(x):
    scale = x * 2
    # graftlint: host-callback — scale rides as an explicit operand
    return x + io_callback(
        fetch_host, jax.ShapeDtypeStruct((), np.float32), scale
    )


def host_side_callback_free(x):
    # callbacks in plain host code are not policed
    return jnp.asarray(fetch_host(x))


SCALE = 2.0


def global_reader(v):
    # free name `scale`... no: `SCALE` resolves to the module global —
    # it must NOT collide with a caller local of the same spelling
    return np.float32(SCALE) * np.asarray(v).sum()


@jax.jit
def name_collision_is_not_a_leak(x):
    SCALE = x * 3  # noqa: F841 — the collision under test
    # graftlint: host-callback — deliberate host reduction
    return x + jax.pure_callback(
        global_reader, jax.ShapeDtypeStruct((), np.float32), x
    )
