"""GL09 negative cases: table-derived placements stay silent."""

from mpitree_tpu.parallel import partition


def table_derived(mesh):
    spec = partition.spec_for("x_binned", mesh)
    ins = partition.in_specs_for(mesh, ("y", "node_id", ("mcw", 0)))
    outs = partition.out_specs_for(mesh, ("node_id",))
    return spec, ins, outs


def dynamic_names_never_guessed(mesh, names):
    # non-literal name lists resolve at runtime; graftlint never guesses
    return partition.in_specs_for(mesh, names)


def unrelated_spec_for(metric):
    # a LOCAL helper that happens to be called spec_for is not the
    # partition table (the obs/diff.py idiom) — names are not checked
    def spec_for(m):
        return {"name": m}

    return spec_for(metric)
