"""Suppression fixtures: seeded violations silenced by directives."""

import jax

# graftlint: disable-file=GL04
import jax.numpy as jnp


@jax.jit
def suppressed_same_line(x):
    return x.sum().item()  # graftlint: disable=GL01


@jax.jit
def suppressed_line_above(x):
    # graftlint: disable=GL01
    return float(x.sum())


@jax.jit
def suppressed_by_file_directive(x):
    return jnp.zeros((8, 128)) + x  # GL04, silenced file-wide above
