"""GL08 negative cases the path-sensitive scan must NOT flag.

The duals of ``gl08_path_bad.py``: every read here sits on a path where
the name was rebound first, or on a path that never made the donating
call at all — the false positives the line-ordered rule produced.
"""

import jax
import jax.numpy as jnp


def advance(nid, xb):
    return nid + xb.sum(axis=1).astype(nid.dtype)


def call_on_one_branch(flag, xb, nid0):
    # the donation happens only on the then-path, which returns; the
    # else-path's read never saw a donated buffer (the old rule flagged
    # it purely because it sat on a later line)
    step = jax.jit(advance, donate_argnums=(0,))
    if flag:
        out = step(nid0, xb)
        return out
    return nid0 * 2


def rebind_path_reads_freely(flag, xb, nid0):
    # the branch that rebinds may read the fresh binding; the branch
    # that kept the dead buffer reads nothing
    step = jax.jit(advance, donate_argnums=(0,))
    out = step(nid0, xb)
    if flag:
        nid0 = jnp.zeros_like(out)
        out = out + nid0
    return out


def terminating_branch_then_rebind(flag, xb, nid0):
    # the donated path returns before any read; the fall-through rebinds
    # before its read — both paths clean
    step = jax.jit(advance, donate_argnums=(0,))
    out = step(nid0, xb)
    if flag:
        return out
    nid0 = jax.device_put(out)
    return out + nid0


def loop_rebind_still_sanctioned(xb, nid0):
    # the canonical level-loop idiom must survive the rewrite untouched
    step = jax.jit(advance, donate_argnums=(0,))
    for _ in range(4):
        nid0 = step(nid0, xb)
    return nid0
