"""Seeded GL12 violations: unpriced device collectives, a wire= naming no
priced site, and event/decision names absent from the registry
(``gl12_ledger_decl.py``)."""

import jax
from jax import lax
# graftlint: partition-table — fixture scenarios spell specs inline
from jax.sharding import PartitionSpec as P

from mesh_decl import DATA_AXIS  # noqa: F401 (lint input only)


def make_unpriced(mesh):
    def local_step(x, y):
        h = x * y
        return lax.psum(h, DATA_AXIS)  # expect: GL12

    return jax.jit(jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
    ))


def make_ghost_site(mesh):
    # graftlint: wire=ghost_site
    def local_step(x):
        return lax.psum(x, DATA_AXIS)  # expect: GL12

    return jax.jit(jax.shard_map(
        local_step, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P()
    ))


def emit_unregistered(obs):
    # host code: only the NAME congruence leg applies here
    obs.event("fallback_firedd", "typo'd kind")  # expect: GL12
    obs.decision("engine_pickk", "typo'd key")  # expect: GL12
    warn_event(obs, "mystery_kind", "never registered")  # expect: GL12


def warn_event(obs, kind, message):
    """Fixture stand-in so the module is self-contained (lint-only)."""
    obs.event(kind, message)
