"""Seeded GL09 axis-conformance violation: a sanctioned partition-table
module whose spec spells an axis no static mesh metadata declares — the
spec silently replicates on every real mesh."""

# graftlint: partition-table
from jax.sharding import PartitionSpec as P

GHOST_RULES_DOC = "the axis below is declared by no Mesh/*_AXIS constant"

PARTITION_RULES = [
    (r"^ghost_rows$", P("ghost")),  # expect: GL09
    (r".*", P()),
]
