"""GL12-clean twins: every device collective maps to a priced site (via
call-line, comment-block, and enclosing-def annotations — all three
placements) and every event/decision name is registered."""

import jax
from jax import lax
# graftlint: partition-table — fixture scenarios spell specs inline
from jax.sharding import PartitionSpec as P

from mesh_decl import DATA_AXIS  # noqa: F401 (lint input only)


def make_priced_def_level(mesh):
    # Factory whose every collective belongs to one site: annotate once
    # on the enclosing def.
    # graftlint: wire=hist_psum
    def local_step(x, y):
        return lax.psum(x * y, DATA_AXIS)

    return jax.jit(jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
    ))


def make_priced_call_line(mesh):
    def local_step(x):
        g = lax.all_gather(x, "model")  # graftlint: wire=winner_gather
        # The *_bytes helper stem is also a priced site:
        # graftlint: wire=counts_psum
        return lax.psum(g.sum(), DATA_AXIS)

    return jax.jit(jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, "model"),),
        out_specs=P(),
    ))


def emit_registered(obs):
    obs.event("fallback_fired", "registered kind")
    obs.decision("engine_pick", "fused")
