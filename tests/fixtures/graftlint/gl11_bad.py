"""Seeded GL11 violations: unlocked guarded access (plain write, mutator
call, torn read), a condition op outside the owning lock, a bare
``lock-free`` escape with no justification, and an ABBA acquisition-order
inversion."""

import threading


class BadCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0
        self._items = []

    def locked_add(self, n):
        with self._lock:
            self._total += n
            self._items.append(n)

    def racy_add(self, n):
        self._total += n  # expect: GL11
        self._items.append(n)  # expect: GL11

    def racy_read(self):
        return self._total  # expect: GL11


class BadWaiter:
    def __init__(self):
        self._cond = threading.Condition()
        self._ready = False

    def set_ready(self):
        with self._cond:
            self._ready = True
            self._cond.notify_all()

    def wait_ready(self):
        self._cond.wait()  # expect: GL11


class BadEscape:
    def __init__(self):
        self._lock = threading.Lock()
        self._hits = 0

    def bump(self):
        with self._lock:
            self._hits += 1

    def peek(self):
        # expect: GL11 # graftlint: lock-free
        return self._hits


class BadOrder:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self._n = 0

    def forward(self):
        with self._alock:
            with self._block:
                self._n += 1

    def backward(self):
        with self._block:
            with self._alock:  # expect: GL11
                self._n -= 1
