"""Clean GL03 body-axis shapes: bound literal axes, dynamic axes, and a
second mesh axis bound through the specs (the (data, feature) idiom)."""

import jax
from jax import lax
# graftlint: partition-table — fixture scenarios spell specs inline
from jax.sharding import PartitionSpec as P

from mesh_decl import DATA_AXIS  # noqa: F401 (lint input only)


def make_two_axis_program(mesh):
    """Feature-axis collectives are fine when the specs bind the axis."""

    # graftlint: wire=hist_psum, winner_gather
    def local_step(x, y):
        h = lax.psum(x * y, DATA_AXIS)
        j = lax.axis_index("model")
        g = lax.all_gather(h, "model")
        return g[j]

    return jax.jit(jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, "model"), P(DATA_AXIS)),
        out_specs=P(),
    ))


def make_dynamic_axis_program(mesh, axis):
    """Parameterized axes are invisible to the static check — skipped."""

    # graftlint: wire=hist_psum
    def local_step(x):
        return lax.psum(x, axis)

    return jax.shard_map(
        local_step, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P()
    )


def make_dynamic_specs_program(mesh, in_specs):
    """Dynamically built specs (the partition-rule table) — skipped."""

    # graftlint: wire=hist_psum
    def local_step(x):
        return lax.psum(x, "model")

    return jax.shard_map(
        local_step, mesh=mesh, in_specs=in_specs, out_specs=P()
    )
