"""Seeded GL03 body-axis violations: collectives inside a shard_map body
over an axis the enclosing call's PartitionSpecs do not bind (the 2-D
(data, feature) mesh lesson — "model" IS declared by mesh_decl's Mesh
literal, so only the spec-binding rule fires, not the declared-axis one).
"""

import jax
from jax import lax
# graftlint: partition-table — fixture scenarios spell specs inline
from jax.sharding import PartitionSpec as P

from mesh_decl import DATA_AXIS  # noqa: F401 (lint input only)


def make_unbound_body_psum(mesh):
    # graftlint: wire=hist_psum
    def local_step(x, y):
        h = lax.psum(x * y, DATA_AXIS)  # bound by the in_specs — fine
        return lax.psum(h, "model")  # expect: GL03

    return jax.jit(jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
    ))


def make_unbound_nested_gather(mesh):
    # graftlint: wire=winner_gather
    def body(x):
        def merge(v):
            return lax.all_gather(v, "model")  # expect: GL03

        return merge(x)

    return jax.shard_map(
        body, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P(DATA_AXIS)
    )
