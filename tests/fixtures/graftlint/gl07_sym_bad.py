"""Seeded GL07 violations on SYMBOLIC dims — provable via the fact domain.

Every site here was invisible to the literal-only rule (symbolic block
dims forced a bail); symdim's guard/round_up/binding facts make each one
a proof, not a guess.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _round_up(x, k):
    return (x + k - 1) // k * k


def doubler(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def guarded_rows_blow_vmem(row_tile):
    # the raise-guard proves row_tile >= 4096, so the in-block alone is
    # at least 4096 x 1024 x 4 B = 16 MiB — over budget on EVERY path,
    # exactly the overrun the literal-only rule skipped
    if row_tile < 4096:
        raise ValueError("row_tile too small")
    tile = _round_up(row_tile, 8)
    bins = 1024
    return pl.pallas_call(  # expect: GL07
        doubler,
        grid=(2,),
        in_specs=[pl.BlockSpec((tile, bins), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tile, bins), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((8192, 1024), jnp.float32),
    )


def guarded_grid_cannot_cover(row_tile):
    # row_tile <= 8 proved by the guard: 2 grid steps x at-most-8 rows
    # cover at most 16 of the 64 output rows
    if row_tile > 8:
        raise ValueError("row_tile too large")
    return pl.pallas_call(
        doubler,
        grid=(2,),
        in_specs=[pl.BlockSpec((row_tile, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((row_tile, 128), lambda i: (i, 0)),  # expect: GL07
        out_shape=jax.ShapeDtypeStruct((64, 128), jnp.float32),
    )


def loop_carried_round_up_blows_vmem(row_tile):
    # v4: the retry loop re-rounds the SAME name each pass. The widening
    # fixpoint joins the init fact with the loop rebind — round_up never
    # shrinks, so the >= 4096 lower bound survives the hull and the
    # in-block stays provably over budget on every iteration
    if row_tile < 4096:
        raise ValueError("row_tile too small")
    tile = _round_up(row_tile, 8)
    for _ in range(3):
        tile = _round_up(tile, 128)
    return pl.pallas_call(  # expect: GL07
        doubler,
        grid=(2,),
        in_specs=[pl.BlockSpec((tile, 1024), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tile, 1024), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((8192, 1024), jnp.float32),
    )


def tuple_unpacked_dims_blow_vmem(row_tile):
    # v4: both block dims land through ONE literal tuple unpack — each
    # element is its own single assignment, so `tile` carries the guard's
    # >= 4096 bound and `bins` is exactly 1024: 16 MiB per block
    if row_tile < 4096:
        raise ValueError("row_tile too small")
    tile, bins = _round_up(row_tile, 8), 1024
    return pl.pallas_call(  # expect: GL07
        doubler,
        grid=(2,),
        in_specs=[pl.BlockSpec((tile, bins), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tile, bins), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((8192, 1024), jnp.float32),
    )


def bf16_sublane_via_binding():
    # the single-assignment binding makes `rows` exactly 24 — passes the
    # f32 floor but breaks bf16's 16-row sublane tiling
    rows = 24
    return pl.pallas_call(
        doubler,
        grid=(4,),
        in_specs=[pl.BlockSpec((rows, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((rows, 128), lambda i: (i, 0)),  # expect: GL07
        out_shape=jax.ShapeDtypeStruct((96, 128), jnp.bfloat16),
    )
