"""GL07 negative cases: well-tiled, covered, VMEM-sane pallas_calls."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def doubler(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def bf16_sublane_aligned():
    return pl.pallas_call(
        doubler,
        grid=(2,),
        in_specs=[pl.BlockSpec((16, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((16, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.bfloat16),
    )


def grid_covers_exactly():
    return pl.pallas_call(
        doubler,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
    )


def symbolic_dims_are_skipped(row_tile, n_rows):
    # graftlint never guesses: UNGUARDED symbolic dims carry no provable
    # facts, so every check stays silent (guarded dims live in
    # gl07_sym_bad.py / gl07_sym_ok.py)
    return pl.pallas_call(
        doubler,
        grid=(n_rows // row_tile,),
        in_specs=[pl.BlockSpec((row_tile, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((row_tile, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_rows, 128), jnp.float32),
    )


def degenerate_dims_allowed():
    # 1 stays legal in any position (the (Rt, 1) slot-column idiom)
    return pl.pallas_call(
        doubler,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, 128), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, 512), jnp.bfloat16),
    )
