"""GL11-clean twins: locked discipline throughout, an injected lock, a
helper that inherits the lock from its only (locked) call site, a
justified ``lock-free`` escape, condition ops under the owning lock, and
one consistent two-lock acquisition order."""

import threading


class GoodCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0
        self._items = []

    def add(self, n):
        with self._lock:
            self._total += n
            self._bump(n)

    def _bump(self, n):
        # only ever called under the lock — inherits it (held fixpoint)
        self._items.append(n)

    def snapshot(self):
        # graftlint: lock-free — monitoring read of one int; a torn read
        # only skews a gauge, never corrupts state
        return self._total


class InjectedLock:
    def __init__(self, lock):
        self._lock = lock
        self._rows = {}

    def put(self, k, v):
        with self._lock:
            self._rows[k] = v


class GoodWaiter:
    def __init__(self):
        self._cond = threading.Condition()
        self._ready = False

    def set_ready(self):
        with self._cond:
            self._ready = True
            self._cond.notify_all()

    def wait_ready(self):
        with self._cond:
            while not self._ready:
                self._cond.wait()


class GoodOrder:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self._n = 0

    def one(self):
        with self._alock:
            with self._block:
                self._n += 1

    def two(self):
        with self._alock:
            with self._block:
                self._n -= 1
