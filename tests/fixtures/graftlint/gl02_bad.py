"""Seeded GL02 violations: recompile hazards at jit boundaries."""

from functools import partial

import jax


@jax.jit
def missing_static(x, n_bins: int):  # expect: GL02
    return x * n_bins


@partial(jax.jit, static_argnames=("n_binz",))
def typo_static(x):  # expect: GL02
    return x + 1


@partial(jax.jit, static_argnames=("flag",))
def branch_on_traced(x, *, flag: bool):
    y = x * 2
    if y.sum() > 0:  # expect: GL02
        return x
    return -x


@partial(jax.jit, static_argnames=("depth",))
def while_on_traced(x, *, depth: int):
    while x.sum() < depth:  # expect: GL02
        x = x * 2
    return x


def wrapped_later(x, max_depth):  # expect: GL02
    return x[:max_depth]


wrapped = jax.jit(wrapped_later, static_argnames=())
