"""Negative cases: correct idioms that must produce NO findings."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
# graftlint: partition-table — fixture scenarios spell specs inline
from jax.sharding import PartitionSpec as P

from mesh_decl import DATA_AXIS


@partial(jax.jit, static_argnames=("n_bins", "mode"))
def good_jit(x, *, n_bins: int, mode: str = "auto"):
    # statics branch fine; shape-derived values launder tracedness
    N, F = x.shape
    if mode == "auto":
        n_bins = min(n_bins, 128)
    if N != F:
        x = x[:, :N]
    acc = jnp.zeros((N, n_bins), jnp.float32)
    return acc + x[:, :1]


@jax.jit
def good_contract(a, b):
    return lax.dot_general(
        a, b,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def make_good_collective(mesh):
    # graftlint: wire=hist_psum
    def local_step(x, y):
        h = jnp.zeros(x.shape, jnp.float32) + x * y
        return lax.psum(h, DATA_AXIS)

    return jax.jit(jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
    ))


def good_blockspec(row_tile):
    # literal dims on tile boundaries; 1 allowed for degenerate dims
    return pl.BlockSpec((row_tile, 1), lambda i: (i, 0)), pl.BlockSpec(
        (8, 256), lambda i: (i, 0)
    )


def host_side_materialization(tree):
    # host code: one bulk pull then Python scalars — the GL01-clean shape
    feature = np.asarray(tree.feature)
    values = np.asarray(tree.value).tolist()
    return [int(feature[i]) + values[i] for i in range(len(values))]


def host_loop_with_coercions(rows):
    # int()/float() in host loops are fine; only .item() per element syncs
    return [float(r) for r in rows]


def host_only_accumulator(ids, w):
    # undtyped np.zeros consumed only by host numpy: the f64 default is
    # deliberate (exact bincount accumulation) and never crosses to device
    votes = np.zeros((len(ids), 4))
    np.add.at(votes, ids, w)
    return votes.sum()


def dtyped_alloc_feeds_device(x):
    # explicit dtype: the transfer width is pinned — no finding
    acc = np.zeros((8, 128), np.float32)
    return jnp.asarray(acc) + x


def rebound_name_is_host_only(x):
    # 'buf' feeds jax ABOVE the np.zeros rebind — the allocation below is
    # a different (host-only) binding and must not fire
    buf = jnp.asarray(x)
    total = jnp.sum(buf)
    buf = np.zeros((4, 4))
    buf[0, 0] = float(total)
    return buf
