"""Seeded GL01 violations: host-device syncs inside device code."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("scale",))
def item_in_jit(x, *, scale: int):
    total = x.sum()
    return total.item() * scale  # expect: GL01


@jax.jit
def coerce_in_jit(x):
    s = float(x.sum())  # expect: GL01
    return jnp.float32(s)


@jax.jit
def asarray_in_jit(x):
    h = np.asarray(x)  # expect: GL01
    return h + 1


@jax.jit
def device_get_in_jit(x):
    return jax.device_get(x)  # expect: GL01


@jax.jit
def block_in_jit(x):
    return (x * 2).block_until_ready()  # expect: GL01


def helper_called_from_jit(h):
    # reached transitively from routed_entry: still device code
    return int(h)  # expect: GL01


@jax.jit
def routed_entry(x):
    return helper_called_from_jit(x.sum())


def item_per_element(values):
    out = []
    for v in values:
        out.append(v.item())  # expect: GL01
    return out


def item_in_comprehension(arr):
    return [arr[i].item() for i in range(3)]  # expect: GL01
