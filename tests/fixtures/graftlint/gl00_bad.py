"""Seeded GL00 violations: suppressions that no longer suppress anything.

The RUF100-style audit: a dead directive reads as load-bearing forever —
after a refactor fixes the underlying finding, the stale comment is the
finding.
"""

import jax
import jax.numpy as jnp


@jax.jit
def fixed_long_ago(x):
    total = jnp.sum(x, dtype=jnp.float32)
    return total * 2  # graftlint: disable=GL01  # expect: GL00


@jax.jit
def wrong_rule_listed(x):
    # the GL04 half is live, the GL03 half never fires here
    # graftlint: disable=GL03  # expect: GL00
    # graftlint: disable=GL04
    return jnp.zeros((8, 128)) + x
