"""Seeded GL10 violations: project knobs read outside the registry."""

import os


def direct_get():
    return os.environ.get("MPITREE_TPU_DEBUG")  # expect: GL10


def getenv_spelling():
    return os.getenv("MPITREE_TPU_PROFILE", "0")  # expect: GL10


def subscript_read():
    return os.environ["MPITREE_TPU_ENGINE"]  # expect: GL10


def foreign_keys_stay_legal():
    # non-project env vars are out of GL10's jurisdiction
    return os.environ.get("COORDINATOR_ADDRESS")


def dynamic_keys_never_guessed(name):
    # a computed key is resolved at runtime; graftlint never guesses
    return os.environ.get(name)
