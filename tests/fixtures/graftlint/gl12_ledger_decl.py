"""Fixture ledger authority: the priced-site vocabulary and event
registry GL12's congruence checks resolve against — the fixture mirror of
``mpitree_tpu/obs/record.py`` (wire sites) + ``mpitree_tpu/obs/events.py``
(event/decision names). Its presence is what ACTIVATES both GL12 legs
over the fixture set, so every device collective in the other fixtures
carries a ``wire=`` annotation and every literal event name used by the
gl12 twins must appear here.
"""

# graftlint: event-registry


# Wire authority: dict keys are priced sites (axis attribution rides the
# values, irrelevant to the lint).
COLLECTIVE_AXES = {
    "hist_psum": "data",
    "winner_gather": "feature",
}


# A payload helper also names a priced site (its ``_bytes`` stem).
def counts_psum_bytes(*, n_slots: int) -> int:
    return n_slots * 4


class Event:
    def __init__(self, kind, doc=""):
        self.kind = kind
        self.doc = doc


class Decision:
    def __init__(self, key, doc=""):
        self.key = key
        self.doc = doc


EVENTS = (
    Event("fallback_fired", "kernel tier degraded to the XLA path"),
    Event("budget_exceeded", "a priced plan crossed its byte budget"),
)

DECISIONS = (
    Decision("engine_pick", "which engine the resolver chose"),
)
