"""Axis declarations the GL03 fixtures resolve against (lint input only)."""

import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"


def make_mesh(devs):
    return Mesh(np.array(devs), (DATA_AXIS, "model"))
