"""Fixture partition authority: the table GL09 name checks resolve against.

Carries the ``partition-table`` directive, so its own ``P(...)``
constructions are sanctioned — the fixture mirror of
``mpitree_tpu/parallel/partition.py``.
"""

# graftlint: partition-table
from jax.sharding import PartitionSpec as P

# Declared axis constants: the v4 axis-conformance leg checks every axis
# a sanctioned spec spells against the lint set's mesh metadata.
D_AXIS = "d"
F_AXIS = "f"

PARTITION_RULES = [
    (r"^x_binned$", P(D_AXIS, F_AXIS)),
    (r"^(y|node_id)$", P(D_AXIS)),
    (r".*", P()),
]


def spec_for(name, mesh):
    for pattern, spec in PARTITION_RULES:
        import re

        if re.match(pattern, name):
            return spec
    return P()
