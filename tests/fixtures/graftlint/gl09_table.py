"""Fixture partition authority: the table GL09 name checks resolve against.

Carries the ``partition-table`` directive, so its own ``P(...)``
constructions are sanctioned — the fixture mirror of
``mpitree_tpu/parallel/partition.py``.
"""

# graftlint: partition-table
from jax.sharding import PartitionSpec as P

PARTITION_RULES = [
    (r"^x_binned$", P("d", "f")),
    (r"^(y|node_id)$", P("d")),
    (r".*", P()),
]


def spec_for(name, mesh):
    for pattern, spec in PARTITION_RULES:
        import re

        if re.match(pattern, name):
            return spec
    return P()
