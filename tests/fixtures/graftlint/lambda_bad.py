"""Seeded violations inside wrapper-rooted lambdas (synthetic FuncInfos).

The PR-1 engine could not root ``jax.vmap(lambda ...)`` (ROADMAP: "lambdas
aren't FuncInfos") — a sync in a vmapped lambda body escaped every rule.
"""

import jax

per_row_sync = jax.vmap(lambda row: row.sum().item())  # expect: GL01

jitted_coercion = jax.jit(lambda x: float(x.mean()))  # expect: GL01


def factory(xs):
    # rooted through a call argument inside a host function too
    return jax.vmap(lambda r: r.max().item())(xs)  # expect: GL01
