"""Seeded GL07 violations: Pallas kernel hygiene breaks."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def doubler(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def bf16_sublane_too_short():
    # (8, 128) satisfies the f32 floor (GL04-silent) but bf16 tiles 16-row
    # sublanes: the out block must be a multiple of (16, 128)
    return pl.pallas_call(
        doubler,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),  # expect: GL07
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.bfloat16),
    )


def grid_undercovers_rows():
    # 2 grid steps x 8-row blocks cover 16 of 32 output rows
    return pl.pallas_call(
        doubler,
        grid=(2,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),  # expect: GL07
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
    )


def vmem_blowout():
    # 8 MiB in-block + 8 MiB out-block (double-buffered -> 16 MiB) blow
    # the ~10 MiB per-step budget: Mosaic fails allocation on hardware
    return pl.pallas_call(  # expect: GL07
        doubler,
        grid=(2,),
        in_specs=[pl.BlockSpec((4096, 512), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((4096, 512), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((8192, 512), jnp.float32),
    )


def const_offset_leaves_prefix_uncovered():
    # a constant index map writes exactly ONE block; at offset 1 the
    # first 8 rows are never visited
    return pl.pallas_call(
        doubler,
        grid=(1,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (1, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (1, 0)),  # expect: GL07
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
    )


def kernel_partial(scale, x_ref, o_ref):
    o_ref[...] = x_ref[...] * scale


def grid_spec_binding_resolves():
    # grid/in_specs/out_specs riding a PrefetchScalarGridSpec-style local
    # binding still resolve (the ops/wide_hist.py idiom)
    grid_spec = pl.GridSpec(
        grid=(2,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),  # expect: GL07
    )
    return pl.pallas_call(
        functools.partial(kernel_partial, 2.0),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((64, 128), jnp.float32),
    )
