"""Background flusher with a documented-but-unimplemented discipline.

Concurrency: a daemon thread flushes the buffer while callers append
concurrently — every touch of the shared buffer is supposed to be
serialized.
"""

import threading

_BUF = []


def start_flusher():
    t = threading.Thread(target=_BUF.clear, daemon=True)  # expect: GL11
    t.start()
    return t
