"""Seeded GL09 violations: placements dodging the partition table."""

from jax.sharding import PartitionSpec as P

from mpitree_tpu.parallel import partition


def adhoc_literal_spec():
    # engine code constructing its own placement instead of deriving it
    # through the table
    return P("d", None)  # expect: GL09


def typo_falls_to_catchall(mesh):
    # "x_binnedd" matches only the catch-all replicate rule — a silent
    # full-copy where a (data, feature) shard was intended
    return partition.spec_for("x_binnedd", mesh)  # expect: GL09


def unknown_name_in_specs(mesh):
    # "nod_id" is a typo of "node_id"; "y" conforms and the ("lam", 0)
    # scalar pair is the sanctioned replicate spelling
    return partition.in_specs_for(mesh, ("y", "nod_id", ("lam", 0)))  # expect: GL09
