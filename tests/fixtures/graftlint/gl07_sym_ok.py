"""GL07 negative cases on symbolic dims: facts that entail NO violation.

The dual of ``gl07_sym_bad.py`` — same shapes of symbolic reasoning, but
each site is either provably sane, runtime-gated, or honestly unknown.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _round_up(x, k):
    return (x + k - 1) // k * k


def fits_vmem(*nbytes):
    return sum(nbytes) < (10 << 20)


def doubler(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def vmem_guarded_site(row_tile):
    # same lower-bound blowout as gl07_sym_bad.guarded_rows_blow_vmem, but
    # the scope runtime-gates its working set — the fits_vmem raise-guard
    # subsumes the static bound, so GL07 stays quiet
    if row_tile < 4096:
        raise ValueError("row_tile too small")
    tile = _round_up(row_tile, 8)
    if not fits_vmem(tile * 1024 * 4 * 3):
        raise ValueError("working set exceeds VMEM")
    return pl.pallas_call(
        doubler,
        grid=(2,),
        in_specs=[pl.BlockSpec((tile, 1024), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tile, 1024), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((8192, 1024), jnp.float32),
    )


def bounded_both_ways(row_tile):
    # 8 <= row_tile <= 256 and a multiple of 8: the VMEM lower bound is
    # tiny and 8 grid steps x at-most-256 rows cover the 64-row output
    if row_tile < 8:
        raise ValueError("row_tile too small")
    if row_tile > 256:
        raise ValueError("row_tile too large")
    tile = _round_up(row_tile, 8)
    return pl.pallas_call(
        doubler,
        grid=(8,),
        in_specs=[pl.BlockSpec((tile, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tile, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((64, 128), jnp.float32),
    )


def loop_carried_round_up_stays_bounded(passes):
    # v4 loop-carried fixpoint: init 8, each pass re-rounds to 128 — the
    # join settles at the 8..128 hull with divisor 8, so the block is
    # provably small and 8 grid steps x at-most-128 rows cover 64
    tile = 8
    for _ in range(passes):
        tile = _round_up(tile, 128)
    return pl.pallas_call(
        doubler,
        grid=(8,),
        in_specs=[pl.BlockSpec((tile, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tile, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((64, 128), jnp.float32),
    )


def loop_doubling_widens_to_divisor_only(steps):
    # v4: `grow * 2` never stabilizes inside the pass budget — the bounds
    # widen away and only the divisor chain (gcd-monotone, guaranteed to
    # settle) survives. No bound, no finding: honest unknown, not a guess
    grow = 8
    for _ in range(steps):
        grow = grow * 2
    return pl.pallas_call(
        doubler,
        grid=(2,),
        in_specs=[pl.BlockSpec((grow, 1024), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((grow, 1024), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((8192, 1024), jnp.float32),
    )


def rebound_name_stays_unknown(row_tile, wide):
    # `tile` is bound twice — symdim refuses to guess across branches,
    # so no fact forms and no check can fire
    tile = _round_up(row_tile, 8)
    if wide:
        tile = _round_up(row_tile, 16)
    return pl.pallas_call(
        doubler,
        grid=(2,),
        in_specs=[pl.BlockSpec((tile, 1024), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tile, 1024), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((8192, 1024), jnp.float32),
    )
