"""Seeded GL08 violations: donated buffers read after the call."""

import jax
from functools import partial


def advance(nid, xb):
    return nid + xb.sum(axis=1).astype(nid.dtype)


def read_after_donation(xb, nid0):
    step = jax.jit(advance, donate_argnums=(0,))
    out = step(nid0, xb)
    return out + nid0.sum()  # expect: GL08


def loop_without_rebind(xb, nid0):
    step = jax.jit(advance, donate_argnums=(0,))
    out = None
    for _ in range(4):
        out = step(nid0, xb)  # expect: GL08
    return out


def make_step():
    return jax.jit(advance, donate_argnums=(0,))


def factory_caller(xb, nid0):
    step = make_step()
    acc = step(nid0, xb)
    return acc * nid0  # expect: GL08


@partial(jax.jit, donate_argnames=("state",))
def consume(state, x):
    return state + x


def decorated_caller(state, x):
    y = consume(state, x)
    return y + state.mean()  # expect: GL08
