"""Fixture registry with a knob the README never documents (doc drift)."""

# graftlint: knob-registry
from mpitree_tpu.config.knobs import Knob

KNOBS = (
    Knob("MPITREE_TPU_NOT_IN_README_XYZZY", "bool", False,  # expect: GL10
         "fixture-only knob that must trip the README drift leg"),
)
