"""Seeded GL08 violations only a PATH-SENSITIVE scan can pin correctly.

Each case forks control flow after the donating call; the garbage read
sits on exactly one path. The line-ordered rule either missed these (a
lexically-earlier rebind on the OTHER branch masked the read) or could
not tell the branches apart.
"""

import jax
import jax.numpy as jnp


def advance(nid, xb):
    return nid + xb.sum(axis=1).astype(nid.dtype)


def read_on_sibling_branch(flag, xb, nid0):
    # the then-branch rebinds; the else-branch still holds the dead
    # buffer — lexical order put the rebind first, masking this read
    # from the old line-ordered scan
    step = jax.jit(advance, donate_argnums=(0,))
    out = step(nid0, xb)
    if flag:
        nid0 = jnp.zeros_like(out)
        probe = nid0.sum()
    else:
        probe = nid0.sum()  # expect: GL08
    return out, probe


def read_after_partial_rebind(flag, xb, nid0):
    # only ONE branch rebinds: the fall-through path joins DONATED, so
    # the read after the `if` is garbage whenever flag is False
    step = jax.jit(advance, donate_argnums=(0,))
    out = step(nid0, xb)
    if flag:
        nid0 = jnp.zeros_like(out)
    return out + nid0  # expect: GL08
