"""GL05 negative cases: donated, suppressed, and loop-free jits."""

import jax
import jax.numpy as jnp
from functools import partial
from jax import lax
# graftlint: partition-table — fixture scenarios spell specs inline
from jax.sharding import PartitionSpec as P

from mesh_decl import DATA_AXIS


def level_body(state):
    nid, depth = state
    return nid * 2 + 1, depth + 1


def level_cond(state):
    return state[1] < 8


def fused_build(nid0):
    return lax.while_loop(level_cond, level_body, (nid0, 0))


def make_fused_donating(mesh):
    sharded = jax.shard_map(
        fused_build, mesh=mesh, in_specs=(P(DATA_AXIS),),
        out_specs=(P(DATA_AXIS), P()),
    )
    return jax.jit(sharded, donate_argnums=(0,))


@partial(jax.jit, donate_argnames=("nid",))
def scanned_update_donating(nid, steps):
    def body(carry, s):
        return carry + s, ()

    out, _ = lax.scan(body, nid, steps)
    return out


def make_fused_opted_out(mesh):
    sharded = jax.shard_map(
        fused_build, mesh=mesh, in_specs=(P(DATA_AXIS),),
        out_specs=(P(DATA_AXIS), P()),
    )
    # inputs reused across calls: donation would invalidate them
    return jax.jit(sharded)  # graftlint: disable=GL05


@jax.jit
def loop_free(x, y):
    # no lax loop: plain fused arithmetic needs no donation story
    return jnp.where(x > 0, x, y).sum()
