"""Seeded GL06 violations: undisciplined host callbacks in device code."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback


def log_host(x):
    # host-side sink — reachability must NOT treat this as device code
    print(np.asarray(x).sum())


def stats_host():
    return np.float32(0.0)


@jax.jit
def undirected_callback(x):
    jax.debug.callback(log_host, x)  # expect: GL06
    return x * 2


@jax.jit
def no_result_shapes(x):
    # graftlint: host-callback — deliberate host fetch
    y = jax.pure_callback(stats_host)  # expect: GL06
    return x + y


@jax.jit
def traced_result_shapes(x):
    total = x.sum()
    # graftlint: host-callback — deliberate host fetch
    return x + jax.pure_callback(
        stats_host,
        jnp.zeros_like(total),  # expect: GL06
    )


@jax.jit
def closure_over_traced(x):
    scale = x * 2

    def fetch():
        return np.asarray(scale).sum()

    # graftlint: host-callback — deliberate host fetch
    return x + io_callback(fetch, jax.ShapeDtypeStruct((), np.float32))  # expect: GL06
