"""Seeded GL05 violations: fused-state jits that donate nothing."""

import jax
import jax.numpy as jnp
from functools import partial
from jax import lax
# graftlint: partition-table — fixture scenarios spell specs inline
from jax.sharding import PartitionSpec as P

from mesh_decl import DATA_AXIS


def level_body(state):
    nid, depth = state
    return nid * 2 + 1, depth + 1


def level_cond(state):
    return state[1] < 8


def fused_build(nid0):
    return lax.while_loop(level_cond, level_body, (nid0, 0))


def make_fused(mesh):
    sharded = jax.shard_map(
        fused_build, mesh=mesh, in_specs=(P(DATA_AXIS),),
        out_specs=(P(DATA_AXIS), P()),
    )
    return jax.jit(sharded)  # expect: GL05


def make_fused_direct():
    return jax.jit(fused_build)  # expect: GL05


@jax.jit  # expect: GL05
def scanned_update(nid, steps):
    def body(carry, s):
        return carry + s, ()

    out, _ = lax.scan(body, nid, steps)
    return out


@partial(jax.jit, static_argnames=("depth",))  # expect: GL05
def fori_descend(x, nodes, *, depth: int):
    def body(_, node):
        return node * 2

    return lax.fori_loop(0, depth, body, jnp.zeros_like(nodes))
