"""Seeded GL03 violations: undeclared collective axes, short shard_map specs."""

import jax
from jax import lax
# graftlint: partition-table — fixture scenarios spell specs inline
from jax.sharding import PartitionSpec as P

from mesh_decl import DATA_AXIS  # noqa: F401 (lint input only)


def make_bad_axis(mesh):
    # graftlint: wire=hist_psum
    def local_step(x, y):
        h = x + y
        return lax.psum(h, "rows")  # expect: GL03

    return jax.jit(jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
    ))


def make_short_specs(mesh):
    def local_update(a, b, c):
        return a + b + c

    in_specs = (P(DATA_AXIS), P(DATA_AXIS))  # expect: GL03
    return jax.shard_map(
        local_update, mesh=mesh, in_specs=in_specs, out_specs=P(DATA_AXIS)
    )


def bad_axis_index():
    return lax.axis_index("chips")  # expect: GL03


def bad_all_gather(x):
    return lax.all_gather(x, axis_name="replica")  # expect: GL03
