"""GL08 negative cases: donation used the way the contract intends."""

import jax
from functools import partial


def advance(nid, xb):
    return nid + xb.sum(axis=1).astype(nid.dtype)


def rebind_level_loop(xb, nid0):
    # the canonical fused-builder shape: each call consumes the previous
    # buffer and rebinds the name to the fresh output
    step = jax.jit(advance, donate_argnums=(0,))
    for _ in range(4):
        nid0 = step(nid0, xb)
    return nid0


def last_use_at_call(xb, nid0):
    step = jax.jit(advance, donate_argnums=(0,))
    return step(nid0, xb)


def fresh_expression_donated(xb, nid0):
    step = jax.jit(advance, donate_argnums=(0,))
    out = step(nid0 * 2, xb)
    return out + nid0.sum()  # nid0 itself was never donated


def restore_before_read(xb, nid0):
    step = jax.jit(advance, donate_argnums=(0,))
    out = step(nid0, xb)
    nid0 = jax.device_put(out)
    return out + nid0.sum()  # reads the fresh binding, not the donated one


@partial(jax.jit, donate_argnames=("state",))
def consume(state, x):
    return state + x


def read_other_args_freely(state, x):
    y = consume(state, x)
    return y + x.sum()  # x is not donated; reading it stays legal


def metadata_survives_donation(xb, nid0):
    # .shape/.ndim/len() read the retained aval, never the released
    # buffer — legal after donation
    step = jax.jit(advance, donate_argnums=(0,))
    out = step(nid0, xb)
    assert out.shape == nid0.shape and len(nid0) == nid0.shape[0]
    return out
