"""sklearn estimator-conformance suite (check_estimator).

The reference claims sklearn compatibility only by inheritance
(``decision_tree.py:17``; SURVEY.md §4). Here the full ``check_estimator``
battery runs against every estimator, with an explicit allowlist for the two
deliberate deviations:

- ``predict_proba`` returns RAW CLASS COUNTS, not probabilities — the
  reference's documented quirk (``decision_tree.py:192-227``), which trips
  sklearn's proba-sums-to-1 assertion;
- bootstrap forests cannot satisfy weight-vs-row-duplication equivalence
  (resampling distributions differ; sklearn's own forests are exempted the
  same way).
"""

import warnings

import pytest
from sklearn.utils.estimator_checks import check_estimator

from mpitree_tpu import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
    RandomForestClassifier,
    RandomForestRegressor,
)

EXPECTED_FAILURES = {
    "DecisionTreeClassifier": {
        # raw-count predict_proba (reference parity quirk)
        "check_classifiers_train",
    },
    "DecisionTreeRegressor": set(),
    "RandomForestClassifier": {
        "check_sample_weight_equivalence_on_dense_data",  # bootstrap
    },
    "RandomForestRegressor": {
        "check_sample_weight_equivalence_on_dense_data",  # bootstrap
    },
}


@pytest.mark.parametrize(
    "estimator",
    [
        DecisionTreeClassifier(max_depth=4),
        DecisionTreeRegressor(max_depth=4),
        RandomForestClassifier(n_estimators=3, max_depth=3),
        RandomForestRegressor(n_estimators=3, max_depth=3),
    ],
    ids=lambda e: type(e).__name__,
)
def test_sklearn_conformance(estimator):
    name = type(estimator).__name__
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        results = check_estimator(estimator, on_fail=None)
    unexpected = [
        r
        for r in results
        if r.get("status") not in ("passed", "skipped")
        and r.get("check_name") not in EXPECTED_FAILURES[name]
    ]
    assert not unexpected, [
        (r.get("check_name"), str(r.get("exception"))[:120]) for r in unexpected
    ]
    n_passed = sum(r.get("status") == "passed" for r in results)
    assert n_passed >= 55  # the battery is substantive, not vacuous
