"""Partition-rule table + 2-D mesh shape policy (ISSUE 10 tentpole).

The table (``parallel/partition.py``) is the ONE declarative map from
build-state array names to PartitionSpecs over the ``(data, feature)``
mesh; both device engines derive their shard_map in_specs and initial
placements from it. These tests pin the rules, the 1-D trim, the
shard/sharding-tree helpers (SNIPPETS [2]/[3] idiom), and the
``data_feature_shape`` policy mirroring ``tree_data_shape``.
"""

from __future__ import annotations

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mpitree_tpu.parallel import mesh as mesh_lib
from mpitree_tpu.parallel import partition
from mpitree_tpu.parallel.mesh import DATA_AXIS, FEATURE_AXIS


def test_rule_table_covers_build_state_names():
    expect = {
        "x_binned": P(DATA_AXIS, FEATURE_AXIS),
        "y": P(DATA_AXIS),
        "weight": P(DATA_AXIS),
        "sample_weight": P(DATA_AXIS),
        "node_id": P(DATA_AXIS),
        "nid0": P(DATA_AXIS),
        "cand_mask": P(FEATURE_AXIS, None),
        "cand_masks": P(FEATURE_AXIS, None),
        "parent_hist": P(None, FEATURE_AXIS, None, None),
        "hist_keep": P(None, FEATURE_AXIS, None, None),
        # host-built per-node tables and config vectors replicate
        "is_small": P(),
        "parent_slot": P(),
        "node_mask": P(),
        "draws": P(),
        "mono_cst": P(),
        "mono_lo": P(),
        "mono_hi": P(),
        "is_split": P(),
        "feat": P(),
        "bin": P(),
        "left_id": P(),
        "right_id": P(),
    }
    for name, spec in expect.items():
        assert partition.match_partition_rules(name) == spec, name


def test_scalars_never_partition():
    # the SNIPPETS [2] rule: 0-d values get P() regardless of their name
    assert partition.match_partition_rules("x_binned", ndim=0) == P()
    assert partition.match_partition_rules("chunk_lo", ndim=0) == P()


def test_rank_mismatch_is_a_table_bug():
    with pytest.raises(ValueError, match="rank"):
        partition.match_partition_rules("x_binned", ndim=1)


def test_trim_to_1d_mesh_drops_feature_axis():
    mesh1d = mesh_lib.resolve_mesh(n_devices=4)
    assert partition.spec_for("x_binned", mesh1d) == P(DATA_AXIS, None)
    assert partition.spec_for("cand_mask", mesh1d) == P(None, None)
    assert partition.spec_for("parent_hist", mesh1d) == P(
        None, None, None, None
    )
    mesh2d = mesh_lib.resolve_mesh(n_devices=(2, 2))
    assert partition.spec_for("x_binned", mesh2d) == P(
        DATA_AXIS, FEATURE_AXIS
    )


def test_in_specs_for_orders_and_scalars():
    mesh2d = mesh_lib.resolve_mesh(n_devices=(2, 2))
    specs = partition.in_specs_for(
        mesh2d, ("x_binned", "y", ("chunk_lo", 0), "cand_mask")
    )
    assert specs == (
        P(DATA_AXIS, FEATURE_AXIS), P(DATA_AXIS), P(), P(FEATURE_AXIS, None)
    )


def test_shard_build_state_places_per_table():
    mesh = mesh_lib.resolve_mesh(n_devices=(4, 2))
    state = {
        "x_binned": np.zeros((16, 6), np.int32),
        "y": np.zeros(16, np.int32),
        "weight": np.ones(16, np.float32),
        "node_id": np.zeros(16, np.int32),
        "cand_mask": np.ones((6, 4), bool),
        "mcw": np.float32(0.0),  # scalar -> replicated
    }
    tree = partition.sharding_tree(mesh, state)
    assert tree["x_binned"].spec == P(DATA_AXIS, FEATURE_AXIS)
    assert tree["cand_mask"].spec == P(FEATURE_AXIS, None)
    assert tree["mcw"].spec == P()
    placed = partition.shard_build_state(mesh, state)
    for name, v in placed.items():
        assert v.sharding.spec == tree[name].spec, name
    # per-shard slab shapes: rows /4, features /2
    shard_shapes = {
        s.data.shape for s in placed["x_binned"].addressable_shards
    }
    assert shard_shapes == {(4, 3)}


def test_unknown_name_without_catchall_raises():
    with pytest.raises(ValueError, match="not found"):
        partition.match_partition_rules(
            "mystery", rules=partition.PARTITION_RULES[:-1]
        )


# ---------------------------------------------------------------------------
# mesh shape policy: data axis stays widest; the feature axis engages
# only when one shard's histogram slab exceeds the budget — the mirror of
# tree_data_shape's HBM guard.
# ---------------------------------------------------------------------------

def test_data_feature_shape_defaults_to_all_data():
    assert mesh_lib.data_feature_shape(8, 54) == (8, 1)
    assert mesh_lib.data_feature_shape(8, 54, hist_bytes=1 << 20) == (8, 1)


def test_data_feature_shape_widens_features_under_budget_pressure():
    # slab must fit 1 MiB: 4 MiB full histogram -> 4 feature shards
    assert mesh_lib.data_feature_shape(
        8, 54, hist_bytes=4 << 20, hist_budget=1 << 20
    ) == (2, 4)
    # 2 MiB -> 2 shards suffice (widest data axis that fits)
    assert mesh_lib.data_feature_shape(
        8, 54, hist_bytes=2 << 20, hist_budget=1 << 20
    ) == (4, 2)


def test_data_feature_shape_caps_at_feature_count_and_degrades():
    # only 3 features: divisor 4 of 8 is unusable, widest usable is 2 —
    # used even though the slab still exceeds the budget (degrade, never
    # refuse)
    assert mesh_lib.data_feature_shape(
        8, 3, hist_bytes=64 << 20, hist_budget=1 << 20
    ) == (4, 2)
    assert mesh_lib.data_feature_shape(1, 54, hist_budget=1) == (1, 1)


def test_resolve_mesh_2d_applies_policy():
    m = mesh_lib.resolve_mesh_2d(
        n_features=54, hist_bytes=4 << 20, hist_budget=1 << 20,
        n_devices=8,
    )
    assert dict(zip(m.axis_names, m.devices.shape)) == {
        DATA_AXIS: 2, FEATURE_AXIS: 4
    }
    # an explicit tuple bypasses the policy
    m2 = mesh_lib.resolve_mesh_2d(n_features=54, n_devices=(4, 2))
    assert dict(zip(m2.axis_names, m2.devices.shape)) == {
        DATA_AXIS: 4, FEATURE_AXIS: 2
    }
    # df == 1 resolves to the plain 1-D data mesh
    m3 = mesh_lib.resolve_mesh_2d(n_features=54, n_devices=8)
    assert m3.axis_names == (DATA_AXIS,)
