"""mpitree_tpu.obs — schema, gating, accounting, and registry contracts.

The two satellite guarantees ISSUE 3 pins here:

- **golden schema**: ``BuildRecord.to_dict()``'s top-level field names are
  frozen — bench/watcher consumers parse them out of committed
  ``BENCH_TPU.jsonl`` lines, so a rename must bump ``SCHEMA_VERSION``
  and fail THIS test first, never break consumers silently;
- **disabled path**: with observability off a fit allocates no per-level
  record rows and stays within 5% wall time of a stripped timer on the
  2k-row smoke workload.
"""

import dataclasses
import json
import time

import numpy as np
import pytest

from mpitree_tpu.core.builder import BuildConfig, build_tree
from mpitree_tpu.obs import (
    SCHEMA_VERSION,
    TOP_LEVEL_FIELDS,
    BuildObserver,
    BuildRecord,
    CompileRegistry,
    digest,
)
from mpitree_tpu.obs import accounting
from mpitree_tpu.ops.binning import bin_dataset
from mpitree_tpu.parallel import mesh as mesh_lib
from mpitree_tpu.parallel.collective import (
    counts_psum_bytes,
    gbdt_leaf_psum_bytes,
    replication_check_bytes,
    select_global_bytes,
    split_psum_bytes,
)
from mpitree_tpu.utils.profiling import PhaseTimer, trace


def _data(n=2000, f=8, seed=5):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64) + (X[:, 1] > 0.5)
    return X, y


# ---------------------------------------------------------------------------
# golden schema
# ---------------------------------------------------------------------------

def test_build_record_schema_golden():
    """Field names are pinned: renaming/removing one is a versioned act."""
    rep = BuildObserver(timing=False).report()
    assert tuple(sorted(rep)) == tuple(sorted(TOP_LEVEL_FIELDS))
    # v4 (ISSUE 9): top-level wire (the collective ledger's per-site/
    # per-fit/per-shard wire-traffic estimates) and digest
    # wire_bytes/wire_shard_bytes; compile entries may carry 'seconds'
    # (cold-dispatch attribution per jit entry point)
    # v5 (ISSUE 10): wire attributes per MESH AXIS (site entries carry
    # 'axis', top level gains axes/data_bytes/feature_bytes) and the
    # digest gains feature_shards
    # v6 (ISSUE 12): top-level memory (the obs.memory device/host
    # ledger) and digest hbm_peak_bytes/host_peak_bytes
    # v7 (ISSUE 13): top-level fingerprints (per-level u64 build-state
    # fingerprints, obs/fingerprint.py) and the digest's whole-fit
    # fingerprint
    # v8 (ISSUE 14, resilience v2): digest gains level_retries /
    # oom_rescues (the sub-build retry + OOM-rescue rung counters)
    # v9 (ISSUE 18): top-level compute (the obs.cost XLA cost-model
    # ledger: per-entry flop/byte floors, utilization, roofline) and
    # digest util_pct/roofline
    assert rep["schema"] == SCHEMA_VERSION == 9
    # dataclass fields and the pinned tuple must agree too
    assert tuple(
        f.name for f in dataclasses.fields(BuildRecord)
    ) == TOP_LEVEL_FIELDS
    # digest field names are part of the same contract (bench section
    # lines and the watcher format stored digests)
    assert tuple(sorted(digest(rep))) == tuple(sorted((
        "engine", "reason", "n_nodes", "depth", "levels", "compile_new",
        "psum_bytes", "sub_frac", "expansions", "rounds_per_dispatch",
        "events", "wire_bytes", "wire_shard_bytes", "feature_shards",
        "hbm_peak_bytes", "host_peak_bytes", "fingerprint",
        "level_retries", "oom_rescues",
        "util_pct", "roofline",
        "wall_s",
    )))


def test_record_json_round_trip():
    obs = BuildObserver(timing=False)
    obs.counter("x", 3)
    obs.decision("engine", "fused", reason="r", rows=np.int64(10))
    obs.event("f32_ceiling", "msg")
    obs.collective("split_hist_psum", calls=2, nbytes=np.int64(1024))
    rep = obs.report()
    text = json.dumps(rep)  # numpy scalars must already be coerced
    assert json.loads(text) == rep
    rec = BuildRecord.from_json(text)
    assert rec.counters == {"x": 3}
    assert rec.engine["value"] == "fused"


def test_digest_shape():
    obs = BuildObserver(timing=False)
    obs.decision("engine", "levelwise", reason="because")
    obs.collective("split_hist_psum", calls=4, nbytes=2_000_000)
    obs.compile_note("split_fn_digest_test", ("k",))
    rep = obs.report()
    d = digest(rep)
    assert d["engine"] == "levelwise"
    assert d["psum_bytes"] == 2_000_000
    assert d["compile_new"] == 1
    assert d["sub_frac"] is None  # no row counters recorded
    # the one-line string rendering is bench_tpu.format_record_digest —
    # deliberately jax-free, covered by tests/test_bench_contract.py


# ---------------------------------------------------------------------------
# gating: always-on vs profile-gated channels
# ---------------------------------------------------------------------------

def test_level_rows_gated_and_capped():
    off = BuildObserver(timing=False)
    off.level(level=0, frontier=1)
    assert off.record.levels == []  # disabled: never allocated

    on = BuildObserver(timing=True)
    for i in range(on.MAX_LEVEL_ROWS + 5):
        on.level(level=i, frontier=1)
    assert len(on.record.levels) == on.MAX_LEVEL_ROWS
    assert on.record.counters["levels_dropped"] == 5  # honest cap


def test_level_rows_stream_past_cap(tmp_path):
    """ISSUE 8: with a sink, rows past the cap stream instead of drop —
    leaf-wise builds emit one row per expansion and need the tail."""
    obs = BuildObserver(timing=True)
    spill = tmp_path / "levels.jsonl"
    obs.stream_levels_to(spill)
    total = obs.MAX_LEVEL_ROWS + 7
    for i in range(total):
        obs.level(level=i, frontier=1, rows_scanned=np.int64(i))
    rep = obs.report()
    assert len(rep["levels"]) == obs.MAX_LEVEL_ROWS
    assert "levels_dropped" not in rep["counters"]
    assert rep["level_stream"] == {"path": str(spill), "rows": 7}
    rows = [json.loads(line) for line in spill.read_text().splitlines()]
    assert [r["level"] for r in rows] == list(range(obs.MAX_LEVEL_ROWS, total))
    assert all(isinstance(r["rows_scanned"], int) for r in rows)  # jsonable


def test_level_rows_stream_env_dir(tmp_path, monkeypatch):
    """MPITREE_TPU_OBS_STREAM_DIR configures the sink ambiently (estimators
    build their observer internally)."""
    monkeypatch.setenv("MPITREE_TPU_OBS_STREAM_DIR", str(tmp_path))
    obs = BuildObserver(timing=True)
    for i in range(obs.MAX_LEVEL_ROWS + 2):
        obs.level(level=i)
    rep = obs.report()
    assert rep["level_stream"]["rows"] == 2
    assert rep["level_stream"]["path"].startswith(str(tmp_path))


def test_level_rows_stream_unwritable_dir_degrades(tmp_path, monkeypatch):
    """An unwritable ambient sink must never abort a fit: rows past the
    cap drop with a typed event instead of raising out of the build."""
    # a FILE as the dir's parent raises even for root (chmod-based
    # read-only dirs don't)
    blocker = tmp_path / "blocker"
    blocker.write_text("")
    monkeypatch.setenv("MPITREE_TPU_OBS_STREAM_DIR", str(blocker / "sub"))
    obs = BuildObserver(timing=True)
    for i in range(obs.MAX_LEVEL_ROWS + 3):
        obs.level(level=i)  # must not raise
    rep = obs.report()
    assert rep["counters"]["levels_dropped"] == 3
    assert rep["level_stream"] == {}
    assert any(
        e["kind"] == "level_stream_failed" for e in rep["events"]
    )


def test_level_stream_fd_closed_on_report(tmp_path):
    """report() closes the spill fd (no leak per fit in long-lived
    processes); a post-report spill reopens the same file in append."""
    obs = BuildObserver(timing=True)
    spill = tmp_path / "levels.jsonl"
    obs.stream_levels_to(spill)
    for i in range(obs.MAX_LEVEL_ROWS + 2):
        obs.level(level=i)
    obs.report()
    assert obs._level_stream_file is None
    obs.level(level=99999)  # reopens in append mode
    rep = obs.report()
    assert rep["level_stream"]["rows"] == 3
    rows = [json.loads(line) for line in spill.read_text().splitlines()]
    assert rows[-1]["level"] == 99999


def test_events_capped_honestly():
    obs = BuildObserver(timing=False)
    for i in range(obs.MAX_EVENTS + 3):
        obs.event("k", f"m{i}")
    assert len(obs.record.events) == obs.MAX_EVENTS
    assert obs.record.counters["events_dropped"] == 3


def test_compile_registry_counts_and_churn_warning():
    reg = CompileRegistry()
    assert reg.note("entry", ("a",)) is True
    assert reg.note("entry", ("a",)) is False  # cached executable
    assert reg.count("entry") == 1
    with pytest.warns(UserWarning, match="recompile churn"):
        for i in range(1, 64):
            reg.note("entry", ("key", i))
    # warns once, not on every further key
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        reg.note("entry", ("key", 999))


def test_compile_registry_mirrors_lru_eviction():
    """A key evicted from the factory's lru_cache re-compiles on device —
    the registry must report it as new again, not claim it warm."""
    reg = CompileRegistry()
    assert reg.note("e", "k0", cache_size=2) is True
    assert reg.note("e", "k1", cache_size=2) is True
    assert reg.note("e", "k0", cache_size=2) is False  # still cached
    assert reg.note("e", "k2", cache_size=2) is True   # evicts k1 (LRU)
    assert reg.note("e", "k1", cache_size=2) is True   # evicted: re-lowers
    assert reg.note("e", "k0", cache_size=2) is True   # k0 evicted by k1
    assert reg.count("e") == 5  # lowering EVENTS, not distinct keys


# ---------------------------------------------------------------------------
# static accounting
# ---------------------------------------------------------------------------

def test_collective_byte_helpers():
    assert split_psum_bytes(
        n_slots=8, n_features=4, n_bins=16, n_channels=3
    ) == 8 * 4 * 16 * 3 * 4
    assert split_psum_bytes(
        n_slots=8, n_features=4, n_bins=16, n_channels=3, itemsize=8
    ) == 8 * 4 * 16 * 3 * 8
    assert counts_psum_bytes(n_slots=64, n_channels=7) == 64 * 7 * 4
    # winner merge: the (4, K) f32 pack PLUS the (K,) non-constant psum
    # that decides the merged `constant` flag — 5 f32 per slot total
    assert select_global_bytes(n_slots=16) == 5 * 16 * 4
    # GBDT leaf refit: G and H over the padded M node slots + two loss
    # scalars, widened on scoped-x64
    assert gbdt_leaf_psum_bytes(n_slots=63) == 2 * 63 * 4 + 8
    assert gbdt_leaf_psum_bytes(n_slots=63, itemsize=8) == 2 * 63 * 8 + 8
    # determinism probe: scalar participant count + scalar fingerprint
    assert replication_check_bytes() == 2 * 4


def test_leafwise_replay_prices_gbdt_leaf_psum():
    """The fused-rounds replay must carry the per-round leaf G/H + loss
    psums in the wire ledger — present exactly when the engine passes the
    padded slot count, absent for plain (non-GBDT) leaf-wise builds."""
    import types

    tree = types.SimpleNamespace(
        n_node_samples=np.array([10, 6, 4]),
        left=np.array([1, -1, -1]),
        right=np.array([2, -1, -1]),
        depth=np.array([0, 1, 1]),
    )
    common = dict(n_features=4, n_bins=16, n_channels=2,
                  task="regression", subtraction=False)
    _, coll, _ = accounting.leafwise_scan_rows(tree, **common)
    assert "gbdt_leaf_psum" not in coll
    _, coll, _ = accounting.leafwise_scan_rows(
        tree, gbdt_leaf_slots=63, gbdt_x64=True, **common
    )
    assert coll["gbdt_leaf_psum"] == {"calls": 1, "bytes": 2 * 63 * 8 + 8}


def test_debug_build_prices_replication_check():
    """The determinism probe's scalar psums are real fabric traffic: a
    debug build must surface a ``replication_check`` ledger entry whose
    bytes match calls x the static per-probe payload (and a non-debug
    build must not invent one)."""
    X, y = _data(400, f=4)
    binned = bin_dataset(X, max_bins=16, binning="quantile")
    mesh = mesh_lib.resolve_mesh(n_devices=None)
    n_classes = int(y.max()) + 1

    obs = BuildObserver(timing=False)
    build_tree(
        binned, y, config=BuildConfig(max_depth=3, debug=True), mesh=mesh,
        n_classes=n_classes, timer=obs,
    )
    entry = obs.record.collectives.get("replication_check")
    assert entry is not None, sorted(obs.record.collectives)
    assert entry["calls"] >= 1
    assert entry["bytes"] == entry["calls"] * replication_check_bytes()

    plain = BuildObserver(timing=False)
    build_tree(
        binned, y, config=BuildConfig(max_depth=3), mesh=mesh,
        n_classes=n_classes, timer=plain,
    )
    assert "replication_check" not in plain.record.collectives


def test_fused_level_rows_replay_matches_depth_histogram():
    # A 3-level tree: 1 root, 2, then 4 nodes at the terminal depth cap.
    depths = np.array([0, 1, 1, 2, 2, 2, 2], np.int32)
    rows, coll = accounting.fused_level_rows(
        depths, n_slots=64, tiers=(8,), n_features=5, n_bins=16,
        n_channels=3, counts_channels=3, max_depth=2, task="classification",
    )
    assert [r["frontier"] for r in rows] == [1, 2, 4]
    assert [r["splits"] for r in rows] == [1, 2, 0]
    # interior levels ride the 8-slot tier; the depth-2 level is terminal
    per_chunk = split_psum_bytes(
        n_slots=8, n_features=5, n_bins=16, n_channels=3
    )
    assert coll["split_hist_psum"] == {"calls": 2, "bytes": 2 * per_chunk}
    assert coll["counts_psum"]["calls"] == 1
    assert rows[2]["hist_bytes"] == 0  # terminal: counts-only scatter


def test_effective_tiers_trim_matches_depth_cap():
    # depth cap 3 bounds interior frontiers at 4: the 64 tier is dead
    assert accounting.effective_tiers((8, 64), 3) == (8,)
    assert accounting.effective_tiers((8, 64), -1) == (8, 64)
    assert accounting.interior_big_reachable((8,), 3) is False
    assert accounting.interior_big_reachable((8, 64), -1) is True


# ---------------------------------------------------------------------------
# disabled path: no rows, <5% wall overhead on the 2k-row smoke workload
# ---------------------------------------------------------------------------

def test_disabled_observability_no_rows_and_cheap():
    """Medians over interleaved repeats (ISSUE 9 satellite): the old
    one-shot/best-of ratio flaked under concurrent background load —
    one descheduled run on either side flipped the verdict. Interleaving
    exposes both timers to the same load profile and the median shrugs
    off asymmetric outliers that min() happened to absorb only when the
    spike hit the lucky side.

    Hardened again (ISSUE 18, the PR 16 contention flake): the verdict
    is the median of the PAIRED per-repeat deltas, not a ratio of two
    independent medians — each pair runs back to back under the same
    load, so a spike that lands between repeats inflates both sides of
    its pair and cancels, where before it could straddle the two
    separately-computed medians."""
    import statistics

    X, y = _data(2000)
    binned = bin_dataset(X, max_bins=64, binning="quantile")
    mesh = mesh_lib.resolve_mesh(n_devices=None)
    cfg = BuildConfig(max_depth=8, engine="levelwise")
    n_classes = int(y.max()) + 1

    def run(timer):
        t0 = time.perf_counter()
        build_tree(
            binned, y, config=cfg, mesh=mesh, n_classes=n_classes,
            timer=timer,
        )
        return time.perf_counter() - t0

    run(PhaseTimer(enabled=False))  # compile warm-up, both paths share it
    t_plain, t_obs = [], []
    obs_timers = []
    for _ in range(9):  # interleaved so load spikes hit both sides alike
        t_plain.append(run(PhaseTimer(enabled=False)))
        obs = BuildObserver(timing=False)
        t_obs.append(run(obs))
        obs_timers.append(obs)
    for obs in obs_timers:
        assert obs.record.levels == []  # no per-level rows allocated
        assert obs.record.phases == {}
    med_plain = statistics.median(t_plain)
    med_delta = statistics.median(
        o - p for p, o in zip(t_plain, t_obs)
    )
    # <5% wall vs the stripped timer (plus 5ms absolute for clock grain)
    assert med_delta <= med_plain * 0.05 + 0.005, (
        f"disabled-observability overhead: median paired delta "
        f"{med_delta:.4f}s vs {med_plain:.4f}s stripped "
        f"({sorted(t_obs)} vs {sorted(t_plain)})"
    )
    # ...while the always-on channels still populated for free
    rep = obs_timers[-1].report()
    assert rep["engine"]["value"] == "levelwise"
    assert rep["collectives"]["split_hist_psum"]["bytes"] > 0


# ---------------------------------------------------------------------------
# trace() half-entered hazard (satellite 2)
# ---------------------------------------------------------------------------

def test_trace_entry_failure_stops_profiler_and_reports(monkeypatch):
    import jax

    stopped = []

    class _Boom:
        def __enter__(self):
            raise RuntimeError("log dir unwritable")

        def __exit__(self, *a):  # pragma: no cover — must not be reached
            raise AssertionError("half-entered ctx must not __exit__")

    monkeypatch.setattr(jax.profiler, "trace", lambda log_dir: _Boom())
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: stopped.append(True)
    )
    obs = BuildObserver(timing=False)
    with trace("/nonexistent/dir", on_event=obs.event):
        ran = True
    assert ran
    assert stopped == [True]  # any half-started profiler was stopped
    assert obs.record.events == [{
        "kind": "trace_unavailable",
        "message": "RuntimeError: log dir unwritable",
    }]


def test_trace_still_noop_without_callback(monkeypatch):
    import jax

    class _Boom:
        def __enter__(self):
            raise RuntimeError("nope")

        def __exit__(self, *a):
            raise AssertionError

    monkeypatch.setattr(jax.profiler, "trace", lambda log_dir: _Boom())
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    with trace("/nonexistent/dir"):
        pass  # old callers: silent no-op, but profiler is stopped
