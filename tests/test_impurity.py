import jax.numpy as jnp
import numpy as np

from mpitree_tpu.ops.histogram import class_histogram, moment_histogram
from mpitree_tpu.ops.impurity import (
    best_split_classification,
    best_split_regression,
    class_impurity,
)


def test_entropy_closed_form():
    counts = jnp.array([[8.0, 8.0], [16.0, 0.0], [4.0, 12.0]])
    n = counts.sum(-1)
    h = class_impurity(counts, n, "entropy")
    expect = [1.0, 0.0, -(0.25 * np.log2(0.25) + 0.75 * np.log2(0.75))]
    np.testing.assert_allclose(np.asarray(h), expect, rtol=1e-6)


def test_gini_closed_form():
    counts = jnp.array([[8.0, 8.0], [16.0, 0.0], [4.0, 12.0]])
    n = counts.sum(-1)
    g = class_impurity(counts, n, "gini")
    np.testing.assert_allclose(np.asarray(g), [0.5, 0.0, 1 - 0.25**2 - 0.75**2],
                               rtol=1e-6)


def _hist_for(X_binned, y, n_slots, n_bins, n_classes):
    return class_histogram(
        jnp.asarray(X_binned), jnp.asarray(y),
        jnp.zeros(len(y), jnp.int32), jnp.int32(0),
        n_slots=n_slots, n_bins=n_bins, n_classes=n_classes,
    )


def test_histogram_counts():
    X = np.array([[0, 1], [1, 1], [2, 0], [0, 0]], np.int32)
    y = np.array([0, 1, 1, 0], np.int32)
    h = np.asarray(_hist_for(X, y, 1, 3, 2))
    assert h.shape == (1, 2, 2, 3)  # (slots, features, classes, bins)
    assert h[0, 0, 0, 0] == 2  # rows 0,3 in bin 0 of feature 0, class 0
    assert h[0, 0, 1, 1] == 1  # row 1: feature 0 bin 1, class 1
    assert h[0, 1, 0, 1] == 1  # row 0: feature 1 bin 1, class 0
    assert h.sum() == 2 * 4  # every row counted once per feature


def test_histogram_masks_inactive_rows():
    X = np.zeros((4, 1), np.int32)
    y = np.zeros(4, np.int32)
    nid = jnp.asarray(np.array([0, -1, 5, 0], np.int32))
    h = class_histogram(jnp.asarray(X), jnp.asarray(y), nid, jnp.int32(0),
                        n_slots=2, n_bins=1, n_classes=1)
    assert np.asarray(h).sum() == 2  # rows 1 (padding) and 2 (other chunk) dropped


def test_best_split_simple_separation():
    # Feature 0 separates classes perfectly at bin 0; feature 1 is noise.
    X = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.int32)
    y = np.array([0, 0, 1, 1], np.int32)
    h = _hist_for(X, y, 1, 2, 2)
    d = best_split_classification(h, jnp.ones((2, 2), bool))
    assert int(d.feature[0]) == 0
    assert int(d.bin[0]) == 0
    np.testing.assert_allclose(float(d.cost[0]), 0.0, atol=1e-6)
    np.testing.assert_allclose(float(d.impurity[0]), 1.0, rtol=1e-6)
    assert not bool(d.constant[0])


def test_best_split_tie_breaks_lowest_feature_and_threshold():
    # Two identical features -> lowest index must win; symmetric thresholds
    # with equal cost -> lowest bin must win.
    X = np.array([[0, 0], [1, 1], [2, 2], [3, 3]], np.int32)
    y = np.array([0, 1, 0, 1], np.int32)
    h = _hist_for(X, y, 1, 4, 2)
    d = best_split_classification(h, jnp.ones((2, 4), bool))
    assert int(d.feature[0]) == 0
    costs_by_bin = []
    # brute-force the per-bin costs to find the expected first argmin
    for b in range(3):
        m = X[:, 0] <= b
        def ent(v):
            if len(v) == 0:
                return 0.0
            p = np.bincount(v) / len(v)
            p = p[p > 0]
            return -(p * np.log2(p)).sum()
        costs_by_bin.append((m.sum() * ent(y[m]) + (~m).sum() * ent(y[~m])) / 4)
    assert int(d.bin[0]) == int(np.argmin(costs_by_bin))


def test_constant_node_flag():
    X = np.zeros((5, 3), np.int32)
    y = np.array([0, 1, 0, 1, 0], np.int32)
    h = _hist_for(X, y, 1, 2, 2)
    d = best_split_classification(h, jnp.ones((3, 2), bool))
    assert bool(d.constant[0])
    assert np.isinf(float(d.cost[0]))  # no valid candidate either


def test_regression_split_variance_reduction():
    X = np.array([[0], [0], [1], [1]], np.int32)
    y = np.array([1.0, 1.0, 5.0, 5.0], np.float32)
    h = moment_histogram(jnp.asarray(X), jnp.asarray(y),
                         jnp.zeros(4, jnp.int32), jnp.int32(0),
                         n_slots=1, n_bins=2)
    d = best_split_regression(h, jnp.ones((1, 2), bool))
    assert int(d.feature[0]) == 0 and int(d.bin[0]) == 0
    np.testing.assert_allclose(float(d.cost[0]), 0.0, atol=1e-5)
    np.testing.assert_allclose(float(d.impurity[0]), 4.0, rtol=1e-5)  # var of y
