"""parallel/distributed.initialize() — idempotency/no-op unit coverage.

ISSUE 10 satellite: the multi-host bootstrap previously had zero direct
tests (its siblings, ``test_distributed_failures``/``_twoprocess``, cover
runtime failure semantics and need working process spawning). These pin
the SINGLE-host contracts: no-op without a coordinator, idempotent once
joined, graceful degrade when the runtime refuses, and the rank/size
view's field set — all monkeypatched, no real coordination service.
"""

from __future__ import annotations

import warnings

import pytest

from mpitree_tpu.parallel import distributed


@pytest.fixture(autouse=True)
def _reset_state(monkeypatch):
    """Each test sees a fresh module flag and a coordinator-free env."""
    monkeypatch.setattr(distributed, "_initialized", False)
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)


def test_initialize_is_a_noop_without_coordinator(monkeypatch):
    calls = []
    monkeypatch.setattr(
        distributed.jax.distributed, "initialize",
        lambda **kw: calls.append(kw),
    )
    assert distributed.initialize() is None
    assert calls == []  # single host, nothing to join
    assert distributed._initialized is False


def test_initialize_joins_once_and_is_idempotent(monkeypatch):
    calls = []

    def fake_init(**kw):
        calls.append(kw)

    monkeypatch.setattr(
        distributed.jax.distributed, "initialize", fake_init
    )
    distributed.initialize(
        coordinator_address="localhost:1234", num_processes=2, process_id=0,
        initialization_timeout=3,
    )
    assert distributed._initialized is True
    assert len(calls) == 1
    assert calls[0]["coordinator_address"] == "localhost:1234"
    assert calls[0]["initialization_timeout"] == 3  # knob passthrough
    # the second call must not re-join (the runtime raises on re-init)
    distributed.initialize(
        coordinator_address="localhost:9999", num_processes=2, process_id=0,
    )
    assert len(calls) == 1


def test_env_coordinator_triggers_join(monkeypatch):
    calls = []
    monkeypatch.setenv("COORDINATOR_ADDRESS", "host:8476")
    monkeypatch.setattr(
        distributed.jax.distributed, "initialize",
        lambda **kw: calls.append(kw),
    )
    distributed.initialize()  # env-driven discovery path
    assert len(calls) == 1
    assert distributed._initialized is True


def test_runtime_refusal_degrades_to_warning(monkeypatch):
    def refuse(**kw):
        raise RuntimeError("backend already initialized")

    monkeypatch.setattr(distributed.jax.distributed, "initialize", refuse)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        distributed.initialize(
            coordinator_address="localhost:1234", num_processes=2,
            process_id=0,
        )
    assert any(
        "distributed.initialize skipped" in str(w.message) for w in caught
    )
    assert distributed._initialized is False  # a later call may retry


def test_process_info_field_set():
    info = distributed.process_info()
    assert set(info) == {
        "process_index", "process_count", "local_devices", "global_devices",
    }
    assert info["process_count"] >= 1
    assert info["global_devices"] >= info["local_devices"] >= 1
