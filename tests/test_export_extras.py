"""Graphviz export and decision_path (sklearn-surface accessors)."""

import numpy as np

from mpitree_tpu import DecisionTreeClassifier, DecisionTreeRegressor


def _data(n=300, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = ((X[:, 0] > 0) + 2 * (X[:, 1] > 0.3)).astype(np.int64)
    return X, y


def test_export_dot_structure():
    X, y = _data()
    clf = DecisionTreeClassifier(max_depth=3, backend="host").fit(X, y)
    dot = clf.export_dot(feature_names=["a", "b", "c", "d"],
                         class_names=["u", "v", "w", "x"])
    assert dot.startswith("digraph Tree {") and dot.endswith("}")
    t = clf.tree_
    n_interior = int((t.feature >= 0).sum())
    assert dot.count(" -> ") == 2 * n_interior
    # every node appears with a label; leaves name classes, splits features
    for i in range(t.n_nodes):
        assert f'{i} [label="' in dot
    assert "class = " in dot and " <= " in dot
    assert 'headlabel="True"' in dot  # root edge annotations


def test_export_dot_regression():
    X, _ = _data(seed=1)
    yr = (X[:, 0] * 2).astype(np.float64)
    reg = DecisionTreeRegressor(max_depth=3, backend="host").fit(X, yr)
    dot = reg.export_dot()
    assert "value = " in dot and dot.count(" -> ") == 2 * int(
        (reg.tree_.feature >= 0).sum()
    )


def test_decision_path_matches_manual_walk():
    X, y = _data(seed=2)
    clf = DecisionTreeClassifier(max_depth=4, backend="host").fit(X, y)
    paths = clf.decision_path(X)
    t = clf.tree_
    assert paths.shape == (len(X), t.n_nodes)
    leaf_ids = clf.apply(X)
    for i in rng_rows(len(X)):
        # manual root->leaf walk
        expect = []
        node = 0
        while True:
            expect.append(node)
            if t.feature[node] < 0:
                break
            node = int(
                t.left[node]
                if X[i, t.feature[node]] <= t.threshold[node]
                else t.right[node]
            )
        got = paths.indices[paths.indptr[i]:paths.indptr[i + 1]]
        assert list(got) == expect
        assert expect[-1] == leaf_ids[i]
    # every row visits the root; row sums are path lengths (depth+1)
    assert (paths[:, 0].toarray().ravel() == 1).all()
    np.testing.assert_array_equal(
        np.asarray(paths.sum(axis=1)).ravel(), t.depth[leaf_ids] + 1
    )


def rng_rows(n, k=25, seed=3):
    return np.random.default_rng(seed).choice(n, size=min(k, n), replace=False)


def test_export_dot_escaping_and_validation():
    import pytest

    X, y = _data(seed=4)
    clf = DecisionTreeClassifier(max_depth=2, backend="host").fit(X, y)
    dot = clf.export_dot(feature_names=['si"ze', "b\\w", "c", "d"])
    assert '\\"' in dot and "\\\\" in dot  # quotes and backslashes escaped
    with pytest.raises(ValueError):
        clf.export_dot(feature_names=["only", "two"])
