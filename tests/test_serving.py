"""mpitree_tpu.serving — the compiled-inference parity and contract suite.

The load-bearing pins (ISSUE 7 acceptance):

- **Bit-identical parity**: ``CompiledModel.predict`` / ``predict_proba``
  / ``decision_function`` equal the estimator outputs EXACTLY (not
  allclose) for single trees, forests, ExtraTrees, and GBDT — including
  multi-device fits — because the fused traversal reproduces the
  estimators' host f64 sequential aggregation op for op.
- **True-depth descent**: a depth-capped ensemble whose members stopped
  early descends its TRUE depth, not the ``max_depth`` budget.
- **Warm request path**: after a registry publish, a request storm (and a
  model swap) adds ZERO compile cache-key entries and ZERO explicit
  device_put transfers on the request path.
- **Resilience**: a chaos-injected serving dispatch blip rides the retry
  ladder and still answers.
- **Kernel tier**: the Pallas traversal (interpret mode on this CPU mesh)
  agrees with the XLA tier; the forced-kernel policy falls back
  gracefully with a typed event off-TPU.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from mpitree_tpu import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
    ExtraTreesClassifier,
    GradientBoostingClassifier,
    GradientBoostingRegressor,
    RandomForestClassifier,
    RandomForestRegressor,
)
from mpitree_tpu.obs import REGISTRY
from mpitree_tpu.resilience import chaos
from mpitree_tpu.resilience.chaos import Fault
from mpitree_tpu.serving import (
    ModelRegistry,
    StreamStage,
    compile_model,
    tables_for,
)
from mpitree_tpu.serving import pallas_serve
from mpitree_tpu.serving.tables import table_notes


@pytest.fixture(autouse=True)
def _clean_chaos(monkeypatch):
    chaos.clear()
    monkeypatch.delenv("MPITREE_TPU_CHAOS", raising=False)
    monkeypatch.setenv("MPITREE_TPU_BACKOFF_S", "0")
    yield
    chaos.clear()


def _cls_data(n=300, f=8, c=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] ** 2 + rng.normal(scale=0.3, size=n) > 0.4
         ).astype(int)
    if c > 2:
        y = y + (X[:, 2] > 0.8).astype(int)
    return X, y


def _reg_data(n=300, f=6, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = X[:, 0] * 2 + np.sin(X[:, 1]) + rng.normal(scale=0.1, size=n)
    return X, y


def _oracle_leaf(tree, X):
    out = np.zeros(len(X), np.int32)
    for i, row in enumerate(X):
        n = 0
        while tree.feature[n] >= 0:
            n = (tree.left[n] if row[tree.feature[n]] <= tree.threshold[n]
                 else tree.right[n])
        out[i] = n
    return out


# ---------------------------------------------------------------------------
# Tables: depth packing, true depth, cached device residency
# ---------------------------------------------------------------------------

def test_table_depth_packing_and_oracle_descent():
    X, y = _cls_data()
    f = RandomForestClassifier(
        n_estimators=5, max_depth=6, random_state=0
    ).fit(X, y)
    [tb] = tables_for(f.trees_, group_bytes=None)
    # Level slabs: offsets monotone, cover all nodes, and every node's
    # depth matches its slab.
    assert tb.level_off[0] == 0 and tb.level_off[-1] == tb.n_nodes
    depths = np.concatenate([
        np.full(int(tb.level_off[d + 1] - tb.level_off[d]), d)
        for d in range(len(tb.level_off) - 1)
    ])
    all_depth = np.concatenate(
        [np.asarray(t.depth) for t in f.trees_]
    )[tb.scatter_order()]
    assert np.array_equal(depths, all_depth)
    # Children stay consistent through the permutation.
    inner = tb.feature >= 0
    assert (tb.left[inner] >= 0).all() and (tb.right[inner] >= 0).all()
    # The flat descent agrees with a per-row host recursion.
    from mpitree_tpu.ops.predict import stacked_leaf_ids

    ids = stacked_leaf_ids(f.trees_, X)
    for i, t in enumerate(f.trees_):
        assert np.array_equal(ids[i], _oracle_leaf(t, X))


def test_true_depth_n_steps_not_estimator_budget():
    # Tiny 1-feature data: trees cannot use their max_depth=9 budget.
    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 1)).astype(np.float32)
    y = (X[:, 0] > 0).astype(int)
    g = GradientBoostingClassifier(
        max_iter=4, max_depth=9, min_samples_leaf=30, random_state=0
    ).fit(X, y)
    true_depth = max(t.max_depth for t in g.trees_)
    assert true_depth < 9  # the premise: members stopped early
    [tb] = tables_for(g.trees_, group_bytes=None)
    assert tb.n_steps == max(true_depth, 1)
    assert table_notes(g.trees_)["n_steps"] == tb.n_steps
    # And the short descent still lands every row on its leaf.
    cm = compile_model(g)
    assert np.array_equal(cm.predict(X), g.predict(X))


def test_fit_report_carries_serving_notes():
    X, y = _cls_data()
    clf = DecisionTreeClassifier(max_depth=5).fit(X, y)
    notes = clf.fit_report_["decisions"]["serving"]
    assert notes["value"] == "flat-table"
    assert notes["inputs"]["n_steps"] == max(clf.tree_.max_depth, 1)
    f = RandomForestRegressor(n_estimators=3, max_depth=4).fit(
        *_reg_data()
    )
    assert f.fit_report_["decisions"]["serving"]["inputs"]["n_trees"] == 3


def test_stacked_leaf_ids_no_reupload_after_warm(monkeypatch):
    X, y = _cls_data()
    f = RandomForestClassifier(
        n_estimators=4, max_depth=5, random_state=0
    ).fit(X, y)
    from mpitree_tpu.ops import predict as predict_mod

    predict_mod.stacked_leaf_ids(f.trees_, X)  # build + upload tables
    calls = []
    real = jax.device_put
    monkeypatch.setattr(
        jax, "device_put", lambda *a, **k: calls.append(a) or real(*a, **k)
    )
    predict_mod.stacked_leaf_ids(f.trees_, X)
    # Only the query batch transfers — the PR-6-era per-call re-upload of
    # every tree slice is gone.
    assert len(calls) == 1


def test_stacked_leaf_ids_grouping_matches_single_table():
    X, y = _cls_data()
    f = RandomForestClassifier(
        n_estimators=6, max_depth=5, random_state=0
    ).fit(X, y)
    from mpitree_tpu.ops.predict import stacked_leaf_ids

    one = stacked_leaf_ids(f.trees_, X)
    # A tiny byte budget forces multiple tables; ids must not change.
    few = stacked_leaf_ids(f.trees_, X, group_bytes=1)
    assert len(tables_for(f.trees_, group_bytes=1)) > 1
    assert np.array_equal(one, few)


# ---------------------------------------------------------------------------
# Parity: serving outputs bit-identical to the estimator surface
# ---------------------------------------------------------------------------

def test_parity_classifier_tree():
    X, y = _cls_data()
    clf = DecisionTreeClassifier(max_depth=7).fit(X, y)
    cm = compile_model(clf)
    sp, ep = cm.predict_proba(X), clf.predict_proba(X)
    assert sp.dtype == ep.dtype and np.array_equal(sp, ep)
    assert np.array_equal(cm.predict(X), clf.predict(X))


def test_parity_classifier_tree_monotonic():
    X, y = _cls_data(c=2)
    cst = np.zeros(X.shape[1], int)
    cst[0] = 1
    clf = DecisionTreeClassifier(max_depth=5, monotonic_cst=cst).fit(X, y)
    cm = compile_model(clf)
    assert np.array_equal(cm.predict(X), clf.predict(X))


def test_parity_regressor_tree():
    X, y = _reg_data()
    r = DecisionTreeRegressor(max_depth=7).fit(X, y)
    cm = compile_model(r)
    assert np.array_equal(cm.predict(X), r.predict(X))


def test_parity_forest_classifier():
    X, y = _cls_data()
    f = RandomForestClassifier(
        n_estimators=9, max_depth=6, random_state=0
    ).fit(X, y)
    cm = compile_model(f)
    assert np.array_equal(cm.predict_proba(X), f.predict_proba(X))
    assert np.array_equal(cm.predict(X), f.predict(X))


def test_parity_extratrees():
    X, y = _cls_data()
    f = ExtraTreesClassifier(
        n_estimators=6, max_depth=6, random_state=0
    ).fit(X, y)
    cm = compile_model(f)
    assert np.array_equal(cm.predict_proba(X), f.predict_proba(X))


def test_parity_forest_regressor():
    X, y = _reg_data()
    f = RandomForestRegressor(
        n_estimators=7, max_depth=6, random_state=0
    ).fit(X, y)
    cm = compile_model(f)
    assert np.array_equal(cm.predict(X), f.predict(X))


def test_parity_gbdt_classifier_multiclass():
    X, y = _cls_data(c=3)
    g = GradientBoostingClassifier(
        max_iter=10, max_depth=3, random_state=0
    ).fit(X, y)
    cm = compile_model(g)
    assert np.array_equal(cm.decision_function(X), g.decision_function(X))
    assert np.array_equal(cm.predict_proba(X), g.predict_proba(X))
    assert np.array_equal(cm.predict(X), g.predict(X))


def test_recompile_after_lr_edit_rebuilds_margin_channel():
    # The node table (and its value channels) cache on the trees_ anchor
    # and OUTLIVE a CompiledModel; the margin channel bakes the learning
    # rate in. Editing lr and recompiling must serve the NEW scaling, not
    # the cached channel built under the old one.
    X, y = _cls_data(c=3)
    g = GradientBoostingClassifier(
        max_iter=6, max_depth=3, random_state=0
    ).fit(X, y)
    compile_model(g)  # populates the lr=0.1 channel on the shared table
    g.learning_rate = 0.05
    cm2 = compile_model(g)
    assert np.array_equal(cm2.decision_function(X), g.decision_function(X))


def test_parity_gbdt_binary_and_regressor():
    X, y = _cls_data(c=2)
    g = GradientBoostingClassifier(
        max_iter=8, max_depth=3, random_state=0
    ).fit(X, y)
    cm = compile_model(g)
    assert np.array_equal(cm.decision_function(X), g.decision_function(X))
    Xr, yr = _reg_data()
    gr = GradientBoostingRegressor(
        max_iter=8, max_depth=3, random_state=0
    ).fit(Xr, yr)
    assert np.array_equal(compile_model(gr).predict(Xr), gr.predict(Xr))


def test_parity_multidevice_fit():
    # A mesh-built forest serves from the same tables; serving stays
    # bit-identical to the (mesh-sharded) estimator predict.
    X, y = _cls_data(n=512)
    f = RandomForestClassifier(
        n_estimators=4, max_depth=5, random_state=0, n_devices=8,
        backend="cpu",
    ).fit(X, y)
    cm = compile_model(f)
    assert np.array_equal(cm.predict_proba(X), f.predict_proba(X))


def test_bucketing_pads_and_chunks():
    X, y = _cls_data()
    f = RandomForestClassifier(
        n_estimators=5, max_depth=5, random_state=0
    ).fit(X, y)
    cm = compile_model(f, buckets=(1, 16, 64))
    for n in (1, 2, 16, 17, 63, 64):  # pad-to-bucket shapes
        idx = np.arange(n) % len(X)
        assert np.array_equal(
            cm.predict_proba(X[idx]), f.predict_proba(X[idx])
        ), n
    big = np.tile(X, (2, 1))[:300]  # > max bucket: chunked dispatches
    assert np.array_equal(cm.predict_proba(big), f.predict_proba(big))


# ---------------------------------------------------------------------------
# Registry: warm pool, swap-under-load, zero-transfer request path
# ---------------------------------------------------------------------------

def test_registry_swap_zero_new_lowerings_on_request_path(monkeypatch):
    X, y = _cls_data()
    f1 = RandomForestClassifier(
        n_estimators=5, max_depth=5, random_state=0
    ).fit(X, y)
    f2 = RandomForestClassifier(
        n_estimators=5, max_depth=5, random_state=1
    ).fit(X, y)
    reg = ModelRegistry(buckets=(1, 16, 64))
    reg.publish("m", f1)
    reg.predict("m", X[:3])  # request warm-pool sanity
    reg.publish("m", f2)     # swap: compiles happen HERE (warmup)...
    n0 = REGISTRY.count("serving_traverse")
    calls = []
    real = jax.device_put
    monkeypatch.setattr(
        jax, "device_put", lambda *a, **k: calls.append(a) or real(*a, **k)
    )
    for n in (1, 2, 16, 16, 64, 40, 130):  # ...and NONE here.
        idx = np.arange(n) % len(X)
        out = reg.predict("m", X[idx])
        assert out.shape == (n,)
    assert REGISTRY.count("serving_traverse") == n0
    # Zero explicit transfers on the warmed request path: the table and
    # value channels are cached device-resident; only the batch (and its
    # donated accumulator) ride each jit call implicitly.
    assert calls == []
    assert reg.models()["m"]["generation"] == 2


def test_registry_unknown_name():
    reg = ModelRegistry()
    with pytest.raises(KeyError, match="no model published"):
        reg.get("ghost")


def test_serving_dispatch_blip_rides_retry_ladder():
    X, y = _cls_data()
    g = GradientBoostingClassifier(
        max_iter=4, max_depth=3, random_state=0
    ).fit(X, y)
    reg = ModelRegistry(buckets=(64,))
    with pytest.warns(UserWarning, match="transient device failure"):
        with chaos.active(Fault("serving_dispatch", 1, "unavailable")) as plan:
            reg.publish("g", g, warm=False)
            out = reg.predict("g", X[:10])
    assert plan.fired == [("serving_dispatch", 1, "unavailable")]
    assert np.array_equal(out, g.predict(X[:10]))
    rep = reg.get("g").serve_report_
    assert rep["counters"]["device_retries"] == 1
    assert any(e["kind"] == "device_retry" for e in rep["events"])


def test_serve_report_counters_and_decisions():
    X, y = _cls_data()
    f = RandomForestClassifier(
        n_estimators=3, max_depth=4, random_state=0
    ).fit(X, y)
    cm = compile_model(f, buckets=(32,))
    cm.predict(X[:10])
    rep = cm.serve_report_
    assert rep["decisions"]["serving_compile"]["value"] == "forest_proba"
    assert rep["decisions"]["serving_kernel"]["value"] == "xla"
    assert rep["counters"]["serving_requests"] >= 1
    assert rep["counters"]["serving_rows"] >= 10
    assert "serving_traverse" in rep["compile"]


def test_monotonic_forest_classifier_parity():
    # ISSUE 17 satellite: the constrained-forest serving channel is OPEN
    # (it used to refuse). The clipped class-0 fraction is a per-NODE
    # quantity — rows are final at build, ride the pure-add
    # ``forest_values`` kind, and the estimator equivalence is
    # bit-identical on the CPU tier.
    X, y = _cls_data(c=2)
    cst = np.zeros(X.shape[1], int)
    cst[0] = 1
    f = RandomForestClassifier(
        n_estimators=3, max_depth=4, random_state=0, monotonic_cst=cst
    ).fit(X, y)
    cm = compile_model(f)
    assert cm.kind == "forest_values"
    np.testing.assert_array_equal(cm.predict(X), f.predict(X))
    np.testing.assert_allclose(
        np.asarray(cm.predict_proba(X)), f.predict_proba(X),
        rtol=0, atol=0,
    )


def test_monotonic_forest_regressor_parity():
    # Regressor clipping is baked into count[:, 0] at fit time, so the
    # constrained forest serves the ordinary mean channel bit-identically.
    X, y = _reg_data()
    cst = np.zeros(X.shape[1], int)
    cst[0] = 1
    f = RandomForestRegressor(
        n_estimators=3, max_depth=4, random_state=0, monotonic_cst=cst
    ).fit(X, y)
    cm = compile_model(f)
    np.testing.assert_allclose(
        np.asarray(cm.predict(X)).ravel(), f.predict(X), rtol=0, atol=0
    )


# ---------------------------------------------------------------------------
# Streaming stage
# ---------------------------------------------------------------------------

def test_stream_stage_parity_and_backpressure():
    X, y = _cls_data()
    g = GradientBoostingClassifier(
        max_iter=6, max_depth=3, random_state=0
    ).fit(X, y)
    cm = compile_model(g, buckets=(64,))
    stage = StreamStage(cm, depth=2)
    results = []
    for lo in range(0, 300, 30):
        results += stage.submit(X[lo:lo + 30])
        assert len(stage._inflight) <= 2  # backpressure bound
    results += stage.drain()
    assert [t for t, _ in results] == list(range(10))  # order preserved
    got = np.concatenate([r for _, r in results], axis=0)
    assert np.array_equal(got, cm.raw(X))


def test_stream_stage_forest_mean_shape():
    # forest means travel on device as an (N, 1) accumulator column; the
    # stage must hand back the estimator-shaped (N,) result like raw().
    X, y = _reg_data()
    f = RandomForestRegressor(
        n_estimators=4, max_depth=4, random_state=0
    ).fit(X, y)
    cm = compile_model(f, buckets=(64,))
    stage = StreamStage(cm, depth=2)
    results = stage.submit(X[:50]) + stage.drain()
    [(_, out)] = results
    assert out.shape == (50,)
    assert np.array_equal(out, f.predict(X[:50]))


def test_stream_stage_rejects_bad_depth():
    X, y = _cls_data()
    g = DecisionTreeClassifier(max_depth=3).fit(X, y)
    with pytest.raises(ValueError, match="depth"):
        StreamStage(compile_model(g), depth=0)


# ---------------------------------------------------------------------------
# Pallas kernel tier (interpret mode on this CPU mesh) + policy
# ---------------------------------------------------------------------------

def _kernel_reference(trees, X, agg, n_out, values_fn):
    """Float32 reference for the kernel semantics (numpy)."""
    out = np.zeros((len(X), n_out), np.float32)
    for t_i, t in enumerate(trees):
        ids = _oracle_leaf(t, X)
        vals = np.asarray(values_fn(t), np.float32).reshape(t.n_nodes, -1)
        leaf = vals[ids]
        if agg == "norm":
            leaf = leaf / np.maximum(
                leaf.sum(axis=1, keepdims=True), 1.0
            )
            out += leaf
        elif agg == "percls":
            out[:, t_i % n_out] += leaf[:, 0]
        else:
            out += leaf
    return out


@pytest.mark.parametrize("agg", ["norm", "sum", "percls"])
def test_pallas_kernel_matches_reference(agg):
    X, y = _cls_data(n=80, f=5)
    f = RandomForestClassifier(
        n_estimators=4, max_depth=4, random_state=0
    ).fit(X, y)
    trees = list(f.trees_)
    C = len(f.classes_)
    if agg == "norm":
        n_out, kv = C, C
        values_fn = lambda t: np.asarray(t.count, np.float32)  # noqa: E731
    elif agg == "percls":
        n_out, kv = 2, 1
        values_fn = lambda t: np.asarray(  # noqa: E731
            t.count[:, 0], np.float32
        )
    else:
        n_out, kv = 1, 1
        values_fn = lambda t: np.asarray(  # noqa: E731
            t.n_node_samples, np.float32
        )
    tbl, _ = pallas_serve.build_kernel_tables(trees)
    vals = pallas_serve.build_kernel_values(trees, values_fn, kv)
    n_steps = max(t.max_depth for t in trees)
    got = np.asarray(pallas_serve.traverse_batch_pallas(
        X, tbl, vals, n_steps=max(n_steps, 1), agg=agg, n_out=n_out,
        kv=kv, row_tile=32, interpret=True,
    ))
    want = _kernel_reference(trees, X, agg, n_out, values_fn)
    # Integer-valued f32 payloads: the one-hot contraction is exact.
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_resolve_serving_kernel_policy(monkeypatch):
    from mpitree_tpu.obs import BuildObserver

    monkeypatch.delenv("MPITREE_TPU_SERVING_KERNEL", raising=False)
    # auto: off this CPU mesh (no Mosaic backend).
    assert not pallas_serve.resolve_serving_kernel(
        "cpu", n_nodes_max=100, n_features=8, kv=3, n_out=3
    )
    # forced pallas off-TPU: GRACEFUL fallback + typed event.
    obs = BuildObserver()
    monkeypatch.setenv("MPITREE_TPU_SERVING_KERNEL", "pallas")
    assert not pallas_serve.resolve_serving_kernel(
        "cpu", n_nodes_max=100, n_features=8, kv=3, n_out=3, obs=obs
    )
    assert any(
        e["kind"] == "serving_pallas_fallback"
        for e in obs.record.events
    )
    monkeypatch.setenv("MPITREE_TPU_SERVING_KERNEL", "xla")
    assert not pallas_serve.resolve_serving_kernel(
        "tpu", n_nodes_max=100, n_features=8, kv=3, n_out=3
    )
    monkeypatch.setenv("MPITREE_TPU_SERVING_KERNEL", "bogus")
    with pytest.raises(ValueError, match="MPITREE_TPU_SERVING_KERNEL"):
        pallas_serve.resolve_serving_kernel(
            "tpu", n_nodes_max=100, n_features=8, kv=3, n_out=3
        )
    # VMEM sizing: a table too large for the budget is rejected.
    assert not pallas_serve.fits_vmem(3_000_000, 54, 7, 7)
    assert pallas_serve.fits_vmem(2048, 54, 7, 7)


def test_serving_bench_headline_consumer(tmp_path):
    import json

    import bench_tpu

    path = tmp_path / "cap.jsonl"
    rec = {
        "platform_probe": "cpu",
        "serving": {
            "platform": "cpu", "n_trees": 504,
            "b1_p50_ms": 1.2, "b1_p99_ms": 3.0,
            "b64_p50_ms": 1.5, "b64_p99_ms": 3.1,
            "b4096_p50_ms": 20.0, "b4096_p99_ms": 25.0,
            "sustained_rows_per_s": 1_000_000,
            "speedup_vs_estimator": 3.4, "kernel": "xla",
            "request_path_lowerings": 0,
        },
    }
    path.write_text(json.dumps(rec) + "\n")
    line = bench_tpu.serving_headline(str(path))
    assert "504 trees" in line and "p99=3.0ms" in line
    assert "3.4x vs estimator" in line and "request_compiles=0" in line
    assert bench_tpu.serving_headline(str(tmp_path / "none.jsonl")) is None


# ---------------------------------------------------------------------------
# Batching fairness moved to the serving scheduler (ISSUE 17): the EDF
# ordering / burst-cannot-starve / deadline-miss pins now live at
# subsystem level in tests/test_serving_sched.py — the example
# micro-batcher they exercised was replaced by serving.scheduler.
# ---------------------------------------------------------------------------
