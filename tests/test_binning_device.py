"""Device binning is bit-identical to host binning.

The engine-identity contract (device tree == host tree) rests on both
paths consuming the same BinnedData; ``bin_dataset_device`` computes it on
the accelerator (sort/dedup-gather/compare-reduce, no scalar scatters), so
its thresholds, candidate counts, bin ids, n_bins and quantized flag must
match ``bin_dataset`` exactly — on duplicates-heavy, constant, near-unique
and overflow columns, in both "auto" and "quantile" modes.
"""

import numpy as np
import pytest

from mpitree_tpu.ops.binning import bin_dataset, bin_dataset_device


def _assert_identical(host, dev):
    np.testing.assert_array_equal(np.asarray(dev.x_binned), host.x_binned)
    np.testing.assert_array_equal(dev.thresholds, host.thresholds)
    np.testing.assert_array_equal(dev.n_cand, host.n_cand)
    assert dev.n_bins == host.n_bins
    assert dev.quantized == host.quantized
    assert dev.thresholds.dtype == host.thresholds.dtype
    assert np.asarray(dev.x_binned).dtype == host.x_binned.dtype


def _mixed_matrix(seed, n, max_bins):
    """Columns spanning every regime: constant, binary, duplicates-heavy
    (fits exact), exactly-at-the-boundary, and unique-per-row (overflows
    into quantile)."""
    rng = np.random.default_rng(seed)
    cols = [
        np.full(n, 3.25, np.float32),                       # constant
        rng.integers(0, 2, n).astype(np.float32),           # binary
        rng.integers(0, max_bins // 2, n).astype(np.float32),
        rng.integers(0, max_bins, n).astype(np.float32),    # boundary-ish
        rng.normal(size=n).astype(np.float32),              # ~all unique
        np.round(rng.normal(size=n), 1).astype(np.float32),
    ]
    return np.stack(cols, axis=1)


@pytest.mark.parametrize("binning", ["auto", "quantile"])
@pytest.mark.parametrize("seed,n,max_bins", [
    (0, 500, 32), (1, 1000, 64), (2, 257, 8), (3, 64, 256),
])
def test_device_matches_host(binning, seed, n, max_bins):
    X = _mixed_matrix(seed, n, max_bins)
    host = bin_dataset(X, max_bins=max_bins, binning=binning)
    dev = bin_dataset_device(X, max_bins=max_bins, binning=binning)
    _assert_identical(host, dev)


@pytest.mark.parametrize("seed", range(4))
def test_device_matches_host_fuzz(seed):
    rng = np.random.default_rng(1000 + seed)
    n = int(rng.integers(2, 600))
    f = int(rng.integers(1, 9))
    max_bins = int(rng.integers(2, 128))
    # heavy duplicate mass to stress the dedup/compaction paths
    X = np.round(
        rng.normal(size=(n, f)) * rng.integers(1, 50), 1
    ).astype(np.float32)
    for binning in ("auto", "quantile"):
        host = bin_dataset(X, max_bins=max_bins, binning=binning)
        dev = bin_dataset_device(X, max_bins=max_bins, binning=binning)
        _assert_identical(host, dev)


def test_single_row_and_single_feature():
    X = np.array([[7.0]], np.float32)
    _assert_identical(bin_dataset(X), bin_dataset_device(X))


def test_zero_rows_degenerate():
    X = np.empty((0, 3), np.float32)
    _assert_identical(bin_dataset(X), bin_dataset_device(X))


def test_max_bins_one_degenerate():
    # Q=0: zero candidates everywhere; host returns (F, 1) +inf thresholds
    # and n_cand 0 — the device path must match exactly (it delegates).
    rng = np.random.default_rng(2)
    X = rng.normal(size=(50, 3)).astype(np.float32)
    for binning in ("auto", "quantile"):
        host = bin_dataset(X, max_bins=1, binning=binning)
        dev = bin_dataset_device(X, max_bins=1, binning=binning)
        _assert_identical(host, dev)
        assert host.n_bins == 1 and host.n_cand.max(initial=0) == 0


def test_exact_mode_is_host_only():
    X = np.ones((4, 2), np.float32)
    with pytest.raises(ValueError, match="exact"):
        bin_dataset_device(X, binning="exact")


def test_estimator_identity_device_vs_host_binning(monkeypatch):
    """The same tree, bit for bit, whether the binned matrix was produced
    on host or on device (the engine-identity contract's new seam)."""
    from mpitree_tpu import DecisionTreeClassifier

    rng = np.random.default_rng(0)
    X = np.round(rng.normal(size=(400, 5)), 1).astype(np.float32)
    y = rng.integers(0, 3, 400)

    def fit():
        return DecisionTreeClassifier(
            max_depth=6, max_bins=16, backend="cpu"
        ).fit(X, y)

    # force=1: the cpu backend routes host by default (XLA-CPU binning is
    # ~26x slower than numpy at scale) — the seam still has to be identical
    monkeypatch.setenv("MPITREE_TPU_DEVICE_BIN", "1")
    dev_tree = fit().export_text()
    monkeypatch.setenv("MPITREE_TPU_DEVICE_BIN", "0")
    host_tree = fit().export_text()
    assert dev_tree == host_tree


def test_device_binned_uneven_rows_pad_on_device(monkeypatch):
    """N not divisible by the mesh width exercises pad_row_arrays' jnp
    branch (np.concatenate would silently pull the device matrix back to
    host); the fitted tree must equal the host-binned fit regardless."""
    from mpitree_tpu import DecisionTreeClassifier

    rng = np.random.default_rng(3)
    X = np.round(rng.normal(size=(401, 4)), 1).astype(np.float32)
    y = rng.integers(0, 3, 401)

    def fit():
        return DecisionTreeClassifier(
            max_depth=5, max_bins=16, backend="cpu", n_devices=8
        ).fit(X, y)

    monkeypatch.setenv("MPITREE_TPU_DEVICE_BIN", "1")
    dev_tree = fit().export_text()
    monkeypatch.setenv("MPITREE_TPU_DEVICE_BIN", "0")
    assert dev_tree == fit().export_text()


def test_device_array_output_feeds_builders():
    """x_binned comes back as a jax.Array (device-resident) — the point of
    the exercise; the shard step must not silently round-trip it to host."""
    import jax

    X = _mixed_matrix(5, 200, 16)
    dev = bin_dataset_device(X, max_bins=16)
    assert isinstance(dev.x_binned, jax.Array)
    assert isinstance(dev.thresholds, np.ndarray)
    assert isinstance(dev.n_cand, np.ndarray)
