"""Fused single-program builder: identity with the levelwise engine.

The fused engine (core/fused_builder.py) runs the whole build in one
lax.while_loop device program; its trees must match the host-orchestrated
levelwise engine exactly — same splits, counts, depths, rendering — at every
mesh size (classification exactly; regression up to f32 tie noise).
"""

import numpy as np
import pytest

from mpitree_tpu.core.builder import BuildConfig, build_tree
from mpitree_tpu.core.fused_builder import _node_capacity
from mpitree_tpu.ops.binning import bin_dataset
from mpitree_tpu.parallel import mesh as mesh_lib


def _build(X, y, engine, *, n_devices=1, task="classification", **kw):
    binned = bin_dataset(X, max_bins=64, binning="auto")
    mesh = mesh_lib.resolve_mesh(n_devices=n_devices)
    cfg = BuildConfig(task=task, criterion=kw.pop("criterion", "entropy")
                      if task == "classification" else "mse", engine=engine,
                      **kw)
    n_classes = int(y.max()) + 1 if task == "classification" else None
    return build_tree(binned, y, config=cfg, mesh=mesh, n_classes=n_classes)


def _assert_same_tree(a, b):
    np.testing.assert_array_equal(a.feature, b.feature)
    np.testing.assert_array_equal(a.left, b.left)
    np.testing.assert_array_equal(a.right, b.right)
    np.testing.assert_array_equal(a.parent, b.parent)
    np.testing.assert_array_equal(a.depth, b.depth)
    np.testing.assert_allclose(a.threshold, b.threshold, equal_nan=True)
    np.testing.assert_array_equal(a.count, b.count)
    np.testing.assert_array_equal(a.n_node_samples, b.n_node_samples)


@pytest.fixture(scope="module")
def clf_data():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(900, 6)).astype(np.float32)
    y = ((X[:, 0] > 0) + 2 * (X[:, 1] > 0.3)).astype(np.int64)
    return X, y


@pytest.mark.parametrize("n_devices", [1, 2, 8])
@pytest.mark.parametrize("criterion", ["entropy", "gini"])
def test_fused_equals_levelwise(clf_data, n_devices, criterion):
    X, y = clf_data
    a = _build(X, y, "fused", n_devices=n_devices, max_depth=7,
               criterion=criterion)
    b = _build(X, y, "levelwise", n_devices=n_devices, max_depth=7,
               criterion=criterion)
    _assert_same_tree(a, b)


def test_fused_unbounded_depth(clf_data):
    X, y = clf_data
    a = _build(X, y, "fused", max_depth=None)
    b = _build(X, y, "levelwise", max_depth=None)
    _assert_same_tree(a, b)


def test_fused_min_samples_split(clf_data):
    X, y = clf_data
    a = _build(X, y, "fused", max_depth=10, min_samples_split=40)
    b = _build(X, y, "levelwise", max_depth=10, min_samples_split=40)
    _assert_same_tree(a, b)


def test_fused_regression_quality(clf_data):
    X, _ = clf_data
    yr = (np.sin(X[:, 0]) + X[:, 1]).astype(np.float32)
    binned = bin_dataset(X, max_bins=64, binning="auto")
    mesh = mesh_lib.resolve_mesh(n_devices=8)
    a = build_tree(binned, yr, config=BuildConfig(
        task="regression", criterion="mse", max_depth=6, engine="fused"),
        mesh=mesh, refit_targets=yr.astype(np.float64))
    b = build_tree(binned, yr, config=BuildConfig(
        task="regression", criterion="mse", max_depth=6, engine="levelwise"),
        mesh=mesh, refit_targets=yr.astype(np.float64))
    assert a.n_nodes == b.n_nodes
    assert (a.feature == b.feature).mean() > 0.9


def test_fused_single_row_and_constant():
    X = np.ones((5, 3), np.float32)
    y = np.array([1, 1, 1, 1, 1])
    t = _build(X, y, "fused")
    assert t.n_nodes == 1 and t.feature[0] == -1
    X1 = np.array([[1.0, 2.0]], np.float32)
    t1 = _build(X1, np.array([0]), "fused")
    assert t1.n_nodes == 1


def test_node_capacity():
    # True bounds (199, 15, 1) rounded up to powers of two so nearby sample
    # counts share one compiled executable.
    assert _node_capacity(100, None) == 256
    assert _node_capacity(10**6, 3) == 16
    assert _node_capacity(1, None) == 1


def test_multi_chunk_frontier_identity():
    """Frontiers wider than the K-slot chunk walk BOTH the stats sweep and
    the child allocation in chunks; the allocation's rank offsets carry
    across chunk boundaries (child ids must stay contiguous in frontier
    order). Force n_chunks > 1 with a tiny chunk cap and pin identity
    against the host tier."""
    import numpy as np

    from mpitree_tpu.core.builder import BuildConfig, build_tree
    from mpitree_tpu.core.host_builder import build_tree_host
    from mpitree_tpu.ops.binning import bin_dataset
    from mpitree_tpu.parallel import mesh as mesh_lib

    rng = np.random.default_rng(3)
    X = rng.standard_normal((1500, 6)).astype(np.float32)
    y = rng.integers(0, 3, 1500).astype(np.int32)
    binned = bin_dataset(X, max_bins=16, binning="quantile")
    mesh = mesh_lib.resolve_mesh(n_devices=2)
    cfg = BuildConfig(
        task="classification", criterion="entropy", max_depth=10,
        max_frontier_chunk=32, frontier_tiers=(8,),
    )
    host = build_tree_host(binned, y, config=cfg, n_classes=3)
    dev = build_tree(
        binned, y, config=BuildConfig(**{**cfg.__dict__, "engine": "fused"}),
        mesh=mesh, n_classes=3,
    )
    # Deep levels exceed 32 live nodes -> multi-chunk stats + allocation.
    assert host.n_nodes > 64
    assert host.n_nodes == dev.n_nodes
    np.testing.assert_array_equal(host.feature, dev.feature)
    np.testing.assert_array_equal(host.count, dev.count)
    np.testing.assert_array_equal(host.left, dev.left)
    np.testing.assert_array_equal(host.parent, dev.parent)


def test_multi_chunk_frontier_with_sampling():
    """Per-node feature-sampling keys propagate to children through the
    chunked allocation (round 5): keys ride the same K-sized scatters as
    the parent links, with rank offsets carried across chunks. Force
    n_chunks > 1 (tiny chunk cap) and pin identity against the host
    tier, which computes the same path-hashed keys in numpy. (The wide
    histogram tier needs >= wide_hist.MIN_SLOTS slots, so a 64-slot
    chunk rides the scatter — its own multi-chunk coverage is
    test_multi_chunk_frontier_identity at the default chunk widths plus
    tests/test_wide_hist.py.)"""
    import dataclasses

    from mpitree_tpu.core.host_builder import build_tree_host
    from mpitree_tpu.ops.sampling import NodeFeatureSampler

    rng = np.random.default_rng(11)
    X = rng.standard_normal((2000, 6)).astype(np.float32)
    y = rng.integers(0, 3, 2000).astype(np.int32)
    binned = bin_dataset(X, max_bins=16, binning="quantile")
    sampler = NodeFeatureSampler(k=3, n_features=6, seed=5)
    mesh = mesh_lib.resolve_mesh(n_devices=2)
    cfg = BuildConfig(
        task="classification", criterion="entropy", max_depth=11,
        max_frontier_chunk=64, frontier_tiers=(8,),
    )
    host = build_tree_host(
        binned, y, config=cfg, n_classes=3, feature_sampler=sampler
    )
    dev = build_tree(
        binned, y, config=dataclasses.replace(cfg, engine="fused"),
        mesh=mesh, n_classes=3, feature_sampler=sampler,
    )
    assert host.n_nodes > 128  # frontiers crossed the 64-slot chunk
    assert dev.n_nodes == host.n_nodes
    np.testing.assert_array_equal(dev.feature, host.feature)
    np.testing.assert_array_equal(dev.count, host.count)
    np.testing.assert_array_equal(dev.parent, host.parent)
