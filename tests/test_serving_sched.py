"""Serving v2 subsystem tests (ISSUE 17): the EDF continuous-batching
scheduler with admission control + QoS classes, and the quantized node
tables behind ``compile_model(quantize=)``.

Scheduler pins (the ones the deleted example ``MicroBatcher`` tests
carried now live here, at subsystem level):

- **EDF ordering**: a tight-deadline arrival jumps a queued loose
  backlog — deterministic via a gate-held worker, no sleeps-as-sync.
- **Burst cannot starve**: under a ``sched_dispatch`` hang, admissions
  shed loudly (typed ``RejectedRequest``) but every ADMITTED future
  still resolves.
- **Admission control**: all five typed reject reasons, per-(model, qos)
  depth isolation, EWMA-feasibility shedding AND its idle-queue
  recovery.
- **PR-7 pins with the scheduler + quantize on**: zero new compile keys,
  zero explicit device transfers on the warmed request path.

Quantize pins: floor-rounded bf16 thresholds route lattice inputs
identically to f32; the exactness report against an independent numpy
oracle; refusal leaves the old registry slot serving; integer channels
pass through; the Pallas int8-lattice tier matches the XLA quantized
tier; the VMEM tier fits >2x the f32 ensemble.
"""
from __future__ import annotations

import threading
import time

import numpy as np
import pytest

import jax

from mpitree_tpu import (
    DecisionTreeClassifier,
    GradientBoostingClassifier,
    RandomForestClassifier,
)
from mpitree_tpu.obs import REGISTRY
from mpitree_tpu.obs import memory as memory_lib
from mpitree_tpu.resilience import chaos
from mpitree_tpu.resilience.chaos import Fault
from mpitree_tpu.serving import (
    ModelRegistry,
    RejectedRequest,
    Scheduler,
    compile_model,
    parse_qos,
)
from mpitree_tpu.serving import pallas_serve
from mpitree_tpu.serving import quantize as quantize_lib
from mpitree_tpu.serving.quantize import QuantizationError


@pytest.fixture(autouse=True)
def _clean_chaos(monkeypatch):
    chaos.clear()
    monkeypatch.delenv("MPITREE_TPU_CHAOS", raising=False)
    monkeypatch.setenv("MPITREE_TPU_BACKOFF_S", "0")
    yield
    chaos.clear()


def _cls_data(n=300, f=8, c=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] ** 2 + rng.normal(scale=0.3, size=n) > 0.4
         ).astype(int)
    if c > 2:
        y = y + (X[:, 2] > 0.8).astype(int)
    return X, y


# CPU-scale QoS spec: the knob default targets accelerator latency and
# would (honestly) shed on a CPU test runner.
_QOS = "interactive:10000:64;batch:60000:64"


# ---------------------------------------------------------------------------
# deterministic scheduler harness: a gate-held stub model
# ---------------------------------------------------------------------------

class _GateModel:
    """Stub CompiledModel: echoes row ids, blocks in raw() while the
    gate is cleared — the deterministic 'worker is busy' lever."""

    n_features = 2

    def __init__(self, buckets=(1, 2), delay=0.0, n_out=1):
        self.buckets = tuple(buckets)
        self.delay = delay
        self.n_out = n_out
        self.gate = threading.Event()
        self.gate.set()
        self.entered = threading.Event()
        self.calls = []   # list of per-dispatch row-id lists
        self.missed = 0

    def raw(self, X):
        self.entered.set()
        self.gate.wait(10)
        if self.delay:
            time.sleep(self.delay)
        self.calls.append([int(r[0]) for r in X])
        return np.repeat(
            np.asarray(X[:, :1], np.float32), self.n_out, axis=1
        )

    def note_deadline_miss(self, n=1):
        self.missed += n


class _StubRegistry:
    def __init__(self, models):
        self._models = dict(models)

    def get(self, name):
        if name not in self._models:
            raise KeyError(f"no model published as {name!r}")
        return self._models[name]

    def metrics_families(self):
        return []


def _hold(sched, model, mid=0.0):
    """Park the worker inside model.raw: clear the gate, submit one
    request, wait until the worker has actually entered raw()."""
    model.gate.clear()
    model.entered.clear()
    f = sched.submit("m", [mid, 0.0], deadline_ms=30000)
    assert model.entered.wait(10), "worker never reached raw()"
    return f


# ---------------------------------------------------------------------------
# QoS grammar
# ---------------------------------------------------------------------------

def test_parse_qos_grammar_and_errors():
    classes = parse_qos("interactive:50:256; batch:2000:4096;")
    assert [c.name for c in classes] == ["interactive", "batch"]
    assert classes[0].deadline_ms == 50.0
    assert classes[1].queue_depth == 4096
    for bad in ("", "a:b:c", "a:10", "a:-5:4", "a:10:0"):
        with pytest.raises(ValueError):
            parse_qos(bad)


# ---------------------------------------------------------------------------
# EDF ordering (migrated microbatcher pin, now deterministic)
# ---------------------------------------------------------------------------

def test_edf_tight_deadline_jumps_queued_backlog():
    m = _GateModel(buckets=(1, 2))
    with Scheduler(_StubRegistry({"m": m}), qos=_QOS, shed_depth=64,
                   margin_ms=5, wait_ms=1) as s:
        f0 = _hold(s, m)
        # Loose backlog queues behind the held worker...
        loose = [
            s.submit("m", [i, 0.0], deadline_ms=20000 - i * 1000)
            for i in (1, 2, 3, 4)
        ]
        # ...then a tight deadline arrives LAST.
        tight = s.submit("m", [9, 0.0], deadline_ms=1000)
        m.gate.set()
        for f in [f0, tight, *loose]:
            assert f.result(timeout=10).shape == (1,)
        order = [i for batch in m.calls for i in batch]
        # Dispatch order is EDF, not FIFO: the tight request leads the
        # first post-hold batch, and the loose ones drain by deadline.
        assert order == [0, 9, 4, 3, 2, 1]
        assert s.stats()["dispatches"] == len(m.calls)


def test_qos_depth_bound_sheds_only_that_class():
    m = _GateModel(buckets=(1, 64))
    spec = "interactive:10000:3;batch:60000:64"
    with Scheduler(_StubRegistry({"m": m}), qos=spec, shed_depth=64,
                   margin_ms=5, wait_ms=1) as s:
        f0 = _hold(s, m)
        admitted = [
            s.submit("m", [i, 0.0], qos="interactive") for i in (1, 2, 3)
        ]
        with pytest.raises(RejectedRequest) as ei:
            s.submit("m", [4, 0.0], qos="interactive")
        assert ei.value.reason == "queue_full"
        # Isolation: the flooded class sheds against ITS OWN bound; the
        # other class still admits.
        b = s.submit("m", [5, 0.0], qos="batch")
        m.gate.set()
        for f in [f0, b, *admitted]:
            f.result(timeout=10)
        assert s.stats()["shed"] == {"queue_full": 1}


def test_typed_rejects_global_depth_unknowns_shutdown():
    m = _GateModel(buckets=(1, 2))
    s = Scheduler(_StubRegistry({"m": m}), qos=_QOS, shed_depth=2,
                  margin_ms=5, wait_ms=1)
    with pytest.raises(RejectedRequest) as ei:
        s.submit("ghost", [0.0, 0.0])
    assert ei.value.reason == "unknown_model"
    with pytest.raises(RejectedRequest) as ei:
        s.submit("m", [0.0, 0.0], qos="premium")
    assert ei.value.reason == "unknown_class"
    f0 = _hold(s, m)
    f1 = s.submit("m", [1, 0.0])
    f2 = s.submit("m", [2, 0.0])
    with pytest.raises(RejectedRequest) as ei:  # global in-flight bound
        s.submit("m", [3, 0.0])
    assert ei.value.reason == "queue_full"
    m.gate.set()
    for f in (f0, f1, f2):
        f.result(timeout=10)
    s.close()
    with pytest.raises(RejectedRequest) as ei:
        s.submit("m", [0.0, 0.0])
    assert ei.value.reason == "shutdown"
    shed = s.stats()["shed"]
    assert shed["queue_full"] == 1 and shed["shutdown"] == 1


def test_deadline_feasibility_sheds_and_recovers():
    m = _GateModel(buckets=(1, 2), delay=0.05)
    with Scheduler(_StubRegistry({"m": m}), qos=_QOS, shed_depth=64,
                   margin_ms=25, wait_ms=1) as s:
        # Inside the close margin: infeasible even on an idle queue.
        with pytest.raises(RejectedRequest) as ei:
            s.submit("m", [0, 0.0], deadline_ms=10)
        assert ei.value.reason == "deadline_infeasible"
        # Teach the EWMA the model is ~50ms.
        s.submit("m", [1, 0.0]).result(timeout=10)
        f0 = _hold(s, m, mid=2)
        q = s.submit("m", [3, 0.0])  # backlog ahead of the next arrival
        with pytest.raises(RejectedRequest) as ei:
            s.submit("m", [4, 0.0], deadline_ms=30)  # 30ms < ~50ms EWMA
        assert ei.value.reason == "deadline_infeasible"
        m.gate.set()
        f0.result(timeout=10)
        q.result(timeout=10)
        assert s.drain(10)
        # RECOVERY: the same 30ms deadline on an IDLE queue is admitted
        # (dispatching is the only way the estimate corrects itself —
        # worst case is one recorded miss, never a permanent lockout).
        m.delay = 0.0
        out = s.submit("m", [5, 0.0], deadline_ms=30).result(timeout=10)
        assert out[0] == 5.0
        assert s.stats()["shed"]["deadline_infeasible"] == 2


def test_deadline_miss_counted_and_reported_to_model():
    m = _GateModel(buckets=(1, 2), delay=0.08)
    with Scheduler(_StubRegistry({"m": m}), qos=_QOS, shed_depth=8,
                   margin_ms=5, wait_ms=1) as s:
        # No estimate yet -> admitted (never guess); the dispatch then
        # overruns the deadline and the miss is counted on BOTH sides.
        s.submit("m", [0, 0.0], deadline_ms=20).result(timeout=10)
        st = s.stats()
    assert st["deadline_misses"] == 1
    assert m.missed == 1
    assert st["class_latency_ms"]["interactive"]["count"] == 1


# ---------------------------------------------------------------------------
# real registry: burst/hang, blip requeue, raw parity, PR-7 pins
# ---------------------------------------------------------------------------

def _registry(quantize=None, buckets=(1, 8, 64)):
    X, y = _cls_data()
    clf = RandomForestClassifier(
        n_estimators=4, max_depth=4, random_state=0
    ).fit(X, y)
    reg = ModelRegistry(buckets=buckets)
    reg.publish("rf", clf, quantize=quantize)
    return reg, X


def test_burst_sheds_loudly_but_cannot_starve_admitted():
    reg, X = _registry()
    spec = "interactive:10000:16;batch:60000:24"
    with Scheduler(reg, qos=spec, shed_depth=32, margin_ms=5,
                   wait_ms=1) as s:
        with chaos.active(
            Fault("sched_dispatch", 1, "hang", 0.3)
        ) as plan:
            admitted, shed = [], 0
            for i in range(200):
                try:
                    admitted.append(s.submit(
                        "rf", X[i % len(X)],
                        qos="interactive" if i % 2 else "batch",
                    ))
                except RejectedRequest as e:
                    assert e.reason in ("queue_full",
                                        "deadline_infeasible")
                    shed += 1
            assert shed > 0, "burst never hit the admission bounds"
            # The starvation pin: every ADMITTED future resolves.
            for f in admitted:
                out = f.result(timeout=30)
                assert out.shape == (3,) and np.isfinite(out).all()
        assert plan.fired == [("sched_dispatch", 1, "hang")]
        st = s.stats()
        assert sum(st["shed"].values()) == shed
        # Both classes recover admission after the burst drains.
        for q in ("interactive", "batch"):
            s.submit("rf", X[0], qos=q).result(timeout=10)


def test_dispatch_blip_requeues_once_with_correct_results():
    reg, X = _registry()
    cm = reg.get("rf")
    with Scheduler(reg, qos=_QOS, shed_depth=64, margin_ms=5,
                   wait_ms=1) as s:
        with chaos.active(Fault("sched_dispatch", 1, "unavailable")):
            futs = [s.submit("rf", X[i]) for i in range(3)]
            got = np.stack([f.result(timeout=30) for f in futs])
        assert s.stats()["requeues"] >= 1
    np.testing.assert_allclose(got, cm.raw(X[:3]), rtol=0, atol=1e-6)


def test_scheduled_results_match_direct_raw():
    reg, X = _registry(quantize="int8")
    cm = reg.get("rf")
    assert cm.quantize == "int8"
    with Scheduler(reg, qos=_QOS, shed_depth=256, margin_ms=5,
                   wait_ms=2) as s:
        futs = [
            s.submit("rf", X[i], qos="interactive" if i % 3 else "batch")
            for i in range(40)
        ]
        got = np.stack([f.result(timeout=30) for f in futs])
    # Coalescing must be invisible: per-row results equal the direct
    # whole-batch dispatch regardless of how the scheduler batched them.
    np.testing.assert_allclose(got, cm.raw(X[:40]), rtol=0, atol=1e-6)


def test_scheduler_quantized_zero_new_keys_zero_transfers(monkeypatch):
    """The PR-7 pins with BOTH ISSUE-17 features on: scheduler batches
    ride the warm bucket shapes (zero new compile keys) and touch no
    explicit device_put on the request path."""
    reg, X = _registry(quantize="int8")
    with Scheduler(reg, qos=_QOS, shed_depth=256, margin_ms=5,
                   wait_ms=2) as s:
        s.submit("rf", X[0]).result(timeout=30)  # scheduler warm pass
        n0 = REGISTRY.count("serving_traverse")
        calls = []
        real = jax.device_put
        monkeypatch.setattr(
            jax, "device_put",
            lambda *a, **k: calls.append(a) or real(*a, **k),
        )
        futs = [s.submit("rf", X[i % len(X)]) for i in range(30)]
        for f in futs:
            f.result(timeout=30)
        assert s.drain(10)
    assert REGISTRY.count("serving_traverse") == n0
    assert calls == []


def test_metrics_text_merges_families_under_single_type_lines():
    reg, X = _registry()
    with Scheduler(reg, qos=_QOS, shed_depth=64, margin_ms=5,
                   wait_ms=1) as s:
        s.submit("rf", X[0]).result(timeout=30)
        with pytest.raises(RejectedRequest):
            s.submit("ghost", X[0])
        text = s.metrics_text()
    for needle in (
        'mpitree_sched_shed_total{reason="unknown_model"} 1',
        "mpitree_sched_dispatches_total 1",
        "mpitree_sched_queue_depth{",
        "mpitree_sched_class_latency_seconds",
        "mpitree_serving_request_seconds",  # the registry's family
    ):
        assert needle in text, f"missing {needle!r}"
    types = [ln.split()[2] for ln in text.splitlines()
             if ln.startswith("# TYPE")]
    assert len(types) == len(set(types)), "duplicate # TYPE families"


# ---------------------------------------------------------------------------
# quantized node tables
# ---------------------------------------------------------------------------

def test_quantize_thresholds_floor_property():
    rng = np.random.default_rng(3)
    t = np.concatenate([
        rng.normal(scale=30.0, size=4000).astype(np.float32),
        np.float32([0.0, 1.0, -1.0, 2.5, 1e-30, -1e-30, 3.1e38]),
    ])
    q = quantize_lib.quantize_thresholds(t)
    qf = np.asarray(q, np.float32)
    # Floor semantics: q is the largest bf16 <= t...
    assert (qf <= t).all()
    bits = np.asarray(q).view(np.uint16).astype(np.int64)
    # One bf16 ulp toward +inf: magnitude up for positives, magnitude
    # down for negatives, smallest positive subnormal from zero.
    up = np.where(qf > 0, bits + 1, np.where(qf < 0, bits - 1, 0x0001))
    nxt = up.astype(np.uint16).view(np.asarray(q).dtype).astype(
        np.float32
    )
    # ...so the misroute gap (q, t] holds NO bf16 lattice point: the
    # next representable above q already overshoots t.
    assert (nxt > t).all()


def test_lattice_inputs_route_identically_after_quantization():
    X, y = _cls_data(n=400)
    clf = RandomForestClassifier(
        n_estimators=3, max_depth=6, random_state=1
    ).fit(X, y)
    cm = compile_model(clf, quantize="int8", buckets=(64,))
    tb = cm.table
    Xc = quantize_lib.synthesize_calibration(tb, cm.n_features, rows=512)
    assert np.array_equal(
        Xc, Xc.astype(np.dtype("float32")).astype(
            np.float32))  # sanity: f32
    thr_ref = np.nan_to_num(np.asarray(tb.threshold, np.float32), nan=0.0)
    thr_q = np.asarray(
        quantize_lib.quantize_thresholds(tb.threshold), np.float32
    )
    args = (tb.feature, tb.left, tb.right, tb.root, tb.n_steps)
    ids_ref = quantize_lib._host_descend(
        Xc, args[0], thr_ref, args[1], args[2], args[3], args[4]
    )
    ids_q = quantize_lib._host_descend(
        Xc, args[0], thr_q, args[1], args[2], args[3], args[4]
    )
    # bf16-lattice inputs route IDENTICALLY (the floor theorem): the
    # default calibration isolates VALUE error, and the report says so.
    assert np.array_equal(ids_ref, ids_q)
    assert cm._quant.report["rerouted_rows"] == 0


def test_quantized_exactness_vs_independent_host_oracle():
    reg, X = _registry(quantize="int8")
    cm = reg.get("rf")
    rep = cm.serve_report_["quantization"]
    assert rep["mode"] == "int8" and rep["ok"]
    assert rep["max_abs_delta"] <= rep["tolerance"]
    # Independent oracle: numpy descent over the QUANTIZED arrays +
    # dequantized rows must reproduce what the XLA tier serves.
    st = cm._quant
    ids = quantize_lib._host_descend(
        X[:64], np.asarray(st.feature, np.int64),
        np.asarray(st.threshold, np.float32), np.asarray(st.left),
        np.asarray(st.right), np.asarray(st.root), cm.table.n_steps,
    )
    want = quantize_lib._host_apply(
        cm.kind, ids, st.rows_host, cm._scale_host, cm.n_out
    )
    np.testing.assert_allclose(cm.raw(X[:64]), want, rtol=0, atol=2e-6)


def test_quantize_refusal_is_typed_and_keeps_old_slot_serving():
    X, y = _cls_data()
    clf = RandomForestClassifier(
        n_estimators=4, max_depth=4, random_state=0
    ).fit(X, y)
    reg = ModelRegistry(buckets=(64,))
    reg.publish("rf", clf)
    before = reg.predict("rf", X[:8])
    with pytest.raises(QuantizationError) as ei:
        reg.publish("rf", clf, quantize="int8", quantize_tol=1e-12)
    assert ei.value.report["ok"] is False
    assert ei.value.report["max_abs_delta"] > 1e-12
    # The refusal failed the publish BEFORE the slot flip: generation 1
    # (f32 tables) still serves.
    assert reg.models()["rf"]["generation"] == 1
    assert reg.get("rf").quantize is None
    np.testing.assert_array_equal(reg.predict("rf", X[:8]), before)


def test_integer_channel_passes_through_unquantized():
    X, y = _cls_data()
    t = DecisionTreeClassifier(max_depth=4).fit(X, y)
    cm = compile_model(t, quantize="int8", buckets=(64,))
    # Single-tree label gathers are exact AND minimal already: an int8
    # affine could only add error, so quantize resolves to off.
    assert cm.quantize is None and cm.exact
    assert cm.serve_report_["quantization"] == {"mode": "off"}
    np.testing.assert_array_equal(cm.predict(X[:32]), t.predict(X[:32]))


def test_quantized_pallas_kernel_matches_xla_tier():
    """The Mosaic tier's int8 raw-lattice value blocks + ONE post-kernel
    affine serve exactly what the XLA quantized tier serves (the affine
    is linear across the ensemble sum — only f32 rounding remains)."""
    reg, X = _registry(quantize="int8", buckets=(64,))
    cm = reg.get("rf")
    trees = cm.trees
    tbl, _ = pallas_serve.build_kernel_tables_quantized(trees)
    per = cm._quant.q_rows_per_tree(trees, cm.table)
    kv = cm.n_out
    vals = pallas_serve.build_kernel_values(
        trees, lambda t: per[id(t)], kv, dtype=np.int8
    )
    raw = pallas_serve.traverse_batch_pallas(
        X[:40], tbl, vals, n_steps=cm.table.n_steps, agg="sum",
        n_out=kv, kv=kv, row_tile=64, interpret=True, quantized=True,
    )
    vs = np.asarray(cm._quant.vscale, np.float32)
    vb = np.asarray(cm._quant.vbase, np.float32)
    T = len(trees)
    got = (np.asarray(raw)[:40, :kv] * vs[None, :kv]
           + T * vb[None, :kv]) / np.float32(cm._scale_host)
    np.testing.assert_allclose(got, cm.raw(X[:40]), rtol=0, atol=1e-6)


def test_quantized_vmem_tier_fits_over_2x_the_ensemble():
    """The capacity claim, priced through the ONE source
    (obs.memory.serve_kernel_row_tile): at the bench shape the int8
    tier admits >2x the nodes the f32 tier does."""

    def max_nodes(quantized):
        lo, hi = 128, 1 << 22
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if memory_lib.serve_kernel_row_tile(
                mid, 54, 1, 7, quantized=quantized
            ) is not None:
                lo = mid
            else:
                hi = mid - 1
        return lo

    f32, int8 = max_nodes(False), max_nodes(True)
    assert int8 / f32 > 2.0, f"capacity ratio {int8 / f32:.2f} <= 2"


def test_affine_int8_roundtrip_error_bounded_by_half_step():
    rng = np.random.default_rng(7)
    prep = np.concatenate([
        rng.normal(scale=4.0, size=(200, 3)).astype(np.float32),
        np.full((8, 3), 2.5, np.float32),     # constant block
    ])
    prep[:, 2] = 1.25                          # constant CHANNEL: exact
    q, scale, base = quantize_lib.affine_int8(prep)
    assert q.dtype == np.int8
    deq = quantize_lib.dequantize(q, scale, base)
    err = np.abs(deq - prep)
    assert (err <= scale[None, :] / 2 + 1e-7).all()
    np.testing.assert_array_equal(deq[:, 2], prep[:, 2])
